#!/usr/bin/env python3
"""Partitioned control-plane smoke — the ISSUE 18 acceptance drill,
CI-shaped (< 90 s, CPU-only, real HTTP end to end).

Three partition subprocesses (``python -m agent_tpu.controller.server``,
each with its own segmented journal) behind one stateless in-process
router; real ``Agent`` threads that only ever see the router URL. Four
legs:

- **Sharded drain, bit-identical** — a bulk map-reduce submitted through
  the router lands whole on its home partition (placement stamp matches
  the ring computed client-side), drains through the fleet, and the
  reduce result is bit-identical to a single-controller in-process
  reference of the same workload.
- **Cross-partition steal** — the bulk's CSV path is chosen so EVERY
  shard homes on one partition (skewed submit); agents homed on the
  other two partitions must steal it (router
  ``lease_grants_stolen_total`` > 0) instead of idling.
- **Partition kill** — a second bulk's home partition is SIGKILLed
  mid-drain; the surviving partitions grant new successes within the
  poll window (never stall), the victim restarts over its own journal,
  the drain completes, and the union of the partitions' final journal
  replays shows every job terminal on exactly one partition and billed
  exactly once (zero lost / double-applied / double-billed).
- **429 pass-through** — a second cluster with ``SCHED_MAX_PENDING``
  set small: the router forwards the home partition's 429 verbatim
  (``retry_after_ms`` intact) with the home partition stamped into the
  body, while submits homed on the other partition still land 200 —
  backpressure is per-partition, not global.

Exit 0 = clean; 1 = problems (listed one per line).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from controller_failover_soak import (  # noqa: E402 — shared drill kit
    JOURNAL_CFG,
    PLUGIN_SRC,
    build_csv,
    canonical,
    free_port,
    http_json,
    make_agent,
    start_partition_proc,
    wait_for_status,
)

from agent_tpu.agent.app import Agent  # noqa: E402
from agent_tpu.chaos import LoopbackSession  # noqa: E402
from agent_tpu.config import AgentConfig, Config  # noqa: E402
from agent_tpu.controller.core import Controller  # noqa: E402
from agent_tpu.controller.partition import (  # noqa: E402
    PartitionMap,
    job_id_for_partition,
    placement_key,
)
from agent_tpu.controller.router import RouterServer  # noqa: E402
from agent_tpu.sched.steal import StealPolicy  # noqa: E402

SHARDS = 12
ROWS_PER_SHARD = 25
SLEEP_MS = 80.0
SURVIVOR_WINDOW_SEC = 5.0
DRAIN_DEADLINE_SEC = 60.0


def pick_csv_for_home(tmp: str, pmap: PartitionMap, target: str,
                      stem: str) -> str:
    """A CSV filename whose placement key lands on ``target`` — how the
    smoke skews an entire bulk onto one partition deterministically."""
    for i in range(1000):
        cand = os.path.join(tmp, f"{stem}{i}.csv")
        if pmap.ring.place(placement_key(None, f"csv\x1f{cand}")) == target:
            return cand
    raise RuntimeError(f"no CSV name landing on {target} in 1000 tries")


def reference_reduce(tmp: str, csv_path: str) -> str:
    """Single-controller in-process drain of the identical workload —
    the bit-identity anchor for both partitioned bulks."""
    controller = Controller(
        lease_ttl_sec=10.0, max_attempts=10, requeue_delay_sec=0.01,
        sweep_interval_sec=0.1,
    )
    agents = [
        Agent(
            config=Config(agent=AgentConfig(
                controller_url="http://loopback", agent_name=f"ref-{i}",
                tasks=("slow_risk", "risk_accumulate", "echo"),
                max_tasks=2, idle_sleep_sec=0.01, error_backoff_sec=0.01,
                retry_base_sec=0.005, retry_max_sec=0.05,
                pipeline_depth=0,
            )),
            session=LoopbackSession(controller),
        )
        for i in range(2)
    ]
    for a in agents:
        a._profile = {"tier": "partition-smoke"}
    threads = [
        threading.Thread(target=a.run, daemon=True) for a in agents
    ]
    try:
        for t in threads:
            t.start()
        _, reduce_id = controller.submit_csv_job(
            csv_path, total_rows=SHARDS * ROWS_PER_SHARD,
            shard_size=ROWS_PER_SHARD, map_op="slow_risk",
            extra_payload={"field": "risk", "sleep_ms": 0.0},
            reduce_op="risk_accumulate", collect_partials=True,
        )
        deadline = time.monotonic() + DRAIN_DEADLINE_SEC
        while time.monotonic() < deadline and not controller.drained():
            time.sleep(0.02)
        if not controller.drained():
            raise RuntimeError(
                f"reference drain stuck: {controller.counts()}"
            )
        job = controller.job_snapshot(reduce_id)
        if job["state"] != "succeeded":
            raise RuntimeError(f"reference reduce {job['state']!r}")
        return canonical(job["result"])
    finally:
        for a in agents:
            a.request_drain(reason="reference done")
        for t in threads:
            t.join(timeout=10)
        controller.close()


def submit_bulk(router_url: str, csv_path: str,
                sleep_ms: float) -> Tuple[List[str], str, str]:
    status, body = http_json(router_url + "/v1/jobs", {
        "source_uri": csv_path,
        "total_rows": SHARDS * ROWS_PER_SHARD,
        "shard_size": ROWS_PER_SHARD,
        "map_op": "slow_risk",
        "extra_payload": {"field": "risk", "sleep_ms": sleep_ms},
        "reduce_op": "risk_accumulate",
        "collect_partials": True,
    })
    if status != 200:
        raise RuntimeError(f"bulk submit: HTTP {status} {body}")
    return body["job_ids"], body["reduce_id"], body["partition"]


def wait_drained(router_url: str, deadline_sec: float) -> bool:
    deadline = time.monotonic() + deadline_sec
    while time.monotonic() < deadline:
        _, body = http_json(router_url + "/v1/status", timeout=3)
        if (body or {}).get("drained"):
            return True
        time.sleep(0.1)
    return False


def run_sharded_cluster(tmp: str, reference: str) -> List[str]:
    """Legs 1–3 on one 3-partition cluster: sharded drain bit-identity,
    steal under skew, and the partition kill."""
    problems: List[str] = []
    names = ["p0", "p1", "p2"]
    ports = {n: free_port() for n in names}
    urls = {n: f"http://127.0.0.1:{ports[n]}" for n in names}
    journals = {
        n: os.path.join(tmp, f"journal.{n}.jsonl") for n in names
    }
    procs = {
        n: start_partition_proc(n, ports[n], journals[n], {})
        for n in names
    }
    pmap = PartitionMap({n: (urls[n],) for n in names})
    router: Optional[RouterServer] = None
    agents: List[Agent] = []
    threads: List[threading.Thread] = []
    try:
        for n in names:
            if not wait_for_status(urls[n], 20.0):
                return [f"partition {n} never became healthy"]
        router = RouterServer(
            pmap, steal=StealPolicy(enabled=True, min_advantage=1),
            depth_cache_sec=0.1,
        ).start()
        agents = [make_agent(f"pc-{i}", [router.url]) for i in range(3)]
        threads = [
            threading.Thread(target=a.run, daemon=True) for a in agents
        ]
        for t in threads:
            t.start()

        # ---- leg 1+2: one skewed bulk — every shard homes on home_a,
        # so the drain itself proves stealing (3 agents, at most one
        # homed there) AND the sharded bit-identity.
        home_a = names[0]
        csv_a = pick_csv_for_home(tmp, pmap, home_a, "bulk_a")
        build_csv(csv_a, SHARDS * ROWS_PER_SHARD)
        shard_ids_a, reduce_a, stamped = submit_bulk(
            router.url, csv_a, SLEEP_MS
        )
        if stamped != home_a:
            problems.append(
                f"router stamped {stamped!r} but the ring computed "
                f"{home_a!r} client-side — placement is not deterministic"
            )
        if not wait_drained(router.url, DRAIN_DEADLINE_SEC):
            _, body = http_json(router.url + "/v1/status", timeout=3)
            return problems + [
                f"sharded drain stuck: {(body or {}).get('counts')}"
            ]
        status, snap = http_json(
            router.url + f"/v1/jobs/{reduce_a}", timeout=5
        )
        if status != 200 or snap.get("state") != "succeeded":
            problems.append(
                f"reduce A: HTTP {status} state "
                f"{(snap or {}).get('state')!r}"
            )
        elif canonical(snap["result"]) != reference:
            problems.append(
                "sharded reduce diverged from the single-controller "
                f"reference\n  want {reference}\n"
                f"  got  {canonical(snap['result'])}"
            )
        stats = router.core.stats()
        stolen_after_a = stats.get("lease_grants_stolen_total", 0)
        if stolen_after_a <= 0:
            problems.append(
                "skewed bulk drained with zero stolen lease grants — "
                f"work stealing never engaged (router stats {stats})"
            )

        # ---- leg 3: a second bulk on a DIFFERENT home; SIGKILL that
        # home mid-drain; survivors must keep granting.
        victim = next(n for n in names if n != home_a)
        csv_b = pick_csv_for_home(tmp, pmap, victim, "bulk_b")
        build_csv(csv_b, SHARDS * ROWS_PER_SHARD)
        shard_ids_b, reduce_b, stamped_b = submit_bulk(
            router.url, csv_b, SLEEP_MS
        )
        if stamped_b != victim:
            problems.append(
                f"bulk B stamped {stamped_b!r}, expected {victim!r}"
            )
        # A few singles that home on the SURVIVORS, so "survivors never
        # stall" measures real post-kill progress.
        single_ids: List[str] = []
        survivors = [n for n in names if n != victim]
        for k, surv in enumerate(survivors * 3):
            jid = job_id_for_partition(
                pmap.ring, surv, prefix=f"pk-single-{k}"
            )
            status, body = http_json(router.url + "/v1/jobs", {
                "op": "slow_risk",
                "payload": {"values": [1.0], "sleep_ms": SLEEP_MS},
                "job_id": jid,
            })
            if status != 200:
                problems.append(f"single {jid}: HTTP {status} {body}")
                continue
            single_ids.append(jid)

        # Kill once bulk B is genuinely in flight on its home.
        kill_deadline = time.monotonic() + 30.0
        while time.monotonic() < kill_deadline:
            _, ps = http_json(urls[victim] + "/v1/status", timeout=3)
            by_op = (ps or {}).get("counts_by_op", {})
            if by_op.get("slow_risk", {}).get("succeeded", 0) >= 2:
                break
            time.sleep(0.05)
        procs[victim].send_signal(signal.SIGKILL)
        procs[victim].wait(timeout=10)

        def survivor_succeeded() -> int:
            total = 0
            _, sbody = http_json(router.url + "/v1/status", timeout=3)
            for row in (sbody or {}).get("partitions", []):
                if row.get("name") != victim and row.get("ok"):
                    total += int(
                        (row.get("counts") or {}).get("succeeded", 0)
                    )
            return total

        base = survivor_succeeded()
        stall_deadline = time.monotonic() + SURVIVOR_WINDOW_SEC
        stalled = True
        while time.monotonic() < stall_deadline:
            if survivor_succeeded() > base:
                stalled = False
                break
            time.sleep(0.05)
        if stalled:
            problems.append(
                "surviving partitions granted nothing within "
                f"{SURVIVOR_WINDOW_SEC}s of the {victim} kill — the "
                "fleet stalled behind a dead partition"
            )

        # Restart the victim over its own journal: replay requeues its
        # in-flight shards; the drain must then complete.
        procs[victim] = start_partition_proc(
            victim, ports[victim], journals[victim], {}
        )
        if not wait_for_status(urls[victim], 20.0):
            return problems + [
                f"killed partition {victim} never came back"
            ]
        if not wait_drained(router.url, DRAIN_DEADLINE_SEC):
            _, body = http_json(router.url + "/v1/status", timeout=3)
            return problems + [
                f"post-kill drain stuck: {(body or {}).get('counts')}"
            ]
        status, snap = http_json(
            router.url + f"/v1/jobs/{reduce_b}", timeout=5
        )
        if status != 200 or snap.get("state") != "succeeded":
            problems.append(
                f"reduce B: HTTP {status} state "
                f"{(snap or {}).get('state')!r}"
            )
        elif canonical(snap["result"]) != reference:
            problems.append(
                "post-kill reduce diverged from the reference\n"
                f"  want {reference}\n"
                f"  got  {canonical(snap['result'])}"
            )

        # ---- fleet retires through the drain path (spool flushes) ----
        for a in agents:
            a.request_drain(reason="smoke done")
        for t in threads:
            t.join(timeout=15)
        leftover = [len(a.spool) for a in agents if len(a.spool)]
        if leftover:
            problems.append(f"agents left spooled results: {leftover}")

        # ---- exactly-once across the union of the journals ----
        expected = (
            set(shard_ids_a) | set(shard_ids_b)
            | {reduce_a, reduce_b} | set(single_ids)
        )
        for n in names:
            procs[n].terminate()
            procs[n].wait(timeout=10)
        owners: Dict[str, List[str]] = {}
        billed_total = 0
        for n in names:
            replayed = Controller(
                partition=n, journal_path=journals[n],
                journal=JOURNAL_CFG,
            )
            try:
                if (replayed.journal_torn_tail
                        or replayed.journal_replay_skipped):
                    problems.append(
                        f"{n} journal damage (torn "
                        f"{replayed.journal_torn_tail}, skipped "
                        f"{replayed.journal_replay_skipped})"
                    )
                for jid in expected:
                    try:
                        jsnap = replayed.job_snapshot(jid)
                    except KeyError:
                        continue
                    owners.setdefault(jid, []).append(n)
                    if jsnap["state"] != "succeeded":
                        problems.append(
                            f"{n}: {jid} state {jsnap['state']!r}"
                        )
                if replayed.usage is not None:
                    billed_total += replayed.usage.billed_tasks
                    multi = {
                        jid: cnt for jid, cnt in
                        replayed.usage.job_billed_attempts().items()
                        if cnt != 1
                    }
                    if multi:
                        problems.append(
                            f"{n} billed != once: "
                            f"{dict(list(multi.items())[:5])}"
                        )
            finally:
                replayed.close()
        lost = [jid for jid in expected if jid not in owners]
        if lost:
            problems.append(
                f"{len(lost)} job(s) on no partition journal: "
                f"{sorted(lost)[:5]}"
            )
        double = {j: ps for j, ps in owners.items() if len(ps) > 1}
        if double:
            problems.append(
                "jobs applied on multiple partitions: "
                f"{dict(list(double.items())[:5])}"
            )
        if billed_total != len(expected):
            problems.append(
                f"fleet billed {billed_total} != jobs {len(expected)}"
            )

        print(json.dumps({
            "leg": "sharded+steal+kill", "victim": victim,
            "jobs": len(expected),
            "stolen_grants": stolen_after_a,
            "router": router.core.stats(), "ok": not problems,
        }, sort_keys=True))
        return problems
    finally:
        for a in agents:
            a.request_drain(reason="cleanup")
        for t in threads:
            t.join(timeout=10)
        if router is not None:
            router.stop()
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


def run_backpressure(tmp: str) -> List[str]:
    """Leg 4: a 2-partition cluster with a 3-job admission budget — the
    router must pass the home partition's 429 through untouched (with
    ``retry_after_ms``) and stamp which partition said no, while the
    other partition keeps accepting."""
    problems: List[str] = []
    names = ["q0", "q1"]
    ports = {n: free_port() for n in names}
    urls = {n: f"http://127.0.0.1:{ports[n]}" for n in names}
    procs = {
        n: start_partition_proc(
            n, ports[n], os.path.join(tmp, f"bp.{n}.jsonl"),
            {"SCHED_MAX_PENDING": "3"},
        )
        for n in names
    }
    pmap = PartitionMap({n: (urls[n],) for n in names})
    router: Optional[RouterServer] = None
    try:
        for n in names:
            if not wait_for_status(urls[n], 20.0):
                return [f"backpressure partition {n} never healthy"]
        router = RouterServer(pmap).start()

        # Fill q0 to its budget with ids the ring homes there; nothing
        # leases (no agents), so the 4th submit must 429.
        got_429: Optional[Tuple[int, Any]] = None
        for k in range(4):
            jid = job_id_for_partition(
                pmap.ring, "q0", prefix=f"bp-{k}"
            )
            status, body = http_json(router.url + "/v1/jobs", {
                "op": "echo", "payload": {"k": k}, "job_id": jid,
            })
            if status == 429:
                got_429 = (status, body)
                break
            if status != 200:
                problems.append(f"fill submit {k}: HTTP {status} {body}")
        if got_429 is None:
            problems.append(
                "4 submits past a 3-job budget never 429ed — admission "
                "is not enforced through the router"
            )
        else:
            _, body = got_429
            if not isinstance(body, dict):
                problems.append(f"429 body not JSON: {body!r}")
            else:
                if "retry_after_ms" not in body:
                    problems.append(
                        f"429 body lost retry_after_ms: {body}"
                    )
                if body.get("partition") != "q0":
                    problems.append(
                        "429 body does not name the rejecting "
                        f"partition: {body}"
                    )
        # The OTHER partition's budget is untouched: its home submits
        # still land — rejection is per-partition, not fleet-wide.
        jid = job_id_for_partition(pmap.ring, "q1", prefix="bp-ok")
        status, body = http_json(router.url + "/v1/jobs", {
            "op": "echo", "payload": {"k": -1}, "job_id": jid,
        })
        if status != 200:
            problems.append(
                f"submit homed on the un-full partition got HTTP "
                f"{status} {body} — backpressure leaked fleet-wide"
            )
        print(json.dumps({
            "leg": "backpressure",
            "rejected": got_429 is not None,
            "router": router.core.stats() if router else {},
            "ok": not problems,
        }, sort_keys=True))
        return problems
    finally:
        if router is not None:
            router.stop()
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


def main() -> int:
    # slow_risk through the designed plugin channel (agents run it
    # in-process; the subprocess partitions never execute ops).
    from agent_tpu.ops import load_plugins

    tmp_root = tempfile.mkdtemp(prefix="partition_smoke_plugin_")
    plugin_path = os.path.join(tmp_root, "slow_risk_plugin.py")
    with open(plugin_path, "w", encoding="utf-8") as f:
        f.write(PLUGIN_SRC)
    if "slow_risk" not in load_plugins(plugin_path):
        from agent_tpu.ops import OPS_LOAD_ERRORS

        print(f"slow_risk plugin failed to load: {OPS_LOAD_ERRORS}")
        return 1

    t0 = time.monotonic()
    problems: List[str] = []
    with tempfile.TemporaryDirectory(prefix="partition_smoke_") as tmp:
        # The reference drains the SAME rows the partitioned bulks use
        # (build_csv is deterministic in row count), so one reference
        # anchors both reduces.
        ref_csv = os.path.join(tmp, "reference.csv")
        build_csv(ref_csv, SHARDS * ROWS_PER_SHARD)
        try:
            reference = reference_reduce(tmp, ref_csv)
        except RuntimeError as exc:
            print(f"reference run failed: {exc}")
            return 1
        problems += run_sharded_cluster(tmp, reference)
        problems += run_backpressure(tmp)
    elapsed = round(time.monotonic() - t0, 1)
    if problems:
        for p in problems:
            print(p)
        print(f"FAILED: {len(problems)} problem(s) in {elapsed}s")
        return 1
    print(f"partitioned controller smoke: OK ({elapsed}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
