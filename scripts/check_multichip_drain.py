#!/usr/bin/env python3
"""Multi-chip drain smoke (ISSUE 7) — the CI gate for fleet and mesh mode.

Four checks on the forced-host CPU shape (4 virtual devices):

1. **Fleet-of-2 is bit-identical**: two agent subprocesses, each pinned to a
   disjoint 2-device slice (``CHIP_SLICE``), drain a sharded classify job
   from one fair-scheduled controller over real HTTP; per-shard
   indices/scores equal the 1-chip reference drain exactly, and EVERY fleet
   member executed at least one shard (the fair scheduler's idle-preference
   spreading, not one agent hoovering the queue).
2. **dp=4 mesh is bit-identical**: one agent whose runtime owns all 4
   devices as a ``dp=4`` mesh executes the same shards dp-sharded
   (``runtime.put_batch`` → ``NamedSharding(P("dp"))`` end-to-end, double-
   buffered feed and binary wire intact) with identical results.
3. **Scaling sanity floor**: fleet-of-2 rows/sec ÷ (2 × 1-chip rows/sec)
   is recorded and must clear a floor — 0.45 with ≥3 host cores (CI), 0.15
   on starved single-core boxes (throughput must at least be conserved).
   The real ≥0.8 bar at 4 agents lives in ``bench.py``'s ``drain_multichip``
   leg, gated on core count.
4. **MPMD pipeline chain**: summarize's encoder and decoder run as separate
   ops on DIFFERENT agents (``summarize_encode`` / ``summarize_decode``)
   chained through controller dependency gating (``after`` +
   ``collect_partials``) — the stretch leg of arXiv 2412.14374 over the
   existing lease protocol — and the chained summaries equal the monolithic
   ``map_summarize`` output.

Exit 0 = all clean; 1 = problems (listed one per line).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from typing import Any, Dict, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TINY = {
    "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
    "max_len": 64, "dtype": "float32", "n_classes": 16,
}
TINY_S2S = {
    "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
    "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
}
ROWS, SHARD = 2048, 64          # 32 shards per drain
DRAIN_DEADLINE_SEC = 420.0
READY_TIMEOUT_SEC = 300.0

# (mode, n_agents, devices_per_agent, MESH_SHAPE)
MODES: Tuple[Tuple[str, int, int, str], ...] = (
    ("chip1", 1, 1, ""),
    ("fleet2", 2, 2, ""),
    ("mesh4", 1, 4, "dp=4"),
)


def build_csv(path: str, rows: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text\n")
        for i in range(rows):
            f.write(f'{i},"multichip smoke row {i} with a text payload"\n')


def _tail_logs(log_dir: str, n: int = 1500) -> List[str]:
    out = []
    try:
        for name in sorted(os.listdir(log_dir)):
            with open(os.path.join(log_dir, name), "rb") as f:
                data = f.read()[-n:]
            out.append(f"--- {name} ---\n{data.decode(errors='replace')}")
    except OSError:
        pass
    return out


def run_mode(
    mode: str, n_agents: int, devices_per_agent: int, mesh_shape: str,
    csv: str, extra: Dict[str, Any], tmp: str,
) -> Tuple[List[str], Dict[str, Any]]:
    """One drain in one mode → (problems, record)."""
    from agent_tpu.agent import fleet
    from agent_tpu.config import SchedConfig
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer

    problems: List[str] = []
    record: Dict[str, Any] = {
        "mode": mode, "n_agents": n_agents,
        "n_chips": n_agents * devices_per_agent,
    }
    warm_file = os.path.join(tmp, f"warm_{mode}.json")
    with open(warm_file, "w", encoding="utf-8") as f:
        json.dump([{
            "op": "map_classify_tpu",
            "payload": {**extra, "source_uri": csv, "start_row": 0,
                        "shard_size": SHARD},
        }], f)
    log_dir = os.path.join(tmp, f"logs_{mode}")
    # The fair policy is the one under test: idle-preference and
    # queue_depth-aware grants are what spread shards across the fleet.
    controller = Controller(
        lease_ttl_sec=600.0, sched=SchedConfig(policy="fair")
    )
    server = ControllerServer(controller).start()
    handle = fleet.spawn_fleet(
        n_agents, devices_per_agent,
        controller_url=server.url, tasks="map_classify_tpu",
        platform="cpu", name_prefix=mode, mesh_shape=mesh_shape,
        warm_file=warm_file, log_dir=log_dir,
        extra_env={
            "IDLE_SLEEP_SEC": "0.02",
            # One "chip" must not borrow the whole host's BLAS pool, or the
            # 1-chip reference silently uses N cores and every scaling
            # ratio deflates.
            "OMP_NUM_THREADS": "1",
            "OPENBLAS_NUM_THREADS": "1",
        },
    )
    try:
        if not fleet.wait_for_agents(
            controller.agents_summary, handle.names,
            timeout=READY_TIMEOUT_SEC, fleet=handle,
        ):
            return (
                [f"{mode}: fleet not ready (alive={handle.alive()}, "
                 f"failures={handle.poll_failures()})"] + _tail_logs(log_dir),
                record,
            )
        t0 = time.perf_counter()
        shard_ids, _ = controller.submit_csv_job(
            csv, total_rows=ROWS, shard_size=SHARD,
            map_op="map_classify_tpu", extra_payload=extra,
        )
        deadline = time.monotonic() + DRAIN_DEADLINE_SEC
        while not controller.drained():
            if time.monotonic() > deadline:
                return (
                    [f"{mode}: drain did not finish: {controller.counts()}"]
                    + _tail_logs(log_dir),
                    record,
                )
            if handle.poll_failures():
                return (
                    [f"{mode}: fleet member died mid-drain: "
                     f"{handle.poll_failures()}"] + _tail_logs(log_dir),
                    record,
                )
            time.sleep(0.02)
        wall = time.perf_counter() - t0
        counts = controller.counts()
        if counts != {"succeeded": ROWS // SHARD}:
            problems.append(f"{mode}: bad terminal counts {counts}")
        per_agent: Dict[str, int] = {name: 0 for name in handle.names}
        results: Dict[int, Any] = {}
        for jid in shard_ids:
            snap = controller.job_snapshot(jid)
            r = snap["result"]
            if not (isinstance(r, dict) and r.get("ok") is True):
                problems.append(f"{mode}: shard {jid} non-ok result")
                continue
            results[snap_start(controller, jid)] = (
                r.get("indices"), r.get("scores")
            )
            if snap["agent"] in per_agent:
                per_agent[snap["agent"]] += 1
        record.update(
            rows_per_sec=round(ROWS / wall, 1),
            wall_s=round(wall, 2),
            per_agent_shards=per_agent,
        )
        zero = [a for a, n in per_agent.items() if n == 0]
        if zero:
            problems.append(
                f"{mode}: agent(s) got ZERO shards: {zero} "
                f"(per-agent {per_agent})"
            )
        record["results"] = results
    finally:
        handle.stop()
        server.stop()
    return problems, record


def snap_start(controller, job_id: str) -> int:
    return int(controller.job(job_id).payload["start_row"])


def check_fleet_and_mesh(tmp: str) -> Tuple[List[str], Dict[str, Any]]:
    problems: List[str] = []
    extra = {"text_field": "text", "allow_fallback": False,
             "result_format": "columnar", "model_config": dict(TINY),
             "topk": 3}
    csv = os.path.join(tmp, "rows.csv")
    build_csv(csv, ROWS)
    records: Dict[str, Dict[str, Any]] = {}
    for mode, n_agents, dev_per, mesh in MODES:
        mode_problems, record = run_mode(
            mode, n_agents, dev_per, mesh, csv, extra, tmp
        )
        problems += mode_problems
        records[mode] = record
        if mode_problems:
            return problems, records  # later checks compare against chip1

    ref = records["chip1"].pop("results")
    for mode in ("fleet2", "mesh4"):
        got = records[mode].pop("results")
        if got != ref:
            diverged = sorted(
                start for start in ref
                if got.get(start) != ref[start]
            )[:5]
            problems.append(
                f"{mode}: NOT bit-identical to the 1-chip reference "
                f"(first diverging shards at start_row {diverged})"
            )
        else:
            records[mode]["bit_identical"] = True

    r1 = records["chip1"].get("rows_per_sec") or 0.0
    r2 = records["fleet2"].get("rows_per_sec") or 0.0
    eff = r2 / (2 * r1) if r1 else 0.0
    records["fleet2"]["scaling_efficiency"] = round(eff, 3)
    floor = 0.45 if (os.cpu_count() or 1) >= 3 else 0.15
    if eff < floor:
        problems.append(
            f"fleet2 scaling_efficiency {eff:.3f} below the sanity floor "
            f"{floor} (chip1 {r1} vs fleet2 {r2} rows/s, "
            f"{os.cpu_count()} cores)"
        )
    if not problems:
        print(json.dumps({
            "check": "fleet_and_mesh", "ok": True,
            "modes": {
                m: {k: v for k, v in rec.items() if k != "results"}
                for m, rec in records.items()
            },
        }, sort_keys=True))
    return problems, records


def check_mpmd_pipeline() -> List[str]:
    """Encoder and decoder stages on DIFFERENT agents, chained through
    controller dep-gating; output equals the monolithic op."""
    from agent_tpu.agent.app import Agent
    from agent_tpu.chaos import LoopbackSession
    from agent_tpu.config import AgentConfig, Config
    from agent_tpu.controller.core import Controller
    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext
    from agent_tpu.runtime.runtime import get_runtime

    problems: List[str] = []
    texts = [f"mpmd pipeline row {i} with text to summarize"
             for i in range(96)]
    shards = [texts[i:i + 32] for i in range(0, len(texts), 32)]
    runtime = get_runtime()

    # Monolithic reference: the fused map_summarize drain of the same rows.
    reference: List[str] = []
    for shard in shards:
        out = get_op("map_summarize")(
            {"texts": shard, "max_length": 8,
             "model_config": dict(TINY_S2S)},
            OpContext(runtime=runtime),
        )
        if not out.get("ok"):
            return [f"mpmd: monolithic reference failed: {str(out)[:200]}"]
        reference.extend(out["summaries"])

    controller = Controller()
    decode_ids = []
    for i, shard in enumerate(shards):
        enc_id = controller.submit(
            "summarize_encode",
            {"texts": shard, "model_config": dict(TINY_S2S)},
            job_id=f"enc-{i}",
        )
        decode_ids.append(controller.submit(
            "summarize_decode",
            {"max_length": 8, "model_config": dict(TINY_S2S),
             "__collect_partials__": True},
            job_id=f"dec-{i}",
            after=[enc_id],
        ))

    def stage_agent(name: str, tasks: Tuple[str, ...]) -> Agent:
        agent = Agent(
            config=Config(agent=AgentConfig(
                controller_url="http://loopback", agent_name=name,
                tasks=tasks, idle_sleep_sec=0.0,
            )),
            session=LoopbackSession(controller), runtime=runtime,
        )
        agent._profile = {"tier": "smoke"}
        return agent

    enc_agent = stage_agent("mpmd-enc", ("summarize_encode",))
    dec_agent = stage_agent("mpmd-dec", ("summarize_decode",))
    deadline = time.monotonic() + 240.0
    while not controller.drained():
        if time.monotonic() > deadline:
            return [f"mpmd: chain did not drain: {controller.counts()}"]
        enc_agent.step()
        dec_agent.step()

    chained: List[str] = []
    for jid in decode_ids:
        snap = controller.job_snapshot(jid)
        if snap["agent"] != "mpmd-dec":
            problems.append(
                f"mpmd: decode job {jid} ran on {snap['agent']!r}, "
                "not the decode-stage agent"
            )
        r = snap["result"]
        if not (isinstance(r, dict) and r.get("ok") is True):
            return [f"mpmd: decode job {jid} failed: {str(r)[:200]}"]
        chained.extend(r["summaries"])
    for i in range(len(shards)):
        if controller.job_snapshot(f"enc-{i}")["agent"] != "mpmd-enc":
            problems.append(f"mpmd: encode job enc-{i} ran on the wrong agent")
    if chained != reference:
        n_diff = sum(1 for a, b in zip(chained, reference) if a != b)
        problems.append(
            f"mpmd: chained summaries diverged from monolithic "
            f"({n_diff}/{len(reference)} rows differ)"
        )
    if not problems:
        print(json.dumps({
            "check": "mpmd_pipeline", "ok": True, "rows": len(reference),
            "stages": {"encode": "mpmd-enc", "decode": "mpmd-dec"},
            "identical_to_monolithic": True,
        }, sort_keys=True))
    return problems


def main() -> int:
    t0 = time.monotonic()
    problems: List[str] = []
    with tempfile.TemporaryDirectory(prefix="multichip_") as tmp:
        mode_problems, _records = check_fleet_and_mesh(tmp)
        problems += mode_problems
    problems += check_mpmd_pipeline()
    elapsed = round(time.monotonic() - t0, 1)
    if problems:
        for p in problems:
            print(p)
        print(f"check_multichip_drain: FAILED ({len(problems)} problem(s), "
              f"{elapsed}s)")
        return 1
    print(f"check_multichip_drain: OK ({elapsed}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
