#!/usr/bin/env python3
"""CI smoke check for the observability surface (ISSUE 2 satellite).

Boots a real ``ControllerServer``, drains a tiny job through the real
``Agent`` loop over HTTP (a stdlib urllib shim stands in for requests so
this needs nothing beyond the repo), then:

- scrapes ``GET /v1/metrics`` and validates the Prometheus text exposition
  structurally (``agent_tpu.obs.validate_exposition``: malformed lines,
  missing TYPE declarations, incomplete histograms) plus the presence of the
  core series every dashboard will key on;
- pins the extended ``GET /v1/status`` fields;
- confirms ``GET /v1/debug/events`` serves trace-correlated flight-recorder
  events.

Exit 0 = clean; 1 = problems (listed one per line). Style sibling of
``scripts/check_doc_claims.py``: repo-rooted, zero external deps.
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED_SERIES = (
    # controller-side
    "controller_lease_requests_total",
    "controller_tasks_leased_total",
    "controller_results_total",
    "controller_queue_wait_seconds",
    "controller_queue_depth",
    # fleet-merged agent-side
    "tasks_total",
    "lease_requests_total",
    # synthetic liveness
    "agent_last_seen_seconds",
)

REQUIRED_STATUS_KEYS = (
    "counts", "counts_by_op", "queue_depth", "drained", "stale_results",
    "agents", "summary", "last_metrics",
)


class _UrllibSession:
    """The minimal ``requests.Session`` surface Agent needs, on stdlib."""

    def post(self, url, json=None, timeout=10.0):  # noqa: A002 — shim API
        import json as _json

        data = _json.dumps(json or {}).encode()
        req = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/json"}
        )
        try:
            resp = urllib.request.urlopen(req, timeout=timeout)
            body = resp.read()
            status = resp.status
        except urllib.error.HTTPError as exc:
            body = exc.read()
            status = exc.code

        class _Resp:
            status_code = status
            text = body.decode("utf-8", errors="replace")

            def json(self):
                return _json.loads(body)

        return _Resp()


def main() -> int:
    from agent_tpu.agent.app import Agent
    from agent_tpu.config import AgentConfig, Config
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer
    from agent_tpu.obs.metrics import validate_exposition

    problems = []
    controller = Controller()
    with ControllerServer(controller) as server:
        for i in range(3):
            controller.submit("echo", {"i": i})
        cfg = Config(agent=AgentConfig(
            controller_url=server.url, agent_name="ci-smoke",
            tasks=("echo",), max_tasks=4, idle_sleep_sec=0.0,
        ))
        agent = Agent(config=cfg, session=_UrllibSession())
        agent._profile = {"tier": "ci"}
        agent.run(max_steps=5)  # serial loop; flushes metrics at the end
        if not controller.drained():
            problems.append("tiny drain did not complete in 5 steps")

        with urllib.request.urlopen(server.url + "/v1/metrics") as r:
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
        if "text/plain" not in ctype:
            problems.append(f"/v1/metrics content-type {ctype!r}")
        problems += validate_exposition(text, required=REQUIRED_SERIES)
        if 'tasks_total{op="echo",status="succeeded"} 3' not in text:
            problems.append(
                "fleet-merged agent series missing/incorrect: expected "
                'tasks_total{op="echo",status="succeeded"} 3'
            )

        with urllib.request.urlopen(server.url + "/v1/status") as r:
            status = json.load(r)
        for key in REQUIRED_STATUS_KEYS:
            if key not in status:
                problems.append(f"/v1/status missing key {key!r}")
        if status.get("counts_by_op", {}).get("echo", {}).get("succeeded") != 3:
            problems.append("/v1/status counts_by_op.echo.succeeded != 3")

        with urllib.request.urlopen(server.url + "/v1/debug/events") as r:
            events = json.load(r).get("events", [])
        kinds = {e.get("kind") for e in events}
        if not {"submit", "lease", "result"} <= kinds:
            problems.append(
                f"/v1/debug/events missing core kinds (got {sorted(kinds)})"
            )

    if problems:
        for p in problems:
            print(p)
        print(f"FAILED: {len(problems)} problem(s)")
        return 1
    print("metrics endpoint smoke check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
