#!/usr/bin/env python3
"""Elastic-fleet churn soak — the scenario that proves the swarm survives
planet-scale churn (ISSUE 10; ROADMAP item 4).

Two runs per seed, both in-process (real ``Agent`` loops on fleet threads +
real ``Controller`` through ``chaos.LoopbackSession`` — deterministic
arrivals, no sockets, no jax):

1. **Calm reference** — the SAME seeded open-loop traffic (diurnal base +
   a 10× burst window of deadline-tagged interactive jobs, multi-tenant,
   riding a bulk map-reduce) drained by a FIXED fleet at max size, no
   faults. Records the reduce result and the interactive-tier p99.
2. **Churn run** — identical traffic against an AUTOSCALED fleet
   (``agent_tpu/autoscale.py`` consuming ``/v1/health``) under seeded
   preemption chaos: ``spot_reclaim`` (graceful drain — the member
   finishes/releases its lease, flushes spool + final metrics, exits) and
   ``hard_kill`` (transport severed mid-work, no drain — recovery is lease
   TTL expiry + epoch fencing) while the controller journals everything.

Asserts (the ISSUE 10 acceptance bar):

- the churn run's reduce result is **bit-identical** to the calm reference;
- **zero jobs lost or double-billed**: every job terminal-succeeded, usage
  ledger ``billed == jobs``, no job billed twice, zero ``dead`` from churn;
- **≥ 3 spot reclaims and ≥ 1 hard kill** actually happened, and the
  autoscaler **replaced the capacity**;
- **≥ 2 scale-down events**, every gracefully retired member exited via
  the drain path: clean thread exit, empty spool, controller marked it
  ``draining``, and **no lease left stranded** on it (unstarted tasks were
  released, not abandoned to the TTL);
- interactive-tier **p99 stays within the pinned degradation bound** of
  the calm reference during the 10× burst;
- ``fleet_size`` demonstrably responds: scale-up fired on queue pressure /
  SLO burn during the burst, scale-down fired on idle in the tail, and the
  families ride the controller's ``/v1/metrics``;
- after the run the **journal replays** into a fresh controller with
  identical job states/epochs/attempts, an empty scheduler queue, an
  identical usage ledger, and zero torn/skipped lines in ``/v1/status``'s
  new ``journal`` block.

Exit 0 = all seeds clean; 1 = problems (listed one per line). CI runs
``--quick --seed 7`` (CPU-shaped, < 60 s).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from agent_tpu.agent.app import Agent
from agent_tpu.autoscale import Autoscaler, ThreadFleetDriver
from agent_tpu.chaos import FaultPlan, LoopbackSession
from agent_tpu.config import (
    AgentConfig,
    AutoscaleConfig,
    Config,
    SchedConfig,
    SloConfig,
)
from agent_tpu.controller.core import Controller
from agent_tpu.loadgen import (
    ArrivalPattern,
    LoadGen,
    LoadGenStats,
    TrafficClass,
    session_submitter,
)

# Timing fields legitimately differ run to run; everything else in the
# reduce result must match bit for bit (same exclusion set as chaos_soak).
VOLATILE_KEYS = ("compute_time_ms", "duration_ms", "timings", "trace",
                 "usage")

TERMINAL = ("succeeded", "failed", "dead")

# The interactive probe ships through the designed extension point
# (OPS_PLUGIN_PATH / load_plugins), not a registry monkey-patch: a
# payload-controlled service time is what makes a 10× burst actually queue
# on a CPU runner, so the autoscaler has something real to react to.
PLUGIN_SRC = '''\
"""Soak-only op: payload-controlled service time (interactive traffic)."""
import time

from agent_tpu.ops import register_op


@register_op("elastic_probe")
def run(payload, ctx=None):
    time.sleep(float(payload.get("sleep_ms", 1.0)) / 1e3)
    return {"ok": True, "seq": payload.get("seq")}
'''

# CI-shrunk SLO spec: the burst must be able to drive a visible burn on the
# interactive tier inside a seconds-long window.
SLO_SPEC = json.dumps([
    {"tier": 8, "p99_ms": 400.0, "availability": 0.999},
])


def canonical(result: Any) -> str:
    if isinstance(result, dict):
        result = {k: v for k, v in result.items() if k not in VOLATILE_KEYS}
    return json.dumps(result, sort_keys=True, default=str)


def build_csv(path: str, rows: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text,risk\n")
        for i in range(rows):
            f.write(f'{i},"record {i}",{(i % 17) * 0.25}\n')


def percentile(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
    return ordered[idx]


def make_controller(tmp: str, journal: bool, ttl: float) -> Controller:
    return Controller(
        lease_ttl_sec=ttl,
        max_attempts=10,
        requeue_delay_sec=0.01,
        sweep_interval_sec=0.1,
        sched=SchedConfig(policy="fair"),
        journal_path=(
            os.path.join(tmp, "elastic_journal.jsonl") if journal else None
        ),
        slo=SloConfig(
            enabled=True, spec=SLO_SPEC,
            window_short_sec=2.0, window_long_sec=8.0,
            burn_warn=2.0, burn_page=10.0,
        ),
    )


def agent_factory(controller: Controller, probe_sleep_guard: float = 0.0):
    def build(name: str) -> Agent:
        cfg = Config(agent=AgentConfig(
            controller_url="http://loopback", agent_name=name,
            tasks=("risk_accumulate", "elastic_probe"),
            max_tasks=2, idle_sleep_sec=0.01,
            error_backoff_sec=0.01, retry_base_sec=0.005,
            retry_max_sec=0.05, pipeline_depth=0,
        ))
        agent = Agent(config=cfg, session=LoopbackSession(controller))
        agent._profile = {"tier": "elastic-soak"}  # skip hardware probing
        return agent

    return build


class CompletionWatcher:
    """Tracks submit→terminal latency per interactive job by polling job
    states (25 ms cadence — an in-process snapshot read)."""

    def __init__(self, controller: Controller) -> None:
        self.controller = controller
        self._lock = threading.Lock()
        self._pending: Dict[str, Tuple[str, float]] = {}
        self.latencies: Dict[str, List[float]] = {}
        self.states: Dict[str, str] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="soak-watcher", daemon=True
        )

    def track(self, job_id: str, cls: str) -> None:
        with self._lock:
            self._pending[job_id] = (cls, time.monotonic())

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                pending = list(self._pending.items())
            now = time.monotonic()
            for job_id, (cls, t0) in pending:
                try:
                    state = self.controller.job_snapshot(job_id)["state"]
                except KeyError:
                    continue
                if state in TERMINAL:
                    with self._lock:
                        self._pending.pop(job_id, None)
                        self.latencies.setdefault(cls, []).append(now - t0)
                        self.states[job_id] = state
            time.sleep(0.025)

    def start(self) -> "CompletionWatcher":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def all_latencies(self) -> List[float]:
        with self._lock:
            return [v for vs in self.latencies.values() for v in vs]


class SoakDriver(ThreadFleetDriver):
    """ThreadFleetDriver plus the stranded-lease probe: the instant a
    graceful retirement completes, nothing may still be leased to the
    retired member (the drain released what it did not finish — the TTL is
    never the mechanism on the graceful path)."""

    def __init__(self, controller: Controller, **kw: Any) -> None:
        super().__init__(**kw)
        self.controller = controller
        self.stranded: List[Tuple[str, List[str]]] = []

    def retire_member(self, name: str) -> bool:
        ok = super().retire_member(name)
        if ok:
            leases = self.controller.leased_to(name)
            if leases:
                self.stranded.append((name, leases))
        return ok


def build_traffic(
    csv_path: str, shards: int, rows_per_shard: int, args: Any, seed: int,
) -> LoadGen:
    """The interactive mix: two tenants of deadline-tagged tier-8 probes —
    the class the SLO objective judges and the burst hammers."""
    def probe_payload(sleep_ms: float):
        def build(rng, seq):
            return {"sleep_ms": sleep_ms, "seq": seq}
        return build

    classes = [
        TrafficClass(
            name=f"interactive-rt{t}", op="elastic_probe", weight=1.0,
            tenant=f"rt{t}", priority=8,
            deadline_sec=args.interactive_deadline_sec,
            payload_fn=probe_payload(args.probe_sleep_ms),
        )
        for t in (1, 2)
    ]
    pattern = ArrivalPattern(
        args.base_rate,
        diurnal_amplitude=0.3,
        diurnal_period_sec=max(4.0, args.duration_sec),
        bursts=[(
            args.burst_at_sec,
            args.burst_at_sec + args.burst_len_sec,
            args.burst_factor,
        )],
    )
    return LoadGen(classes, pattern, seed=seed)


def submit_bulk(
    controller: Controller, csv_path: str, shards: int, rows_per_shard: int
) -> Tuple[List[str], str]:
    shard_ids, reduce_id = controller.submit_csv_job(
        csv_path,
        total_rows=shards * rows_per_shard,
        shard_size=rows_per_shard,
        map_op="risk_accumulate",
        extra_payload={"field": "risk"},
        reduce_op="risk_accumulate",
        collect_partials=True,
        tenant="bulk",
        priority=2,
    )
    return shard_ids, reduce_id


def run_traffic(
    controller: Controller,
    loadgen: LoadGen,
    watcher: CompletionWatcher,
    duration_sec: float,
) -> LoadGenStats:
    submit = session_submitter(LoopbackSession(controller))

    def tracked(arrival):
        job_id = submit(arrival)
        watcher.track(job_id, arrival.cls.name)
        return job_id

    return loadgen.run(tracked, duration_sec)


def wait_drained(controller: Controller, deadline_sec: float) -> bool:
    deadline = time.monotonic() + deadline_sec
    while time.monotonic() < deadline:
        if controller.drained():
            return True
        time.sleep(0.05)
    return controller.drained()


def run_reference(
    tmp: str, csv_path: str, shards: int, rows_per_shard: int,
    args: Any, seed: int,
) -> Tuple[Dict[str, Any], List[str]]:
    """Calm drain of the identical workload on a fixed max-size fleet."""
    problems: List[str] = []
    controller = make_controller(tmp, journal=False, ttl=args.lease_ttl_sec)
    driver = ThreadFleetDriver(
        agent_factory(controller), name_prefix=f"ref-{seed}"
    )
    watcher = CompletionWatcher(controller).start()
    out: Dict[str, Any] = {}
    try:
        driver.spawn(args.max_agents)
        _, reduce_id = submit_bulk(
            controller, csv_path, shards, rows_per_shard
        )
        loadgen = build_traffic(csv_path, shards, rows_per_shard, args, seed)
        stats = run_traffic(controller, loadgen, watcher, args.duration_sec)
        if not wait_drained(controller, args.deadline_sec):
            problems.append(
                f"seed {seed}: reference drain did not complete "
                f"(counts {controller.counts()})"
            )
            return out, problems
        time.sleep(0.1)  # let the watcher record the drain tail
        job = controller.job_snapshot(reduce_id)
        if job["state"] != "succeeded":
            problems.append(
                f"seed {seed}: reference reduce state {job['state']!r}"
            )
            return out, problems
        out["reduce"] = canonical(job["result"])
        out["p99"] = percentile(watcher.all_latencies(), 0.99)
        out["submitted"] = stats.total_submitted()
        if out["p99"] is None:
            problems.append(f"seed {seed}: reference measured no latencies")
    finally:
        watcher.stop()
        driver.retire(driver.size())
        controller.close()
    return out, problems


def run_churn(
    tmp: str, csv_path: str, shards: int, rows_per_shard: int,
    args: Any, seed: int, reference: Dict[str, Any],
) -> List[str]:
    problems: List[str] = []
    controller = make_controller(tmp, journal=True, ttl=args.lease_ttl_sec)
    plan = FaultPlan(
        seed=seed,
        spot_reclaim=args.reclaim_prob,
        hard_kill=args.kill_prob,
    )
    driver = SoakDriver(
        controller,
        agent_factory=agent_factory(controller),
        name_prefix=f"churn-{seed}",
    )
    scaler = Autoscaler(
        driver,
        controller.health_json,
        config=AutoscaleConfig(
            min_agents=args.min_agents,
            max_agents=args.max_agents,
            interval_sec=0.25,
            up_queue_per_agent=3.0,
            up_starvation_sec=3.0,
            step_up=2,
            step_down=1,
            down_idle_evals=3,
            down_max_duty=0.95,
            up_cooldown_sec=1.0,
            down_cooldown_sec=1.0,
        ),
        registry=controller.metrics,  # families ride /v1/metrics
    )
    watcher = CompletionWatcher(controller).start()
    stop_scaler = threading.Event()
    scaler_thread = threading.Thread(
        target=scaler.run, args=(stop_scaler,), kwargs={"interval_sec": 0.25},
        name="soak-autoscaler", daemon=True,
    )
    reclaims = 0
    kills = 0
    peak_fleet = 0
    tail_size: Optional[int] = None

    def reclaim_one() -> bool:
        """Gracefully reclaim the newest member (spot SIGTERM model) —
        records drain-path evidence via the driver."""
        names = driver.names()
        if len(names) <= 1:
            return False  # never empty the fleet outright
        return driver.retire_member(names[-1])

    def kill_one() -> bool:
        names = driver.names()
        if len(names) <= 1:
            return False
        return driver.kill(names[-1])

    try:
        driver.spawn(args.min_agents)
        scaler_thread.start()
        _, reduce_id = submit_bulk(
            controller, csv_path, shards, rows_per_shard
        )
        loadgen = build_traffic(csv_path, shards, rows_per_shard, args, seed)
        traffic_done: List[LoadGenStats] = []

        def traffic_thread() -> None:
            traffic_done.append(
                run_traffic(controller, loadgen, watcher, args.duration_sec)
            )

        gen = threading.Thread(
            target=traffic_thread, name="soak-loadgen", daemon=True
        )
        gen.start()

        # Churn: from just before the burst to just past it, one seeded
        # Bernoulli draw per live member per tick — the chaos fault kinds
        # doing the reclaiming, not an ad-hoc schedule.
        t0 = time.monotonic()
        churn_end = args.burst_at_sec + args.burst_len_sec + 2.0
        while time.monotonic() - t0 < churn_end:
            if time.monotonic() - t0 >= max(0.0, args.burst_at_sec - 1.0):
                for _name in driver.names():
                    if plan.decide("hard_kill"):
                        if kill_one():
                            kills += 1
                    elif plan.decide("spot_reclaim"):
                        if reclaim_one():
                            reclaims += 1
            peak_fleet = max(peak_fleet, driver.size())
            time.sleep(0.5)
        gen.join(timeout=args.duration_sec + 30)

        # Guarantee the acceptance floor deterministically: if the seeded
        # draws came up short, keep reclaiming/killing (the autoscaler
        # replaces capacity in between).
        force_deadline = time.monotonic() + 20.0
        while (
            (reclaims < args.min_reclaims or kills < args.min_kills)
            and time.monotonic() < force_deadline
        ):
            if kills < args.min_kills:
                if kill_one():
                    kills += 1
                    continue
            elif reclaim_one():
                reclaims += 1
                continue
            time.sleep(0.25)  # fleet at floor: wait for replacement
        peak_fleet = max(peak_fleet, driver.size())

        if not wait_drained(controller, args.deadline_sec):
            problems.append(
                f"seed {seed}: churn drain did not complete "
                f"(counts {controller.counts()})"
            )
        else:
            time.sleep(0.1)
            # Idle tail: the autoscaler must bring the fleet back to min.
            tail_deadline = time.monotonic() + args.tail_sec
            while time.monotonic() < tail_deadline:
                if (
                    driver.size() <= args.min_agents
                    and scaler.scale_downs >= args.min_scale_downs
                ):
                    break
                time.sleep(0.1)
            tail_size = driver.size()
    finally:
        stop_scaler.set()
        scaler_thread.join(timeout=10)
        # The floor members retire through the same drain path — their
        # exits feed the drain assertions below too.
        driver.retire(driver.size())
        watcher.stop()
    if tail_size is None:
        controller.close()
        return problems

    counts = controller.counts()
    stats = traffic_done[0] if traffic_done else LoadGenStats()
    n_jobs = shards + 1 + stats.total_submitted()

    # ---- zero lost work, bit-identical output ----
    if counts.get("dead"):
        problems.append(
            f"seed {seed}: {counts['dead']} dead job(s) — churn alone must "
            "kill nothing"
        )
    if counts.get("failed"):
        problems.append(f"seed {seed}: {counts['failed']} failed job(s)")
    reduce_job = controller.job_snapshot(reduce_id)
    if reduce_job["state"] != "succeeded":
        problems.append(
            f"seed {seed}: churn reduce state {reduce_job['state']!r}"
        )
        controller.close()
        return problems
    got = canonical(reduce_job["result"])
    if got != reference.get("reduce"):
        problems.append(
            f"seed {seed}: churn reduce diverged from calm reference\n"
            f"  want {reference.get('reduce')}\n  got  {got}"
        )
    bad_states = {
        j: s for j, s in watcher.states.items() if s != "succeeded"
    }
    if bad_states:
        problems.append(
            f"seed {seed}: interactive jobs not succeeded: "
            f"{dict(list(bad_states.items())[:5])}"
        )

    # ---- zero double-billing ----
    if controller.usage is None:
        problems.append(f"seed {seed}: usage ledger disabled")
    else:
        billed = controller.usage.billed_tasks
        if billed != n_jobs:
            problems.append(
                f"seed {seed}: usage billed {billed} != jobs {n_jobs} "
                "(lost or double-billed work)"
            )
        multi = {
            jid: n
            for jid, n in controller.usage.job_billed_attempts().items()
            if n != 1
        }
        if multi:
            problems.append(f"seed {seed}: jobs billed != once: {multi}")

    # ---- churn actually happened, capacity came back ----
    if reclaims < args.min_reclaims:
        problems.append(
            f"seed {seed}: only {reclaims} spot reclaim(s) "
            f"(need >= {args.min_reclaims})"
        )
    if kills < args.min_kills:
        problems.append(
            f"seed {seed}: only {kills} hard kill(s) "
            f"(need >= {args.min_kills})"
        )
    if scaler.replacements < 1:
        problems.append(
            f"seed {seed}: autoscaler never replaced reclaimed capacity"
        )

    # ---- elasticity: up on pressure, down on idle ----
    if scaler.scale_ups < 1:
        problems.append(
            f"seed {seed}: no scale-up during a 10× burst"
        )
    if scaler.scale_downs < args.min_scale_downs:
        problems.append(
            f"seed {seed}: {scaler.scale_downs} scale-down(s) "
            f"(need >= {args.min_scale_downs})"
        )
    if peak_fleet <= args.min_agents:
        problems.append(
            f"seed {seed}: fleet never grew past its floor "
            f"(peak {peak_fleet})"
        )
    if tail_size > args.min_agents:
        problems.append(
            f"seed {seed}: idle tail left {tail_size} members "
            f"(min {args.min_agents})"
        )
    snap = controller.metrics.snapshot()
    if not snap.get("fleet_size", {}).get("series"):
        problems.append(
            f"seed {seed}: fleet_size family missing from the controller "
            "registry"
        )
    if not snap.get("autoscale_decisions_total", {}).get("series"):
        problems.append(f"seed {seed}: autoscale_decisions_total missing")

    # ---- every graceful retirement exited via the drain path ----
    summary = controller.agents_summary()
    for entry in driver.retired:
        name = entry["name"]
        if not entry["clean_exit"]:
            problems.append(f"seed {seed}: retired {name} did not exit")
        if entry["spool_len"]:
            problems.append(
                f"seed {seed}: retired {name} left {entry['spool_len']} "
                "spooled result(s)"
            )
        if not summary.get(name, {}).get("draining"):
            problems.append(
                f"seed {seed}: controller never marked {name} draining"
            )
    # No stranded leases: probed at the instant each retirement completed
    # (post-drain everything is terminal, so only the live probe counts).
    if driver.stranded:
        problems.append(
            f"seed {seed}: stranded leases at retirement: "
            f"{driver.stranded[:5]}"
        )

    # ---- bounded interactive p99 degradation ----
    p99 = percentile(watcher.all_latencies(), 0.99)
    ref_p99 = reference.get("p99")
    if p99 is None:
        problems.append(f"seed {seed}: churn run measured no latencies")
    elif ref_p99:
        bound = max(args.p99_floor_sec, args.p99_factor * ref_p99)
        if p99 > bound:
            problems.append(
                f"seed {seed}: interactive p99 {p99:.3f}s exceeds bound "
                f"{bound:.3f}s (reference {ref_p99:.3f}s)"
            )

    # ---- journal replays to the identical ledger/scheduler state ----
    job_ids = stats.job_ids() + [reduce_id] + [
        jid for jid in controller._jobs  # noqa: SLF001 — soak introspection
    ]
    live_snap = {
        jid: controller.job_snapshot(jid) for jid in set(job_ids)
    }
    live_usage_attempts = (
        controller.usage.job_billed_attempts()
        if controller.usage is not None else {}
    )
    live_billed = (
        controller.usage.billed_tasks if controller.usage is not None else 0
    )
    journal_path = os.path.join(tmp, "elastic_journal.jsonl")
    controller.close()
    replayed = Controller(
        lease_ttl_sec=args.lease_ttl_sec,
        sched=SchedConfig(policy="fair"),
        journal_path=journal_path,
    )
    try:
        if replayed.journal_torn_tail or replayed.journal_replay_skipped:
            problems.append(
                f"seed {seed}: journal replay damage "
                f"(torn_tail {replayed.journal_torn_tail}, skipped "
                f"{replayed.journal_replay_skipped})"
            )
        if replayed.queue_depth() != 0:
            problems.append(
                f"seed {seed}: replayed scheduler queue depth "
                f"{replayed.queue_depth()} != 0"
            )
        for jid, live in live_snap.items():
            try:
                re = replayed.job_snapshot(jid)
            except KeyError:
                problems.append(f"seed {seed}: job {jid} lost in replay")
                continue
            for k in ("state", "job_epoch", "attempts"):
                if re[k] != live[k]:
                    problems.append(
                        f"seed {seed}: replay {jid} {k} {re[k]!r} != "
                        f"live {live[k]!r}"
                    )
                    break
        if replayed.usage is not None:
            if replayed.usage.billed_tasks != live_billed:
                problems.append(
                    f"seed {seed}: replayed ledger billed "
                    f"{replayed.usage.billed_tasks} != live {live_billed}"
                )
            if replayed.usage.job_billed_attempts() != live_usage_attempts:
                problems.append(
                    f"seed {seed}: replayed per-job billing diverged"
                )
    finally:
        replayed.close()

    print(json.dumps({
        "scenario": "churn", "seed": seed, "jobs": n_jobs,
        "interactive": stats.total_submitted(),
        "rejected": stats.total_rejected(),
        "reclaims": reclaims, "kills": kills,
        "scale_ups": scaler.scale_ups, "scale_downs": scaler.scale_downs,
        "replacements": scaler.replacements, "peak_fleet": peak_fleet,
        "p99_s": round(p99, 3) if p99 is not None else None,
        "ref_p99_s": round(ref_p99, 3) if ref_p99 else None,
        "counts": counts, "ok": not problems,
    }, sort_keys=True))
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--seeds", type=str, default="",
                    help="comma-separated seed list (overrides --seed)")
    ap.add_argument("--shards", type=int, default=16)
    ap.add_argument("--rows-per-shard", type=int, default=50)
    ap.add_argument("--duration-sec", type=float, default=12.0,
                    help="open-loop traffic window")
    ap.add_argument("--base-rate", type=float, default=2.0)
    ap.add_argument("--burst-factor", type=float, default=10.0)
    ap.add_argument("--burst-at-sec", type=float, default=3.0)
    ap.add_argument("--burst-len-sec", type=float, default=4.0)
    ap.add_argument("--probe-sleep-ms", type=float, default=150.0,
                    help="interactive service time (what makes the burst "
                         "queue)")
    ap.add_argument("--interactive-deadline-sec", type=float, default=45.0)
    ap.add_argument("--min-agents", type=int, default=2)
    ap.add_argument("--max-agents", type=int, default=6)
    ap.add_argument("--lease-ttl-sec", type=float, default=2.0)
    ap.add_argument("--reclaim-prob", type=float, default=0.06,
                    help="per-member per-tick spot_reclaim probability")
    ap.add_argument("--kill-prob", type=float, default=0.03)
    ap.add_argument("--min-reclaims", type=int, default=3)
    ap.add_argument("--min-kills", type=int, default=1)
    ap.add_argument("--min-scale-downs", type=int, default=2)
    ap.add_argument("--p99-factor", type=float, default=25.0,
                    help="churn p99 must stay within factor× the calm p99")
    ap.add_argument("--p99-floor-sec", type=float, default=5.0,
                    help="absolute p99 bound floor (CI noise guard)")
    ap.add_argument("--tail-sec", type=float, default=25.0,
                    help="idle window for scale-down to reach the floor")
    ap.add_argument("--deadline-sec", type=float, default=120.0)
    ap.add_argument("--quick", action="store_true",
                    help="CI sizing: shrinks traffic for < 60 s total")
    args = ap.parse_args(argv)

    if args.quick:
        args.shards = min(args.shards, 12)
        args.rows_per_shard = min(args.rows_per_shard, 40)
        args.duration_sec = min(args.duration_sec, 10.0)
        args.burst_at_sec = min(args.burst_at_sec, 3.0)
        args.burst_len_sec = min(args.burst_len_sec, 3.0)
        args.deadline_sec = min(args.deadline_sec, 60.0)
        args.tail_sec = min(args.tail_sec, 20.0)

    seeds = (
        [int(s) for s in args.seeds.split(",") if s.strip()]
        if args.seeds else [args.seed]
    )

    tmp_root = tempfile.mkdtemp(prefix="elastic_soak_")
    plugin_path = os.path.join(tmp_root, "elastic_probe_plugin.py")
    with open(plugin_path, "w", encoding="utf-8") as f:
        f.write(PLUGIN_SRC)
    from agent_tpu.ops import load_plugins

    if "elastic_probe" not in load_plugins(plugin_path):
        from agent_tpu.ops import OPS_LOAD_ERRORS

        print(f"elastic_probe plugin failed to load: {OPS_LOAD_ERRORS}")
        return 1

    problems: List[str] = []
    t0 = time.monotonic()
    for seed in seeds:
        with tempfile.TemporaryDirectory(
            prefix=f"elastic_round_{seed}_", dir=tmp_root
        ) as tmp:
            csv_path = os.path.join(tmp, "rows.csv")
            build_csv(csv_path, args.shards * args.rows_per_shard)
            reference, ref_problems = run_reference(
                tmp, csv_path, args.shards, args.rows_per_shard, args, seed
            )
            problems += ref_problems
            if not ref_problems:
                problems += run_churn(
                    tmp, csv_path, args.shards, args.rows_per_shard, args,
                    seed, reference,
                )

    elapsed = round(time.monotonic() - t0, 3)
    if problems:
        for p in problems:
            print(p)
        print(f"FAILED: {len(problems)} problem(s) in {elapsed}s")
        return 1
    print(
        f"elastic soak: OK ({len(seeds)} seed(s), {args.shards} shards, "
        f"{elapsed}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
