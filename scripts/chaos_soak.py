#!/usr/bin/env python3
"""Chaos soak: drain a multi-shard CSV map-reduce job under a seeded fault
plan and prove the fault-tolerance invariants hold (ISSUE 3).

Three scenarios per seed, all in-process (real ``Agent`` loop + real
``Controller`` through ``chaos.LoopbackSession`` — deterministic, no
sockets, no jax):

1. **Reference drain** — no faults; records the reduce result.
2. **Chaos drain** — the same job under a ``FaultPlan`` injecting transport
   drops, fabricated 500s, duplicated result deliveries, lease drops,
   duplicate tasks, stale epochs, and agent crash-restarts mid-lease.
   Asserts: every job reaches a terminal state, the reduce output is
   bit-identical to the reference (volatile timing fields excluded), no
   result was applied twice (accepted successes == jobs; rejections cover
   the injected duplicates), and every injected fault is accounted for in
   metrics (``chaos_faults_injected_total`` agent-side,
   ``controller_faults_injected_total`` controller-side).
3. **Controller outage** — results complete while the controller is "down"
   (shorter than the lease TTL), spool, then redeliver: zero shard
   re-executions, ``result_post_failures_total`` + redelivery counters
   observed.

Exit 0 = all seeds clean; 1 = problems (listed one per line). CI runs
``--seed 7 --shards 16 --quick``; the acceptance bar is ≥3 seeds, e.g.
``--seeds 7,8,9``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from agent_tpu.agent.app import Agent
from agent_tpu.chaos import ChaosSession, FaultPlan, GatedSession, LoopbackSession
from agent_tpu.config import AgentConfig, Config, ObsConfig, SchedConfig
from agent_tpu.controller.core import TERMINAL_STATES, Controller
from agent_tpu.obs.metrics import MetricsRegistry

# Timing fields legitimately differ run to run; everything else in the
# reduce result must match bit for bit. `usage` (ISSUE 9) is wall-clock
# seconds by definition — volatile like the timings it rides beside.
VOLATILE_KEYS = ("compute_time_ms", "duration_ms", "timings", "trace",
                 "usage")


def canonical(result: Any) -> str:
    if isinstance(result, dict):
        result = {k: v for k, v in result.items() if k not in VOLATILE_KEYS}
    return json.dumps(result, sort_keys=True, default=str)


def build_csv(path: str, rows: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text,risk\n")
        for i in range(rows):
            f.write(f'{i},"record {i}",{(i % 17) * 0.25}\n')


def make_agent(
    controller: Controller,
    name: str,
    plan: Optional[FaultPlan] = None,
    max_tasks: int = 2,
) -> Agent:
    from agent_tpu.config import env_bool, env_int

    cfg = Config(agent=AgentConfig(
        controller_url="http://loopback", agent_name=name,
        tasks=("risk_accumulate",), max_tasks=max_tasks,
        idle_sleep_sec=0.0, error_backoff_sec=0.0,
        retry_base_sec=0.001, retry_max_sec=0.01,
        # --pipeline mode honors the data-plane env knobs (the config here
        # is built directly, so from_env() never runs for soak agents).
        stage_workers=max(0, env_int("STAGE_WORKERS", 0)),
        stage_autotune=env_bool("STAGE_AUTOTUNE", True),
        feed_double_buffer=env_bool("FEED_DOUBLE_BUFFER", True),
    ))
    registry = MetricsRegistry()
    session: Any = LoopbackSession(controller)
    if plan is not None:
        session = ChaosSession(session, plan, registry=registry)
    agent = Agent(config=cfg, session=session, registry=registry)
    agent._profile = {"tier": "chaos-soak"}  # skip hardware probing
    return agent


def submit_job(
    controller: Controller, csv_path: str, shards: int, rows_per_shard: int
) -> Tuple[List[str], str]:
    shard_ids, reduce_id = controller.submit_csv_job(
        csv_path,
        total_rows=shards * rows_per_shard,
        shard_size=rows_per_shard,
        map_op="risk_accumulate",
        extra_payload={"field": "risk"},
        reduce_op="risk_accumulate",
        collect_partials=True,
    )
    return shard_ids, reduce_id


def drive_drain_pipelined(
    controller: Controller,
    agent: Agent,
    deadline_sec: float,
) -> Tuple[List[Agent], int, bool]:
    """ISSUE 6: drive ONE agent through the real ``PipelineRunner`` — the
    staging pool (STAGE_WORKERS/STAGE_AUTOTUNE honored via config) + the
    double-buffered feed — instead of the serial step loop. Crash-restart
    injection is a step-loop construct and is not consulted here (the plan
    simply never decides ``agent_crash``, so the fault accounting stays
    consistent). Same return shape as :func:`drive_drain`."""
    import threading

    from agent_tpu.agent.pipeline import PipelineRunner

    # The poster thread must post through the SAME loopback/chaos session
    # the lease loop uses (its default — a fresh requests.Session — would
    # try to reach the fake URL over the network).
    agent.post_session_factory = lambda: agent.session
    agent.running = True
    deadline = time.monotonic() + deadline_sec

    def watch() -> None:
        while not controller.drained() and time.monotonic() < deadline:
            time.sleep(0.02)
        agent.running = False

    watcher = threading.Thread(target=watch, daemon=True)
    watcher.start()
    PipelineRunner(agent, depth=2).run()
    watcher.join(timeout=10)
    return [agent], 0, controller.drained()


def drive_drain(
    controller: Controller,
    agents: List[Agent],
    plan: Optional[FaultPlan],
    deadline_sec: float,
    pipeline: bool = False,
) -> Tuple[List[Agent], int, bool]:
    """Step the agents until the controller drains (or the deadline hits).

    ``agent_crash`` decisions abandon a *granted* lease and replace the
    agent with a fresh incarnation (same registry — counters continue): the
    crash-restart-mid-lease fault. Returns (final agents, crashes, drained).
    """
    if pipeline:
        return drive_drain_pipelined(controller, agents[0], deadline_sec)
    crashes = 0
    deadline = time.monotonic() + deadline_sec
    while not controller.drained() and time.monotonic() < deadline:
        for i, agent in enumerate(agents):
            agent.flush_spool()
            try:
                leased = agent.lease_once()
            except RuntimeError:
                continue  # injected lease fault; backoff is irrelevant here
            if leased is None:
                continue
            if plan is not None and plan.decide("agent_crash"):
                crashes += 1
                fresh = Agent(
                    config=agent.config, session=agent.session,
                    registry=agent.obs, recorder=agent.recorder,
                )
                fresh._profile = agent._profile
                fresh.tasks_done = agent.tasks_done
                agents[i] = fresh  # the granted lease dies with the old one
                continue
            lease_id, tasks = leased
            for task in tasks:
                agent.run_task(lease_id, task)
        # Let the TTL sweeper publish expiries even when every agent idles.
        controller.sweep()
    for agent in agents:
        agent.flush_spool(force=True)
    return agents, crashes, controller.drained()


def executed_total(agents: List[Agent]) -> int:
    return sum(
        s.get("value", 0)
        for a in agents
        for s in a.obs.snapshot().get("tasks_total", {}).get("series", [])
    )


def counter_total(registry: MetricsRegistry, name: str,
                  **match: str) -> float:
    total = 0.0
    for s in registry.snapshot().get(name, {}).get("series", []):
        labels = s.get("labels", {})
        if all(labels.get(k) == v for k, v in match.items()):
            total += s.get("value", 0)
    return total


def run_reference(csv_path: str, shards: int, rows_per_shard: int,
                  deadline_sec: float,
                  pipeline: bool = False) -> Tuple[str, List[str]]:
    problems: List[str] = []
    controller = Controller(lease_ttl_sec=30.0)
    _, reduce_id = submit_job(controller, csv_path, shards, rows_per_shard)
    agents = [make_agent(controller, "ref-agent")]
    _, _, drained = drive_drain(controller, agents, None, deadline_sec,
                                pipeline=pipeline)
    if not drained:
        problems.append("reference drain did not complete")
        return "", problems
    job = controller.job_snapshot(reduce_id)
    if job["state"] != "succeeded":
        problems.append(f"reference reduce state {job['state']!r}")
        return "", problems
    return canonical(job["result"]), problems


def run_chaos(
    seed: int, csv_path: str, shards: int, rows_per_shard: int,
    fault_rate: float, n_agents: int, deadline_sec: float,
    reference: str, pipeline: bool = False,
) -> List[str]:
    problems: List[str] = []
    if pipeline:
        n_agents = 1  # the pipelined drive owns one device loop
    plan = FaultPlan(
        seed=seed,
        drop_request=fault_rate * 0.5,
        drop_response=fault_rate * 0.25,
        http_500=fault_rate * 0.25,
        duplicate_result=0.10,
        drop_lease=0.10,
        duplicate_task=0.05,
        stale_epoch=0.05,
        # Crash-restart is a step-loop construct; the pipelined drive never
        # consults it, so keep the plan's decision stream comparable.
        agent_crash=0.0 if pipeline else 0.05,
    )
    # Short TTL so abandoned leases requeue inside the deadline; a generous
    # per-job budget because chaos retries must not exhaust it (transport
    # faults never reach `report`, but stale-epoch re-leases burn attempts).
    controller = Controller(
        lease_ttl_sec=0.5, max_attempts=10, requeue_delay_sec=0.01,
        sweep_interval_sec=0.1,
    )
    controller.inject(plan=plan)
    _, reduce_id = submit_job(controller, csv_path, shards, rows_per_shard)
    agents = [
        make_agent(controller, f"chaos-{seed}-{i}", plan=plan)
        for i in range(n_agents)
    ]
    try:
        agents, crashes, drained = drive_drain(
            controller, agents, plan, deadline_sec, pipeline=pipeline
        )
    finally:
        controller.close()

    n_jobs = shards + 1
    if not drained:
        problems.append(
            f"seed {seed}: chaos drain did not reach terminal states "
            f"(counts {controller.counts()})"
        )
        return problems
    for state in controller.counts():
        if state not in TERMINAL_STATES:
            problems.append(f"seed {seed}: non-terminal state {state!r}")
    reduce_job = controller.job_snapshot(reduce_id)
    if reduce_job["state"] != "succeeded":
        problems.append(
            f"seed {seed}: reduce state {reduce_job['state']!r} "
            f"(error {reduce_job['error']!r})"
        )
        return problems
    got = canonical(reduce_job["result"])
    if got != reference:
        problems.append(
            f"seed {seed}: reduce result diverged from fault-free reference\n"
            f"  want {reference}\n  got  {got}"
        )

    # No double application: exactly one accepted success per job.
    accepted = counter_total(
        controller.metrics, "controller_results_total", outcome="succeeded"
    )
    if accepted != n_jobs:
        problems.append(
            f"seed {seed}: accepted successes {accepted} != jobs {n_jobs} "
            "(a result was applied twice or lost)"
        )
    # Usage billing exactly-once (ISSUE 9): under duplicate deliveries,
    # stale epochs, and crash-retries, every job bills exactly ONE result
    # application into the showback ledger — billed task count matches the
    # accepted successes, and no job carries more than one billed attempt.
    if controller.usage is not None:
        billed = controller.usage.billed_tasks
        if billed != n_jobs:
            problems.append(
                f"seed {seed}: usage billed {billed} tasks != jobs {n_jobs} "
                "(a retry/duplicate double-billed or a result went unbilled)"
            )
        multi = {
            jid: n
            for jid, n in controller.usage.job_billed_attempts().items()
            if n != 1
        }
        if multi:
            problems.append(
                f"seed {seed}: jobs billed != once: {multi}"
            )

    # Every duplicate delivery must surface as a counted rejection; the
    # epoch fence + duplicate guard are the only things standing between an
    # at-least-once transport and double application.
    dup_injected = plan.counts.get("duplicate_result", 0)
    rejected = counter_total(
        controller.metrics, "controller_results_total", outcome="duplicate"
    ) + counter_total(
        controller.metrics, "controller_results_total", outcome="stale_epoch"
    )
    if rejected < dup_injected:
        problems.append(
            f"seed {seed}: rejections {rejected} < injected duplicate "
            f"deliveries {dup_injected}"
        )

    # Fault accounting: agent-side transport injections all land in the
    # fleet metric; controller-side consumed injections land in the
    # controller metric (duplicate_task/stale_epoch only *consume* when a
    # task leases, so metric <= plan count for those).
    for fault in ("drop_request", "drop_response", "http_500",
                  "duplicate_result", "delay"):
        injected = plan.counts.get(fault, 0)
        observed = sum(
            counter_total(a.obs, "chaos_faults_injected_total", fault=fault)
            for a in agents
        )
        if observed != injected:
            problems.append(
                f"seed {seed}: {fault} metric {observed} != injected {injected}"
            )
    drop_lease_metric = counter_total(
        controller.metrics, "controller_faults_injected_total",
        fault="drop_lease",
    )
    if drop_lease_metric != plan.counts.get("drop_lease", 0):
        problems.append(
            f"seed {seed}: drop_lease metric {drop_lease_metric} != "
            f"injected {plan.counts.get('drop_lease', 0)}"
        )
    for fault in ("duplicate_task", "stale_epoch"):
        consumed = counter_total(
            controller.metrics, "controller_faults_injected_total",
            fault=fault,
        )
        if consumed > plan.counts.get(fault, 0):
            problems.append(
                f"seed {seed}: {fault} consumed {consumed} > decided "
                f"{plan.counts.get(fault, 0)}"
            )

    total_injected = plan.total_injected()
    print(json.dumps({
        "scenario": "chaos", "seed": seed, "shards": shards,
        "jobs": n_jobs, "crashes": crashes,
        "faults_injected": dict(sorted(plan.counts.items())),
        "total_injected": total_injected,
        "stale_results": controller.stale_results,
        "counts": controller.counts(),
        "ok": not problems,
    }, sort_keys=True))
    if total_injected == 0:
        problems.append(f"seed {seed}: plan injected zero faults — soak vacuous")
    return problems


def run_anomaly_drill(
    seed: int, deadline_sec: float, calm: bool = False,
) -> List[str]:
    """ISSUE 20: the forensics drill. A calm trickle warms the detector's
    baseline, then a delay-fault burst stalls the agent while submissions
    continue — queue depth spikes far past the robust baseline and the
    detector must confirm exactly ONE anomaly episode, which must snapshot
    exactly ONE incident bundle. With ``calm=True`` the burst never
    happens and the same drive must produce ZERO anomalies and ZERO
    bundles (the false-positive gate)."""
    problems: List[str] = []
    label = "calm" if calm else "burst"
    with tempfile.TemporaryDirectory(prefix=f"anomaly_{label}_") as tmp:
        obs = ObsConfig(
            tsdb_dir=os.path.join(tmp, "tsdb"), tsdb_interval_sec=0.03,
            anomaly_window=60, anomaly_warmup=10, anomaly_z=8.0,
            anomaly_confirm=2, anomaly_clear=5,
            incident_dir=os.path.join(tmp, "incidents"),
            incident_min_interval_sec=60.0,
        )
        controller = Controller(
            lease_ttl_sec=5.0, max_attempts=5, requeue_delay_sec=0.01,
            obs=obs,
        )
        plan = FaultPlan(seed=seed)  # delay flipped live for the burst
        agent = make_agent(controller, f"anom-{seed}", plan=plan)
        submitted = 0

        def pump(n: int) -> None:
            nonlocal submitted
            for _ in range(n):
                controller.submit(
                    "risk_accumulate",
                    {"values": [float(submitted % 5), 1.0]},
                    job_id=f"anom-{label}-{seed}-{submitted}",
                )
                submitted += 1

        def drive(until: float, per_tick: int) -> None:
            while time.monotonic() < until:
                pump(per_tick)
                agent.flush_spool()
                try:
                    leased = agent.lease_once()
                except RuntimeError:
                    leased = None
                if leased is not None:
                    lease_id, tasks = leased
                    for task in tasks:
                        agent.run_task(lease_id, task)
                controller.sweep()  # interval-gated TSDB sample rides here
                time.sleep(0.005)

        try:
            # Calm warmup: the trickle drains as fast as it arrives, so
            # the baseline learns a near-zero queue.
            drive(time.monotonic() + 1.5, per_tick=1)
            if not calm:
                # The burst: every transport request now sleeps, the agent
                # stalls, and submissions keep landing.
                plan.delay = 1.0
                plan.delay_max_sec = 0.12
                drive(time.monotonic() + 1.2, per_tick=4)
                plan.delay = 0.0
            # Recovery drain: everything terminal, detector sees the
            # episode clear.
            deadline = time.monotonic() + deadline_sec
            while not controller.drained() and time.monotonic() < deadline:
                agent.flush_spool()
                try:
                    leased = agent.lease_once()
                except RuntimeError:
                    leased = None
                if leased is not None:
                    lease_id, tasks = leased
                    for task in tasks:
                        agent.run_task(lease_id, task)
                controller.sweep()
            agent.flush_spool(force=True)
            drained = controller.drained()

            astats = controller.anomaly.stats() \
                if controller.anomaly is not None else {}
            bundles = controller.incidents.list() \
                if controller.incidents is not None else []
            anomaly_bundles = [b for b in bundles if b["kind"] == "anomaly"]
            if not drained:
                problems.append(
                    f"anomaly drill ({label}, seed {seed}): drain did not "
                    f"complete (counts {controller.counts()})"
                )
            if calm:
                if astats.get("events_total", 0) != 0:
                    problems.append(
                        f"anomaly drill (calm, seed {seed}): false "
                        f"positive — detector confirmed {astats}"
                    )
                if anomaly_bundles:
                    problems.append(
                        f"anomaly drill (calm, seed {seed}): "
                        f"{len(anomaly_bundles)} unexpected incident "
                        "bundle(s)"
                    )
            else:
                if plan.counts.get("delay", 0) == 0:
                    problems.append(
                        f"anomaly drill (burst, seed {seed}): no delay "
                        "faults injected — drill vacuous"
                    )
                if astats.get("events_total", 0) != 1:
                    problems.append(
                        f"anomaly drill (burst, seed {seed}): expected "
                        f"exactly 1 confirmed episode, got {astats}"
                    )
                if len(anomaly_bundles) != 1:
                    problems.append(
                        f"anomaly drill (burst, seed {seed}): expected "
                        f"exactly 1 incident bundle, got "
                        f"{[b['id'] for b in anomaly_bundles]}"
                    )
                elif anomaly_bundles[0]["key"] != "queue_depth":
                    problems.append(
                        f"anomaly drill (burst, seed {seed}): bundle "
                        f"watched {anomaly_bundles[0]['key']!r}, expected "
                        "queue_depth"
                    )
                else:
                    # The bundle is a real forensic: correlated sections
                    # present and the full body fetchable by id.
                    body = controller.incidents.get(anomaly_bundles[0]["id"])
                    for section in ("timeseries", "status", "health"):
                        if section not in (body or {}).get("sections", {}):
                            problems.append(
                                f"anomaly drill (burst, seed {seed}): "
                                f"bundle missing section {section!r}"
                            )
            if not calm:
                print(json.dumps({
                    "scenario": "anomaly_drill", "seed": seed,
                    "submitted": submitted,
                    "delays_injected": plan.counts.get("delay", 0),
                    "detector": astats,
                    "incidents": [b["id"] for b in anomaly_bundles],
                    "ok": not problems,
                }, sort_keys=True))
        finally:
            controller.close()
    return problems


def run_fair(
    seed: int, csv_path: str, shards: int, rows_per_shard: int,
    fault_rate: float, n_agents: int, tenants: int, deadline_sec: float,
    reference: str,
) -> List[str]:
    """Fair-policy soak (ISSUE 4): a bulk tenant's sharded map-reduce
    drains alongside other tenants' priority-8 interactive singles under
    the same seeded fault plan. Asserts the fifo-chaos invariants (terminal
    states, bit-identical reduce, single application) PLUS the fairness
    bar: no tenant starves (zero ``dead``), every priority-8 single is
    first-leased before ≥90% of bulk shards, and the per-tenant
    ``sched_queue_depth`` / starvation-age series exist. The seeded plan +
    deterministic scheduler make the whole drain replayable."""
    problems: List[str] = []
    plan = FaultPlan(
        seed=seed,
        drop_request=fault_rate * 0.5,
        drop_response=fault_rate * 0.25,
        http_500=fault_rate * 0.25,
        duplicate_result=0.10,
        drop_lease=0.10,
        duplicate_task=0.05,
        stale_epoch=0.05,
    )
    controller = Controller(
        lease_ttl_sec=0.5, max_attempts=10, requeue_delay_sec=0.01,
        sweep_interval_sec=0.1, sched=SchedConfig(policy="fair"),
    )
    controller.inject(plan=plan)
    shard_ids, reduce_id = controller.submit_csv_job(
        csv_path,
        total_rows=shards * rows_per_shard,
        shard_size=rows_per_shard,
        map_op="risk_accumulate",
        extra_payload={"field": "risk"},
        reduce_op="risk_accumulate",
        collect_partials=True,
        tenant="bulk",
    )
    single_ids: List[str] = []
    for t in range(1, max(2, tenants)):
        for k in range(4):
            single_ids.append(controller.submit(
                "risk_accumulate",
                {
                    "source_uri": csv_path,
                    "start_row": (k % shards) * rows_per_shard,
                    "shard_size": rows_per_shard,
                    "field": "risk",
                },
                tenant=f"rt{t}",
                priority=8,
            ))
    agents = [
        make_agent(controller, f"fair-{seed}-{i}", plan=plan)
        for i in range(n_agents)
    ]
    try:
        agents, _, drained = drive_drain(
            controller, agents, plan, deadline_sec
        )
    finally:
        controller.close()

    n_jobs = shards + 1 + len(single_ids)
    if not drained:
        return [
            f"seed {seed}: fair drain did not reach terminal states "
            f"(counts {controller.counts()})"
        ]
    counts = controller.counts()
    if counts.get("dead"):
        problems.append(
            f"seed {seed}: {counts['dead']} dead job(s) under fair policy "
            "(starvation or retry exhaustion)"
        )
    reduce_job = controller.job_snapshot(reduce_id)
    if reduce_job["state"] != "succeeded":
        problems.append(
            f"seed {seed}: fair reduce state {reduce_job['state']!r}"
        )
        return problems
    got = canonical(reduce_job["result"])
    if got != reference:
        problems.append(
            f"seed {seed}: fair reduce diverged from fault-free reference\n"
            f"  want {reference}\n  got  {got}"
        )
    accepted = counter_total(
        controller.metrics, "controller_results_total", outcome="succeeded"
    )
    if accepted != n_jobs:
        problems.append(
            f"seed {seed}: accepted successes {accepted} != jobs {n_jobs}"
        )

    # Fairness: first-lease order from the flight recorder — every
    # priority-8 single must beat ≥90% of bulk shards to its first lease.
    first_lease: Dict[str, int] = {}
    for ev in controller.recorder.events():
        if ev.get("kind") == "lease" and ev.get("job_id") not in first_lease:
            first_lease[ev["job_id"]] = len(first_lease)
    missing = [j for j in single_ids + shard_ids if j not in first_lease]
    if missing:
        problems.append(f"seed {seed}: jobs never leased: {missing[:5]}")
    else:
        bulk_pos = sorted(first_lease[j] for j in shard_ids)
        p90_bulk = bulk_pos[int(0.9 * (len(bulk_pos) - 1))]
        late = [j for j in single_ids if first_lease[j] > p90_bulk]
        if late:
            problems.append(
                f"seed {seed}: {len(late)} priority-8 single(s) first-leased "
                f"after the 90th-percentile bulk shard (fair-share failed)"
            )
    snap = controller.metrics.snapshot()
    tenants_seen = {
        s["labels"].get("tenant")
        for s in snap.get("sched_queue_depth", {}).get("series", [])
    }
    want_tenants = {"bulk"} | {f"rt{t}" for t in range(1, max(2, tenants))}
    if not want_tenants <= tenants_seen:
        problems.append(
            f"seed {seed}: sched_queue_depth missing tenants "
            f"{sorted(want_tenants - tenants_seen)}"
        )
    if not snap.get("sched_starvation_age_seconds", {}).get("series"):
        problems.append(f"seed {seed}: no starvation-age observations")

    print(json.dumps({
        "scenario": "fair", "seed": seed, "shards": shards,
        "tenants": sorted(want_tenants), "jobs": n_jobs,
        "faults_injected": dict(sorted(plan.counts.items())),
        "counts": counts, "ok": not problems,
    }, sort_keys=True))
    return problems


def run_outage(seed: int, csv_path: str, shards: int, rows_per_shard: int,
               deadline_sec: float) -> List[str]:
    """Controller 'outage' shorter than the lease TTL: completed results
    spool and redeliver; no shard re-executes."""
    problems: List[str] = []
    controller = Controller(lease_ttl_sec=60.0)
    shard_ids, reduce_id = submit_job(
        controller, csv_path, shards, rows_per_shard
    )
    agent = make_agent(controller, f"outage-{seed}", max_tasks=shards)
    gate = GatedSession(agent.session)
    agent.session = gate

    # Lease every shard, then lose the controller before anything posts.
    leased = agent.lease_once()
    if leased is None:
        return [f"seed {seed}: outage scenario leased nothing"]
    lease_id, tasks = leased
    gate.down = True
    for task in tasks:
        agent.run_task(lease_id, task)  # executes; posts spool
    spooled = len(agent.spool)
    post_failures = counter_total(agent.obs, "result_post_failures_total")
    if spooled != len(tasks):
        problems.append(
            f"seed {seed}: {spooled} spooled != {len(tasks)} completed"
        )
    if post_failures != len(tasks):
        problems.append(
            f"seed {seed}: result_post_failures_total {post_failures} != "
            f"{len(tasks)}"
        )

    # Controller back inside the lease window → spool drains, reduce runs.
    gate.down = False
    delivered = agent.flush_spool(force=True)
    _, _, drained = drive_drain(controller, [agent], None, deadline_sec)
    if not drained:
        problems.append(f"seed {seed}: outage drain did not complete")
        return problems
    redelivered = counter_total(
        agent.obs, "result_redeliveries_total", outcome="delivered"
    )
    expired = counter_total(
        controller.metrics, "controller_lease_expirations_total"
    )
    reexecutions = executed_total([agent]) - (shards + 1)
    for jid in shard_ids:
        if controller.job_snapshot(jid)["attempts"] != 1:
            problems.append(f"seed {seed}: shard {jid} re-leased after outage")
    if reexecutions != 0:
        problems.append(
            f"seed {seed}: {reexecutions} re-executions after outage "
            "(spool should have redelivered instead)"
        )
    if delivered != spooled or redelivered != spooled:
        problems.append(
            f"seed {seed}: redelivered {redelivered} != spooled {spooled}"
        )
    if expired != 0:
        problems.append(
            f"seed {seed}: {expired} lease expirations during an outage "
            "shorter than the TTL"
        )
    if controller.job_snapshot(reduce_id)["state"] != "succeeded":
        problems.append(f"seed {seed}: reduce failed after outage")
    print(json.dumps({
        "scenario": "outage", "seed": seed, "shards": shards,
        "spooled": spooled, "redelivered": redelivered,
        "post_failures": post_failures, "re_executions": reexecutions,
        "ok": not problems,
    }, sort_keys=True))
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--seeds", type=str, default="",
                    help="comma-separated seed list (overrides --seed)")
    ap.add_argument("--shards", type=int, default=16)
    ap.add_argument("--rows-per-shard", type=int, default=50)
    ap.add_argument("--fault-rate", type=float, default=0.25,
                    help="total transport-fault probability per request")
    ap.add_argument("--agents", type=int, default=2)
    ap.add_argument("--deadline-sec", type=float, default=120.0,
                    help="per-scenario wall-clock budget")
    ap.add_argument("--quick", action="store_true",
                    help="CI sizing: caps shards/rows/deadline for <1 min")
    ap.add_argument("--policy", choices=("fifo", "fair"), default="fifo",
                    help="scheduler policy under chaos (ISSUE 4); `fair` "
                         "adds multi-tenant fairness assertions")
    ap.add_argument("--tenants", type=int, default=3,
                    help="tenant count for --policy fair (1 bulk + N-1 "
                         "interactive)")
    ap.add_argument("--pipeline", action="store_true",
                    help="drive the reference + chaos drains through the "
                         "real PipelineRunner (staging pool, "
                         "STAGE_WORKERS/STAGE_AUTOTUNE honored) instead of "
                         "the serial step loop (ISSUE 6)")
    args = ap.parse_args(argv)

    shards = args.shards
    rows = args.rows_per_shard
    deadline = args.deadline_sec
    if args.quick:
        shards = min(shards, 16)
        rows = min(rows, 25)
        deadline = min(deadline, 45.0)
    seeds = (
        [int(s) for s in args.seeds.split(",") if s.strip()]
        if args.seeds else [args.seed]
    )

    problems: List[str] = []
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as tmp:
        csv_path = os.path.join(tmp, "rows.csv")
        build_csv(csv_path, shards * rows)
        reference, ref_problems = run_reference(csv_path, shards, rows,
                                                deadline,
                                                pipeline=args.pipeline)
        problems += ref_problems
        if not ref_problems:
            for seed in seeds:
                if args.policy == "fair":
                    problems += run_fair(
                        seed, csv_path, shards, rows, args.fault_rate,
                        args.agents, args.tenants, deadline, reference,
                    )
                else:
                    problems += run_chaos(
                        seed, csv_path, shards, rows, args.fault_rate,
                        args.agents, deadline, reference,
                        pipeline=args.pipeline,
                    )
                    # The outage scenario is deliberately step-driven (it
                    # gates the session mid-lease); it runs serial either
                    # way.
                    problems += run_outage(
                        seed, csv_path, shards, rows, deadline
                    )
            # ISSUE 20 forensics drill: one latency burst must confirm
            # exactly one anomaly + one incident bundle, and calm seeded
            # drives must confirm NONE (the false-positive gate).
            if args.policy == "fifo":
                problems += run_anomaly_drill(seeds[0], deadline)
                for calm_seed in range(seeds[0] + 100, seeds[0] + 105):
                    problems += run_anomaly_drill(
                        calm_seed, deadline, calm=True
                    )

    elapsed = round(time.monotonic() - t0, 3)
    if problems:
        for p in problems:
            print(p)
        print(f"FAILED: {len(problems)} problem(s) in {elapsed}s")
        return 1
    print(f"chaos soak: OK ({len(seeds)} seed(s), {shards} shards, {elapsed}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
