#!/usr/bin/env python3
"""Scheduler fairness smoke (ISSUE 4) — the CI gate next to the metrics
smoke.

Two tenants share a fair-policy controller, driven by the real ``Agent``
loop over ``chaos.LoopbackSession`` (in-process, deterministic, no jax):

- tenant ``bulk`` submits one 64-shard CSV drain at the default priority —
  the traffic class that starves everything behind it under plain FIFO;
- tenant ``rt`` submits a handful of priority-9 singles at the same time.

Asserts:

1. **Priority wins**: every priority-9 job is first-leased before ≥90% of
   the bulk shards (the acceptance bar), and completes first.
2. **No starvation**: both tenants fully drain; zero ``dead`` jobs; the
   per-tenant ``sched_queue_depth`` gauges and the starvation-age
   histogram are present in the controller registry.
3. **Admission backpressure**: with a pending budget configured, an
   over-budget submit returns HTTP 429 + ``retry_after_ms``, and the
   unmodified agent-side retry classifier calls it transient.

Exit 0 = clean; 1 = problems (one per line).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from typing import Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from agent_tpu.agent.app import Agent
from agent_tpu.chaos import LoopbackSession
from agent_tpu.config import AgentConfig, Config, SchedConfig
from agent_tpu.controller.core import Controller
from agent_tpu.utils.retry import TRANSIENT, classify_http

SHARDS = 64
ROWS_PER_SHARD = 10
SINGLES = 6


def build_csv(path: str, rows: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text,risk\n")
        for i in range(rows):
            f.write(f'{i},"record {i}",{(i % 13) * 0.5}\n')


def make_agent(controller: Controller, name: str) -> Agent:
    cfg = Config(agent=AgentConfig(
        controller_url="http://loopback", agent_name=name,
        tasks=("risk_accumulate",), max_tasks=2,
        idle_sleep_sec=0.0, error_backoff_sec=0.0,
    ))
    agent = Agent(config=cfg, session=LoopbackSession(controller))
    agent._profile = {"tier": "sched-smoke"}  # skip hardware probing
    return agent


def main() -> int:
    problems: List[str] = []
    controller = Controller(
        lease_ttl_sec=30.0, sched=SchedConfig(policy="fair")
    )
    with tempfile.TemporaryDirectory(prefix="sched_fairness_") as tmp:
        csv_path = os.path.join(tmp, "rows.csv")
        build_csv(csv_path, SHARDS * ROWS_PER_SHARD)
        shard_ids, reduce_id = controller.submit_csv_job(
            csv_path,
            total_rows=SHARDS * ROWS_PER_SHARD,
            shard_size=ROWS_PER_SHARD,
            map_op="risk_accumulate",
            extra_payload={"field": "risk"},
            reduce_op="risk_accumulate",
            collect_partials=True,
            tenant="bulk",
        )
        single_ids = [
            controller.submit(
                "risk_accumulate",
                {
                    "source_uri": csv_path,
                    "start_row": k * ROWS_PER_SHARD,
                    "shard_size": ROWS_PER_SHARD,
                    "field": "risk",
                },
                tenant="rt",
                priority=9,
            )
            for k in range(SINGLES)
        ]

        # Drain with the real agent loop; track the order completions land.
        agent = make_agent(controller, "smoke-agent")
        completion_order: List[str] = []
        deadline = time.monotonic() + 120.0
        while not controller.drained() and time.monotonic() < deadline:
            leased = agent.lease_once()
            if leased is None:
                controller.sweep()
                continue
            lease_id, tasks = leased
            for task in tasks:
                agent.run_task(lease_id, task)
                completion_order.append(task["id"])

        if not controller.drained():
            print(f"drain did not complete (counts {controller.counts()})")
            return 1
        counts = controller.counts()
        if counts.get("dead") or counts.get("failed"):
            problems.append(f"dead/failed jobs under fair policy: {counts}")

        # Priority wins: every p9 single first-leases (and completes)
        # before ≥90% of the bulk shards.
        first_lease: Dict[str, int] = {}
        for ev in controller.recorder.events():
            if ev.get("kind") == "lease" \
                    and ev.get("job_id") not in first_lease:
                first_lease[ev["job_id"]] = len(first_lease)
        bulk_pos = sorted(first_lease[j] for j in shard_ids)
        p90_bulk = bulk_pos[int(0.9 * (len(bulk_pos) - 1))]
        late = [j for j in single_ids if first_lease[j] > p90_bulk]
        if late:
            problems.append(
                f"{len(late)}/{len(single_ids)} priority-9 jobs first-leased "
                f"after the 90th-percentile bulk shard"
            )
        done_pos = {j: i for i, j in enumerate(completion_order)}
        last_single_done = max(done_pos[j] for j in single_ids)
        bulk_done_before = sum(
            1 for j in shard_ids if done_pos[j] < last_single_done
        )
        if bulk_done_before > int(0.5 * SHARDS):
            problems.append(
                f"priority-9 singles completed after {bulk_done_before}/"
                f"{SHARDS} bulk shards — priority did not complete first"
            )

        snap = controller.metrics.snapshot()
        tenants = {
            s["labels"].get("tenant")
            for s in snap.get("sched_queue_depth", {}).get("series", [])
        }
        if not {"bulk", "rt"} <= tenants:
            problems.append(f"sched_queue_depth tenants missing: {tenants}")
        if not snap.get("sched_starvation_age_seconds", {}).get("series"):
            problems.append("sched_starvation_age_seconds has no series")

    # Admission backpressure: over-budget submit → 429, transient class.
    bounded = Controller(sched=SchedConfig(
        policy="fair", max_pending=3, retry_after_ms=250,
    ))
    session = LoopbackSession(bounded)
    statuses = []
    for i in range(5):
        resp = session.post(
            "http://loopback/v1/jobs",
            json={"op": "echo", "payload": {"i": i}, "tenant": "rt"},
        )
        statuses.append(resp.status_code)
    if statuses.count(429) != 2 or statuses.count(200) != 3:
        problems.append(f"admission statuses {statuses} != [200]*3 + [429]*2")
    else:
        body = session.post(
            "http://loopback/v1/jobs", json={"op": "echo"}
        ).json()
        if body.get("retry_after_ms") != 250:
            problems.append(f"429 body missing retry_after_ms: {body}")
    if classify_http(429) != TRANSIENT:
        problems.append("classify_http(429) is not transient")

    print(json.dumps({
        "shards": SHARDS, "singles": SINGLES,
        "p90_bulk_first_lease": p90_bulk,
        "single_first_leases": sorted(first_lease[j] for j in single_ids),
        "ok": not problems,
    }, sort_keys=True))
    if problems:
        for p in problems:
            print(p)
        print(f"FAILED: {len(problems)} problem(s)")
        return 1
    print("sched fairness smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
