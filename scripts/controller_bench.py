#!/usr/bin/env python3
"""Controller micro-bench — the control-plane ceiling as tracked numbers
(ISSUE 14; ROADMAP item 3a).

Every data-plane leg got faster for nine PRs while the control plane's
capacity was never measured. Three legs, no jax, < 30 s:

- **submits/sec** — in-process ``Controller.submit`` throughput against a
  live segmented journal (the production write path: JSON encode + append
  + flush per event).
- **lease-grants/sec** — ``lease()`` round-trips granting ``--grant``
  tasks each (the scheduler take + lease bookkeeping + task
  serialization hot path), and the tasks/sec they move.
- **replay** — the compaction claim as a number: a ``--events``-event
  journal (synthetic submit/result pairs, a ``--live`` pending tail —
  O(history) is the point, so history dwarfs live state) replayed two
  ways: full history (legacy single file) vs snapshot + tail (after one
  compacting snapshot). ``--assert-speedup N`` fails the run when
  snapshot replay is not at least N× faster — the ISSUE 14 acceptance
  bar runs this at 5 on a ≥ 50k-event journal in CI.

Emits one flat JSON line (``controller_*`` fields) that ``bench.py``
embeds in its artifact, so ``scripts/check_bench_regression.py`` trends
the control plane like every other leg.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from agent_tpu.config import JournalConfig
from agent_tpu.controller.core import Controller

SEG_CFG = JournalConfig(
    segment_max_bytes=4 * 1024 * 1024, snapshot_every_events=0
)


def bench_submits(n: int, tmp: str) -> Dict[str, Any]:
    path = os.path.join(tmp, "submit_bench.jsonl")
    c = Controller(journal_path=path, journal=SEG_CFG)
    t0 = time.perf_counter()
    for i in range(n):
        c.submit("echo", {"i": i})
    dt = time.perf_counter() - t0
    c.close()
    return {
        "submits": n,
        "submits_per_sec": round(n / dt, 1),
        "wall_s": round(dt, 4),
    }


def bench_leases(n_jobs: int, grant: int, tmp: str) -> Dict[str, Any]:
    path = os.path.join(tmp, "lease_bench.jsonl")
    c = Controller(journal_path=path, journal=SEG_CFG)
    for i in range(n_jobs):
        c.submit("echo", {"i": i})
    caps = {"ops": ["echo"]}
    grants = 0
    tasks = 0
    t0 = time.perf_counter()
    while True:
        lease = c.lease("bench", caps, max_tasks=grant)
        if lease is None:
            break
        grants += 1
        tasks += len(lease["tasks"])
    dt = time.perf_counter() - t0
    c.close()
    return {
        "grants": grants,
        "tasks_leased": tasks,
        "grant_size": grant,
        "lease_grants_per_sec": round(grants / dt, 1),
        "tasks_leased_per_sec": round(tasks / dt, 1),
        "wall_s": round(dt, 4),
    }


def _write_synthetic_journal(path: str, n_events: int, live: int) -> int:
    """A journal whose history dwarfs its live state: ``n_events`` as
    submit+result pairs (terminal jobs — pure history) followed by
    ``live`` pending submits (the state that must survive). Written as
    raw JSONL — exactly the bytes the controller would have journaled,
    without paying the controller to produce them."""
    written = 0
    with open(path, "w", encoding="utf-8") as f:
        pairs = max(0, (n_events - live) // 2)
        for i in range(pairs):
            jid = f"hist-{i}"
            f.write(json.dumps({
                "ev": "submit", "job_id": jid, "op": "echo",
                "payload": {"i": i}, "after": [], "required_labels": {},
                "max_attempts": None,
            }) + "\n")
            f.write(json.dumps({
                "ev": "result", "job_id": jid, "state": "succeeded",
                "epoch": 0, "attempts": 1, "result": None, "error": None,
            }) + "\n")
            written += 2
        for i in range(live):
            f.write(json.dumps({
                "ev": "submit", "job_id": f"live-{i}", "op": "echo",
                "payload": {"i": i}, "after": [], "required_labels": {},
                "max_attempts": None,
            }) + "\n")
            written += 1
    return written


def bench_replay(n_events: int, live: int, tmp: str) -> Dict[str, Any]:
    path = os.path.join(tmp, "replay_bench.jsonl")
    written = _write_synthetic_journal(path, n_events, live)

    # Full-history replay: the legacy cost a restarted controller paid.
    t0 = time.perf_counter()
    c = Controller(journal_path=path)
    t_full = time.perf_counter() - t0
    counts_full = c.counts()
    assert counts_full.get("pending") == live, counts_full
    c.close()

    # Compact: one snapshot covers the whole history. The planet-scale
    # configuration bounds terminal-job retention (SNAPSHOT_RETAIN_
    # TERMINAL) — that is what makes the snapshot O(live state + window)
    # instead of O(every job ever submitted).
    snap_cfg = JournalConfig(
        segment_max_bytes=4 * 1024 * 1024, snapshot_every_events=1,
        snapshot_retain_terminal=max(100, live),
    )
    c = Controller(journal_path=path, journal=snap_cfg)
    c.maybe_snapshot(force=True)
    c.close()
    # ...and the next incarnation replays snapshot + empty tail.
    t0 = time.perf_counter()
    c = Controller(journal_path=path, journal=snap_cfg)
    t_compacted = time.perf_counter() - t0
    counts_snap = c.counts()
    # Live state is intact; history beyond the retention window is
    # forgotten (late duplicates reject as unknown job — still at most
    # once).
    assert counts_snap.get("pending") == live, counts_snap
    assert counts_snap.get("succeeded", 0) <= counts_full["succeeded"]
    assert c.journal_status()["last_replay_sec"] <= t_compacted
    c.close()

    return {
        "events": written,
        "live_jobs": live,
        "replay_full_sec": round(t_full, 4),
        "replay_events_per_sec": round(written / t_full, 1),
        "replay_compacted_sec": round(t_compacted, 4),
        "replay_speedup": round(t_full / max(1e-9, t_compacted), 1),
    }


def bench_partitioned_submits(
    submits: int, partitions: int, tmp: str
) -> Dict[str, Any]:
    """Aggregate submit throughput of N partitions running CONCURRENTLY
    in separate processes (ISSUE 18) — each partition a real
    ``Controller`` journaling to its own segmented journal, exactly the
    per-partition write path of the partitioned control plane. Separate
    processes because that is the deployment shape AND the measurement
    requirement: N controllers in one process share a GIL and would bench
    lock contention, not scaling. Aggregate = total submits / slowest
    child wall (children start together; python startup is excluded
    because each child times only its own submit loop)."""
    import subprocess

    procs = []
    for i in range(partitions):
        path = os.path.join(tmp, f"agg_submit.p{i}.jsonl")
        procs.append(subprocess.Popen(
            [
                sys.executable, os.path.abspath(__file__),
                "--_child-submits", str(submits),
                "--_child-journal", path,
                "--_child-partition", f"p{i}",
            ],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, cwd=REPO,
        ))
    total = 0
    walls: List[float] = []
    for proc in procs:
        out, err = proc.communicate(timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"partition child failed rc={proc.returncode}: "
                f"{err.decode(errors='replace')[:300]}"
            )
        child = json.loads(out.decode())
        total += child["submits"]
        walls.append(child["wall_s"])
    wall = max(walls)
    return {
        "partitions": partitions,
        "submits": total,
        "agg_submits_per_sec": round(total / wall, 1),
        "child_walls_s": [round(w, 4) for w in walls],
        "wall_s": round(wall, 4),
    }


def run_bench(
    submits: int = 20_000,
    lease_jobs: int = 20_000,
    grant: int = 16,
    replay_events: int = 50_000,
    replay_live: int = 500,
    partitions: int = 0,
) -> Dict[str, Any]:
    """All legs → one flat dict (the ``controller_*`` bench fields).
    Importable — ``bench.py``'s controller leg calls this.
    ``partitions > 0`` adds the ISSUE 18 aggregate-submits leg (N
    concurrent partition processes) and its ``agg_*`` fields."""
    with tempfile.TemporaryDirectory(prefix="controller_bench_") as tmp:
        sub = bench_submits(submits, tmp)
        lease = bench_leases(lease_jobs, grant, tmp)
        replay = bench_replay(replay_events, replay_live, tmp)
        agg = (
            bench_partitioned_submits(submits, partitions, tmp)
            if partitions > 0 else None
        )
    out = {
        "submits_per_sec": sub["submits_per_sec"],
        "lease_grants_per_sec": lease["lease_grants_per_sec"],
        "tasks_leased_per_sec": lease["tasks_leased_per_sec"],
        "replay_events": replay["events"],
        "replay_full_sec": replay["replay_full_sec"],
        "replay_events_per_sec": replay["replay_events_per_sec"],
        "replay_compacted_sec": replay["replay_compacted_sec"],
        "replay_speedup": replay["replay_speedup"],
        "detail": {"submit": sub, "lease": lease, "replay": replay},
    }
    if agg is not None:
        host_cores = os.cpu_count() or 1
        out["agg_partitions"] = partitions
        out["agg_submits_per_sec"] = agg["agg_submits_per_sec"]
        out["agg_speedup_vs_single"] = round(
            agg["agg_submits_per_sec"] / max(1e-9, sub["submits_per_sec"]),
            2,
        )
        # Core-count-aware floor: N partition children + the parent need
        # real cores or the leg measures scheduling starvation, not the
        # control plane (the ISSUE 16 starved_fields convention).
        out["agg_starved"] = host_cores < partitions + 1
        out["host_cores"] = host_cores
        out["detail"]["agg"] = agg
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--submits", type=int, default=20_000)
    ap.add_argument("--lease-jobs", type=int, default=20_000)
    ap.add_argument("--grant", type=int, default=16)
    ap.add_argument("--replay-events", type=int, default=50_000)
    ap.add_argument("--replay-live", type=int, default=500)
    ap.add_argument("--assert-speedup", type=float, default=0.0,
                    help="fail unless snapshot replay is at least this "
                         "many times faster than full-history replay "
                         "(the ISSUE 14 acceptance bar runs 5)")
    ap.add_argument("--quick", action="store_true",
                    help="CI sizing (replay stays >= 50k events — the "
                         "acceptance bar's floor)")
    ap.add_argument("--partitions", type=int, default=0,
                    help="also bench N concurrent partition processes "
                         "(ISSUE 18); records controller_agg_submits_"
                         "per_sec and asserts aggregate >= 2x single on "
                         "hosts with enough cores")
    # Hidden child mode: one partition's submit loop in its own process.
    ap.add_argument("--_child-submits", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--_child-journal", default="",
                    help=argparse.SUPPRESS)
    ap.add_argument("--_child-partition", default="",
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args._child_submits > 0:
        # Child mode — one partition, own journal, JSON on stdout.
        tmp = os.path.dirname(args._child_journal) or "."
        c = Controller(
            journal_path=args._child_journal, journal=SEG_CFG,
            partition=args._child_partition or None,
        )
        t0 = time.perf_counter()
        for i in range(args._child_submits):
            c.submit("echo", {"i": i})
        dt = time.perf_counter() - t0
        c.close()
        print(json.dumps({
            "partition": args._child_partition,
            "submits": args._child_submits,
            "wall_s": dt,
            "tmp": tmp,
        }), flush=True)
        return 0

    if args.quick:
        args.submits = min(args.submits, 10_000)
        args.lease_jobs = min(args.lease_jobs, 10_000)

    out = run_bench(
        submits=args.submits, lease_jobs=args.lease_jobs,
        grant=args.grant, replay_events=args.replay_events,
        replay_live=args.replay_live, partitions=args.partitions,
    )
    print(json.dumps(out, sort_keys=True), flush=True)
    if args.assert_speedup > 0 and out["replay_speedup"] < args.assert_speedup:
        print(
            f"FAILED: replay speedup {out['replay_speedup']}x < required "
            f"{args.assert_speedup}x on a {out['replay_events']}-event "
            "journal — snapshot replay is not O(live state)"
        )
        return 1
    if args.partitions > 0:
        if out["agg_starved"]:
            print(
                f"STARVED: {out['host_cores']} cores < "
                f"{args.partitions + 1} needed — aggregate recorded "
                "but the >=2x floor is not asserted", file=sys.stderr,
            )
        elif out["agg_speedup_vs_single"] < 2.0:
            print(
                f"FAILED: aggregate {out['agg_submits_per_sec']}/s is "
                f"only {out['agg_speedup_vs_single']}x the single-"
                f"partition {out['submits_per_sec']}/s across "
                f"{args.partitions} partitions on a {out['host_cores']}-"
                "core host — sharding is not scaling submits"
            )
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
