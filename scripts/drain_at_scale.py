"""At-scale mixed drain: the literal BASELINE.json north-star job shape.

Drains an N-row (default 10M) CSV through BOTH model ops — every row
classified AND summarized — via the real controller/HTTP/agent/pipeline
path, with per-row results streaming to JSONL sinks (``output_uri``) so the
controller carries receipts, not payloads.

Run on the TPU host:

    python scripts/drain_at_scale.py --rows 10000000 \
        --workdir /tmp/drain10m --report DRAIN_AT_SCALE.json

Multi-chip legs (ISSUE 7): ``--agents N`` drains through a fleet of N
device-pinned agent subprocesses (``agent_tpu/agent/fleet.py``; on TPU
hardware pass ``--fleet-platform tpu`` so each member owns disjoint chips
via TPU_VISIBLE_DEVICES); ``--mesh-dp N`` drains through ONE agent whose
runtime executes dp-sharded over an N-device mesh. Both record per-agent
shard counts and the trace-derived stage/execute overlap per agent, and
exit nonzero if any agent got zero shards.

The report JSON records wall time, per-op rows/sec and device-busy seconds,
shard counts, retry/failure counts, n_chips, and sink row totals — the
artifact PARITY.md cites for the "drains a 10M-row classify+summarize job"
sentence.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CLASSIFY_SHARD = 8192
# SLO objectives for the drain (ISSUE 8): op-keyed, generous p99 (bulk
# shards legitimately run seconds) — the point is recording attainment and
# the verdict in the artifact, not paging a healthy drain.
SLO_SPEC = (
    '[{"name": "classify", "op": "map_classify_tpu",'
    ' "p99_ms": 600000, "availability": 0.999},'
    ' {"name": "summarize", "op": "map_summarize",'
    ' "p99_ms": 600000, "availability": 0.999}]'
)
# Summarize throughput scales with decode rows in flight (measured on v5e,
# payload-size sweep: 4,980 → 8,093 rows/s from 1k → 8k rows, dispatched
# as chained ≤MAX_DECODE_ROWS programs; one single B=8192 program measured
# 9,132 — see ops/map_summarize.MAX_DECODE_ROWS). One shard = one op call.
SUMMARIZE_SHARD = 8192
SUMMARIZE_MAX_NEW = 32


def build_csv(path: str, n_rows: int) -> None:
    if os.path.exists(path):
        return
    tmp = path + ".tmp"
    t0 = time.perf_counter()
    with open(tmp, "w") as f:
        f.write("id,text,risk\n")
        for i in range(n_rows):
            f.write(
                f'{i},"drain record {i} with a payload of text to classify '
                f'and summarize",{i % 89}\n'
            )
    os.replace(tmp, path)
    print(f"csv built: {n_rows} rows, "
          f"{os.path.getsize(path) / 1e6:.0f} MB, "
          f"{time.perf_counter() - t0:.0f}s", flush=True)


def warm_payload_specs(csv_path, n_rows, classify_extra, summarize_extra,
                       warm_out):
    """``[{op, payload}]`` covering BOTH length buckets of BOTH ops (row ids
    grow 1→7 digits across the dataset, crossing a bucket boundary) — the
    single warm-shape definition shared by the in-process warm submissions
    and the fleet members' local pre-lease warmup."""
    specs = []
    for op_name, shard, extra in (
        ("map_classify_tpu", CLASSIFY_SHARD, classify_extra),
        ("map_summarize", SUMMARIZE_SHARD, summarize_extra),
    ):
        starts = [0]
        tail = max(0, n_rows - min(shard, n_rows))
        if tail > 0:
            starts.append(tail)
        for start in starts:
            specs.append({"op": op_name, "payload": {
                **extra,
                "source_uri": csv_path,
                "start_row": start,
                "shard_size": min(shard, n_rows - start),
                "output_uri": warm_out,
            }})
    return specs


def per_agent_shards(controller, job_ids):
    """{agent: executed shard count} over ``job_ids`` (succeeded jobs)."""
    counts = {}
    for jid in job_ids:
        agent = controller.job_snapshot(jid)["agent"]
        if agent:
            counts[agent] = counts.get(agent, 0) + 1
    return counts


def health_report(server_url):
    """Flat per-op SLO attainment / MFU + the verdict off ``GET
    /v1/health`` (ISSUE 8 satellite). None when unreachable — callers FAIL
    the drain on that (the fields were promised, silence is rot)."""
    from agent_tpu.obs.scrape import fetch_health

    health = fetch_health(server_url)
    if health is None:
        return None
    attain = {
        o.get("op", o["objective"]): o.get("attainment")
        for o in health["slo"]["objectives"]
    }
    mfu: dict = {}
    duty: dict = {}
    for name, row in (health.get("agents") or {}).items():
        duty[name] = row.get("duty_cycle")
        for op, v in (row.get("mfu") or {}).items():
            mfu.setdefault(op, []).append(v)
    return {
        "verdict": health["verdict"],
        "attain": attain,
        # Fleet MFU per op = mean across reporting agents.
        "mfu": {
            op: round(sum(vs) / len(vs), 4) for op, vs in mfu.items()
        },
        "duty": duty,
    }


def health_fields(hf):
    """The flat report fields both drain modes record."""
    return {
        "health_verdict": hf["verdict"],
        "slo_attainment_classify": hf["attain"].get("map_classify_tpu"),
        "slo_attainment_summarize": hf["attain"].get("map_summarize"),
        "mfu_classify": hf["mfu"].get("map_classify_tpu"),
        "mfu_summarize": hf["mfu"].get("map_summarize"),
        "duty_cycle_by_agent": hf["duty"],
    }


def overlap_report(server_url):
    """(fleet overlap, per-agent overlap) from the trace window; either may
    be None when tracing is off — callers decide how loud to be."""
    from agent_tpu.obs.scrape import (
        collect_trace_spans,
        overlap_by_process,
        overlap_from_spans,
    )

    spans = collect_trace_spans(server_url)
    if spans is None:
        return None, None
    return overlap_from_spans(spans), overlap_by_process(spans)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--workdir", default="/tmp/drain_at_scale")
    ap.add_argument("--report", default="DRAIN_AT_SCALE.json")
    ap.add_argument("--progress-sec", type=float, default=60.0)
    # Multi-chip legs (ISSUE 7): a fleet of N pinned agent processes, or
    # one dp=N mesh agent. Default (1, 0) keeps the classic in-process leg.
    ap.add_argument("--agents", type=int, default=1)
    ap.add_argument("--devices-per-agent", type=int, default=1)
    ap.add_argument("--mesh-dp", type=int, default=0,
                    help="run ONE agent with MESH_SHAPE=dp=N (N devices)")
    ap.add_argument("--fleet-platform", choices=("cpu", "tpu"),
                    default="cpu",
                    help="fleet pinning mode: cpu = forced-host virtual "
                         "devices; tpu = hardware chips")
    # bf16 is the default: W8A8's dynamic activation quantization costs
    # more than the MXU saves on [B, 256]-thin decode matmuls (measured
    # 3,983 int8 vs 4,980 bf16 rows/s at B=1024); int8 pays off on
    # big-matmul encoders (BERT-base leg 1.21×), not this decode.
    ap.add_argument("--summarize-quant", default="none",
                    choices=("int8", "none"))
    args = ap.parse_args()

    if args.agents > 1 or args.mesh_dp > 1 or args.devices_per_agent > 1:
        return main_fleet(args)

    import requests

    from agent_tpu.agent.app import Agent
    from agent_tpu.agent.pipeline import PipelineRunner
    from agent_tpu.config import AgentConfig, Config
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer
    from agent_tpu.runtime.runtime import get_runtime

    os.makedirs(args.workdir, exist_ok=True)
    csv_path = os.path.join(args.workdir, f"drain_{args.rows}.csv")
    classify_out = os.path.join(args.workdir, "classify_out")
    summarize_out = os.path.join(args.workdir, "summarize_out")
    build_csv(csv_path, args.rows)

    from agent_tpu.config import SloConfig

    runtime = get_runtime()
    controller = Controller(
        lease_ttl_sec=600.0, slo=SloConfig(spec=SLO_SPEC)
    )
    t_start = time.perf_counter()
    with ControllerServer(controller) as server:
        cfg = Config(
            agent=AgentConfig(
                controller_url=server.url,
                agent_name="drain-at-scale",
                tasks=("map_classify_tpu", "map_summarize"),
                idle_sleep_sec=0.0,
            )
        )
        agent = Agent(config=cfg, session=requests.Session(), runtime=runtime)
        agent._profile = {"tier": "at-scale"}

        # ONE payload definition per op, shared verbatim by the warm
        # submissions and the timed submit_csv_job below — a drifted copy
        # would warm a different executable than the drain uses.
        classify_extra = {
            "text_field": "text", "allow_fallback": False,
            "output_uri": classify_out,
        }
        summarize_extra = {
            "text_field": "text", "allow_fallback": False,
            "max_length": SUMMARIZE_MAX_NEW, "output_uri": summarize_out,
            **(
                {"model_config": {"quant": args.summarize_quant}}
                if args.summarize_quant != "none" else {}
            ),
        }

        # Warm the executable cache OUTSIDE the timed window (same
        # methodology as bench.py's drain leg: compile is a once-per-process
        # cost — reference handle-singleton semantics — and a cold ~2-7 min
        # XLA compile mid-drain is compiler time, not drain time). Row ids
        # grow 1→7 digits across the dataset, crossing a length-bucket
        # boundary, so warm shards come from BOTH ends of the CSV — per-op
        # tail positions, so each op warms its own full shard shape.
        # Warm results go to a scratch sink dir: the real sinks must contain
        # EXACTLY the timed job's shards for the post-run validation.
        warm_out = os.path.join(args.workdir, "warm_out")
        warm_specs = warm_payload_specs(
            csv_path, args.rows, classify_extra, summarize_extra, warm_out
        )
        for spec in warm_specs:
            controller.submit(spec["op"], spec["payload"])
        n_warm = len(warm_specs)
        agent.running = True
        warm_done = {}

        def warm_watch():
            while not controller.drained():
                time.sleep(0.05)
            warm_done["ok"] = True
            agent.running = False

        threading.Thread(target=warm_watch, daemon=True).start()
        t_warm = time.perf_counter()
        PipelineRunner(agent, depth=2).run()
        assert warm_done.get("ok"), "warmup drain did not complete"
        # Every warm shard must have SUCCEEDED — a failed warm shard means
        # a cold cache (compile lands in the timed window) and corrupts the
        # warm-exclusion arithmetic in the report.
        warm_results = controller.results()
        warm_bad = [
            j for j, r in warm_results.items()
            if not (isinstance(r, dict) and r.get("ok") is True)
        ]
        assert len(warm_results) == n_warm and not warm_bad, (
            f"warmup failed: {len(warm_results)}/{n_warm} results, "
            f"bad={warm_bad}"
        )
        print(f"warmup done ({time.perf_counter() - t_warm:.0f}s, "
              f"{n_warm} shards, both buckets x both ops)", flush=True)
        agent.running = True
        warm_jobs = set(warm_results)
        # Per-op attribution now scrapes /v1/metrics (fleet task_phase
        # series); the warm shards already counted, so the timed numbers
        # are the scrape delta across the timed window.
        from agent_tpu.obs.scrape import fetch_metrics_text, op_phase_seconds

        drain_ops = ("map_classify_tpu", "map_summarize")
        pre_text = fetch_metrics_text(server.url)
        span_pre = (
            op_phase_seconds(pre_text, drain_ops)
            if pre_text is not None else None
        )
        t_start = time.perf_counter()  # the timed window starts POST-warmup

        controller.submit_csv_job(
            csv_path, total_rows=args.rows, shard_size=CLASSIFY_SHARD,
            map_op="map_classify_tpu", extra_payload=classify_extra,
        )
        controller.submit_csv_job(
            csv_path, total_rows=args.rows, shard_size=SUMMARIZE_SHARD,
            map_op="map_summarize", extra_payload=summarize_extra,
        )
        # Timed-drain shard count and progress EXCLUDE the warm shards
        # (already succeeded in the controller's cumulative counts).
        n_shards = sum(controller.counts().values()) - n_warm
        print(f"submitted {n_shards} shards "
              f"({args.rows} rows x 2 ops)", flush=True)

        done = {}

        def watch():
            # Stall accounting: the TPU tunnel on this host exhibits
            # multi-minute outages (device thread blocked in tcp_recvmsg,
            # zero completions). Gaps > STALL_GAP_S with no new completion
            # are summed into tunnel_stall_s so the artifact separates
            # framework throughput from infrastructure outage — both the
            # raw wall rate and the stall-excluded rate are recorded.
            STALL_GAP_S = 60.0
            last = 0.0
            last_done_n = -1
            last_change = time.perf_counter()
            stall_s = 0.0
            while not controller.drained():
                time.sleep(1.0)
                now = time.perf_counter()
                c = controller.counts()
                done_n = c.get("succeeded", 0) + c.get("failed", 0) - n_warm
                if done_n != last_done_n:
                    gap = now - last_change
                    if gap > STALL_GAP_S:
                        stall_s += gap
                        print(f"[stall] {gap:.0f}s with no completions",
                              flush=True)
                    last_done_n = done_n
                    last_change = now
                if now - last >= args.progress_sec:
                    last = now
                    print(
                        f"[{now - t_start:7.0f}s] {json.dumps(c)} "
                        f"({done_n}/{n_shards} shards)",
                        flush=True,
                    )
            gap = time.perf_counter() - last_change
            if gap > STALL_GAP_S:
                stall_s += gap
            done["wall"] = time.perf_counter() - t_start
            done["stall_s"] = stall_s
            agent.running = False

        threading.Thread(target=watch, daemon=True).start()
        PipelineRunner(agent, depth=2).run()
        wall = done.get("wall", time.perf_counter() - t_start)
        stall_s = done.get("stall_s", 0.0)

        from agent_tpu.utils.spans import op_span_ms, result_op

        counts = dict(controller.counts())
        if counts.get("succeeded"):
            counts["succeeded"] -= n_warm  # warm shards are untimed
        ok_results = []
        rows_written = {"map_classify_tpu": 0, "map_summarize": 0}
        not_ok = 0
        for job_id, r in controller.results().items():
            if job_id in warm_jobs:
                continue  # warm shards ran outside the timed window
            if not isinstance(r, dict) or r.get("ok") is not True:
                not_ok += 1
                continue
            ok_results.append(r)
            op = result_op(r)
            if op in rows_written:
                rows_written[op] += int(r.get("rows_written", 0))
        # Per-shard device-side span = dispatch + deferred fetch. Primary
        # source: scraped /v1/metrics fleet series (execute+fetch sums,
        # delta vs the post-warmup scrape); fallback: result-body summing
        # (agent_tpu.utils.spans, shared with bench.py) when scraping is
        # unavailable.
        post_text = fetch_metrics_text(server.url)
        busy_s = {}
        span_source = "scrape"
        if span_pre is not None and post_text is not None:
            span_post = op_phase_seconds(post_text, drain_ops)
            busy_s = {op: span_post[op] - span_pre[op] for op in drain_ops}
        if not any(busy_s.values()):
            span_source = "result_bodies"
            busy_ms = op_span_ms(ok_results, drain_ops)
            busy_s = {op: busy_ms[op] / 1e3 for op in drain_ops}

        # Slowest-job trace (ISSUE 5 satellite) + stage/execute overlap
        # (ISSUE 6 satellite): per-phase attribution and the cross-job
        # concurrency ratio, both from GET /v1/trace/*. A broken trace path
        # FAILS the drain (nonzero exit) rather than silently omitting the
        # breakdown.
        from agent_tpu.obs import trace as obs_trace
        from agent_tpu.obs.scrape import slowest_trace, stage_execute_overlap
        from agent_tpu.obs.trace import phase_breakdown

        trace_line = None
        overlap = None
        if obs_trace.enabled():
            worst = slowest_trace(server.url)
            if worst is None:
                print(
                    "DRAIN FAILED: trace path broken — /v1/traces or "
                    "/v1/trace/{job_id} returned nothing for a drained run",
                    flush=True,
                )
                return 1
            trace_line = phase_breakdown(worst)
            print(f"[slowest shard] {trace_line}", flush=True)
            overlap = stage_execute_overlap(server.url)
            if overlap is None:
                print(
                    "DRAIN FAILED: no closed stage/execute spans in the "
                    "trace window — overlap breakdown unavailable",
                    flush=True,
                )
                return 1
            print(
                f"[overlap] {overlap['overlap_ratio']:.3f} of stage wall "
                f"time hidden behind execute (stage p50 "
                f"{overlap['stage_p50_ms']:.1f} ms vs execute p50 "
                f"{overlap['execute_p50_ms']:.1f} ms)",
                flush=True,
            )
            # Per-agent attribution (ISSUE 7 satellite): trivially one
            # entry here; the fleet leg reports one per member.
            from agent_tpu.obs.scrape import stage_execute_overlap_by_agent

            overlap_by_agent = stage_execute_overlap_by_agent(server.url)
        else:
            overlap_by_agent = None
        agent_shards = per_agent_shards(
            controller,
            [j for j in controller.results() if j not in warm_jobs],
        )
        # Fleet health rollup (ISSUE 8 satellite): verdict + flat per-op
        # attainment/MFU in the artifact; an unreachable /v1/health FAILS
        # the drain instead of silently omitting the promised fields.
        hf = health_report(server.url)
        if hf is None:
            print("DRAIN FAILED: GET /v1/health unreachable", flush=True)
            return 1
        print(f"[health] verdict={hf['verdict']} "
              f"attainment={hf['attain']} mfu={hf['mfu']}", flush=True)

    report = {
        **health_fields(hf),
        "rows": args.rows,
        "ops": ["map_classify_tpu", "map_summarize"],
        "wall_s": round(wall, 1),
        "shards": n_shards,
        "counts": counts,
        "non_ok_results": not_ok,
        "total_rows_per_sec": round(2 * args.rows / wall, 1),
        # Tunnel outages (>60s with zero completions; the device thread sits
        # in tcp_recvmsg) summed by the watch loop. The stall-excluded rate
        # is what the framework sustains when the link is up; BOTH numbers
        # are recorded — neither is hidden in prose.
        "tunnel_stall_s": round(stall_s, 1),
        "rows_per_sec_excl_stalls": round(
            2 * args.rows / max(wall - stall_s, 1e-9), 1
        ),
        # "span" = per-shard dispatch + deferred-fetch wait summed per op.
        # Under pipeline overlap this can over- or under-count true device
        # busy time; wall_s / total_rows_per_sec are the primary metrics.
        # (Renamed from the pre-deferred-fetch "device_busy_s" so old
        # reports aren't compared against a different quantity.)
        "span_source": span_source,
        # Per-phase breakdown of the slowest job's assembled trace
        # (GET /v1/trace/{job_id}); None only with TRACE_ENABLED=0.
        "slowest_trace": trace_line,
        # Stage/execute concurrency over the trace window (ISSUE 6): the
        # fraction of stage wall time the staging pool hid behind device
        # execute, with per-phase p50s; None only with TRACE_ENABLED=0.
        "stage_execute_overlap": overlap,
        # Multi-chip accounting (ISSUE 7): who executed what, and each
        # member's own overlap picture.
        "mode": "single",
        "per_agent_shards": agent_shards,
        "stage_execute_overlap_by_agent": overlap_by_agent,
        "classify": {
            "shard_size": CLASSIFY_SHARD,
            "rows_written": rows_written["map_classify_tpu"],
            "device_span_s": round(busy_s["map_classify_tpu"], 1),
            "rows_per_span_sec": round(
                args.rows / busy_s["map_classify_tpu"], 1
            ) if busy_s["map_classify_tpu"] else None,
        },
        "summarize": {
            "shard_size": SUMMARIZE_SHARD,
            "max_new_tokens": SUMMARIZE_MAX_NEW,
            "quant": args.summarize_quant,
            "rows_written": rows_written["map_summarize"],
            "device_span_s": round(busy_s["map_summarize"], 1),
            "rows_per_span_sec": round(
                args.rows / busy_s["map_summarize"], 1
            ) if busy_s["map_summarize"] else None,
        },
        "platform": runtime.platform,
        "n_chips": runtime.n_devices,
    }
    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report), flush=True)

    ok = (
        counts.get("failed", 0) == 0
        and not_ok == 0
        and rows_written["map_classify_tpu"] == args.rows
        and rows_written["map_summarize"] == args.rows
        # Zero-shard agents fail the drain (ISSUE 7): an idle member means
        # placement is broken even when the rows all landed.
        and bool(agent_shards)
        and all(v > 0 for v in agent_shards.values())
    )
    print("DRAIN", "OK" if ok else "FAILED", flush=True)
    return 0 if ok else 1


def main_fleet(args) -> int:
    """Multi-chip leg: the same classify+summarize drain executed by a
    fleet of pinned agent subprocesses (``--agents N``) or one dp=N mesh
    agent (``--mesh-dp N``), timed post-warmup like the in-process leg."""
    from agent_tpu.agent import fleet as fleet_mod
    from agent_tpu.config import SchedConfig
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer
    from agent_tpu.obs.scrape import (
        fetch_metrics_text,
        op_phase_seconds,
        slowest_trace,
    )
    from agent_tpu.obs import trace as obs_trace
    from agent_tpu.obs.trace import phase_breakdown

    if args.mesh_dp > 1 and args.agents > 1:
        print("--agents and --mesh-dp are alternative modes; pick one",
              flush=True)
        return 2
    mode = "mesh" if args.mesh_dp > 1 else "fleet"
    n_agents = 1 if mode == "mesh" else args.agents
    dev_per = args.mesh_dp if mode == "mesh" else args.devices_per_agent
    mesh_shape = f"dp={args.mesh_dp}" if mode == "mesh" else ""

    os.makedirs(args.workdir, exist_ok=True)
    csv_path = os.path.join(args.workdir, f"drain_{args.rows}.csv")
    classify_out = os.path.join(args.workdir, "classify_out")
    summarize_out = os.path.join(args.workdir, "summarize_out")
    build_csv(csv_path, args.rows)

    classify_extra = {
        "text_field": "text", "allow_fallback": False,
        "output_uri": classify_out,
    }
    summarize_extra = {
        "text_field": "text", "allow_fallback": False,
        "max_length": SUMMARIZE_MAX_NEW, "output_uri": summarize_out,
        **(
            {"model_config": {"quant": args.summarize_quant}}
            if args.summarize_quant != "none" else {}
        ),
    }
    # Fleet members warm LOCALLY (pre-lease, both ops × both length
    # buckets) — compile is per-process and must stay out of the window.
    warm_file = os.path.join(args.workdir, "fleet_warm.json")
    with open(warm_file, "w") as f:
        json.dump(warm_payload_specs(
            csv_path, args.rows, classify_extra, summarize_extra,
            os.path.join(args.workdir, "warm_out"),
        ), f)

    from agent_tpu.config import SloConfig

    controller = Controller(
        lease_ttl_sec=600.0, sched=SchedConfig(policy="fair"),
        slo=SloConfig(spec=SLO_SPEC),
    )
    drain_ops = ("map_classify_tpu", "map_summarize")
    with ControllerServer(controller) as server:
        handle = fleet_mod.spawn_fleet(
            n_agents, dev_per,
            controller_url=server.url,
            tasks="map_classify_tpu,map_summarize",
            platform=args.fleet_platform, name_prefix="drain",
            mesh_shape=mesh_shape, warm_file=warm_file,
            log_dir=os.path.join(args.workdir, "fleet_logs"),
            extra_env={"IDLE_SLEEP_SEC": "0.02"},
        )
        try:
            if not fleet_mod.wait_for_agents(
                controller.agents_summary, handle.names, timeout=1800.0,
                fleet=handle,
            ):
                print(
                    f"DRAIN FAILED: fleet not ready (failures="
                    f"{handle.poll_failures()}); see "
                    f"{args.workdir}/fleet_logs", flush=True,
                )
                return 1
            print(f"fleet ready: {handle.names} "
                  f"({mode}, {dev_per} device(s) each)", flush=True)
            pre_text = fetch_metrics_text(server.url)
            span_pre = (
                op_phase_seconds(pre_text, drain_ops)
                if pre_text is not None else None
            )
            t_start = time.perf_counter()
            shard_ids = []
            for op_name, shard, extra in (
                ("map_classify_tpu", CLASSIFY_SHARD, classify_extra),
                ("map_summarize", SUMMARIZE_SHARD, summarize_extra),
            ):
                ids, _ = controller.submit_csv_job(
                    csv_path, total_rows=args.rows, shard_size=shard,
                    map_op=op_name, extra_payload=extra,
                )
                shard_ids.extend(ids)
            n_shards = len(shard_ids)
            print(f"submitted {n_shards} shards "
                  f"({args.rows} rows x 2 ops)", flush=True)
            last = 0.0
            while not controller.drained():
                time.sleep(1.0)
                if handle.poll_failures():
                    print(
                        f"DRAIN FAILED: fleet member died "
                        f"({handle.poll_failures()})", flush=True,
                    )
                    return 1
                now = time.perf_counter()
                if now - last >= args.progress_sec:
                    last = now
                    print(
                        f"[{now - t_start:7.0f}s] "
                        f"{json.dumps(controller.counts())}", flush=True,
                    )
            wall = time.perf_counter() - t_start

            counts = dict(controller.counts())
            rows_written = {"map_classify_tpu": 0, "map_summarize": 0}
            not_ok = 0
            from agent_tpu.utils.spans import result_op

            for jid in shard_ids:
                r = controller.job_snapshot(jid)["result"]
                if not isinstance(r, dict) or r.get("ok") is not True:
                    not_ok += 1
                    continue
                op = result_op(r)
                if op in rows_written:
                    rows_written[op] += int(r.get("rows_written", 0))
            post_text = fetch_metrics_text(server.url)
            busy_s = {}
            if span_pre is not None and post_text is not None:
                span_post = op_phase_seconds(post_text, drain_ops)
                busy_s = {
                    op: span_post[op] - span_pre[op] for op in drain_ops
                }
            agent_shards = per_agent_shards(controller, shard_ids)
            # Fleet chip accounting: every member pushed its runtime
            # describe() through the lease metrics channel.
            n_chips = 0
            platform = None
            for entry in controller.agents_summary().values():
                dev = (entry.get("metrics") or {}).get("device") or {}
                n_chips += int(dev.get("n_devices") or 0)
                platform = dev.get("platform") or platform
            trace_line = None
            overlap = None
            overlap_by_agent = None
            if obs_trace.enabled():
                worst = slowest_trace(server.url)
                if worst is None:
                    print("DRAIN FAILED: trace path broken for the fleet "
                          "drain", flush=True)
                    return 1
                trace_line = phase_breakdown(worst)
                print(f"[slowest shard] {trace_line}", flush=True)
                overlap, overlap_by_agent = overlap_report(server.url)
                if not overlap_by_agent:
                    print("DRAIN FAILED: no per-agent stage/execute "
                          "overlap assembled", flush=True)
                    return 1
                for name, o in sorted(overlap_by_agent.items()):
                    print(
                        f"[overlap {name}] {o['overlap_ratio']:.3f} hidden "
                        f"(stage p50 {o['stage_p50_ms']:.1f} ms, execute "
                        f"p50 {o['execute_p50_ms']:.1f} ms)", flush=True,
                    )
            # Fleet health rollup (ISSUE 8): same contract as the single
            # leg — the promised fields or a loud failure.
            hf = health_report(server.url)
            if hf is None:
                print("DRAIN FAILED: GET /v1/health unreachable",
                      flush=True)
                return 1
            print(f"[health] verdict={hf['verdict']} "
                  f"attainment={hf['attain']} mfu={hf['mfu']}", flush=True)
        finally:
            handle.stop()

    report = {
        **health_fields(hf),
        "rows": args.rows,
        "ops": list(drain_ops),
        "mode": mode,
        "agents": n_agents,
        "devices_per_agent": dev_per,
        "wall_s": round(wall, 1),
        "shards": n_shards,
        "counts": counts,
        "non_ok_results": not_ok,
        "total_rows_per_sec": round(2 * args.rows / wall, 1),
        "per_agent_shards": agent_shards,
        "slowest_trace": trace_line,
        "stage_execute_overlap": overlap,
        "stage_execute_overlap_by_agent": overlap_by_agent,
        "classify": {
            "shard_size": CLASSIFY_SHARD,
            "rows_written": rows_written["map_classify_tpu"],
            "device_span_s": round(busy_s.get("map_classify_tpu", 0.0), 1),
        },
        "summarize": {
            "shard_size": SUMMARIZE_SHARD,
            "max_new_tokens": SUMMARIZE_MAX_NEW,
            "quant": args.summarize_quant,
            "rows_written": rows_written["map_summarize"],
            "device_span_s": round(busy_s.get("map_summarize", 0.0), 1),
        },
        "platform": platform,
        "n_chips": n_chips,
    }
    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report), flush=True)

    zero = [a for a, v in agent_shards.items() if v == 0]
    # An agent that executed nothing never appears in the per-job agent
    # fields at all — the absent members are the real zero-shard signal.
    missing = [a for a in handle.names if a not in agent_shards]
    ok = (
        counts.get("failed", 0) == 0
        and not_ok == 0
        and rows_written["map_classify_tpu"] == args.rows
        and rows_written["map_summarize"] == args.rows
        and n_chips >= n_agents * dev_per
        and not zero
        and not missing  # an agent that executed nothing never appears
    )
    if zero or missing:
        print(f"ZERO-SHARD AGENTS: {zero + missing}", flush=True)
    print("DRAIN", "OK" if ok else "FAILED", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
