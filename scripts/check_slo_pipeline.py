#!/usr/bin/env python3
"""CI smoke for the fleet health & SLO engine (ISSUE 8).

Drives a mixed interactive+bulk drain through the real ``Agent`` loop over
``chaos.LoopbackSession`` against a controller with CI-shrunk SLO windows,
then asserts the acceptance bar end to end:

1. healthy traffic (1ms interactive ops on tier 8 + bulk risk_accumulate
   shards) → ``/v1/health`` verdict ``ok``, interactive attainment ≈ 1;
2. an injected latency regression (the probe op sleeps past the p99
   target) drops attainment, drives the burn rate through ``warn`` into
   ``page``, and flips the verdict within one short window — served over
   real HTTP, not just in-process;
3. entering ``page`` auto-dumps BOTH flight-recorder rings (controller at
   the transition, agent on the next granted lease via the piggybacked
   alert), tagged with the breaching objective;
4. clean traffic recovers the verdict to ``ok`` through the hysteresis
   exit (short-window burn below exit_frac × threshold);
5. ``SLO_ENABLED=0`` no-ops the whole path: no tracker, no ``slo_*``
   metric families, health still serves fleet/queue signals;
6. steady-state overhead: rows/sec over a 1024-row-shard drain with the
   SLO engine on stays within 10% of off (best-of-3 interleaved — the
   true cost is ≤2%, the CI bar absorbs shared-runner noise).

Exit 0 = clean; 1 = problems (one per line). Style sibling of
``scripts/check_trace_pipeline.py``: repo-rooted, stdlib-only driver.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import tempfile
import time
import urllib.request
from typing import List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from agent_tpu.agent.app import Agent
from agent_tpu.chaos import LoopbackSession
from agent_tpu.config import AgentConfig, Config, SloConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.server import ControllerServer

# CI-shrunk windows: the production shape is 5m/1h; the MATH is identical
# (cell width = short/5), so seconds-scale windows pin the same behavior.
WINDOW_SHORT = 2.0
WINDOW_LONG = 8.0
BURN_WARN = 2.0
BURN_PAGE = 6.0

SLO_SPEC = json.dumps([
    {"name": "interactive", "tier": 8, "p99_ms": 150, "availability": 0.9},
    {"name": "bulk", "op": "risk_accumulate", "p99_ms": 60000,
     "availability": 0.9},
])

BULK_SHARDS = 8
BULK_ROWS_PER_SHARD = 16

BENCH_SHARDS = 16
BENCH_ROWS_PER_SHARD = 1024
BENCH_ROUNDS = 3
BENCH_TOLERANCE = 0.90

# The injected-latency probe ships through the designed extension point
# (OPS_PLUGIN_PATH / load_plugins), not a registry monkey-patch.
PLUGIN_SRC = '''\
"""Smoke-only op: payload-controlled latency (the injected regression)."""
import time

from agent_tpu.ops import register_op


@register_op("interactive_probe")
def run(payload, ctx=None):
    time.sleep(float(payload.get("sleep_ms", 1.0)) / 1e3)
    return {"ok": True}
'''


def build_csv(path: str, rows: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text,risk\n")
        for i in range(rows):
            f.write(f'{i},"record {i}",{(i % 13) * 0.5}\n')


def make_controller(enabled: bool = True) -> Controller:
    return Controller(
        lease_ttl_sec=30.0,
        slo=SloConfig(
            enabled=enabled, spec=SLO_SPEC,
            window_short_sec=WINDOW_SHORT, window_long_sec=WINDOW_LONG,
            burn_warn=BURN_WARN, burn_page=BURN_PAGE, burn_exit_frac=0.5,
        ),
    )


def make_agent(controller: Controller, tasks: Tuple[str, ...],
               name: str = "slo-smoke") -> Agent:
    cfg = Config(agent=AgentConfig(
        controller_url="http://loopback", agent_name=name,
        tasks=tasks, max_tasks=4, idle_sleep_sec=0.0, error_backoff_sec=0.0,
    ))
    agent = Agent(config=cfg, session=LoopbackSession(controller))
    agent._profile = {"tier": "slo-smoke"}  # skip hardware probing
    return agent


def drain(controller: Controller, agent: Agent, deadline_s: float = 60.0
          ) -> bool:
    deadline = time.monotonic() + deadline_s
    while not controller.drained() and time.monotonic() < deadline:
        leased = agent.lease_once()
        if leased is None:
            controller.sweep()
            continue
        lease_id, tasks = leased
        for task in tasks:
            agent.run_task(lease_id, task)
    agent.push_metrics()
    return controller.drained()


def interactive_attainment(controller: Controller) -> Optional[float]:
    for obj in controller.slo.evaluate():
        if obj["objective"] == "interactive":
            return obj["attainment"]
    return None


def interactive_state(controller: Controller) -> str:
    return controller.slo.states()["interactive"]


def http_health(server_url: str) -> dict:
    with urllib.request.urlopen(server_url + "/v1/health", timeout=10) as r:
        return json.load(r)


def drain_rows_per_sec(csv_path: str, enabled: bool) -> float:
    rows = BENCH_SHARDS * BENCH_ROWS_PER_SHARD
    controller = make_controller(enabled=enabled)
    controller.submit_csv_job(
        csv_path, total_rows=rows, shard_size=BENCH_ROWS_PER_SHARD,
        map_op="risk_accumulate", extra_payload={"field": "risk"},
        reduce_op="risk_accumulate", collect_partials=True,
    )
    agent = make_agent(controller, tasks=("risk_accumulate",), name="bench")
    t0 = time.perf_counter()
    if not drain(controller, agent, deadline_s=120.0):
        raise RuntimeError(f"bench drain wedged: {controller.counts()}")
    return rows / (time.perf_counter() - t0)


def main() -> int:
    problems: List[str] = []
    tmp = tempfile.mkdtemp(prefix="slo_smoke_")
    os.environ["FLIGHT_RECORDER_DIR"] = tmp

    plugin_path = os.path.join(tmp, "interactive_probe_plugin.py")
    with open(plugin_path, "w", encoding="utf-8") as f:
        f.write(PLUGIN_SRC)
    from agent_tpu.ops import load_plugins

    if "interactive_probe" not in load_plugins(plugin_path):
        from agent_tpu.ops import OPS_LOAD_ERRORS

        print(f"interactive_probe plugin failed to load: {OPS_LOAD_ERRORS}")
        return 1

    csv_path = os.path.join(tmp, "bulk.csv")
    build_csv(csv_path, BULK_SHARDS * BULK_ROWS_PER_SHARD)

    controller = make_controller()
    agent = make_agent(
        controller, tasks=("risk_accumulate", "interactive_probe")
    )

    with ControllerServer(controller) as server:
        # ---- phase 1: healthy mixed traffic ----
        controller.submit_csv_job(
            csv_path, total_rows=BULK_SHARDS * BULK_ROWS_PER_SHARD,
            shard_size=BULK_ROWS_PER_SHARD, map_op="risk_accumulate",
            extra_payload={"field": "risk"},
        )
        for _ in range(12):
            controller.submit(
                "interactive_probe", {"sleep_ms": 1.0}, priority=8,
            )
        if not drain(controller, agent):
            print(f"healthy drain wedged: {controller.counts()}")
            return 1
        attain_healthy = interactive_attainment(controller)
        health = http_health(server.url)
        if health["verdict"] != "ok":
            problems.append(
                f"healthy phase verdict {health['verdict']!r}, want ok "
                f"(reasons={health['reasons']})"
            )
        if attain_healthy is None or attain_healthy < 0.99:
            problems.append(
                f"healthy interactive attainment {attain_healthy}, want ≈1"
            )
        agents_row = health["agents"].get("slo-smoke") or {}
        if agents_row.get("duty_cycle") is None:
            problems.append("health carries no agent duty cycle")

        # ---- phase 2: injected latency regression ----
        t_regress = time.monotonic()
        for _ in range(12):
            controller.submit(
                "interactive_probe", {"sleep_ms": 300.0}, priority=8,
            )
        if not drain(controller, agent):
            print(f"regression drain wedged: {controller.counts()}")
            return 1
        results = controller.slo.evaluate()
        inter = next(
            o for o in results if o["objective"] == "interactive"
        )
        flip_s = time.monotonic() - t_regress
        if inter["attainment"] is None or inter["attainment"] >= 0.5:
            problems.append(
                f"regression did not drop attainment: {inter['attainment']}"
            )
        if inter["burn_rate_short"] < BURN_WARN:
            problems.append(
                f"short burn {inter['burn_rate_short']} never reached the "
                f"warn threshold {BURN_WARN}"
            )
        if inter["state"] != "page":
            problems.append(
                f"regression state {inter['state']!r}, want page "
                f"(burn short={inter['burn_rate_short']}, "
                f"long={inter['burn_rate_long']})"
            )
        health = http_health(server.url)
        if health["verdict"] != "page":
            problems.append(
                f"/v1/health verdict {health['verdict']!r} under "
                "regression, want page"
            )
        elif flip_s > WINDOW_SHORT + 10.0:
            problems.append(
                f"verdict flip took {flip_s:.1f}s — not within one short "
                "window of the regression"
            )
        bulk = next(o for o in results if o["objective"] == "bulk")
        if bulk["state"] != "ok":
            problems.append(
                f"bulk objective collaterally {bulk['state']!r} — "
                "selectors must isolate the breaching class"
            )

        # ---- phase 3: both flight recorders auto-dumped, tagged ----
        if len(controller.slo_dump_paths) != 1:
            problems.append(
                f"controller page dumps: {controller.slo_dump_paths} "
                "(want exactly one)"
            )
        else:
            dump = controller.slo_dump_paths[0]
            if "slo-interactive" not in dump or "tier8" not in dump:
                problems.append(f"controller dump path untagged: {dump}")
            kinds = {
                json.loads(line)["kind"] for line in open(dump)
            }
            if "slo_alert" not in kinds:
                problems.append("controller dump lacks the slo_alert event")
        # The agent dumps on the next granted lease carrying the alert —
        # the regression drain already leased while paging, so the dump
        # must exist by now.
        if len(agent.slo_dump_paths) != 1:
            problems.append(
                f"agent page dumps: {agent.slo_dump_paths} (want exactly "
                "one — the piggybacked alert should have fired it)"
            )
        elif "agent-slo-smoke-slo-interactive" not in agent.slo_dump_paths[0]:
            problems.append(
                f"agent dump path untagged: {agent.slo_dump_paths[0]}"
            )
        stray = [
            p for p in glob.glob(os.path.join(tmp, "agent_tpu_flight_*"))
            if p not in controller.slo_dump_paths
            and p not in agent.slo_dump_paths
        ]
        if stray:
            problems.append(f"unexpected extra dumps: {stray}")

        # ---- phase 4: recovery with hysteresis ----
        recovered = False
        deadline = time.monotonic() + 6.0 * WINDOW_LONG
        while time.monotonic() < deadline:
            for _ in range(4):
                controller.submit(
                    "interactive_probe", {"sleep_ms": 1.0}, priority=8,
                )
            drain(controller, agent, deadline_s=30.0)
            controller.sweep()
            if interactive_state(controller) == "ok":
                recovered = True
                break
            time.sleep(WINDOW_SHORT / 4.0)
        if not recovered:
            problems.append(
                f"verdict never recovered to ok "
                f"(state={interactive_state(controller)})"
            )
        else:
            health = http_health(server.url)
            if health["verdict"] != "ok":
                problems.append(
                    f"post-recovery /v1/health verdict "
                    f"{health['verdict']!r}, want ok"
                )

    # ---- phase 5: SLO_ENABLED=0 no-ops the path ----
    off = make_controller(enabled=False)
    off.submit("interactive_probe", {"sleep_ms": 300.0}, priority=8)
    off_agent = make_agent(off, tasks=("interactive_probe",), name="off")
    if not drain(off, off_agent):
        problems.append("SLO-disabled drain wedged")
    h = off.health_json()
    if h["slo"] != {"enabled": False, "objectives": []}:
        problems.append(f"disabled health still judges: {h['slo']}")
    if h["verdict"] != "ok":
        problems.append(f"disabled verdict {h['verdict']!r}, want ok")
    slo_fams = [k for k in off.metrics.snapshot() if k.startswith("slo_")]
    if slo_fams:
        problems.append(f"disabled controller registered {slo_fams}")

    # ---- phase 6: steady-state overhead on the 1024-row-shard drain ----
    bench_csv = os.path.join(tmp, "bench.csv")
    build_csv(bench_csv, BENCH_SHARDS * BENCH_ROWS_PER_SHARD)
    best = {False: 0.0, True: 0.0}
    for _ in range(BENCH_ROUNDS):
        for mode in (False, True):
            best[mode] = max(best[mode], drain_rows_per_sec(bench_csv, mode))
    ratio = best[True] / best[False] if best[False] else 0.0
    print(
        f"slo overhead: off {best[False]:.0f} rows/s, on "
        f"{best[True]:.0f} rows/s (ratio {ratio:.3f})"
    )
    if ratio < BENCH_TOLERANCE:
        problems.append(
            f"SLO-on drain rate {best[True]:.0f} rows/s is below "
            f"{BENCH_TOLERANCE:.0%} of SLO-off {best[False]:.0f} rows/s"
        )

    if problems:
        for p in problems:
            print(p)
        print(f"FAILED: {len(problems)} problem(s)")
        return 1
    print("slo pipeline smoke check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
