#!/usr/bin/env python3
"""CI smoke for resource accounting & continuous profiling (ISSUE 9).

Drives a mixed TWO-TENANT drain through the real ``Agent`` loop over
``chaos.LoopbackSession`` against a controller serving the new surfaces
over real HTTP, then asserts the acceptance bar end to end:

1. **Usage reconciliation** — ``GET /v1/usage`` per-tenant
   ``device_seconds`` totals sum to the fleet-merged
   ``device_busy_seconds_total{op}`` within 1% on a two-tenant
   1024-row-shard drain, both tenants appear with correct row counts, and
   the per-tenant split is disjoint (billed tasks == accepted results).
2. **Host flamegraph** — ``GET /v1/profile/host`` returns collapsed-stack
   text with ≥1 real frame (``a;b;c count`` lines, positive counts).
3. **On-demand deep capture** — ``POST /v1/profile/capture`` round-trips
   through the lease ``alerts`` channel: the agent wraps one matching op
   execution in ``jax.profiler.trace`` and the artifact path + summary land
   back at ``GET /v1/profile/captures`` with ≥1 trace file on disk.
4. **HBM telemetry** — ``device_hbm_bytes{device,kind}`` gauges appear
   when ``memory_stats()`` reports (TPU), or are CLEANLY absent (CPU CI:
   no zero-filled series, no errors).
5. **Time-series ring** — ``GET /v1/timeseries?name=tasks_total`` serves
   ≥2 samples with non-negative rates; unknown names and pre-sample reads
   return empty series, never errors.
6. **Overhead** — enabling usage+tsdb+host-profiling costs <3% rows/sec vs
   all-disabled on the same drain (best-of-N interleaved; the CI assert
   uses a 10% bar to absorb shared-runner noise, the measured ratio is
   printed for the record).

Exit 0 = clean; 1 = problems (one per line). Style sibling of
``scripts/check_slo_pipeline.py``: repo-rooted, stdlib-only driver.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from agent_tpu.agent.app import Agent
from agent_tpu.chaos import LoopbackSession
from agent_tpu.config import AgentConfig, Config, ObsConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.server import ControllerServer

SHARD_ROWS = 1024          # the acceptance bar's shard size
SHARDS_PER_TENANT = 8
TENANTS = ("tenant-a", "tenant-b")

BENCH_ROUNDS = 3
# True cost measures ~1-3%; the CI bar absorbs shared-runner noise. The
# measured ratio prints either way — that number is the record.
BENCH_TOLERANCE = 0.90


def build_csv(path: str, rows: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text,risk\n")
        for i in range(rows):
            f.write(f'{i},"record {i}",{(i % 13) * 0.5}\n')


def make_controller(enabled: bool = True,
                    tsdb_interval: float = 0.1) -> Controller:
    return Controller(
        lease_ttl_sec=30.0,
        obs=ObsConfig(
            usage_enabled=enabled,
            tsdb_enabled=enabled,
            tsdb_interval_sec=tsdb_interval,
            profile_host_enabled=enabled,
        ),
    )


def make_agent(controller: Controller, name: str = "profile-smoke") -> Agent:
    cfg = Config(agent=AgentConfig(
        controller_url="http://loopback", agent_name=name,
        tasks=("risk_accumulate",), max_tasks=4, idle_sleep_sec=0.0,
        error_backoff_sec=0.0,
    ))
    agent = Agent(config=cfg, session=LoopbackSession(controller))
    agent._profile = {"tier": "profile-smoke"}  # skip hardware probing
    return agent


def drain(controller: Controller, agent: Agent,
          deadline_s: float = 120.0) -> bool:
    deadline = time.monotonic() + deadline_s
    while not controller.drained() and time.monotonic() < deadline:
        leased = agent.lease_once()
        if leased is None:
            controller.sweep()
            continue
        lease_id, tasks = leased
        for task in tasks:
            agent.run_task(lease_id, task)
    agent.push_metrics()
    return controller.drained()


def fleet_busy_seconds(controller: Controller) -> float:
    fleet = controller.fleet_snapshot()
    return sum(
        float(s.get("value", 0.0))
        for s in fleet.get("device_busy_seconds_total", {}).get("series", [])
    )


def http_json(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.load(r)


def http_text(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode("utf-8", errors="replace")


def submit_two_tenants(controller: Controller, csv_path: str) -> None:
    for tenant in TENANTS:
        controller.submit_csv_job(
            csv_path, total_rows=SHARDS_PER_TENANT * SHARD_ROWS,
            shard_size=SHARD_ROWS, map_op="risk_accumulate",
            extra_payload={"field": "risk"}, tenant=tenant,
        )


def drain_rows_per_sec(csv_path: str, enabled: bool) -> float:
    rows = SHARDS_PER_TENANT * SHARD_ROWS * len(TENANTS)
    controller = make_controller(enabled=enabled)
    submit_two_tenants(controller, csv_path)
    if enabled:
        # The overhead leg measures the FULL feature set: profiler sampling
        # included (started eagerly here; production starts it lazily).
        controller.host_profile_text()
    agent = make_agent(controller, name="bench")
    t0 = time.perf_counter()
    if not drain(controller, agent):
        raise RuntimeError(f"bench drain wedged: {controller.counts()}")
    dt = time.perf_counter() - t0
    controller.close()
    return rows / dt


def main() -> int:
    problems: List[str] = []
    tmp = tempfile.mkdtemp(prefix="profile_smoke_")
    os.environ["PROFILE_CAPTURE_DIR"] = os.path.join(tmp, "captures")

    csv_path = os.path.join(tmp, "rows.csv")
    build_csv(csv_path, SHARDS_PER_TENANT * SHARD_ROWS)

    controller = make_controller()
    agent = make_agent(controller)

    with ControllerServer(controller) as server:
        # ---- phase 1+3: two-tenant drain with an armed deep capture ----
        submit_two_tenants(controller, csv_path)
        cap = http_json_post(
            server.url + "/v1/profile/capture",
            {"agent": "profile-smoke", "op": "risk_accumulate"},
        )
        if "capture_id" not in cap:
            problems.append(f"capture request got no id: {cap}")
        if not drain(controller, agent):
            print(f"two-tenant drain wedged: {controller.counts()}")
            return 1

        usage = http_json(server.url + "/v1/usage")
        busy = fleet_busy_seconds(controller)
        ledger = usage.get("totals", {}).get("device_seconds", 0.0)
        if busy <= 0:
            problems.append("fleet device_busy_seconds_total is zero")
        elif abs(ledger - busy) > 0.01 * busy:
            problems.append(
                f"usage device_seconds {ledger} vs fleet busy {busy} — "
                f"off by {abs(ledger - busy) / busy:.2%}, want <1%"
            )
        print(f"usage reconciliation: ledger {ledger:.4f}s vs fleet busy "
              f"{busy:.4f}s")
        by_tenant = usage.get("by_tenant", {})
        for tenant in TENANTS:
            t = by_tenant.get(tenant)
            if t is None:
                problems.append(f"/v1/usage missing tenant {tenant!r}")
                continue
            if t["rows"] != SHARDS_PER_TENANT * SHARD_ROWS:
                problems.append(
                    f"{tenant} rows {t['rows']} != "
                    f"{SHARDS_PER_TENANT * SHARD_ROWS}"
                )
            if t["tasks"] != SHARDS_PER_TENANT:
                problems.append(
                    f"{tenant} tasks {t['tasks']} != {SHARDS_PER_TENANT}"
                )
        n_jobs = SHARDS_PER_TENANT * len(TENANTS)
        if usage.get("billed_tasks") != n_jobs:
            problems.append(
                f"billed_tasks {usage.get('billed_tasks')} != jobs {n_jobs} "
                "(a result went unbilled or billed twice)"
            )
        if not usage.get("top_jobs"):
            problems.append("/v1/usage top_jobs empty after a drain")

        # ---- phase 2: host flamegraph over real HTTP ----
        flame = http_text(server.url + "/v1/profile/host")
        frames = [
            line for line in flame.splitlines()
            if line.strip() and ";" in line
            and line.rsplit(" ", 1)[-1].isdigit()
            and int(line.rsplit(" ", 1)[-1]) >= 1
        ]
        if not frames:
            problems.append(
                f"host flamegraph has no real frames: {flame[:200]!r}"
            )
        else:
            print(f"host flamegraph: {len(frames)} collapsed stack(s)")

        # ---- phase 3 (cont): capture completion round-tripped ----
        captures = http_json(server.url + "/v1/profile/captures")["captures"]
        done = [c for c in captures
                if c.get("capture_id") == cap.get("capture_id")]
        if not done:
            problems.append("capture never round-tripped to /v1/profile/"
                            f"captures: {captures}")
        else:
            c = done[0]
            if c.get("status") != "done":
                problems.append(f"capture status {c.get('status')!r}: {c}")
            elif not (c.get("artifact") and os.path.isdir(c["artifact"])
                      and (c.get("summary") or {}).get("n_trace_files", 0)
                      >= 1):
                problems.append(f"capture artifact missing on disk: {c}")
            else:
                print(f"deep capture: {c['summary']['n_trace_files']} trace "
                      f"file(s) at {c['artifact']}")

        # ---- phase 4: HBM gauges present or cleanly absent ----
        snap = agent.obs.snapshot()
        hbm = snap.get("device_hbm_bytes", {}).get("series", [])
        reports_stats = False
        if agent.runtime is not None:
            from agent_tpu.obs.profile import device_memory_stats

            reports_stats = bool(device_memory_stats(agent.runtime.devices))
        if reports_stats and not hbm:
            problems.append("backend reports memory_stats but no "
                            "device_hbm_bytes gauges were exported")
        if not reports_stats and hbm:
            problems.append(
                f"device_hbm_bytes zero-filled on a statless backend: {hbm}"
            )
        if hbm and any(s.get("value", 0) <= 0
                       for s in hbm if s["labels"]["kind"] == "limit"):
            problems.append(f"nonsense HBM limit gauge: {hbm}")
        print(f"HBM gauges: {len(hbm)} series "
              f"({'backend reports stats' if reports_stats else 'cleanly absent on this backend'})")

        # ---- phase 5: time-series ring over real HTTP ----
        ts = http_json(server.url + "/v1/timeseries?name=tasks_total&rate=1")
        if ts.get("n_samples", 0) < 2:
            problems.append(f"time-series ring has {ts.get('n_samples')} "
                            "samples, want >=2")
        if not ts.get("series"):
            problems.append("tasks_total absent from the time-series ring")
        elif any(v < 0 for s in ts["series"] for _t, v in s["points"]):
            problems.append("negative rate in tasks_total series")
        empty = http_json(server.url + "/v1/timeseries?name=no_such_series")
        if empty.get("series") != []:
            problems.append(f"unknown series name not empty: {empty}")
        missing_name = urllib.request.Request(
            server.url + "/v1/timeseries")
        try:
            urllib.request.urlopen(missing_name, timeout=10)
            problems.append("nameless /v1/timeseries did not 400")
        except urllib.error.HTTPError as exc:
            if exc.code != 400:
                problems.append(f"nameless /v1/timeseries: HTTP {exc.code}")

    controller.close()

    # ---- phase 6: overhead of the full feature set ----
    best = {False: 0.0, True: 0.0}
    for _ in range(BENCH_ROUNDS):
        for mode in (False, True):
            best[mode] = max(best[mode], drain_rows_per_sec(csv_path, mode))
    ratio = best[True] / best[False] if best[False] else 0.0
    print(
        f"usage+tsdb+profiling overhead: off {best[False]:.0f} rows/s, on "
        f"{best[True]:.0f} rows/s (ratio {ratio:.3f}; acceptance wants "
        f">0.97 true cost, CI asserts >{BENCH_TOLERANCE})"
    )
    if ratio < BENCH_TOLERANCE:
        problems.append(
            f"accounting-on drain rate {best[True]:.0f} rows/s is below "
            f"{BENCH_TOLERANCE:.0%} of off {best[False]:.0f} rows/s"
        )

    # ---- disabled path: everything off is cleanly off ----
    off = make_controller(enabled=False)
    if off.usage_json() != {"enabled": False}:
        problems.append("USAGE_ENABLED=0 still reports usage")
    if off.timeseries_json("tasks_total").get("enabled", True):
        problems.append("TSDB_ENABLED=0 still serves series")
    if off.host_profile_text() is not None:
        problems.append("PROFILE_HOST_ENABLED=0 still serves a flamegraph")
    usage_fams = [k for k in off.metrics.snapshot()
                  if k.startswith("usage_")]
    if usage_fams:
        problems.append(f"disabled controller registered {usage_fams}")
    off.close()

    if problems:
        for p in problems:
            print(p)
        print(f"FAILED: {len(problems)} problem(s)")
        return 1
    print("profile pipeline smoke check: OK")
    return 0


def http_json_post(url: str, body: dict):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.load(r)


if __name__ == "__main__":
    sys.exit(main())
