#!/usr/bin/env python3
"""CI smoke for the workflow DAG engine + result cache (ISSUE 19).

Drives a 4-stage fan-out/fan-in workflow (tokenize → 3 accumulate shards →
reduce → report) through real agents, twice over each transport, and asserts
the DAG acceptance bar:

1. LOOPBACK leg (``chaos.LoopbackSession``, real ``Agent`` loop, no
   sockets): the DAG drains end-to-end with every stage SUCCEEDED and ONE
   complete trace tree — a single root span, every other span's parent
   resolving inside the tree;
2. a second byte-identical submission is served ≥90% from the result cache
   (here: fully — zero additional agent executions) with BIT-IDENTICAL
   results, and the per-tenant dedupe ratio shows up in the usage report;
3. a stage that permanently fails cascades ``DependencyFailed`` through
   every downstream stage — nothing leases, nothing hangs;
4. a controller crash mid-DAG (journal truncated at a torn tail, no
   close) replays into a rebuilt in-flight workflow — terminal stages
   stay terminal, the critical stage is re-armed — and the resumed run's
   final output is bit-identical to an uncrashed reference run;
5. HTTP leg (real ``ControllerServer`` + ``requests`` + a pipelined
   agent): ``POST /v1/workflows`` → ``GET /v1/workflows/{id}`` to
   terminal, cached rerun bit-identical, dedupe ratio in ``/v1/usage``.

CPU-shape smoke (host-only ops, JAX_PLATFORMS=cpu). Exit 0 = all bars met.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

DOC = {
    "stages": [
        {"name": "tok", "op": "map_tokenize",
         "payload": {"text": "dag smoke corpus " * 16, "mode": "chars",
                     "chunk_size": 32}},
        {"name": "cls", "op": "risk_accumulate",
         "payload": {"values": [1.0, 2.0, 3.0, 5.0]},
         "after": ["tok"], "fan_out": 3, "collect": False},
        {"name": "acc", "op": "risk_accumulate", "payload": {},
         "after": ["cls"]},
        {"name": "rep", "op": "echo", "payload": {"final": True},
         "after": ["acc"]},
    ]
}

# All-echo variant for the crash leg: echo results carry no timings, so a
# resumed run's recomputed stages byte-match an uncrashed reference.
ECHO_DOC = {
    "stages": [
        {"name": "tok", "op": "echo", "payload": {"v": 1}},
        {"name": "cls", "op": "echo", "payload": {"v": 2},
         "after": ["tok"], "fan_out": 3, "collect": False},
        {"name": "acc", "op": "echo", "payload": {},
         "after": ["cls"]},
        {"name": "rep", "op": "echo", "payload": {"final": True},
         "after": ["acc"]},
    ]
}

OPS = ("echo", "map_tokenize", "risk_accumulate")


def make_loopback_agent(controller, name="dag-smoke"):
    from agent_tpu.agent.app import Agent
    from agent_tpu.chaos import LoopbackSession
    from agent_tpu.config import AgentConfig, Config

    cfg = Config(agent=AgentConfig(
        controller_url="http://loopback", agent_name=name,
        tasks=OPS, max_tasks=4,
        idle_sleep_sec=0.0, error_backoff_sec=0.0,
        retry_base_sec=0.001, retry_max_sec=0.01,
    ))
    agent = Agent(config=cfg, session=LoopbackSession(controller))
    # The pipelined poster thread builds its own requests.Session unless
    # told otherwise — route it through the loopback too.
    agent.post_session_factory = lambda: agent.session
    agent._profile = {"tier": "smoke"}
    return agent


def wait_workflow(controller, wid, timeout=120.0):
    deadline = time.monotonic() + timeout
    while True:
        wj = controller.workflow_json(wid)
        if wj is not None and wj["state"] in ("succeeded", "dead"):
            return wj
        assert time.monotonic() < deadline, (
            f"workflow {wid} stuck: {wj and wj['state']}"
        )
        time.sleep(0.02)


def run_agent_while(controller, fn):
    agent = make_loopback_agent(controller)
    t = threading.Thread(target=agent.run, daemon=True)
    t.start()
    try:
        return fn()
    finally:
        agent.running = False
        t.join(timeout=60)


def results_bytes(wj):
    return json.dumps(wj["results"], sort_keys=True).encode()


def assert_one_trace_tree(controller, wid, n_jobs):
    spans = controller.traces.spans(wid)
    roots = [s for s in spans if not s.get("parent_span_id")]
    assert len(roots) == 1, f"{len(roots)} roots in trace {wid}"
    assert roots[0]["name"] == "workflow", roots[0]
    ids = {s["span_id"] for s in spans}
    dangling = [
        s["name"] for s in spans
        if s.get("parent_span_id") and s["parent_span_id"] not in ids
    ]
    assert not dangling, f"spans with unresolved parents: {dangling}"
    assert len(spans) > n_jobs, f"only {len(spans)} spans for {n_jobs} jobs"


def loopback_leg():
    """Bars 1-3 over LoopbackSession."""
    from agent_tpu.controller.core import Controller

    controller = Controller(lease_ttl_sec=600.0)

    # Bar 1: drain + single trace tree.
    out = controller.submit_workflow(DOC, tenant="acme")
    wid = out["workflow_id"]
    assert out["stages"] == ["tok", "cls", "acc", "rep"]
    wj1 = run_agent_while(controller, lambda: wait_workflow(controller, wid))
    assert wj1["state"] == "succeeded", wj1
    assert wj1["terminal_jobs"] == wj1["total_jobs"] == 6
    (rep,) = wj1["results"].values()
    assert rep["echo"]["partials"][0]["count"] == 12  # 3 shards x 4 values
    assert_one_trace_tree(controller, wid, 6)

    # Bar 2: byte-identical resubmission, served from cache. The agent
    # keeps polling, but every stage lands as a lease-path cache hit —
    # cache_hits == total_jobs proves zero re-executions.
    out2 = controller.submit_workflow(DOC, tenant="acme")
    wj2 = run_agent_while(
        controller,
        lambda: wait_workflow(controller, out2["workflow_id"], timeout=30.0),
    )
    assert wj2["state"] == "succeeded", wj2
    assert wj2["cache_hits"] >= 0.9 * wj2["total_jobs"], wj2["cache_hits"]
    assert wj2["cache_hits"] == wj2["total_jobs"] == 6, wj2
    assert json.dumps(list(wj1["results"].values()), sort_keys=True) \
        == json.dumps(list(wj2["results"].values()), sort_keys=True)
    usage = controller.usage_json()
    assert usage["totals"]["result_cache_hits"] == wj2["cache_hits"]
    assert usage["by_tenant"]["acme"]["result_dedupe_ratio"] is not None

    # Bar 3: DependencyFailed cascade from a permanently failing stage.
    out3 = controller.submit_workflow({
        "stages": [
            # A failed-shard partial makes risk_accumulate raise (hard
            # failure, not an ok:False soft result) — with max_attempts=1
            # the stage dies permanently and the cascade must fire.
            {"name": "boom", "op": "risk_accumulate",
             "payload": {"partials": [{"ok": False, "error": "poisoned"}]},
             "max_attempts": 1, "collect": False},
            {"name": "victim", "op": "echo", "payload": {},
             "after": ["boom"]},
        ]
    })
    wj3 = run_agent_while(
        controller,
        lambda: wait_workflow(controller, out3["workflow_id"]),
    )
    assert wj3["state"] == "dead", wj3
    assert wj3["terminal_jobs"] == 2
    victim = controller.job_snapshot(out3["job_ids"][-1])
    assert victim["state"] == "dead"
    assert victim["error"]["type"] == "DependencyFailed", victim["error"]
    assert controller.lease("probe", {"ops": list(OPS)}) is None

    return (
        f"loopback: drained 6/6 with 1 trace tree, rerun "
        f"{wj2['cache_hits']}/{wj2['total_jobs']} from cache bit-identical, "
        f"cascade killed {wj3['terminal_jobs']} jobs"
    )


def deterministic_drain(controller, limit=None):
    """Drain echo jobs through the public lease/report API with
    deterministic result bodies (agent wrappers embed random lease ids,
    which would defeat the byte-compare)."""
    done = 0
    while limit is None or done < limit:
        lease = controller.lease("det", {"ops": ["echo"]}, max_tasks=1)
        if lease is None:
            break
        for t in lease["tasks"]:
            controller.report(
                lease["lease_id"], t["id"], t["job_epoch"], "succeeded",
                result={"ok": True, "echo": t["payload"]},
            )
            done += 1
    return done


def crash_replay_leg(tmpdir):
    """Bar 4: kill the controller mid-DAG, replay, finish, byte-compare."""
    from agent_tpu.config import FlowConfig
    from agent_tpu.controller.core import Controller

    # Reference: same DAG, no crash. Cache off so every stage really runs.
    ref = Controller(flow=FlowConfig(cache_enabled=False))
    rout = ref.submit_workflow(ECHO_DOC, workflow_id="wf-crash")
    deterministic_drain(ref)
    ref_wj = ref.workflow_json(rout["workflow_id"])
    assert ref_wj["state"] == "succeeded"

    # Crashing run: drain tok + the 3 cls shards, then die WITHOUT close().
    jp = os.path.join(tmpdir, "journal.jsonl")
    c1 = Controller(journal_path=jp, flow=FlowConfig(cache_enabled=False))
    c1.submit_workflow(ECHO_DOC, workflow_id="wf-crash")
    assert deterministic_drain(c1, limit=4) == 4
    # Simulate the kill: a torn, unflushed final line on the journal tail.
    with open(jp, "ab") as f:
        f.write(b'{"ev": "result", "job_id": "wf-crash')

    c2 = Controller(journal_path=jp, flow=FlowConfig(cache_enabled=False))
    wj = c2.workflow_json("wf-crash")
    assert wj is not None and wj["state"] == "running", wj
    assert wj["terminal_jobs"] == 4, wj
    assert wj["critical_stage"] == "acc", wj
    deterministic_drain(c2)
    got_wj = c2.workflow_json("wf-crash")
    assert got_wj["state"] == "succeeded", got_wj
    assert results_bytes(got_wj) == results_bytes(ref_wj), (
        "resumed DAG output diverged from the uncrashed reference"
    )
    return "crash-replay: resumed 4/6 -> 6/6, output bit-identical"


def http_leg():
    """Bar 5: the same contract over real sockets."""
    import requests

    from agent_tpu.agent.app import Agent
    from agent_tpu.agent.pipeline import PipelineRunner
    from agent_tpu.config import AgentConfig, Config
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer

    controller = Controller(lease_ttl_sec=600.0)
    server = ControllerServer(controller).start()
    try:
        cfg = Config(agent=AgentConfig(
            controller_url=server.url, agent_name="dag-http",
            tasks=OPS, idle_sleep_sec=0.0,
        ))
        agent = Agent(config=cfg, session=requests.Session())
        agent._profile = {"tier": "smoke"}
        runner = PipelineRunner(agent, depth=2)
        t = threading.Thread(target=runner.run, daemon=True)
        t.start()
        sess = requests.Session()

        def submit():
            r = sess.post(server.url + "/v1/workflows",
                          json=dict(DOC, tenant="acme"), timeout=30)
            assert r.status_code == 200, r.text
            return r.json()["workflow_id"]

        def wait_http(wid):
            deadline = time.monotonic() + 120
            while True:
                r = sess.get(server.url + f"/v1/workflows/{wid}", timeout=30)
                assert r.status_code == 200, r.text
                wj = r.json()
                if wj["state"] in ("succeeded", "dead"):
                    return wj
                assert time.monotonic() < deadline, wj
                time.sleep(0.05)

        wj1 = wait_http(submit())
        assert wj1["state"] == "succeeded", wj1
        wj2 = wait_http(submit())
        assert wj2["state"] == "succeeded", wj2
        assert wj2["cache_hits"] >= 0.9 * wj2["total_jobs"], wj2
        assert json.dumps(list(wj1["results"].values()), sort_keys=True) \
            == json.dumps(list(wj2["results"].values()), sort_keys=True)
        r = sess.get(server.url + "/v1/usage", timeout=30)
        assert r.status_code == 200, r.text
        usage = r.json()
        assert usage["totals"]["result_cache_hits"] >= wj2["cache_hits"]
        assert usage["by_tenant"]["acme"]["result_dedupe_ratio"] is not None
        agent.running = False
        t.join(timeout=60)
        return (
            f"http: 2 submits, rerun {wj2['cache_hits']}/"
            f"{wj2['total_jobs']} cached, dedupe ratio "
            f"{usage['by_tenant']['acme']['result_dedupe_ratio']}"
        )
    finally:
        server.stop()


def main() -> int:
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory() as td:
        print("[dag-smoke] loopback leg ...", flush=True)
        line1 = loopback_leg()
        print("[dag-smoke] crash-replay leg ...", flush=True)
        line2 = crash_replay_leg(td)
        print("[dag-smoke] http leg ...", flush=True)
        line3 = http_leg()
    print(
        f"[dag-smoke] OK: {line1}; {line2}; {line3}; "
        f"wall {time.monotonic() - t0:.1f}s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
