#!/usr/bin/env python3
"""swarmtop — live terminal dashboard for an agent-tpu fleet (ISSUE 8/9).

Renders fleet state from ``GET /v1/health`` + ``/v1/status`` +
``/v1/timeseries`` the way ``top`` renders a host: a verdict banner, per-SLO
attainment/burn/budget rows, queue pressure by tier, one row per agent
(liveness, rolling duty cycle, per-op MFU, staged queue depth), and trend
sparklines (tasks/s, rows/s, queue depth, duty cycle) fed by the
controller's time-series ring — rates come from the controller's own
sampling clock, not from client-side scrape deltas, so the first frame
already has history (``/v1/metrics`` scrape deltas remain the fallback
against controllers predating the ring).

    python scripts/swarmtop.py --url http://controller:8080
    python scripts/swarmtop.py --url ... --once        # one frame (CI/cron)
    python scripts/swarmtop.py --url ... --json        # one JSON doc (scripting)
    python scripts/swarmtop.py --url ... --interval 5  # refresh cadence

Dependency-free by the obs charter: stdlib urllib + ANSI escapes only.
``--once`` / ``--json`` / ``--no-color`` make it pipeline-safe; exit code 2
when the controller is unreachable (so a watchdog cron can alert on it),
else 0.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from agent_tpu.obs.metrics import parse_exposition  # noqa: E402

RESET = "\x1b[0m"
BOLD = "\x1b[1m"
DIM = "\x1b[2m"
FG = {"ok": "\x1b[32m", "warn": "\x1b[33m", "page": "\x1b[31m"}
CLEAR = "\x1b[2J\x1b[H"


def fetch_json(url: str, timeout: float = 5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            if resp.status != 200:
                return None
            return json.loads(resp.read().decode("utf-8", errors="replace"))
    except Exception:  # noqa: BLE001 — a down controller renders as such
        return None


def fetch_text(url: str, timeout: float = 5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            if resp.status != 200:
                return None
            return resp.read().decode("utf-8", errors="replace")
    except Exception:  # noqa: BLE001
        return None


def fmt_pct(v, digits: int = 1) -> str:
    return f"{v * 100:.{digits}f}%" if isinstance(v, (int, float)) else "-"


def fmt_num(v, digits: int = 2) -> str:
    return f"{v:.{digits}f}" if isinstance(v, (int, float)) else "-"


def bar(frac, width: int = 10) -> str:
    """A tiny utilization bar: ``[####......]``."""
    if not isinstance(frac, (int, float)):
        return "[" + " " * width + "]"
    n = max(0, min(width, int(round(frac * width))))
    return "[" + "#" * n + "." * (width - n) + "]"


class Colors:
    def __init__(self, enabled: bool) -> None:
        self.enabled = enabled

    def paint(self, text: str, *codes: str) -> str:
        if not self.enabled or not codes:
            return text
        return "".join(codes) + text + RESET

    def state(self, state: str) -> str:
        return self.paint(state.upper(), FG.get(state, ""), BOLD)


def _expo_quantile(samples, family: str, q: float):
    """q-quantile (seconds) from a family's cumulative ``_bucket`` samples
    in a parsed exposition (label sets merged — swarmtop shows the fleet
    line); None when the family is empty."""
    acc = {}
    for labels, v in samples.get(family + "_bucket", []):
        le = labels.get("le")
        if le is None:
            continue
        edge = float("inf") if le in ("+Inf", "inf") else float(le)
        acc[edge] = acc.get(edge, 0.0) + v
    if not acc:
        return None
    edges = sorted(acc)
    cum = [acc[e] for e in edges]
    total = cum[-1]
    if total <= 0:
        return None
    target = q * total
    prev_edge, prev_cum = 0.0, 0.0
    for edge, c in zip(edges, cum):
        if c >= target:
            if edge == float("inf"):
                finite = [e for e in edges if e != float("inf")]
                return finite[-1] if finite else None
            width = c - prev_cum
            frac = (target - prev_cum) / width if width > 0 else 1.0
            return prev_edge + (edge - prev_edge) * frac
        prev_edge, prev_cum = edge, c
    return edges[-2] if len(edges) > 1 else None


def serving_summary(metrics_text, status):
    """The serving row's feed (ISSUE 15/16): request-state counts off the
    /v1/status serving block + TTFT p99, running-batch occupancy, prefix-
    cache hit rate, and paged-KV pool occupancy off the exposition. None
    when serving is disabled."""
    serving = (status or {}).get("serving") or {}
    if not serving.get("enabled"):
        return None
    out = {
        "requests": serving.get("requests") or {},
        "bucketed": serving.get("bucketed", 0),
        "in_flight": serving.get("jobs_in_flight", 0),
        "rejected": serving.get("rejected", 0),
        "ttft_p99_ms": None,
        "occupancy": None,
        "prefix_hit_rate": None,
        "kv_blocks_free": None,
        "kv_blocks_total": None,
    }
    if metrics_text:
        try:
            samples = parse_exposition(metrics_text)
        except ValueError:
            samples = {}
        p99 = _expo_quantile(samples, "serve_ttft_seconds", 0.99)
        out["ttft_p99_ms"] = p99 * 1e3 if p99 is not None else None
        occ = [
            v for labels, v in samples.get("serve_batch_occupancy", [])
            if "agent" not in labels
        ]
        out["occupancy"] = max(occ) if occ else None
        # Prefix-cache hit rate (ISSUE 16): cumulative hits/(hits+misses)
        # off the event-labeled counter.
        events = {}
        for labels, v in samples.get("serve_prefix_cache_events_total", []):
            if "agent" in labels:
                continue
            events[labels.get("event")] = events.get(
                labels.get("event"), 0.0
            ) + v
        looked = events.get("hits", 0.0) + events.get("misses", 0.0)
        if looked > 0:
            out["prefix_hit_rate"] = events.get("hits", 0.0) / looked
        # Paged-KV pool occupancy (ISSUE 16): free/total block gauges.
        for key, fam in (("kv_blocks_free", "serve_kv_blocks_free"),
                         ("kv_blocks_total", "serve_kv_blocks_total")):
            vals = [
                v for labels, v in samples.get(fam, [])
                if "agent" not in labels
            ]
            out[key] = vals[-1] if vals else None
    return out


def request_tail(base: str, limit: int = 5):
    """The per-request tail panel's feed (ISSUE 17): the slowest/error
    records the wide-event request log kept, newest first, each with its
    dominant TTFT component ("why was THIS request slow"). None when the
    controller predates the log or serving is off."""
    body = fetch_json(
        base + f"/v1/debug/requests?slow=1&limit={int(limit)}"
    )
    if not isinstance(body, dict) or not body.get("enabled"):
        return None
    out = []
    for rec in body.get("requests") or []:
        comps = rec.get("components") or {}
        dom = rec.get("dominant_component")
        out.append({
            "req_id": rec.get("req_id"),
            "tenant": rec.get("tenant"),
            "op": rec.get("op"),
            "outcome": rec.get("outcome"),
            "path": rec.get("path"),
            "ttft_ms": rec.get("ttft_ms"),
            "tpot_ms": rec.get("tpot_ms"),
            "dominant_component": dom,
            "dominant_ms": comps.get(dom),
            "kept": rec.get("kept"),
        })
    return out


def partition_rows(status, health):
    """Per-partition rows for a partitioned control plane (ISSUE 18):
    the router's merged ``/v1/status`` carries one row per partition
    (reachability, counts, queue, journal block); the merged health's
    partition-stamped reasons turn into a per-partition verdict. None
    against a plain single controller."""
    if not isinstance(status, dict) or not status.get("partitioned"):
        return None
    flagged = set()
    for r in (health or {}).get("reasons") or []:
        if isinstance(r, dict) and r.get("partition"):
            flagged.add(r["partition"])
    rows = []
    for row in status.get("partitions") or []:
        name = row.get("name")
        ok = bool(row.get("ok"))
        counts = row.get("counts") or {}
        j = row.get("journal") or {}
        rows.append({
            "name": name,
            "ok": ok,
            "verdict": (
                "page" if not ok
                else ("warn" if name in flagged else "ok")
            ),
            "queue_depth": row.get("queue_depth"),
            "succeeded": counts.get("succeeded", 0),
            "pending": counts.get("pending", 0),
            "running": counts.get("running", 0),
            "drained": row.get("drained"),
            "journal_segments": j.get("segments"),
            "journal_bytes": j.get("bytes"),
            "snapshot_age_sec": j.get("last_snapshot_age_sec"),
            "promotions": j.get("promotions"),
        })
    return rows


def workflow_rows(base: str):
    """Workflows panel feed (ISSUE 19): the DAG list off ``GET
    /v1/workflows`` (per-DAG stage progress, critical-path stage, cache
    hits), the result-cache counters, and per-tenant dedupe ratios off
    ``/v1/usage``. None against a controller predating workflows."""
    body = fetch_json(base + "/v1/workflows")
    if not isinstance(body, dict) or "workflows" not in body:
        return None
    dedupe = {}
    usage = fetch_json(base + "/v1/usage")
    for tenant, rec in ((usage or {}).get("by_tenant") or {}).items():
        if isinstance(rec, dict) and rec.get("result_dedupe_ratio"):
            dedupe[tenant] = rec["result_dedupe_ratio"]
    return {
        "workflows": body.get("workflows") or [],
        "result_cache": body.get("result_cache"),
        "dedupe_by_tenant": dedupe,
    }


def tasks_total(metrics_text) -> float:
    """Fleet-wide completed tasks off the exposition (unlabeled merge only —
    ``agent``-labeled duplicates would double-count). The scrape-delta
    FALLBACK rate source for controllers without a time-series ring."""
    if not metrics_text:
        return 0.0
    try:
        samples = parse_exposition(metrics_text)
    except ValueError:
        return 0.0
    return sum(
        v for labels, v in samples.get("tasks_total", [])
        if "agent" not in labels
    )


# ---- time-series trends (ISSUE 9: rates from the controller's ring) ----

SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def spark(values, width: int = 12) -> str:
    """Unicode sparkline of the last ``width`` values (empty-safe)."""
    vals = [v for v in values if isinstance(v, (int, float))][-width:]
    if not vals:
        return "-" * width
    hi = max(vals)
    if hi <= 0:
        return SPARK_BLOCKS[0] * len(vals)
    return "".join(
        SPARK_BLOCKS[min(len(SPARK_BLOCKS) - 1,
                         int(v / hi * (len(SPARK_BLOCKS) - 1) + 0.5))]
        for v in vals
    )


def parse_since(text):
    """``10m`` / ``2h`` / ``600`` → seconds-ago (float), or None."""
    if not text:
        return None
    text = text.strip().lower()
    mult = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}.get(text[-1])
    try:
        return float(text[:-1]) * mult if mult else float(text)
    except ValueError:
        return None


def fetch_series(base: str, name: str, rate: bool = False, since=None,
                 **labels):
    """``GET /v1/timeseries`` → summed-across-series ``[(ts, value), ...]``
    (label sets collapse — swarmtop trends the fleet line), or None when
    the endpoint is absent/disabled (pre-ring controller). ``since``
    (seconds-ago) reads durable history through the ISSUE 20 store —
    windows past the ring get a downsampling ``step`` so the payload
    stays bounded."""
    q = f"name={name}" + ("&rate=1" if rate else "")
    if since is not None:
        q += f"&since={since:g}"
        if since > 1800:
            q += f"&step={60 if since <= 43200 else 600}"
    for k, v in labels.items():
        q += f"&{k}={v}"
    body = fetch_json(base + "/v1/timeseries?" + q)
    if not isinstance(body, dict) or not body.get("enabled", True):
        return None
    acc = {}
    for s in body.get("series", []):
        for t, v in s.get("points", []):
            acc[t] = acc.get(t, 0.0) + v
    return sorted(acc.items())


def collect_trends(base: str, since=None):
    """The sparkline feed: tasks/s + rows/s rates, queue depth and duty
    cycle levels. Each value is ``[(ts, v), ...]`` or None when the ring
    doesn't carry the family (yet). ``since`` widens every trend to
    durable history (``--since 10m``)."""
    return {
        "tasks_per_sec": fetch_series(
            base, "tasks_total", rate=True, since=since),
        "rows_per_sec": fetch_series(
            base, "usage_rows_total", rate=True, since=since),
        "queue_depth": fetch_series(
            base, "controller_queue_depth", state="leasable", since=since
        ),
        "duty_cycle": fetch_series(base, "device_duty_cycle", since=since),
        # Serving (ISSUE 15): emitted tokens/sec off the controller's
        # completion fan-out counter.
        "serve_tok_per_sec": fetch_series(
            base, "serve_tokens_total", rate=True, since=since
        ),
    }


def incident_summary(base: str):
    """``GET /v1/incidents`` → the Incidents line feed: total count plus
    the newest few bundle headers; None when the endpoint is absent
    (pre-ISSUE-20 controller) or forensics are disabled."""
    body = fetch_json(base + "/v1/incidents")
    if not isinstance(body, dict) or not body.get("enabled", False):
        return None
    rows = body.get("incidents") or []
    return {"count": len(rows), "newest": rows[:3]}


def last_value(points):
    return points[-1][1] if points else None


def render(health, status, rate, colors: Colors, trends=None,
           serving=None, req_tail=None, partitions=None,
           workflows=None, incidents=None) -> str:
    lines = []
    verdict = health.get("verdict", "?")
    now = time.strftime("%H:%M:%S")
    reasons = health.get("reasons") or []
    head = (
        f"{colors.paint('swarmtop', BOLD)}  {now}   verdict: "
        f"{colors.state(verdict)}"
    )
    if rate is not None:
        head += f"   fleet: {rate:.1f} tasks/s"
    lines.append(head)
    for r in reasons:
        lines.append(colors.paint(f"  ! {json.dumps(r)}", FG["warn"]))
    if incidents is not None:
        newest = ", ".join(
            f"{h.get('id')} {h.get('kind')}/{h.get('key')} "
            f"({max(0, time.time() - (h.get('wall') or 0)):.0f}s ago)"
            for h in incidents.get("newest", [])
        ) or "none"
        line = f"incidents: {incidents.get('count', 0)}   {newest}"
        lines.append(
            colors.paint("  " + line,
                         FG["warn"] if incidents.get("count") else DIM)
        )
    lines.append("")

    slo = health.get("slo", {})
    lines.append(colors.paint(
        f"SLO objectives ({'on' if slo.get('enabled') else 'OFF'})", BOLD))
    objectives = slo.get("objectives") or []
    if objectives:
        lines.append(colors.paint(
            f"  {'objective':<24}{'state':<7}{'attain':>8}{'burn 5m':>9}"
            f"{'burn 1h':>9}{'budget':>8}{'p99 ms':>9}{'reqs':>7}", DIM))
        for o in objectives:
            short = (o.get("windows") or {}).get("short") or {}
            state = str(o.get("state", "?"))
            # Pad on the PLAIN text, colorize after — ANSI codes have
            # nonzero len() and would wreck the column math.
            state_cell = colors.paint(
                state.upper(), FG.get(state, ""), BOLD
            ) + " " * max(0, 7 - len(state))
            lines.append(
                f"  {str(o.get('objective'))[:23]:<24}"
                f"{state_cell}"
                f"{fmt_pct(o.get('attainment'), 2):>8}"
                f"{fmt_num(o.get('burn_rate_short')):>9}"
                f"{fmt_num(o.get('burn_rate_long')):>9}"
                f"{fmt_pct(o.get('error_budget_remaining'), 0):>8}"
                f"{fmt_num(short.get('p99_ms'), 1):>9}"
                f"{short.get('requests', 0):>7}"
            )
    else:
        lines.append(colors.paint("  (no objectives configured)", DIM))
    lines.append("")

    if trends and any(trends.values()):
        # Sparkline columns off the controller's time-series ring (ISSUE 9):
        # history exists from frame one, no client-side delta bookkeeping.
        lines.append(colors.paint("Trends", BOLD))
        rows = (
            ("tasks/s", trends.get("tasks_per_sec"), 1, ""),
            ("rows/s", trends.get("rows_per_sec"), 0, ""),
            ("queue", trends.get("queue_depth"), 0, ""),
            ("duty", trends.get("duty_cycle"), 2, "x"),
        )
        for label, points, digits, unit in rows:
            if not points:
                continue
            vals = [v for _t, v in points]
            lines.append(
                f"  {label:<9}{spark(vals)}  "
                f"{fmt_num(vals[-1], digits)}{unit}"
            )
        lines.append("")

    if serving is not None:
        # Serving row (ISSUE 15/16): the /v1/infer front door at a glance
        # — request states, TTFT p99, tok/s, running-batch occupancy,
        # prefix-cache hit rate, paged-KV pool fill.
        reqs = serving.get("requests") or {}
        req_s = " ".join(
            f"{k}={v}" for k, v in sorted(reqs.items())
        ) or "-"
        tok_rate = last_value((trends or {}).get("serve_tok_per_sec"))
        lines.append(
            f"{colors.paint('Serving', BOLD)}"
            f"  ttft p99: {fmt_num(serving.get('ttft_p99_ms'), 1)}ms"
            f"  tok/s: {fmt_num(tok_rate, 1)}"
            f"  occupancy: {bar((serving.get('occupancy') or 0) / 16.0, 8)}"
            f" {fmt_num(serving.get('occupancy'), 0)}"
            f"  waiting: {serving.get('bucketed', 0)}"
            f"  batches in flight: {serving.get('in_flight', 0)}"
            f"  429s: {serving.get('rejected', 0)}"
        )
        hit_rate = serving.get("prefix_hit_rate")
        kv_total = serving.get("kv_blocks_total")
        kv_free = serving.get("kv_blocks_free")
        if hit_rate is not None or kv_total:
            used = (
                (kv_total - (kv_free or 0)) if kv_total else None
            )
            kv_s = (
                f"{bar(used / kv_total if kv_total else 0.0, 8)} "
                f"{fmt_num(used, 0)}/{fmt_num(kv_total, 0)} blocks"
                if kv_total else "-"
            )
            lines.append(
                f"  prefix cache: "
                f"{fmt_num((hit_rate or 0.0) * 100.0, 1)}% hit"
                f"  kv pool: {kv_s}"
            )
        lines.append(colors.paint(f"  requests: {req_s}", DIM))
        if req_tail:
            # Per-request tail (ISSUE 17): the slowest/error records the
            # wide-event log kept, each blamed on its dominant TTFT
            # component — request-level "why", not another aggregate.
            lines.append(colors.paint(
                f"  {'slow requests':<18}{'op':<11}{'outcome':<11}"
                f"{'ttft ms':>9}  {'dominant component':<22}", DIM))
            for rec in req_tail:
                dom = rec.get("dominant_component") or "-"
                dom_ms = rec.get("dominant_ms")
                dom_s = (
                    f"{dom} ({fmt_num(dom_ms, 1)}ms)"
                    if dom_ms is not None else dom
                )
                line = (
                    f"  {str(rec.get('req_id'))[:17]:<18}"
                    f"{str(rec.get('op'))[:10]:<11}"
                    f"{str(rec.get('outcome'))[:10]:<11}"
                    f"{fmt_num(rec.get('ttft_ms'), 1):>9}  "
                    f"{dom_s:<22}"
                )
                if rec.get("outcome") != "completed":
                    line = colors.paint(line, FG["warn"])
                lines.append(line)
        lines.append("")

    if workflows is not None:
        # Workflows panel (ISSUE 19): active DAGs with stage progress and
        # the critical-path stage the scheduler is preferring, plus the
        # content-addressed result cache's dedupe numbers.
        wfs = workflows.get("workflows") or []
        active = [w for w in wfs if w.get("state") == "running"]
        cache = workflows.get("result_cache")
        head = (
            f"{colors.paint('Workflows', BOLD)}  active {len(active)}"
            f"  total {len(wfs)}"
        )
        if cache:
            head += (
                f"  cache: {fmt_pct(cache.get('hit_rate'), 1)} hit"
                f"  {fmt_num(cache.get('entries'), 0)}/"
                f"{fmt_num(cache.get('capacity'), 0)} entries"
                f"  model {cache.get('model_version')}"
            )
        lines.append(head)
        dedupe = workflows.get("dedupe_by_tenant") or {}
        if dedupe:
            lines.append(colors.paint(
                "  dedupe: " + " ".join(
                    f"{t}={fmt_pct(r, 1)}"
                    for t, r in sorted(dedupe.items())
                ), DIM))
        shown = active[:5] if active else wfs[-3:]
        if shown:
            lines.append(colors.paint(
                f"  {'workflow':<22}{'tenant':<10}{'state':<11}"
                f"{'progress':<16}{'jobs':>9}{'hits':>6}"
                f"  {'critical stage':<14}", DIM))
            for w in shown:
                total = w.get("total_jobs") or 0
                done = w.get("terminal_jobs") or 0
                frac = done / total if total else 0.0
                state = str(w.get("state", "?"))
                state_cell = colors.paint(
                    state.upper(),
                    FG.get("page" if state == "dead" else "ok", ""),
                ) + " " * max(0, 11 - len(state))
                line = (
                    f"  {str(w.get('workflow_id'))[:21]:<22}"
                    f"{str(w.get('tenant'))[:9]:<10}"
                    f"{state_cell}"
                    f"{bar(frac, 10)} {fmt_pct(frac, 0):>4} "
                    f"{done:>4}/{total:<4}"
                    f"{w.get('cache_hits', 0):>6}"
                    f"  {str(w.get('critical_stage') or '-')[:13]:<14}"
                )
                if w.get("failed_jobs"):
                    line = colors.paint(line, FG["warn"])
                lines.append(line)
        lines.append("")

    q = health.get("queue", {})
    tiers = ", ".join(
        f"t{k}:{v}" for k, v in sorted(
            (q.get("by_tier") or {}).items(), key=lambda kv: -int(kv[0])
        )
    ) or "-"
    starv = q.get("starvation_age_sec")
    lines.append(
        f"{colors.paint('Queue', BOLD)}  depth {q.get('depth', 0)}"
        f"  by tier: {tiers}"
        f"  oldest wait: {fmt_num(starv, 1)}s"
    )
    counts = health.get("counts") or {}
    if counts:
        lines.append(colors.paint(
            "  jobs: " + " ".join(
                f"{k}={v}" for k, v in sorted(counts.items())
            ), DIM))
    lines.append("")

    if partitions:
        # Partitioned control plane (ISSUE 18): one row per controller
        # partition behind the router — reachability, its own queue and
        # journal — beside the fleet merge above, so a killed partition
        # reads as one red row, not a mystery dip in the fleet line.
        lines.append(colors.paint(f"Partitions ({len(partitions)})", BOLD))
        lines.append(colors.paint(
            f"  {'partition':<12}{'state':<7}{'queue':>7}{'done':>7}"
            f"{'pending':>9}{'segs':>6}{'journal':>10}{'snap age':>10}",
            DIM))
        for p in partitions:
            label = "down" if not p.get("ok") else str(
                p.get("verdict", "?"))
            state_cell = colors.paint(
                label.upper(), FG.get(p.get("verdict"), ""), BOLD
            ) + " " * max(0, 7 - len(label))
            jb = p.get("journal_bytes")
            jb_s = (f"{jb / 1024:.0f}KB"
                    if isinstance(jb, (int, float)) else "-")
            lines.append(
                f"  {str(p.get('name'))[:11]:<12}"
                f"{state_cell}"
                f"{fmt_num(p.get('queue_depth'), 0):>7}"
                f"{p.get('succeeded', 0):>7}"
                f"{p.get('pending', 0):>9}"
                f"{fmt_num(p.get('journal_segments'), 0):>6}"
                f"{jb_s:>10}"
                f"{fmt_num(p.get('snapshot_age_sec'), 1):>10}"
            )
        router = (status or {}).get("router") or {}
        router_s = " ".join(
            f"{k}={v}" for k, v in sorted(router.items())
            if isinstance(v, (int, float))
        )
        if router_s:
            lines.append(colors.paint(f"  router: {router_s}", DIM))
        lines.append("")

    fleet = health.get("fleet", {})
    lines.append(colors.paint(
        f"Agents ({fleet.get('n_agents', 0)} seen, "
        f"{fleet.get('n_stale', 0)} stale)", BOLD))
    agents = health.get("agents") or {}
    if agents:
        lines.append(colors.paint(
            f"  {'agent':<20}{'seen':>7}{'duty':>18}{'mfu':>16}"
            f"{'staged':>8}{'busy s':>9}", DIM))
        for name, row in agents.items():
            mfu = row.get("mfu") or {}
            mfu_s = ",".join(
                f"{op.replace('map_', '')[:8]}:{fmt_pct(v, 1)}"
                for op, v in sorted(mfu.items())
            ) or "-"
            duty = row.get("duty_cycle")
            seen = f"{row.get('last_seen_sec_ago', 0):.0f}s"
            line = (
                f"  {name[:19]:<20}{seen:>7}"
                f"{bar(duty):>12} {fmt_pct(duty, 0):>5}"
                f"{mfu_s:>16}"
                f"{fmt_num(row.get('queue_depth'), 0):>8}"
                f"{fmt_num(row.get('device_busy_s'), 1):>9}"
            )
            if row.get("stale"):
                line = colors.paint(line, FG["warn"])
            lines.append(line)
    else:
        lines.append(colors.paint("  (no agent has leased yet)", DIM))

    summary = (status or {}).get("summary") or {}
    phases = summary.get("task_phase_seconds") or {}
    if phases:
        lines.append("")
        lines.append(colors.paint("Phase p99 (ms, fleet)", BOLD))
        for op, per in sorted(phases.items()):
            cells = "  ".join(
                f"{ph}:{(st.get('p99') or 0) * 1e3:.1f}"
                for ph, st in sorted(per.items())
            )
            lines.append(f"  {op:<20} {cells}")
    return "\n".join(lines) + "\n"


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=os.environ.get(
        "CONTROLLER_URL", "http://127.0.0.1:8080"))
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (CI / cron)")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON document "
                         "(health + status + usage + trend series) and "
                         "exit — the scripting mode")
    ap.add_argument("--no-color", action="store_true")
    ap.add_argument("--since", default="",
                    help="trend window from durable history, e.g. 10m / "
                         "2h / 600 (seconds) — reads the on-disk TSDB "
                         "through ?since=/?step= instead of the live ring")
    args = ap.parse_args()
    base = args.url.rstrip("/")
    since = parse_since(args.since)
    if args.since and since is None:
        print(f"swarmtop: bad --since {args.since!r}", file=sys.stderr)
        return 2
    colors = Colors(
        enabled=not args.no_color and not args.json
        and (sys.stdout.isatty() or os.environ.get("FORCE_COLOR"))
    )

    prev_tasks = None
    prev_t = None
    while True:
        health = fetch_json(base + "/v1/health")
        if health is None:
            print(f"swarmtop: controller unreachable at {base}",
                  file=sys.stderr)
            if args.once or args.json:
                return 2
            time.sleep(args.interval)
            continue
        status = fetch_json(base + "/v1/status")
        trends = collect_trends(base, since=since)
        metrics_text = fetch_text(base + "/v1/metrics")
        serving = serving_summary(metrics_text, status)
        req_tail = request_tail(base) if serving is not None else None
        partitions = partition_rows(status, health)
        workflows = workflow_rows(base)
        incidents = incident_summary(base)
        if args.json:
            # One-shot scripting mode (ISSUE 9 satellite): everything the
            # dashboard renders, as one JSON doc on stdout.
            doc = {
                "generated_at": time.time(),
                "url": base,
                "health": health,
                "status": status,
                "usage": fetch_json(base + "/v1/usage"),
                "trends": trends,
                "serving": serving,
                "request_tail": req_tail,
                "partitions": partitions,
                "workflows": workflows,
                "incidents": incidents,
                "rates": {
                    "tasks_per_sec": last_value(trends["tasks_per_sec"]),
                    "rows_per_sec": last_value(trends["rows_per_sec"]),
                    "serve_tok_per_sec": last_value(
                        trends["serve_tok_per_sec"]
                    ),
                },
            }
            json.dump(doc, sys.stdout, sort_keys=True)
            sys.stdout.write("\n")
            return 0
        # Rate from the controller's ring; scrape-delta only as the
        # fallback against pre-ring controllers.
        rate = last_value(trends.get("tasks_per_sec"))
        if rate is None:
            total = tasks_total(metrics_text)
            now = time.monotonic()
            if prev_tasks is not None and now > prev_t:
                rate = max(0.0, (total - prev_tasks) / (now - prev_t))
            prev_tasks, prev_t = total, now
        frame = render(health, status, rate, colors, trends=trends,
                       serving=serving, req_tail=req_tail,
                       partitions=partitions, workflows=workflows,
                       incidents=incidents)
        if args.once:
            sys.stdout.write(frame)
            return 0
        sys.stdout.write((CLEAR if colors.enabled else "") + frame)
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
