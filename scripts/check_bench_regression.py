#!/usr/bin/env python3
"""Bench trend guard: compare a bench run's flat fields against the best
prior ``BENCH_r*.json`` artifact (ISSUE 9 satellite).

Every bench round records flat trend fields (``e2e_drain_rows_per_sec``,
``bert_base_mfu``, ``classify_p50_batch_ms``, ...) precisely so regressions
would be visible — but nothing ever *compared* them, so a regression only
surfaced if a reviewer happened to diff two JSON artifacts by eye. This
script closes the loop:

- the CURRENT run is ``--current FILE`` (a ``BENCH_r*.json`` artifact or a
  raw ``bench.py`` stdout JSON line); default = the highest-numbered
  repo-root ``BENCH_r*.json`` with a parseable payload;
- the BASELINE per field is the best value any PRIOR artifact recorded
  (max for rates/ratios, min for latency/size fields) — one lucky round
  sets the bar, one noisy round cannot lower it;
- a field regresses when it falls outside the per-field tolerance
  (``--tolerance`` default 15%, wider for the known-noisy drain legs);
  regressions exit 1 with a readable diff, one line per field.

``--advisory`` reports but always exits 0 — the CI mode on CPU shapes,
where absolute numbers measure the runner, not the code (the ISSUE 9
acceptance bar: CI-wired, advisory on CPU). Run it strict on real TPU
hardware after a bench round.

``--enforce-fields f1,f2`` (ISSUE 10 satellite) promotes the named fields
to ENFORCING even under ``--advisory``: a regression in one of them exits 1
regardless. CI judges committed artifacts (not a fresh run), so enforcing
is deterministic — it fires only when a NEW BENCH_r* artifact lands in the
repo with a regressed field, which is exactly the review moment it should
block. Wired for the drain flat fields with multi-round history.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Fields where SMALLER is better; everything else numeric is a rate/ratio.
LOWER_BETTER = {
    "classify_p50_batch_ms",
    "wire_bytes_per_row",
    "controller_replay_compacted_sec",
    # Serving latencies (ISSUE 15/16) — the p99 tail is the product
    # problem (BENCH_r07: p50 27.7ms, p99 1231ms), so it's tracked AND
    # CI-enforced (ci.yml --enforce-fields).
    "serving_ttft_p50_ms",
    "serving_ttft_p99_ms",
    "serving_disagg_ttft_p99_ms",
    # Incident forensics (ISSUE 20): the bundle snapshot runs inline on
    # the sample path when an anomaly confirms — latency is the number.
    "incident_capture_ms",
}

# Fields that are identity/config, not performance — never judged.
SKIP_FIELDS = {
    "n_chips",
    "multichip_n_chips",
    "host_cores",     # host shape, not a perf number (ISSUE 16)
    "value",          # duplicate of the flagship flat field
    "vs_baseline",    # derived from `value`
    # Instrumentation self-check, not a perf number (ISSUE 17): bench
    # asserts it <= 0.10 itself; the sub-tolerance residue is noise.
    "serving_ttft_decomposition_max_err",
}

# Known-noisy legs get a wider default band (measured spreads: flagship
# 11.7%, long_ctx 14.0% at windows=3 — see bench.py's NOISY_WINDOWS note).
PER_FIELD_TOLERANCE = {
    "e2e_drain_rows_per_sec": 0.25,
    "drain_staged_rows_per_sec": 0.25,
    "multichip_rows_per_sec": 0.25,
    "multichip_scaling_efficiency": 0.25,
    "long_ctx_rows_per_sec": 0.25,
    "csv_index_mb_per_sec": 0.25,
    # Serving legs ride an open-loop arrival schedule + HTTP, the noisiest
    # combination the bench runs (ISSUE 15).
    "serving_ttft_p50_ms": 0.35,
    "serving_ttft_p99_ms": 0.35,
    "serving_tok_per_sec": 0.35,
    "serving_beam_tok_per_sec": 0.25,
    "serving_beam_speedup_vs_static": 0.25,
    # Disaggregated serving (ISSUE 16): same open-loop noise profile.
    "serving_disagg_tok_per_sec": 0.35,
    "serving_disagg_ttft_p99_ms": 0.35,
    "serving_disagg_vs_colocated": 0.25,
    # Partitioned control plane (ISSUE 18): 3 concurrent journaling
    # processes contend for disk + cores — wider band than the
    # single-process controller legs.
    "controller_agg_submits_per_sec": 0.25,
    "controller_agg_speedup_vs_single": 0.25,
    # Workflow DAG + result cache (ISSUE 19): the DAG leg is a drain leg
    # (same noise profile as the other rows/sec fields); the effective
    # speedup divides two drain rates, compounding their noise. The hit
    # rate itself is near-deterministic (zipfian seed is fixed), so it
    # keeps the default band.
    "dag_rows_per_sec": 0.25,
    "cache_effective_speedup": 0.25,
    # Durable telemetry (ISSUE 20): the overhead ratio divides two drain
    # rates (noise compounds); the capture latency is a sub-ms median on
    # a shared runner.
    "tsdb_overhead_ratio": 0.15,
    "incident_capture_ms": 0.50,
}


def bench_round(path: str) -> int:
    m = re.search(r"BENCH_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


def load_flat_fields(path: str) -> Optional[Dict[str, float]]:
    """Numeric top-level fields of one artifact. Handles both the driver
    wrapper shape (``{"parsed": {...}}``) and a raw bench stdout JSON;
    returns None when the payload is missing/unparseable (BENCH_r04/r05
    record ``parsed: null`` — a real state this must tolerate).

    Fields the artifact names in its own ``starved_fields`` list are
    dropped (ISSUE 16): a round run with fewer host cores than the leg
    needs records the starvation, not the code — those numbers must
    neither set baselines nor count as regressions (BENCH_r06's
    scaling_efficiency 0.187 was a 1-core container, not a 5× slowdown).
    """
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(doc, dict) and "parsed" in doc:
        doc = doc["parsed"]
    if not isinstance(doc, dict):
        return None
    starved = {
        s for s in (doc.get("starved_fields") or []) if isinstance(s, str)
    }
    out: Dict[str, float] = {}
    for key, value in doc.items():
        if key in SKIP_FIELDS or key in starved:
            continue
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        out[key] = float(value)
    return out or None


def best_prior(
    artifacts: List[Tuple[int, str, Dict[str, float]]], field: str
) -> Optional[Tuple[float, str]]:
    """(best value, source artifact) for one field across prior rounds."""
    best: Optional[Tuple[float, str]] = None
    pick = min if field in LOWER_BETTER else max
    for _, path, fields in artifacts:
        if field not in fields:
            continue
        v = fields[field]
        if best is None or pick(v, best[0]) == v:
            best = (v, os.path.basename(path))
    return best


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--current", default="",
                    help="artifact or raw bench-JSON to judge (default: "
                         "the newest parseable repo-root BENCH_r*.json)")
    ap.add_argument("--baseline-glob",
                    default=os.path.join(REPO, "BENCH_r*.json"))
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="default allowed fractional regression per field")
    ap.add_argument("--advisory", action="store_true",
                    help="report regressions but exit 0 (the CI mode on "
                         "CPU shapes)")
    ap.add_argument("--enforce-fields", default="",
                    help="comma-separated fields judged ENFORCING even "
                         "under --advisory (regressions there exit 1)")
    args = ap.parse_args(argv)
    enforced = {
        f.strip() for f in args.enforce_fields.split(",") if f.strip()
    }

    rounds = sorted(
        (bench_round(p), p, load_flat_fields(p))
        for p in glob.glob(args.baseline_glob)
    )
    parseable = [(n, p, f) for n, p, f in rounds if f is not None]
    if not parseable:
        print("check_bench_regression: no parseable BENCH_r*.json artifacts"
              " — nothing to judge")
        return 0

    if args.current:
        current_path = args.current
        current = load_flat_fields(current_path)
        priors = parseable
    else:
        n, current_path, current = parseable[-1]
        priors = [e for e in parseable if e[0] != n]
    if current is None:
        print(f"check_bench_regression: {current_path} has no parseable "
              "flat fields")
        return 0 if args.advisory else 1
    if not priors:
        print(f"check_bench_regression: {os.path.basename(current_path)} is "
              "the only parseable artifact — baseline established, "
              "nothing to compare")
        return 0

    regressions: List[str] = []
    enforced_regressions: List[str] = []
    improved = judged = 0
    for field in sorted(current):
        base = best_prior(priors, field)
        if base is None:
            continue  # new field this round — becomes the baseline
        judged += 1
        baseline, source = base
        now = current[field]
        tol = PER_FIELD_TOLERANCE.get(field, args.tolerance)
        if field in LOWER_BETTER:
            bad = baseline > 0 and now > baseline * (1.0 + tol)
            delta = (now - baseline) / baseline if baseline else 0.0
        else:
            bad = baseline > 0 and now < baseline * (1.0 - tol)
            delta = (now - baseline) / baseline if baseline else 0.0
        if bad:
            tag = " [ENFORCED]" if field in enforced else ""
            line = (
                f"  {field}: {now:g} vs best {baseline:g} ({source}) "
                f"— {delta:+.1%}, tolerance ±{tol:.0%}{tag}"
            )
            regressions.append(line)
            if field in enforced:
                enforced_regressions.append(line)
        elif (delta > 0) != (field in LOWER_BETTER):
            improved += 1

    label = os.path.basename(current_path)
    if regressions:
        print(f"check_bench_regression: {len(regressions)} regression(s) "
              f"in {label} vs best of {len(priors)} prior artifact(s):")
        for line in regressions:
            print(line)
        if args.advisory:
            if enforced_regressions:
                print(
                    f"{len(enforced_regressions)} regression(s) in "
                    "ENFORCED fields: exit 1 despite advisory mode"
                )
                return 1
            print("ADVISORY mode: exit 0 (CPU-shape numbers measure the "
                  "runner, not the code)")
            return 0
        return 1
    print(
        f"check_bench_regression: OK — {label}: {judged} field(s) judged, "
        f"{improved} improved, 0 regressed "
        f"(vs best of {len(priors)} prior artifact(s))"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
