#!/usr/bin/env python3
"""CI guard against citation drift: docs/source citing artifacts that do
not exist.

Two rounds of review flagged the same class of rot (VERDICT r4/r5): prose
in ``models/quant.py`` / ``PARITY.md`` citing ``scripts/*.py`` measurement
drivers that were never committed, and README/docstrings quoting bench
ratios attributed to ``BENCH_r*`` artifacts that don't match any recorded
file. This script makes that drift a CI failure instead of a reviewer
finding:

- every ``scripts/<name>.py`` citation must name a file that exists under
  ``scripts/``;
- every ``BENCH_r<NN>`` artifact key must have a recorded
  ``BENCH_r<NN>.json`` at the repo root.

Reviewer/driver artifacts (VERDICT.md, ADVICE.md, ISSUE.md, CHANGES.md)
are excluded: they legitimately cite missing things (that is their job —
e.g. "``scripts/foo.py`` does not exist") and name future artifacts
("Done = BENCH_r06 has ...").

Run from anywhere: paths resolve relative to the repo root (this file's
parent's parent). Exit 0 = clean, 1 = stale citations (listed one per
line as ``path:lineno: message``).
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Files whose JOB is to cite missing/future artifacts.
EXCLUDE_FILES = {"VERDICT.md", "ADVICE.md", "ISSUE.md", "CHANGES.md"}
EXCLUDE_DIRS = {".git", ".hypothesis", "__pycache__", ".pytest_cache",
                "node_modules", ".venv"}

SCRIPT_RE = re.compile(r"scripts/([A-Za-z0-9_\-]+\.py)")
BENCH_RE = re.compile(r"\bBENCH_r(\d+)\b")


def _scan_file(path: str) -> list:
    problems = []
    rel = os.path.relpath(path, REPO)
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            lines = f.readlines()
    except OSError as exc:
        return [f"{rel}:0: unreadable ({exc})"]
    for lineno, line in enumerate(lines, 1):
        for m in SCRIPT_RE.finditer(line):
            target = os.path.join(REPO, "scripts", m.group(1))
            if not os.path.exists(target):
                problems.append(
                    f"{rel}:{lineno}: cites scripts/{m.group(1)} "
                    "which does not exist"
                )
        for m in BENCH_RE.finditer(line):
            artifact = f"BENCH_r{m.group(1)}.json"
            if not os.path.exists(os.path.join(REPO, artifact)):
                problems.append(
                    f"{rel}:{lineno}: cites {m.group(0)} but {artifact} "
                    "is not recorded in the repo"
                )
    return problems


def main() -> int:
    self_path = os.path.abspath(__file__)
    problems = []
    n_scanned = 0
    for dirpath, dirnames, filenames in os.walk(REPO):
        dirnames[:] = [d for d in dirnames if d not in EXCLUDE_DIRS]
        for name in sorted(filenames):
            if not name.endswith((".py", ".md")):
                continue
            if name in EXCLUDE_FILES:
                continue
            path = os.path.join(dirpath, name)
            if os.path.abspath(path) == self_path:
                continue
            n_scanned += 1
            problems.extend(_scan_file(path))
    if problems:
        print(f"check_doc_claims: {len(problems)} stale citation(s) "
              f"in {n_scanned} files:")
        for p in problems:
            print(f"  {p}")
        return 1
    print(f"check_doc_claims: OK ({n_scanned} files scanned)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
