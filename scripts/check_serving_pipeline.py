#!/usr/bin/env python3
"""CI smoke for the online-serving front door (ISSUE 15).

Drives interactive ``POST /v1/infer`` requests against a REAL HTTP
controller + a real pipelined agent WHILE a bulk classify drain runs
through the same agent, and asserts the serving acceptance bar:

1. every interactive request completes (classify + summarize, greedy and
   beam, mixed per-request ``max_length`` budgets);
2. TTFT stays under a generous CI bound (the compile cost is paid by a
   warmup request, so the bound judges queueing+decode, not tracing);
3. iteration-level batching actually batched: some serving batch reports
   running-batch occupancy > 1 (several requests seated at once);
4. the bulk drain's results are BIT-IDENTICAL to a serving-off reference
   drain of the same job — interactive traffic must not perturb batch
   results;
5. the SLO engine judged the serving stream: the default
   ``interactive_ttft`` objective (metric: ttft) saw every completed
   request;
6. (ISSUE 16) the DISAGGREGATED leg: with ``SERVE_DISAGG`` splitting every
   summarize into a serve_prefill → serve_decode chain executed by two
   SEPARATE in-process agents (one advertising only prefill, one only
   decode + bulk), the summaries are bit-identical to a colocated run of
   the same texts, and TTFT holds the same bound while a bulk drain runs
   alongside on the decode agent.

CPU-shape smoke (tiny models, JAX_PLATFORMS=cpu): wall target well under a
minute of drain work. Exit 0 = all bars met.
"""

from __future__ import annotations

import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

TINY_S2S = {
    "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
    "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
}
TINY_CLS = {
    "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
    "max_len": 64, "dtype": "float32", "n_classes": 16,
}
BULK_ROWS = 1024
BULK_SHARD = 128
N_INFER = 24
TTFT_BOUND_MS = 30_000.0   # generous: 1-core CI containers stall freely
DISAGG_N = 10              # prefix-heavy: 10 requests over 3 shared docs


def write_csv(path: str, rows: int) -> None:
    with open(path, "w") as f:
        f.write("id,text\n")
        for i in range(rows):
            f.write(f'{i},"serving smoke record {i} with a payload"\n')


def bulk_results(controller, shard_ids):
    out = {}
    for jid in shard_ids:
        snap = controller.job_snapshot(jid)
        assert snap["state"] == "succeeded", (jid, snap["state"],
                                              snap["error"])
        r = snap["result"]
        assert isinstance(r, dict) and r.get("ok") is True, (jid, r)
        out[controller.job(jid).payload["start_row"]] = (
            r["indices"], r["scores"],
        )
    return out


def drain_reference(csv_path):
    """Serving-off reference drain: same bulk job, no interactive load."""
    import requests

    from agent_tpu.agent.app import Agent
    from agent_tpu.agent.pipeline import PipelineRunner
    from agent_tpu.config import AgentConfig, Config, ServeConfig
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer

    controller = Controller(
        lease_ttl_sec=600.0, serve=ServeConfig(enabled=False),
    )
    server = ControllerServer(controller).start()
    try:
        cfg = Config(agent=AgentConfig(
            controller_url=server.url, agent_name="smoke-ref",
            tasks=("map_classify_tpu",), idle_sleep_sec=0.0,
        ))
        agent = Agent(config=cfg, session=requests.Session())
        agent._profile = {"tier": "smoke"}
        runner = PipelineRunner(agent, depth=2)
        t = threading.Thread(target=runner.run, daemon=True)
        t.start()
        shard_ids, _ = controller.submit_csv_job(
            csv_path, total_rows=BULK_ROWS, shard_size=BULK_SHARD,
            map_op="map_classify_tpu",
            extra_payload={"text_field": "text", "allow_fallback": False,
                           "result_format": "columnar",
                           "model_config": TINY_CLS},
        )
        deadline = time.monotonic() + 600
        while not controller.drained():
            assert time.monotonic() < deadline, controller.counts()
            time.sleep(0.02)
        agent.running = False
        t.join(timeout=60)
        return bulk_results(controller, shard_ids)
    finally:
        server.stop()


def disagg_leg(csv_path) -> str:
    """Bar 6 (ISSUE 16): prefill and decode on separate agents, outputs
    bit-identical to the colocated path, TTFT bound held under bulk load.

    Two stacks over the same texts: colocated (one agent advertising
    ``serve_summarize``) and disaggregated (an agent advertising ONLY
    ``serve_prefill`` plus an agent advertising ``serve_decode`` and the
    bulk op — the KV handoff really crosses an agent boundary, with the
    controller's dependency gating as the inter-stage queue). The engine
    store is reset between stacks so both start cold."""
    import requests

    from agent_tpu.agent.app import Agent
    from agent_tpu.agent.pipeline import PipelineRunner
    from agent_tpu.config import AgentConfig, Config, ServeConfig
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer
    from agent_tpu.ops.serve_infer import reset_engines

    texts = [
        f"disagg shared context document {i % 3} "
        + "with a common preamble clause " * 4
        for i in range(DISAGG_N)
    ]
    params = {"model_config": TINY_S2S, "max_length": 6}

    def run_stack(disagg, agent_specs, with_bulk):
        reset_engines()
        controller = Controller(
            lease_ttl_sec=600.0,
            serve=ServeConfig(max_wait_ms=10.0, max_batch=4,
                              disaggregated=disagg),
        )
        server = ControllerServer(controller).start()
        agents, threads = [], []
        try:
            for name, tasks in agent_specs:
                cfg = Config(agent=AgentConfig(
                    controller_url=server.url, agent_name=name,
                    tasks=tasks, idle_sleep_sec=0.0,
                ))
                a = Agent(config=cfg, session=requests.Session())
                a._profile = {"tier": "smoke"}
                runner = PipelineRunner(a, depth=2)
                th = threading.Thread(target=runner.run, daemon=True)
                th.start()
                agents.append(a)
                threads.append(th)
            sess = requests.Session()
            r = sess.post(server.url + "/v1/infer", json={
                "op": "summarize", "text": "warm the serving path",
                "params": params,
            }, timeout=600)
            assert r.status_code == 200, r.text
            assert r.json()["state"] == "done", r.json()

            shard_ids = None
            if with_bulk:
                shard_ids, _ = controller.submit_csv_job(
                    csv_path, total_rows=BULK_ROWS, shard_size=BULK_SHARD,
                    map_op="map_classify_tpu",
                    extra_payload={"text_field": "text",
                                   "allow_fallback": False,
                                   "result_format": "columnar",
                                   "model_config": TINY_CLS},
                )
            rids = []
            for text in texts:
                r = sess.post(server.url + "/v1/infer", json={
                    "op": "summarize", "text": text, "wait": False,
                    "params": params,
                }, timeout=30)
                assert r.status_code == 200, r.text
                rids.append(r.json()["req_id"])
            snaps = [controller.wait_infer(rid, 300.0) for rid in rids]
            for snap in snaps:
                assert snap is not None and snap["state"] == "done", snap
            ttfts = [s["ttft_ms"] for s in snaps
                     if s.get("ttft_ms") is not None]
            assert ttfts and max(ttfts) < TTFT_BOUND_MS, (
                f"disagg TTFT bound breached: max {max(ttfts)}ms"
            )
            if with_bulk:
                deadline = time.monotonic() + 600
                while not controller.drained():
                    assert time.monotonic() < deadline, controller.counts()
                    time.sleep(0.02)
                bulk_results(controller, shard_ids)  # all shards succeeded
            if disagg:
                ops = {
                    controller.job(jid).op for jid in controller.results()
                }
                assert {"serve_prefill", "serve_decode"} <= ops, (
                    f"disagg chain did not split: ops {sorted(ops)}"
                )
                hits = controller._m_serve_prefix.value(event="hits")
                assert hits > 0, "shared-prefix mix produced no cache hits"
            for a in agents:
                a.running = False
            for th in threads:
                th.join(timeout=60)
            return [s["result"]["summary"] for s in snaps], ttfts
        finally:
            for a in agents:
                a.running = False
            server.stop()

    print("[serving-smoke] disaggregated leg: colocated reference ...",
          flush=True)
    colo, _ = run_stack(
        False, [("smoke-colo", ("serve_summarize",))], with_bulk=False,
    )
    print("[serving-smoke] disaggregated leg: split agents + bulk ...",
          flush=True)
    dis, ttfts = run_stack(
        True,
        [("smoke-prefill", ("serve_prefill",)),
         ("smoke-decode", ("serve_decode", "map_classify_tpu"))],
        with_bulk=True,
    )
    assert dis == colo, (
        "disaggregated summaries diverged from the colocated path"
    )
    return (
        f"disagg {len(dis)} reqs bit-identical across split agents "
        f"(ttft max {max(ttfts):.0f}ms under bulk)"
    )


def main() -> int:
    import requests

    from agent_tpu.agent.app import Agent
    from agent_tpu.agent.pipeline import PipelineRunner
    from agent_tpu.config import AgentConfig, Config, ServeConfig
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer
    from agent_tpu.loadgen import (
        ArrivalPattern,
        LoadGen,
        TrafficClass,
        session_submitter,
    )

    t_start = time.monotonic()
    with tempfile.TemporaryDirectory() as td:
        csv_path = os.path.join(td, "bulk.csv")
        write_csv(csv_path, BULK_ROWS)
        print("[serving-smoke] serving-off reference drain ...", flush=True)
        reference = drain_reference(csv_path)

        controller = Controller(
            lease_ttl_sec=600.0,
            serve=ServeConfig(max_wait_ms=15.0, max_batch=6),
        )
        server = ControllerServer(controller).start()
        try:
            cfg = Config(agent=AgentConfig(
                controller_url=server.url, agent_name="smoke-serving",
                tasks=("serve_classify", "serve_summarize",
                       "map_classify_tpu"),
                idle_sleep_sec=0.0,
            ))
            agent = Agent(config=cfg, session=requests.Session())
            agent._profile = {"tier": "smoke"}
            runner = PipelineRunner(agent, depth=2)
            t = threading.Thread(target=runner.run, daemon=True)
            t.start()

            # Warm the serving + bulk executables (compile cost must not
            # count against the TTFT bound — production pays it at boot).
            sess = requests.Session()
            for op, params in (
                ("classify", {"model_config": TINY_CLS, "topk": 2}),
                ("summarize", {"model_config": TINY_S2S, "max_length": 4}),
                ("summarize", {"model_config": TINY_S2S, "max_length": 4,
                               "num_beams": 2}),
            ):
                r = sess.post(server.url + "/v1/infer", json={
                    "op": op, "text": "warm the serving path", "params": params,
                }, timeout=600)
                assert r.status_code == 200, r.text
                assert r.json()["state"] == "done", r.json()

            # Interactive load (one shared traffic driver with elastic_soak:
            # loadgen's infer route) over a running bulk drain.
            print("[serving-smoke] bulk drain + interactive load ...",
                  flush=True)
            shard_ids, _ = controller.submit_csv_job(
                csv_path, total_rows=BULK_ROWS, shard_size=BULK_SHARD,
                map_op="map_classify_tpu",
                extra_payload={"text_field": "text", "allow_fallback": False,
                               "result_format": "columnar",
                               "model_config": TINY_CLS},
            )
            classes = [
                TrafficClass(
                    name="classify", op="classify", weight=1.0,
                    route="infer",
                    payload_fn=lambda rng, seq: {
                        "text": f"interactive classify {seq}",
                        "params": {"model_config": TINY_CLS, "topk": 2},
                    },
                ),
                TrafficClass(
                    name="summarize", op="summarize", weight=2.0,
                    route="infer",
                    payload_fn=lambda rng, seq: {
                        "text": f"interactive summarize {seq} "
                                + "payload " * (seq % 3 + 1),
                        "params": {
                            "model_config": TINY_S2S,
                            "max_length": 3 + seq % 6,
                            **({"num_beams": 2} if seq % 3 == 0 else {}),
                        },
                    },
                ),
            ]
            gen = LoadGen(classes, ArrivalPattern(6.0), seed=7)
            stats = gen.run(session_submitter(sess, server.url),
                            max(4.0, N_INFER / 6.0))
            req_ids = stats.job_ids()
            assert len(req_ids) >= N_INFER // 2, (
                f"loadgen submitted only {len(req_ids)} requests"
            )

            # A concurrent volley on top of the open-loop trickle: 8
            # same-bucket summarize requests posted together, so the
            # coalescer and the decode engine demonstrably share the batch
            # (bar 3 needs overlap, which a trickle of fast tiny decodes
            # rarely produces by luck).
            volley_ids = []
            for i in range(8):
                r = sess.post(server.url + "/v1/infer", json={
                    "op": "summarize", "text": f"volley request {i}",
                    "wait": False,
                    "params": {"model_config": TINY_S2S,
                               "max_length": 4 + i},
                }, timeout=30)
                assert r.status_code == 200, r.text
                volley_ids.append(r.json()["req_id"])
            req_ids.extend(volley_ids)

            # Bar 1+2: every request completes, TTFT under the CI bound.
            snaps = []
            for rid in req_ids:
                snap = controller.wait_infer(rid, 300.0)
                assert snap is not None and snap["state"] == "done", snap
                snaps.append(snap)
            ttfts = [s["ttft_ms"] for s in snaps
                     if s.get("ttft_ms") is not None]
            assert ttfts and max(ttfts) < TTFT_BOUND_MS, (
                f"TTFT bound breached: max {max(ttfts)}ms"
            )

            deadline = time.monotonic() + 600
            while not controller.drained():
                assert time.monotonic() < deadline, controller.counts()
                time.sleep(0.02)

            # Bar 3: batching actually batched — some serving batch held
            # more than one request in the running batch / forward.
            max_occ = 0
            for jid in controller.results():
                if not jid.startswith("serve-"):
                    continue
                r = controller.job(jid).result
                if isinstance(r, dict):
                    max_occ = max(max_occ, int(r.get("max_occupancy") or 0))
            assert max_occ > 1, (
                f"no serving batch ever held >1 request (max {max_occ})"
            )

            # Bar 4: bulk results bit-identical to the serving-off drain.
            got = bulk_results(controller, shard_ids)
            assert got == reference, (
                "bulk drain results diverged with serving traffic on"
            )

            # Bar 5: the interactive_ttft objective saw the stream.
            results = {r["objective"]: r for r in controller.slo.evaluate()}
            seen = results["interactive_ttft"]["windows"]["long"]["requests"]
            assert seen >= len(snaps), (
                f"TTFT objective saw {seen} < {len(snaps)} requests"
            )

            agent.running = False
            t.join(timeout=60)
        finally:
            server.stop()

        # Bar 6 (ISSUE 16): the disaggregated prefill/decode leg.
        disagg_line = disagg_leg(csv_path)
    print(
        f"[serving-smoke] OK: {len(snaps)} interactive requests "
        f"(ttft p50 {sorted(ttfts)[len(ttfts) // 2]:.0f}ms, "
        f"max {max(ttfts):.0f}ms), max occupancy {max_occ}, "
        f"bulk bit-identical over {len(reference)} shards, "
        f"{disagg_line}, "
        f"wall {time.monotonic() - t_start:.1f}s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
