#!/usr/bin/env python3
"""Fleet launcher CLI (ISSUE 7): N device-pinned agent processes on this
host, all leasing from one controller.

    # 4 single-chip agents against a running controller
    python scripts/fleet.py --agents 4 --controller http://ctrl:8080 \
        --tasks map_classify_tpu,map_summarize --platform tpu

    # CI/virtual shape: 2 agents x 2 forced host devices each
    python scripts/fleet.py --agents 2 --devices-per-agent 2 \
        --controller http://127.0.0.1:8080

Each member is pinned to a disjoint device slice (``CHIP_SLICE``; plus
``TPU_VISIBLE_DEVICES`` on hardware — see ``agent_tpu/agent/fleet.py``) and
optionally pre-warms its executables from ``--warm-file`` before the first
lease. The launcher waits for every member's first controller poll, then
blocks until SIGINT/SIGTERM, which it forwards for a graceful drain.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_TASKS = "map_classify_tpu,map_summarize"


def _http_agents(controller_url: str):
    """``agents_summary`` via GET /v1/status (the launcher has no in-process
    controller)."""
    url = controller_url.rstrip("/") + "/v1/status"
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return json.load(resp).get("agents") or {}
    except Exception:  # noqa: BLE001 — not up yet
        return {}


def main() -> int:
    from agent_tpu.agent import fleet

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--agents", type=int, default=2)
    ap.add_argument("--devices-per-agent", type=int, default=1)
    ap.add_argument("--controller", required=True,
                    help="controller base URL (http://host:port)")
    ap.add_argument("--tasks", default=DEFAULT_TASKS)
    ap.add_argument("--platform", choices=("cpu", "tpu"), default="cpu",
                    help="cpu = forced-host virtual devices (CI shape); "
                         "tpu = hardware chip pinning")
    ap.add_argument("--mesh-shape", default="",
                    help='per-member MESH_SHAPE, e.g. "dp=2"')
    ap.add_argument("--warm-file", default="",
                    help="JSON [{op, payload}] each member runs pre-lease")
    ap.add_argument("--log-dir", default="",
                    help="per-member log files (default: inherit stdout)")
    ap.add_argument("--name-prefix", default=fleet.DEFAULT_NAME_PREFIX)
    ap.add_argument("--ready-timeout", type=float, default=300.0)
    args = ap.parse_args()
    if args.agents < 1:
        print("--agents must be >= 1", flush=True)
        return 2

    handle = fleet.spawn_fleet(
        args.agents, args.devices_per_agent,
        controller_url=args.controller, tasks=args.tasks,
        platform=args.platform, name_prefix=args.name_prefix,
        mesh_shape=args.mesh_shape, warm_file=args.warm_file,
        log_dir=args.log_dir or None,
    )
    print(
        f"fleet up: {args.agents} agent(s) x {args.devices_per_agent} "
        f"device(s) ({args.platform}), members={handle.names}",
        flush=True,
    )
    ready = fleet.wait_for_agents(
        lambda: _http_agents(args.controller), handle.names,
        timeout=args.ready_timeout, fleet=handle,
    )
    if not ready:
        print("fleet NOT ready (timeout or member death) — stopping",
              flush=True)
        handle.stop()
        return 1
    print("fleet ready: every member polled the controller", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    while not stop.is_set():
        stop.wait(1.0)
        failures = handle.poll_failures()
        if failures:
            print(f"fleet member(s) died: exit codes {failures}", flush=True)
            handle.stop()
            return 1
    print("stopping fleet (graceful drain)", flush=True)
    handle.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
