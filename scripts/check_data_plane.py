#!/usr/bin/env python3
"""Data-plane smoke (ISSUE 6) — the CI gate for the staging pool and the
binary shard wire. Three checks, all in-process on the CPU backend:

1. **Parallel staging is bit-identical**: a multi-shard classify drain
   through the real ``PipelineRunner`` with ``STAGE_WORKERS=4`` + autotune
   + the double-buffered feed produces exactly the results of the
   single-worker reference drain (per-shard indices AND scores).
2. **Binary-wire negotiation, both directions**: a binary-capable agent
   against a JSON-only controller (``wire_binary=False``) and a JSON-only
   agent (``WIRE_BINARY=0`` semantics) against a binary controller both
   stay on plain JSON; the negotiated pair demonstrably carries the
   ``__bin__`` envelope on tasks and results, stores bit-identical decoded
   results, and shrinks task+result wire bytes/row by ≥ 3× vs JSON.
3. **Chaos composes**: ``chaos_soak.py --quick --pipeline`` (the soak's
   drains driven through the staging pool, ``STAGE_WORKERS=4``) is green.

Exit 0 = all clean; 1 = problems (listed one per line).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TINY = {
    "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
    "max_len": 64, "dtype": "float32", "n_classes": 16,
}
TINY_S2S = {
    "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
    "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
}
ROWS, SHARD = 192, 24


class CountingSession:
    """Loopback wrapper that measures what the JSON wire WOULD carry for
    the shard payloads themselves — ``len(json.dumps(...))`` of every
    posted ``result`` body and every leased task ``payload`` (the ISSUE 6
    acceptance bar is task+result bytes/row; lease metrics/span piggyback
    is control-plane traffic and identical in both modes)."""

    def __init__(self, inner: Any) -> None:
        self.inner = inner
        self.bytes_results = 0
        self.bytes_tasks = 0
        self.binary_results = 0
        self.binary_tasks = 0

    def post(self, url: str, json_body: Any = None, timeout: Any = None,
             **kw: Any):
        body = kw.pop("json", json_body)
        resp = self.inner.post(url, json=body, timeout=timeout)
        from agent_tpu.data import wire

        def nbytes(obj: Any) -> int:
            return len(json.dumps(obj, separators=(",", ":"), default=str))

        if url.endswith("/v1/results") and isinstance(body, dict):
            self.bytes_results += nbytes(body.get("result"))
            if wire.is_binary_result(body.get("result")):
                self.binary_results += 1
        elif url.endswith("/v1/leases") and resp.status_code == 200:
            for task in resp.json().get("tasks", []):
                self.bytes_tasks += nbytes(task.get("payload"))
                if wire.is_binary_payload(task.get("payload")):
                    self.binary_tasks += 1
        return resp


def build_csv(path: str, rows: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text\n")
        for i in range(rows):
            f.write(f'{i},"data plane smoke row {i} with a text payload"\n')


def drain_pipelined(controller, agent, workers, autotune, deadline_sec=120.0):
    from agent_tpu.agent.pipeline import PipelineRunner

    agent.post_session_factory = lambda: agent.session
    agent.running = True
    deadline = time.monotonic() + deadline_sec

    def watch():
        while not controller.drained() and time.monotonic() < deadline:
            time.sleep(0.02)
        agent.running = False

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    PipelineRunner(agent, depth=2, workers=workers, autotune=autotune).run()
    t.join(timeout=10)
    return controller.drained()


def make_agent(controller, name, tasks, wire_binary=True, session=None):
    from agent_tpu.agent.app import Agent
    from agent_tpu.chaos import LoopbackSession
    from agent_tpu.config import AgentConfig, Config

    cfg = Config(agent=AgentConfig(
        controller_url="http://loopback", agent_name=name, tasks=tasks,
        idle_sleep_sec=0.0, max_tasks=2, wire_binary=wire_binary,
    ))
    agent = Agent(
        config=cfg, session=session or LoopbackSession(controller)
    )
    agent._profile = {"tier": "smoke"}
    return agent


def check_parallel_staging() -> List[str]:
    """Multi-worker staged drain must be bit-identical to single-worker."""
    from agent_tpu.controller.core import Controller

    problems: List[str] = []
    extra = {"text_field": "text", "allow_fallback": False,
             "result_format": "columnar", "model_config": dict(TINY),
             "topk": 3}
    with tempfile.TemporaryDirectory(prefix="data_plane_") as tmp:
        csv = os.path.join(tmp, "rows.csv")
        build_csv(csv, ROWS)
        by_workers = {}
        for workers, autotune in ((1, False), (4, True)):
            controller = Controller()
            controller.submit_csv_job(
                csv, total_rows=ROWS, shard_size=SHARD,
                map_op="map_classify_tpu", extra_payload=extra,
            )
            agent = make_agent(controller, f"stage-{workers}",
                               ("map_classify_tpu",))
            if not drain_pipelined(controller, agent, workers, autotune):
                return [f"staging drain (workers={workers}) did not finish: "
                        f"{controller.counts()}"]
            counts = controller.counts()
            if counts != {"succeeded": ROWS // SHARD}:
                return [f"staging drain (workers={workers}) bad counts "
                        f"{counts}"]
            by_workers[workers] = {
                controller.job(j).payload["start_row"]: r
                for j, r in controller.results().items()
            }
        for start, want in sorted(by_workers[1].items()):
            got = by_workers[4][start]
            for key in ("indices", "scores"):
                if got[key] != want[key]:
                    problems.append(
                        f"multi-worker shard @{start} diverged on {key!r}"
                    )
    if not problems:
        print(json.dumps({
            "check": "parallel_staging", "workers": [1, 4],
            "shards": ROWS // SHARD, "bit_identical": True, "ok": True,
        }, sort_keys=True))
    return problems


def _texts_jobs(controller, texts):
    ids = []
    for i in range(0, len(texts), 64):
        ids.append(controller.submit("map_classify_tpu", {
            "texts": texts[i:i + 64], "topk": 3,
            "result_format": "columnar", "model_config": dict(TINY),
            "allow_fallback": False,
        }))
    ids.append(controller.submit("map_summarize", {
        "texts": texts[:32], "max_length": 6,
        "model_config": dict(TINY_S2S),
    }))
    return ids


def _drain_serial(controller, agent, max_steps=64):
    for _ in range(max_steps):
        if controller.drained():
            return True
        agent.step()
    return controller.drained()


def check_wire_negotiation() -> List[str]:
    """Negotiation matrix + the ≥3× task+result bytes/row bar."""
    from agent_tpu.chaos import LoopbackSession
    from agent_tpu.controller.core import Controller

    problems: List[str] = []
    texts = [f"binary wire check row {i} with some payload text"
             for i in range(256)]
    tasks = ("map_classify_tpu", "map_summarize")
    runs = {}
    for label, ctrl_bin, agent_bin in (
        ("json", False, True),     # binary agent vs JSON-only controller
        ("json_agent", True, False),  # JSON-only agent vs binary controller
        ("binary", True, True),
    ):
        controller = Controller(wire_binary=ctrl_bin)
        job_ids = _texts_jobs(controller, texts)
        session = CountingSession(LoopbackSession(controller))
        agent = make_agent(controller, f"wire-{label}", tasks,
                           wire_binary=agent_bin, session=session)
        if not _drain_serial(controller, agent):
            return [f"wire drain {label!r} did not finish: "
                    f"{controller.counts()}"]
        runs[label] = {
            "results": [controller.job_snapshot(j)["result"]
                        for j in job_ids],
            "session": session,
        }

    for label in ("json", "json_agent"):
        s = runs[label]["session"]
        if s.binary_tasks or s.binary_results:
            problems.append(
                f"{label}: envelope on the wire without negotiation "
                f"(tasks={s.binary_tasks}, results={s.binary_results})"
            )
    s_bin = runs["binary"]["session"]
    if not s_bin.binary_tasks or not s_bin.binary_results:
        problems.append(
            f"binary: negotiation did not engage (tasks="
            f"{s_bin.binary_tasks}, results={s_bin.binary_results})"
        )
    for ref_res, bin_res in zip(runs["json"]["results"],
                                runs["binary"]["results"]):
        for key in ("indices", "scores", "summaries", "summary"):
            if (ref_res or {}).get(key) != (bin_res or {}).get(key):
                problems.append(f"binary vs JSON result diverged on {key!r}")

    rows = len(texts) + 32  # classify rows + summarize rows
    s_json = runs["json"]["session"]
    bytes_json = s_json.bytes_results + s_json.bytes_tasks
    bytes_bin = s_bin.bytes_results + s_bin.bytes_tasks
    shrink = bytes_json / max(1, bytes_bin)
    if shrink < 3.0:
        problems.append(
            f"binary wire shrank task+result bytes only {shrink:.2f}x "
            f"(json {bytes_json} B vs binary {bytes_bin} B) — bar is 3x"
        )
    if not problems:
        print(json.dumps({
            "check": "wire_negotiation",
            "bytes_per_row_json": round(bytes_json / rows, 1),
            "bytes_per_row_binary": round(bytes_bin / rows, 1),
            "wire_shrink_x": round(shrink, 2),
            "binary_tasks": s_bin.binary_tasks,
            "binary_results": s_bin.binary_results,
            "ok": True,
        }, sort_keys=True))
    return problems


def check_chaos_with_pool() -> List[str]:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["STAGE_WORKERS"] = "4"
    env["STAGE_AUTOTUNE"] = "1"
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "chaos_soak.py"),
         "--seed", "7", "--shards", "16", "--quick", "--pipeline"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    if proc.returncode != 0:
        return [
            "chaos_soak --quick --pipeline (STAGE_WORKERS=4) failed:",
            proc.stdout[-2000:], proc.stderr[-2000:],
        ]
    print(json.dumps({"check": "chaos_with_pool", "ok": True}))
    return []


def main() -> int:
    t0 = time.monotonic()
    problems: List[str] = []
    problems += check_parallel_staging()
    problems += check_wire_negotiation()
    problems += check_chaos_with_pool()
    elapsed = round(time.monotonic() - t0, 1)
    if problems:
        for p in problems:
            print(p)
        print(f"check_data_plane: FAILED ({len(problems)} problem(s), "
              f"{elapsed}s)")
        return 1
    print(f"check_data_plane: OK ({elapsed}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
