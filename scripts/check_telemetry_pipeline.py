#!/usr/bin/env python3
"""CI smoke for durable fleet telemetry & incident forensics (ISSUE 20).

Four phases, each over the real surfaces:

1. **Durability** — a real ``agent_tpu.controller.server`` subprocess
   persists sweep samples into ``TSDB_DIR``; it is SIGKILLed mid-write and
   restarted on the same directory. Every sample the first incarnation
   served over ``GET /v1/timeseries?since=`` must still be served by the
   second, from disk (``source == "tsdb"``).
2. **Fleet history** — two partitioned controllers behind a
   ``RouterServer`` collector: the router's ``/v1/timeseries?since=``
   answers one fleet-wide query with both ``partition`` labels present.
3. **Forensics** — a calm warmup then a queue-depth burst on a live
   controller: the detector must confirm exactly ONE anomaly, ``/v1/health``
   must carry it as a warn reason, and ``/v1/incidents`` must hold exactly
   ONE correlated bundle (timeseries + flight recorder + status + health)
   fetchable by id.
4. **Overhead** — the same drain with the durable store on vs off:
   rows/sec with telemetry on must stay >=90% of off in CI (the true cost
   measures <5%; the printed ratio is the record, bench.py tracks it as
   ``tsdb_overhead_ratio``).

Exit 0 = clean; 1 = problems (one per line). Style sibling of
``scripts/check_profile_pipeline.py``: repo-rooted, stdlib-only driver.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from agent_tpu.agent.app import Agent
from agent_tpu.chaos import LoopbackSession
from agent_tpu.config import AgentConfig, Config, ObsConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.server import ControllerServer
from agent_tpu.controller.router import PartitionMap, RouterServer

SHARD_ROWS = 1024
SHARDS = 8
BENCH_ROUNDS = 3
# True cost measures <5%; the CI bar absorbs shared-runner noise. The
# measured ratio prints either way — that number is the record.
BENCH_TOLERANCE = 0.90


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def http_json(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.load(r)


def wait_http(url: str, deadline_s: float = 20.0) -> bool:
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            http_json(url, timeout=2)
            return True
        except Exception:  # noqa: BLE001 — still starting
            time.sleep(0.1)
    return False


def build_csv(path: str, rows: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text,risk\n")
        for i in range(rows):
            f.write(f'{i},"record {i}",{(i % 13) * 0.5}\n')


def make_agent(controller: Controller, name: str) -> Agent:
    cfg = Config(agent=AgentConfig(
        controller_url="http://loopback", agent_name=name,
        tasks=("risk_accumulate",), max_tasks=4, idle_sleep_sec=0.0,
        error_backoff_sec=0.0,
    ))
    agent = Agent(config=cfg, session=LoopbackSession(controller))
    agent._profile = {"tier": "telemetry-smoke"}
    return agent


def drain(controller: Controller, agent: Agent,
          deadline_s: float = 120.0) -> bool:
    deadline = time.monotonic() + deadline_s
    while not controller.drained() and time.monotonic() < deadline:
        leased = agent.lease_once()
        if leased is None:
            controller.sweep()
            continue
        lease_id, tasks = leased
        for task in tasks:
            agent.run_task(lease_id, task)
    agent.push_metrics()
    return controller.drained()


def spawn_server(port: int, tsdb_dir: str, incident_dir: str,
                 journal: str) -> subprocess.Popen:
    env = dict(os.environ)
    env.update(
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
        CONTROLLER_HOST="127.0.0.1",
        CONTROLLER_PORT=str(port),
        CONTROLLER_JOURNAL=journal,
        CONTROLLER_SWEEP_SEC="0.1",
        TSDB_DIR=tsdb_dir,
        TSDB_INTERVAL="0.1",
        INCIDENT_DIR=incident_dir,
    )
    return subprocess.Popen(
        [sys.executable, "-m", "agent_tpu.controller.server"],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def phase_durability(tmp: str, problems: List[str]) -> None:
    """SIGKILL + restart: pre-kill samples stay queryable over HTTP."""
    port = free_port()
    url = f"http://127.0.0.1:{port}"
    tsdb_dir = os.path.join(tmp, "tsdb")
    incident_dir = os.path.join(tmp, "incidents")
    journal = os.path.join(tmp, "journal.jsonl")
    proc = spawn_server(port, tsdb_dir, incident_dir, journal)
    proc2: Optional[subprocess.Popen] = None
    try:
        if not wait_http(url + "/v1/status"):
            problems.append("durability: server never became healthy")
            return
        # Let the sweeper persist a few samples, then capture them.
        prekill: List[float] = []
        deadline = time.monotonic() + 15.0
        while len(prekill) < 5 and time.monotonic() < deadline:
            time.sleep(0.3)
            body = http_json(
                url + "/v1/timeseries?name=controller_queue_depth&since=600"
            )
            prekill = [
                w for s in body.get("series", [])
                for w, _v in s.get("points", [])
            ]
        if len(prekill) < 5:
            problems.append(
                f"durability: only {len(prekill)} pre-kill samples landed"
            )
            return
        if body.get("source") != "tsdb":
            problems.append(
                f"durability: live history source {body.get('source')!r}, "
                "want 'tsdb'"
            )
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)

        proc2 = spawn_server(port, tsdb_dir, incident_dir, journal)
        if not wait_http(url + "/v1/status"):
            problems.append("durability: restarted server never healthy")
            return
        body = http_json(
            url + "/v1/timeseries?name=controller_queue_depth&since=600"
        )
        post = {
            w for s in body.get("series", [])
            for w, _v in s.get("points", [])
        }
        missing = [w for w in prekill if w not in post]
        if body.get("source") != "tsdb":
            problems.append(
                f"durability: post-restart source {body.get('source')!r}"
            )
        if missing:
            problems.append(
                f"durability: {len(missing)}/{len(prekill)} pre-kill "
                f"samples lost across SIGKILL+restart (e.g. {missing[0]})"
            )
        print(f"durability: {len(prekill)} pre-kill samples intact "
              "across SIGKILL+restart")
    finally:
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)


def phase_fleet(tmp: str, problems: List[str]) -> None:
    """Router collector: one query answers across both partitions."""
    ctrls, srvs = [], []
    router = None
    try:
        for i in range(2):
            obs = ObsConfig(
                tsdb_dir=os.path.join(tmp, f"tsdb-p{i}"),
                tsdb_interval_sec=0.05,
            )
            c = Controller(journal_path=None, obs=obs,
                           sweep_interval_sec=0.05, partition=f"p{i}")
            c.start_sweeper()
            c.submit("risk_accumulate", {"values": [1.0, float(i)]})
            s = ControllerServer(c, host="127.0.0.1", port=0)
            s.start()
            ctrls.append(c)
            srvs.append(s)
        pmap = PartitionMap({"p0": [srvs[0].url], "p1": [srvs[1].url]})
        router = RouterServer(
            pmap, host="127.0.0.1", port=0, collect_interval_sec=0.1,
            fleet_tsdb_dir=os.path.join(tmp, "fleet-tsdb"),
        )
        router.start()
        deadline = time.monotonic() + 15.0
        parts: set = set()
        while parts != {"p0", "p1"} and time.monotonic() < deadline:
            time.sleep(0.3)
            body = http_json(
                router.url
                + "/v1/timeseries?name=controller_queue_depth&since=600"
            )
            parts = {
                s.get("labels", {}).get("partition")
                for s in body.get("series", [])
            }
        if parts != {"p0", "p1"}:
            problems.append(
                f"fleet: router history covered partitions {parts}, "
                "want both p0 and p1"
            )
        else:
            stats = router.collector.stats()
            if stats.get("scrape_errors", 0) > 0:
                problems.append(
                    f"fleet: collector scrape errors {stats}"
                )
            print(f"fleet: one router query spans {sorted(parts)} "
                  f"({stats.get('samples_collected', 0)} samples collected)")
    finally:
        if router is not None:
            router.stop()
        for s in srvs:
            s.stop()
        for c in ctrls:
            c.close()


def phase_forensics(tmp: str, problems: List[str]) -> None:
    """Calm warmup then a queue burst: exactly one anomaly, one bundle."""
    obs = ObsConfig(
        tsdb_dir=os.path.join(tmp, "tsdb-forensics"),
        tsdb_interval_sec=0.03,
        anomaly_window=60, anomaly_warmup=10, anomaly_confirm=2,
        incident_dir=os.path.join(tmp, "incidents-forensics"),
    )
    c = Controller(journal_path=None, obs=obs)
    srv = ControllerServer(c, host="127.0.0.1", port=0)
    srv.start()
    try:
        # Calm baseline: empty queue, sampled well past warmup.
        for _ in range(20):
            c.sweep()
            time.sleep(0.035)
        # The burst: 100 jobs land with no agent draining them.
        for i in range(100):
            c.submit("risk_accumulate", {"values": [1.0]},
                     job_id=f"burst-{i}")
        for _ in range(10):
            c.sweep()
            time.sleep(0.035)

        health = http_json(srv.url + "/v1/health")
        anomaly_reasons = [
            r for r in health.get("reasons", [])
            if r.get("kind") == "anomaly"
        ]
        if health.get("verdict") not in ("warn", "page") \
                or not anomaly_reasons:
            problems.append(
                f"forensics: /v1/health verdict {health.get('verdict')!r} "
                f"reasons {health.get('reasons')} — no anomaly warn"
            )
        listing = http_json(srv.url + "/v1/incidents")
        bundles = [
            h for h in listing.get("incidents", [])
            if h.get("kind") == "anomaly"
        ]
        if len(bundles) != 1:
            problems.append(
                f"forensics: {len(bundles)} anomaly bundles, want exactly 1"
            )
            return
        head = bundles[0]
        if head.get("key") != "queue_depth":
            problems.append(
                f"forensics: bundle watched {head.get('key')!r}, "
                "want queue_depth"
            )
        body = http_json(srv.url + "/v1/incidents/" + head["id"])
        sections = (body.get("incident") or {}).get("sections", {})
        for section in ("timeseries", "flight_recorder", "status", "health"):
            if section not in sections:
                problems.append(
                    f"forensics: bundle missing section {section!r}"
                )
        print(f"forensics: one anomaly -> one bundle {head['id']} "
              f"(z={head.get('reason', {}).get('z')})")
    finally:
        srv.stop()
        c.close()


def drain_rows_per_sec(tmp: str, csv_path: str, enabled: bool,
                       round_i: int) -> float:
    rows = SHARDS * SHARD_ROWS
    obs = ObsConfig(
        tsdb_dir=os.path.join(tmp, f"bench-tsdb-{round_i}")
        if enabled else "",
        tsdb_interval_sec=0.1,
        anomaly_enabled=enabled,
        incident_enabled=enabled,
    )
    controller = Controller(journal_path=None, obs=obs)
    controller.submit_csv_job(
        csv_path, total_rows=rows, shard_size=SHARD_ROWS,
        map_op="risk_accumulate", extra_payload={"field": "risk"},
    )
    agent = make_agent(controller, name=f"bench-{round_i}")
    t0 = time.perf_counter()
    if not drain(controller, agent):
        raise RuntimeError(f"bench drain wedged: {controller.counts()}")
    dt = time.perf_counter() - t0
    controller.close()
    return rows / dt


def phase_overhead(tmp: str, problems: List[str]) -> None:
    csv_path = os.path.join(tmp, "rows.csv")
    build_csv(csv_path, SHARDS * SHARD_ROWS)
    best_on = best_off = 0.0
    for i in range(BENCH_ROUNDS):  # interleaved best-of-N
        best_off = max(best_off, drain_rows_per_sec(tmp, csv_path, False, i))
        best_on = max(best_on, drain_rows_per_sec(tmp, csv_path, True, i))
    ratio = best_on / best_off if best_off > 0 else 0.0
    print(f"overhead: telemetry-on {best_on:,.0f} rows/s vs off "
          f"{best_off:,.0f} rows/s — ratio {ratio:.3f}")
    if ratio < BENCH_TOLERANCE:
        problems.append(
            f"overhead: tsdb-on throughput ratio {ratio:.3f} < "
            f"{BENCH_TOLERANCE} of tsdb-off"
        )


def main() -> int:
    problems: List[str] = []
    with tempfile.TemporaryDirectory(prefix="telemetry_smoke_") as tmp:
        phase_durability(tmp, problems)
        phase_fleet(tmp, problems)
        phase_forensics(tmp, problems)
        phase_overhead(tmp, problems)
    if problems:
        for p in problems:
            print(p)
        print(f"FAILED: {len(problems)} problem(s)")
        return 1
    print("telemetry pipeline smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
