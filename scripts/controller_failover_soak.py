#!/usr/bin/env python3
"""Controller crash-survival soak — the chaos drill that finally attacks
the control plane itself (ISSUE 14; ROADMAP item 3c).

Two runs per seed:

1. **Calm reference** — an in-process ``Controller`` + ``LoopbackSession``
   agents drain the identical seeded workload (bulk risk_accumulate
   map-reduce + seeded echo singles). Records the canonical reduce result.
2. **Failover run** — the primary controller is a REAL subprocess
   (``python -m agent_tpu.controller.server``) journaling to a segmented
   journal with compacting snapshots; a ``HotStandby`` tails the journal
   in-process; real ``Agent`` threads lease/post over real HTTP with a
   ``CONTROLLER_URLS`` failover list. Mid-drain, under seeded load, the
   chaos plan's ``controller_kill`` draw SIGKILLs the primary — no
   close(), no fsync, a possibly-torn final journal line. The standby
   promotes (final tail + seal + epoch-fenced requeue) and serves on the
   pre-agreed standby port; agents fail over; the spool redelivers
   completed results to the new incarnation; a submitter keeps submitting
   singles across the flip with deterministic job ids (a duplicate-id 400
   after a lost response = already submitted = success).

Asserts (the ISSUE 14 acceptance bar):

- the failover run's reduce result is **bit-identical** to the calm
  reference;
- **zero lost / double-applied / double-billed jobs**: every job terminal
  ``succeeded``, ledger ``billed == jobs``, every job billed exactly once;
- **≥ 1 controller kill** actually happened (seeded, with a deterministic
  force-by-deadline backstop), every agent **failed over** (counter ≥ 1),
  the standby **promoted exactly once**, and ≥ 1 compacting **snapshot**
  landed during the run;
- after the drain the **journal replays** into a fresh controller with
  identical job states/epochs/attempts, an identical usage ledger, an
  empty scheduler queue, and **zero torn/skipped lines** (promotion sealed
  the primary's torn death write);
- the promoted incarnation's ``/v1/status`` ``journal`` block rides real
  HTTP with ``promotions: 1``.

Exit 0 = all seeds clean; 1 = problems (listed one per line). CI runs
``--quick --seed 7`` (CPU-shaped, < 90 s).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from agent_tpu.agent.app import Agent
from agent_tpu.chaos import FaultPlan, LoopbackSession
from agent_tpu.config import AgentConfig, Config, JournalConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.journal import list_segments, load_snapshot
from agent_tpu.controller.server import ControllerServer
from agent_tpu.controller.standby import HotStandby

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Timing/attribution fields legitimately differ run to run; everything
# else in the reduce result must match bit for bit (same exclusion set as
# chaos_soak / elastic_soak).
VOLATILE_KEYS = ("compute_time_ms", "duration_ms", "timings", "trace",
                 "usage")

JOURNAL_CFG = JournalConfig(
    segment_max_bytes=8 * 1024, snapshot_every_events=30
)

# The throttled map op ships through the designed extension point
# (OPS_PLUGIN_PATH / load_plugins), not a registry monkey-patch: a
# payload-controlled service time is what keeps the drain IN FLIGHT long
# enough for the seeded controller_kill to land mid-drain on a CPU
# runner. It returns risk_accumulate's result unchanged, so the reduce
# stays bit-identical to the calm reference.
PLUGIN_SRC = '''\
"""Soak-only op: risk_accumulate with payload-controlled service time."""
import time

from agent_tpu.ops import register_op
from agent_tpu.ops.risk_accumulate import run as _risk


@register_op("slow_risk")
def run(payload, ctx=None):
    out = _risk(payload, ctx)
    time.sleep(float(payload.get("sleep_ms", 0.0)) / 1e3)
    return out
'''


def canonical(result: Any) -> str:
    if isinstance(result, dict):
        result = {k: v for k, v in result.items() if k not in VOLATILE_KEYS}
    return json.dumps(result, sort_keys=True, default=str)


def build_csv(path: str, rows: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text,risk\n")
        for i in range(rows):
            f.write(f'{i},"record {i}",{(i % 17) * 0.25}\n')


def free_port() -> int:
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def http_json(
    url: str, body: Optional[Dict[str, Any]] = None, timeout: float = 5.0
) -> Tuple[int, Any]:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return resp.status, (json.loads(raw) if raw else None)
    except urllib.error.HTTPError as exc:
        raw = exc.read()
        try:
            return exc.code, json.loads(raw) if raw else None
        except ValueError:
            return exc.code, raw.decode(errors="replace")


def wait_for_status(url: str, deadline_sec: float) -> bool:
    deadline = time.monotonic() + deadline_sec
    while time.monotonic() < deadline:
        try:
            status, _ = http_json(url + "/v1/status", timeout=2)
            if status == 200:
                return True
        except Exception:  # noqa: BLE001 — still booting
            pass
        time.sleep(0.05)
    return False


def make_agent(name: str, urls: List[str]) -> Agent:
    cfg = Config(agent=AgentConfig(
        controller_url=urls[0], controller_urls=tuple(urls),
        agent_name=name, tasks=("slow_risk", "risk_accumulate", "echo"),
        max_tasks=2, idle_sleep_sec=0.02, http_timeout_sec=5.0,
        error_backoff_sec=0.05, retry_base_sec=0.02, retry_max_sec=0.25,
        pipeline_depth=0,
    ))
    agent = Agent(config=cfg)
    agent._profile = {"tier": "failover-soak"}  # skip hardware probing
    return agent


def submit_bulk_http(
    url: str, csv_path: str, shards: int, rows_per_shard: int,
    sleep_ms: float,
) -> Tuple[List[str], str]:
    status, body = http_json(url + "/v1/jobs", {
        "source_uri": csv_path,
        "total_rows": shards * rows_per_shard,
        "shard_size": rows_per_shard,
        "map_op": "slow_risk",
        "extra_payload": {"field": "risk", "sleep_ms": sleep_ms},
        "reduce_op": "risk_accumulate",
        "collect_partials": True,
    })
    if status != 200:
        raise RuntimeError(f"bulk submit failed: HTTP {status} {body}")
    return body["job_ids"], body["reduce_id"]


class SingleSubmitter:
    """Paced seeded echo singles with deterministic job ids, submitted
    across the failover flip: each id retries round-robin over the URL
    list until accepted — a duplicate-id 400 after a lost response means
    the dead primary already journaled it, which is success."""

    def __init__(self, urls: List[str], seed: int, n: int,
                 window_sec: float) -> None:
        self.urls = urls
        self.seed = seed
        self.n = n
        self.window_sec = window_sec
        self.submitted: List[str] = []
        self.duplicate_acks = 0
        self._thread = threading.Thread(
            target=self._run, name="soak-submitter", daemon=True
        )

    def _submit_one(self, i: int) -> Optional[str]:
        job_id = f"single-{self.seed}-{i}"
        body = {"op": "echo", "payload": {"seq": i, "seed": self.seed},
                "job_id": job_id}
        deadline = time.monotonic() + 30.0
        k = 0
        while time.monotonic() < deadline:
            url = self.urls[k % len(self.urls)]
            k += 1
            try:
                status, resp = http_json(
                    url + "/v1/jobs", body, timeout=3
                )
            except Exception:  # noqa: BLE001 — controller down: rotate
                time.sleep(0.05)
                continue
            if status == 200:
                return job_id
            if status == 400 and "duplicate job id" in str(resp):
                self.duplicate_acks += 1
                return job_id
            time.sleep(0.05)
        return None

    def _run(self) -> None:
        gap = self.window_sec / max(1, self.n)
        for i in range(self.n):
            jid = self._submit_one(i)
            if jid is not None:
                self.submitted.append(jid)
            time.sleep(gap)

    def start(self) -> "SingleSubmitter":
        self._thread.start()
        return self

    def join(self, timeout: float) -> None:
        self._thread.join(timeout=timeout)


def run_reference(
    tmp: str, csv_path: str, shards: int, rows_per_shard: int, seed: int,
    args: Any,
) -> Tuple[Dict[str, Any], List[str]]:
    """Calm in-process drain of the identical workload — the bit-identity
    anchor."""
    problems: List[str] = []
    out: Dict[str, Any] = {}
    controller = Controller(
        lease_ttl_sec=10.0, max_attempts=10, requeue_delay_sec=0.01,
        sweep_interval_sec=0.1,
    )
    agents = [
        Agent(
            config=Config(agent=AgentConfig(
                controller_url="http://loopback", agent_name=f"ref-{i}",
                tasks=("slow_risk", "risk_accumulate", "echo"),
                max_tasks=2,
                idle_sleep_sec=0.01, error_backoff_sec=0.01,
                retry_base_sec=0.005, retry_max_sec=0.05, pipeline_depth=0,
            )),
            session=LoopbackSession(controller),
        )
        for i in range(2)
    ]
    for a in agents:
        a._profile = {"tier": "failover-soak"}
    threads = [
        threading.Thread(target=a.run, name=f"ref-agent-{i}", daemon=True)
        for i, a in enumerate(agents)
    ]
    try:
        for t in threads:
            t.start()
        _, reduce_id = controller.submit_csv_job(
            csv_path, total_rows=shards * rows_per_shard,
            shard_size=rows_per_shard, map_op="slow_risk",
            extra_payload={"field": "risk", "sleep_ms": args.sleep_ms},
            reduce_op="risk_accumulate",
            collect_partials=True,
        )
        for i in range(args.singles):
            controller.submit(
                "echo", {"seq": i, "seed": seed},
                job_id=f"single-{seed}-{i}",
            )
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not controller.drained():
            time.sleep(0.05)
        if not controller.drained():
            problems.append(
                f"seed {seed}: reference drain did not complete "
                f"(counts {controller.counts()})"
            )
            return out, problems
        job = controller.job_snapshot(reduce_id)
        if job["state"] != "succeeded":
            problems.append(
                f"seed {seed}: reference reduce state {job['state']!r}"
            )
            return out, problems
        out["reduce"] = canonical(job["result"])
    finally:
        for a in agents:
            a.request_drain(reason="reference done")
        for t in threads:
            t.join(timeout=10)
        controller.close()
    return out, problems


def run_failover(
    tmp: str, csv_path: str, shards: int, rows_per_shard: int, seed: int,
    args: Any, reference: Dict[str, Any],
) -> List[str]:
    problems: List[str] = []
    journal_path = os.path.join(tmp, "controller_journal.jsonl")
    port_a, port_b = free_port(), free_port()
    url_a = f"http://127.0.0.1:{port_a}"
    url_b = f"http://127.0.0.1:{port_b}"
    urls = [url_a, url_b]
    plan = FaultPlan(seed=seed, controller_kill=args.kill_prob)

    env = dict(os.environ)
    env.update(
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        CONTROLLER_HOST="127.0.0.1",
        CONTROLLER_PORT=str(port_a),
        CONTROLLER_JOURNAL=journal_path,
        JOURNAL_SEGMENT_MAX_BYTES=str(JOURNAL_CFG.segment_max_bytes),
        SNAPSHOT_EVERY_EVENTS=str(JOURNAL_CFG.snapshot_every_events),
        LEASE_TTL_SEC="3",
        MAX_ATTEMPTS="10",
        REQUEUE_DELAY_SEC="0.01",
        CONTROLLER_SWEEP_SEC="0.2",
    )
    primary = subprocess.Popen(
        [sys.executable, "-m", "agent_tpu.controller.server"],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    standby: Optional[HotStandby] = None
    standby_server: Optional[ControllerServer] = None
    promoted: Optional[Controller] = None
    agents: List[Agent] = []
    threads: List[threading.Thread] = []
    kills = 0
    succeeded_at_kill = 0
    try:
        if not wait_for_status(url_a, 20.0):
            problems.append(f"seed {seed}: primary never became healthy")
            return problems
        standby = HotStandby(
            journal_path, journal=JOURNAL_CFG, poll_interval_sec=0.02,
            sweep_interval_sec=0.2, lease_ttl_sec=3.0, max_attempts=10,
            requeue_delay_sec=0.01,
        ).start()

        agents = [
            make_agent(f"fo-{seed}-{i}", urls) for i in range(args.agents)
        ]
        threads = [
            threading.Thread(target=a.run, name=f"fo-agent-{i}",
                             daemon=True)
            for i, a in enumerate(agents)
        ]
        for t in threads:
            t.start()

        shard_ids, reduce_id = submit_bulk_http(
            url_a, csv_path, shards, rows_per_shard, args.sleep_ms
        )
        submitter = SingleSubmitter(
            urls, seed, args.singles, args.submit_window_sec
        ).start()

        # ---- the drill: seeded controller_kill once enough is in flight
        kill_floor = max(1, int(shards * args.kill_after_frac))
        force_deadline = time.monotonic() + args.kill_deadline_sec
        while kills == 0:
            try:
                status, body = http_json(url_a + "/v1/status", timeout=2)
                by_op = (body or {}).get("counts_by_op", {})
                shards_done = by_op.get("slow_risk", {}).get(
                    "succeeded", 0
                )
            except Exception:  # noqa: BLE001 — primary gone early?
                problems.append(
                    f"seed {seed}: primary unreachable before the kill"
                )
                break
            # Armed once the drain is genuinely IN FLIGHT (shard
            # successes, not singles — the mid-drain guarantee); forced
            # once the window starts closing or the deadline passes, so
            # the drill happens even when the seeded draws come up short.
            armed = shards_done >= kill_floor
            forced = (
                time.monotonic() > force_deadline
                or shards_done >= max(kill_floor + 1, int(shards * 0.6))
            )
            if armed and (plan.decide("controller_kill") or forced):
                primary.send_signal(signal.SIGKILL)
                primary.wait(timeout=10)
                kills += 1
                succeeded_at_kill = shards_done
                if forced and not plan.counts.get("controller_kill"):
                    # Deterministic backstop, still counted as the fault.
                    plan.counts["controller_kill"] = \
                        plan.counts.get("controller_kill", 0) + 1
                break
            time.sleep(0.05)
        if kills == 0:
            return problems
        if succeeded_at_kill >= shards:
            problems.append(
                f"seed {seed}: kill landed too late "
                f"({succeeded_at_kill} >= {shards} shards done) — not a "
                "mid-drain drill; raise --sleep-ms"
            )

        # ---- promotion: the standby becomes the controller on url_b
        promoted = standby.promote()
        standby_server = ControllerServer(
            promoted, host="127.0.0.1", port=port_b
        ).start()

        submitter.join(timeout=args.submit_window_sec + 60.0)
        expected = set(shard_ids) | {reduce_id} | set(submitter.submitted)
        n_jobs = len(expected)
        if len(submitter.submitted) != args.singles:
            problems.append(
                f"seed {seed}: only {len(submitter.submitted)}/"
                f"{args.singles} singles submitted across the flip"
            )

        deadline = time.monotonic() + args.deadline_sec
        while time.monotonic() < deadline and not promoted.drained():
            time.sleep(0.05)
        if not promoted.drained():
            problems.append(
                f"seed {seed}: failover drain did not complete "
                f"(counts {promoted.counts()})"
            )
            return problems

        # ---- zero lost work, bit-identical output ----
        counts = promoted.counts()
        if counts.get("failed") or counts.get("dead"):
            problems.append(
                f"seed {seed}: failed/dead jobs after failover: {counts}"
            )
        if counts.get("succeeded", 0) != n_jobs:
            problems.append(
                f"seed {seed}: {counts.get('succeeded', 0)} succeeded != "
                f"{n_jobs} submitted (lost work)"
            )
        for jid in expected:
            try:
                snap = promoted.job_snapshot(jid)
            except KeyError:
                problems.append(
                    f"seed {seed}: job {jid} lost across the flip"
                )
                continue
            if snap["state"] != "succeeded":
                problems.append(
                    f"seed {seed}: job {jid} state {snap['state']!r}"
                )
        reduce_job = promoted.job_snapshot(reduce_id)
        got = canonical(reduce_job["result"])
        if got != reference.get("reduce"):
            problems.append(
                f"seed {seed}: reduce diverged across the flip\n"
                f"  want {reference.get('reduce')}\n  got  {got}"
            )

        # ---- zero double-billing (double-application would show here) --
        if promoted.usage is None:
            problems.append(f"seed {seed}: usage ledger disabled")
        else:
            billed = promoted.usage.billed_tasks
            if billed != n_jobs:
                problems.append(
                    f"seed {seed}: usage billed {billed} != jobs {n_jobs} "
                    "(lost or double-billed work)"
                )
            multi = {
                jid: n
                for jid, n in promoted.usage.job_billed_attempts().items()
                if n != 1
            }
            if multi:
                problems.append(
                    f"seed {seed}: jobs billed != once: "
                    f"{dict(list(multi.items())[:5])}"
                )

        # ---- the failover machinery actually engaged ----
        failovers = 0
        for a in agents:
            snap = a.obs.snapshot()
            for s in snap.get("controller_failovers_total", {}).get(
                "series", []
            ):
                failovers += int(s.get("value", 0))
        if failovers < args.agents:
            problems.append(
                f"seed {seed}: only {failovers} agent failovers "
                f"(expected >= {args.agents} — every agent must rotate)"
            )
        if promoted.promotions != 1:
            problems.append(
                f"seed {seed}: promotions {promoted.promotions} != 1"
            )
        if load_snapshot(journal_path) is None:
            problems.append(
                f"seed {seed}: no compacting snapshot landed during the "
                "run (SNAPSHOT_EVERY_EVENTS never fired?)"
            )
        n_segments = len(list_segments(journal_path))
        if n_segments > 200:
            problems.append(
                f"seed {seed}: {n_segments} journal segments on disk — "
                "compaction is not collecting covered segments"
            )

        # ---- the promoted /v1/status journal block over real HTTP ----
        status, body = http_json(url_b + "/v1/status", timeout=3)
        jblock = (body or {}).get("journal", {})
        if status != 200 or jblock.get("promotions") != 1:
            problems.append(
                f"seed {seed}: standby /v1/status journal block wrong: "
                f"HTTP {status} {jblock}"
            )
        for key in ("segments", "bytes", "last_snapshot_age_sec",
                    "last_replay_sec"):
            if key not in jblock:
                problems.append(
                    f"seed {seed}: journal status block missing {key!r}"
                )

        # ---- retire the fleet through the drain path ----
        for a in agents:
            a.request_drain(reason="soak done")
        for t in threads:
            t.join(timeout=15)
        leftover = [len(a.spool) for a in agents if len(a.spool)]
        if leftover:
            problems.append(
                f"seed {seed}: agents left spooled results: {leftover}"
            )

        # ---- the healed journal replays to the identical state ----
        live = {}
        for jid in expected:
            try:
                live[jid] = promoted.job_snapshot(jid)
            except KeyError:
                pass  # already recorded as lost above
        live_billed = promoted.usage.billed_tasks \
            if promoted.usage is not None else 0
        live_attempts = promoted.usage.job_billed_attempts() \
            if promoted.usage is not None else {}
        standby_server.stop()
        standby_server = None
        promoted.close()
        replayed = Controller(journal_path=journal_path, journal=JOURNAL_CFG)
        try:
            if replayed.journal_torn_tail or replayed.journal_replay_skipped:
                problems.append(
                    f"seed {seed}: journal replay damage after the flip "
                    f"(torn {replayed.journal_torn_tail}, skipped "
                    f"{replayed.journal_replay_skipped}) — promotion "
                    "failed to seal the torn tail"
                )
            if replayed.queue_depth() != 0:
                problems.append(
                    f"seed {seed}: replayed queue depth "
                    f"{replayed.queue_depth()} != 0"
                )
            for jid, want in live.items():
                try:
                    got_snap = replayed.job_snapshot(jid)
                except KeyError:
                    problems.append(
                        f"seed {seed}: job {jid} lost in final replay"
                    )
                    continue
                for k in ("state", "job_epoch", "attempts"):
                    if got_snap[k] != want[k]:
                        problems.append(
                            f"seed {seed}: replay {jid} {k} "
                            f"{got_snap[k]!r} != live {want[k]!r}"
                        )
                        break
            if replayed.usage is not None:
                if replayed.usage.billed_tasks != live_billed:
                    problems.append(
                        f"seed {seed}: replayed ledger billed "
                        f"{replayed.usage.billed_tasks} != live "
                        f"{live_billed}"
                    )
                if replayed.usage.job_billed_attempts() != live_attempts:
                    problems.append(
                        f"seed {seed}: replayed per-job billing diverged"
                    )
        finally:
            replayed.close()
        promoted = None

        print(json.dumps({
            "scenario": "controller_failover", "seed": seed,
            "jobs": n_jobs, "singles": len(submitter.submitted),
            "duplicate_acks": submitter.duplicate_acks,
            "controller_kills": kills,
            "plan_counts": plan.counts,
            "agent_failovers": failovers,
            "torn_sealed_bytes": standby.torn_sealed_bytes,
            "snapshots": jblock.get("snapshots_written"),
            "segments": n_segments,
            "counts": counts, "ok": not problems,
        }, sort_keys=True))
        return problems
    finally:
        for a in agents:
            a.request_drain(reason="cleanup")
        for t in threads:
            t.join(timeout=10)
        if standby is not None:
            standby.stop()
        if standby_server is not None:
            standby_server.stop()
        if promoted is not None:
            promoted.close()
        if primary.poll() is None:
            primary.kill()
            primary.wait(timeout=10)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--seeds", type=str, default="",
                    help="comma-separated seed list (overrides --seed)")
    ap.add_argument("--shards", type=int, default=24)
    ap.add_argument("--rows-per-shard", type=int, default=40)
    ap.add_argument("--singles", type=int, default=40,
                    help="seeded echo singles submitted across the flip")
    ap.add_argument("--agents", type=int, default=3)
    ap.add_argument("--kill-prob", type=float, default=0.35,
                    help="per-tick controller_kill probability once armed")
    ap.add_argument("--kill-after-frac", type=float, default=0.25,
                    help="arm the kill once this fraction of shards "
                         "succeeded (mid-drain, not at the edges)")
    ap.add_argument("--kill-deadline-sec", type=float, default=25.0,
                    help="force the kill by this deadline if the seeded "
                         "draws came up short")
    ap.add_argument("--submit-window-sec", type=float, default=6.0)
    ap.add_argument("--sleep-ms", type=float, default=120.0,
                    help="per-shard service time — what keeps the drain "
                         "in flight long enough to kill mid-drain")
    ap.add_argument("--deadline-sec", type=float, default=90.0)
    ap.add_argument("--quick", action="store_true",
                    help="CI sizing: shrinks the workload for < 90 s")
    args = ap.parse_args(argv)

    if args.quick:
        args.shards = min(args.shards, 16)
        args.rows_per_shard = min(args.rows_per_shard, 30)
        args.singles = min(args.singles, 24)
        args.submit_window_sec = min(args.submit_window_sec, 4.0)
        args.deadline_sec = min(args.deadline_sec, 60.0)

    seeds = (
        [int(s) for s in args.seeds.split(",") if s.strip()]
        if args.seeds else [args.seed]
    )

    # The throttled map op, loaded through the designed plugin channel.
    tmp_root = tempfile.mkdtemp(prefix="failover_soak_plugin_")
    plugin_path = os.path.join(tmp_root, "slow_risk_plugin.py")
    with open(plugin_path, "w", encoding="utf-8") as f:
        f.write(PLUGIN_SRC)
    from agent_tpu.ops import load_plugins

    if "slow_risk" not in load_plugins(plugin_path):
        from agent_tpu.ops import OPS_LOAD_ERRORS

        print(f"slow_risk plugin failed to load: {OPS_LOAD_ERRORS}")
        return 1

    problems: List[str] = []
    t0 = time.monotonic()
    for seed in seeds:
        with tempfile.TemporaryDirectory(
            prefix=f"failover_soak_{seed}_"
        ) as tmp:
            csv_path = os.path.join(tmp, "rows.csv")
            build_csv(csv_path, args.shards * args.rows_per_shard)
            reference, ref_problems = run_reference(
                tmp, csv_path, args.shards, args.rows_per_shard, seed,
                args,
            )
            problems += ref_problems
            if not ref_problems:
                problems += run_failover(
                    tmp, csv_path, args.shards, args.rows_per_shard,
                    seed, args, reference,
                )
    elapsed = round(time.monotonic() - t0, 3)
    if problems:
        for p in problems:
            print(p)
        print(f"FAILED: {len(problems)} problem(s) in {elapsed}s")
        return 1
    print(
        f"controller failover soak: OK ({len(seeds)} seed(s), "
        f"{args.shards} shards, {elapsed}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
