#!/usr/bin/env python3
"""Controller crash-survival soak — the chaos drill that finally attacks
the control plane itself (ISSUE 14; ROADMAP item 3c).

Two runs per seed:

1. **Calm reference** — an in-process ``Controller`` + ``LoopbackSession``
   agents drain the identical seeded workload (bulk risk_accumulate
   map-reduce + seeded echo singles). Records the canonical reduce result.
2. **Failover run** — the primary controller is a REAL subprocess
   (``python -m agent_tpu.controller.server``) journaling to a segmented
   journal with compacting snapshots; a ``HotStandby`` tails the journal
   in-process; real ``Agent`` threads lease/post over real HTTP with a
   ``CONTROLLER_URLS`` failover list. Mid-drain, under seeded load, the
   chaos plan's ``controller_kill`` draw SIGKILLs the primary — no
   close(), no fsync, a possibly-torn final journal line. The standby
   promotes (final tail + seal + epoch-fenced requeue) and serves on the
   pre-agreed standby port; agents fail over; the spool redelivers
   completed results to the new incarnation; a submitter keeps submitting
   singles across the flip with deterministic job ids (a duplicate-id 400
   after a lost response = already submitted = success).

Asserts (the ISSUE 14 acceptance bar):

- the failover run's reduce result is **bit-identical** to the calm
  reference;
- **zero lost / double-applied / double-billed jobs**: every job terminal
  ``succeeded``, ledger ``billed == jobs``, every job billed exactly once;
- **≥ 1 controller kill** actually happened (seeded, with a deterministic
  force-by-deadline backstop), every agent **failed over** (counter ≥ 1),
  the standby **promoted exactly once**, and ≥ 1 compacting **snapshot**
  landed during the run;
- after the drain the **journal replays** into a fresh controller with
  identical job states/epochs/attempts, an identical usage ledger, an
  empty scheduler queue, and **zero torn/skipped lines** (promotion sealed
  the primary's torn death write);
- the promoted incarnation's ``/v1/status`` ``journal`` block rides real
  HTTP with ``promotions: 1``.

``--partitions N`` (N > 1) swaps the drill for the ISSUE 18
**partition_kill** variant: N partition subprocesses (each with its own
segmented journal) behind one stateless in-process router; agents and the
submitter only ever see the router URL. Mid-drain the bulk job's home
partition is SIGKILLed. Asserts: surviving partitions land NEW successes
within ``--survivor-window-sec`` of the kill (never stall), the victim
restarts over its own journal (replay requeues, restart seals the torn
death write), spooled results redeliver through the router's tagged lease
ids, the drain completes with the reduce bit-identical to the calm
reference, and the union of the partitions' final journal replays shows
every job terminal on exactly one partition, billed exactly once, zero
torn/skipped lines.

Exit 0 = all seeds clean; 1 = problems (listed one per line). CI runs
``--quick --seed 7`` (CPU-shaped, < 90 s).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from agent_tpu.agent.app import Agent
from agent_tpu.chaos import FaultPlan, LoopbackSession
from agent_tpu.config import AgentConfig, Config, JournalConfig, ObsConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.journal import list_segments, load_snapshot
from agent_tpu.controller.server import ControllerServer
from agent_tpu.controller.standby import HotStandby

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Timing/attribution fields legitimately differ run to run; everything
# else in the reduce result must match bit for bit (same exclusion set as
# chaos_soak / elastic_soak).
VOLATILE_KEYS = ("compute_time_ms", "duration_ms", "timings", "trace",
                 "usage")

JOURNAL_CFG = JournalConfig(
    segment_max_bytes=8 * 1024, snapshot_every_events=30
)

# The throttled map op ships through the designed extension point
# (OPS_PLUGIN_PATH / load_plugins), not a registry monkey-patch: a
# payload-controlled service time is what keeps the drain IN FLIGHT long
# enough for the seeded controller_kill to land mid-drain on a CPU
# runner. It returns risk_accumulate's result unchanged, so the reduce
# stays bit-identical to the calm reference.
PLUGIN_SRC = '''\
"""Soak-only op: risk_accumulate with payload-controlled service time."""
import time

from agent_tpu.ops import register_op
from agent_tpu.ops.risk_accumulate import run as _risk


@register_op("slow_risk")
def run(payload, ctx=None):
    out = _risk(payload, ctx)
    time.sleep(float(payload.get("sleep_ms", 0.0)) / 1e3)
    return out
'''


def canonical(result: Any) -> str:
    if isinstance(result, dict):
        result = {k: v for k, v in result.items() if k not in VOLATILE_KEYS}
    return json.dumps(result, sort_keys=True, default=str)


def build_csv(path: str, rows: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text,risk\n")
        for i in range(rows):
            f.write(f'{i},"record {i}",{(i % 17) * 0.25}\n')


def free_port() -> int:
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def http_json(
    url: str, body: Optional[Dict[str, Any]] = None, timeout: float = 5.0
) -> Tuple[int, Any]:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return resp.status, (json.loads(raw) if raw else None)
    except urllib.error.HTTPError as exc:
        raw = exc.read()
        try:
            return exc.code, json.loads(raw) if raw else None
        except ValueError:
            return exc.code, raw.decode(errors="replace")


def wait_for_status(url: str, deadline_sec: float) -> bool:
    deadline = time.monotonic() + deadline_sec
    while time.monotonic() < deadline:
        try:
            status, _ = http_json(url + "/v1/status", timeout=2)
            if status == 200:
                return True
        except Exception:  # noqa: BLE001 — still booting
            pass
        time.sleep(0.05)
    return False


def make_agent(name: str, urls: List[str]) -> Agent:
    cfg = Config(agent=AgentConfig(
        controller_url=urls[0], controller_urls=tuple(urls),
        agent_name=name, tasks=("slow_risk", "risk_accumulate", "echo"),
        max_tasks=2, idle_sleep_sec=0.02, http_timeout_sec=5.0,
        error_backoff_sec=0.05, retry_base_sec=0.02, retry_max_sec=0.25,
        pipeline_depth=0,
    ))
    agent = Agent(config=cfg)
    agent._profile = {"tier": "failover-soak"}  # skip hardware probing
    return agent


def submit_bulk_http(
    url: str, csv_path: str, shards: int, rows_per_shard: int,
    sleep_ms: float,
) -> Tuple[List[str], str]:
    status, body = http_json(url + "/v1/jobs", {
        "source_uri": csv_path,
        "total_rows": shards * rows_per_shard,
        "shard_size": rows_per_shard,
        "map_op": "slow_risk",
        "extra_payload": {"field": "risk", "sleep_ms": sleep_ms},
        "reduce_op": "risk_accumulate",
        "collect_partials": True,
    })
    if status != 200:
        raise RuntimeError(f"bulk submit failed: HTTP {status} {body}")
    return body["job_ids"], body["reduce_id"]


class SingleSubmitter:
    """Paced seeded echo singles with deterministic job ids, submitted
    across the failover flip: each id retries round-robin over the URL
    list until accepted — a duplicate-id 400 after a lost response means
    the dead primary already journaled it, which is success."""

    def __init__(self, urls: List[str], seed: int, n: int,
                 window_sec: float) -> None:
        self.urls = urls
        self.seed = seed
        self.n = n
        self.window_sec = window_sec
        self.submitted: List[str] = []
        self.duplicate_acks = 0
        self._thread = threading.Thread(
            target=self._run, name="soak-submitter", daemon=True
        )

    def _submit_one(self, i: int) -> Optional[str]:
        job_id = f"single-{self.seed}-{i}"
        body = {"op": "echo", "payload": {"seq": i, "seed": self.seed},
                "job_id": job_id}
        deadline = time.monotonic() + 30.0
        k = 0
        while time.monotonic() < deadline:
            url = self.urls[k % len(self.urls)]
            k += 1
            try:
                status, resp = http_json(
                    url + "/v1/jobs", body, timeout=3
                )
            except Exception:  # noqa: BLE001 — controller down: rotate
                time.sleep(0.05)
                continue
            if status == 200:
                return job_id
            if status == 400 and "duplicate job id" in str(resp):
                self.duplicate_acks += 1
                return job_id
            time.sleep(0.05)
        return None

    def _run(self) -> None:
        gap = self.window_sec / max(1, self.n)
        for i in range(self.n):
            jid = self._submit_one(i)
            if jid is not None:
                self.submitted.append(jid)
            time.sleep(gap)

    def start(self) -> "SingleSubmitter":
        self._thread.start()
        return self

    def join(self, timeout: float) -> None:
        self._thread.join(timeout=timeout)


def run_reference(
    tmp: str, csv_path: str, shards: int, rows_per_shard: int, seed: int,
    args: Any,
) -> Tuple[Dict[str, Any], List[str]]:
    """Calm in-process drain of the identical workload — the bit-identity
    anchor."""
    problems: List[str] = []
    out: Dict[str, Any] = {}
    controller = Controller(
        lease_ttl_sec=10.0, max_attempts=10, requeue_delay_sec=0.01,
        sweep_interval_sec=0.1,
    )
    agents = [
        Agent(
            config=Config(agent=AgentConfig(
                controller_url="http://loopback", agent_name=f"ref-{i}",
                tasks=("slow_risk", "risk_accumulate", "echo"),
                max_tasks=2,
                idle_sleep_sec=0.01, error_backoff_sec=0.01,
                retry_base_sec=0.005, retry_max_sec=0.05, pipeline_depth=0,
            )),
            session=LoopbackSession(controller),
        )
        for i in range(2)
    ]
    for a in agents:
        a._profile = {"tier": "failover-soak"}
    threads = [
        threading.Thread(target=a.run, name=f"ref-agent-{i}", daemon=True)
        for i, a in enumerate(agents)
    ]
    try:
        for t in threads:
            t.start()
        _, reduce_id = controller.submit_csv_job(
            csv_path, total_rows=shards * rows_per_shard,
            shard_size=rows_per_shard, map_op="slow_risk",
            extra_payload={"field": "risk", "sleep_ms": args.sleep_ms},
            reduce_op="risk_accumulate",
            collect_partials=True,
        )
        for i in range(args.singles):
            controller.submit(
                "echo", {"seq": i, "seed": seed},
                job_id=f"single-{seed}-{i}",
            )
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and not controller.drained():
            time.sleep(0.05)
        if not controller.drained():
            problems.append(
                f"seed {seed}: reference drain did not complete "
                f"(counts {controller.counts()})"
            )
            return out, problems
        job = controller.job_snapshot(reduce_id)
        if job["state"] != "succeeded":
            problems.append(
                f"seed {seed}: reference reduce state {job['state']!r}"
            )
            return out, problems
        out["reduce"] = canonical(job["result"])
    finally:
        for a in agents:
            a.request_drain(reason="reference done")
        for t in threads:
            t.join(timeout=10)
        controller.close()
    return out, problems


def run_failover(
    tmp: str, csv_path: str, shards: int, rows_per_shard: int, seed: int,
    args: Any, reference: Dict[str, Any],
) -> List[str]:
    problems: List[str] = []
    journal_path = os.path.join(tmp, "controller_journal.jsonl")
    port_a, port_b = free_port(), free_port()
    url_a = f"http://127.0.0.1:{port_a}"
    url_b = f"http://127.0.0.1:{port_b}"
    urls = [url_a, url_b]
    plan = FaultPlan(seed=seed, controller_kill=args.kill_prob)

    env = dict(os.environ)
    env.update(
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        CONTROLLER_HOST="127.0.0.1",
        CONTROLLER_PORT=str(port_a),
        CONTROLLER_JOURNAL=journal_path,
        JOURNAL_SEGMENT_MAX_BYTES=str(JOURNAL_CFG.segment_max_bytes),
        SNAPSHOT_EVERY_EVENTS=str(JOURNAL_CFG.snapshot_every_events),
        LEASE_TTL_SEC="3",
        MAX_ATTEMPTS="10",
        REQUEUE_DELAY_SEC="0.01",
        CONTROLLER_SWEEP_SEC="0.2",
        # Durable telemetry (ISSUE 20): the primary persists samples here
        # and the promoted standby reopens the same store — pre-kill
        # history must stay queryable after the flip.
        TSDB_DIR=os.path.join(tmp, "tsdb"),
        TSDB_INTERVAL="0.2",
        INCIDENT_DIR=os.path.join(tmp, "incidents"),
    )
    primary = subprocess.Popen(
        [sys.executable, "-m", "agent_tpu.controller.server"],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    standby: Optional[HotStandby] = None
    standby_server: Optional[ControllerServer] = None
    promoted: Optional[Controller] = None
    agents: List[Agent] = []
    threads: List[threading.Thread] = []
    kills = 0
    succeeded_at_kill = 0
    prekill_walls: List[float] = []
    try:
        if not wait_for_status(url_a, 20.0):
            problems.append(f"seed {seed}: primary never became healthy")
            return problems
        standby = HotStandby(
            journal_path, journal=JOURNAL_CFG, poll_interval_sec=0.02,
            sweep_interval_sec=0.2, lease_ttl_sec=3.0, max_attempts=10,
            requeue_delay_sec=0.01,
            # Same durable store the primary writes; the replica defers
            # opening it (HotStandby sets tsdb_defer_open) until promotion.
            obs=ObsConfig(
                tsdb_dir=env["TSDB_DIR"], tsdb_interval_sec=0.2,
                incident_dir=env["INCIDENT_DIR"],
            ),
        ).start()

        agents = [
            make_agent(f"fo-{seed}-{i}", urls) for i in range(args.agents)
        ]
        threads = [
            threading.Thread(target=a.run, name=f"fo-agent-{i}",
                             daemon=True)
            for i, a in enumerate(agents)
        ]
        for t in threads:
            t.start()

        shard_ids, reduce_id = submit_bulk_http(
            url_a, csv_path, shards, rows_per_shard, args.sleep_ms
        )
        submitter = SingleSubmitter(
            urls, seed, args.singles, args.submit_window_sec
        ).start()

        # ---- the drill: seeded controller_kill once enough is in flight
        kill_floor = max(1, int(shards * args.kill_after_frac))
        force_deadline = time.monotonic() + args.kill_deadline_sec
        while kills == 0:
            try:
                status, body = http_json(url_a + "/v1/status", timeout=2)
                by_op = (body or {}).get("counts_by_op", {})
                shards_done = by_op.get("slow_risk", {}).get(
                    "succeeded", 0
                )
            except Exception:  # noqa: BLE001 — primary gone early?
                problems.append(
                    f"seed {seed}: primary unreachable before the kill"
                )
                break
            # Armed once the drain is genuinely IN FLIGHT (shard
            # successes, not singles — the mid-drain guarantee); forced
            # once the window starts closing or the deadline passes, so
            # the drill happens even when the seeded draws come up short.
            armed = shards_done >= kill_floor
            forced = (
                time.monotonic() > force_deadline
                or shards_done >= max(kill_floor + 1, int(shards * 0.6))
            )
            if armed and (plan.decide("controller_kill") or forced):
                # Snapshot the primary's durable history moments before
                # the kill: these exact samples must still be queryable
                # from the promoted standby (same TSDB_DIR).
                try:
                    _, ts_body = http_json(
                        url_a + "/v1/timeseries"
                        "?name=controller_queue_depth&since=600",
                        timeout=2,
                    )
                    for s in (ts_body or {}).get("series", []):
                        prekill_walls.extend(
                            w for w, _v in s.get("points", [])
                        )
                except Exception:  # noqa: BLE001 — capture best-effort
                    pass
                primary.send_signal(signal.SIGKILL)
                primary.wait(timeout=10)
                kills += 1
                succeeded_at_kill = shards_done
                if forced and not plan.counts.get("controller_kill"):
                    # Deterministic backstop, still counted as the fault.
                    plan.counts["controller_kill"] = \
                        plan.counts.get("controller_kill", 0) + 1
                break
            time.sleep(0.05)
        if kills == 0:
            return problems
        if succeeded_at_kill >= shards:
            problems.append(
                f"seed {seed}: kill landed too late "
                f"({succeeded_at_kill} >= {shards} shards done) — not a "
                "mid-drain drill; raise --sleep-ms"
            )

        # ---- promotion: the standby becomes the controller on url_b
        promoted = standby.promote()
        standby_server = ControllerServer(
            promoted, host="127.0.0.1", port=port_b
        ).start()

        submitter.join(timeout=args.submit_window_sec + 60.0)
        expected = set(shard_ids) | {reduce_id} | set(submitter.submitted)
        n_jobs = len(expected)
        if len(submitter.submitted) != args.singles:
            problems.append(
                f"seed {seed}: only {len(submitter.submitted)}/"
                f"{args.singles} singles submitted across the flip"
            )

        deadline = time.monotonic() + args.deadline_sec
        while time.monotonic() < deadline and not promoted.drained():
            time.sleep(0.05)
        if not promoted.drained():
            problems.append(
                f"seed {seed}: failover drain did not complete "
                f"(counts {promoted.counts()})"
            )
            return problems

        # ---- zero lost work, bit-identical output ----
        counts = promoted.counts()
        if counts.get("failed") or counts.get("dead"):
            problems.append(
                f"seed {seed}: failed/dead jobs after failover: {counts}"
            )
        if counts.get("succeeded", 0) != n_jobs:
            problems.append(
                f"seed {seed}: {counts.get('succeeded', 0)} succeeded != "
                f"{n_jobs} submitted (lost work)"
            )
        for jid in expected:
            try:
                snap = promoted.job_snapshot(jid)
            except KeyError:
                problems.append(
                    f"seed {seed}: job {jid} lost across the flip"
                )
                continue
            if snap["state"] != "succeeded":
                problems.append(
                    f"seed {seed}: job {jid} state {snap['state']!r}"
                )
        reduce_job = promoted.job_snapshot(reduce_id)
        got = canonical(reduce_job["result"])
        if got != reference.get("reduce"):
            problems.append(
                f"seed {seed}: reduce diverged across the flip\n"
                f"  want {reference.get('reduce')}\n  got  {got}"
            )

        # ---- zero double-billing (double-application would show here) --
        if promoted.usage is None:
            problems.append(f"seed {seed}: usage ledger disabled")
        else:
            billed = promoted.usage.billed_tasks
            if billed != n_jobs:
                problems.append(
                    f"seed {seed}: usage billed {billed} != jobs {n_jobs} "
                    "(lost or double-billed work)"
                )
            multi = {
                jid: n
                for jid, n in promoted.usage.job_billed_attempts().items()
                if n != 1
            }
            if multi:
                problems.append(
                    f"seed {seed}: jobs billed != once: "
                    f"{dict(list(multi.items())[:5])}"
                )

        # ---- the failover machinery actually engaged ----
        failovers = 0
        for a in agents:
            snap = a.obs.snapshot()
            for s in snap.get("controller_failovers_total", {}).get(
                "series", []
            ):
                failovers += int(s.get("value", 0))
        if failovers < args.agents:
            problems.append(
                f"seed {seed}: only {failovers} agent failovers "
                f"(expected >= {args.agents} — every agent must rotate)"
            )
        if promoted.promotions != 1:
            problems.append(
                f"seed {seed}: promotions {promoted.promotions} != 1"
            )
        if load_snapshot(journal_path) is None:
            problems.append(
                f"seed {seed}: no compacting snapshot landed during the "
                "run (SNAPSHOT_EVERY_EVENTS never fired?)"
            )
        n_segments = len(list_segments(journal_path))
        if n_segments > 200:
            problems.append(
                f"seed {seed}: {n_segments} journal segments on disk — "
                "compaction is not collecting covered segments"
            )

        # ---- the promoted /v1/status journal block over real HTTP ----
        status, body = http_json(url_b + "/v1/status", timeout=3)
        jblock = (body or {}).get("journal", {})
        if status != 200 or jblock.get("promotions") != 1:
            problems.append(
                f"seed {seed}: standby /v1/status journal block wrong: "
                f"HTTP {status} {jblock}"
            )
        for key in ("segments", "bytes", "last_snapshot_age_sec",
                    "last_replay_sec"):
            if key not in jblock:
                problems.append(
                    f"seed {seed}: journal status block missing {key!r}"
                )

        # ---- durable telemetry survives the flip (ISSUE 20): samples the
        # dead primary persisted are queryable from the promoted standby
        # over real HTTP, out of the reopened on-disk store. ----
        if prekill_walls:
            status, ts_body = http_json(
                url_b + "/v1/timeseries"
                "?name=controller_queue_depth&since=3600",
                timeout=3,
            )
            post_walls = set()
            for s in (ts_body or {}).get("series", []):
                post_walls.update(w for w, _v in s.get("points", []))
            missing = [w for w in prekill_walls if w not in post_walls]
            if status != 200 or (ts_body or {}).get("source") != "tsdb":
                problems.append(
                    f"seed {seed}: promoted /v1/timeseries history not "
                    f"served from the durable store: HTTP {status} "
                    f"source={(ts_body or {}).get('source')!r}"
                )
            elif missing:
                problems.append(
                    f"seed {seed}: {len(missing)}/{len(prekill_walls)} "
                    "pre-kill telemetry samples lost across promotion "
                    f"(e.g. wall {missing[0]})"
                )
        else:
            problems.append(
                f"seed {seed}: no pre-kill telemetry captured — the "
                "primary's TSDB never produced samples before the kill"
            )

        # ---- retire the fleet through the drain path ----
        for a in agents:
            a.request_drain(reason="soak done")
        for t in threads:
            t.join(timeout=15)
        leftover = [len(a.spool) for a in agents if len(a.spool)]
        if leftover:
            problems.append(
                f"seed {seed}: agents left spooled results: {leftover}"
            )

        # ---- the healed journal replays to the identical state ----
        live = {}
        for jid in expected:
            try:
                live[jid] = promoted.job_snapshot(jid)
            except KeyError:
                pass  # already recorded as lost above
        live_billed = promoted.usage.billed_tasks \
            if promoted.usage is not None else 0
        live_attempts = promoted.usage.job_billed_attempts() \
            if promoted.usage is not None else {}
        standby_server.stop()
        standby_server = None
        promoted.close()
        replayed = Controller(journal_path=journal_path, journal=JOURNAL_CFG)
        try:
            if replayed.journal_torn_tail or replayed.journal_replay_skipped:
                problems.append(
                    f"seed {seed}: journal replay damage after the flip "
                    f"(torn {replayed.journal_torn_tail}, skipped "
                    f"{replayed.journal_replay_skipped}) — promotion "
                    "failed to seal the torn tail"
                )
            if replayed.queue_depth() != 0:
                problems.append(
                    f"seed {seed}: replayed queue depth "
                    f"{replayed.queue_depth()} != 0"
                )
            for jid, want in live.items():
                try:
                    got_snap = replayed.job_snapshot(jid)
                except KeyError:
                    problems.append(
                        f"seed {seed}: job {jid} lost in final replay"
                    )
                    continue
                for k in ("state", "job_epoch", "attempts"):
                    if got_snap[k] != want[k]:
                        problems.append(
                            f"seed {seed}: replay {jid} {k} "
                            f"{got_snap[k]!r} != live {want[k]!r}"
                        )
                        break
            if replayed.usage is not None:
                if replayed.usage.billed_tasks != live_billed:
                    problems.append(
                        f"seed {seed}: replayed ledger billed "
                        f"{replayed.usage.billed_tasks} != live "
                        f"{live_billed}"
                    )
                if replayed.usage.job_billed_attempts() != live_attempts:
                    problems.append(
                        f"seed {seed}: replayed per-job billing diverged"
                    )
        finally:
            replayed.close()
        promoted = None

        print(json.dumps({
            "scenario": "controller_failover", "seed": seed,
            "jobs": n_jobs, "singles": len(submitter.submitted),
            "duplicate_acks": submitter.duplicate_acks,
            "controller_kills": kills,
            "plan_counts": plan.counts,
            "agent_failovers": failovers,
            "torn_sealed_bytes": standby.torn_sealed_bytes,
            "snapshots": jblock.get("snapshots_written"),
            "segments": n_segments,
            "counts": counts, "ok": not problems,
        }, sort_keys=True))
        return problems
    finally:
        for a in agents:
            a.request_drain(reason="cleanup")
        for t in threads:
            t.join(timeout=10)
        if standby is not None:
            standby.stop()
        if standby_server is not None:
            standby_server.stop()
        if promoted is not None:
            promoted.close()
        if primary.poll() is None:
            primary.kill()
            primary.wait(timeout=10)


def start_partition_proc(
    name: str, port: int, journal_path: str, extra_env: Dict[str, str],
) -> subprocess.Popen:
    env = dict(os.environ)
    env.update(
        PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        CONTROLLER_HOST="127.0.0.1",
        CONTROLLER_PORT=str(port),
        CONTROLLER_JOURNAL=journal_path,
        CONTROLLER_PARTITION=name,
        JOURNAL_SEGMENT_MAX_BYTES=str(JOURNAL_CFG.segment_max_bytes),
        SNAPSHOT_EVERY_EVENTS=str(JOURNAL_CFG.snapshot_every_events),
        LEASE_TTL_SEC="3",
        MAX_ATTEMPTS="10",
        REQUEUE_DELAY_SEC="0.01",
        CONTROLLER_SWEEP_SEC="0.2",
    )
    env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-m", "agent_tpu.controller.server"],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def run_partition_kill(
    tmp: str, csv_path: str, shards: int, rows_per_shard: int, seed: int,
    args: Any, reference: Dict[str, Any],
) -> List[str]:
    """The ISSUE 18 drill: N partition subprocesses behind one stateless
    router; SIGKILL the partition that owns the bulk job mid-drain.
    Survivors must keep granting leases (never stall), the killed
    partition's jobs requeue on restart via journal replay, and the end
    state is bit-identical / billed-exactly-once across the union of the
    partitions' journals."""
    problems: List[str] = []
    n = args.partitions
    names = [f"p{i}" for i in range(n)]
    ports = {name: free_port() for name in names}
    urls = {name: f"http://127.0.0.1:{ports[name]}" for name in names}
    journals = {
        name: os.path.join(tmp, f"journal.{name}.jsonl") for name in names
    }
    procs: Dict[str, subprocess.Popen] = {}
    router = None
    agents: List[Agent] = []
    threads: List[threading.Thread] = []
    try:
        for name in names:
            procs[name] = start_partition_proc(
                name, ports[name], journals[name], {}
            )
        for name in names:
            if not wait_for_status(urls[name], 20.0):
                problems.append(
                    f"seed {seed}: partition {name} never became healthy"
                )
                return problems

        from agent_tpu.controller.partition import PartitionMap
        from agent_tpu.controller.router import RouterServer
        from agent_tpu.sched.steal import StealPolicy

        pmap = PartitionMap({name: (urls[name],) for name in names})
        router = RouterServer(
            pmap, steal=StealPolicy(enabled=True, min_advantage=1),
            depth_cache_sec=0.1,
        ).start()

        agents = [
            make_agent(f"pk-{seed}-{i}", [router.url])
            for i in range(args.agents)
        ]
        threads = [
            threading.Thread(target=a.run, name=f"pk-agent-{i}",
                             daemon=True)
            for i, a in enumerate(agents)
        ]
        for t in threads:
            t.start()

        # TWO bulk CSVs on two different partitions: bulk A's home is the
        # kill target (the partition with the most to lose mid-drain);
        # bulk B keeps a SURVIVOR partition busy across the kill so the
        # never-stall assertion measures real survivor progress, not an
        # accidentally-empty fleet. Same rows, so both reduces must match
        # the calm reference bit for bit. CSV placement keys on
        # source_uri, so the B home is picked client-side by filename.
        from agent_tpu.controller.partition import placement_key

        home_a = pmap.ring.place(placement_key(None, f"csv\x1f{csv_path}"))
        csv_b = None
        for i in range(1000):
            cand = os.path.join(tmp, f"rows_b{i}.csv")
            if pmap.ring.place(
                placement_key(None, f"csv\x1f{cand}")
            ) != home_a:
                csv_b = cand
                break
        if csv_b is None:
            problems.append(
                f"seed {seed}: could not place a second bulk off {home_a}"
            )
            return problems
        import shutil

        shutil.copyfile(csv_path, csv_b)

        def submit_bulk(path: str) -> Tuple[List[str], str, str]:
            status, body = http_json(router.url + "/v1/jobs", {
                "source_uri": path,
                "total_rows": shards * rows_per_shard,
                "shard_size": rows_per_shard,
                "map_op": "slow_risk",
                "extra_payload": {
                    "field": "risk", "sleep_ms": args.sleep_ms,
                },
                "reduce_op": "risk_accumulate",
                "collect_partials": True,
            })
            if status != 200:
                raise RuntimeError(
                    f"bulk submit via router failed: HTTP {status} {body}"
                )
            return body["job_ids"], body["reduce_id"], body["partition"]

        try:
            shard_ids_a, reduce_a, victim = submit_bulk(csv_path)
            shard_ids_b, reduce_b, home_b = submit_bulk(csv_b)
        except RuntimeError as exc:
            problems.append(f"seed {seed}: {exc}")
            return problems
        if victim != home_a or home_b == victim or victim not in names:
            problems.append(
                f"seed {seed}: placement disagrees with the router "
                f"(computed A={home_a} B!={home_a}, stamped A={victim} "
                f"B={home_b}) — the hash is not deterministic across "
                "processes"
            )
            return problems
        shard_ids = shard_ids_a + shard_ids_b
        n_bulk_shards = len(shard_ids)
        submitter = SingleSubmitter(
            [router.url], seed, args.singles, args.submit_window_sec
        ).start()

        # ---- SIGKILL bulk A's home partition once mid-drain ----
        plan = FaultPlan(seed=seed, controller_kill=args.kill_prob)
        kill_floor = max(1, int(n_bulk_shards * args.kill_after_frac))
        force_deadline = time.monotonic() + args.kill_deadline_sec
        kills = 0
        succeeded_at_kill = 0
        while kills == 0:
            try:
                status, body = http_json(
                    router.url + "/v1/status", timeout=3
                )
                by_op = (body or {}).get("counts_by_op", {})
                shards_done = by_op.get("slow_risk", {}).get(
                    "succeeded", 0
                )
            except Exception:  # noqa: BLE001 — router must stay up
                problems.append(
                    f"seed {seed}: router unreachable before the kill"
                )
                return problems
            armed = shards_done >= kill_floor
            forced = (
                time.monotonic() > force_deadline
                or shards_done >= max(
                    kill_floor + 1, int(n_bulk_shards * 0.6)
                )
            )
            if armed and (plan.decide("controller_kill") or forced):
                procs[victim].send_signal(signal.SIGKILL)
                procs[victim].wait(timeout=10)
                kills += 1
                succeeded_at_kill = shards_done
                break
            time.sleep(0.05)
        if succeeded_at_kill >= n_bulk_shards:
            problems.append(
                f"seed {seed}: partition kill landed too late "
                f"({succeeded_at_kill} >= {n_bulk_shards} shards done) — "
                "raise --sleep-ms"
            )

        # ---- survivors never stall: succeeded counts on the surviving
        # partitions keep climbing while the victim is dark ----
        def survivor_succeeded() -> int:
            total = 0
            _, sbody = http_json(router.url + "/v1/status", timeout=3)
            for row in (sbody or {}).get("partitions", []):
                if row.get("name") != victim and row.get("ok"):
                    total += int(
                        (row.get("counts") or {}).get("succeeded", 0)
                    )
            return total

        base = survivor_succeeded()
        stall_deadline = time.monotonic() + args.survivor_window_sec
        survivor_latency = None
        while time.monotonic() < stall_deadline:
            if survivor_succeeded() > base:
                survivor_latency = round(
                    args.survivor_window_sec
                    - (stall_deadline - time.monotonic()), 3,
                )
                break
            time.sleep(0.05)
        if survivor_latency is None:
            problems.append(
                f"seed {seed}: surviving partitions stalled — no new "
                f"successes within {args.survivor_window_sec}s of the "
                f"{victim} kill"
            )

        # ---- the killed partition restarts over its own journal:
        # replay requeues its in-flight jobs (epoch-fenced), spooled
        # results redeliver through the router's lease-id tag ----
        procs[victim] = start_partition_proc(
            victim, ports[victim], journals[victim], {}
        )
        if not wait_for_status(urls[victim], 20.0):
            problems.append(
                f"seed {seed}: killed partition {victim} never came back"
            )
            return problems

        submitter.join(timeout=args.submit_window_sec + 60.0)
        expected = (
            set(shard_ids) | {reduce_a, reduce_b}
            | set(submitter.submitted)
        )
        n_jobs = len(expected)
        if len(submitter.submitted) != args.singles:
            problems.append(
                f"seed {seed}: only {len(submitter.submitted)}/"
                f"{args.singles} singles submitted across the kill"
            )

        deadline = time.monotonic() + args.deadline_sec
        drained = False
        while time.monotonic() < deadline:
            _, sbody = http_json(router.url + "/v1/status", timeout=3)
            if (sbody or {}).get("drained"):
                drained = True
                break
            time.sleep(0.1)
        if not drained:
            _, sbody = http_json(router.url + "/v1/status", timeout=3)
            problems.append(
                f"seed {seed}: partitioned drain did not complete "
                f"(counts {(sbody or {}).get('counts')})"
            )
            # Name the stuck jobs and where they live — a chaos drill
            # that fails with a bare count is undebuggable.
            for jid in sorted(expected):
                _, jsnap = http_json(
                    router.url + f"/v1/jobs/{jid}", timeout=3
                )
                state = (jsnap or {}).get("state")
                if state != "succeeded":
                    problems.append(
                        f"seed {seed}:   stuck {jid}: state {state!r}"
                    )
            for name in names:
                _, ps = http_json(urls[name] + "/v1/status", timeout=3)
                _, pd = http_json(urls[name] + "/v1/depth", timeout=3)
                problems.append(
                    f"seed {seed}:   {name} counts="
                    f"{(ps or {}).get('counts')} depth={pd}"
                )
            return problems

        # ---- both reduces bit-identical, via the by-id fan-out ----
        for tag, rid in (("A", reduce_a), ("B", reduce_b)):
            status, snap = http_json(
                router.url + f"/v1/jobs/{rid}", timeout=5
            )
            if status != 200 or snap.get("state") != "succeeded":
                problems.append(
                    f"seed {seed}: reduce {tag} {rid} HTTP {status} state "
                    f"{(snap or {}).get('state')!r}"
                )
                continue
            got = canonical(snap["result"])
            if got != reference.get("reduce"):
                problems.append(
                    f"seed {seed}: reduce {tag} diverged across the "
                    f"partition kill\n  want {reference.get('reduce')}\n"
                    f"  got  {got}"
                )

        router_stats = router.core.stats()

        # ---- retire the fleet, then flush any spooled results ----
        for a in agents:
            a.request_drain(reason="partition drill done")
        for t in threads:
            t.join(timeout=15)
        leftover = [len(a.spool) for a in agents if len(a.spool)]
        if leftover:
            problems.append(
                f"seed {seed}: agents left spooled results: {leftover}"
            )

        # ---- final per-partition journal replay: the union of the
        # partitions' journals is the fleet state — every job terminal
        # on exactly one partition, billed exactly once, no torn/skipped
        # lines (restart sealed the SIGKILL's torn death write) ----
        for name in names:
            procs[name].terminate()
            procs[name].wait(timeout=10)
        states: Dict[str, str] = {}
        owners: Dict[str, List[str]] = {}
        billed_total = 0
        for name in names:
            replayed = Controller(
                partition=name, journal_path=journals[name],
                journal=JOURNAL_CFG,
            )
            try:
                if (replayed.journal_torn_tail
                        or replayed.journal_replay_skipped):
                    problems.append(
                        f"seed {seed}: {name} journal damage after the "
                        f"drill (torn {replayed.journal_torn_tail}, "
                        f"skipped {replayed.journal_replay_skipped}) — "
                        "restart failed to seal the torn tail"
                    )
                if replayed.queue_depth() != 0:
                    problems.append(
                        f"seed {seed}: {name} replayed queue depth "
                        f"{replayed.queue_depth()} != 0"
                    )
                for jid in expected:
                    try:
                        jsnap = replayed.job_snapshot(jid)
                    except KeyError:
                        continue
                    owners.setdefault(jid, []).append(name)
                    states[jid] = jsnap["state"]
                if replayed.usage is not None:
                    billed_total += replayed.usage.billed_tasks
                    multi = {
                        jid: cnt for jid, cnt in
                        replayed.usage.job_billed_attempts().items()
                        if cnt != 1
                    }
                    if multi:
                        problems.append(
                            f"seed {seed}: {name} billed != once: "
                            f"{dict(list(multi.items())[:5])}"
                        )
            finally:
                replayed.close()
        lost = [jid for jid in expected if jid not in owners]
        if lost:
            problems.append(
                f"seed {seed}: {len(lost)} job(s) on no partition "
                f"journal (lost): {sorted(lost)[:5]}"
            )
        double = {jid: ps for jid, ps in owners.items() if len(ps) > 1}
        if double:
            problems.append(
                f"seed {seed}: jobs applied on multiple partitions: "
                f"{dict(list(double.items())[:5])}"
            )
        bad_state = {
            jid: s for jid, s in states.items() if s != "succeeded"
        }
        if bad_state:
            problems.append(
                f"seed {seed}: non-terminal jobs after the drill: "
                f"{dict(list(bad_state.items())[:5])}"
            )
        if billed_total != n_jobs:
            problems.append(
                f"seed {seed}: fleet billed {billed_total} != jobs "
                f"{n_jobs} (lost or double-billed work)"
            )

        print(json.dumps({
            "scenario": "partition_kill", "seed": seed,
            "partitions": n, "victim": victim,
            "jobs": n_jobs, "singles": len(submitter.submitted),
            "duplicate_acks": submitter.duplicate_acks,
            "survivor_first_success_sec": survivor_latency,
            "router": router_stats, "ok": not problems,
        }, sort_keys=True))
        return problems
    finally:
        for a in agents:
            a.request_drain(reason="cleanup")
        for t in threads:
            t.join(timeout=10)
        if router is not None:
            router.stop()
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--seeds", type=str, default="",
                    help="comma-separated seed list (overrides --seed)")
    ap.add_argument("--shards", type=int, default=24)
    ap.add_argument("--rows-per-shard", type=int, default=40)
    ap.add_argument("--singles", type=int, default=40,
                    help="seeded echo singles submitted across the flip")
    ap.add_argument("--agents", type=int, default=3)
    ap.add_argument("--kill-prob", type=float, default=0.35,
                    help="per-tick controller_kill probability once armed")
    ap.add_argument("--kill-after-frac", type=float, default=0.25,
                    help="arm the kill once this fraction of shards "
                         "succeeded (mid-drain, not at the edges)")
    ap.add_argument("--kill-deadline-sec", type=float, default=25.0,
                    help="force the kill by this deadline if the seeded "
                         "draws came up short")
    ap.add_argument("--submit-window-sec", type=float, default=6.0)
    ap.add_argument("--sleep-ms", type=float, default=120.0,
                    help="per-shard service time — what keeps the drain "
                         "in flight long enough to kill mid-drain")
    ap.add_argument("--deadline-sec", type=float, default=90.0)
    ap.add_argument("--partitions", type=int, default=1,
                    help="> 1 runs the ISSUE 18 partition_kill drill: N "
                         "partition subprocesses behind one stateless "
                         "router; the bulk job's home partition is "
                         "SIGKILLed mid-drain and restarted over its "
                         "journal. 1 (default) keeps the classic "
                         "single-controller standby-promotion drill.")
    ap.add_argument("--survivor-window-sec", type=float, default=5.0,
                    help="partition_kill: surviving partitions must land "
                         "a NEW success within this window of the kill "
                         "(the never-stall bar)")
    ap.add_argument("--quick", action="store_true",
                    help="CI sizing: shrinks the workload for < 90 s")
    args = ap.parse_args(argv)

    if args.quick:
        args.shards = min(args.shards, 16)
        args.rows_per_shard = min(args.rows_per_shard, 30)
        args.singles = min(args.singles, 24)
        args.submit_window_sec = min(args.submit_window_sec, 4.0)
        args.deadline_sec = min(args.deadline_sec, 60.0)

    seeds = (
        [int(s) for s in args.seeds.split(",") if s.strip()]
        if args.seeds else [args.seed]
    )

    # The throttled map op, loaded through the designed plugin channel.
    tmp_root = tempfile.mkdtemp(prefix="failover_soak_plugin_")
    plugin_path = os.path.join(tmp_root, "slow_risk_plugin.py")
    with open(plugin_path, "w", encoding="utf-8") as f:
        f.write(PLUGIN_SRC)
    from agent_tpu.ops import load_plugins

    if "slow_risk" not in load_plugins(plugin_path):
        from agent_tpu.ops import OPS_LOAD_ERRORS

        print(f"slow_risk plugin failed to load: {OPS_LOAD_ERRORS}")
        return 1

    problems: List[str] = []
    t0 = time.monotonic()
    for seed in seeds:
        with tempfile.TemporaryDirectory(
            prefix=f"failover_soak_{seed}_"
        ) as tmp:
            csv_path = os.path.join(tmp, "rows.csv")
            build_csv(csv_path, args.shards * args.rows_per_shard)
            reference, ref_problems = run_reference(
                tmp, csv_path, args.shards, args.rows_per_shard, seed,
                args,
            )
            problems += ref_problems
            if not ref_problems and args.partitions > 1:
                problems += run_partition_kill(
                    tmp, csv_path, args.shards, args.rows_per_shard,
                    seed, args, reference,
                )
            elif not ref_problems:
                problems += run_failover(
                    tmp, csv_path, args.shards, args.rows_per_shard,
                    seed, args, reference,
                )
    elapsed = round(time.monotonic() - t0, 3)
    if problems:
        for p in problems:
            print(p)
        print(f"FAILED: {len(problems)} problem(s) in {elapsed}s")
        return 1
    drill = (
        f"partition_kill x{args.partitions}" if args.partitions > 1
        else "controller failover"
    )
    print(
        f"{drill} soak: OK ({len(seeds)} seed(s), "
        f"{args.shards} shards, {elapsed}s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
