#!/usr/bin/env python3
"""CI smoke for the distributed-tracing pipeline (ISSUE 5).

Drains a small multi-shard CSV map-reduce plus two ``compile_probe`` jobs
(a smoke-local plugin op whose cold ``ExecutableCache`` build emits an
``xla.compile`` span) through the real ``Agent`` loop over
``chaos.LoopbackSession``, then asserts the acceptance criteria end to end:

1. every terminal job's ``GET /v1/trace/{job_id}`` is a single-rooted,
   causally consistent (gap-free: no orphans, no open spans) tree covering
   submit → sched.decide → lease → stage → execute → post → apply;
2. the Perfetto export (``?format=perfetto``) round-trips through JSON and
   passes ``validate_chrome_trace`` — the schema the legacy Perfetto
   importer requires;
3. at least one ``xla.compile`` span lands on the cold-cache probe run, and
   the warm re-run stays a cache hit (counters prove it);
4. the ``/v1/metrics`` exposition validates and its ``task_phase_seconds``
   buckets carry OpenMetrics exemplars whose trace_ids all resolve to jobs
   this smoke actually submitted;
5. tracing is pay-for-what-you-use: rows/sec over a CSV map-reduce drain
   (1024-row shards — 8x smaller than the 8192-row shards real drains
   use, so the bound is conservative) with tracing on stays within 10% of
   tracing off (best-of-5 each way, interleaved — best-of damps the
   scheduler noise that dwarfs the ~2% true overhead on shared runners);
6. ``scripts/chaos_soak.py --quick`` still reconciles with tracing enabled
   (subprocess, ``TRACE_ENABLED=1``).

Exit 0 = clean; 1 = problems (one per line). Style sibling of
``scripts/check_metrics_endpoint.py``: repo-rooted, zero external deps
(jax is optional — the probe's build falls back to a host callable).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from agent_tpu.agent.app import Agent
from agent_tpu.chaos import LoopbackSession
from agent_tpu.config import AgentConfig, Config
from agent_tpu.controller.core import Controller
from agent_tpu.controller.server import ControllerServer
from agent_tpu.obs import trace as obs_trace
from agent_tpu.obs.metrics import parse_exemplars, validate_exposition
from agent_tpu.obs.trace import validate_chrome_trace

SHARDS = 8
ROWS_PER_SHARD = 10
# submit → … → apply: the causal chain every drained job must show.
REQUIRED_SPANS = (
    "submit", "sched.decide", "lease", "stage", "execute", "post", "apply",
)
BENCH_SHARDS = 24
BENCH_ROWS_PER_SHARD = 1024
BENCH_ROUNDS = 5
BENCH_TOLERANCE = 0.90  # tracing-on rows/sec must stay within 10% of off

# The probe op ships through the designed extension point (OPS_PLUGIN_PATH
# / load_plugins) rather than monkey-patching the registry. Its build runs
# inside the agent's ambient TraceContext, so the emitted span parents to
# the triggering job's execute span — the same path a real op's
# runtime.compiled() miss takes.
PLUGIN_SRC = '''\
"""Smoke-only op: a cold ExecutableCache build per distinct payload n."""
import time

from agent_tpu.ops import register_op
from agent_tpu.runtime.executor import ExecutableCache

_CACHE = ExecutableCache()


@register_op("compile_probe")
def run(payload, ctx=None):
    t0 = time.perf_counter()
    n = int(payload.get("n", 8))

    def build():
        try:
            import jax
            import jax.numpy as jnp

            fn = jax.jit(lambda x: (x * 2.0 + 1.0).sum())
            fn(jnp.zeros((n,), jnp.float32))  # the actual XLA compile

            def call():
                return float(fn(jnp.arange(n, dtype=jnp.float32)))
        except Exception:  # jax-less host: the cache path is still the test

            def call():
                return float(sum(2.0 * i + 1.0 for i in range(n)))

        return call

    t1 = time.perf_counter()
    fn = _CACHE.get_or_build(("compile_probe", n), build)
    value = fn()
    t2 = time.perf_counter()
    if ctx is not None:
        # Stamp phase timings per the op contract (see
        # map_classify_tpu.CONTRACT.md): the serial loop turns these into
        # task_phase_seconds observations carrying the job exemplar.
        ctx.tags.setdefault("timings", {}).update({
            "stage_ms": (t1 - t0) * 1000.0,
            "device_ms": (t2 - t1) * 1000.0,
        })
    return {
        "ok": True,
        "value": value,
        "compute_time_ms": (time.perf_counter() - t0) * 1000.0,
    }
'''


def build_csv(path: str, rows: int) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text,risk\n")
        for i in range(rows):
            f.write(f'{i},"record {i}",{(i % 13) * 0.5}\n')


def make_agent(
    controller: Controller, tasks: Tuple[str, ...], max_tasks: int = 4
) -> Agent:
    cfg = Config(agent=AgentConfig(
        controller_url="http://loopback", agent_name="trace-smoke",
        tasks=tasks, max_tasks=max_tasks,
        idle_sleep_sec=0.0, error_backoff_sec=0.0,
    ))
    agent = Agent(config=cfg, session=LoopbackSession(controller))
    agent._profile = {"tier": "trace-smoke"}  # skip hardware probing
    return agent


def drain(controller: Controller, agent: Agent, deadline_s: float = 60.0
          ) -> bool:
    """Run the real lease/execute/post loop until drained; sweeps on idle
    so dep-gated reduce jobs release. Final metrics-only flush ships the
    tail spans (the last post span postdates its own post)."""
    deadline = time.monotonic() + deadline_s
    while not controller.drained() and time.monotonic() < deadline:
        leased = agent.lease_once()
        if leased is None:
            controller.sweep()
            continue
        lease_id, tasks = leased
        for task in tasks:
            agent.run_task(lease_id, task)
    agent.push_metrics()
    return controller.drained()


def check_trace_trees(controller: Controller, job_ids: List[str],
                      problems: List[str]) -> None:
    for jid in job_ids:
        t = controller.trace_json(jid)
        if t is None:
            problems.append(f"job {jid}: no trace assembled")
            continue
        if not t["complete"]:
            problems.append(
                f"job {jid}: trace not gap-free (roots={t['roots']}, "
                f"orphans={t['orphans']}, open={t['open_spans']})"
            )
        names = {s["name"] for s in t["spans"]}
        missing = [n for n in REQUIRED_SPANS if n not in names]
        if missing:
            problems.append(f"job {jid}: missing spans {missing}")
        # causal consistency: every non-root parent id resolves in-trace
        ids = {s["span_id"] for s in t["spans"]}
        for s in t["spans"]:
            p = s.get("parent_span_id")
            if p is not None and p not in ids:
                problems.append(
                    f"job {jid}: span {s['name']} dangles from {p}"
                )


def check_http_surface(controller: Controller, job_id: str,
                       problems: List[str]) -> None:
    with ControllerServer(controller) as server:
        with urllib.request.urlopen(
            f"{server.url}/v1/trace/{job_id}"
        ) as r:
            body = json.load(r)
        if not body.get("complete"):
            problems.append("/v1/trace over HTTP lost completeness")
        with urllib.request.urlopen(
            f"{server.url}/v1/trace/{job_id}?format=perfetto"
        ) as r:
            raw = r.read().decode()
        perfetto = json.loads(raw)  # "the export loads": JSON round-trip
        schema = validate_chrome_trace(perfetto)
        if schema:
            problems.append(f"perfetto export schema problems: {schema}")
        if not any(
            e.get("ph") == "X" for e in perfetto.get("traceEvents", [])
        ):
            problems.append("perfetto export carries no X events")
        with urllib.request.urlopen(f"{server.url}/v1/traces?limit=4") as r:
            listing = json.load(r).get("traces", [])
        if len(listing) != 4:
            problems.append(f"/v1/traces?limit=4 returned {len(listing)}")


def check_exemplars(controller: Controller, job_ids: List[str],
                    problems: List[str]) -> None:
    text = controller.metrics_text()
    problems += validate_exposition(text)
    exemplars = parse_exemplars(text)
    phase_ex = exemplars.get("task_phase_seconds_bucket", [])
    if not phase_ex:
        problems.append("task_phase_seconds buckets carry no exemplars")
    known = set(job_ids)
    for _labels, ex_labels, _v in (
        e for samples in exemplars.values() for e in samples
    ):
        jid = ex_labels.get("trace_id")
        if jid not in known:
            problems.append(f"exemplar references unknown job {jid!r}")


def drain_rows_per_sec(csv_path: str) -> float:
    rows = BENCH_SHARDS * BENCH_ROWS_PER_SHARD
    controller = Controller(lease_ttl_sec=30.0)
    controller.submit_csv_job(
        csv_path, total_rows=rows, shard_size=BENCH_ROWS_PER_SHARD,
        map_op="risk_accumulate", extra_payload={"field": "risk"},
        reduce_op="risk_accumulate", collect_partials=True,
    )
    agent = make_agent(controller, tasks=("risk_accumulate",), max_tasks=8)
    t0 = time.perf_counter()
    while not controller.drained():
        leased = agent.lease_once()
        if leased is None:
            controller.sweep()
            continue
        lease_id, tasks = leased
        for task in tasks:
            agent.run_task(lease_id, task)
    return rows / (time.perf_counter() - t0)


def main() -> int:
    problems: List[str] = []
    obs_trace.set_enabled(True)  # host env must not decide phase 1
    with tempfile.TemporaryDirectory(prefix="trace_smoke_") as tmp:
        plugin_path = os.path.join(tmp, "compile_probe_plugin.py")
        with open(plugin_path, "w", encoding="utf-8") as f:
            f.write(PLUGIN_SRC)
        from agent_tpu.ops import load_plugins

        if "compile_probe" not in load_plugins(plugin_path):
            from agent_tpu.ops import OPS_LOAD_ERRORS

            print(f"compile_probe plugin failed to load: {OPS_LOAD_ERRORS}")
            return 1

        csv_path = os.path.join(tmp, "rows.csv")
        build_csv(csv_path, SHARDS * ROWS_PER_SHARD)
        controller = Controller(lease_ttl_sec=30.0)
        shard_ids, reduce_id = controller.submit_csv_job(
            csv_path,
            total_rows=SHARDS * ROWS_PER_SHARD,
            shard_size=ROWS_PER_SHARD,
            map_op="risk_accumulate",
            extra_payload={"field": "risk"},
            reduce_op="risk_accumulate",
            collect_partials=True,
        )
        cold_probe = controller.submit("compile_probe", {"n": 16})
        warm_probe = controller.submit("compile_probe", {"n": 16})
        job_ids = list(shard_ids) + [reduce_id, cold_probe, warm_probe]

        agent = make_agent(
            controller, tasks=("risk_accumulate", "compile_probe")
        )
        if not drain(controller, agent):
            print(f"drain did not complete (counts {controller.counts()})")
            return 1
        counts = controller.counts()
        if counts.get("failed") or counts.get("dead"):
            problems.append(f"failed/dead jobs in the smoke drain: {counts}")

        check_trace_trees(controller, job_ids, problems)

        # Cold cache ⇒ exactly one xla.compile span, on the first probe.
        compile_spans = [
            s for jid in (cold_probe, warm_probe)
            for s in (controller.traces.spans(jid) or [])
            if s["name"] == "xla.compile"
        ]
        if not compile_spans:
            problems.append("no xla.compile span on the cold-cache run")
        elif compile_spans[0]["trace_id"] != cold_probe:
            problems.append("xla.compile span attributed to the wrong job")
        if any(s["trace_id"] == warm_probe for s in compile_spans):
            problems.append("warm probe re-compiled (cache hit expected)")
        cache = agent.obs.counter(
            "runtime_compile_cache_total", "", ("op", "outcome")
        )
        if cache.value(op="compile_probe", outcome="miss") != 1:
            problems.append("compile cache miss counter != 1")
        if cache.value(op="compile_probe", outcome="hit") != 1:
            problems.append("compile cache hit counter != 1")

        check_http_surface(controller, reduce_id, problems)
        check_exemplars(controller, job_ids, problems)

    # 5. overhead bound: best-of-N rows/sec over the CSV drain, tracing
    # off vs on, interleaved so machine drift hits both modes alike.
    with tempfile.TemporaryDirectory(prefix="trace_bench_") as tmp:
        bench_csv = os.path.join(tmp, "bench.csv")
        build_csv(bench_csv, BENCH_SHARDS * BENCH_ROWS_PER_SHARD)
        best = {False: 0.0, True: 0.0}
        for _ in range(BENCH_ROUNDS):
            for mode in (False, True):
                obs_trace.set_enabled(mode)
                best[mode] = max(best[mode], drain_rows_per_sec(bench_csv))
    obs_trace.set_enabled(None)  # restore the env-driven default
    ratio = best[True] / best[False] if best[False] else 0.0
    print(
        f"tracing overhead: off {best[False]:.0f} rows/s, "
        f"on {best[True]:.0f} rows/s (ratio {ratio:.3f})"
    )
    if ratio < BENCH_TOLERANCE:
        problems.append(
            f"tracing-on drain rate {best[True]:.0f} rows/s is below "
            f"{BENCH_TOLERANCE:.0%} of tracing-off {best[False]:.0f} rows/s"
        )

    # 6. the chaos soak still reconciles with tracing forced on.
    env = dict(os.environ, TRACE_ENABLED="1")
    soak = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_soak.py"),
         "--seed", "7", "--shards", "8", "--quick"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    if soak.returncode != 0:
        tail = (soak.stdout + soak.stderr).strip().splitlines()[-8:]
        problems.append(
            "chaos_soak --quick failed with TRACE_ENABLED=1: "
            + " | ".join(tail)
        )

    if problems:
        for p in problems:
            print(p)
        print(f"FAILED: {len(problems)} problem(s)")
        return 1
    print("trace pipeline smoke check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
