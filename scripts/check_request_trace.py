#!/usr/bin/env python3
"""CI smoke for request-level serving observability (ISSUE 17).

Drives seeded mixed interactive traffic against a REAL HTTP controller +
real pipelined agents — colocated AND disaggregated — and asserts the
observability acceptance bar end to end:

1. STITCHED TRACES: every completed request resolves via
   ``GET /v1/trace/{req_id}`` to one complete span tree (root ``infer``,
   ``bucket.wait``, the six synthesized ``ttft.*`` component spans, and
   ``decode``) whose span links pull the coalesced batch job's trace —
   and, on the disaggregated path, the ``serve_prefill`` job's trace —
   inline under ``linked_traces``;
2. GAP-FREE DECOMPOSITION: the six TTFT components
   (bucket_wait → queue_wait → prefill → handoff → kv_wait →
   first_decode) telescope — their sum matches the measured TTFT within
   10% on every completed request, both paths;
3. TAIL SAMPLING: with ``SERVE_REQLOG_SAMPLE=0.0`` (healthy sampling
   OFF), the wide-event request log still retains 100% of injected
   failures (``kept="error"``) while dropping the healthy mid-pack;
4. OVERHEAD: a 1024-row serving smoke with instrumentation ON
   (``TRACE_ENABLED=1``) stays within 5% of the throughput of the same
   smoke with tracing OFF — per-request observability must not tax the
   serving path.

CPU-shape smoke (tiny models, JAX_PLATFORMS=cpu). Exit 0 = all bars met.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

TINY_S2S = {
    "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
    "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
}
TINY_CLS = {
    "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
    "max_len": 64, "dtype": "float32", "n_classes": 16,
}
SEED = 17
N_MIXED = 24           # colocated leg: seeded classify/summarize mix
N_DISAGG = 8           # disagg leg: shared-prefix summarize requests
N_HEALTHY = 60         # sampling leg: healthy traffic past warmup
N_FAILING = 10         # sampling leg: injected failures
OVERHEAD_ROWS = 1024   # overhead leg: serving rows per timed run
OVERHEAD_TOL = 0.05    # instrumentation tax bound (ISSUE 17 acceptance)
OVERHEAD_ATTEMPTS = 3  # noisy-runner retries — any attempt under the bar
COMPONENTS = (
    "bucket_wait", "queue_wait", "prefill", "handoff", "kv_wait",
    "first_decode",
)


class Stack:
    """One live serving stack: HTTP controller + in-process agents."""

    def __init__(self, serve, agent_specs):
        import requests

        from agent_tpu.agent.app import Agent
        from agent_tpu.agent.pipeline import PipelineRunner
        from agent_tpu.config import AgentConfig, Config
        from agent_tpu.controller.core import Controller
        from agent_tpu.controller.server import ControllerServer
        from agent_tpu.ops.serve_infer import reset_engines

        reset_engines()
        self.controller = Controller(lease_ttl_sec=600.0, serve=serve)
        self.server = ControllerServer(self.controller).start()
        self.url = self.server.url
        self.sess = requests.Session()
        self.agents, self.threads = [], []
        for name, tasks in agent_specs:
            cfg = Config(agent=AgentConfig(
                controller_url=self.url, agent_name=name,
                tasks=tasks, idle_sleep_sec=0.0,
            ))
            a = Agent(config=cfg, session=requests.Session())
            a._profile = {"tier": "smoke"}
            runner = PipelineRunner(a, depth=2)
            th = threading.Thread(target=runner.run, daemon=True)
            th.start()
            self.agents.append(a)
            self.threads.append(th)

    def infer(self, body, timeout=600):
        r = self.sess.post(self.url + "/v1/infer", json=body,
                           timeout=timeout)
        assert r.status_code == 200, (r.status_code, r.text)
        return r.json()

    def get_json(self, path, timeout=60):
        r = self.sess.get(self.url + path, timeout=timeout)
        assert r.status_code == 200, (path, r.status_code, r.text)
        return r.json()

    def wait_all(self, req_ids, want="done"):
        snaps = []
        for rid in req_ids:
            snap = self.controller.wait_infer(rid, 300.0)
            assert snap is not None and snap["state"] == want, (rid, snap)
            snaps.append(snap)
        return snaps

    def records(self, **params):
        qs = "&".join(f"{k}={v}" for k, v in params.items())
        doc = self.get_json(f"/v1/debug/requests?{qs}")
        assert doc["enabled"] is True, doc
        return doc

    def close(self):
        for a in self.agents:
            a.running = False
        for th in self.threads:
            th.join(timeout=60)
        self.server.stop()


def assert_decomposed(rec):
    """Bar 2: the component chain telescopes to the measured TTFT."""
    comps = rec.get("components") or {}
    missing = [c for c in COMPONENTS if not isinstance(
        comps.get(c), (int, float))]
    assert not missing, (rec["req_id"], f"components missing {missing}")
    ttft = rec.get("ttft_ms")
    assert isinstance(ttft, (int, float)) and ttft >= 0, rec
    total = sum(comps[c] for c in COMPONENTS)
    # 10% relative, 1ms absolute floor (sub-ms TTFTs judge rounding noise).
    tol = max(1.0, 0.10 * ttft)
    assert abs(total - ttft) <= tol, (
        f"{rec['req_id']}: components sum {total:.3f}ms vs "
        f"ttft {ttft:.3f}ms (tol {tol:.3f}ms) — gap in the stitched chain"
    )


def assert_stitched(stack, rec, want_prefill):
    """Bar 1: GET /v1/trace/{req_id} is one complete tree linked into the
    coalesced batch job (and the prefill job on the disagg path)."""
    rid = rec["req_id"]
    doc = stack.get_json(f"/v1/trace/{rid}")
    assert doc.get("complete") is True, (rid, doc.get("orphans"), doc)
    names = {s["name"] for s in doc["spans"]}
    want = {"infer", "bucket.wait"} | {f"ttft.{c}" for c in COMPONENTS}
    assert want <= names, (rid, f"spans missing {sorted(want - names)}")
    linked = {t["trace_id"] for t in doc.get("linked_traces") or []}
    assert rec.get("job_id") in linked, (
        f"{rid}: batch job {rec.get('job_id')} not stitched in "
        f"(linked: {sorted(linked)})"
    )
    if want_prefill:
        assert rec.get("prefill_job_id") in linked, (
            f"{rid}: prefill job {rec.get('prefill_job_id')} not stitched "
            f"into the disagg trace (linked: {sorted(linked)})"
        )


def colocated_leg():
    """Bars 1+2 on the colocated path: seeded classify/summarize mix."""
    from agent_tpu.config import ServeConfig

    rng = random.Random(SEED)
    stack = Stack(
        ServeConfig(max_wait_ms=10.0, max_batch=4),
        [("smoke-colo", ("serve_classify", "serve_summarize"))],
    )
    try:
        for op, params in (
            ("classify", {"model_config": TINY_CLS, "topk": 2}),
            ("summarize", {"model_config": TINY_S2S, "max_length": 4}),
        ):
            out = stack.infer({"op": op, "text": "warm the serving path",
                               "params": params})
            assert out["state"] == "done", out
        rids = []
        for i in range(N_MIXED):
            if rng.random() < 0.4:
                body = {"op": "classify",
                        "text": f"mixed classify {i} " + "pad " * (i % 4),
                        "params": {"model_config": TINY_CLS, "topk": 2}}
            else:
                body = {"op": "summarize",
                        "text": f"mixed summarize {i} "
                                + "payload " * (i % 3 + 1),
                        "params": {"model_config": TINY_S2S,
                                   "max_length": 3 + i % 5}}
            body["wait"] = False
            rids.append(stack.infer(body, timeout=30)["req_id"])
        stack.wait_all(rids)
        recs = {
            r["req_id"]: r
            for r in stack.records(limit=500)["requests"]
        }
        for rid in rids:
            rec = recs.get(rid)
            assert rec is not None, f"{rid}: no wide-event record"
            assert rec["outcome"] == "completed", rec
            assert rec["path"] == "colocated", rec
            assert_decomposed(rec)
            assert_stitched(stack, rec, want_prefill=False)
        return len(rids)
    finally:
        stack.close()


def disagg_leg():
    """Bars 1+2 across the prefill → decode handoff: the stitched trace
    must span both pools, with the prefill job linked in."""
    from agent_tpu.config import ServeConfig

    stack = Stack(
        ServeConfig(max_wait_ms=10.0, max_batch=4, disaggregated=True),
        [("smoke-prefill", ("serve_prefill",)),
         ("smoke-decode", ("serve_decode",))],
    )
    try:
        out = stack.infer({
            "op": "summarize", "text": "warm the serving path",
            "params": {"model_config": TINY_S2S, "max_length": 4},
        })
        assert out["state"] == "done", out
        rids = []
        for i in range(N_DISAGG):
            rids.append(stack.infer({
                "op": "summarize", "wait": False,
                "text": f"disagg shared doc {i % 2} "
                        + "with a common preamble clause " * 4,
                "params": {"model_config": TINY_S2S, "max_length": 5},
            }, timeout=30)["req_id"])
        stack.wait_all(rids)
        recs = {
            r["req_id"]: r
            for r in stack.records(limit=500)["requests"]
        }
        for rid in rids:
            rec = recs.get(rid)
            assert rec is not None, f"{rid}: no wide-event record"
            assert rec["outcome"] == "completed", rec
            assert rec["path"] == "disagg", rec
            assert rec.get("prefill_job_id"), rec
            assert_decomposed(rec)
            assert_stitched(stack, rec, want_prefill=True)
        return len(rids)
    finally:
        stack.close()


def sampling_leg():
    """Bar 3: SERVE_REQLOG_SAMPLE=0.0 — every injected failure survives
    tail sampling, healthy mid-pack traffic does not."""
    from agent_tpu.config import ServeConfig

    stack = Stack(
        ServeConfig(max_wait_ms=5.0, max_batch=8, reqlog_sample=0.0),
        [("smoke-sampling", ("serve_classify",))],
    )
    try:
        out = stack.infer({
            "op": "classify", "text": "warm the serving path",
            "params": {"model_config": TINY_CLS, "topk": 2},
        })
        assert out["state"] == "done", out
        healthy = []
        for i in range(N_HEALTHY):
            healthy.append(stack.infer({
                "op": "classify", "wait": False,
                "text": f"healthy request {i} " + "pad " * (i % 5),
                "params": {"model_config": TINY_CLS, "topk": 2},
            }, timeout=30)["req_id"])
        stack.wait_all(healthy)
        # Failure injection: topk=0 passes front-door validation but the
        # op soft-fails the whole batch, so every rider lands failed (the
        # requests share their own bucket — topk is batch signature).
        failing = []
        for i in range(N_FAILING):
            failing.append(stack.infer({
                "op": "classify", "wait": False,
                "text": f"doomed request {i}",
                "params": {"model_config": TINY_CLS, "topk": 0},
            }, timeout=30)["req_id"])
        stack.wait_all(failing, want="failed")

        doc = stack.records(outcome="failed", limit=500)
        failed = {r["req_id"]: r for r in doc["requests"]}
        lost = [rid for rid in failing if rid not in failed]
        assert not lost, (
            f"tail sampling dropped {len(lost)} of {len(failing)} "
            f"failures at sample=0.0: {lost}"
        )
        for rid in failing:
            assert failed[rid]["kept"] == "error", failed[rid]
        stats = doc["stats"]
        assert stats["sampled_out"] > 0, (
            "sample=0.0 dropped nothing — healthy traffic never faced "
            f"the sampling coin: {stats}"
        )
        return len(failing), stats["sampled_out"]
    finally:
        stack.close()


def _timed_run(rows):
    """One overhead-leg run: `rows` classify requests through /v1/infer,
    wall-clock from first post to last completion."""
    from agent_tpu.config import ServeConfig

    stack = Stack(
        ServeConfig(max_wait_ms=5.0, max_batch=32, max_pending=0),
        [("smoke-overhead", ("serve_classify",))],
    )
    try:
        out = stack.infer({
            "op": "classify", "text": "warm the serving path",
            "params": {"model_config": TINY_CLS, "topk": 1},
        })
        assert out["state"] == "done", out
        t0 = time.monotonic()
        rids = []
        for i in range(rows):
            rids.append(stack.infer({
                "op": "classify", "wait": False,
                "text": f"overhead row {i}",
                "params": {"model_config": TINY_CLS, "topk": 1},
            }, timeout=30)["req_id"])
        stack.wait_all(rids)
        wall = time.monotonic() - t0
        return rows / wall
    finally:
        stack.close()


def overhead_leg():
    """Bar 4: instrumentation ON within OVERHEAD_TOL of tracing OFF.
    Noisy CI runners get OVERHEAD_ATTEMPTS interleaved on/off pairs and
    the best observed rate per mode — one stalled run must not fail the
    build, a real per-request tax shows up in every pair."""
    from agent_tpu.obs import trace

    best_on = best_off = 0.0
    try:
        for attempt in range(1, OVERHEAD_ATTEMPTS + 1):
            trace.set_enabled(False)
            best_off = max(best_off, _timed_run(OVERHEAD_ROWS))
            trace.set_enabled(True)
            best_on = max(best_on, _timed_run(OVERHEAD_ROWS))
            overhead = 1.0 - best_on / best_off
            print(
                f"[request-trace-smoke] overhead attempt {attempt}: "
                f"on {best_on:.0f} rows/s vs off {best_off:.0f} rows/s "
                f"({overhead:+.1%})", flush=True,
            )
            if best_on >= best_off * (1.0 - OVERHEAD_TOL):
                return best_on, best_off, 1.0 - best_on / best_off
        raise AssertionError(
            f"instrumentation overhead {1.0 - best_on / best_off:.1%} "
            f"exceeds {OVERHEAD_TOL:.0%} after {OVERHEAD_ATTEMPTS} "
            f"attempts (on {best_on:.0f} vs off {best_off:.0f} rows/s)"
        )
    finally:
        trace.set_enabled(None)  # restore the TRACE_ENABLED env check


def main() -> int:
    t_start = time.monotonic()
    print("[request-trace-smoke] colocated leg ...", flush=True)
    n_colo = colocated_leg()
    print("[request-trace-smoke] disaggregated leg ...", flush=True)
    n_disagg = disagg_leg()
    print("[request-trace-smoke] tail-sampling leg ...", flush=True)
    n_errors, n_dropped = sampling_leg()
    print("[request-trace-smoke] overhead leg ...", flush=True)
    rps_on, rps_off, overhead = overhead_leg()
    print(
        f"[request-trace-smoke] OK: {n_colo} colocated + {n_disagg} disagg "
        f"requests stitched and decomposed within 10%, "
        f"{n_errors}/{n_errors} errors kept at sample=0.0 "
        f"({n_dropped} healthy sampled out), "
        f"overhead {overhead:+.1%} at {OVERHEAD_ROWS} rows "
        f"(on {rps_on:.0f} vs off {rps_off:.0f} rows/s), "
        f"wall {time.monotonic() - t_start:.1f}s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
