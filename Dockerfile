# Container image for the agent (successor of reference Dockerfile:1-39).
# The reference installed the Coral Edge TPU runtime (libedgetpu1-std) from
# the Coral APT repo; on Cloud TPU the native runtime is libtpu, delivered as
# a Python wheel via the jax[tpu] extra — no APT layer needed.

FROM python:3.12-slim

ENV PYTHONUNBUFFERED=1 \
    PYTHONDONTWRITEBYTECODE=1 \
    PIP_DISABLE_PIP_VERSION_CHECK=1

# g++ compiles the optional native CSV scanner (agent_tpu/data/native) at
# first use; the agent degrades to the vectorized-numpy scanner without it.
RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ \
 && rm -rf /var/lib/apt/lists/*

WORKDIR /app

COPY pyproject.toml requirements.txt README.md ./
COPY agent_tpu ./agent_tpu

# TPU wheel index hosts libtpu (the successor of the reference's Coral extra
# index, reference Dockerfile:25-30). Harmless off-TPU: jax falls back to cpu.
RUN python -m pip install --no-cache-dir \
      -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
      "jax[tpu]>=0.9" && \
    python -m pip install --no-cache-dir .[metrics]

# Same default env surface as the reference (Dockerfile:35-36).
ENV CONTROLLER_URL="http://controller:8080"
ENV AGENT_NAME="agent-tpu-base"
ENV TASKS="echo,map_classify_tpu"

CMD ["python", "-m", "agent_tpu.agent.app"]
