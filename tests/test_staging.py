"""Staging pool + autotuner (ISSUE 6): regulator math, the adjustable
gate, multi-worker drains bit-identical to single-worker, the sentinel
contract, and the double-buffered device feed."""

import queue
import threading
import time

import jax
import numpy as np
import pytest
import requests

from agent_tpu.agent.app import Agent
from agent_tpu.config import AgentConfig, Config, DeviceConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.server import ControllerServer
from agent_tpu.data.staging import (
    AdjustableGate,
    PhaseRatioSampler,
    default_workers,
    desired_workers,
)
from agent_tpu.obs.metrics import MetricsRegistry
from agent_tpu.runtime.runtime import TpuRuntime

TINY = {
    "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
    "max_len": 64, "dtype": "float32", "n_classes": 16,
}


@pytest.fixture(scope="module")
def runtime():
    return TpuRuntime(
        config=DeviceConfig(tpu_disabled=True, mesh_shape={"dp": 8}),
        devices=jax.devices("cpu"),
    )


# ---------------------------------------------------------------------------
# Regulator math + primitives
# ---------------------------------------------------------------------------


def test_desired_workers_tracks_the_stage_execute_ratio():
    # Stage cheaper than execute → one worker suffices.
    assert desired_workers(0.01, 0.05, 4) == 1
    # Stage 2.5× execute → 3 workers to hide it.
    assert desired_workers(0.25, 0.10, 8) == 3
    # Clamped at the pool size.
    assert desired_workers(1.0, 0.01, 4) == 4
    # Device starving with no execute signal → saturate.
    assert desired_workers(0.2, 0.0, 4) == 4
    # Nothing measured → stay serial.
    assert desired_workers(0.0, 0.0, 4) == 1
    assert 1 <= default_workers() <= 4


def test_adjustable_gate_limits_and_retunes():
    gate = AdjustableGate(2)
    assert gate.acquire(0.01) and gate.acquire(0.01)
    assert not gate.acquire(0.01)  # at the limit
    gate.set_limit(3)
    assert gate.acquire(0.01)      # widened live
    gate.release()
    gate.set_limit(1)
    assert not gate.acquire(0.01)  # narrowed below the active count
    gate.release()
    gate.release()
    assert gate.acquire(0.01)


def test_phase_ratio_sampler_windows_the_registry():
    reg = MetricsRegistry()
    hist = reg.histogram("task_phase_seconds", "t", ("op", "phase"))
    sampler = PhaseRatioSampler(reg)
    assert sampler.sample() is None  # nothing recorded yet
    for _ in range(4):
        hist.observe(0.2, op="a", phase="stage")
        hist.observe(0.05, op="a", phase="execute")
    stage_s, exec_s = sampler.sample()
    assert stage_s == pytest.approx(0.2)
    assert exec_s == pytest.approx(0.05)
    # The next window is a DELTA: two fresh samples are below the minimum.
    hist.observe(0.3, op="a", phase="stage")
    hist.observe(0.3, op="a", phase="execute")
    assert sampler.sample() is None


# ---------------------------------------------------------------------------
# Drains through the real pipeline
# ---------------------------------------------------------------------------


def _csv(tmp_path, n=96):
    path = tmp_path / "rows.csv"
    lines = ["id,text"]
    for i in range(n):
        lines.append(f'{i},"staging pool row {i} with text"')
    path.write_text("\n".join(lines) + "\n", encoding="utf-8")
    return str(path)


def _drain(controller, server, runtime, workers, autotune=False,
           double_buffer=True, depth=2):
    from agent_tpu.agent.pipeline import PipelineRunner

    cfg = Config(agent=AgentConfig(
        controller_url=server.url, agent_name=f"pool-{workers}",
        tasks=("map_classify_tpu",), idle_sleep_sec=0.0,
    ))
    agent = Agent(config=cfg, session=requests.Session(), runtime=runtime)
    agent._profile = {"tier": "test"}
    agent.running = True

    def watch():
        deadline = time.time() + 120
        while not controller.drained() and time.time() < deadline:
            time.sleep(0.02)
        agent.running = False

    watcher = threading.Thread(target=watch, daemon=True)
    watcher.start()
    PipelineRunner(
        agent, depth=depth, workers=workers, autotune=autotune,
        double_buffer=double_buffer,
    ).run()
    watcher.join(timeout=5)
    return agent


def test_multi_worker_drain_bit_identical_to_single(runtime, tmp_path):
    """The CI acceptance bar in miniature: 4 stage workers + autotune +
    double buffering produce exactly the single-worker results."""
    csv = _csv(tmp_path)
    extra = {"text_field": "text", "allow_fallback": False,
             "result_format": "columnar", "model_config": dict(TINY),
             "topk": 3}

    results = {}
    for workers, autotune in ((1, False), (4, True)):
        controller = Controller()
        controller.submit_csv_job(csv, total_rows=96, shard_size=12,
                                  map_op="map_classify_tpu",
                                  extra_payload=extra)
        with ControllerServer(controller) as server:
            _drain(controller, server, runtime, workers, autotune=autotune)
        assert controller.counts() == {"succeeded": 8}
        results[workers] = {
            controller.job(j).payload["start_row"]: r
            for j, r in controller.results().items()
        }
    assert set(results[1]) == set(results[4])
    for start, want in results[1].items():
        got = results[4][start]
        assert got["indices"] == want["indices"], f"shard @{start}"
        assert got["scores"] == want["scores"], f"shard @{start}"


def test_pool_gauges_and_backlog_advertisement(runtime, tmp_path):
    """The pool exports its knob positions and feeds the scheduler-facing
    queue_depth from the live backlog (staged + awaiting a worker)."""
    csv = _csv(tmp_path, n=48)
    controller = Controller()
    controller.submit_csv_job(csv, total_rows=48, shard_size=12,
                              map_op="map_classify_tpu",
                              extra_payload={"text_field": "text",
                                             "allow_fallback": False,
                                             "model_config": dict(TINY)})
    with ControllerServer(controller) as server:
        agent = _drain(controller, server, runtime, workers=3)
    snap = agent.obs.snapshot()
    assert snap["stage_pool_workers"]["series"][0]["value"] == 3
    assert snap["stage_prefetch_depth"]["series"][0]["value"] >= 2
    assert agent.staged_depth_fn is not None
    assert agent.staged_depth_fn() == 0  # drained


def test_last_worker_owns_the_stop_sentinel():
    """However many workers die in whatever order, the device loop gets
    EXACTLY one stop token — a lost sentinel would hang the device thread,
    a duplicate would kill a later incarnation's loop early."""
    from agent_tpu.data.staging import StagingPool

    class StubAgent:
        running = False  # feeder exits immediately

        class config:
            class agent:
                stage_workers = 3
                stage_autotune = False
                idle_sleep_sec = 0.0

        obs = MetricsRegistry()

    stop = object()
    staged_q = queue.Queue(maxsize=4)
    pool = StagingPool(
        StubAgent(), staged_q, lambda lease_id, task: None, stop,
        max_workers=3, autotune=False,
    )
    pool.start()
    pool.join(timeout=10)
    assert staged_q.get(timeout=1) is stop
    assert staged_q.qsize() == 0


def test_prefeed_places_chunks_on_device(runtime):
    """The double-buffered feed replaces staged numpy chunks with device
    arrays ahead of execute; the op's own put_batch then passes them
    through, and values survive exactly."""
    from agent_tpu.agent.pipeline import PipelineRunner, _Item

    cfg = Config(agent=AgentConfig(tasks=("echo",)))
    agent = Agent.__new__(Agent)
    agent.config = cfg
    agent.runtime = runtime
    runner = PipelineRunner.__new__(PipelineRunner)
    runner.agent = agent

    ids = np.arange(64, dtype=np.uint16).reshape(8, 8)
    lengths = np.full(8, 8, dtype=np.int32)
    item = _Item("l1", "j1", 0, "map_classify_tpu", {}, None, 0.0,
                 staged={"chunks": [(ids, lengths, 8)], "other": "kept"})
    runner._prefeed(item)
    fed_ids, fed_lengths, n = item.staged["chunks"][0]
    assert isinstance(fed_ids, jax.Array) and isinstance(fed_lengths, jax.Array)
    assert n == 8 and item.staged["other"] == "kept"
    np.testing.assert_array_equal(np.asarray(fed_ids), ids)
    # Re-putting an already-placed array is the op's execute path — no-op.
    again = runtime.put_batch(fed_ids)
    np.testing.assert_array_equal(np.asarray(again), ids)

    # Monolithic / failed / resultful items are left alone.
    mono = _Item("l1", "j2", 0, "echo", {}, None, 0.0, monolithic=True)
    runner._prefeed(mono)
    assert mono.staged is None


# ---------------------------------------------------------------------------
# Stage/execute overlap (ISSUE 6 satellite — the drain_at_scale breakdown)
# ---------------------------------------------------------------------------


def test_overlap_from_spans_math():
    from agent_tpu.obs.scrape import overlap_from_spans

    def span(name, start, dur_s):
        return {"name": name, "start_wall": start,
                "duration_ms": dur_s * 1e3}

    # Job B's stage [1, 3) fully inside job A's execute [0, 4): hidden.
    # Job C's stage [5, 7) overlaps execute [6, 8) for half its span.
    spans = [
        span("execute", 0.0, 4.0), span("execute", 6.0, 2.0),
        span("stage", 1.0, 2.0), span("stage", 5.0, 2.0),
        span("post", 0.0, 1.0),          # other phases ignored
        {"name": "stage", "start_wall": 9.0, "duration_ms": None},  # open
    ]
    out = overlap_from_spans(spans)
    assert out["n_stage_spans"] == 2 and out["n_execute_spans"] == 2
    assert out["stage_total_s"] == pytest.approx(4.0)
    assert out["overlap_ratio"] == pytest.approx(3.0 / 4.0)
    assert out["stage_p50_ms"] == pytest.approx(2000.0)
    # No closed spans of both kinds → None (drain_at_scale fails loudly).
    assert overlap_from_spans([span("stage", 0, 1)]) is None
    assert overlap_from_spans([]) is None


def test_stage_execute_overlap_from_a_real_drain(runtime, tmp_path):
    """End-to-end: a pipelined drain's trace window yields an overlap
    breakdown via the HTTP trace endpoints — the exact call
    scripts/drain_at_scale.py makes (and fails loudly on None)."""
    from agent_tpu.obs.scrape import stage_execute_overlap

    csv = _csv(tmp_path, n=48)
    controller = Controller()
    controller.submit_csv_job(csv, total_rows=48, shard_size=12,
                              map_op="map_classify_tpu",
                              extra_payload={"text_field": "text",
                                             "allow_fallback": False,
                                             "model_config": dict(TINY)})
    with ControllerServer(controller) as server:
        _drain(controller, server, runtime, workers=2)
        out = stage_execute_overlap(server.url)
    assert out is not None, "trace window yielded no overlap breakdown"
    assert out["n_stage_spans"] == 4 and out["n_execute_spans"] == 4
    assert 0.0 <= out["overlap_ratio"] <= 1.0
    assert out["stage_p50_ms"] > 0 and out["execute_p50_ms"] > 0
