"""Test environment: force the CPU backend with 8 virtual devices.

Per SURVEY.md §4.3, all mesh/sharding/collective logic is exercised hermetically
on a virtual multi-chip mesh (``--xla_force_host_platform_device_count=8``) so CI
needs no TPU; TPU is a backend switch. This must run before anything imports
jax, hence module-level in conftest.
"""

import os

# Overwrite, not setdefault: the host environment pins JAX_PLATFORMS to the
# real TPU plugin, and tests must be hermetic on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The TPU plugin in this environment re-registers itself regardless of
# JAX_PLATFORMS; the config update below (before any backend use) is what
# actually pins the cpu backend.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_csv(tmp_path):
    """A small CSV with quoted commas and a quoted embedded newline."""
    path = tmp_path / "data.csv"
    rows = ['id,text,risk']
    for i in range(25):
        rows.append(f'{i},"row {i}, text",{i * 0.5}')
    # Row with an embedded newline inside quotes (index 25).
    rows.append('25,"line one\nline two",12.5')
    path.write_text("\n".join(rows) + "\n", encoding="utf-8")
    return str(path)
