"""Binary shard wire (ISSUE 6): codec round trips (seeded-random always-on
plus a hypothesis variant, matching the ``tests/test_sched.py`` pattern),
the JSON-equivalence contract for classify/summarize columns, the
compressed/uncompressed fallback, negotiation in both directions, and a
full JSON↔binary LoopbackSession drain equivalence."""

import json
import random
import string

import numpy as np
import pytest

from agent_tpu.data import wire

# ---------------------------------------------------------------------------
# Codec round trips
# ---------------------------------------------------------------------------


def _random_cols(rng: random.Random):
    """One random column set: arrays (every supported dtype), string lists
    (non-ASCII, empty strings, empty lists), and JSON leftovers."""
    cols = {}
    n = rng.randint(1, 5)
    alphabet = string.ascii_letters + "äöüß日本語🙂 ,\"'\\\n"
    for i in range(n):
        kind = rng.choice(("arr_i", "arr_f", "strs", "json"))
        name = f"c{i}"
        if kind == "arr_i":
            dtype = rng.choice(
                (np.int8, np.int16, np.int32, np.int64, np.uint8, np.uint16)
            )
            info = np.iinfo(dtype)
            shape = rng.choice(((rng.randint(0, 8),),
                                (rng.randint(1, 6), rng.randint(1, 4))))
            cols[name] = np.array(
                [rng.randint(max(info.min, -1000), min(info.max, 1000))
                 for _ in range(int(np.prod(shape)))],
                dtype=dtype,
            ).reshape(shape)
        elif kind == "arr_f":
            dtype = rng.choice((np.float32, np.float64))
            shape = (rng.randint(0, 16),)
            cols[name] = np.array(
                [rng.uniform(-1e6, 1e6) for _ in range(shape[0])], dtype=dtype
            )
        elif kind == "strs":
            cols[name] = [
                "".join(rng.choice(alphabet)
                        for _ in range(rng.randint(0, 40)))
                for _ in range(rng.randint(0, 12))
            ]
        else:
            cols[name] = {
                "k": rng.randint(-5, 5),
                "v": [rng.random(), None, "πλ"],
            }
    return cols


def _expect(cols):
    """What decode must return: arrays tolist()-ed, everything else as-is
    (JSON values round-trip through json semantics)."""
    out = {}
    for k, v in cols.items():
        if isinstance(v, np.ndarray):
            out[k] = v.tolist()
        else:
            out[k] = json.loads(json.dumps(v)) if not (
                isinstance(v, list) and all(isinstance(t, str) for t in v)
            ) else v
    return out


def test_round_trip_seeded_random():
    for seed in range(40):
        rng = random.Random(seed)
        cols = _random_cols(rng)
        compress = rng.choice((None, True, False))
        got = wire.decode_blob(wire.encode_blob(cols, compress=compress))
        assert got == _expect(cols), f"seed {seed} (compress={compress})"


def test_round_trip_hypothesis():
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    @hyp.settings(deadline=None, max_examples=50)
    @hyp.given(seed=st.integers(min_value=0, max_value=2**31),
               compress=st.sampled_from((None, True, False)))
    def run(seed, compress):
        cols = _random_cols(random.Random(seed))
        got = wire.decode_blob(wire.encode_blob(cols, compress=compress))
        assert got == _expect(cols)

    run()


def test_compression_flag_and_fallback():
    """Adaptive compression keeps zlib only when it shrinks; the flag byte
    records which body the blob carries and both decode identically."""
    repetitive = {"texts": ["the same line"] * 200}
    blob = wire.encode_blob(repetitive)
    assert blob[2] & 0x01, "repetitive text should have compressed"
    assert len(blob) < len(json.dumps(repetitive["texts"]))

    raw = wire.encode_blob(repetitive, compress=False)
    assert not raw[2] & 0x01
    assert wire.decode_blob(raw) == wire.decode_blob(blob)

    # High-entropy bytes (uint8 of a seeded RNG) must not be bloated by a
    # futile zlib pass: adaptive falls back to the uncompressed body.
    noise = {"v": np.frombuffer(random.Random(3).randbytes(4096), np.uint8)}
    adaptive = wire.encode_blob(noise)
    forced = wire.encode_blob(noise, compress=True)
    assert not adaptive[2] & 0x01
    assert len(adaptive) <= len(forced) + 16
    assert wire.decode_blob(adaptive) == wire.decode_blob(forced)


def test_malformed_blobs_raise_value_error():
    good = wire.encode_blob({"a": [1, 2]})
    for bad in (b"", b"XX\x00", good[:-3], b"AW\x01notzlib",
                good[:2] + b"\x01" + b"\x00" * 4):
        with pytest.raises(ValueError):
            wire.decode_blob(bad)
    with pytest.raises(ValueError):
        wire.unpack_b64("!!! not base64 !!!")
    with pytest.raises(ValueError):
        wire.unpack_b64(12345)  # type: ignore[arg-type]


def test_int_width_shrink_preserves_values():
    arr = np.array([[0, 1], [126, -127]], dtype=np.int32)
    blob = wire.encode_blob({"i": arr}, compress=False)
    # int8 on the wire (1 byte/value) but the SAME Python ints back.
    assert wire.decode_blob(blob)["i"] == arr.tolist()
    small = len(blob)
    wide = len(wire.encode_blob(
        {"i": np.array([[0, 1], [126, 1 << 20]], np.int32)}, compress=False))
    assert small < wide


# ---------------------------------------------------------------------------
# JSON-equivalence of the op column shapes
# ---------------------------------------------------------------------------


def test_classify_columns_match_json_path_bitwise():
    """The binary classify result decodes to EXACTLY the lists the JSON
    finalize would have produced: same np.round(f32, 6) → widen floats,
    same ints."""
    rng = np.random.default_rng(11)
    vals = rng.random((64, 5), dtype=np.float32)
    idx = rng.integers(0, 1000, (64, 5)).astype(np.int32)
    json_shape = {
        "indices": np.asarray(idx).tolist(),
        "scores": np.round(np.asarray(vals), 6).tolist(),
    }
    result = wire.attach_result_columns(
        {"ok": True, "op": "map_classify_tpu"},
        {"indices": np.ascontiguousarray(idx),
         "scores": np.round(np.asarray(vals), 6)},
    )
    decoded = wire.decode_result(result)
    assert decoded["indices"] == json_shape["indices"]
    assert decoded["scores"] == json_shape["scores"]
    assert "__bin__" not in decoded


def test_summarize_columns_round_trip_with_empty_and_non_ascii():
    summaries = ["ein Résumé 🙂", "", "plain", "改行\nあり"]
    result = wire.attach_result_columns(
        {"ok": True, "op": "map_summarize", "summary": summaries[0]},
        {"summaries": summaries},
    )
    decoded = wire.decode_result(result)
    assert decoded["summaries"] == summaries
    assert decoded["summary"] == summaries[0]


def test_task_payload_round_trip_and_empty_shard():
    payload = {
        "texts": ["ä", "", "long row " * 50],
        "topk": 3, "result_format": "columnar",
        "model_config": {"d_model": 32}, "allow_fallback": False,
    }
    enc = wire.encode_task_payload(payload)
    assert set(enc) == {"__bin__"}
    assert wire.decode_task_payload(enc) == payload
    # Empty texts (an empty shard) round-trips too — encodable_task refuses
    # to encode it (nothing to gain), but the codec itself must not choke.
    empty = {"texts": [], "topk": 1}
    assert wire.decode_task_payload(wire.encode_task_payload(empty)) == empty
    assert not wire.encodable_task("map_classify_tpu", empty)
    assert not wire.encodable_task("echo", payload)
    assert wire.encodable_task("map_classify_tpu", payload)
    assert wire.encodable_task("map_summarize", payload)


# ---------------------------------------------------------------------------
# Negotiation + full LoopbackSession drain equivalence
# ---------------------------------------------------------------------------

TINY = {
    "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
    "max_len": 64, "dtype": "float32", "n_classes": 16,
}

TINY_S2S = {
    "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
    "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
}


def _drain_loopback(wire_binary_controller=True, wire_binary_agent=True,
                    observe=None):
    """Submit one classify (texts payload, columnar) + one summarize job,
    drain through the real serial agent loop over a LoopbackSession, and
    return (controller, classify_result, summarize_result)."""
    from agent_tpu.agent.app import Agent
    from agent_tpu.chaos import LoopbackSession
    from agent_tpu.config import AgentConfig, Config
    from agent_tpu.controller.core import Controller

    controller = Controller(wire_binary=wire_binary_controller)
    texts = [f"wire équivalence row {i} 🙂" for i in range(24)]
    c_id = controller.submit("map_classify_tpu", {
        "texts": texts, "topk": 3, "result_format": "columnar",
        "model_config": dict(TINY), "allow_fallback": False,
    })
    s_id = controller.submit("map_summarize", {
        "texts": texts[:8], "max_length": 6,
        "model_config": dict(TINY_S2S),
    })
    session = LoopbackSession(controller)
    if observe is not None:
        session = observe(session)
    cfg = Config(agent=AgentConfig(
        controller_url="http://loopback", agent_name="wire-test",
        tasks=("map_classify_tpu", "map_summarize"),
        idle_sleep_sec=0.0, wire_binary=wire_binary_agent, max_tasks=2,
    ))
    agent = Agent(config=cfg, session=session)
    agent._profile = {"tier": "test"}
    for _ in range(16):
        if controller.drained():
            break
        agent.step()
    assert controller.drained(), controller.counts()
    return (
        controller,
        controller.job_snapshot(c_id)["result"],
        controller.job_snapshot(s_id)["result"],
    )


class _Recorder:
    """Session wrapper that records every posted body and returned lease."""

    def __init__(self, inner):
        self.inner = inner
        self.posted = []
        self.leases = []

    def post(self, url, json=None, timeout=None):  # noqa: A002
        self.posted.append((url, json))
        resp = self.inner.post(url, json=json, timeout=timeout)
        if url.endswith("/v1/leases") and resp.status_code == 200:
            self.leases.append(resp.json())
        return resp


def test_loopback_drain_json_binary_equivalence():
    """The acceptance bar: a binary-wire drain stores bit-identical results
    to a JSON-wire drain, while the wire itself demonstrably carried the
    envelope (tasks AND results) only in the negotiated case."""
    _, c_json, s_json = _drain_loopback(wire_binary_controller=False)
    rec = {}

    def observing(inner):
        rec["session"] = _Recorder(inner)
        return rec["session"]

    controller, c_bin, s_bin = _drain_loopback(
        wire_binary_controller=True, observe=observing
    )
    assert c_bin["indices"] == c_json["indices"]
    assert c_bin["scores"] == c_json["scores"]
    assert s_bin["summaries"] == s_json["summaries"]
    assert s_bin["summary"] == s_json["summary"]
    # The stored results never expose the envelope…
    assert "__bin__" not in c_bin and "__bin__" not in s_bin
    # …but the wire actually carried it: negotiated grants, encoded task
    # payloads, and binary result bodies.
    session = rec["session"]
    assert any(body.get("wire") == "b1" for body in session.leases)
    wired_tasks = [
        t for body in session.leases for t in body.get("tasks", [])
        if wire.is_binary_payload(t.get("payload"))
    ]
    assert wired_tasks, "no task payload was binary-encoded"
    wired_results = [
        b for url, b in session.posted
        if url.endswith("/v1/results") and wire.is_binary_result(b.get("result"))
    ]
    assert wired_results, "no result body was binary-encoded"
    snap = controller.metrics.snapshot()
    series = {
        s["labels"]["direction"]: s["value"]
        for s in snap.get("controller_wire_total", {}).get("series", [])
    }
    assert series.get("task", 0) >= 1
    assert series.get("result", 0) >= 2


def test_json_only_agent_against_binary_controller():
    """Opt-in is bilateral: a WIRE_BINARY=0 agent never advertises, so a
    binary-capable controller keeps the whole exchange plain JSON."""
    rec = {}

    def observing(inner):
        rec["session"] = _Recorder(inner)
        return rec["session"]

    _, c_res, s_res = _drain_loopback(
        wire_binary_controller=True, wire_binary_agent=False,
        observe=observing,
    )
    session = rec["session"]
    assert all("wire" not in body for body in session.leases)
    assert all(
        not wire.is_binary_payload(t.get("payload"))
        for body in session.leases for t in body.get("tasks", [])
    )
    assert all(
        not wire.is_binary_result(b.get("result"))
        for url, b in session.posted if url.endswith("/v1/results")
    )
    assert isinstance(c_res["indices"], list)
    assert isinstance(s_res["summaries"], list)


def test_undecodable_result_envelope_is_counted_not_fatal():
    from agent_tpu.controller.core import Controller

    c = Controller()
    c.submit("echo", {}, job_id="j1")
    lease = c.lease("a", {"ops": ["echo"]})
    c.report(lease["lease_id"], "j1", 0, "succeeded",
             result={"ok": True, "__bin__": "@@@ corrupt @@@"})
    job = c.job_snapshot("j1")
    assert job["state"] == "succeeded"
    assert job["result"]["__bin__"] == "@@@ corrupt @@@"  # kept, debuggable
    series = {
        s["labels"]["direction"]: s["value"]
        for s in c.metrics.snapshot()["controller_wire_total"]["series"]
    }
    assert series.get("result_error") == 1
