"""PP and MoE must be SERVABLE through the op contract, not just provable
in a harness (SURVEY §2.8 "strategies usable by the workload"; VERDICT r3
ask #5): ``model_config: {"pp": 2}`` routes ``map_classify_tpu`` through the
GPipe shard_map schedule, a pp axis on the serving mesh does the same with
no payload change, and ``model_config: {"moe_experts": N}`` serves a Switch
MoE encoder whose experts shard over an ``ep`` mesh axis when present.
Every strategy's outputs must match the plain dense/unsharded forward.
"""

import numpy as np
import pytest

import jax

from agent_tpu.config import DeviceConfig
from agent_tpu.ops import get_op
from agent_tpu.runtime.context import OpContext
from agent_tpu.runtime.runtime import TpuRuntime, get_runtime

BASE_CONFIG = {
    "vocab_size": 260, "d_model": 32, "n_heads": 4, "n_layers": 2,
    "d_ff": 64, "max_len": 64, "n_classes": 16, "dtype": "float32",
}
TEXTS = ["strategy serving row %d" % i for i in range(16)]


def _classify(runtime, model_config):
    out = get_op("map_classify_tpu")(
        {
            "texts": TEXTS,
            "topk": 3,
            "allow_fallback": False,
            "result_format": "columnar",
            "model_config": model_config,
        },
        OpContext(runtime=runtime),
    )
    assert out["ok"] is True, out
    return np.asarray(out["indices"]), np.asarray(out["scores"])


def _mesh_runtime(shape):
    return TpuRuntime(
        config=DeviceConfig(mesh_shape=shape), devices=jax.devices()[:8]
    )


def test_pp_via_model_config_matches_dense():
    """{"pp": 2} on the default (no-pp-axis) mesh: the op derives a dp×pp
    mesh over the same devices and the results equal the pp=1 serve."""
    rt = get_runtime()
    want_idx, want_scores = _classify(rt, BASE_CONFIG)
    got_idx, got_scores = _classify(rt, {**BASE_CONFIG, "pp": 2})
    np.testing.assert_array_equal(got_idx, want_idx)
    np.testing.assert_allclose(got_scores, want_scores, atol=1e-5)


def test_pp_via_mesh_axis_matches_dense():
    """A pp axis on the serving mesh routes through the pipeline with NO
    payload change — the mesh is the config (scaling-book recipe)."""
    rt_pp = _mesh_runtime({"dp": 4, "pp": 2})
    assert rt_pp.axis_size("pp") == 2
    want_idx, want_scores = _classify(get_runtime(), BASE_CONFIG)
    got_idx, got_scores = _classify(rt_pp, BASE_CONFIG)
    np.testing.assert_array_equal(got_idx, want_idx)
    np.testing.assert_allclose(got_scores, want_scores, atol=1e-5)


def test_moe_serves_and_ep_sharding_matches_unsharded():
    """moe_experts=4 serves through the op; an ep=4 mesh (experts sharded,
    all-to-all at dispatch/combine) returns the same results as the
    unsharded MoE on the default mesh."""
    moe_config = {**BASE_CONFIG, "moe_experts": 4}
    want_idx, want_scores = _classify(get_runtime(), moe_config)
    rt_ep = _mesh_runtime({"dp": 2, "ep": 4})
    got_idx, got_scores = _classify(rt_ep, moe_config)
    np.testing.assert_array_equal(got_idx, want_idx)
    np.testing.assert_allclose(got_scores, want_scores, atol=1e-5)


def test_moe_output_differs_from_dense_ffn():
    """The MoE path must actually run experts — not silently fall back to
    the dense FFN (same seed would then give identical logits)."""
    rt = get_runtime()
    dense_idx, dense_scores = _classify(rt, BASE_CONFIG)
    moe_idx, moe_scores = _classify(rt, {**BASE_CONFIG, "moe_experts": 4})
    assert not (
        np.array_equal(moe_idx, dense_idx)
        and np.allclose(moe_scores, dense_scores)
    ), "MoE config produced bit-identical results to the dense FFN"


def test_pp_int8_matches_unpipelined_int8():
    """{"pp": 2, "quant": "int8"} (round-5: the former soft-rejection is a
    serving mode): the pipelined int8 forward runs the SAME quantized ops in
    the same order as the non-pp int8 serve, so results match."""
    rt = get_runtime()
    want_idx, want_scores = _classify(rt, {**BASE_CONFIG, "quant": "int8"})
    got_idx, got_scores = _classify(
        rt, {**BASE_CONFIG, "quant": "int8", "pp": 2}
    )
    np.testing.assert_array_equal(got_idx, want_idx)
    np.testing.assert_allclose(got_scores, want_scores, atol=1e-5)


def test_moe_int8_serves_and_tracks_bf16_moe():
    """{"moe_experts": 4, "quant": "int8"}: expert FFNs run W8A8 with
    per-expert scales (quant.qmoe_expert). The quantized MoE must (a) serve,
    (b) track the unquantized MoE's decisions, and (c) actually differ from
    it bit-wise (else the quant transform silently skipped the experts)."""
    rt = get_runtime()
    moe_config = {**BASE_CONFIG, "moe_experts": 4}
    want_idx, want_scores = _classify(rt, moe_config)
    got_idx, got_scores = _classify(rt, {**moe_config, "quant": "int8"})
    top1_agree = np.mean(got_idx[:, 0] == want_idx[:, 0])
    assert top1_agree >= 0.9, f"top-1 agreement only {top1_agree:.2f}"
    assert not np.array_equal(got_scores, want_scores), (
        "int8 MoE bit-identical to f32 MoE — experts were not quantized"
    )


def test_moe_int8_ep_sharding_matches_unsharded():
    """The quantized MoE over an ep=4 mesh (per-expert int8 tables + scales
    sharded over ep, all-to-all at dispatch/combine) equals the unsharded
    quantized MoE — the ask's 'dryrun serves one quantized ep config',
    pinned as an equality test."""
    moe_int8 = {**BASE_CONFIG, "moe_experts": 4, "quant": "int8"}
    want_idx, want_scores = _classify(get_runtime(), moe_int8)
    rt_ep = _mesh_runtime({"dp": 2, "ep": 4})
    got_idx, got_scores = _classify(rt_ep, moe_int8)
    np.testing.assert_array_equal(got_idx, want_idx)
    np.testing.assert_allclose(got_scores, want_scores, atol=1e-5)


def test_pp_w8a16_matches_unpipelined_w8a16():
    """{"pp": 2, "quant": "w8a16"}: the pipelined weight-only forward runs
    the SAME wdense/wproj ops in the same order as the non-pp w8a16 serve,
    so results match — W8A16 composes with PP the way int8 does."""
    rt = get_runtime()
    want_idx, want_scores = _classify(rt, {**BASE_CONFIG, "quant": "w8a16"})
    got_idx, got_scores = _classify(
        rt, {**BASE_CONFIG, "quant": "w8a16", "pp": 2}
    )
    np.testing.assert_array_equal(got_idx, want_idx)
    np.testing.assert_allclose(got_scores, want_scores, atol=1e-5)


def test_moe_w8a16_serves_and_tracks_bf16_moe():
    """{"moe_experts": 4, "quant": "w8a16"}: expert FFNs run weight-only
    int8 (quant.wmoe_expert) with per-expert scales. The quantized MoE must
    (a) serve, (b) track the unquantized MoE's decisions, and (c) actually
    differ from it bit-wise (else the transform silently skipped the
    experts)."""
    rt = get_runtime()
    moe_config = {**BASE_CONFIG, "moe_experts": 4}
    want_idx, want_scores = _classify(rt, moe_config)
    got_idx, got_scores = _classify(rt, {**moe_config, "quant": "w8a16"})
    top1_agree = np.mean(got_idx[:, 0] == want_idx[:, 0])
    assert top1_agree >= 0.9, f"top-1 agreement only {top1_agree:.2f}"
    assert not np.array_equal(got_scores, want_scores), (
        "w8a16 MoE bit-identical to f32 MoE — experts were not quantized"
    )


def test_moe_w8a16_ep_sharding_matches_unsharded():
    """The W8A16 MoE over an ep=4 mesh (per-expert int8 tables + scales
    sharded over ep, all-to-all at dispatch/combine) equals the unsharded
    W8A16 MoE — the same composition guarantee the int8 mode carries."""
    moe_w8a16 = {**BASE_CONFIG, "moe_experts": 4, "quant": "w8a16"}
    want_idx, want_scores = _classify(get_runtime(), moe_w8a16)
    rt_ep = _mesh_runtime({"dp": 2, "ep": 4})
    got_idx, got_scores = _classify(rt_ep, moe_w8a16)
    np.testing.assert_array_equal(got_idx, want_idx)
    np.testing.assert_allclose(got_scores, want_scores, atol=1e-5)


@pytest.mark.parametrize(
    "bad_config, msg",
    [
        ({"pp": 2, "n_layers": 3}, "not divisible"),
        ({"pp": 2, "moe_experts": 4}, "cannot combine"),
    ],
)
def test_unsupported_strategy_combinations_reject_softly(bad_config, msg):
    out = get_op("map_classify_tpu")(
        {
            "texts": ["x"],
            "model_config": {**BASE_CONFIG, **bad_config},
        },
        OpContext(runtime=get_runtime()),
    )
    assert out["ok"] is False and msg in out["error"], out


@pytest.mark.parametrize(
    "bad_config, msg",
    [
        ({"moe_experts": 4}, "cannot combine"),
        ({"n_layers": 3}, "not divisible"),
    ],
)
def test_mesh_pp_axis_route_enforces_same_guards(bad_config, msg):
    """The mesh-axis pp route (no payload pp at all) must hit the SAME
    strategy guards as model_config {"pp": N} — a pp-mesh worker receiving
    an MoE/odd-depth config must soft-reject, not crash in the jit."""
    rt_pp = _mesh_runtime({"dp": 4, "pp": 2})
    out = get_op("map_classify_tpu")(
        {
            "texts": ["x"],
            "model_config": {**BASE_CONFIG, **bad_config},
        },
        OpContext(runtime=rt_pp),
    )
    assert out["ok"] is False and msg in out["error"], out
