"""Resource accounting & continuous profiling (ISSUE 9): the usage ledger,
the controller time-series ring, host profiler / HBM telemetry / deep-capture
coordination, and their controller+agent integration — including the
CPU-backend edge cases the satellite list names (memory_stats None/partial,
empty window reads, retry/fenced-duplicate billing, journal replay)."""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from agent_tpu.agent.app import Agent
from agent_tpu.chaos import LoopbackSession
from agent_tpu.config import AgentConfig, Config, ObsConfig
from agent_tpu.controller.core import Controller
from agent_tpu.obs.metrics import MetricsRegistry
from agent_tpu.obs.profile import (
    CaptureCoordinator,
    HostProfiler,
    device_memory_stats,
    hbm_totals,
)
from agent_tpu.obs.timeseries import (
    TimeSeriesRing,
    flatten_snapshot,
    points_to_rates,
)
from agent_tpu.obs.usage import UsageLedger, sanitize_usage, stamp_usage


# ---- ledger units ----

class TestUsageLedger:
    def bill_one(self, ledger, job="j1", attempt=1, **usage):
        return ledger.bill(
            job, tenant="t", tier=4, op="op", attempt=attempt,
            usage=usage or {"device_s": 1.0},
        )

    def test_bill_accumulates_and_reports(self):
        led = UsageLedger()
        self.bill_one(led, device_s=2.0, host_s=0.5, rows=10, flops=100.0)
        rep = led.report()
        assert rep["billed_tasks"] == 1
        assert rep["totals"]["device_seconds"] == 2.0
        assert rep["totals"]["host_seconds"] == 0.5
        assert rep["totals"]["rows"] == 10
        assert rep["by_tenant"]["t"]["by_op"]["op"]["flops"] == 100.0
        assert rep["by_tenant"]["t"]["by_tier"]["4"]["tasks"] == 1

    def test_same_attempt_bills_once(self):
        led = UsageLedger()
        assert self.bill_one(led, attempt=1) is not None
        assert self.bill_one(led, attempt=1) is None  # duplicate delivery
        assert led.billed_tasks == 1
        assert led.job_billed_attempts() == {"j1": 1}

    def test_distinct_attempts_bill_separately(self):
        # A failed attempt 1 that produced a structured result and a
        # succeeding attempt 2 BOTH consumed the fleet — both bill; the
        # dedupe key is (job, attempt), not the job.
        led = UsageLedger()
        self.bill_one(led, attempt=1, device_s=1.0)
        self.bill_one(led, attempt=2, device_s=3.0)
        rep = led.report()
        assert led.billed_tasks == 2
        assert rep["totals"]["device_seconds"] == 4.0
        assert rep["top_jobs"][0]["attempts_billed"] == 2

    def test_chips_scale_chip_seconds(self):
        led = UsageLedger()
        self.bill_one(led, device_s=2.0, chips=4)
        rep = led.report()
        assert rep["totals"]["device_seconds"] == 2.0
        assert rep["totals"]["chip_seconds"] == 8.0

    def test_cost_estimate(self):
        led = UsageLedger(cost_per_chip_hour=3.6)
        self.bill_one(led, device_s=1000.0)
        rep = led.report()
        assert rep["totals"]["est_cost"] == 1.0  # 1000s/3600 * 3.6
        assert UsageLedger().report()["totals"]["est_cost"] is None

    def test_wire_bytes_bill(self):
        led = UsageLedger()
        billed = led.bill("j1", tenant="t", tier=0, op="op", attempt=1,
                          usage=None, wire_bytes=512)
        assert billed == {"wire_bytes": 512}
        assert led.report()["totals"]["wire_bytes"] == 512

    def test_nothing_measurable_not_billed(self):
        led = UsageLedger()
        assert led.bill("j1", tenant="t", tier=0, op="op", attempt=1,
                        usage=None, wire_bytes=0) is None
        assert led.billed_tasks == 0

    def test_top_k_ordering(self):
        led = UsageLedger(top_k=2)
        for i, dev in enumerate((1.0, 5.0, 3.0)):
            self.bill_one(led, job=f"j{i}", device_s=dev)
        top = led.report()["top_jobs"]
        assert [e["job_id"] for e in top] == ["j1", "j2"]

    def test_eviction_bound_keeps_expensive(self):
        led = UsageLedger(max_jobs=16)
        for i in range(40):
            self.bill_one(led, job=f"j{i}", device_s=float(i))
        assert len(led.job_billed_attempts()) <= 16
        assert led.evicted_jobs > 0
        # The biggest consumers survive eviction; aggregates never evict.
        assert "j39" in led.job_billed_attempts()
        assert led.report()["totals"]["tasks"] == 40

    def test_sanitize_rejects_hostile_wire(self):
        assert sanitize_usage(None) == {}
        assert sanitize_usage("nope") == {}
        assert sanitize_usage({
            "device_s": float("nan"), "host_s": -1.0, "rows": True,
            "flops": float("inf"), "junk": 5.0, "chips": 2,
        }) == {"chips": 2.0}

    def test_registry_counters(self):
        reg = MetricsRegistry()
        led = UsageLedger(registry=reg)
        led.bill("j1", tenant="a", tier=4, op="x", attempt=1,
                 usage={"device_s": 2.0, "rows": 7})
        snap = reg.snapshot()
        dev = snap["usage_device_seconds_total"]["series"][0]
        assert dev["labels"] == {"tenant": "a", "op": "x"}
        assert dev["value"] == 2.0
        assert snap["usage_rows_total"]["series"][0]["value"] == 7

    def test_stamp_usage_accumulates(self):
        tags: dict = {}
        stamp_usage(tags, device_s=1.0, chips=4)
        stamp_usage(tags, device_s=0.5, host_s=0.25)
        stamp_usage(None, device_s=9.0)  # no ctx — no-op
        assert tags["usage"] == {"device_s": 1.5, "chips": 4.0,
                                 "host_s": 0.25}


# ---- time-series ring units ----

class TestTimeSeriesRing:
    def snap(self, value, name="c_total"):
        return {name: {"type": "counter", "series": [
            {"labels": {"op": "x"}, "value": value},
        ]}}

    def test_interval_gating(self):
        clk = {"t": 0.0}
        ring = TimeSeriesRing(window_sec=100, interval_sec=10,
                              clock=lambda: clk["t"])
        assert ring.maybe_sample(lambda: [self.snap(1)])
        assert not ring.maybe_sample(lambda: [self.snap(2)])  # too soon
        clk["t"] = 10.0
        assert ring.maybe_sample(lambda: [self.snap(2)])
        assert len(ring) == 2

    def test_empty_window_reads(self):
        ring = TimeSeriesRing()
        assert ring.series("anything") == []
        out = ring.query("anything", rate=True)
        assert out["series"] == [] and out["n_samples"] == 0
        ring.sample([self.snap(1)])
        assert ring.series("other_name") == []  # unknown name, non-empty ring

    def test_ring_bound(self):
        clk = {"t": 0.0}
        ring = TimeSeriesRing(window_sec=10, interval_sec=1,
                              clock=lambda: clk["t"])
        for i in range(50):
            clk["t"] = float(i)
            ring.sample([self.snap(i)], now=clk["t"], wall=float(i))
        assert len(ring) <= 11

    def test_rates_clamped_on_reset(self):
        pts = [(0.0, 10.0), (1.0, 20.0), (2.0, 5.0), (3.0, 6.0)]
        rates = points_to_rates(pts)
        assert rates == [(1.0, 10.0), (2.0, 0.0), (3.0, 1.0)]

    def test_label_filter_and_rate_query(self):
        ring = TimeSeriesRing()
        for i, wall in ((0, 0.0), (10, 1.0)):
            ring.sample([{
                "t": {"type": "counter", "series": [
                    {"labels": {"op": "a"}, "value": float(i)},
                    {"labels": {"op": "b"}, "value": float(i * 2)},
                ]},
            }], now=wall, wall=wall)
        out = ring.query("t", {"op": "a"}, rate=True)
        assert len(out["series"]) == 1
        assert out["series"][0]["labels"] == {"op": "a"}
        assert out["series"][0]["points"] == [[1.0, 10.0]]

    def test_histograms_flatten_to_sum_count(self):
        flat = flatten_snapshot({
            "h": {"type": "histogram", "buckets": [1.0], "series": [
                {"labels": {"op": "x"}, "counts": [1, 0], "sum": 0.5,
                 "count": 1},
            ]},
        })
        key = json.dumps([["op", "x"]], separators=(",", ":"))
        assert flat["h_sum"][key] == 0.5
        assert flat["h_count"][key] == 1.0


# ---- device memory stats (all devices, None/partial tolerated) ----

class FakeDev:
    def __init__(self, stats, platform="tpu"):
        self._stats = stats
        self.platform = platform

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


class TestDeviceMemoryStats:
    def test_none_and_partial_and_raising(self):
        devs = [
            FakeDev(None, platform="cpu"),
            FakeDev({"bytes_in_use": 5, "bytes_limit": 100}),
            FakeDev(RuntimeError("boom")),
            FakeDev({"bytes_limit": 200, "peak_bytes_in_use": 50}),
            FakeDev({"weird": 1}),
        ]
        out = device_memory_stats(devs)
        assert out == [
            {"device": "1", "platform": "tpu", "used": 5, "limit": 100},
            {"device": "3", "platform": "tpu", "limit": 200, "peak": 50},
        ]

    def test_all_cpu_is_empty_not_error(self):
        assert device_memory_stats([FakeDev(None, "cpu")] * 4) == []
        assert hbm_totals([FakeDev(None, "cpu")]) is None

    def test_totals_sum_all_devices(self):
        out = hbm_totals([
            FakeDev({"bytes_in_use": 5, "bytes_limit": 100}),
            FakeDev({"bytes_in_use": 7, "bytes_limit": 100}),
        ])
        assert out["used"] == 12 and out["limit"] == 200
        assert len(out["per_device"]) == 2

    def test_runtime_describe_reports_all_devices(self):
        # The ISSUE 9 satellite: describe() must not probe only devices[0].
        from agent_tpu.runtime.runtime import TpuRuntime

        class _Desc(TpuRuntime):  # bypass __init__: fake the device list
            def __init__(self, devices):
                self.devices = devices

        rt = _Desc.__new__(_Desc)
        rt.devices = [
            FakeDev({"bytes_in_use": 1, "bytes_limit": 10}),
            FakeDev({"bytes_in_use": 2, "bytes_limit": 10}),
        ]
        from agent_tpu.obs.profile import hbm_totals as totals

        out = totals(rt.devices)
        assert out["used"] == 3 and out["limit"] == 20


# ---- host profiler ----

class TestHostProfiler:
    def test_samples_real_frames(self):
        stop = threading.Event()

        def busy_beaver():
            while not stop.is_set():
                time.sleep(0.005)

        t = threading.Thread(target=busy_beaver, name="beaver", daemon=True)
        t.start()
        prof = HostProfiler(hz=200.0)
        try:
            for _ in range(5):
                prof.sample_once()
        finally:
            stop.set()
        text = prof.collapsed()
        lines = [ln for ln in text.splitlines() if ln.strip()]
        assert lines
        for ln in lines:
            stack, count = ln.rsplit(" ", 1)
            assert int(count) >= 1 and ";" in stack
        assert "busy_beaver" in text and "beaver" in text

    def test_bounded_distinct_stacks(self):
        prof = HostProfiler(max_stacks=16)
        with prof._lock:
            pass
        for i in range(100):
            with prof._lock:
                key = (f"synthetic-{i}",)
                if key not in prof._counts and \
                        len(prof._counts) >= prof.max_stacks:
                    key = prof.OVERFLOW_KEY
                prof._counts[key] = prof._counts.get(key, 0) + 1
        assert len(prof._counts) <= prof.max_stacks + 1

    def test_start_stop_idempotent(self):
        prof = HostProfiler(hz=100.0).start()
        assert prof.running
        prof.start()  # second start is a no-op
        time.sleep(0.05)
        prof.stop()
        assert not prof.running
        assert prof.n_samples >= 1


# ---- capture coordinator ----

class TestCaptureCoordinator:
    def test_request_deliver_complete(self):
        cc = CaptureCoordinator()
        rec = cc.request("agent-1", op="map_x", duration_ms=100)
        cid = rec["capture_id"]
        assert cc.pending_for("other-agent") == []
        alerts = cc.pending_for("agent-1")
        assert alerts == [{"kind": "profile_capture", "capture_id": cid,
                           "op": "map_x", "duration_ms": 100}]
        assert cc.pending_for("agent-1") == []  # delivered once
        assert cc.complete({"capture_id": cid, "status": "done",
                            "artifact": "/tmp/x", "summary": {"n": 1}})
        assert not cc.complete({"capture_id": cid})  # terminal — dropped
        snap = cc.snapshot()
        assert snap[0]["status"] == "done"
        assert snap[0]["artifact"] == "/tmp/x"

    def test_validation(self):
        cc = CaptureCoordinator()
        with pytest.raises(ValueError):
            cc.request("")
        with pytest.raises(ValueError):
            cc.request("a", op="")
        with pytest.raises(ValueError):
            cc.request("a", duration_ms=-1)
        assert not cc.complete("garbage")
        assert not cc.complete({"capture_id": "unknown"})

    def test_bounded(self):
        cc = CaptureCoordinator(max_captures=4)
        for _ in range(10):
            cc.request("a")
        assert len(cc.snapshot()) == 4


# ---- controller integration ----

def _make_agent(controller, name="usage-test", tasks=("risk_accumulate",)):
    cfg = Config(agent=AgentConfig(
        controller_url="http://loopback", agent_name=name, tasks=tasks,
        max_tasks=4, idle_sleep_sec=0.0, error_backoff_sec=0.0,
    ))
    agent = Agent(config=cfg, session=LoopbackSession(controller))
    agent._profile = {"tier": "test"}
    return agent


def _drain(controller, agent, deadline_s=60.0):
    deadline = time.monotonic() + deadline_s
    while not controller.drained() and time.monotonic() < deadline:
        leased = agent.lease_once()
        if leased is None:
            controller.sweep()
            continue
        lease_id, tasks = leased
        for task in tasks:
            agent.run_task(lease_id, task)
    agent.push_metrics()
    assert controller.drained(), controller.counts()


def _build_csv(path, rows):
    with open(path, "w", encoding="utf-8") as f:
        f.write("id,text,risk\n")
        for i in range(rows):
            f.write(f'{i},"r {i}",{i % 5}\n')


class TestControllerUsage:
    def test_two_tenant_reconciliation(self, tmp_path):
        csv = str(tmp_path / "r.csv")
        _build_csv(csv, 100)
        c = Controller(lease_ttl_sec=30.0)
        for tenant in ("alpha", "beta"):
            c.submit_csv_job(csv, total_rows=100, shard_size=25,
                             map_op="risk_accumulate",
                             extra_payload={"field": "risk"}, tenant=tenant)
        agent = _make_agent(c)
        _drain(c, agent)
        usage = c.usage_json()
        assert usage["billed_tasks"] == 8
        assert set(usage["by_tenant"]) == {"alpha", "beta"}
        for t in ("alpha", "beta"):
            assert usage["by_tenant"][t]["rows"] == 100
            assert usage["by_tenant"][t]["tasks"] == 4
        busy = sum(
            s["value"] for s in c.fleet_snapshot()
            .get("device_busy_seconds_total", {}).get("series", [])
        )
        ledger = usage["totals"]["device_seconds"]
        assert busy > 0
        assert abs(ledger - busy) <= 0.01 * busy
        # Live queue context rides the report (drained → zeroes).
        assert usage["pending_by_tenant"] == {}
        c.close()

    def test_fenced_duplicate_not_billed(self):
        # stale_epoch: the execution happens but the result is fenced —
        # the ledger bills only the accepted application of the retry.
        c = Controller(lease_ttl_sec=0.01, max_attempts=5)
        jid = c.submit("echo", {"v": 1}, tenant="t")
        c.inject("stale_epoch")
        lease = c.lease("a", capabilities={"ops": ["echo"]})
        task = lease["tasks"][0]
        out = c.report(lease["lease_id"], jid, task["job_epoch"],
                       "succeeded", result={"ok": True,
                                            "usage": {"device_s": 1.0}})
        assert out == {"accepted": False, "reason": "stale epoch"}
        assert c.usage.billed_tasks == 0
        # TTL-expire the fenced lease, re-lease at the bumped epoch; that
        # application bills once.
        time.sleep(0.02)
        c.sweep()
        lease2 = c.lease("a", capabilities={"ops": ["echo"]})
        task2 = lease2["tasks"][0]
        c.report(lease2["lease_id"], jid, task2["job_epoch"], "succeeded",
                 result={"ok": True, "usage": {"device_s": 2.0}})
        assert c.usage.billed_tasks == 1
        assert c.usage_json()["totals"]["device_seconds"] == 2.0
        # A redelivery of the accepted attempt is a counted duplicate, not
        # a second bill.
        out = c.report(lease2["lease_id"], jid, task2["job_epoch"],
                       "succeeded", result={"ok": True,
                                            "usage": {"device_s": 2.0}})
        assert out["accepted"] is False
        assert c.usage.billed_tasks == 1
        c.close()

    def test_retry_attempts_bill_individually(self):
        # Attempt 1 fails transiently WITH a structured result-less error →
        # no usage to bill; attempt 2 succeeds with usage → exactly one
        # bill. "Attempt 2 must not double-bill."
        c = Controller(lease_ttl_sec=30.0, max_attempts=3)
        jid = c.submit("echo", {"v": 1}, tenant="t")
        lease = c.lease("a", capabilities={"ops": ["echo"]})
        task = lease["tasks"][0]
        c.report(lease["lease_id"], jid, task["job_epoch"], "failed",
                 error={"type": "Transient", "message": "x", "trace": ""})
        assert c.usage.billed_tasks == 0
        lease2 = c.lease("a", capabilities={"ops": ["echo"]})
        task2 = lease2["tasks"][0]
        c.report(lease2["lease_id"], jid, task2["job_epoch"], "succeeded",
                 result={"ok": True, "usage": {"device_s": 1.0}})
        assert c.usage.billed_tasks == 1
        assert c.usage.job_billed_attempts() == {jid: 1}
        c.close()

    def test_journal_replay_rebuilds_usage(self, tmp_path):
        csv = str(tmp_path / "r.csv")
        _build_csv(csv, 50)
        journal = str(tmp_path / "journal.jsonl")
        c = Controller(lease_ttl_sec=30.0, journal_path=journal)
        c.submit_csv_job(csv, total_rows=50, shard_size=25,
                         map_op="risk_accumulate",
                         extra_payload={"field": "risk"}, tenant="alpha")
        agent = _make_agent(c)
        _drain(c, agent)
        before = c.usage_json()
        c.close()
        c2 = Controller(lease_ttl_sec=30.0, journal_path=journal)
        after = c2.usage_json()
        assert after["billed_tasks"] == before["billed_tasks"]
        assert after["totals"] == before["totals"]
        assert after["by_tenant"]["alpha"]["rows"] == 50
        c2.close()

    def test_usage_disabled_no_ops(self, tmp_path):
        journal = str(tmp_path / "j.jsonl")
        c = Controller(journal_path=journal,
                       obs=ObsConfig(usage_enabled=False))
        jid = c.submit("echo", {"v": 1})
        lease = c.lease("a", capabilities={"ops": ["echo"]})
        c.report(lease["lease_id"], jid, lease["tasks"][0]["job_epoch"],
                 "succeeded", result={"ok": True,
                                      "usage": {"device_s": 1.0}})
        assert c.usage_json() == {"enabled": False}
        assert not [k for k in c.metrics.snapshot()
                    if k.startswith("usage_")]
        # Journal stays byte-free of usage keys when the ledger is off.
        with open(journal) as f:
            assert not any("\"usage\"" in line for line in f)
        c.close()

    def test_timeseries_endpoint_shapes(self):
        c = Controller(obs=ObsConfig(tsdb_interval_sec=0.05))
        out = c.timeseries_json("tasks_total")
        assert out["enabled"] and out["series"] == []  # empty window read
        c.sweep()
        time.sleep(0.06)
        c.sweep()
        names = c.timeseries_names()
        assert "controller_queue_depth" in names
        depth = c.timeseries_json("controller_queue_depth",
                                  {"state": "leasable"})
        assert len(depth["series"]) == 1
        assert len(depth["series"][0]["points"]) >= 2
        off = Controller(obs=ObsConfig(tsdb_enabled=False))
        assert off.timeseries_json("x") == {
            "enabled": False, "name": "x", "series": [],
        }
        off.close()
        c.close()


class TestAgentTelemetry:
    def test_hbm_gauges_absent_on_statless_backend(self):
        c = Controller()

        class _Rt:
            devices = [FakeDev(None, "cpu")]

            def describe(self):
                return {"platform": "cpu", "n_devices": 1}

        agent = _make_agent(c)
        agent.runtime = _Rt()
        agent._metrics()
        assert "device_hbm_bytes" not in {
            k for k, fam in agent.obs.snapshot().items() if fam["series"]
        } or not agent.obs.snapshot()["device_hbm_bytes"]["series"]
        c.close()

    def test_hbm_gauges_cover_all_devices(self):
        c = Controller()

        class _Rt:
            devices = [
                FakeDev({"bytes_in_use": 5, "bytes_limit": 100}),
                FakeDev({"bytes_in_use": 7, "bytes_limit": 100,
                         "peak_bytes_in_use": 9}),
            ]

            def describe(self):
                return {"platform": "tpu", "n_devices": 2}

        agent = _make_agent(c)
        agent.runtime = _Rt()
        agent._metrics()
        series = agent.obs.snapshot()["device_hbm_bytes"]["series"]
        got = {(s["labels"]["device"], s["labels"]["kind"]): s["value"]
               for s in series}
        assert got[("0", "used")] == 5 and got[("1", "used")] == 7
        assert got[("1", "peak")] == 9
        assert ("0", "peak") not in got  # partial dicts stay partial
        c.close()

    def test_capture_round_trip_through_alerts(self, tmp_path):
        os.environ["PROFILE_CAPTURE_DIR"] = str(tmp_path / "caps")
        try:
            c = Controller(lease_ttl_sec=30.0)
            agent = _make_agent(c, name="cap-agent", tasks=("echo",))
            req = c.request_capture("cap-agent", op="echo")
            c.submit("echo", {"v": 1})
            _drain(c, agent)
            caps = c.captures_json()["captures"]
            assert len(caps) == 1
            rec = caps[0]
            assert rec["capture_id"] == req["capture_id"]
            assert rec["status"] == "done", rec
            assert os.path.isdir(rec["artifact"])
            assert rec["summary"]["n_trace_files"] >= 1
            c.close()
        finally:
            os.environ.pop("PROFILE_CAPTURE_DIR", None)

    def test_capture_wrong_agent_never_fires(self):
        c = Controller(lease_ttl_sec=30.0)
        agent = _make_agent(c, name="right-agent", tasks=("echo",))
        c.request_capture("other-agent", op="echo")
        c.submit("echo", {"v": 1})
        _drain(c, agent)
        rec = c.captures_json()["captures"][0]
        assert rec["status"] == "requested"  # still waiting for its agent
        c.close()

    def test_tenant_plumbs_through_task_wire(self):
        # Non-default tenants ride the task wire and land in the result's
        # trace tags; default-tenant tasks keep the exact legacy keys.
        c = Controller(lease_ttl_sec=0.01)
        agent = _make_agent(c, tasks=("echo",))
        jid_t = c.submit("echo", {"v": 1}, tenant="acme")
        jid_d = c.submit("echo", {"v": 2})
        lease = c.lease("a-probe", capabilities={"ops": ["echo"]},
                        max_tasks=2)
        by_id = {t["id"]: t for t in lease["tasks"]}
        assert by_id[jid_t]["tenant"] == "acme"
        assert "tenant" not in by_id[jid_d]
        # TTL-expire the probe's lease (it never reports), then drain
        # through the real agent loop.
        time.sleep(0.02)
        c.sweep()
        _drain(c, agent)
        res = c.job_snapshot(jid_t)["result"]
        assert res["trace"]["tenant"] == "acme"
        assert "tenant" not in c.job_snapshot(jid_d)["result"]["trace"]
        c.close()

    def test_usage_rides_result_bodies(self):
        c = Controller(lease_ttl_sec=30.0)
        agent = _make_agent(c, tasks=("echo",))
        jid = c.submit("echo", {"v": 1}, tenant="t")
        _drain(c, agent)
        result = c.job_snapshot(jid)["result"]
        assert isinstance(result.get("usage"), dict)
        assert result["usage"]["device_s"] > 0
        assert result["usage"]["host_s"] >= 0
        c.close()


class TestHostProfileSurface:
    def test_lazy_start_and_text(self):
        c = Controller()
        assert c.host_profiler is None  # no thread until asked
        text = c.host_profile_text()
        assert c.host_profiler is not None and c.host_profiler.running
        lines = [ln for ln in text.splitlines() if ln.strip()]
        assert lines and all(
            ln.rsplit(" ", 1)[1].isdigit() for ln in lines
        )
        c.close()
        assert not c.host_profiler.running

    def test_disabled_serves_none(self):
        c = Controller(obs=ObsConfig(profile_host_enabled=False))
        assert c.host_profile_text() is None
        assert c.host_profiler is None
        c.close()
