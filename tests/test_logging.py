"""Direct unit tests for utils/logging.py (ISSUE 2 satellite) — previously
only exercised incidentally through agent/controller flows: ``log`` field
rendering (including non-JSON-serializable values), and ``RateLimiter``
window behavior under a fake clock."""

import json

from agent_tpu.utils.logging import PREFIX, RateLimiter, log


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestLog:
    def test_plain_message(self, capsys):
        log("agent up")
        out = capsys.readouterr().out
        assert out == f"{PREFIX} agent up\n"

    def test_fields_render_as_sorted_compact_json(self, capsys):
        log("task done", op="echo", n=3)
        out = capsys.readouterr().out.strip()
        prefix = f"{PREFIX} task done "
        assert out.startswith(prefix)
        assert json.loads(out[len(prefix):]) == {"op": "echo", "n": 3}
        # sort_keys: deterministic line for greps
        assert out.index('"n"') < out.index('"op"')

    def test_non_json_serializable_fields_stringify(self, capsys):
        log("weird", value={1, 2})  # sets are not JSON — default=str applies
        out = capsys.readouterr().out
        tail = json.loads(out.strip()[len(f"{PREFIX} weird "):])
        assert tail["value"] in ("{1, 2}", "{2, 1}")

    def test_fields_unstringifiable_fall_back_to_repr(self, capsys):
        class Cursed:
            def __str__(self):
                raise TypeError("no str for you")

            def __repr__(self):
                return "<cursed>"

        log("worse", value=Cursed())
        out = capsys.readouterr().out
        # json.dumps(default=str) raised → repr(fields) fallback, line still
        # prints (logging must never throw on hot paths).
        assert out.startswith(f"{PREFIX} worse ")
        assert "<cursed>" in out


class TestRateLimiter:
    def test_window_gates_per_key(self):
        clock = FakeClock()
        rl = RateLimiter(every_sec=10.0, clock=clock)
        assert rl.ready("lease") is True
        assert rl.ready("lease") is False     # inside the window
        assert rl.ready("result") is True     # other keys independent
        clock.t = 9.999
        assert rl.ready("lease") is False
        clock.t = 10.0
        assert rl.ready("lease") is True      # window elapsed exactly
        clock.t = 10.5
        assert rl.ready("lease") is False     # window restarted at 10.0

    def test_log_returns_whether_it_logged(self, capsys):
        clock = FakeClock()
        rl = RateLimiter(every_sec=5.0, clock=clock)
        assert rl.log("exec", "op raised", op="echo") is True
        assert rl.log("exec", "op raised", op="echo") is False
        out = capsys.readouterr().out
        assert out.count("exec: op raised") == 1
        clock.t = 5.0
        assert rl.log("exec", "op raised", op="echo") is True

    def test_suppressed_attempt_does_not_reset_window(self):
        clock = FakeClock()
        rl = RateLimiter(every_sec=10.0, clock=clock)
        assert rl.ready("k")
        clock.t = 6.0
        assert not rl.ready("k")  # suppressed — must NOT push the window out
        clock.t = 10.0
        assert rl.ready("k")      # measured from the last LOGGED event
