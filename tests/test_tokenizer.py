"""Tokenizer + padding-bucket tests (static shapes are what keep pjit from
retracing — SURVEY.md §7 hard parts)."""

import numpy as np

from agent_tpu.models.tokenizer import (
    BOS_ID,
    EOS_ID,
    PAD_ID,
    UNK_ID,
    ByteTokenizer,
    WordPieceTokenizer,
    bucket_length,
    pad_batch,
)


def test_byte_roundtrip():
    tok = ByteTokenizer()
    for text in ["hello world", "unicode: ü≈ 🙂", ""]:
        assert tok.decode(tok.encode(text)) == text
    ids = tok.encode("hi", add_bos=True, add_eos=True)
    assert ids[0] == BOS_ID and ids[-1] == EOS_ID
    assert tok.vocab_size == 260


def test_wordpiece_train_encode_decode():
    corpus = ["the quick brown fox", "the lazy dog", "quick quick fox"]
    tok = WordPieceTokenizer.train(corpus, vocab_size=256)
    ids = tok.encode("the quick fox")
    assert all(i != UNK_ID for i in ids)
    assert tok.decode(ids) == "the quick fox"
    # Unseen word decomposes into character pieces, not UNK.
    ids2 = tok.encode("dogfox")
    assert UNK_ID not in ids2


def test_wordpiece_save_load(tmp_path):
    tok = WordPieceTokenizer.train(["alpha beta gamma"], vocab_size=64)
    p = tmp_path / "vocab.txt"
    tok.save(str(p))
    tok2 = WordPieceTokenizer.from_file(str(p))
    assert tok2.vocab == tok.vocab


def test_bucket_length():
    assert bucket_length(1) == 16
    assert bucket_length(16) == 16
    assert bucket_length(17) == 32
    assert bucket_length(10_000) == 4096  # clamps to top bucket


def test_pad_batch_static_shapes():
    seqs = [[5, 6, 7], list(range(20))]
    ids, mask = pad_batch(seqs)
    assert ids.shape == (2, 32)  # longest is 20 → bucket 32
    assert mask.sum() == 23
    assert ids.dtype == np.int32
    assert (ids[0, 3:] == PAD_ID).all()


def test_pad_batch_batch_buckets():
    ids, mask = pad_batch([[1, 2]] * 3, batch_buckets=(4, 8))
    assert ids.shape == (4, 16)
    assert mask[3].sum() == 0  # appended all-pad row


def test_byte_encode_pad_matches_encode_plus_pad():
    """The fused fast path must produce exactly encode()+pad_batch ids."""
    import numpy as np

    from agent_tpu.models.tokenizer import (
        ByteTokenizer, byte_encode_pad, pad_batch,
    )

    texts = ["hello world", "ünïcödé £ text", "", "a" * 300, "nul\x00byte"]
    tok = ByteTokenizer()
    seqs = [tok.encode(t)[:128] for t in texts]
    want_ids, want_mask = pad_batch(seqs, buckets=[16, 64, 128],
                                    batch_buckets=[8])
    got_ids, got_lengths = byte_encode_pad(texts, buckets=[16, 64, 128],
                                           batch_buckets=[8], max_len_cap=128)
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_array_equal(
        got_lengths, want_mask.sum(axis=1).astype(np.int32)
    )


def test_byte_encode_pad_raw_uint8_reconstructs_exactly():
    """The uint8 wire (unshifted bytes) must reconstruct the shifted ids via
    (raw + N_SPECIAL) * mask — the device-side formula in map_classify_tpu —
    including body NUL bytes, empty rows, and truncated rows."""
    import numpy as np

    import pytest

    from agent_tpu.models.tokenizer import N_SPECIAL, byte_encode_pad

    texts = ["hello world", "ünïcödé £ text", "", "a" * 300, "nul\x00byte"]
    kw = dict(buckets=[16, 64, 128], batch_buckets=[8], max_len_cap=128)
    want_ids, want_lengths = byte_encode_pad(texts, **kw)
    raw, lengths = byte_encode_pad(texts, raw_uint8=True, **kw)
    assert raw.dtype == np.uint8
    np.testing.assert_array_equal(lengths, want_lengths)
    L = raw.shape[1]
    mask = (np.arange(L)[None, :] < lengths[:, None]).astype(np.int32)
    np.testing.assert_array_equal((raw.astype(np.int32) + N_SPECIAL) * mask,
                                  want_ids)
    with pytest.raises(ValueError):
        byte_encode_pad(texts, raw_uint8=True, add_eos=True, **kw)


def test_stage_text_chunks_byte_path_ships_uint8():
    """The classify byte path stages the uint8 raw wire; BOS/EOS staging
    (summarize) and small-vocab configs stay on the uint16 id wire."""
    import numpy as np

    from agent_tpu.ops._model_common import stage_text_chunks

    chunks = stage_text_chunks(
        1, ["alpha", "beta"], max_len=128, vocab_size=260, max_batch=8
    )
    assert all(ids.dtype == np.uint8 for ids, _, _ in chunks)
    chunks = stage_text_chunks(
        1, ["alpha", "beta"], max_len=128, vocab_size=260, max_batch=8,
        add_bos=True, add_eos=True,
    )
    assert all(ids.dtype == np.uint16 for ids, _, _ in chunks)
    # vocab too small to hold all byte ids: raw wire must not engage
    chunks = stage_text_chunks(
        1, ["alpha"], max_len=128, vocab_size=100, max_batch=8
    )
    assert all(ids.dtype == np.uint16 for ids, _, _ in chunks)


def test_byte_encode_pad_bos_eos_matches_encode_plus_pad():
    """BOS/EOS semantics must match encode(add_bos, add_eos)[:cap] exactly,
    including the EOS lost to truncation at the cap boundary."""
    import numpy as np

    from agent_tpu.models.tokenizer import (
        ByteTokenizer, byte_encode_pad, pad_batch,
    )

    tok = ByteTokenizer()
    # 126/127/128 body bytes straddle the cap-128 boundary with bos+eos.
    texts = ["hello", "", "y" * 126, "y" * 127, "y" * 128, "nul\x00b"]
    seqs = [tok.encode(t, add_bos=True, add_eos=True)[:128] for t in texts]
    want_ids, want_mask = pad_batch(seqs, buckets=[16, 64, 128],
                                    batch_buckets=[8])
    got_ids, got_lengths = byte_encode_pad(
        texts, buckets=[16, 64, 128], batch_buckets=[8], max_len_cap=128,
        add_bos=True, add_eos=True,
    )
    np.testing.assert_array_equal(got_ids, want_ids)
    np.testing.assert_array_equal(
        got_lengths, want_mask.sum(axis=1).astype(np.int32)
    )


def test_byte_encode_pad_cap_above_top_bucket_truncates():
    """cap > largest bucket must truncate to the bucket (bucket_length's
    'callers truncate to it' contract), not overflow the row write."""
    import numpy as np

    from agent_tpu.models.tokenizer import byte_encode_pad

    ids, lengths = byte_encode_pad(["y" * 90], buckets=[16, 32, 64],
                                   max_len_cap=100)
    assert ids.shape[1] == 64 and lengths[0] == 64
    ids, lengths = byte_encode_pad(["y" * 90], buckets=[16, 32, 64],
                                   max_len_cap=100, add_bos=True, add_eos=True)
    assert ids.shape[1] == 64 and lengths[0] == 64
    assert ids[0, 0] == 1  # BOS survives; EOS lost to truncation
    assert (ids[0] == 2).sum() == 0
