"""Config surface tests — the env-var schema is the compatibility contract
(SURVEY.md §5.6)."""

import agent_tpu
from agent_tpu.config import (
    AgentConfig,
    Config,
    DeviceConfig,
    parse_labels,
    parse_tasks,
)


def test_version():
    assert agent_tpu.__version__


def test_parse_labels_grammar():
    # Same grammar as reference app.py:49-63.
    assert parse_labels("k=v, zone=us, flag") == {"k": "v", "zone": "us", "flag": True}
    assert parse_labels("") == {}
    assert parse_labels(",,") == {}
    assert parse_labels("a=1=2") == {"a": "1=2"}


def test_parse_tasks_dedup_order():
    assert parse_tasks("echo,map_classify_tpu,echo") == ("echo", "map_classify_tpu")
    assert parse_tasks("") == ()


def test_agent_config_defaults(monkeypatch):
    for var in ("CONTROLLER_URL", "MAX_TASKS", "TASKS"):
        monkeypatch.delenv(var, raising=False)
    cfg = AgentConfig.from_env()
    assert cfg.controller_url == "http://10.11.12.54:8080"  # ref app.py:21-23
    assert cfg.max_tasks == 1  # ref app.py:30-31
    assert cfg.tasks == ("echo", "map_classify_tpu")  # ref app.py:38
    assert cfg.lease_timeout_ms == 3000
    assert cfg.idle_sleep_sec == 0.25


def test_env_overrides(monkeypatch):
    monkeypatch.setenv("CONTROLLER_URL", "http://ctrl:9999/")
    monkeypatch.setenv("MAX_TASKS", "3")
    monkeypatch.setenv("TASKS", "echo,risk_accumulate")
    monkeypatch.setenv("MESH_SHAPE", "dp=2,tp=4")
    cfg = Config.from_env()
    assert cfg.agent.controller_url == "http://ctrl:9999"
    assert cfg.agent.max_tasks == 3
    assert cfg.agent.tasks == ("echo", "risk_accumulate")
    assert cfg.device.mesh_shape == {"dp": 2, "tp": 4}


def test_fault_tolerance_env_knobs(monkeypatch):
    for var in ("RETRY_BASE_SEC", "RETRY_MAX_SEC", "RETRY_DEADLINE_SEC",
                "RESULT_SPOOL_PATH", "RESULT_SPOOL_MAX"):
        monkeypatch.delenv(var, raising=False)
    cfg = AgentConfig.from_env()
    assert cfg.retry_base_sec == 0.5
    assert cfg.retry_max_sec == 30.0
    assert cfg.retry_deadline_sec == 0.0
    assert cfg.result_spool_path == ""
    assert cfg.result_spool_max == 512

    monkeypatch.setenv("RETRY_BASE_SEC", "0.1")
    monkeypatch.setenv("RETRY_MAX_SEC", "5")
    monkeypatch.setenv("RETRY_DEADLINE_SEC", "120")
    monkeypatch.setenv("RESULT_SPOOL_PATH", "/tmp/spool.jsonl")
    monkeypatch.setenv("RESULT_SPOOL_MAX", "0")  # floored at 1
    cfg = AgentConfig.from_env()
    assert cfg.retry_base_sec == 0.1
    assert cfg.retry_max_sec == 5.0
    assert cfg.retry_deadline_sec == 120.0
    assert cfg.result_spool_path == "/tmp/spool.jsonl"
    assert cfg.result_spool_max == 1


def test_forgiving_parses(monkeypatch):
    # Bad values fall back to defaults (reference worker_sizing.py:12-41).
    monkeypatch.setenv("MAX_TASKS", "not-a-number")
    monkeypatch.setenv("HTTP_TIMEOUT_SEC", "")
    monkeypatch.setenv("TPU_DISABLED", "yes")
    cfg = Config.from_env()
    assert cfg.agent.max_tasks == 1
    assert cfg.agent.http_timeout_sec == 10.0
    assert cfg.device.tpu_disabled is True
