"""True multi-process SPMD tests: two local CPU processes joined through
``jax.distributed`` (the same coordination service a TPU slice uses — here the
collectives ride Gloo instead of ICI, which is exactly the DCN-tier path).

Each test spawns subprocesses because a JAX process can join a coordination
service only once per lifetime.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(code: str, *args: str, timeout: float = 120.0):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # 1 local device per process
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code, str(pid), *args],
            env=env,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    return procs, outs


BROADCAST_CODE = textwrap.dedent("""
    import sys, os
    import jax; jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.getcwd())
    pid, port = int(sys.argv[1]), sys.argv[2]
    from agent_tpu.runtime.distributed import (
        broadcast_shutdown, broadcast_task, is_shutdown, maybe_initialize)
    info = maybe_initialize(f"localhost:{port}", 2, pid)
    assert info.process_count == 2
    task = {"op": "echo", "payload": {"msg": "hi", "n": 42}}
    if info.is_leader:
        assert broadcast_task(task) == task
        broadcast_shutdown()
    else:
        assert broadcast_task(None) == task
        assert is_shutdown(broadcast_task(None))
    print(f"OK {pid}")
""")


AGENT_CODE = textwrap.dedent("""
    import sys, os
    import jax; jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.getcwd())
    pid, port = int(sys.argv[1]), sys.argv[2]
    os.environ["COORDINATOR_ADDRESS"] = f"localhost:{port}"
    os.environ["NUM_PROCESSES"] = "2"
    os.environ["PROCESS_ID"] = str(pid)
    os.environ["TASKS"] = "echo,risk_accumulate"

    from agent_tpu.config import Config
    from agent_tpu.agent.app import Agent
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer

    if pid == 0:
        # Leader host: in-proc controller + leader agent.
        ctrl = Controller()
        for i in range(3):
            ctrl.submit("echo", {"i": i})
        ctrl.submit("risk_accumulate", {"values": [1.0, 2.0, 3.0]})
        with ControllerServer(ctrl) as srv:
            os.environ["CONTROLLER_URL"] = srv.url
            import requests
            agent = Agent(config=Config.from_env(), session=requests.Session())
            while not ctrl.drained():
                agent.step()
            agent.running = False
            agent.run(max_steps=0)   # triggers the finally-broadcast shutdown
            res = ctrl.results()
            assert len(res) == 4, res
            risk = [r for r in res.values() if "sum" in (r or {})][0]
            assert abs(risk["sum"] - 6.0) < 1e-6, risk
        print("OK 0")
    else:
        # Follower host: no HTTP; lockstep-executes broadcast tasks.
        agent = Agent(config=Config.from_env(), session=object())
        agent.run()
        assert agent.tasks_done == 4, agent.tasks_done
        print(f"OK 1")
""")


@pytest.mark.parametrize("code,name", [
    (BROADCAST_CODE, "broadcast"),
    (AGENT_CODE, "agent_leader_follower"),
], ids=["broadcast", "agent_leader_follower"])
def test_two_process_multihost(code, name):
    port = _free_port()
    procs, outs = _spawn(code, str(port))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-3000:]}"
        assert f"OK {pid}" in out, f"proc {pid} output:\n{out[-3000:]}"
