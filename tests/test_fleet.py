"""Multi-chip fleet machinery (ISSUE 7): chip-slice pinning (config →
runtime → capabilities), the fleet launcher's per-member environments, and
the MPMD summarize encode/decode pipeline split (dep-gated across two
agents, bit-identical to the monolithic op)."""

import json

import jax
import pytest

from agent_tpu.agent import fleet
from agent_tpu.agent.app import Agent
from agent_tpu.chaos import LoopbackSession
from agent_tpu.config import AgentConfig, Config, DeviceConfig
from agent_tpu.controller.core import Controller
from agent_tpu.runtime.runtime import (
    TpuRuntime,
    apply_chip_slice,
    parse_chip_slice,
)

TINY_S2S = {
    "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
    "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
}


@pytest.fixture(scope="module")
def runtime():
    return TpuRuntime(
        config=DeviceConfig(tpu_disabled=True, mesh_shape={"dp": 8}),
        devices=jax.devices("cpu"),
    )


# ---- chip-slice grammar + runtime pinning ----

class TestChipSlice:
    def test_parse_valid(self):
        assert parse_chip_slice("0:1") == (0, 1)
        assert parse_chip_slice("4:2") == (4, 2)

    @pytest.mark.parametrize(
        "bad", ["", "3", "1:2:3", "a:1", "1:b", "-1:2", "0:0", "0:-1"]
    )
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            parse_chip_slice(bad)

    def test_apply_slices_and_bounds(self):
        devices = list(range(8))  # any sequence works
        assert apply_chip_slice(devices, "0:2") == [0, 1]
        assert apply_chip_slice(devices, "6:2") == [6, 7]
        with pytest.raises(ValueError):
            apply_chip_slice(devices, "7:2")  # out of range, not truncated

    def test_config_reads_chip_slice_env(self, monkeypatch):
        monkeypatch.setenv("CHIP_SLICE", "2:2")
        assert DeviceConfig.from_env().chip_slice == "2:2"
        monkeypatch.delenv("CHIP_SLICE")
        assert DeviceConfig.from_env().chip_slice == ""

    def test_runtime_owns_only_its_slice(self):
        # conftest forces 8 virtual CPU devices, so a real subset exists.
        rt = TpuRuntime(
            config=DeviceConfig(tpu_disabled=True, chip_slice="2:2")
        )
        assert rt.n_devices == 2
        assert rt.devices == jax.devices("cpu")[2:4]
        assert rt.describe()["chip_slice"] == "2:2"
        assert dict(rt.mesh.shape)["dp"] == 2  # dp absorbs the slice

    def test_explicit_devices_ignore_slice(self):
        # Callers that hand devices in already chose; the slice is for the
        # discovery path only.
        rt = TpuRuntime(
            config=DeviceConfig(tpu_disabled=True, chip_slice="0:1"),
            devices=jax.devices("cpu"),
        )
        assert rt.n_devices == len(jax.devices("cpu"))

    def test_agent_capabilities_advertise_slice(self):
        cfg = Config(
            agent=AgentConfig(tasks=("echo",)),
            device=DeviceConfig(chip_slice="1:3"),
        )
        agent = Agent(config=cfg, session=object())
        assert agent.capabilities()["chip_slice"] == "1:3"
        plain = Agent(
            config=Config(agent=AgentConfig(tasks=("echo",))),
            session=object(),
        )
        assert "chip_slice" not in plain.capabilities()


# ---- launcher environment computation ----

class TestFleetEnv:
    def test_cpu_members_get_disjoint_slices_and_forced_devices(self):
        envs = [
            fleet.agent_env(
                i, 2, 2, controller_url="http://c:1", tasks="echo",
                platform="cpu", base_env={"XLA_FLAGS": "--keep=1 "
                "--xla_force_host_platform_device_count=8"},
            )
            for i in range(2)
        ]
        assert [e["CHIP_SLICE"] for e in envs] == ["0:2", "2:2"]
        assert [e["AGENT_NAME"] for e in envs] == ["fleet-0", "fleet-1"]
        for e in envs:
            # Inherited forced count REPLACED with the fleet's total.
            assert "--xla_force_host_platform_device_count=4" in \
                e["XLA_FLAGS"]
            assert "device_count=8" not in e["XLA_FLAGS"]
            assert "--keep=1" in e["XLA_FLAGS"]
            assert e["JAX_PLATFORMS"] == "cpu"
            assert e["CONTROLLER_URL"] == "http://c:1"
            assert e["TASKS"] == "echo"

    def test_tpu_members_pin_at_process_level(self):
        env = fleet.agent_env(
            1, 4, 2, controller_url="http://c:1", tasks="echo",
            platform="tpu", base_env={},
        )
        assert env["TPU_VISIBLE_DEVICES"] == "2,3"
        # In-process slice is identity over the restricted view.
        assert env["CHIP_SLICE"] == "0:2"
        assert "XLA_FLAGS" not in env or \
            "force_host_platform" not in env["XLA_FLAGS"]

    def test_mesh_and_warm_ride_through(self):
        env = fleet.agent_env(
            0, 1, 4, controller_url="http://c:1", tasks="echo",
            platform="cpu", base_env={}, mesh_shape="dp=4",
            warm_file="/tmp/w.json", extra_env={"IDLE_SLEEP_SEC": "0.01"},
        )
        assert env["MESH_SHAPE"] == "dp=4"
        assert env["AGENT_WARM_FILE"] == "/tmp/w.json"
        assert env["IDLE_SLEEP_SEC"] == "0.01"

    def test_index_bounds(self):
        with pytest.raises(ValueError):
            fleet.agent_env(
                2, 2, 1, controller_url="u", tasks="t", base_env={}
            )

    def test_force_host_devices_idempotent(self):
        flags = fleet.force_host_devices("", 4)
        assert flags == "--xla_force_host_platform_device_count=4"
        assert fleet.force_host_devices(flags, 2) == \
            "--xla_force_host_platform_device_count=2"


# ---- MPMD pipeline split (stretch): encode/decode across two agents ----

class TestMpmdPipeline:
    def _stage_agent(self, controller, runtime, name, tasks):
        agent = Agent(
            config=Config(agent=AgentConfig(
                controller_url="http://loopback", agent_name=name,
                tasks=tasks, idle_sleep_sec=0.0,
            )),
            session=LoopbackSession(controller), runtime=runtime,
        )
        agent._profile = {"tier": "test"}
        return agent

    def test_chained_stages_equal_monolithic(self, runtime):
        from agent_tpu.ops import get_op
        from agent_tpu.runtime.context import OpContext

        texts = [f"mpmd row {i} with text to summarize" for i in range(24)]
        mono = get_op("map_summarize")(
            {"texts": texts, "max_length": 6, "model_config": dict(TINY_S2S)},
            OpContext(runtime=runtime),
        )
        assert mono["ok"] is True

        controller = Controller()
        enc_id = controller.submit(
            "summarize_encode",
            {"texts": texts, "model_config": dict(TINY_S2S)},
        )
        dec_id = controller.submit(
            "summarize_decode",
            {"max_length": 6, "model_config": dict(TINY_S2S),
             "__collect_partials__": True},
            after=[enc_id],
        )
        enc_agent = self._stage_agent(
            controller, runtime, "enc", ("summarize_encode",))
        dec_agent = self._stage_agent(
            controller, runtime, "dec", ("summarize_decode",))
        # Dep gating: the decode stage cannot lease before encode posts.
        assert dec_agent.step() is False
        for _ in range(20):
            if controller.drained():
                break
            enc_agent.step()
            dec_agent.step()
        assert controller.drained(), controller.counts()
        assert controller.job_snapshot(enc_id)["agent"] == "enc"
        dec_snap = controller.job_snapshot(dec_id)
        assert dec_snap["agent"] == "dec"
        assert dec_snap["result"]["summaries"] == mono["summaries"]

    def test_encode_result_survives_json_round_trip(self, runtime):
        """The inter-stage wire is a result body: a JSON round trip (what
        the controller journal/HTTP do) must not perturb the activations
        the decode stage resumes from."""
        from agent_tpu.ops import get_op
        from agent_tpu.runtime.context import OpContext

        texts = ["round trip row one", "round trip row two"]
        ctx = OpContext(runtime=runtime)
        enc = get_op("summarize_encode")(
            {"texts": texts, "model_config": dict(TINY_S2S)}, ctx)
        assert enc["ok"] is True and enc["n_rows"] == 2
        dec_direct = get_op("summarize_decode")(
            {"encoded": enc, "max_length": 6,
             "model_config": dict(TINY_S2S)}, ctx)
        dec_rt = get_op("summarize_decode")(
            {"encoded": json.loads(json.dumps(enc)), "max_length": 6,
             "model_config": dict(TINY_S2S)}, ctx)
        assert dec_direct["summaries"] == dec_rt["summaries"]
        assert len(dec_rt["summaries"]) == 2

    def test_decode_rejects_malformed_inputs(self, runtime):
        from agent_tpu.ops import get_op
        from agent_tpu.runtime.context import OpContext

        ctx = OpContext(runtime=runtime)
        out = get_op("summarize_decode")({"max_length": 6}, ctx)
        assert out["ok"] is False
        out = get_op("summarize_decode")(
            {"encoded": {"op": "other"}, "max_length": 6}, ctx)
        assert out["ok"] is False

    def test_empty_rows_stay_blank_through_the_chain(self, runtime, tmp_path):
        """Drain-mode blank cells: the encode stage marks them, the decode
        stage blanks them — same contract as the fused op."""
        from agent_tpu.ops import get_op
        from agent_tpu.runtime.context import OpContext

        csv = tmp_path / "rows.csv"
        csv.write_text(
            'id,text\n0,"first row"\n1,""\n2,"third row"\n',
            encoding="utf-8",
        )
        ctx = OpContext(runtime=runtime)
        enc = get_op("summarize_encode")(
            {"source_uri": str(csv), "start_row": 0, "shard_size": 3,
             "text_field": "text", "model_config": dict(TINY_S2S)}, ctx)
        assert enc["ok"] is True and enc["empty_rows"] == [1]
        dec = get_op("summarize_decode")(
            {"encoded": enc, "max_length": 6,
             "model_config": dict(TINY_S2S)}, ctx)
        assert dec["summaries"][1] == ""
        assert dec["summaries"][0] != ""


class TestWaitForAgents:
    """ISSUE 10 satellite: the readiness gate's timeout and
    partial-readiness paths (only the happy path was covered)."""

    def _agents_fn(self, *snapshots):
        """agents_fn returning successive snapshots, then the last forever."""
        seq = list(snapshots)

        def fn():
            return seq.pop(0) if len(seq) > 1 else seq[0]

        return fn

    def test_all_ready_immediately(self):
        from agent_tpu.agent.fleet import wait_for_agents

        fn = self._agents_fn({"a": {}, "b": {}})
        assert wait_for_agents(fn, ["a", "b"], timeout=1.0) is True

    def test_partial_readiness_converges(self):
        from agent_tpu.agent.fleet import wait_for_agents

        fn = self._agents_fn({}, {"a": {}}, {"a": {}, "b": {}})
        assert wait_for_agents(fn, ["a", "b"], timeout=5.0) is True

    def test_partial_readiness_times_out(self):
        import time

        from agent_tpu.agent.fleet import wait_for_agents

        t0 = time.monotonic()
        fn = self._agents_fn({"a": {}})  # b never reports in
        assert wait_for_agents(fn, ["a", "b"], timeout=0.4) is False
        assert time.monotonic() - t0 >= 0.3  # actually waited the window

    def test_agents_fn_errors_tolerated_until_timeout(self):
        from agent_tpu.agent.fleet import wait_for_agents

        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionError("controller still booting")
            return {"a": {}}

        assert wait_for_agents(flaky, ["a"], timeout=5.0) is True
        assert calls["n"] >= 3

    def test_dead_member_aborts_the_wait(self):
        from agent_tpu.agent.fleet import Fleet, wait_for_agents

        class DeadProc:
            returncode = 3

            def poll(self):
                return 3

        fleet = Fleet([DeadProc()], ["a"])
        # b never reports AND a member already exited nonzero: fail fast,
        # not at the timeout.
        import time

        t0 = time.monotonic()
        ok = wait_for_agents(
            self._agents_fn({}), ["a"], timeout=30.0, fleet=fleet
        )
        assert ok is False
        assert time.monotonic() - t0 < 5.0
