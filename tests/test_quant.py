"""INT8 quantized execution (models.quant) — the TPU-native successor of the
reference's INT8 TFLite device story (reference ``ops/_tpu_runtime.py:23-31``,
``ops/map_classify_tpu.py:53-74``): same serving contract, W8A8 matmuls.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agent_tpu.config import DeviceConfig
from agent_tpu.models import encoder, layers, quant
from agent_tpu.runtime.context import OpContext
from agent_tpu.runtime.runtime import TpuRuntime


def _runtime(mesh_shape):
    return TpuRuntime(
        config=DeviceConfig(tpu_disabled=True, mesh_shape=mesh_shape),
        devices=jax.devices("cpu")[:8],
    )


@pytest.fixture(scope="module")
def rt():
    return _runtime({"dp": 8, "tp": 1, "sp": 1})


@pytest.fixture(scope="module")
def rt_tp():
    return _runtime({"dp": 4, "tp": 2, "sp": 1})


# ---- kernel-level numerics ----


def test_qdense_close_to_dense():
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    p = {
        "w": jax.random.normal(k1, (64, 96), dtype=jnp.float32) * 0.1,
        "b": jax.random.normal(k2, (96,), dtype=jnp.float32) * 0.01,
    }
    x = jax.random.normal(k3, (8, 64), dtype=jnp.float32)
    want = layers.dense(p, x, jnp.float32)
    got = quant.qdense(quant.quantize_dense(p), x, jnp.float32)
    # W8A8 relative error budget: ~1% of the output scale.
    err = np.abs(np.asarray(got - want))
    assert err.max() <= 0.02 * np.abs(np.asarray(want)).max() + 1e-6


def test_qproj_in_out_close_to_einsum():
    key = jax.random.PRNGKey(1)
    k1, k2, k3 = jax.random.split(key, 3)
    B, L, d, H, E = 2, 16, 32, 4, 8
    w_in = jax.random.normal(k1, (d, H, E), dtype=jnp.float32) * 0.1
    w_out = jax.random.normal(k2, (H, E, d), dtype=jnp.float32) * 0.1
    x = jax.random.normal(k3, (B, L, d), dtype=jnp.float32)

    want_in = jnp.einsum("bld,dhe->bhle", x, w_in)
    got_in = quant.qproj_in(quant.quantize_weight(w_in, (0,)), x, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got_in), np.asarray(want_in),
        atol=0.02 * float(jnp.abs(want_in).max()),
    )

    h = jnp.asarray(want_in)  # [B, H, L, E]
    want_out = jnp.einsum("bhle,hed->bld", h, w_out)
    got_out = quant.qproj_out(
        quant.quantize_weight(w_out, (0, 1)), h, jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(got_out), np.asarray(want_out),
        atol=0.02 * float(jnp.abs(want_out).max()),
    )


def test_weight_roundtrip_exact_for_representable():
    """Weights already on the int8 grid must survive quantization exactly."""
    scale = 0.5 / 127.0
    w = (np.arange(-127, 128, dtype=np.float32) * scale).reshape(1, -1)
    w = np.repeat(w, 4, axis=0)
    q = quant.quantize_weight(w, (0,))
    back = q["w_q"].astype(np.float32) * q["w_scale"]
    np.testing.assert_allclose(back, w, rtol=1e-6)


def test_validate_quant():
    assert quant.validate_quant("int8") == "int8"
    assert quant.validate_quant("w8a16") == "w8a16"
    assert quant.validate_quant("none") == "none"
    with pytest.raises(ValueError, match="quant"):
        quant.validate_quant("int4")


# ---- W8A16 weight-only kernels ----


def test_wdense_matches_dequantized_dense():
    """wdense must equal the plain dense over the DEQUANTIZED table — the
    only approximation in W8A16 is the weight rounding itself (activations
    are untouched), so against w8·scale the match is float-exact-ish."""
    key = jax.random.PRNGKey(2)
    k1, k2, k3 = jax.random.split(key, 3)
    p = {
        "w": jax.random.normal(k1, (64, 96), dtype=jnp.float32) * 0.1,
        "b": jax.random.normal(k2, (96,), dtype=jnp.float32) * 0.01,
    }
    x = jax.random.normal(k3, (8, 64), dtype=jnp.float32)
    q = quant.quantize_dense_w8a16(p)
    assert q["w8"].dtype == np.int8
    deq = {"w": q["w8"].astype(np.float32) * q["w_scale"], "b": q["b"]}
    want = layers.dense(deq, x, jnp.float32)
    got = quant.wdense(q, x, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )
    # And it tracks the ORIGINAL weights within the int8 rounding budget.
    orig = layers.dense(p, x, jnp.float32)
    err = np.abs(np.asarray(got - orig))
    assert err.max() <= 0.02 * np.abs(np.asarray(orig)).max() + 1e-6


def test_wproj_in_out_close_to_einsum():
    key = jax.random.PRNGKey(3)
    k1, k2, k3 = jax.random.split(key, 3)
    B, L, d, H, E = 2, 16, 32, 4, 8
    w_in = jax.random.normal(k1, (d, H, E), dtype=jnp.float32) * 0.1
    w_out = jax.random.normal(k2, (H, E, d), dtype=jnp.float32) * 0.1
    x = jax.random.normal(k3, (B, L, d), dtype=jnp.float32)

    want_in = jnp.einsum("bld,dhe->bhle", x, w_in)
    got_in = quant.wproj_in(
        quant.quantize_weight_w8a16(w_in, (0,)), x, jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(got_in), np.asarray(want_in),
        atol=0.02 * float(jnp.abs(want_in).max()),
    )

    h = jnp.asarray(want_in)  # [B, H, L, E]
    want_out = jnp.einsum("bhle,hed->bld", h, w_out)
    got_out = quant.wproj_out(
        quant.quantize_weight_w8a16(w_out, (0, 1)), h, jnp.float32
    )
    np.testing.assert_allclose(
        np.asarray(got_out), np.asarray(want_out),
        atol=0.02 * float(jnp.abs(want_out).max()),
    )


def test_w8a16_leaf_conventions_are_disjoint():
    """The two leaf predicates must never both claim a leaf — dispatch in
    layers.dense/_proj_* relies on it."""
    w = np.ones((4, 8), np.float32)
    q8 = quant.quantize_weight(w, (0,))
    w8 = quant.quantize_weight_w8a16(w, (0,))
    assert quant.is_quantized(q8) and not quant.is_weight_only(q8)
    assert quant.is_weight_only(w8) and not quant.is_quantized(w8)
    # Same int8 table, same scale — only the leaf key differs.
    np.testing.assert_array_equal(q8["w_q"], w8["w8"])
    np.testing.assert_array_equal(q8["w_scale"], w8["w_scale"])


# ---- model-level numerics ----


@pytest.mark.parametrize("mode", ["int8", "w8a16"])
def test_encoder_forward_quantized_tracks_f32(mode):
    cfg = encoder.EncoderConfig(
        d_model=64, n_heads=4, n_layers=3, d_ff=128, max_len=64,
        n_classes=50, dtype="float32",
    )
    params = encoder.init_params(cfg, model_id="quant-numerics")
    qparams = quant.quantize_encoder(params, mode)
    rng = np.random.default_rng(0)
    B, L = 16, 32
    ids = rng.integers(4, 200, size=(B, L)).astype(np.int32)
    mask = np.ones((B, L), dtype=np.int32)
    want = np.asarray(encoder.forward(params, ids, mask, cfg))
    got = np.asarray(encoder.forward(qparams, ids, mask, cfg))
    # Per-row cosine similarity of the logit vectors stays ~1 through the
    # whole quantized stack.
    cos = (want * got).sum(-1) / (
        np.linalg.norm(want, axis=-1) * np.linalg.norm(got, axis=-1)
    )
    assert cos.min() > 0.999
    # And the decision (top-1 over 50 classes) agrees for most rows.
    agree = (want.argmax(-1) == got.argmax(-1)).mean()
    assert agree >= 0.9


# ---- op contract ----


QCFG = {
    "d_model": 64, "n_heads": 4, "n_layers": 2, "d_ff": 128,
    "max_len": 64, "n_classes": 32, "dtype": "float32",
}


def test_classify_int8_through_op(rt):
    from agent_tpu.ops import get_op

    classify = get_op("map_classify_tpu")
    texts = [f"int8 contract row {i}" for i in range(8)]
    base = {
        "texts": texts, "topk": 3, "model_path": "quant-op",
        "allow_fallback": False, "result_format": "columnar",
    }
    a = classify(
        {**base, "model_config": QCFG}, OpContext(runtime=rt)
    )
    b = classify(
        {**base, "model_config": {**QCFG, "quant": "int8"}},
        OpContext(runtime=rt),
    )
    assert a["ok"] and b["ok"]
    assert len(b["indices"]) == len(texts) and len(b["indices"][0]) == 3
    # int8 compiles/caches under its own key (distinct cfg fingerprint).
    keys = list(rt.cache._cache.keys())
    quant_keys = [
        k for k in keys
        if k[0] == "map_classify_tpu" and ("quant", "int8") in k[-1]
    ]
    assert quant_keys, f"no int8-keyed executable in {keys}"
    # Decisions track the f32 run on a comfortable majority of rows.
    top1_a = [row[0] for row in a["indices"]]
    top1_b = [row[0] for row in b["indices"]]
    agree = np.mean([x == y for x, y in zip(top1_a, top1_b)])
    assert agree >= 0.75


def test_classify_int8_bad_value_soft_error(rt):
    from agent_tpu.ops import get_op

    out = get_op("map_classify_tpu")(
        {"texts": ["x"], "model_config": {**QCFG, "quant": "fp4"}},
        OpContext(runtime=rt),
    )
    assert out["ok"] is False and "quant" in out["error"]


def test_classify_int8_env_switch(rt, monkeypatch):
    """TPU_QUANT=int8 turns quantized serving on without payload changes."""
    from agent_tpu.ops import get_op

    monkeypatch.setenv("TPU_QUANT", "int8")
    out = get_op("map_classify_tpu")(
        {"texts": ["env switch row"], "topk": 3, "model_config": QCFG,
         "model_path": "quant-env", "allow_fallback": False},
        OpContext(runtime=rt),
    )
    assert out["ok"] is True
    keys = [
        k for k in rt.cache._cache.keys()
        if k[0] == "map_classify_tpu" and k[1] == "quant-env"
    ]
    assert keys and all(("quant", "int8") in k[-1] for k in keys)


def test_classify_int8_tp_matches_replicated(rt, rt_tp):
    """Quantized serving on a tp=2 mesh: the int8 tables shard per the
    transformed spec tree and the decisions match the replicated int8 run."""
    from agent_tpu.ops import get_op

    classify = get_op("map_classify_tpu")
    payload = {
        "texts": [f"int8 tp row {i}" for i in range(16)],
        "topk": 5,
        "model_config": {**QCFG, "n_heads": 8, "quant": "int8"},
        "model_path": "quant-tp",
        "allow_fallback": False,
        "result_format": "columnar",
    }
    a = classify(dict(payload), OpContext(runtime=rt))
    b = classify(dict(payload), OpContext(runtime=rt_tp))
    assert a["ok"] and b["ok"]
    assert a["indices"] == b["indices"]
    np.testing.assert_allclose(a["scores"], b["scores"], rtol=1e-4, atol=1e-6)


def test_int8_params_actually_sharded_and_int8(rt_tp):
    """On the tp mesh the resident tables are int8 dtype AND head-sharded —
    the transfer/HBM win and the tp win must compose, not exclude."""
    from agent_tpu.models.encoder import EncoderConfig
    from agent_tpu.ops import get_op
    from agent_tpu.ops._model_common import cfg_key

    cfg_dict = {**QCFG, "n_heads": 8, "quant": "int8"}
    get_op("map_classify_tpu")(
        {"texts": ["shard check"], "model_config": cfg_dict,
         "model_path": "quant-shardcheck", "allow_fallback": False},
        OpContext(runtime=rt_tp),
    )
    cfg = EncoderConfig(**cfg_dict)
    key = (
        "params",
        f"quant-shardcheck#encoder#{hash(cfg_key(cfg)) & 0xFFFFFFFF:08x}",
        "tp",
    )
    params = rt_tp._params.get_or_build(
        key, lambda: pytest.fail("int8 params not cached under the tp key")
    )
    wq = params["blocks"][0]["attn"]["wq"]
    assert wq["w_q"].dtype == jnp.int8
    shard = wq["w_q"].sharding.shard_shape(wq["w_q"].shape)
    assert shard[1] == wq["w_q"].shape[1] // 2      # heads over tp=2
    scale_shard = wq["w_scale"].sharding.shard_shape(wq["w_scale"].shape)
    assert scale_shard[0] == wq["w_scale"].shape[0] // 2  # scales follow


# ---- summarize families ----


def test_summarize_int8_through_op(rt):
    from agent_tpu.ops import get_op

    summarize = get_op("map_summarize")
    cfg = {
        "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
        "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
    }
    payload = {
        "texts": ["an int8 document about quantized decoding " * 3] * 4,
        "max_length": 8,
        "model_config": {**cfg, "quant": "int8"},
        "model_path": "quant-sum",
    }
    out = summarize(dict(payload), OpContext(runtime=rt))
    assert out["ok"] is True
    assert len(out["summaries"]) == 4
    assert all(isinstance(s, str) for s in out["summaries"])
    keys = [
        k for k in rt.cache._cache.keys()
        if k[0] == "map_summarize" and k[1] == "quant-sum"
    ]
    assert keys and all(("quant", "int8") in k[-1] for k in keys)


def test_summarize_int8_tp_matches_replicated(rt, rt_tp):
    from agent_tpu.ops import get_op

    summarize = get_op("map_summarize")
    cfg = {
        "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
        "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
        "quant": "int8",
    }
    payload = {
        "texts": ["a long document about int8 tensor parallel " * 3] * 4,
        "max_length": 8,
        "model_config": cfg,
        "model_path": "quant-sum-tp",
    }
    a = summarize(dict(payload), OpContext(runtime=rt))
    b = summarize(dict(payload), OpContext(runtime=rt_tp))
    assert a["ok"] and b["ok"]
    assert a["summaries"] == b["summaries"]


def test_t5_bart_quantize_trees_close():
    """Quantized BART/T5 teacher-forced logits track the f32 forward — the
    whole-tree transformers hit every hot matmul without breaking shapes."""
    from agent_tpu.models import bart as bart_mod
    from agent_tpu.models import layers as L

    cfg = bart_mod.BartConfig(
        vocab_size=64, d_model=32, n_heads=4, n_enc_layers=1, n_dec_layers=1,
        d_ff=64, max_position=64, dtype="float32",
    )
    rng = np.random.default_rng(1)

    def dense(i, o):
        return {
            "w": rng.normal(size=(i, o), scale=0.1).astype(np.float32),
            "b": rng.normal(size=(o,), scale=0.01).astype(np.float32),
        }

    def ln(d):
        return {
            "scale": np.ones(d, np.float32), "bias": np.zeros(d, np.float32)
        }

    def attn():
        d = cfg.d_model
        return {"q": dense(d, d), "k": dense(d, d), "v": dense(d, d),
                "o": dense(d, d)}

    def blk(cross):
        p = {"self": attn(), "ln1": ln(cfg.d_model),
             "fc1": dense(cfg.d_model, cfg.d_ff),
             "fc2": dense(cfg.d_ff, cfg.d_model), "ln2": ln(cfg.d_model)}
        if cross:
            p["cross"] = attn()
            p["ln_x"] = ln(cfg.d_model)
        return p

    params = {
        "embed": rng.normal(size=(cfg.vocab_size, cfg.d_model), scale=0.1)
        .astype(np.float32),
        "final_logits_bias": np.zeros(cfg.vocab_size, np.float32),
        "enc": {
            "pos": rng.normal(
                size=(cfg.max_position + 2, cfg.d_model), scale=0.02
            ).astype(np.float32),
            "ln_emb": ln(cfg.d_model),
            "layers": [blk(False)],
        },
        "dec": {
            "pos": rng.normal(
                size=(cfg.max_position + 2, cfg.d_model), scale=0.02
            ).astype(np.float32),
            "ln_emb": ln(cfg.d_model),
            "layers": [blk(True)],
        },
    }
    src = rng.integers(4, 60, size=(2, 10)).astype(np.int32)
    mask = np.ones((2, 10), np.int32)
    tgt = rng.integers(4, 60, size=(2, 6)).astype(np.int32)
    enc = bart_mod.encode(params, src, mask, cfg)
    want = np.asarray(bart_mod.decode_full(params, tgt, enc, mask, cfg))
    qp = quant.quantize_bart(params)
    enc_q = bart_mod.encode(qp, src, mask, cfg)
    got = np.asarray(bart_mod.decode_full(qp, tgt, enc_q, mask, cfg))
    assert np.abs(got - want).max() < 0.05 * np.abs(want).max() + 1e-3
    # Unquantized leaves pass through untouched.
    assert qp["embed"] is params["embed"]
    assert L.count_params(params) > 0  # tree still walkable


# ---- W8A16 op contract ----


def test_classify_w8a16_through_op(rt):
    from agent_tpu.ops import get_op

    classify = get_op("map_classify_tpu")
    texts = [f"w8a16 contract row {i}" for i in range(8)]
    base = {
        "texts": texts, "topk": 3, "model_path": "w8a16-op",
        "allow_fallback": False, "result_format": "columnar",
    }
    a = classify({**base, "model_config": QCFG}, OpContext(runtime=rt))
    b = classify(
        {**base, "model_config": {**QCFG, "quant": "w8a16"}},
        OpContext(runtime=rt),
    )
    assert a["ok"] and b["ok"]
    assert len(b["indices"]) == len(texts) and len(b["indices"][0]) == 3
    # w8a16 compiles/caches under its own key (distinct cfg fingerprint).
    keys = list(rt.cache._cache.keys())
    w_keys = [
        k for k in keys
        if k[0] == "map_classify_tpu" and ("quant", "w8a16") in k[-1]
    ]
    assert w_keys, f"no w8a16-keyed executable in {keys}"
    top1_a = [row[0] for row in a["indices"]]
    top1_b = [row[0] for row in b["indices"]]
    agree = np.mean([x == y for x, y in zip(top1_a, top1_b)])
    assert agree >= 0.75


def test_summarize_w8a16_through_op(rt):
    from agent_tpu.ops import get_op

    summarize = get_op("map_summarize")
    cfg = {
        "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
        "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
    }
    payload = {
        "texts": ["a w8a16 document about weight-only decoding " * 3] * 4,
        "max_length": 8,
        "num_beams": 4,  # the decode mode the W8A16 path targets
        "model_config": {**cfg, "quant": "w8a16"},
        "model_path": "w8a16-sum",
    }
    out = summarize(dict(payload), OpContext(runtime=rt))
    assert out["ok"] is True
    assert len(out["summaries"]) == 4
    assert all(isinstance(s, str) for s in out["summaries"])
    keys = [
        k for k in rt.cache._cache.keys()
        if k[0] == "map_summarize" and k[1] == "w8a16-sum"
    ]
    assert keys and all(("quant", "w8a16") in k[-1] for k in keys)


def test_summarize_w8a16_tp_matches_replicated(rt, rt_tp):
    from agent_tpu.ops import get_op

    summarize = get_op("map_summarize")
    cfg = {
        "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
        "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
        "quant": "w8a16",
    }
    payload = {
        "texts": ["a long document about w8a16 tensor parallel " * 3] * 4,
        "max_length": 8,
        "model_config": cfg,
        "model_path": "w8a16-sum-tp",
    }
    a = summarize(dict(payload), OpContext(runtime=rt))
    b = summarize(dict(payload), OpContext(runtime=rt_tp))
    assert a["ok"] and b["ok"]
    assert a["summaries"] == b["summaries"]


def test_w8a16_params_actually_sharded_and_int8(rt_tp):
    """On the tp mesh the resident W8A16 tables are int8 dtype AND
    head-sharded — the spec-tree twin transforms the same paths as int8's,
    so the HBM-bytes win and the tp win compose."""
    from agent_tpu.models.encoder import EncoderConfig
    from agent_tpu.ops import get_op
    from agent_tpu.ops._model_common import cfg_key

    cfg_dict = {**QCFG, "n_heads": 8, "quant": "w8a16"}
    get_op("map_classify_tpu")(
        {"texts": ["w8a16 shard check"], "model_config": cfg_dict,
         "model_path": "w8a16-shardcheck", "allow_fallback": False},
        OpContext(runtime=rt_tp),
    )
    cfg = EncoderConfig(**cfg_dict)
    key = (
        "params",
        f"w8a16-shardcheck#encoder#{hash(cfg_key(cfg)) & 0xFFFFFFFF:08x}",
        "tp",
    )
    params = rt_tp._params.get_or_build(
        key, lambda: pytest.fail("w8a16 params not cached under the tp key")
    )
    wq = params["blocks"][0]["attn"]["wq"]
    assert set(wq) == {"w8", "w_scale"}
    assert wq["w8"].dtype == jnp.int8
    shard = wq["w8"].sharding.shard_shape(wq["w8"].shape)
    assert shard[1] == wq["w8"].shape[1] // 2        # heads over tp=2
    scale_shard = wq["w_scale"].sharding.shard_shape(wq["w_scale"].shape)
    assert scale_shard[0] == wq["w_scale"].shape[0] // 2  # scales follow


def test_w8a16_env_switch(rt, monkeypatch):
    """TPU_QUANT=w8a16 turns weight-only serving on without payload
    changes — the same env path as int8."""
    from agent_tpu.ops import get_op

    monkeypatch.setenv("TPU_QUANT", "w8a16")
    out = get_op("map_classify_tpu")(
        {"texts": ["w8a16 env switch row"], "topk": 3, "model_config": QCFG,
         "model_path": "w8a16-env", "allow_fallback": False},
        OpContext(runtime=rt),
    )
    assert out["ok"] is True
    keys = [
        k for k in rt.cache._cache.keys()
        if k[0] == "map_classify_tpu" and k[1] == "w8a16-env"
    ]
    assert keys and all(("quant", "w8a16") in k[-1] for k in keys)


def test_bad_env_quant_fails_shard_not_soft(rt, monkeypatch):
    """A TPU_QUANT typo is a worker deployment misconfig: the shard must FAIL
    (→ controller retry / visible error), not soft-drop as caller bad_input."""
    from agent_tpu.ops import get_op

    monkeypatch.setenv("TPU_QUANT", "int8x")
    with pytest.raises(RuntimeError, match="TPU_QUANT"):
        get_op("map_classify_tpu")(
            {"texts": ["x"], "model_config": QCFG},
            OpContext(runtime=rt),
        )
    with pytest.raises(RuntimeError, match="TPU_QUANT"):
        get_op("map_summarize")(
            {"texts": ["y"], "max_length": 4,
             "model_config": {"d_model": 32, "n_heads": 4, "n_enc_layers": 1,
                              "n_dec_layers": 1, "d_ff": 64,
                              "max_src_len": 64, "max_tgt_len": 8}},
            OpContext(runtime=rt),
        )
