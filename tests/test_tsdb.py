"""Durable telemetry store (ISSUE 20 tentpole a): segment rotation,
tiered downsampling, retention, torn-tail tolerance, restart reopen, and
the ``?since=``/``?step=`` query path over real HTTP."""

import json
import os
import time
import urllib.request

import pytest

from agent_tpu.config import ObsConfig
from agent_tpu.controller.journal import list_segments
from agent_tpu.obs.timeseries import TimeSeriesRing
from agent_tpu.obs.tsdb import (
    TsdbStore,
    quantile_from_bucket_series,
    query_history,
)

KEY = '[["op","x"]]'


def fill(store, t0, n, cadence=10.0, fams=("c_total",)):
    for i in range(n):
        store.append_sample(
            t0 + i * cadence,
            {fam: {KEY: float(i)} for fam in fams},
        )
    store.flush()


def test_raw_samples_round_trip(tmp_path):
    st = TsdbStore(str(tmp_path))
    t0 = 1_700_000_000.0
    fill(st, t0, 50)
    q = st.query("c_total", since=t0)
    assert len(q["series"]) == 1
    pts = q["series"][0]["points"]
    assert len(pts) == 50
    assert pts[0] == [t0, 0.0]
    assert pts[-1] == [t0 + 490.0, 49.0]
    assert q["source"] == "tsdb"
    st.close()


def test_since_until_window(tmp_path):
    st = TsdbStore(str(tmp_path))
    t0 = 1_700_000_000.0
    fill(st, t0, 50)
    q = st.query("c_total", since=t0 + 100, until=t0 + 200)
    pts = q["series"][0]["points"]
    assert all(t0 + 100 <= t <= t0 + 200 for t, _ in pts)
    assert len(pts) == 11
    st.close()


def test_segment_rotation(tmp_path):
    st = TsdbStore(str(tmp_path), segment_max_bytes=512)
    fill(st, 1_700_000_000.0, 100)
    segs = list_segments(os.path.join(str(tmp_path), "tsdb"))
    assert len(segs) > 1
    # Every sample still readable across the rotated files.
    assert len(st.query("c_total", since=0)["series"][0]["points"]) == 100
    st.close()


def test_downsample_preserves_rates(tmp_path):
    """The 1m tier must reproduce the raw counter rate exactly on full
    buckets: sum/count/min/max/last aggregation loses nothing a rate
    needs (edge buckets are partial by construction — excluded)."""
    st = TsdbStore(str(tmp_path))
    t0 = 1_700_000_000.0
    fill(st, t0, 200)  # +1 per 10s => 0.1/s
    q = st.query("c_total", since=t0, step=60, rate=True)
    rates = [v for _, v in q["series"][0]["points"]][1:-1]
    assert rates
    assert all(abs(r - 0.1) < 1e-6 for r in rates)
    assert q["step"] == 60


def test_step_selects_tier(tmp_path):
    st = TsdbStore(str(tmp_path))
    t0 = 1_700_000_000.0
    fill(st, t0, 400, cadence=10.0)
    assert st.query("c_total", since=t0, step=60)["step"] == 60
    assert st.query("c_total", since=t0, step=600)["step"] == 600
    assert not st.query("c_total", since=t0)["step"]  # raw tier


def test_agg_points_carry_min_max(tmp_path):
    st = TsdbStore(str(tmp_path))
    t0 = 1_700_000_000.0
    for i in range(120):
        st.append_sample(t0 + i * 5, {"g": {KEY: float(i % 10)}})
    st.flush()
    q = st.query("g", since=t0, step=60)
    aggs = q["series"][0]["agg_points"]
    assert aggs
    # Interior buckets saw the full 0..9 sawtooth.
    for _t1, _s, n, mn, mx in aggs[1:-1]:
        assert mn == 0.0 and mx == 9.0 and n == 12


def test_torn_tail_skipped_on_reopen(tmp_path):
    st = TsdbStore(str(tmp_path))
    t0 = 1_700_000_000.0
    fill(st, t0, 30)
    st.close()
    segs = list_segments(os.path.join(str(tmp_path), "tsdb"))
    with open(segs[-1][1], "a", encoding="utf-8") as f:
        f.write('{"ev":"s","wall":170')  # the mid-append crash
    st2 = TsdbStore(str(tmp_path))
    assert len(st2.query("c_total", since=0)["series"][0]["points"]) == 30
    st2.append_sample(t0 + 900, {"c_total": {KEY: 30.0}})
    st2.flush()
    assert len(st2.query("c_total", since=0)["series"][0]["points"]) == 31
    st2.close()


def test_restart_reopens_history(tmp_path):
    t0 = 1_700_000_000.0
    st = TsdbStore(str(tmp_path))
    fill(st, t0, 40)
    st.close()
    st2 = TsdbStore(str(tmp_path))
    assert len(st2.query("c_total", since=0)["series"][0]["points"]) == 40
    st2.close()


def test_byte_retention_drops_oldest_raw_first(tmp_path):
    st = TsdbStore(str(tmp_path), segment_max_bytes=512, max_bytes=2048)
    t0 = 1_700_000_000.0
    fill(st, t0, 400)
    removed = st.gc(now=t0 + 5000)
    assert removed > 0
    total = 0
    for base in ("tsdb", "tsdb-60", "tsdb-600"):
        for _seq, path in list_segments(os.path.join(str(tmp_path), base)):
            total += os.path.getsize(path)
    # The cap, plus one never-evicted active segment per tier.
    assert total <= 2048 + 3 * 512
    # The newest raw samples survive; the oldest were collected.
    pts = st.query("c_total", since=0)["series"][0]["points"]
    assert pts and pts[-1][1] == 399.0
    assert pts[0][1] > 0.0
    st.close()


def test_age_retention(tmp_path):
    st = TsdbStore(str(tmp_path), segment_max_bytes=256,
                   retention_raw_sec=60.0)
    t0 = time.time() - 10_000
    fill(st, t0, 100)
    base = os.path.join(str(tmp_path), "tsdb")
    old = os.path.getmtime(list_segments(base)[0][1])
    os.utime(list_segments(base)[0][1], (old - 10_000, old - 10_000))
    st.gc(now=time.time())
    # The backdated sealed segment is gone; the active one never is.
    assert len(list_segments(base)) >= 1
    st.close()


def test_quantile_from_downsampled_buckets(tmp_path):
    """Merged-histogram quantiles stay computable from the agg tier:
    per-le-slot counters aggregate with min/max, and the windowed
    increase feeds histogram_quantile within one bucket width."""
    st = TsdbStore(str(tmp_path))
    t0 = 1_700_000_000.0
    edges = ["0.1", "0.5", "1.0", "+Inf"]
    # 240 samples; each observation lands in the 0.5..1.0 bucket.
    for i in range(240):
        data = {}
        for le in edges:
            key = json.dumps(sorted([["op", "x"], ["le", le]]),
                             separators=(",", ":"))
            grow = float(i) if le in ("1.0", "+Inf") else 0.0
            data.setdefault("h_bucket", {})[key] = grow
        st.append_sample(t0 + i * 10, data)
    st.flush()
    for step in (None, 60, 600):
        q = st.query("h_bucket", since=t0, step=step)
        est = quantile_from_bucket_series(q["series"], 0.99)
        assert est is not None
        assert 0.5 <= est <= 1.0, (step, est)
    st.close()


def test_query_history_ring_fallback():
    ring = TimeSeriesRing(window_sec=300, interval_sec=1,
                          clock=lambda: 0.0)
    t0 = 1_700_000_000.0
    for i in range(20):
        ring.append_flat(t0 + i, {"g": {KEY: float(i)}}, now=float(i))
    out = query_history("g", since=t0 + 10, ring=ring, store=None)
    assert out["source"] == "ring"
    assert len(out["series"][0]["points"]) == 10


def test_ring_on_sample_hook_persists_every_sample(tmp_path):
    st = TsdbStore(str(tmp_path))
    ring = TimeSeriesRing(window_sec=300, interval_sec=0.0,
                          clock=lambda: 0.0)
    ring.on_sample = lambda wall, mono, data: st.append_sample(wall, data)
    t0 = 1_700_000_000.0
    for i in range(15):
        ring.append_flat(t0 + i, {"g": {KEY: float(i)}}, now=float(i))
    st.flush()
    assert len(st.query("g", since=0)["series"][0]["points"]) == 15
    st.close()


def test_append_never_raises_after_close(tmp_path):
    st = TsdbStore(str(tmp_path))
    st.close()
    st.append_sample(1.0, {"g": {KEY: 1.0}})  # must swallow, not raise
    assert st.stats()["append_errors"] >= 0


def test_controller_http_since_step(tmp_path):
    """End to end: sweeper persists ring samples, ``GET /v1/timeseries``
    serves history with ``?since=``/``?step=``, a restarted controller
    still serves the first incarnation's samples."""
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer

    obs = ObsConfig(tsdb_dir=str(tmp_path), tsdb_interval_sec=0.02)
    c = Controller(journal_path=None, obs=obs, sweep_interval_sec=0.02)
    c.submit("echo", {})
    for _ in range(6):
        c.sweep()
        time.sleep(0.03)
    srv = ControllerServer(c, host="127.0.0.1", port=0)
    srv.start()
    try:
        url = (srv.url + "/v1/timeseries"
               "?name=controller_queue_depth&since=600")
        with urllib.request.urlopen(url, timeout=5) as resp:
            body = json.loads(resp.read())
    finally:
        srv.stop()
        c.close()
    assert body["source"] == "tsdb"
    assert body["series"] and body["series"][0]["points"]
    n_first = len(body["series"][0]["points"])

    c2 = Controller(journal_path=None, obs=obs, sweep_interval_sec=0.02)
    out = c2.timeseries_json("controller_queue_depth",
                             since=time.time() - 600)
    c2.close()
    assert out["source"] == "tsdb"
    assert len(out["series"][0]["points"]) >= n_first


def test_tsdb_disabled_without_dir():
    from agent_tpu.controller.core import Controller

    c = Controller(journal_path=None, obs=ObsConfig(tsdb_dir=""))
    try:
        assert c.tsdb_store is None
        out = c.timeseries_json("controller_queue_depth", since=0.0)
        assert out["source"] == "ring"
    finally:
        c.close()


def test_export_cursor_strictly_newer(tmp_path):
    from agent_tpu.controller.core import Controller

    obs = ObsConfig(tsdb_dir=str(tmp_path), tsdb_interval_sec=0.0)
    c = Controller(journal_path=None, obs=obs)
    try:
        c.sweep()
        first = c.timeseries_export_json(since=0.0)
        assert first["samples"]
        cursor = max(s["wall"] for s in first["samples"])
        again = c.timeseries_export_json(since=cursor)
        assert not again["samples"]
        # The ring's sampling interval clamps at 50ms — wait it out.
        time.sleep(0.06)
        c.sweep()
        newer = c.timeseries_export_json(since=cursor)
        assert newer["samples"]
        assert all(s["wall"] > cursor for s in newer["samples"])
    finally:
        c.close()
