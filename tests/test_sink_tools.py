"""Sink validate/merge tools (``data/sink.py``): the consumer side of the
``output_uri`` shard-file contract."""

import json

import pytest

from agent_tpu.data.sink import main as sink_main
from agent_tpu.data.sink import merge_sink, scan_sink, validate_sink


def _write_shard(d, op, start, rows):
    path = d / f"{op}_rows_{start:012d}.jsonl"
    path.write_text(
        "".join(json.dumps({"row": start + i}) + "\n" for i in range(rows))
    )
    return path


def test_validate_and_merge_roundtrip(tmp_path):
    for start, n in [(0, 4), (4, 4), (8, 2)]:
        _write_shard(tmp_path, "map_summarize", start, n)
    _write_shard(tmp_path, "map_classify_tpu", 0, 3)  # other op: ignored

    out = validate_sink(str(tmp_path), "map_summarize", total_rows=10)
    assert out["shards"] == 3 and out["rows"] == 10

    merged = tmp_path / "merged.jsonl"
    merge_sink(str(tmp_path), "map_summarize", str(merged), total_rows=10)
    rows = [json.loads(ln) for ln in merged.read_text().splitlines()]
    assert [r["row"] for r in rows] == list(range(10))  # dataset row order


def test_validate_detects_gap_overlap_and_total(tmp_path):
    _write_shard(tmp_path, "op", 0, 4)
    _write_shard(tmp_path, "op", 8, 2)  # rows 4..7 missing
    with pytest.raises(ValueError, match="gap"):
        validate_sink(str(tmp_path), "op")

    _write_shard(tmp_path, "op", 4, 5)  # covers 4..8 → overlaps shard at 8
    with pytest.raises(ValueError, match="overlap"):
        validate_sink(str(tmp_path), "op")

    d2 = tmp_path / "short"
    d2.mkdir()
    _write_shard(d2, "op", 0, 4)
    with pytest.raises(ValueError, match="mismatch"):
        validate_sink(str(d2), "op", total_rows=9)
    with pytest.raises(ValueError, match="no 'missing_op'"):
        validate_sink(str(d2), "missing_op")


def test_cli_shapes(tmp_path, capsys):
    _write_shard(tmp_path, "op", 0, 2)
    rc = sink_main(["validate", str(tmp_path), "--op", "op",
                    "--total-rows", "2"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"] is True and out["rows"] == 2

    rc = sink_main(["validate", str(tmp_path), "--op", "op",
                    "--total-rows", "5"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["ok"] is False and "mismatch" in out["error"]


def test_validates_real_op_output(tmp_path):
    """End to end with the actual classify sink writer."""
    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext
    from agent_tpu.runtime.runtime import get_runtime

    classify = get_op("map_classify_tpu")
    ctx = OpContext(runtime=get_runtime())
    for start in (0, 3):
        out = classify(
            {"texts": [f"row {start + i}" for i in range(3)],
             "output_uri": str(tmp_path), "start_row": start,
             "allow_fallback": False},
            ctx,
        )
        assert out["ok"] is True
    summary = validate_sink(str(tmp_path), "map_classify_tpu", total_rows=6)
    assert summary["rows"] == 6
    assert len(scan_sink(str(tmp_path), "map_classify_tpu")) == 2
