"""Online serving front door (ISSUE 15): batch bucketer, continuous-batching
decode engine, controller /v1/infer path, HTTP routes.

The engine correctness tests pin the acceptance bar: tokens emitted per
request through the continuous engine — with early joins and exits, beam
included — are BIT-IDENTICAL to a solo static-batch decode of the same
request.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from agent_tpu.config import ServeConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.serving import ServeFrontDoor
from agent_tpu.sched import AdmissionError

TINY_S2S = {
    "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
    "d_ff": 64, "max_src_len": 32, "max_tgt_len": 20, "dtype": "float32",
}
TINY_CLS = {
    "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
    "max_len": 64, "dtype": "float32", "n_classes": 8,
}


class FakeClock:
    def __init__(self, t: float = 100.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# batch bucketer
# ---------------------------------------------------------------------------

class TestServeBatcher:
    def make(self, **kw):
        clock = FakeClock()
        defaults = dict(max_wait_ms=50.0, max_batch=4, max_pending=0)
        defaults.update(kw)
        return ServeFrontDoor(ServeConfig(**defaults), clock=clock), clock

    def test_bucket_overflow_flushes_immediately(self):
        door, _ = self.make(max_batch=3)
        flushed = []
        for _ in range(7):
            _req, full = door.submit("summarize", "same length text")
            flushed.extend(full)
        # 7 same-bucket requests at max_batch 3 → two full flushes, one
        # request still waiting on the deadline.
        assert [len(b.requests) for b in flushed] == [3, 3]
        assert all(b.reason == "full" for b in flushed)
        assert door.stats()["bucketed"] == 1

    def test_deadline_flush(self):
        door, clock = self.make(max_wait_ms=50.0, max_batch=16)
        door.submit("summarize", "a text")
        clock.advance(0.02)
        assert door.pop_due() == []          # oldest has waited only 20ms
        clock.advance(0.04)
        due = door.pop_due()
        assert len(due) == 1 and due[0].reason == "deadline"
        assert len(due[0].requests) == 1

    def test_empty_queue_stays_idle(self):
        door, clock = self.make()
        clock.advance(10.0)
        assert door.pop_due() == []
        assert door.stats()["open_buckets"] == 0

    def test_buckets_split_by_op_params_tenant_and_length(self):
        door, _ = self.make(max_batch=16)
        door.submit("summarize", "short")
        door.submit("summarize", "x" * 500)                  # other length
        door.submit("summarize", "short", params={"num_beams": 4})
        door.submit("summarize", "short", tenant="acme")
        door.submit("classify", "short")
        assert door.stats()["open_buckets"] == 5

    def test_max_length_is_per_request_not_bucket(self):
        door, _ = self.make(max_batch=2)
        door.submit("summarize", "text a", params={"max_length": 4})
        _, full = door.submit("summarize", "text b",
                              params={"max_length": 9})
        (batch,) = full  # same bucket despite different budgets
        payload = batch.job_payload()
        assert [r["max_length"] for r in payload["requests"]] == [4, 9]

    def test_admission_budget_429(self):
        door, _ = self.make(max_pending=2, max_batch=16)
        door.submit("classify", "one")
        door.submit("classify", "two")
        with pytest.raises(AdmissionError):
            door.submit("classify", "three")
        assert door.rejected == 1

    def test_malformed_requests_raise(self):
        door, _ = self.make()
        with pytest.raises(ValueError):
            door.submit("transcribe", "text")        # unknown op
        with pytest.raises(ValueError):
            door.submit("classify", "")              # empty text
        with pytest.raises(ValueError):
            door.submit("classify", "x", params={"bogus": 1})
        with pytest.raises(ValueError):
            door.submit("classify", "x", priority=99)

    def test_completion_fan_out_and_wait(self):
        door, _ = self.make(max_batch=2)
        r1, _ = door.submit("summarize", "text a")
        r2, full = door.submit("summarize", "text b")
        (batch,) = full
        door.mark_batched(batch, "job-1")
        assert door.get(r1.req_id).state == "batched"
        done = door.complete_job("job-1", True, result={"results": [
            {"req_id": r1.req_id, "summary": "s1", "tokens": 3,
             "ttft_ms": 12.0},
            {"req_id": r2.req_id, "summary": "s2", "tokens": 5,
             "ttft_ms": 15.0},
        ]})
        assert {d.req_id for d in done} == {r1.req_id, r2.req_id}
        snap = door.snapshot(r1.req_id)
        assert snap["state"] == "done"
        assert snap["result"]["summary"] == "s1"
        assert snap["ttft_ms"] == 12.0
        # waiting on an already-terminal request returns immediately
        assert door.wait(r2.req_id, 0.0)["state"] == "done"
        # unknown job fan-out is a no-op
        assert door.complete_job("job-1", True, result={}) == []

    def test_failed_job_fails_riders(self):
        door, _ = self.make(max_batch=1)
        req, full = door.submit("summarize", "text")
        door.mark_batched(full[0], "job-f")
        (done,) = door.complete_job(
            "job-f", False, error={"type": "Boom", "message": "x"}
        )
        assert done.state == "failed"
        assert door.snapshot(req.req_id)["error"]["type"] == "Boom"

    def test_missing_result_entry_fails_that_rider(self):
        door, _ = self.make(max_batch=2)
        r1, _ = door.submit("summarize", "a")
        r2, full = door.submit("summarize", "b")
        door.mark_batched(full[0], "job-m")
        door.complete_job("job-m", True, result={"results": [
            {"req_id": r1.req_id, "summary": "s", "tokens": 1},
        ]})
        assert door.snapshot(r1.req_id)["state"] == "done"
        assert door.snapshot(r2.req_id)["state"] == "failed"


# ---------------------------------------------------------------------------
# continuous-batching engine correctness
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def s2s():
    from agent_tpu.models import seq2seq

    cfg = seq2seq.Seq2SeqConfig(**TINY_S2S)
    params = seq2seq.init_params(cfg, model_id="serving-test")
    return cfg, params


def _requests(cfg, n, seed=0, src_len=16):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        real = int(rng.integers(4, src_len))
        ids = rng.integers(4, cfg.vocab_size, (1, src_len)).astype(np.int32)
        mask = np.zeros((1, src_len), np.int32)
        mask[0, :real] = 1
        limit = int(rng.integers(2, cfg.max_tgt_len))
        out.append((ids, mask, limit))
    return out


def _solo(cfg, params, ids, mask, limit, num_beams):
    import jax.numpy as jnp

    from agent_tpu.models import seq2seq

    if num_beams == 1:
        toks, _ = seq2seq.greedy_generate(
            params, jnp.asarray(ids), jnp.asarray(mask), cfg, limit
        )
    else:
        toks, _ = seq2seq.beam_generate(
            params, jnp.asarray(ids), jnp.asarray(mask), cfg, limit,
            num_beams=num_beams,
        )
    return np.asarray(toks)[0]


def _engine(cfg, params, num_beams, slots=3, src_len=16, **kw):
    from agent_tpu.models import seq2seq
    from agent_tpu.models.decoding import ContinuousBatcher
    from agent_tpu.models.tokenizer import BOS_ID, EOS_ID, PAD_ID

    return ContinuousBatcher(
        seq2seq.make_positional_step(params, cfg),
        seq2seq.make_cache_factory(cfg),
        slots=slots, vocab_size=cfg.vocab_size, max_tokens=cfg.max_tgt_len,
        enc_len=src_len, d_model=cfg.d_model,
        start_id=BOS_ID, eos_id=EOS_ID, pad_id=PAD_ID,
        num_beams=num_beams, **kw,
    )


def _encode(cfg, params, ids, mask):
    import jax
    import jax.numpy as jnp

    from agent_tpu.models import seq2seq

    return np.asarray(jax.jit(
        lambda p, i, m: seq2seq.encode(p, i, m, cfg).astype(jnp.float32)
    )(params, jnp.asarray(ids), jnp.asarray(mask)))


@pytest.mark.parametrize("num_beams", [1, 3])
def test_continuous_engine_bit_identical_with_joins_and_exits(
    s2s, num_beams
):
    """The acceptance bar: staggered joins (mid-decode, via the backlog)
    and early exits (per-request limits freeing slots) leave every
    request's emitted tokens EXACTLY equal to its solo decode."""
    cfg, params = s2s
    reqs = _requests(cfg, 7, seed=num_beams)
    solos = [
        _solo(cfg, params, ids, mask, limit, num_beams)
        for ids, mask, limit in reqs
    ]
    engine = _engine(cfg, params, num_beams, slots=3)
    done = []
    # 4 requests up front (one exceeds capacity → backlog), the rest join
    # mid-flight every other step.
    for i in range(4):
        ids, mask, limit = reqs[i]
        engine.admit(_encode(cfg, params, ids, mask)[0], mask[0], limit,
                     data=i)
    pending = list(range(4, len(reqs)))
    while engine.has_work():
        done.extend(engine.step())
        if pending and engine.steps_run % 2 == 0:
            i = pending.pop(0)
            ids, mask, limit = reqs[i]
            engine.admit(_encode(cfg, params, ids, mask)[0], mask[0],
                         limit, data=i)
    assert len(done) == len(reqs)
    assert engine.max_occupancy == 3           # capacity actually shared
    for ticket in done:
        i = ticket.data
        limit = reqs[i][2]
        assert np.array_equal(ticket.tokens[:limit], solos[i][:limit]), (
            f"request {i} (beams={num_beams}) diverged from solo decode"
        )
        assert ticket.first_token_wall is not None
        assert ticket.steps <= limit


def test_engine_backlog_joins_between_steps(s2s):
    cfg, params = s2s
    reqs = _requests(cfg, 5, seed=9)
    engine = _engine(cfg, params, 1, slots=2)
    for i, (ids, mask, limit) in enumerate(reqs):
        engine.admit(_encode(cfg, params, ids, mask)[0], mask[0], limit,
                     data=i)
    assert engine.occupancy == 2 and engine.backlog == 3
    finished = 0
    while engine.has_work():
        finished += len(engine.step())
        assert engine.occupancy <= 2
    assert finished == 5
    assert engine.mean_occupancy() > 1.0       # the batch stayed shared


def test_engine_per_slot_limits_exit_early(s2s):
    cfg, params = s2s
    ids = np.full((1, 16), 7, np.int32)
    mask = np.ones((1, 16), np.int32)
    engine = _engine(cfg, params, 1, slots=2)
    enc = _encode(cfg, params, ids, mask)[0]
    short = engine.admit(enc, mask[0], 2, data="short")
    long_ = engine.admit(enc, mask[0], 12, data="long")
    order = []
    while engine.has_work():
        order.extend(t.data for t in engine.step())
    assert order[0] == "short"                 # exited at its own limit
    assert short.steps <= 2 and long_.steps <= 12


def test_engine_run_monolithic(s2s):
    cfg, params = s2s
    reqs = _requests(cfg, 3, seed=3)
    engine = _engine(cfg, params, 2, slots=2)
    tickets = [
        engine.admit(_encode(cfg, params, ids, mask)[0], mask[0], limit,
                     data=i)
        for i, (ids, mask, limit) in enumerate(reqs)
    ]
    engine.run(tickets)
    assert all(t.done_wall is not None for t in tickets)


# ---------------------------------------------------------------------------
# controller front door (in-process)
# ---------------------------------------------------------------------------

def _drain_serving(controller, tasks=("serve_classify", "serve_summarize")):
    """Lease + execute serving jobs inline until the queue drains — a
    minimal in-process agent for controller-level tests."""
    from agent_tpu.ops import load_ops
    from agent_tpu.runtime.context import OpContext

    handlers = load_ops(list(tasks))
    for _ in range(50):
        lease = controller.lease(
            agent="test", capabilities={"ops": sorted(handlers)},
            max_tasks=4,
        )
        if lease is None:
            if controller.serve_door.stats()["bucketed"] == 0 \
                    and not controller.serve_door.job_ids():
                return
            time.sleep(0.01)
            continue
        for task in lease["tasks"]:
            fn = handlers[task["op"]]
            result = fn(task["payload"], OpContext())
            controller.report(
                lease_id=lease["lease_id"], job_id=task["id"],
                job_epoch=task["job_epoch"],
                status="succeeded" if result.get("ok") else "failed",
                result=result,
            )


class TestControllerInfer:
    def make(self, **kw):
        defaults = dict(max_wait_ms=0.0, max_batch=4)  # 0ms: flush on pump
        defaults.update(kw)
        return Controller(serve=ServeConfig(**defaults))

    def test_infer_end_to_end_classify_and_summarize(self):
        c = self.make()
        rid_c = c.submit_infer(
            "classify", "classify this text",
            params={"model_config": TINY_CLS, "topk": 2},
        )
        rid_s = c.submit_infer(
            "summarize", "summarize this text",
            params={"model_config": TINY_S2S, "max_length": 4,
                    "num_beams": 2},
        )
        c._serve_pump()
        _drain_serving(c)
        c._serve_reap()
        snap_c = c.infer_snapshot(rid_c)
        snap_s = c.infer_snapshot(rid_s)
        assert snap_c["state"] == "done", snap_c
        assert len(snap_c["result"]["indices"]) == 2
        assert snap_s["state"] == "done", snap_s
        assert isinstance(snap_s["result"]["summary"], str)
        assert snap_s["result"]["tokens"] <= 4
        assert snap_s["ttft_ms"] is not None
        # metrics observed the completions
        snap = c.metrics.snapshot()
        outcomes = {
            (s["labels"]["op"], s["labels"]["outcome"]): s["value"]
            for s in snap["serve_requests_total"]["series"]
        }
        assert outcomes[("classify", "completed")] == 1
        assert outcomes[("summarize", "completed")] == 1

    def test_serve_jobs_ride_interactive_tier_and_tenant(self):
        c = self.make(priority=8)
        c.submit_infer("classify", "text", tenant="acme",
                       params={"model_config": TINY_CLS})
        c._serve_pump()
        (job_id,) = c.serve_door.job_ids()
        job = c.job(job_id)
        assert job.priority == 8
        assert job.tenant == "acme"
        assert job.op == "serve_classify"

    def test_infer_disabled_raises(self):
        c = Controller(serve=ServeConfig(enabled=False))
        with pytest.raises(RuntimeError):
            c.submit_infer("classify", "text")
        assert c.serve_status() == {"enabled": False}

    def test_wait_infer_pumps_the_deadline_flush(self):
        c = self.make(max_wait_ms=10.0)
        done = {}

        def agent_loop():
            deadline = time.monotonic() + 30.0
            while "rid" not in done and time.monotonic() < deadline:
                time.sleep(0.005)
            _drain_serving(c)

        t = threading.Thread(target=agent_loop, daemon=True)
        t.start()
        rid = c.submit_infer("classify", "text",
                             params={"model_config": TINY_CLS})
        done["rid"] = rid
        snap = c.wait_infer(rid, 30.0)
        t.join(timeout=30)
        assert snap["state"] == "done", snap

    def test_slo_ttft_objective_fed(self):
        c = self.make()
        c.submit_infer("summarize", "text",
                       params={"model_config": TINY_S2S, "max_length": 3})
        c._serve_pump()
        _drain_serving(c)
        c._serve_reap()
        results = c.slo.evaluate()
        by_name = {r["objective"]: r for r in results}
        short = by_name["interactive_ttft"]["windows"]["short"]
        assert short["requests"] == 1


# ---------------------------------------------------------------------------
# HTTP routes
# ---------------------------------------------------------------------------

@pytest.fixture()
def http_server():
    requests = pytest.importorskip("requests")
    from agent_tpu.controller.server import ControllerServer

    controller = Controller(
        serve=ServeConfig(max_wait_ms=10.0, max_batch=4)
    )
    server = ControllerServer(controller).start()
    stop = threading.Event()

    def loop():
        from agent_tpu.ops import load_ops
        from agent_tpu.runtime.context import OpContext

        handlers = load_ops(["serve_classify", "serve_summarize"])
        session = requests.Session()
        while not stop.is_set():
            lease = controller.lease(
                agent="http-test", capabilities={"ops": sorted(handlers)},
                max_tasks=4,
            )
            if lease is None:
                time.sleep(0.005)
                continue
            for task in lease["tasks"]:
                fn = handlers[task["op"]]
                out = fn(task["payload"], OpContext())
                controller.report(
                    lease_id=lease["lease_id"], job_id=task["id"],
                    job_epoch=task["job_epoch"],
                    status="succeeded" if out.get("ok") else "failed",
                    result=out,
                )

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    yield server, requests.Session()
    stop.set()
    t.join(timeout=10)
    server.stop()


class TestInferHttp:
    def test_blocking_post(self, http_server):
        server, session = http_server
        r = session.post(server.url + "/v1/infer", json={
            "op": "summarize", "text": "please summarize",
            "params": {"model_config": TINY_S2S, "max_length": 4},
        }, timeout=120)
        assert r.status_code == 200
        body = r.json()
        assert body["state"] == "done", body
        assert isinstance(body["result"]["summary"], str)

    def test_nonblocking_then_get(self, http_server):
        server, session = http_server
        r = session.post(server.url + "/v1/infer", json={
            "op": "classify", "text": "route me", "wait": False,
            "params": {"model_config": TINY_CLS},
        }, timeout=30)
        rid = r.json()["req_id"]
        assert r.json()["state"] == "queued"
        r2 = session.get(
            server.url + f"/v1/infer/{rid}?wait_ms=60000", timeout=120
        )
        assert r2.json()["state"] == "done", r2.json()

    def test_stream_frames_lifecycle(self, http_server):
        import json as _json

        server, session = http_server
        r = session.post(server.url + "/v1/infer", json={
            "op": "summarize", "text": "stream me", "stream": True,
            "params": {"model_config": TINY_S2S, "max_length": 3},
        }, stream=True, timeout=120)
        events = [_json.loads(line) for line in r.iter_lines() if line]
        states = [e["state"] for e in events]
        assert states[0] == "queued"
        assert states[-1] == "done"
        assert "result" in events[-1]

    def test_bad_request_400_and_unknown_404(self, http_server):
        server, session = http_server
        r = session.post(server.url + "/v1/infer", json={
            "op": "transcribe", "text": "x",
        }, timeout=10)
        assert r.status_code == 400
        r2 = session.get(server.url + "/v1/infer/req-nope", timeout=10)
        assert r2.status_code == 404

    def test_admission_429(self):
        requests = pytest.importorskip("requests")
        from agent_tpu.controller.server import ControllerServer

        controller = Controller(serve=ServeConfig(
            max_wait_ms=10_000.0, max_batch=64, max_pending=1,
        ))
        with ControllerServer(controller) as server:
            s = requests.Session()
            r1 = s.post(server.url + "/v1/infer", json={
                "op": "classify", "text": "one", "wait": False,
            }, timeout=10)
            assert r1.status_code == 200
            r2 = s.post(server.url + "/v1/infer", json={
                "op": "classify", "text": "two", "wait": False,
            }, timeout=10)
            assert r2.status_code == 429
            assert "retry_after_ms" in r2.json()
            assert r2.headers.get("Retry-After")

    def test_status_serving_block(self, http_server):
        server, session = http_server
        st = session.get(server.url + "/v1/status", timeout=10).json()
        assert st["serving"]["enabled"] is True


# ---------------------------------------------------------------------------
# request-level observability (ISSUE 17)
# ---------------------------------------------------------------------------

def _hist_count(controller, family, **labels):
    for s in controller.metrics.snapshot().get(family, {}).get("series", []):
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s["count"]
    return 0


class TestRequestObservability:
    def test_flush_reasons_counted_and_bucket_wait_component_fed(self):
        """ISSUE 17 satellite: full and deadline flushes count distinctly
        in serve_batches_total, and BOTH paths feed the bucket_wait
        component histogram once their riders complete."""
        c = Controller(serve=ServeConfig(max_wait_ms=0.0, max_batch=2))
        # Same bucket twice -> the second submit fills it: reason "full".
        for text in ("classify this", "classify that!"):
            c.submit_infer("classify", text,
                           params={"model_config": TINY_CLS})
        assert c._m_serve_batches.value(op="classify", reason="full") == 1
        # A lone rider flushes on the pump cadence: reason "deadline".
        c.submit_infer("classify", "straggler text",
                       params={"model_config": TINY_CLS})
        c._serve_pump()
        assert c._m_serve_batches.value(
            op="classify", reason="deadline"
        ) == 1
        _drain_serving(c)
        c._serve_reap()
        assert _hist_count(
            c, "serve_ttft_component_seconds", component="bucket_wait"
        ) == 3
        reasons = {
            r["flush_reason"] for r in c.requests_json()["requests"]
        }
        assert reasons == {"full", "deadline"}

    def test_stitched_request_trace_and_wide_record(self):
        """Tentpole acceptance (colocated): a completed request resolves to
        ONE complete trace linked into its batch job, its TTFT components
        telescope to the measured TTFT, and the wide-event record carries
        the full schema."""
        c = Controller(serve=ServeConfig(max_wait_ms=0.0, max_batch=4))
        rid = c.submit_infer(
            "summarize", "summarize this text",
            params={"model_config": TINY_S2S, "max_length": 5},
        )
        c._serve_pump()
        _drain_serving(c)
        c._serve_reap()
        (rec,) = c.requests_json()["requests"]
        assert rec["req_id"] == rid and rec["outcome"] == "completed"
        assert rec["path"] == "colocated"
        for key in ("tenant", "op", "bucket", "priority", "ttft_ms",
                    "tpot_ms", "tokens", "steps", "prefix_hit",
                    "kv_wait_ms", "occupancy", "components",
                    "dominant_component", "trace_id", "job_id"):
            assert key in rec, key
        comps = rec["components"]
        assert set(comps) == {"bucket_wait", "queue_wait", "prefill",
                              "handoff", "kv_wait", "first_decode"}
        assert abs(sum(comps.values()) - rec["ttft_ms"]) <= \
            max(1.0, 0.1 * rec["ttft_ms"])
        tr = c.trace_json(rid)
        assert tr is not None and tr["complete"], tr
        names = {s["name"] for s in tr["spans"]}
        assert {"infer", "bucket.wait", "ttft.first_decode",
                "decode"} <= names
        linked = {lt["trace_id"] for lt in tr.get("linked_traces", [])}
        assert rec["job_id"] in linked
        # And the batch job's trace names this rider back.
        job_tr = c.trace_json(rec["job_id"])
        assert rid in {
            lt["trace_id"] for lt in job_tr.get("linked_traces", [])
        }

    def test_disagg_dep_failed_emits_dep_failed_record(self):
        """ISSUE 17 satellite: riders of a serve_decode job killed by a
        dead prefill dependency get outcome=dep_failed in the request
        log (not a generic failure)."""
        c = Controller(serve=ServeConfig(
            max_wait_ms=0.0, max_batch=4, disaggregated=True,
        ))
        rid = c.submit_infer(
            "summarize", "doomed request",
            params={"model_config": TINY_S2S, "max_length": 4},
        )
        c._serve_pump()
        # The front door watches the DECODE job; its dependency is the
        # prefill leg. Fail that leg to death: permanent error, retries
        # exhausted.
        (decode_id,) = c.serve_door.job_ids()
        (pf_id,) = c.job(decode_id).after
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if c.job(pf_id).state in ("failed", "dead"):
                break
            lease = c.lease(
                agent="t", capabilities={"ops": ["serve_prefill"]},
                max_tasks=1,
            )
            if lease is None:
                time.sleep(0.005)
                continue
            for task in lease["tasks"]:
                c.report(
                    lease_id=lease["lease_id"], job_id=task["id"],
                    job_epoch=task["job_epoch"], status="failed",
                    error={"type": "ValueError", "message": "boom"},
                )
        c._serve_pump()
        snap = c.infer_snapshot(rid)
        assert snap["state"] == "failed", snap
        (rec,) = c.requests_json()["requests"]
        assert rec["outcome"] == "dep_failed", rec
        assert rec["error"] == "DependencyFailed"
        # The request's root span closed with the verdict.
        tr = c.trace_json(rid)
        root = next(s for s in tr["spans"] if s["name"] == "infer")
        assert root["attributes"]["outcome"] == "dep_failed"
        assert root["duration_ms"] is not None

    def test_debug_requests_http_filters_and_jsonl(self, http_server):
        """GET /v1/debug/requests: tenant/outcome/slow filters + JSONL
        export; GET /v1/debug/events?req_id= narrows to one request."""
        import json as _json

        server, session = http_server
        r = session.post(server.url + "/v1/infer", json={
            "op": "classify", "text": "observable request",
            "tenant": "acme", "params": {"model_config": TINY_CLS},
        }, timeout=120)
        assert r.json()["state"] == "done", r.json()
        rid = r.json()["req_id"]
        body = session.get(
            server.url + "/v1/debug/requests?tenant=acme", timeout=10
        ).json()
        assert body["enabled"] and body["requests"]
        assert all(rec["tenant"] == "acme" for rec in body["requests"])
        assert body["stats"]["seen"] >= 1
        none = session.get(
            server.url + "/v1/debug/requests?tenant=nobody", timeout=10
        ).json()
        assert none["requests"] == []
        jl = session.get(
            server.url + "/v1/debug/requests?format=jsonl", timeout=10
        )
        assert jl.headers["Content-Type"].startswith("application/jsonl")
        recs = [_json.loads(line) for line in jl.text.splitlines() if line]
        assert any(rec["req_id"] == rid for rec in recs)
        ev = session.get(
            server.url + f"/v1/debug/events?req_id={rid}", timeout=10
        ).json()["events"]
        assert ev and all(e.get("req_id") == rid for e in ev)
        # The stitched trace resolves over HTTP for a req_id too.
        tr = session.get(
            server.url + f"/v1/trace/{rid}", timeout=10
        ).json()
        assert tr["trace_id"] == rid and tr.get("linked_traces")

    def test_usage_surfaces_prefix_dedupe_ratio(self):
        """ISSUE 17 satellite: /v1/usage exposes the per-tenant share of
        prefill rows the prefix cache absorbed."""
        from agent_tpu.obs.usage import UsageLedger

        ledger = UsageLedger()
        ledger.bill(
            job_id="j1", tenant="acme", tier=5, op="serve_summarize",
            attempt=1,
            usage={"device_s": 1.0, "rows": 6, "cache_hit_rows": 2},
        )
        report = ledger.report()
        assert report["by_tenant"]["acme"]["prefix_dedupe_ratio"] == \
            pytest.approx(0.25)
        assert report["totals"]["prefix_dedupe_ratio"] == \
            pytest.approx(0.25)
