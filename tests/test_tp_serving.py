"""TP in the serving path (VERDICT r2 item 3): on a tp>1 mesh the model ops
serve with Megatron-sharded weights and produce the same results as the
replicated run."""

import jax
import numpy as np
import pytest

from agent_tpu.config import DeviceConfig
from agent_tpu.runtime.context import OpContext
from agent_tpu.runtime.runtime import TpuRuntime


def _runtime(mesh_shape):
    return TpuRuntime(
        config=DeviceConfig(tpu_disabled=True, mesh_shape=mesh_shape),
        devices=jax.devices("cpu")[:8],
    )


@pytest.fixture(scope="module")
def rt_rep():
    return _runtime({"dp": 8, "tp": 1, "sp": 1})


@pytest.fixture(scope="module")
def rt_tp():
    return _runtime({"dp": 4, "tp": 2, "sp": 1})


# f32 keeps the replicated-vs-sharded comparison tight; bf16 rounding would
# swamp the tolerance.
MODEL_CONFIG = {
    "d_model": 64, "n_heads": 8, "n_layers": 2, "d_ff": 128,
    "max_len": 128, "n_classes": 64, "dtype": "float32",
}


def test_classify_params_actually_sharded(rt_tp):
    from agent_tpu.ops import get_op

    get_op("map_classify_tpu")(
        {"texts": ["shard check"], "model_config": MODEL_CONFIG,
         "model_path": "tp-shardcheck", "allow_fallback": False},
        OpContext(runtime=rt_tp),
    )
    params = rt_tp._params.get_or_build(
        ("params", "tp-shardcheck#encoder#" + _cfg_hash(), "tp"),
        lambda: pytest.fail("params were not cached under the tp key"),
    )
    wq = params["blocks"][0]["attn"]["wq"]  # [d_model, heads, d_head]
    # Heads shard over tp=2: each device holds half the heads.
    shard_shape = wq.sharding.shard_shape(wq.shape)
    assert shard_shape[1] == wq.shape[1] // 2
    # Embedding shards the vocab dim (260 % 2 == 0).
    emb = params["embed"]
    assert emb.sharding.shard_shape(emb.shape)[0] == emb.shape[0] // 2


def _cfg_hash():
    from agent_tpu.models.encoder import EncoderConfig
    from agent_tpu.ops._model_common import cfg_key

    cfg = EncoderConfig(**{k: v for k, v in MODEL_CONFIG.items()})
    return f"{hash(cfg_key(cfg)) & 0xFFFFFFFF:08x}"


def test_classify_tp_matches_replicated(rt_rep, rt_tp):
    from agent_tpu.ops import get_op

    classify = get_op("map_classify_tpu")
    payload = {
        "texts": [f"tensor parallel serving row {i}" for i in range(16)],
        "topk": 5,
        "model_config": MODEL_CONFIG,
        "model_path": "tp-vs-rep",
        "allow_fallback": False,
        "result_format": "columnar",
    }
    a = classify(dict(payload), OpContext(runtime=rt_rep))
    b = classify(dict(payload), OpContext(runtime=rt_tp))
    assert a["ok"] and b["ok"]
    assert a["indices"] == b["indices"]
    np.testing.assert_allclose(a["scores"], b["scores"], rtol=1e-4, atol=1e-6)


def test_summarize_tp_matches_replicated(rt_rep, rt_tp):
    from agent_tpu.ops import get_op

    summarize = get_op("map_summarize")
    cfg = {
        "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
        "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
    }
    payload = {
        "texts": ["a long document about tensor parallel serving " * 3] * 4,
        "max_length": 8,
        "model_config": cfg,
        "model_path": "tp-sum",
    }
    a = summarize(dict(payload), OpContext(runtime=rt_rep))
    b = summarize(dict(payload), OpContext(runtime=rt_tp))
    assert a["ok"] and b["ok"]
    assert a["summaries"] == b["summaries"]


def test_indivisible_dims_replicate_not_fail(rt_tp):
    """6 heads on tp=2 shards fine, but a 5-class head (5 % 2) must fall back
    to replication for that leaf and still serve."""
    from agent_tpu.ops import get_op

    out = get_op("map_classify_tpu")(
        {
            "texts": ["odd dims row"],
            "topk": 3,
            "model_config": {
                "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
                "max_len": 64, "n_classes": 5, "vocab_size": 261,
                "dtype": "float32",
            },
            "model_path": "tp-odd",
            "allow_fallback": False,
        },
        OpContext(runtime=rt_tp),
    )
    assert out["ok"] is True and len(out["topk"]) == 3


def test_evict_params_covers_both_placement_modes(rt_rep, rt_tp):
    """Eviction must flush whichever placement mode the id is resident under
    (regression: the mode-suffixed cache key made eviction a silent no-op)."""
    from jax.sharding import PartitionSpec as P

    for rt in (rt_rep, rt_tp):
        builds = []

        def make():
            builds.append(1)
            return {"w": np.zeros((8, 8), np.float32)}

        specs = {"w": P("tp", None)}
        rt.get_params("evict-me", make, specs=specs)
        rt.get_params("evict-me", make, specs=specs)
        assert len(builds) == 1  # cached
        rt.evict_params("evict-me")
        rt.get_params("evict-me", make, specs=specs)
        assert len(builds) == 2  # rebuilt after evict


def test_sanitize_specs_unit():
    import numpy as np

    from jax.sharding import PartitionSpec as P

    from agent_tpu.parallel.shardings import sanitize_specs
    from agent_tpu.runtime.mesh import build_mesh

    mesh = build_mesh(jax.devices("cpu")[:8], {"dp": 4, "tp": 2})
    params = {"a": np.zeros((6, 8)), "b": np.zeros((5, 8)), "c": np.zeros(3)}
    specs = {"a": P("tp", None), "b": P("tp", None), "c": P("tp")}
    out = sanitize_specs(mesh, params, specs)
    assert out["a"] == P("tp", None)   # 6 % 2 == 0 → kept
    assert out["b"] == P()             # 5 % 2 → replicated
    assert out["c"] == P()             # 3 % 2 → replicated
