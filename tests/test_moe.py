"""MoE layer: routing/capacity semantics and expert parallelism over ``ep``."""

import numpy as np

import jax
import jax.numpy as jnp

from agent_tpu.models import moe


CFG = moe.MoeConfig(d_model=16, d_ff=32, n_experts=4, capacity_factor=8.0)


def _tokens(T=32, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(T, CFG.d_model)), dtype=jnp.float32)


def test_moe_matches_per_token_expert_at_high_capacity():
    """With capacity ≥ T no token drops, so the einsum dispatch must equal
    routing each token through its argmax expert directly."""
    params = moe.init_moe_ffn(jax.random.PRNGKey(0), CFG)
    x = _tokens()
    y, aux = moe.moe_ffn(params, x, CFG)

    logits = np.asarray(jnp.dot(x, params["router"]["w"]))
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.zeros_like(np.asarray(x))
    for t in range(x.shape[0]):
        e = int(np.argmax(probs[t]))
        h = np.asarray(jax.nn.gelu(jnp.dot(x[t], params["wi"][e])))
        want[t] = probs[t, e] * np.asarray(jnp.dot(h, params["wo"][e]))
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-4)
    assert np.isfinite(float(aux))


def test_moe_capacity_drops_overflow_tokens_to_zero():
    """capacity_factor → tiny capacity: overflowed tokens emit exactly 0
    (their residual path carries them)."""
    cfg = moe.MoeConfig(d_model=16, d_ff=32, n_experts=2, capacity_factor=0.01)
    params = moe.init_moe_ffn(jax.random.PRNGKey(1), cfg)
    x = _tokens(T=64, seed=1)
    y, _ = moe.moe_ffn(params, x, cfg)
    y = np.asarray(y)
    # capacity = 1 per expert → at most 2 nonzero rows.
    nonzero = (np.abs(y).sum(axis=1) > 1e-9).sum()
    assert nonzero <= 2, nonzero
    assert np.isfinite(y).all()


def test_moe_block_residual_and_jit():
    params = moe.init_moe_block(jax.random.PRNGKey(2), CFG)
    x = jnp.asarray(
        np.random.default_rng(2).normal(size=(2, 8, CFG.d_model)),
        dtype=jnp.float32,
    )
    y, aux = jax.jit(lambda p, x: moe.moe_block(p, x, CFG))(params, x)
    assert y.shape == x.shape and np.isfinite(np.asarray(y)).all()


def test_moe_ep_sharded_matches_unsharded():
    """Experts sharded over an 8-way (dp=2, ep=4) mesh must reproduce the
    single-device result — the all-to-all XLA inserts is semantics-free."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from agent_tpu.runtime.mesh import build_mesh

    mesh = build_mesh(jax.devices()[:8], {"dp": 2, "ep": 4})
    assert dict(mesh.shape)["ep"] == 4

    params = moe.init_moe_ffn(jax.random.PRNGKey(3), CFG)
    x = _tokens(T=64, seed=3)
    want, aux_want = moe.moe_ffn(params, x, CFG)

    specs = moe.moe_param_specs(CFG)
    sharded_params = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params,
        specs,
    )
    xs = jax.device_put(x, NamedSharding(mesh, P()))
    got, aux_got = jax.jit(
        lambda p, x: moe.moe_ffn(p, x, CFG, mesh=mesh)
    )(sharded_params, xs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )
    assert abs(float(aux_got) - float(aux_want)) < 1e-5


def test_moe_grouped_routing_matches_concat_of_groups():
    """Multi-group routing == routing each group independently (per-group
    capacity, per-group queues), and the padded tail group discards its pad
    outputs. Also proves grouped + ep-sharded compose."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from agent_tpu.runtime.mesh import build_mesh

    params = moe.init_moe_ffn(jax.random.PRNGKey(5), CFG)
    x = _tokens(T=56, seed=5)          # 56 = 3×16 + 8 → padded tail group
    got, aux = moe.moe_ffn(params, x, CFG, group_size=16)

    pad = jnp.zeros((8, CFG.d_model), x.dtype)
    want_rows = []
    for g in range(4):
        chunk = x[16 * g: 16 * (g + 1)]
        if chunk.shape[0] < 16:
            chunk = jnp.concatenate([chunk, pad], axis=0)[:16]
        y, _ = moe.moe_ffn(params, chunk, CFG)   # one group of 16
        want_rows.append(np.asarray(y))
    want = np.concatenate(want_rows, axis=0)[:56]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)

    mesh = build_mesh(jax.devices()[:8], {"dp": 2, "ep": 4})
    sharded_params = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params,
        moe.moe_param_specs(CFG),
    )
    got_sh, aux_sh = jax.jit(
        lambda p, x: moe.moe_ffn(p, x, CFG, mesh=mesh, group_size=16)
    )(sharded_params, jax.device_put(x, NamedSharding(mesh, P())))
    np.testing.assert_allclose(np.asarray(got_sh), want, rtol=1e-5, atol=1e-5)
    assert abs(float(aux_sh) - float(aux)) < 1e-5


def test_moe_aux_loss_ignores_pad_tokens():
    """Aux statistics must exclude the zero-pad rows of a partial tail
    group (a zero row's argmax is expert 0 — counting pads would bias the
    router against it): aux(24 tokens, group 16) == mean of the two
    groups' standalone aux (capacity is generous, so routing is identical
    with or without padding)."""
    params = moe.init_moe_ffn(jax.random.PRNGKey(7), CFG)
    x = _tokens(T=24, seed=7)
    _, aux = moe.moe_ffn(params, x, CFG, group_size=16)
    _, aux_a = moe.moe_ffn(params, x[:16], CFG)
    _, aux_b = moe.moe_ffn(params, x[16:], CFG)
    want = (float(aux_a) + float(aux_b)) / 2.0
    assert abs(float(aux) - want) < 1e-6


def test_moe_empty_input():
    params = moe.init_moe_ffn(jax.random.PRNGKey(8), CFG)
    y, aux = moe.moe_ffn(params, _tokens(T=8, seed=8)[:0], CFG)
    assert y.shape == (0, CFG.d_model) and float(aux) == 0.0
