"""Registry wiring invariants — the reference's four wiring gaps, as tests
(SURVEY.md §1). These are regression tests for design bugs we must not
reintroduce."""

import pytest

import agent_tpu.ops as ops_pkg
from agent_tpu.ops import (
    OP_TO_MODULE,
    OPS_LOAD_ERRORS,
    OPS_REGISTRY,
    get_op,
    list_ops,
    load_ops,
)


def test_every_mapped_module_exists_and_registers_its_key(monkeypatch):
    """Gaps 2+3: no phantom modules, registered name == map key."""
    monkeypatch.delenv("TASKS", raising=False)
    for name in OP_TO_MODULE:
        fn = get_op(name)
        assert callable(fn), name
        assert name in OPS_REGISTRY, name
    assert OPS_LOAD_ERRORS == []


def test_unknown_op_rich_error(monkeypatch):
    monkeypatch.delenv("TASKS", raising=False)
    with pytest.raises(KeyError) as ei:
        get_op("fibonacci")  # a phantom op the reference mapped (ref ops/__init__.py:21-25)
    assert "known ops" in str(ei.value)


def test_tasks_gating(monkeypatch):
    monkeypatch.setenv("TASKS", "echo")
    assert list_ops() == ["echo"]
    get_op("echo")
    with pytest.raises(KeyError) as ei:
        get_op("risk_accumulate")
    assert "not enabled" in str(ei.value)
    monkeypatch.setenv("TASKS", "*")
    assert set(list_ops()) == set(OP_TO_MODULE)
    monkeypatch.setenv("TASKS", "none")
    assert list_ops() == []


def test_load_ops_resolves_and_raises_early(monkeypatch):
    monkeypatch.delenv("TASKS", raising=False)
    handlers = load_ops(["echo", "risk_accumulate"])
    assert set(handlers) == {"echo", "risk_accumulate"}
    with pytest.raises(KeyError):
        load_ops(["echo", "no_such_op"])


def test_agent_uses_this_registry():
    """Gap 1: the agent loop must dispatch through this registry, not a private
    table (the reference kept a 2-entry inline OPS dict, ref app.py:135-138)."""
    import inspect

    from agent_tpu.agent import app as agent_app

    src = inspect.getsource(agent_app)
    assert "load_ops" in src


class TestPlugins:
    @pytest.fixture(autouse=True)
    def _isolated_registry(self):
        """Remove plugin artifacts after each test (order-independence).

        Deliberately surgical, not a wholesale snapshot/restore: a plugin test
        may import builtin op modules as a side effect, and those stay in
        ``sys.modules`` — wiping their registry entries would leave the
        registry permanently out of sync (re-import is a no-op)."""
        import agent_tpu.ops as ops

        mod_map = dict(ops.OP_TO_MODULE)
        errs = list(ops.OPS_LOAD_ERRORS)
        yield
        for name in list(ops.OP_TO_MODULE):
            if name not in mod_map:          # plugin-attributed op
                del ops.OP_TO_MODULE[name]
                ops.OPS_REGISTRY.pop(name, None)
        ops.OPS_LOAD_ERRORS[:] = errs
        for key in list(ops._imported):
            if key.startswith("plugin:"):
                del ops._imported[key]

    def test_plugin_ops_register_and_dispatch(self, tmp_path):
        """OPS_PLUGIN_PATH modules join the registry — the generalized form of
        the reference's optional tpu_ops.py hook (reference app.py:118-123)."""
        from agent_tpu.ops import OP_TO_MODULE, get_op, load_plugins

        plug = tmp_path / "my_ops.py"
        plug.write_text(
            "from agent_tpu.ops import register_op\n"
            "@register_op('plugin_double')\n"
            "def run(payload, ctx=None):\n"
            "    return {'ok': True, 'doubled': payload['x'] * 2}\n"
        )
        new = load_plugins(str(plug))
        assert new == ["plugin_double"]
        assert "plugin_double" in OP_TO_MODULE
        out = get_op("plugin_double")({"x": 21})
        assert out == {"ok": True, "doubled": 42}

    def test_broken_plugin_is_recorded_not_raised(self, tmp_path):
        from agent_tpu.ops import OPS_LOAD_ERRORS, load_plugins

        bad = tmp_path / "bad.py"
        bad.write_text("raise RuntimeError('boom at import')\n")
        before = len(OPS_LOAD_ERRORS)
        assert load_plugins(str(bad)) == []
        assert len(OPS_LOAD_ERRORS) == before + 1
        assert "boom at import" in OPS_LOAD_ERRORS[-1][1]

    def test_missing_plugin_path_is_recorded(self, tmp_path):
        from agent_tpu.ops import OPS_LOAD_ERRORS, load_plugins

        before = len(OPS_LOAD_ERRORS)
        assert load_plugins(str(tmp_path / "nope.py")) == []
        assert len(OPS_LOAD_ERRORS) == before + 1

    def test_plugin_importing_builtin_does_not_misattribute(self, tmp_path):
        """A plugin that imports a builtin op module must not claim the
        builtin's registry entry (or report it as plugin-new)."""
        from agent_tpu.ops import OP_TO_MODULE, load_plugins

        plug = tmp_path / "reuse.py"
        plug.write_text(
            "from agent_tpu.ops.echo import run as _echo\n"
            "from agent_tpu.ops import register_op\n"
            "@register_op('echo_twice')\n"
            "def run(payload, ctx=None):\n"
            "    return {'ok': True, 'echoes': [_echo(payload), _echo(payload)]}\n"
        )
        new = load_plugins(str(plug))
        assert new == ["echo_twice"]
        assert OP_TO_MODULE["echo"] == "echo"  # builtin attribution intact

    def test_failed_plugin_rolls_back_partial_registration(self, tmp_path):
        from agent_tpu.ops import OPS_REGISTRY, load_plugins

        plug = tmp_path / "half.py"
        plug.write_text(
            "from agent_tpu.ops import register_op\n"
            "@register_op('half_op')\n"
            "def run(payload, ctx=None):\n"
            "    return {'ok': True}\n"
            "raise RuntimeError('died after registering')\n"
        )
        assert load_plugins(str(plug)) == []
        assert "half_op" not in OPS_REGISTRY  # no orphaned handler
