"""Registry wiring invariants — the reference's four wiring gaps, as tests
(SURVEY.md §1). These are regression tests for design bugs we must not
reintroduce."""

import pytest

import agent_tpu.ops as ops_pkg
from agent_tpu.ops import (
    OP_TO_MODULE,
    OPS_LOAD_ERRORS,
    OPS_REGISTRY,
    get_op,
    list_ops,
    load_ops,
)


def test_every_mapped_module_exists_and_registers_its_key(monkeypatch):
    """Gaps 2+3: no phantom modules, registered name == map key."""
    monkeypatch.delenv("TASKS", raising=False)
    for name in OP_TO_MODULE:
        fn = get_op(name)
        assert callable(fn), name
        assert name in OPS_REGISTRY, name
    assert OPS_LOAD_ERRORS == []


def test_unknown_op_rich_error(monkeypatch):
    monkeypatch.delenv("TASKS", raising=False)
    with pytest.raises(KeyError) as ei:
        get_op("fibonacci")  # a phantom op the reference mapped (ref ops/__init__.py:21-25)
    assert "known ops" in str(ei.value)


def test_tasks_gating(monkeypatch):
    monkeypatch.setenv("TASKS", "echo")
    assert list_ops() == ["echo"]
    get_op("echo")
    with pytest.raises(KeyError) as ei:
        get_op("risk_accumulate")
    assert "not enabled" in str(ei.value)
    monkeypatch.setenv("TASKS", "*")
    assert set(list_ops()) == set(OP_TO_MODULE)
    monkeypatch.setenv("TASKS", "none")
    assert list_ops() == []


def test_load_ops_resolves_and_raises_early(monkeypatch):
    monkeypatch.delenv("TASKS", raising=False)
    handlers = load_ops(["echo", "risk_accumulate"])
    assert set(handlers) == {"echo", "risk_accumulate"}
    with pytest.raises(KeyError):
        load_ops(["echo", "no_such_op"])


def test_agent_uses_this_registry():
    """Gap 1: the agent loop must dispatch through this registry, not a private
    table (the reference kept a 2-entry inline OPS dict, ref app.py:135-138)."""
    import inspect

    from agent_tpu.agent import app as agent_app

    src = inspect.getsource(agent_app)
    assert "load_ops" in src
