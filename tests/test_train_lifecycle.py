"""Model lifecycle inside the swarm (VERDICT r2 item 7): train → .npz
checkpoint → serve through map_classify_tpu, with held-out accuracy beating
chance — the framework produces useful output, not just fast output."""

import numpy as np
import pytest

from agent_tpu.runtime.context import OpContext

# Two linearly separable "languages": disjoint keyword vocabularies per class.
_WORDS = {
    0: ["invoice", "payment", "ledger", "account", "balance"],
    1: ["sensor", "voltage", "telemetry", "actuator", "signal"],
}

TINY = {
    "d_model": 32, "n_heads": 4, "n_layers": 2, "d_ff": 64,
    "max_len": 64, "dtype": "float32",
}


def _rows(n, seed=0):
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    for i in range(n):
        cls = i % 2
        words = rng.choice(_WORDS[cls], size=4)
        texts.append(" ".join(words))
        labels.append(cls)
    return texts, labels


@pytest.fixture(scope="module")
def ctx():
    import jax

    from agent_tpu.config import DeviceConfig
    from agent_tpu.runtime.runtime import TpuRuntime

    rt = TpuRuntime(
        config=DeviceConfig(tpu_disabled=True, mesh_shape={"dp": 8}),
        devices=jax.devices("cpu"),
    )
    return OpContext(runtime=rt)


@pytest.fixture()
def train():
    from agent_tpu.ops import get_op

    return get_op("train_classifier")


def test_train_loss_drops_and_artifact_serves(train, ctx, tmp_path):
    texts, labels = _rows(160)
    out_path = str(tmp_path / "clf.npz")
    out = train(
        {
            "texts": texts,
            "labels": labels,
            "output_path": out_path,
            "model_config": dict(TINY),
            "epochs": 10,
            "batch_size": 32,
            "learning_rate": 3e-2,
            "seed": 1,
        },
        ctx,
    )
    assert out["ok"] is True, out
    assert out["last_epoch_loss"] < out["first_epoch_loss"]
    assert out["n_train"] + out["n_eval"] == 160
    assert out["eval_accuracy"] is not None and out["eval_accuracy"] > 0.9

    # Serve the trained artifact through the classify op on held-out text.
    from agent_tpu.ops import get_op

    classify = get_op("map_classify_tpu")
    eval_texts, eval_labels = _rows(32, seed=99)  # unseen combinations
    served = classify(
        {
            "texts": eval_texts,
            "topk": 1,
            "model_path": out_path,
            "model_config": out["model_config"],
            "allow_fallback": False,
            "result_format": "columnar",
        },
        ctx,
    )
    assert served["ok"] is True, served
    pred = [row[0] for row in served["indices"]]
    acc = float(np.mean([p == l for p, l in zip(pred, eval_labels)]))
    assert acc > 0.9, f"served accuracy {acc} not better than chance"


def test_train_from_csv_with_string_labels(train, ctx, tmp_path):
    texts, labels = _rows(60)
    names = {0: "finance", 1: "iot"}
    csv = tmp_path / "train.csv"
    lines = ["id,text,category"]
    for i, (t, l) in enumerate(zip(texts, labels)):
        lines.append(f'{i},"{t}",{names[l]}')
    csv.write_text("\n".join(lines) + "\n", encoding="utf-8")

    out_path = str(tmp_path / "csv_clf.npz")
    out = train(
        {
            "source_uri": str(csv),
            "label_field": "category",
            "output_path": out_path,
            "model_config": dict(TINY),
            "epochs": 3,
            "batch_size": 16,
        },
        ctx,
    )
    assert out["ok"] is True, out
    assert out["label_names"] == ["finance", "iot"]  # sorted string mapping
    assert out["n_train"] + out["n_eval"] == 60
    import json, os

    assert json.load(open(out_path + ".labels.json")) == ["finance", "iot"]
    assert os.path.exists(out_path)


def test_tiny_dataset_smaller_than_batch(train, ctx, tmp_path):
    """n_train < batch and n_eval < dp must still train (batches tile), not
    crash in put_batch on an indivisible shape."""
    texts, labels = _rows(13)
    out = train(
        {
            "texts": texts, "labels": labels,
            "output_path": str(tmp_path / "tiny.npz"),
            "model_config": dict(TINY), "epochs": 1, "batch_size": 64,
            "eval_fraction": 0.2,
        },
        ctx,
    )
    assert out["ok"] is True, out
    assert out["n_train"] + out["n_eval"] == 13
    assert out["eval_accuracy"] is not None


def test_missing_warm_start_rejected(train, ctx, tmp_path):
    """A typo'd init_from .npz must error, not silently train from scratch."""
    out = train(
        {
            "texts": ["a", "b"], "labels": [0, 1],
            "output_path": str(tmp_path / "w.npz"),
            "init_from": str(tmp_path / "does_not_exist.npz"),
        },
        ctx,
    )
    assert out["ok"] is False and "not found" in out["error"]


def test_bad_payloads_soft_fail(train, ctx, tmp_path):
    ok_path = str(tmp_path / "x.npz")
    assert train({"texts": ["a"], "labels": [0]}, ctx)["ok"] is False  # no path
    assert train({"output_path": "x.txt", "texts": ["a"], "labels": [0]},
                 ctx)["ok"] is False
    assert train({"output_path": ok_path}, ctx)["ok"] is False  # no rows
    assert train({"output_path": ok_path, "texts": ["a"], "labels": [0, 1]},
                 ctx)["ok"] is False  # length mismatch
    assert train({"output_path": ok_path, "texts": ["a"], "labels": [5],
                  "model_config": {"n_classes": 2}}, ctx)["ok"] is False
    assert train({"output_path": ok_path, "texts": ["a"], "labels": [0],
                  "epochs": 0}, ctx)["ok"] is False


def test_lifecycle_through_the_swarm(ctx, tmp_path):
    """The full in-swarm story: a train job, then a classify drain gated on
    it serving the produced artifact (controller dependency ordering)."""
    import requests

    from agent_tpu.agent.app import Agent
    from agent_tpu.config import AgentConfig, Config
    from agent_tpu.controller.core import Controller
    from agent_tpu.controller.server import ControllerServer

    texts, labels = _rows(160)
    csv = tmp_path / "serve.csv"
    lines = ["id,text"]
    eval_texts, eval_labels = _rows(24, seed=7)
    for i, t in enumerate(eval_texts):
        lines.append(f'{i},"{t}"')
    csv.write_text("\n".join(lines) + "\n", encoding="utf-8")
    out_path = str(tmp_path / "swarm_clf.npz")

    controller = Controller()
    with ControllerServer(controller) as server:
        cfg = Config(
            agent=AgentConfig(
                controller_url=server.url,
                agent_name="lifecycle",
                tasks=("train_classifier", "map_classify_tpu"),
                idle_sleep_sec=0.0,
            )
        )
        agent = Agent(config=cfg, session=requests.Session(), runtime=ctx.runtime)
        agent._profile = {"tier": "test"}

        train_id = controller.submit(
            "train_classifier",
            {
                "texts": texts, "labels": labels, "output_path": out_path,
                "model_config": dict(TINY), "epochs": 10, "batch_size": 32,
                "learning_rate": 3e-2, "seed": 2,
            },
        )
        serve_id = controller.submit(
            "map_classify_tpu",
            {
                "source_uri": str(csv), "start_row": 0, "shard_size": 24,
                "topk": 1, "model_path": out_path,
                "model_config": dict(TINY, n_classes=2),
                "allow_fallback": False, "result_format": "columnar",
            },
            after=[train_id],
        )
        while not controller.drained():
            agent.step()

    trained = controller.job_snapshot(train_id)
    assert trained["state"] == "succeeded", trained
    served = controller.job_snapshot(serve_id)
    assert served["state"] == "succeeded", served
    pred = [row[0] for row in served["result"]["indices"]]
    acc = float(np.mean([p == l for p, l in zip(pred, eval_labels)]))
    assert acc > 0.9, f"swarm-served accuracy {acc}"


def test_remat_forward_and_step_match_plain():
    """remat=True is a pure memory/compute trade: forward logits and one
    training step's loss must equal the plain path."""
    import jax
    import numpy as np

    from agent_tpu.models import encoder
    from agent_tpu.models.encoder import EncoderConfig
    from agent_tpu.models.train import make_train_step

    cfg = EncoderConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, max_len=16, n_classes=8, dtype="float32")
    params = encoder.init_params(cfg, model_id="remat-test")
    rng = np.random.default_rng(0)
    ids = rng.integers(4, 64, (4, 16)).astype(np.int32)
    mask = np.ones((4, 16), dtype=np.int32)
    labels = rng.integers(0, 8, (4,)).astype(np.int32)

    a = np.asarray(encoder.forward(params, ids, mask, cfg))
    b = np.asarray(encoder.forward(params, ids, mask, cfg, remat=True))
    np.testing.assert_allclose(a, b, atol=1e-6)

    losses = []
    for remat in (False, True):
        # Real copies: the step donates its (params, opt_state) arguments.
        p = jax.tree_util.tree_map(
            lambda x: jax.numpy.array(x, copy=True), params
        )
        init_state, step = make_train_step(cfg, remat=remat)
        _, _, loss = step(p, init_state(p), ids, mask, labels)
        losses.append(float(loss))
    assert abs(losses[0] - losses[1]) < 1e-6, losses


def test_moe_trains_with_aux_loss_and_serves(train, ctx, tmp_path):
    """MoE configs must TRAIN for real: the Switch aux loss flows into the
    objective (router gradient nonzero — without the aux term a router
    trained on a dead-gradient path collapses onto one expert), loss
    drops, and the trained artifact serves back through classify."""
    import jax
    import jax.numpy as jnp

    from agent_tpu.models import encoder
    from agent_tpu.models.encoder import EncoderConfig
    from agent_tpu.models.train import cross_entropy_loss

    # Unit level: router grads are nonzero and aux contributes to loss.
    cfg = EncoderConfig(**TINY, n_classes=8, moe_experts=4)
    params = encoder.init_params(cfg, model_id="moe-aux-test")
    rng = np.random.default_rng(3)
    ids = rng.integers(4, 260, (8, 16)).astype(np.int32)
    mask = np.ones((8, 16), dtype=np.int32)
    labels = rng.integers(0, 8, (8,)).astype(np.int32)
    grads = jax.grad(cross_entropy_loss)(params, ids, mask, labels, cfg)
    router_g = np.concatenate([
        np.asarray(b["moe"]["router"]["w"]).ravel()
        for b in grads["blocks"]
    ])
    assert np.abs(router_g).max() > 0.0, "router received zero gradient"

    logits, aux = encoder.forward(params, ids, mask, cfg, with_aux=True)
    loss_full = float(cross_entropy_loss(params, ids, mask, labels, cfg))
    logp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
    nll = float(-jnp.take_along_axis(
        logp, jnp.asarray(labels)[:, None], axis=-1
    ).mean())
    assert loss_full > nll, "aux loss did not contribute to the objective"
    assert float(aux) > 0.0

    # Op level: train → artifact → serve, same contract as dense.
    texts, labels_t = _rows(160)
    out_path = str(tmp_path / "moe_clf.npz")
    out = train(
        {
            "texts": texts,
            "labels": labels_t,
            "output_path": out_path,
            "model_config": {**TINY, "moe_experts": 4},
            "epochs": 8,
            "batch_size": 32,
            "learning_rate": 3e-2,
            "seed": 1,
        },
        ctx,
    )
    assert out["ok"] is True, out
    assert out["last_epoch_loss"] < out["first_epoch_loss"]

    from agent_tpu.ops import get_op

    classify = get_op("map_classify_tpu")
    eval_texts, eval_labels = _rows(32, seed=99)
    served = classify(
        {
            "texts": eval_texts,
            "topk": 1,
            "model_path": out_path,
            "model_config": out["model_config"],
            "allow_fallback": False,
            "result_format": "columnar",
        },
        ctx,
    )
    assert served["ok"] is True, served
    pred = [row[0] for row in served["indices"]]
    acc = float(np.mean([p == l for p, l in zip(pred, eval_labels)]))
    assert acc > 0.9, f"served MoE accuracy {acc}"
