"""Decode-path raw speed (ISSUE 16): paged KV cache, cross-request prefix
reuse, and the disaggregated prefill/decode chain.

The engine tests pin the same acceptance bar as ``test_serving.py`` — tokens
through the continuous engine are BIT-IDENTICAL to a solo static decode —
but on the PAGED cache layout, including pool-constrained admission (a full
pool delays a request, it never changes its tokens). The controller tests
pin the serving-level bars: a prefix-cache hit is bit-identical to the cold
prefill that populated it, and the disaggregated prefill→decode chain is
bit-identical to the colocated path (JSON and b1 wire both).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from agent_tpu.config import ServeConfig
from agent_tpu.controller.core import Controller
from agent_tpu.models.decoding import KVPoolExhausted
from agent_tpu.ops.prefix_cache import PrefixCache, prefix_key

TINY_S2S = {
    "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
    "d_ff": 64, "max_src_len": 32, "max_tgt_len": 20, "dtype": "float32",
}

# block_size 4 at max_tgt_len 20 → 5 blocks per max-length row: small enough
# that a handful of requests exercises allocate/release/trash-block paths.
BLOCK_SIZE = 4
BLOCKS_PER_ROW = 5


@pytest.fixture(scope="module")
def s2s():
    from agent_tpu.models import seq2seq

    cfg = seq2seq.Seq2SeqConfig(**TINY_S2S)
    params = seq2seq.init_params(cfg, model_id="paged-test")
    return cfg, params


def _requests(cfg, n, seed=0, src_len=16):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        real = int(rng.integers(4, src_len))
        ids = rng.integers(4, cfg.vocab_size, (1, src_len)).astype(np.int32)
        mask = np.zeros((1, src_len), np.int32)
        mask[0, :real] = 1
        limit = int(rng.integers(2, cfg.max_tgt_len))
        out.append((ids, mask, limit))
    return out


def _solo(cfg, params, ids, mask, limit, num_beams):
    import jax.numpy as jnp

    from agent_tpu.models import seq2seq

    if num_beams == 1:
        toks, _ = seq2seq.greedy_generate(
            params, jnp.asarray(ids), jnp.asarray(mask), cfg, limit
        )
    else:
        toks, _ = seq2seq.beam_generate(
            params, jnp.asarray(ids), jnp.asarray(mask), cfg, limit,
            num_beams=num_beams,
        )
    return np.asarray(toks)[0]


def _encode(cfg, params, ids, mask):
    import jax
    import jax.numpy as jnp

    from agent_tpu.models import seq2seq

    return np.asarray(jax.jit(
        lambda p, i, m: seq2seq.encode(p, i, m, cfg).astype(jnp.float32)
    )(params, jnp.asarray(ids), jnp.asarray(mask)))


def _paged_engine(
    cfg, params, num_beams, slots=3, src_len=16, pool_blocks=0, **kw
):
    from agent_tpu.models import seq2seq
    from agent_tpu.models.decoding import ContinuousBatcher
    from agent_tpu.models.tokenizer import BOS_ID, EOS_ID, PAD_ID

    return ContinuousBatcher(
        seq2seq.make_positional_step(params, cfg),
        seq2seq.make_paged_cache_factory(
            cfg, block_size=BLOCK_SIZE, pool_blocks=pool_blocks
        ),
        slots=slots, vocab_size=cfg.vocab_size, max_tokens=cfg.max_tgt_len,
        enc_len=src_len, d_model=cfg.d_model,
        start_id=BOS_ID, eos_id=EOS_ID, pad_id=PAD_ID,
        num_beams=num_beams, **kw,
    )


# ---------------------------------------------------------------------------
# paged engine correctness
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("num_beams", [1, 3])
def test_paged_engine_bit_identical_with_joins_and_exits(s2s, num_beams):
    """The flagship bar on the paged layout: staggered joins and early
    exits over a shared block pool leave every request's tokens EXACTLY
    equal to its solo (dense-cache) decode."""
    cfg, params = s2s
    reqs = _requests(cfg, 7, seed=num_beams)
    solos = [
        _solo(cfg, params, ids, mask, limit, num_beams)
        for ids, mask, limit in reqs
    ]
    engine = _paged_engine(cfg, params, num_beams, slots=3)
    assert engine.paged
    total = engine.kv_blocks_total
    assert total == 3 * num_beams * BLOCKS_PER_ROW  # auto-sized dense parity
    done = []
    for i in range(4):
        ids, mask, limit = reqs[i]
        engine.admit(_encode(cfg, params, ids, mask)[0], mask[0], limit,
                     data=i)
    pending = list(range(4, len(reqs)))
    while engine.has_work():
        done.extend(engine.step())
        if pending and engine.steps_run % 2 == 0:
            i = pending.pop(0)
            ids, mask, limit = reqs[i]
            engine.admit(_encode(cfg, params, ids, mask)[0], mask[0],
                         limit, data=i)
    assert len(done) == len(reqs)
    assert engine.max_occupancy == 3
    for ticket in done:
        i = ticket.data
        limit = reqs[i][2]
        assert np.array_equal(ticket.tokens[:limit], solos[i][:limit]), (
            f"request {i} (beams={num_beams}) diverged from solo decode "
            "on the paged cache"
        )
    # Every block came back to the free list; none leaked into the trash.
    assert engine.kv_blocks_free == total


def test_paged_slot_reuse_returns_blocks(s2s):
    """Churn through more requests than slots: released blocks are reused
    by later seats, the free count never goes negative, and the pool is
    whole after the drain."""
    cfg, params = s2s
    reqs = _requests(cfg, 6, seed=11)
    solos = [_solo(cfg, params, i, m, l, 1) for i, m, l in reqs]
    engine = _paged_engine(cfg, params, 1, slots=2)
    total = engine.kv_blocks_total
    for i, (ids, mask, limit) in enumerate(reqs):
        engine.admit(_encode(cfg, params, ids, mask)[0], mask[0], limit,
                     data=i)
    done = []
    while engine.has_work():
        done.extend(engine.step())
        assert 0 <= engine.kv_blocks_free <= total
    assert len(done) == len(reqs)
    assert engine.max_occupancy == 2
    for t in done:
        limit = reqs[t.data][2]
        assert np.array_equal(t.tokens[:limit], solos[t.data][:limit])
    assert engine.kv_blocks_free == total


def test_paged_never_seatable_request_raises(s2s):
    """A request whose worst-case reservation exceeds the WHOLE pool can
    never run — admit refuses it up front instead of wedging the queue."""
    cfg, params = s2s
    # Minimum legal pool: one max-length row + trash. At 2 beams, a
    # max-length request needs 2 rows' worth — never seatable.
    engine = _paged_engine(
        cfg, params, 2, slots=2, pool_blocks=BLOCKS_PER_ROW + 1
    )
    ids = np.full((1, 16), 7, np.int32)
    mask = np.ones((1, 16), np.int32)
    enc = _encode(cfg, params, ids, mask)[0]
    with pytest.raises(KVPoolExhausted):
        engine.admit(enc, mask[0], cfg.max_tgt_len, data="too-big")
    # A request that fits the pool still seats and completes.
    t = engine.admit(enc, mask[0], BLOCK_SIZE, data="fits")
    while engine.has_work():
        engine.step()
    assert t.done_wall is not None
    assert engine.kv_blocks_free == engine.kv_blocks_total


def test_paged_full_pool_waits_fifo_and_stays_exact(s2s):
    """Pool exhaustion is backpressure, not corruption: with free slots but
    no free blocks, requests wait in FIFO order (no small-request overtake)
    and every one still decodes bit-identically."""
    cfg, params = s2s
    # Usable pool = exactly one max-length greedy row: requests 0 and 2
    # (5 blocks each) serialize the pool even though 3 slots are free.
    engine = _paged_engine(
        cfg, params, 1, slots=3, pool_blocks=BLOCKS_PER_ROW + 1
    )
    limits = [cfg.max_tgt_len - 1, 2, cfg.max_tgt_len - 1]
    reqs = []
    rng = np.random.default_rng(21)
    for limit in limits:
        ids = rng.integers(4, cfg.vocab_size, (1, 16)).astype(np.int32)
        mask = np.ones((1, 16), np.int32)
        reqs.append((ids, mask, limit))
    solos = [_solo(cfg, params, i, m, l, 1) for i, m, l in reqs]
    for i, (ids, mask, limit) in enumerate(reqs):
        engine.admit(_encode(cfg, params, ids, mask)[0], mask[0], limit,
                     data=i)
    # Only the head seats: request 1 needs one block but must not overtake.
    assert engine.occupancy == 1 and engine.backlog == 2
    order = []
    while engine.has_work():
        order.extend(t.data for t in engine.step())
        assert engine.occupancy <= 1   # the pool, not the slots, gates
    assert order == [0, 1, 2]
    # Same workload again, keeping ticket handles for the token checks.
    engine2 = _paged_engine(
        cfg, params, 1, slots=3, pool_blocks=BLOCKS_PER_ROW + 1
    )
    tickets = [
        engine2.admit(_encode(cfg, params, ids, mask)[0], mask[0], limit,
                      data=i)
        for i, (ids, mask, limit) in enumerate(reqs)
    ]
    while engine2.has_work():
        engine2.step()
    for i, t in enumerate(tickets):
        limit = reqs[i][2]
        assert np.array_equal(t.tokens[:limit], solos[i][:limit]), (
            f"request {i} diverged after waiting on the full pool"
        )
    assert engine2.kv_blocks_free == engine2.kv_blocks_total


# ---------------------------------------------------------------------------
# prefix cache unit
# ---------------------------------------------------------------------------

class TestPrefixCache:
    def test_key_separates_model_version_and_length(self):
        row = np.arange(16, dtype=np.int32)
        k = prefix_key("m1", row)
        assert k == prefix_key("m1", row.copy())          # content-stable
        assert k != prefix_key("m2", row)                 # model in the seed
        assert k != prefix_key("m1", row[:8])             # length in the seed
        longer = np.concatenate([row, np.zeros(64, np.int32)])
        assert k != prefix_key("m1", longer)              # pad bucket too
        row2 = row.copy()
        row2[3] += 1
        assert k != prefix_key("m1", row2)                # content-sensitive

    def test_hit_is_bit_exact_and_counted(self):
        cache = PrefixCache(max_entries=4)
        row = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
        key = prefix_key("m", np.arange(8, dtype=np.int32))
        assert cache.get(key) is None                     # cold miss
        cache.put(key, row)
        hit = cache.get(key)
        assert hit is not None and np.array_equal(hit, row)
        assert hit.dtype == np.float32
        s = cache.stats()
        assert s["hits"] == 1 and s["misses"] == 1 and s["hit_rate"] == 0.5

    def test_lru_eviction_order_and_counters(self):
        cache = PrefixCache(max_entries=2)
        rows = {k: np.full((2, 2), i, np.float32)
                for i, k in enumerate("abc")}
        cache.put("a", rows["a"])
        cache.put("b", rows["b"])
        assert cache.get("a") is not None                 # refresh "a"
        cache.put("c", rows["c"])                         # evicts LRU = "b"
        assert cache.get("b") is None
        assert cache.get("a") is not None
        assert cache.get("c") is not None
        assert cache.stats()["evictions"] == 1
        assert len(cache) == 2

    def test_byte_budget_bounds_and_oversized_row(self):
        one_kb = np.zeros(256, np.float32)                # 1024 bytes
        cache = PrefixCache(max_entries=64, max_bytes=2048)
        cache.put("a", one_kb)
        cache.put("b", one_kb)
        cache.put("c", one_kb)                            # over budget → evict
        assert len(cache) == 2 and cache.bytes_used <= 2048
        assert cache.stats()["evictions"] == 1
        cache.put("huge", np.zeros(4096, np.float32))     # > whole budget
        assert cache.get("huge") is None                  # never cached
        assert cache.bytes_used <= 2048


# ---------------------------------------------------------------------------
# controller-level: colocated prefix reuse + disaggregated chain
# ---------------------------------------------------------------------------

SERVE_TASKS = ("serve_summarize", "serve_prefill", "serve_decode")

TEXTS = [
    "shared prefix context document alpha for the serving tests",
    "shared prefix context document alpha for the serving tests",
    "a different text to summarize entirely",
    "shared prefix context document alpha for the serving tests",
]


def _serve_drain(controller, ctx=None):
    """Minimal in-process agent: lease + execute + report until the serving
    door is empty (mirrors ``test_serving._drain_serving``, with the op
    context injectable so the b1-wire test can tag the agent side)."""
    from agent_tpu.ops import load_ops
    from agent_tpu.runtime.context import OpContext

    handlers = load_ops(list(SERVE_TASKS))
    ctx = ctx if ctx is not None else OpContext()
    for _ in range(200):
        lease = controller.lease(
            agent="test", capabilities={"ops": sorted(handlers)},
            max_tasks=4,
        )
        if lease is None:
            if controller.serve_door.stats()["bucketed"] == 0 \
                    and not controller.serve_door.job_ids():
                return
            time.sleep(0.01)
            continue
        for task in lease["tasks"]:
            result = handlers[task["op"]](task["payload"], ctx)
            controller.report(
                lease_id=lease["lease_id"], job_id=task["id"],
                job_epoch=task["job_epoch"],
                status="succeeded" if result.get("ok") else "failed",
                result=result,
            )
    raise AssertionError("serve drain did not converge")


class TestDisaggServing:
    def _round(self, controller, ctx=None):
        rids = [
            controller.submit_infer("summarize", t, params={
                "model_config": TINY_S2S, "max_length": 8, "num_beams": 2,
            })
            for t in TEXTS
        ]
        controller._serve_pump()
        _serve_drain(controller, ctx=ctx)
        controller._serve_reap()
        out = []
        for rid in rids:
            snap = controller.infer_snapshot(rid)
            assert snap["state"] == "done", snap
            assert snap["ttft_ms"] is not None
            out.append(snap["result"]["summary"])
        return out

    def _controller(self, **kw):
        from agent_tpu.ops.serve_infer import reset_engines

        reset_engines()   # fresh engine store + prefix cache per test
        defaults = dict(max_wait_ms=0.0, max_batch=4)
        defaults.update(kw)
        # Result cache off (ISSUE 19): these tests exercise the KV-layer
        # prefix cache, which the front-door result cache would mask on
        # repeated identical requests.
        from agent_tpu.config import FlowConfig

        return Controller(
            serve=ServeConfig(**defaults),
            flow=FlowConfig(cache_enabled=False),
        )

    def test_colocated_prefix_cache_hit_bit_identical(self):
        """The satellite bar: a prefix-cache hit returns output
        bit-identical to the cold prefill, and the controller gauges see
        the paged pool come back whole."""
        c = self._controller()
        first = self._round(c)
        hits_after_cold = c._m_serve_prefix.value(event="hits")
        second = self._round(c)
        assert second == first                       # cached == cold
        hits = c._m_serve_prefix.value(event="hits")
        misses = c._m_serve_prefix.value(event="misses")
        assert hits - hits_after_cold >= len(TEXTS)  # every repeat hit
        assert misses >= 2.0                         # 2 distinct cold texts
        assert c._m_serve_kv_total.value() > 0       # paged is the default
        assert c._m_serve_kv_free.value() == c._m_serve_kv_total.value()

    def test_disagg_chain_bit_identical_to_colocated(self):
        colo = self._round(self._controller())
        c = self._controller(disaggregated=True)
        dis = self._round(c)
        assert dis == colo
        ops = {
            r.get("op") for r in c.results().values() if isinstance(r, dict)
        }
        assert {"serve_prefill", "serve_decode"} <= ops
        # Forwarded prefix/KV stats reached the reap from the decode leg.
        assert c._m_serve_kv_total.value() > 0
        assert c._m_serve_prefix.value(event="misses") >= 1.0

    def test_disagg_b1_wire_handoff_round_trip(self):
        """The KV-handoff envelope survives the binary wire: a disagg run
        whose agent speaks b1 (encoded rows attached as binary columns,
        decoded at report time) equals the JSON-wire run bit-for-bit."""
        from agent_tpu.runtime.context import OpContext

        json_out = self._round(self._controller(disaggregated=True))
        c = self._controller(disaggregated=True)
        b1_out = self._round(c, ctx=OpContext(tags={"wire": "b1"}))
        assert b1_out == json_out

    def test_prefill_failure_cascades_to_decode_rider(self):
        """A dead prefill must not strand its decode job (dep gating only
        releases on success): the reap fails the decode the deadline-death
        way and the rider's wait resolves failed, not hung."""
        c = self._controller(disaggregated=True)
        rid = c.submit_infer("summarize", "text that will not prefill",
                             params={"model_config": TINY_S2S,
                                     "max_length": 4})
        c._serve_pump()
        lease = c.lease(
            agent="test", capabilities={"ops": ["serve_prefill"]},
            max_tasks=4,
        )
        assert lease is not None
        (task,) = lease["tasks"]
        assert task["op"] == "serve_prefill"
        # ValueError is a PERMANENT type: the job sticks FAILED on the
        # first report instead of burning the retry budget.
        c.report(
            lease_id=lease["lease_id"], job_id=task["id"],
            job_epoch=task["job_epoch"], status="failed",
            error={"type": "ValueError", "message": "injected prefill fault"},
        )
        c._serve_pump()
        c._serve_reap()
        snap = c.infer_snapshot(rid)
        assert snap["state"] == "failed", snap
        assert snap["error"]["type"] in ("DependencyFailed", "ValueError")
