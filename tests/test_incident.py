"""Incident forensics + anomaly detection (ISSUE 20 tentpole c): bundle
schema, content-addressed ids, dedup/rate-limit, size bounding, disk
reindex after restart, detector determinism, and the disable knobs."""

import json
import os
import time

import pytest

from agent_tpu.config import ObsConfig
from agent_tpu.obs.anomaly import (
    AnomalyDetector,
    counter_rate,
    default_watches,
    gauge_sum,
)
from agent_tpu.obs.incident import IncidentBundler

KEY = '[["queue","leasable"]]'


def qsample(wall, depth):
    return {"wall": wall,
            "data": {"controller_queue_depth": {KEY: float(depth)}}}


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


# ---- bundler ----

def test_capture_schema_and_content_address(tmp_path):
    b = IncidentBundler(directory=str(tmp_path))
    out = b.capture("anomaly", "queue_depth", {"z": 12.0},
                    {"timeseries": {"a": 1}, "health": {"verdict": "warn"}})
    assert out["id"].startswith("inc-")
    body = b.get(out["id"])
    for field in ("id", "wall", "kind", "key", "reason", "sections"):
        assert field in body
    assert body["sections"]["health"]["verdict"] == "warn"
    # Content-addressed: the id is derived from the bundle body, so the
    # on-disk file round-trips to the same id.
    path = os.path.join(str(tmp_path), out["id"] + ".json")
    with open(path, encoding="utf-8") as f:
        assert json.load(f)["id"] == out["id"]


def test_dedup_rate_limit():
    clk = Clock()
    b = IncidentBundler(min_interval_sec=60.0, clock=clk)
    first = b.capture("anomaly", "queue_depth", {"z": 9}, {"s": 1})
    assert first is not None
    assert b.capture("anomaly", "queue_depth", {"z": 10}, {"s": 2}) is None
    # A different key is its own incident stream.
    assert b.capture("anomaly", "ttft_p99", {"z": 9}, {"s": 3}) is not None
    assert b.stats()["suppressed"] == 1
    # Past the interval the same key captures again.
    clk.t += 61.0
    assert b.capture("anomaly", "queue_depth", {"z": 11}, {"s": 4}) is not None


def test_capacity_evicts_oldest():
    clk = Clock()
    b = IncidentBundler(capacity=3, min_interval_sec=0.0, clock=clk)
    ids = []
    for i in range(5):
        clk.t += 1.0
        ids.append(b.capture("slo_page", f"obj{i}", {}, {"s": i})["id"])
    listed = [h["id"] for h in b.list()]
    assert len(listed) == 3
    assert ids[0] not in listed and ids[-1] in listed


def test_size_bound_drops_largest_section():
    b = IncidentBundler(max_bundle_bytes=2048)
    big = {"rows": ["x" * 100 for _ in range(200)]}
    out = b.capture("anomaly", "queue_depth", {"z": 9},
                    {"huge": big, "small": {"ok": True}})
    body = b.get(out["id"])
    assert "huge" not in body["sections"]
    assert body["sections"]["small"] == {"ok": True}
    assert "huge" in body["truncated_sections"]
    assert len(json.dumps(body)) <= 2048 + 256


def test_disk_reindex_after_restart(tmp_path):
    b = IncidentBundler(directory=str(tmp_path))
    out = b.capture("slo_page", "interactive", {"burn": 15.0}, {"s": 1})
    b2 = IncidentBundler(directory=str(tmp_path))
    headers = b2.list()
    assert [h["id"] for h in headers] == [out["id"]]
    assert b2.get(out["id"])["sections"] == {"s": 1}
    assert b2.get("inc-nope") is None


# ---- detector ----

def test_detector_warmup_gates():
    det = AnomalyDetector(warmup=10, confirm=2)
    prev = None
    events = []
    for i in range(5):
        s = qsample(float(i), 500.0)  # wild values, but under warmup
        events += det.observe(prev, s)
        prev = s
    assert events == []


def test_detector_confirms_exactly_one_episode():
    det = AnomalyDetector(warmup=8, confirm=2, clear=3, z_thresh=8.0)
    prev = None
    events = []
    for i in range(30):
        s = qsample(float(i), 2.0 + (i % 3))
        events += det.observe(prev, s)
        prev = s
    assert events == []
    for i in range(30, 36):
        s = qsample(float(i), 90.0)
        events += det.observe(prev, s)
        prev = s
    assert len(events) == 1
    ev = events[0]
    assert ev["watch"] == "queue_depth" and ev["direction"] == "high"
    assert ev["z"] >= 8.0
    assert det.active()
    # Recovery clears the episode; a later burst is a NEW event.
    for i in range(36, 44):
        s = qsample(float(i), 2.0)
        events += det.observe(prev, s)
        prev = s
    assert not det.active()
    for i in range(44, 48):
        s = qsample(float(i), 90.0)
        events += det.observe(prev, s)
        prev = s
    assert len(events) == 2


def test_detector_deterministic():
    def run():
        det = AnomalyDetector(warmup=8, confirm=2)
        prev, out = None, []
        for i in range(40):
            depth = 3.0 if i < 30 else 120.0
            s = qsample(float(i), depth)
            out += det.observe(prev, s)
            prev = s
        return out

    assert run() == run()


def test_detector_min_delta_suppresses_tiny_shifts():
    # A flat-line baseline has MAD 0 — without the min_delta floor a +1
    # wiggle would z-score to infinity. queue_depth requires |delta|>=10.
    det = AnomalyDetector(warmup=8, confirm=2)
    prev, events = None, []
    for i in range(30):
        s = qsample(float(i), 2.0)
        events += det.observe(prev, s)
        prev = s
    for i in range(30, 36):
        s = qsample(float(i), 5.0)
        events += det.observe(prev, s)
        prev = s
    assert events == []


def test_counter_rate_extractor():
    key = '[["kind","lease"]]'
    prev = {"wall": 100.0,
            "data": {"result_post_failures_total": {key: 10.0}}}
    cur = {"wall": 110.0,
           "data": {"result_post_failures_total": {key: 25.0}}}
    watches = {w.name: w for w in default_watches()}
    assert watches["lease_error_rate"].extract(prev, cur) == pytest.approx(1.5)
    # Counter reset clamps to zero, never a negative rate.
    reset = {"wall": 120.0,
             "data": {"result_post_failures_total": {key: 3.0}}}
    assert watches["lease_error_rate"].extract(cur, reset) == 0.0


def test_gauge_sum_extractor_missing_family():
    assert gauge_sum("nope")(None, {"wall": 1.0, "data": {}}) is None


# ---- controller knobs ----

def test_disable_knobs(tmp_path):
    from agent_tpu.controller.core import Controller

    c = Controller(journal_path=None, obs=ObsConfig(
        anomaly_enabled=False, incident_enabled=False,
        tsdb_dir=str(tmp_path),
    ))
    try:
        assert c.anomaly is None
        assert c.incidents is None
        out = c.incidents_json()
        assert out["enabled"] is False and out["incidents"] == []
        c.sweep()  # sampling still persists without the detector
        assert c.tsdb_store is not None
    finally:
        c.close()


def test_slo_page_captures_incident(tmp_path):
    """The SLO page path snapshots a bundle through the same bundler the
    anomaly path uses — one forensic pipeline for both triggers."""
    from agent_tpu.controller.core import Controller

    c = Controller(journal_path=None, obs=ObsConfig(
        incident_dir=str(tmp_path), tsdb_dir="",
    ))
    try:
        c._capture_incident("slo_page", "interactive",
                            {"objective": "interactive", "burn_short": 20.0})
        out = c.incidents_json()
        assert out["enabled"] and len(out["incidents"]) == 1
        head = out["incidents"][0]
        assert head["kind"] == "slo_page" and head["key"] == "interactive"
        body = c.incidents_json(head["id"])["incident"]
        for section in ("timeseries", "flight_recorder", "status", "health"):
            assert section in body["sections"], section
    finally:
        c.close()
