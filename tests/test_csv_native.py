"""Native C++ CSV scanner vs the vectorized Python scanner: identical offsets
on quoting edge cases, and both fast enough to feed the device (VERDICT item 9:
index build ≥200 MB/s)."""

import os
import time

import numpy as np
import pytest

from agent_tpu.data.csv_index import CsvIndex, _scan_row_offsets_py
from agent_tpu.data.native import native_available, scan_row_offsets_native

EDGE_CASES = [
    # (name, content)
    ("plain", 'a,b,c\n1,2,3\n4,5,6\n'),
    ("quoted_newline", 'a,b\n1,"x\ny"\n2,z\n'),
    ("doubled_quotes", 'a,b\n1,"he said ""hi"""\n2,"a""b"\n'),
    ("quote_spanning_chunks", 'a,b\n1,"' + "x" * 3000 + '\n' + "y" * 3000 + '"\n2,z\n'),
    ("no_trailing_newline", 'a,b\n1,2\n3,4'),
    ("empty_rows", 'a,b\n\n\n1,2\n'),
    ("only_header", 'a,b\n'),
]


@pytest.mark.parametrize("name,content", EDGE_CASES, ids=[c[0] for c in EDGE_CASES])
def test_python_scanner_offsets(tmp_path, name, content):
    p = tmp_path / f"{name}.csv"
    p.write_bytes(content.encode())
    offsets = _scan_row_offsets_py(str(p))
    # Invariants: starts at 0, strictly increasing, every offset follows an
    # unquoted newline.
    assert offsets[0] == 0
    assert (np.diff(offsets) > 0).all()
    data = content.encode()
    for off in offsets[1:]:
        assert data[off - 1 : off] == b"\n"


@pytest.mark.parametrize("name,content", EDGE_CASES, ids=[c[0] for c in EDGE_CASES])
def test_native_matches_python(tmp_path, name, content):
    if not native_available():
        pytest.skip("no C++ toolchain in this environment")
    p = tmp_path / f"{name}.csv"
    p.write_bytes(content.encode())
    native = scan_row_offsets_native(str(p))
    py = _scan_row_offsets_py(str(p))
    np.testing.assert_array_equal(native, py)


def test_quoted_newline_rows_roundtrip(tmp_csv):
    idx = CsvIndex.for_file(tmp_csv)
    rows = idx.read_dict_rows(24, 2)
    assert rows[1]["text"] == "line one\nline two"  # row 25 spans a newline


def _best_throughput(fn, path, size_mb, runs=3):
    """Best-of-N MB/s of *CPU time* (``process_time``), not wall clock: the
    scanners are single-threaded, so bytes per CPU-second measures the
    scanner itself even when the suite shares the host with XLA compiles or
    other jobs that would steal wall-clock slices (round-4 flake: this test
    failed under concurrent load and passed in isolation). Wall-clock
    throughput lives in the bench (``csv_index`` leg), where the box is
    idle."""
    best = 0.0
    n = None
    for _ in range(runs):
        t0 = time.process_time()
        out = fn(path)
        dt = time.process_time() - t0
        best = max(best, size_mb / max(dt, 1e-9))
        n = len(out)
    return best, n


def test_index_build_throughput(tmp_path):
    """The round-1 per-byte loop managed ~20 MB/s; require ≥100 MB/s.

    The floor is 5× the per-byte loop but well under the scanners' idle-box
    rate (1 GB/s+): this is a regression tripwire for a slow-path rewrite,
    not a benchmark, and must not flake when the suite shares the host with
    XLA compiles.
    """
    p = tmp_path / "big.csv"
    with open(p, "w") as f:
        f.write("id,text,risk\n")
        for i in range(300_000):
            f.write(f'{i},"record {i} with a payload of text",{i % 89}\n')
    size_mb = os.path.getsize(p) / 1e6
    mbps, n = _best_throughput(_scan_row_offsets_py, str(p), size_mb)
    assert n == 300_001
    assert mbps >= 100, f"python scan only {mbps:.0f} MB/s"
    if native_available():
        mbps_n, n = _best_throughput(scan_row_offsets_native, str(p), size_mb)
        assert n == 300_001
        assert mbps_n >= 100, f"native scan only {mbps_n:.0f} MB/s"
