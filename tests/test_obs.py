"""Observability subsystem (ISSUE 2): metrics registry semantics, Prometheus
exposition validity, fleet merge, flight recorder bounds and dumps, trace
propagation, and the end-to-end acceptance paths — a pipelined drain showing
phase/queue/idle series on ``GET /v1/metrics`` and an injected
``stale_epoch`` fault incrementing the epoch-fence counter."""

import json
import threading
import time
import urllib.request

import jax
import pytest
import requests

from agent_tpu.agent.app import Agent
from agent_tpu.config import AgentConfig, Config, DeviceConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.server import ControllerServer
from agent_tpu.obs.metrics import (
    MetricsRegistry,
    histogram_quantile,
    merge_snapshots,
    parse_exposition,
    render_snapshots,
    validate_exposition,
)
from agent_tpu.obs.recorder import FlightRecorder
from agent_tpu.runtime.runtime import TpuRuntime

TINY = {
    "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
    "max_len": 64, "dtype": "float32", "n_classes": 16,
}


@pytest.fixture(scope="module")
def runtime():
    return TpuRuntime(
        config=DeviceConfig(tpu_disabled=True, mesh_shape={"dp": 8}),
        devices=jax.devices("cpu"),
    )


# ---- registry unit behavior ----

class TestMetricsRegistry:
    def test_counter_gauge_histogram_basics(self):
        r = MetricsRegistry()
        c = r.counter("tasks_total", "t", ("op", "status"))
        c.inc(op="echo", status="succeeded")
        c.inc(2, op="echo", status="succeeded")
        assert c.value(op="echo", status="succeeded") == 3
        with pytest.raises(ValueError):
            c.inc(-1, op="echo", status="succeeded")  # counters go up
        with pytest.raises(ValueError):
            c.inc(op="echo")  # label mismatch
        g = r.gauge("queue_depth", "q", ("queue",))
        g.set(4, queue="staged")
        g.dec(queue="staged")
        assert g.value(queue="staged") == 3
        h = r.histogram("lat", "l", ("op",), buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v, op="x")
        snap = r.snapshot()["lat"]["series"][0]
        assert snap["counts"] == [1, 1, 1]  # le=0.1, le=1, +Inf overflow
        assert snap["count"] == 3 and snap["sum"] == pytest.approx(5.55)

    def test_reregistration_must_match(self):
        r = MetricsRegistry()
        first = r.counter("x_total", "x", ("a",))
        # get-or-create: same name+type+labels returns the same object
        assert r.counter("x_total", "ignored help", ("a",)) is first
        with pytest.raises(ValueError):
            r.gauge("x_total", "x", ("a",))  # same name, different type
        with pytest.raises(ValueError):
            r.counter("x_total", "x", ("b",))  # different labels

    def test_render_is_valid_exposition_with_escaping(self):
        r = MetricsRegistry()
        c = r.counter("weird_total", 'help with \\ and\nnewline', ("who",))
        c.inc(who='quo"te\nand\\slash')
        text = r.render()
        assert validate_exposition(text) == []
        parsed = parse_exposition(text)
        (labels, value), = parsed["weird_total"]
        assert labels["who"] == 'quo"te\nand\\slash' and value == 1

    def test_merge_and_fleet_render(self):
        def make(n):
            r = MetricsRegistry()
            r.counter("tasks_total", "t", ("op",)).inc(n, op="echo")
            h = r.histogram("task_phase_seconds", "p", ("phase",))
            h.observe(0.01 * n, phase="stage")
            r.gauge("queue_depth", "q", ("queue",)).set(n, queue="staged")
            return r.snapshot()

        fleet = merge_snapshots([make(1), make(2)])
        (s,) = fleet["tasks_total"]["series"]
        assert s["value"] == 3
        (h,) = fleet["task_phase_seconds"]["series"]
        assert h["count"] == 2 and h["sum"] == pytest.approx(0.03)
        (g,) = fleet["queue_depth"]["series"]
        assert g["value"] == 3  # gauges sum across the fleet
        text = render_snapshots([(fleet, {}), (make(1), {"agent": "a1"})])
        assert validate_exposition(text) == []
        # per-agent series carry the agent label; fleet ones do not
        samples = parse_exposition(text)["tasks_total"]
        assert sorted(lbl.get("agent", "") for lbl, _ in samples) == ["", "a1"]

    def test_histogram_quantile_interpolates(self):
        buckets = [0.1, 1.0, 10.0]
        counts = [0, 100, 0, 0]  # all observations in (0.1, 1.0]
        assert histogram_quantile(buckets, counts, 0.5) == pytest.approx(0.55)
        assert histogram_quantile(buckets, [0, 0, 0, 0], 0.5) is None
        # +Inf landings clamp to the largest finite bound
        assert histogram_quantile(buckets, [0, 0, 0, 5], 0.99) == 10.0

    def test_thread_safety_smoke(self):
        r = MetricsRegistry()
        c = r.counter("n_total", "n", ("t",))

        def work():
            for _ in range(1000):
                c.inc(t="x")

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value(t="x") == 8000


class TestFleetMetricsHygiene:
    """ISSUE 7 satellite: once ≥ 2 agents push snapshots, per-agent load
    series (gauges + device busy/idle counters) must carry an ``agent``
    label next to the unlabeled fleet merge — a merged-only view collapses
    the fleet into one number and hides a starving member."""

    @staticmethod
    def _agent_snapshot(n):
        r = MetricsRegistry()
        r.gauge("queue_depth", "q", ("queue",)).set(n, queue="staged")
        r.counter("device_busy_seconds_total", "b").inc(n)
        r.counter("tasks_total", "t", ("op",)).inc(n, op="echo")
        return r.snapshot()

    def test_single_agent_keeps_legacy_unlabeled_shape(self):
        c = Controller()
        c.lease("a1", {"ops": []}, max_tasks=0,
                metrics={"obs": self._agent_snapshot(1)})
        text = c.metrics_text()
        assert validate_exposition(text) == []
        for labels, _ in parse_exposition(text)["queue_depth"]:
            assert "agent" not in labels

    def test_two_agents_get_agent_labeled_gauges_plus_fleet_merge(self):
        c = Controller()
        c.lease("a1", {"ops": []}, max_tasks=0,
                metrics={"obs": self._agent_snapshot(1)})
        c.lease("a2", {"ops": []}, max_tasks=0,
                metrics={"obs": self._agent_snapshot(2)})
        text = c.metrics_text()
        assert validate_exposition(text) == []
        parsed = parse_exposition(text)

        def by_agent(name, **want):
            out = {}
            for labels, value in parsed[name]:
                if all(labels.get(k) == v for k, v in want.items()):
                    out[labels.get("agent", "")] = value
            return out

        # Gauge: per-agent values visible AND the unlabeled fleet sum.
        qd = by_agent("queue_depth", queue="staged")
        assert qd == {"": 3.0, "a1": 1.0, "a2": 2.0}
        busy = by_agent("device_busy_seconds_total")
        assert busy == {"": 3.0, "a1": 1.0, "a2": 2.0}
        # Ordinary counters stay merged-only: no per-agent duplication.
        tasks = by_agent("tasks_total", op="echo")
        assert tasks == {"": 3.0}


class TestScrapeHelpers:
    def test_op_phase_seconds_sums_fleet_series_only(self):
        from agent_tpu.obs.scrape import op_phase_seconds

        r = MetricsRegistry()
        h = r.histogram("task_phase_seconds", "p", ("op", "phase"))
        h.observe(2.0, op="map_classify_tpu", phase="execute")
        h.observe(0.5, op="map_classify_tpu", phase="fetch")
        h.observe(9.0, op="map_classify_tpu", phase="stage")  # not counted
        h.observe(1.0, op="map_summarize", phase="execute")
        snap = r.snapshot()
        # fleet series unlabeled; a per-agent copy must NOT double-count
        text = render_snapshots([(snap, {}), (snap, {"agent": "a1"})])
        spans = op_phase_seconds(
            text, ("map_classify_tpu", "map_summarize")
        )
        assert spans["map_classify_tpu"] == pytest.approx(2.5)
        assert spans["map_summarize"] == pytest.approx(1.0)

    def test_op_phase_seconds_tolerates_garbage(self):
        from agent_tpu.obs.scrape import op_phase_seconds

        assert op_phase_seconds("not prometheus {{{", ("x",)) == {"x": 0.0}

    def test_overlap_from_spans_zero_span_trace(self):
        """ISSUE 8 satellite: a trace window with NO closed stage/execute
        spans (zero-span traces, spans missing durations) must return None,
        not divide by zero or fabricate an overlap."""
        from agent_tpu.obs.scrape import overlap_from_spans

        assert overlap_from_spans([]) is None
        # open spans (no duration) and non-dict garbage are skipped
        assert overlap_from_spans([
            {"name": "stage", "process": "agent:a", "start_wall": 1.0,
             "duration_ms": None},
            {"name": "execute", "process": "agent:a", "start_wall": 1.0},
            "not-a-span", None, 42,
        ]) is None
        # stage spans but no execute (a drain that died pre-dispatch)
        assert overlap_from_spans([
            {"name": "stage", "process": "agent:a", "start_wall": 1.0,
             "duration_ms": 5.0},
        ]) is None

    def test_overlap_by_process_single_agent(self):
        """ISSUE 8 satellite: one-agent grouping — the per-process split
        must yield exactly that agent's overlap (identical to the ungrouped
        computation), with zero-span groups absent, not {} entries."""
        from agent_tpu.obs.scrape import overlap_by_process, overlap_from_spans

        spans = [
            {"name": "execute", "process": "agent:solo", "start_wall": 0.0,
             "duration_ms": 1000.0},
            {"name": "stage", "process": "agent:solo", "start_wall": 0.5,
             "duration_ms": 250.0},
            # controller spans never carry stage/execute and are skipped
            {"name": "apply", "process": "controller", "start_wall": 0.0,
             "duration_ms": 1.0},
            # an agent with only open spans contributes no group
            {"name": "stage", "process": "agent:ghost", "start_wall": 0.0,
             "duration_ms": None},
        ]
        out = overlap_by_process(spans)
        assert set(out) == {"solo"}
        assert out["solo"] == overlap_from_spans(spans[:2])
        assert out["solo"]["overlap_ratio"] == 1.0

    def test_scrape_controller_with_no_agent_snapshots(self):
        """ISSUE 8 satellite: a controller nothing has leased from yet must
        still serve a valid exposition, and the scrape helpers must return
        empty/zero results — not raise — against it."""
        from agent_tpu.obs.scrape import op_phase_seconds

        c = Controller()
        c.submit("echo", {"x": 1})  # queued, never leased
        with ControllerServer(c) as server:
            with urllib.request.urlopen(server.url + "/v1/metrics") as r:
                text = r.read().decode()
            assert validate_exposition(text) == []
            spans = op_phase_seconds(
                text, ("map_classify_tpu", "map_summarize")
            )
            assert spans == {"map_classify_tpu": 0.0, "map_summarize": 0.0}
            # health still answers with an ok verdict and an empty fleet
            with urllib.request.urlopen(server.url + "/v1/health") as r:
                health = json.load(r)
        assert health["verdict"] == "ok"
        assert health["fleet"] == {"n_agents": 0, "n_stale": 0}
        assert health["queue"]["depth"] == 1

    def test_overlap_by_process_groups_agents(self):
        """ISSUE 7: per-agent overlap attribution — each agent's stage
        spans measured against ITS OWN execute spans, controller spans
        skipped."""
        from agent_tpu.obs.scrape import overlap_by_process

        def span(name, proc, start, dur_ms):
            return {"name": name, "process": proc, "start_wall": start,
                    "duration_ms": dur_ms}

        spans = [
            # agent a: stage fully hidden under execute
            span("execute", "agent:a", 0.0, 1000.0),
            span("stage", "agent:a", 0.2, 200.0),
            # agent b: stage entirely OUTSIDE its execute window
            span("execute", "agent:b", 5.0, 1000.0),
            span("stage", "agent:b", 7.0, 200.0),
            span("apply", "controller", 0.0, 1.0),
        ]
        out = overlap_by_process(spans)
        assert set(out) == {"a", "b"}
        assert out["a"]["overlap_ratio"] == 1.0
        assert out["b"]["overlap_ratio"] == 0.0
        # An agent's stage must NOT count as hidden under another agent's
        # execute — that is the whole point of the per-process grouping.


class TestQuantileErrorBound:
    """ISSUE 8 satellite: the fleet-merged histogram quantile estimate has a
    PINNED error bound — within one bucket width of the exact sample
    quantile (documented on ``histogram_quantile``). Property-tested over
    seeded random samples split across random per-agent snapshots, so the
    bound covers the merge path (``merge_snapshots`` sums bucket counts
    losslessly), not just one registry."""

    @staticmethod
    def _exact_quantile(values, q):
        # The q-quantile as "smallest v with ≥ q·n samples ≤ v" — the
        # ceil(q·n)-th order statistic (what the bucket walk targets).
        import math

        vs = sorted(values)
        rank = max(1, math.ceil(q * len(vs)))
        return vs[rank - 1]

    @staticmethod
    def _bucket_width(buckets, value):
        lower = 0.0
        for b in buckets:
            if value <= b:
                return b - lower
            lower = b
        raise AssertionError(f"{value} beyond the finite bucket range")

    def test_merged_quantiles_within_one_bucket_width(self):
        import random

        from agent_tpu.obs.metrics import DEFAULT_BUCKETS

        rng = random.Random(0x5105)
        for _case in range(60):
            n_agents = rng.randint(1, 5)
            regs = [MetricsRegistry() for _ in range(n_agents)]
            hists = [
                r.histogram("merged_lat_seconds", "m", ("op",)) for r in regs
            ]
            values = []
            for _ in range(rng.randint(1, 300)):
                # Log-uniform over the finite range: every bucket decade
                # gets traffic (uniform would pile into the top buckets).
                import math

                v = 10.0 ** rng.uniform(-2.3, math.log10(DEFAULT_BUCKETS[-1]))
                v = min(v, DEFAULT_BUCKETS[-1])
                values.append(v)
                hists[rng.randrange(n_agents)].observe(v, op="x")
            merged = merge_snapshots([r.snapshot() for r in regs])
            fam = merged["merged_lat_seconds"]
            (series,) = fam["series"]
            assert sum(series["counts"]) == len(values)
            for q in (0.5, 0.95, 0.99):
                est = histogram_quantile(fam["buckets"], series["counts"], q)
                exact = self._exact_quantile(values, q)
                width = self._bucket_width(fam["buckets"], exact)
                assert abs(est - exact) <= width + 1e-9, (
                    f"q={q}: estimate {est} vs exact {exact} exceeds one "
                    f"bucket width {width} (n={len(values)}, "
                    f"agents={n_agents})"
                )

    def test_merge_is_lossless_vs_pooled_histogram(self):
        """The merge itself adds NO error: summed per-agent bucket counts
        equal the single-histogram counts over the pooled samples, so the
        merged estimate is bit-identical to the pooled estimate."""
        import random

        rng = random.Random(7)
        pooled = MetricsRegistry()
        ph = pooled.histogram("lat", "l", ("op",))
        regs = [MetricsRegistry() for _ in range(3)]
        hs = [r.histogram("lat", "l", ("op",)) for r in regs]
        for _ in range(500):
            v = rng.expovariate(5.0)
            ph.observe(v, op="x")
            hs[rng.randrange(3)].observe(v, op="x")
        merged = merge_snapshots([r.snapshot() for r in regs])
        (m_series,) = merged["lat"]["series"]
        (p_series,) = pooled.snapshot()["lat"]["series"]
        assert m_series["counts"] == p_series["counts"]
        for q in (0.5, 0.9, 0.99):
            assert histogram_quantile(
                merged["lat"]["buckets"], m_series["counts"], q
            ) == histogram_quantile(
                merged["lat"]["buckets"], p_series["counts"], q
            )


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        fr = FlightRecorder(capacity=16)
        for i in range(10_000):
            fr.record("ev", i=i)
        assert len(fr) == 16
        assert fr.dropped == 10_000 - 16
        assert [e["i"] for e in fr.events()] == list(range(9984, 10_000))

    def test_dump_jsonl_stringifies_exotic_fields(self, tmp_path):
        fr = FlightRecorder(capacity=4)
        fr.record("ev", weird={1, 2}, job_id="j1")
        path = str(tmp_path / "dump.jsonl")
        assert fr.dump(path) == 1
        (line,) = open(path).read().splitlines()
        assert json.loads(line)["job_id"] == "j1"


class TestResultOpAttribution:
    """Satellite: ops stamp "op"; the spans heuristic survives only as a
    fallback for old bodies — both paths covered."""

    def test_explicit_op_key_wins(self):
        from agent_tpu.utils.spans import result_op

        assert result_op({"op": "map_summarize", "summaries": []}) \
            == "map_summarize"
        assert result_op({"op": "map_classify_tpu"}) == "map_classify_tpu"

    def test_heuristic_fallback_for_old_bodies(self):
        from agent_tpu.utils.spans import result_op

        assert result_op({"ok": True, "summaries": ["x"]}) == "map_summarize"
        assert result_op({"ok": True, "summary": "x"}) == "map_summarize"
        assert result_op(
            {"ok": True, "output_path": "/s/map_summarize_rows_0.jsonl"}
        ) == "map_summarize"
        assert result_op({"ok": True}) is None

    def test_summarize_result_carries_op(self, runtime):
        from agent_tpu.ops import get_op
        from agent_tpu.runtime.context import OpContext

        tiny = {
            "d_model": 32, "n_heads": 4, "n_enc_layers": 1,
            "n_dec_layers": 1, "d_ff": 64, "max_src_len": 64,
            "max_tgt_len": 16, "dtype": "float32",
        }
        out = get_op("map_summarize")(
            {"texts": ["op stamping test row"], "max_length": 4,
             "model_config": tiny},
            OpContext(runtime=runtime),
        )
        assert out["ok"] is True and out["op"] == "map_summarize"


# ---- end-to-end acceptance ----

def _scrape(url):
    with urllib.request.urlopen(url + "/v1/metrics") as r:
        text = r.read().decode()
    assert validate_exposition(text) == []
    return text, parse_exposition(text)


def _sample(parsed, name, **want):
    """Sum samples of ``name`` whose labels include ``want``."""
    total, n = 0.0, 0
    for labels, value in parsed.get(name, []):
        if all(labels.get(k) == v for k, v in want.items()):
            total += value
            n += 1
    return total if n else None


def _drain_pipelined(controller, server, runtime, tasks=("map_classify_tpu",)):
    cfg = Config(agent=AgentConfig(
        controller_url=server.url, agent_name="obs-pipe",
        tasks=tasks, idle_sleep_sec=0.0, pipeline_depth=2,
    ))
    agent = Agent(config=cfg, session=requests.Session(), runtime=runtime)
    agent._profile = {"tier": "test"}

    def watch():
        deadline = time.time() + 120
        while not controller.drained() and time.time() < deadline:
            time.sleep(0.02)
        agent.shutdown()

    threading.Thread(target=watch, daemon=True).start()
    agent.run()
    return agent


def test_pipelined_drain_metrics_on_v1_metrics(runtime, tmp_path):
    """The acceptance path: after a pipelined drain, /v1/metrics serves a
    valid exposition whose fleet-merged series show all three phases,
    queue-depth gauges, and device idle time — and the controller-side
    counters/histograms cover the lease/result flow."""
    csv = tmp_path / "rows.csv"
    csv.write_text(
        "id,text\n" + "".join(f'{i},"obs drain row {i}"\n' for i in range(64)),
        encoding="utf-8",
    )
    c = Controller()
    c.submit_csv_job(
        str(csv), total_rows=64, shard_size=16, map_op="map_classify_tpu",
        extra_payload={"text_field": "text", "allow_fallback": False,
                       "result_format": "columnar",
                       "model_config": dict(TINY), "topk": 3},
    )
    with ControllerServer(c) as server:
        _drain_pipelined(c, server, runtime)
        text, parsed = _scrape(server.url)

    assert c.counts() == {"succeeded": 4}
    # fleet-merged agent series (no agent label): all three phases nonzero
    for phase in ("stage", "execute", "finalize"):
        s = _sample(parsed, "task_phase_seconds_sum",
                    op="map_classify_tpu", phase=phase)
        assert s is not None and s > 0, (phase, text)
        assert _sample(parsed, "task_phase_seconds_count",
                       op="map_classify_tpu", phase=phase) == 4
    # queue-depth gauges exist for both pipeline queues
    assert _sample(parsed, "queue_depth", queue="staged") is not None
    assert _sample(parsed, "queue_depth", queue="post") is not None
    # the device thread necessarily idled waiting for the first lease
    assert _sample(parsed, "device_idle_seconds_total") > 0
    assert _sample(parsed, "device_busy_seconds_total") > 0
    assert _sample(parsed, "tasks_total",
                   op="map_classify_tpu", status="succeeded") == 4
    # controller side
    assert _sample(parsed, "controller_tasks_leased_total",
                   op="map_classify_tpu") == 4
    assert _sample(parsed, "controller_results_total",
                   op="map_classify_tpu", outcome="succeeded") == 4
    assert _sample(parsed, "controller_queue_wait_seconds_count",
                   op="map_classify_tpu") == 4
    assert _sample(parsed, "controller_lease_requests_total",
                   outcome="granted") >= 1
    assert _sample(parsed, "agent_last_seen_seconds", agent="obs-pipe") \
        is not None


def test_stale_epoch_fault_increments_fence_counter_end_to_end(runtime):
    """Injected stale_epoch → the agent's result arrives fenced; the
    rejection is a real counter on /v1/metrics, not just an attribute."""
    c = Controller(lease_ttl_sec=0.2)
    c.submit("echo", {"x": 1})
    c.inject("stale_epoch")
    with ControllerServer(c) as server:
        cfg = Config(agent=AgentConfig(
            controller_url=server.url, agent_name="fence",
            tasks=("echo",), idle_sleep_sec=0.0, pipeline_depth=0,
        ))
        agent = Agent(config=cfg, session=requests.Session())
        agent._profile = {"tier": "test"}
        agent.step()  # executes; result is fenced off
        assert c.stale_results == 1
        time.sleep(0.25)  # lease TTL passes; job re-queues at bumped epoch
        deadline = time.time() + 30
        while not c.drained() and time.time() < deadline:
            agent.step()
        assert c.drained()
        agent.push_metrics()
        _, parsed = _scrape(server.url)
    assert _sample(parsed, "controller_results_total",
                   op="echo", outcome="stale_epoch") == 1
    assert _sample(parsed, "controller_results_total",
                   op="echo", outcome="succeeded") == 1
    assert _sample(parsed, "controller_lease_expirations_total",
                   op="echo") == 1
    # the fence event is in the controller's flight recorder, trace-intact
    kinds = {e["kind"] for e in c.recorder.events()}
    assert "epoch_fence" in kinds


def test_trace_propagates_into_result_bodies(runtime, tmp_path):
    """trace={job_id, attempt, lease_id} stamped at lease time reaches the
    stored result via ctx.tags, serial and pipelined alike."""
    c = Controller()
    jid = c.submit("map_classify_tpu",
                   {"texts": ["trace row"], "topk": 2,
                    "model_config": dict(TINY), "allow_fallback": False})
    with ControllerServer(c) as server:
        _drain_pipelined(c, server, runtime)
    result = c.job_snapshot(jid)["result"]
    trace = result["trace"]
    assert trace["job_id"] == jid
    assert trace["attempt"] == 1
    assert isinstance(trace["lease_id"], str) and trace["lease_id"]
    # and the controller's recorder kept lease/result events for the job
    evs = [e for e in c.recorder.events() if e.get("job_id") == jid]
    assert {"submit", "lease", "result"} <= {e["kind"] for e in evs}
    assert any(e.get("lease_id") == trace["lease_id"] for e in evs)


def test_flight_recorder_dumps_correlate_across_both_sides(runtime, tmp_path):
    """Injected failure: a missing shard file hard-fails a job (retry, then
    terminal `dead` once the budget is spent). Dumps from the agent and
    controller recorders both carry the job's trace-correlated events."""
    c = Controller()
    jid = c.submit("map_classify_tpu",
                   {"source_uri": str(tmp_path / "missing.csv"),
                    "start_row": 0, "shard_size": 8})
    with ControllerServer(c) as server:
        agent = _drain_pipelined(c, server, runtime)
    assert c.job_snapshot(jid)["state"] == "dead"

    a_path = str(tmp_path / "agent.jsonl")
    c_path = str(tmp_path / "controller.jsonl")
    agent.recorder.dump(a_path)
    c.recorder.dump(c_path)
    a_events = [json.loads(ln) for ln in open(a_path)]
    c_events = [json.loads(ln) for ln in open(c_path)]
    a_mine = [e for e in a_events if e.get("job_id") == jid]
    c_mine = [e for e in c_events if e.get("job_id") == jid]
    # agent side saw the op raise (twice: attempt + retry)
    errors = [e for e in a_mine if e["kind"] == "error"]
    assert len(errors) == 2
    assert errors[0]["type"] in ("FileNotFoundError", "OSError")
    # controller side saw both lease attempts and the failed results
    assert sum(1 for e in c_mine if e["kind"] == "lease") == 2
    assert sum(1 for e in c_mine
               if e["kind"] == "result" and e["state"] == "failed") == 2
    # correlation: the same lease_id appears on both sides
    a_leases = {e.get("lease_id") for e in a_mine if e.get("lease_id")}
    c_leases = {e.get("lease_id") for e in c_mine if e.get("lease_id")}
    assert a_leases & c_leases


def test_status_summary_exposes_phase_percentiles(runtime, tmp_path):
    csv = tmp_path / "r.csv"
    csv.write_text(
        "id,text\n" + "".join(f'{i},"row {i}"\n' for i in range(32)),
        encoding="utf-8",
    )
    c = Controller()
    c.submit_csv_job(
        str(csv), total_rows=32, shard_size=8, map_op="map_classify_tpu",
        extra_payload={"text_field": "text", "allow_fallback": False,
                       "result_format": "columnar",
                       "model_config": dict(TINY), "topk": 3},
    )
    with ControllerServer(c) as server:
        _drain_pipelined(c, server, runtime)
        with urllib.request.urlopen(server.url + "/v1/status") as r:
            status = json.load(r)
    summary = status["summary"]
    assert summary["ops"]["map_classify_tpu"]["succeeded"] == 4
    phases = summary["task_phase_seconds"]["map_classify_tpu"]
    for phase in ("stage", "execute", "finalize"):
        assert phases[phase]["count"] == 4
        assert phases[phase]["p50"] is not None
        assert phases[phase]["p99"] >= phases[phase]["p50"]
