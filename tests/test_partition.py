"""Partitioned control plane (ISSUE 18): ring stability, routing core,
steal policy, and the spool-redelivery exactly-once pins."""

import json
import os
import random
import subprocess
import sys

import pytest

from agent_tpu.controller.core import Controller
from agent_tpu.controller.partition import (
    HashRing,
    PartitionDown,
    PartitionMap,
    RouterCore,
    job_id_for_partition,
    placement_key,
    stable_hash,
)
from agent_tpu.sched.steal import StealPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Ring stability
# ---------------------------------------------------------------------------


def test_stable_hash_is_not_builtin_hash():
    # blake2b, not hash(): same value in every process regardless of
    # PYTHONHASHSEED, and 64-bit wide.
    v = stable_hash("tenant\x1fjob-1")
    assert isinstance(v, int)
    assert 0 <= v < 2**64
    assert v == stable_hash("tenant\x1fjob-1")


def test_placement_deterministic_across_processes():
    """The whole point of stable_hash: a router replica, a restarted
    router, and an agent-side partition map — different processes with
    different hash seeds — must all place a key identically."""
    keys = [placement_key(t, f"job-{i}")
            for i in range(20) for t in (None, "acme")]
    ring = HashRing(["p0", "p1", "p2"])
    local = [ring.place(k) for k in keys]
    code = (
        "import json, sys\n"
        "from agent_tpu.controller.partition import HashRing\n"
        "ring = HashRing(['p0', 'p1', 'p2'])\n"
        "keys = json.loads(sys.stdin.read())\n"
        "print(json.dumps([ring.place(k) for k in keys]))\n"
    )
    for seed in ("0", "1", "31337"):
        env = dict(os.environ, PYTHONHASHSEED=seed,
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        out = subprocess.run(
            [sys.executable, "-c", code], input=json.dumps(keys),
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert json.loads(out.stdout) == local, (
            f"placement diverged under PYTHONHASHSEED={seed}"
        )


def _remap_check(members, keys, slack=2.0):
    """Rendezvous hashing's minimal-remap property on a concrete key set:
    removing a member moves EXACTLY that member's keys (everyone else's
    argmax is untouched), and adding one moves only keys it now wins —
    ~1/N of them, bounded here by ``slack``/N."""
    ring = HashRing(members)
    n = len(members)
    before = {k: ring.place(k) for k in keys}
    victim = sorted(members)[0]

    ring.remove(victim)
    after_rm = {k: ring.place(k) for k in keys}
    for k in keys:
        if before[k] != victim:
            assert after_rm[k] == before[k], (
                f"{k!r} moved off a surviving member on remove"
            )
    owned = sum(1 for k in keys if before[k] == victim)
    assert owned <= max(4, slack * len(keys) / n)

    ring.add(victim)
    after_add = {k: ring.place(k) for k in keys}
    moved = [k for k in keys if after_add[k] != after_rm[k]]
    for k in moved:
        assert after_add[k] == victim, (
            f"{k!r} moved on add but not onto the new member"
        )
    assert after_add == before  # add-back restores the exact placement
    assert len(moved) <= max(4, slack * len(keys) / n)


def test_ring_remap_bounded_seeded():
    rng = random.Random(7)
    members = [f"p{i}" for i in range(5)]
    keys = [
        placement_key(
            rng.choice([None, "acme", "globex"]),
            f"job-{rng.getrandbits(48):012x}",
        )
        for _ in range(2000)
    ]
    _remap_check(members, keys)


def test_ring_remap_bounded_hypothesis():
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    @hyp.settings(deadline=None, max_examples=30)
    @hyp.given(
        n_members=st.integers(min_value=2, max_value=9),
        seed=st.integers(min_value=0, max_value=2**31),
        n_keys=st.integers(min_value=50, max_value=500),
    )
    def run(n_members, seed, n_keys):
        rng = random.Random(seed)
        members = [f"p{i}" for i in range(n_members)]
        keys = list({
            placement_key(None, f"job-{rng.getrandbits(48):012x}")
            for _ in range(n_keys)
        })
        _remap_check(members, keys, slack=3.0)

    run()


def test_ring_rejects_bad_members():
    with pytest.raises(ValueError):
        HashRing([])
    with pytest.raises(ValueError):
        HashRing(["ok", "bad!name"])  # "!" is the lease-tag separator
    ring = HashRing(["only"])
    with pytest.raises(ValueError):
        ring.remove("only")


def test_job_id_for_partition_lands_where_asked():
    ring = HashRing(["p0", "p1", "p2"])
    for target in ring.members:
        jid = job_id_for_partition(ring, target, prefix="t")
        assert ring.place(placement_key(None, jid)) == target


def test_partition_map_parse_grammar():
    pmap = PartitionMap.parse(
        "p0=http://a:1|http://a-standby:2, p1=http://b:3"
    )
    assert pmap.names == ("p0", "p1")
    assert pmap.urls("p0") == ["http://a:1", "http://a-standby:2"]
    bare = PartitionMap.parse("http://a:1,http://b:2")
    assert bare.names == ("p0", "p1")
    with pytest.raises(ValueError):
        PartitionMap({"bad!": ["http://a"]})
    with pytest.raises(ValueError):
        PartitionMap({})


# ---------------------------------------------------------------------------
# Steal policy
# ---------------------------------------------------------------------------


def test_steal_policy_picks_deepest_eligible():
    p = StealPolicy(enabled=True, min_advantage=2)
    depths = {"home": 1, "a": 2, "b": 6, "c": 4}
    assert p.pick_victim("home", depths) == "b"
    # a is only +1 over home — under the hysteresis, never picked.
    assert p.pick_victim("home", {"home": 1, "a": 2}) is None


def test_steal_policy_skips_unknown_and_ties_by_name():
    p = StealPolicy(enabled=True, min_advantage=1)
    # Unreachable partitions sample as None and are never victims.
    assert p.pick_victim("home", {"home": 0, "a": None}) is None
    # Equal depths: first name in sorted order wins (deterministic).
    assert p.pick_victim("home", {"home": 0, "b": 3, "a": 3}) == "a"
    # A down HOME samples as None -> treated as depth 0, so any survivor
    # with work qualifies — the partition-kill survivability hinge.
    assert p.pick_victim("home", {"home": None, "a": 1}) == "a"


def test_steal_policy_disabled_never_steals():
    p = StealPolicy(enabled=False)
    assert p.pick_victim("home", {"home": 0, "a": 100}) is None


# ---------------------------------------------------------------------------
# RouterCore over stub transports
# ---------------------------------------------------------------------------


class StubTransport:
    """Scripted per-partition responses + a call log."""

    def __init__(self, pmap, responses=None, depths=None):
        self.pmap = pmap
        self.responses = responses or {}
        self.depths = depths or {}
        self.down = set()
        self.calls = []

    def _name(self, url):
        for name in self.pmap.names:
            if url in self.pmap.urls(name):
                return name
        raise AssertionError(f"unknown url {url}")

    def post(self, url, path, body, timeout):
        name = self._name(url)
        self.calls.append((name, url, path, body))
        if name in self.down or url in self.down:
            raise ConnectionError(f"{url} down")
        fn = self.responses.get((name, path))
        if fn is None:
            return 200, {}
        return fn(body)

    def get(self, url, path, timeout):
        name = self._name(url)
        if name in self.down or url in self.down:
            raise ConnectionError(f"{url} down")
        if path == "/v1/depth":
            return 200, {"leasable": self.depths.get(name, 0)}
        return 404, None


def make_core(names=("p0", "p1", "p2"), urls=None, **kwargs):
    pmap = PartitionMap(
        urls or {n: (f"http://{n}",) for n in names}
    )
    stub = StubTransport(pmap)
    core = RouterCore(
        pmap, stub.post, get_fn=stub.get,
        steal=kwargs.pop("steal", StealPolicy(enabled=True,
                                              min_advantage=1)),
        depth_cache_sec=kwargs.pop("depth_cache_sec", 0.0),
        **kwargs,
    )
    return core, stub


def test_route_submit_mints_id_and_hits_home():
    core, stub = make_core()
    status, parsed = core.route_submit({"op": "echo", "payload": {}})
    assert status == 200
    (name, _, path, body) = stub.calls[0]
    assert path == "/v1/jobs"
    assert body["job_id"]  # router minted the id
    assert name == core.home_for_job(None, body["job_id"])
    # A client retry with the minted id lands on the same partition.
    stub.calls.clear()
    core.route_submit({"op": "echo", "job_id": body["job_id"]})
    assert stub.calls[0][0] == name


def test_route_submit_csv_places_whole_bulk_by_source_uri():
    core, stub = make_core()
    want = core.pmap.ring.place(
        placement_key("acme", "csv\x1f/data/rows.csv")
    )
    for _ in range(3):
        core.route_submit({
            "source_uri": "/data/rows.csv", "tenant": "acme",
            "total_rows": 100, "shard_size": 10,
        })
    assert [c[0] for c in stub.calls] == [want] * 3


def test_route_workflow_places_whole_dag_by_graph_id():
    """ISSUE 19: every stage of a DAG lands on ONE partition, keyed by the
    graph id — the same whole-unit rule CSV bulk submits use for
    ``source_uri``. Dep edges never span partitions."""
    core, stub = make_core()
    doc = {
        "tenant": "acme", "workflow_id": "wf-fixed",
        "stages": [{"name": "tok", "op": "echo", "payload": {}}],
    }
    want = core.pmap.ring.place(placement_key("acme", "wf\x1fwf-fixed"))
    for _ in range(3):
        status, parsed = core.route_workflow(dict(doc))
        assert status == 200
        assert parsed["partition"] == want
    assert [c[0] for c in stub.calls] == [want] * 3
    assert all(c[2] == "/v1/workflows" for c in stub.calls)
    assert core.counters["submits_total"] == 3


def test_route_workflow_mints_graph_id_and_resubmit_sticks():
    core, stub = make_core()
    status, _ = core.route_workflow(
        {"stages": [{"name": "a", "op": "echo", "payload": {}}]}
    )
    assert status == 200
    name, _, _, body = stub.calls[0]
    assert body["workflow_id"].startswith("wf-")  # router minted the id
    stub.calls.clear()
    core.route_workflow({"workflow_id": body["workflow_id"], "stages": []})
    assert stub.calls[0][0] == name


def test_stolen_dag_stage_lease_still_tagged_with_owner_partition():
    """Work stealing is unchanged by DAG placement: an agent homed off the
    DAG's owner partition can steal its stages, and the lease id carries
    the OWNER's tag so the result routes back to the partition holding the
    workflow state."""
    core, stub = make_core()
    owner = core.pmap.ring.place(placement_key("acme", "wf\x1fwf-steal"))
    core.route_workflow({
        "tenant": "acme", "workflow_id": "wf-steal",
        "stages": [{"name": "tok", "op": "echo", "payload": {}}],
    })
    agent = next(
        f"w{i}" for i in range(100)
        if core.home_for_agent(f"w{i}") != owner
    )
    home = core.home_for_agent(agent)
    stub.depths.update({n: 0 for n in core.pmap.names})
    stub.depths[owner] = 3
    stub.responses[(home, "/v1/leases")] = lambda body: (204, None)
    stub.responses[(owner, "/v1/leases")] = lambda body: (
        200, {"lease_id": "lease-wf", "tasks": [{"id": "wf-steal-tok"}]}
    )
    status, lease = core.route_lease({"agent": agent, "max_tasks": 1})
    assert status == 200
    assert lease["lease_id"] == f"{owner}!lease-wf"


def test_route_submit_429_passes_through_with_partition_stamp():
    core, stub = make_core()
    jid = job_id_for_partition(core.pmap.ring, "p1", prefix="bp")
    stub.responses[("p1", "/v1/jobs")] = lambda body: (
        429, {"error": "queue full", "retry_after_ms": 1500}
    )
    status, parsed = core.route_submit({"op": "echo", "job_id": jid})
    assert status == 429
    assert parsed["retry_after_ms"] == 1500  # untouched
    assert parsed["partition"] == "p1"       # who said no
    assert core.counters["rejects_429_total"] == 1


def test_route_lease_tags_and_route_result_untags():
    core, stub = make_core()
    agent = "worker-1"
    home = core.home_for_agent(agent)
    stub.responses[(home, "/v1/leases")] = lambda body: (
        200, {"lease_id": "lease-abc", "tasks": [{"id": "j1"}]}
    )
    status, lease = core.route_lease({"agent": agent, "max_tasks": 1})
    assert status == 200
    assert lease["lease_id"] == f"{home}!lease-abc"
    assert core.counters["lease_grants_home_total"] == 1

    # The result follows the tag back and the partition sees its NATIVE id.
    stub.calls.clear()
    stub.responses[(home, "/v1/results")] = lambda body: (
        200, {"accepted": True}
    )
    status, out = core.route_result({
        "lease_id": lease["lease_id"], "job_id": "j1",
        "job_epoch": 0, "status": "succeeded",
    })
    assert status == 200 and out["accepted"]
    (name, _, path, body) = stub.calls[-1]
    assert (name, path) == (home, "/v1/results")
    assert body["lease_id"] == "lease-abc"
    assert core.counters["results_routed_total"] == 1


def test_route_lease_steals_from_deepest_when_home_empty():
    core, stub = make_core()
    agent = "worker-2"
    home = core.home_for_agent(agent)
    victim = next(n for n in core.pmap.names if n != home)
    stub.depths.update({n: 0 for n in core.pmap.names})
    stub.depths[victim] = 5
    stub.responses[(home, "/v1/leases")] = lambda body: (204, None)
    stub.responses[(victim, "/v1/leases")] = lambda body: (
        200, {"lease_id": "lease-v", "tasks": [{"id": "j2"}]}
    )
    status, lease = core.route_lease({"agent": agent, "max_tasks": 1})
    assert status == 200
    assert lease["lease_id"] == f"{victim}!lease-v"
    assert core.counters["lease_grants_stolen_total"] == 1


def test_route_lease_home_down_falls_through_to_steal():
    """A dead home partition must not strand its agents: the lease poll
    falls through to stealing from a survivor with work."""
    core, stub = make_core()
    agent = "worker-3"
    home = core.home_for_agent(agent)
    victim = next(n for n in core.pmap.names if n != home)
    stub.down.add(home)
    stub.depths[victim] = 3
    stub.responses[(victim, "/v1/leases")] = lambda body: (
        200, {"lease_id": "lease-s", "tasks": [{"id": "j3"}]}
    )
    status, lease = core.route_lease({"agent": agent, "max_tasks": 1})
    assert status == 200
    assert lease["lease_id"].startswith(f"{victim}!")

    # Heartbeat polls (max_tasks=0) must surface the outage instead —
    # they carry metrics/spool flushes, not requests for work.
    with pytest.raises(PartitionDown):
        core.route_lease({"agent": agent, "max_tasks": 0})
    # And with no victim holding work, the outage surfaces too.
    stub.depths[victim] = 0
    core.leasable_depths()  # refresh the (uncached) sample
    with pytest.raises(PartitionDown):
        core.route_lease({"agent": agent, "max_tasks": 1})


def test_route_result_untagged_fans_out_until_owner_found():
    core, stub = make_core()
    owner = core.pmap.names[-1]
    for n in core.pmap.names:
        stub.responses[(n, "/v1/results")] = (
            (lambda body: (200, {"accepted": True})) if n == owner
            else (lambda body: (404, {"accepted": False,
                                      "reason": "unknown job"}))
        )
    status, out = core.route_result({
        "lease_id": "lease-legacy", "job_id": "jx", "job_epoch": 0,
        "status": "succeeded",
    })
    assert status == 200 and out["accepted"]
    assert core.counters["results_fanout_total"] == 1


def test_route_result_untagged_unknown_plus_down_partition_raises():
    """'Unknown job' while a partition is dark is NOT an answer — the
    owner might be the dark one, so the agent must spool and retry."""
    core, stub = make_core()
    stub.down.add(core.pmap.names[0])
    for n in core.pmap.names[1:]:
        stub.responses[(n, "/v1/results")] = lambda body: (
            404, {"accepted": False, "reason": "unknown job"}
        )
    with pytest.raises(PartitionDown):
        core.route_result({
            "lease_id": "lease-legacy", "job_id": "jy", "job_epoch": 0,
            "status": "succeeded",
        })


def test_post_partition_rotates_to_standby_url():
    core, stub = make_core(
        names=("p0",),
        urls={"p0": ("http://p0-primary", "http://p0-standby")},
    )
    stub.down.add("http://p0-primary")
    stub.responses[("p0", "/v1/jobs")] = lambda body: (200, {"ok": True})
    status, parsed = core.route_submit({"op": "echo", "job_id": "r1"})
    assert status == 200
    assert stub.calls[-1][1] == "http://p0-standby"
    assert core.counters["partition_failovers_total"] == 1
    # Both URLs dark -> PartitionDown names the partition.
    stub.down.add("http://p0-standby")
    with pytest.raises(PartitionDown) as exc:
        core.route_submit({"op": "echo", "job_id": "r2"})
    assert exc.value.partition == "p0"


# ---------------------------------------------------------------------------
# Spool redelivery + terminal guard, against REAL controllers
# ---------------------------------------------------------------------------


class ControllerFleet:
    """Real in-process Controllers behind RouterCore's transport seam —
    the /v1/jobs, /v1/leases, /v1/results, /v1/depth surface mapped
    straight onto core calls, with a kill/restart switch per partition."""

    def __init__(self, names, tmp):
        self.tmp = tmp
        self.journals = {
            n: os.path.join(tmp, f"journal.{n}.jsonl") for n in names
        }
        self.controllers = {n: self._boot(n) for n in names}
        self.down = set()
        self.pmap = PartitionMap({n: (f"http://{n}",) for n in names})

    def _boot(self, name):
        return Controller(
            partition=name, journal_path=self.journals[name],
            lease_ttl_sec=30.0, requeue_delay_sec=0.0,
        )

    def kill(self, name):
        # SIGKILL-shaped: no close(), the journal keeps the live lease.
        self.down.add(name)

    def restart(self, name):
        self.controllers[name] = self._boot(name)
        self.down.discard(name)

    def close(self):
        for c in self.controllers.values():
            c.close()

    def post(self, url, path, body, timeout):
        name = url.removeprefix("http://")
        if name in self.down:
            raise ConnectionError(f"{name} is down")
        c = self.controllers[name]
        if path == "/v1/jobs":
            jid = c.submit(
                body["op"], body.get("payload"),
                job_id=body.get("job_id"),
            )
            return 200, {"job_id": jid}
        if path == "/v1/leases":
            lease = c.lease(
                str(body.get("agent")), body.get("capabilities"),
                max_tasks=int(body.get("max_tasks", 1)),
            )
            return (204, None) if lease is None else (200, lease)
        if path == "/v1/results":
            out = c.report(
                lease_id=str(body.get("lease_id", "")),
                job_id=str(body.get("job_id", "")),
                job_epoch=body.get("job_epoch"),
                status=str(body.get("status", "")),
                result=body.get("result"), error=body.get("error"),
                # What the HTTP server bills: the measured request size.
                wire_bytes=len(json.dumps(body).encode()),
            )
            return 200, out
        return 404, None

    def get(self, url, path, timeout):
        name = url.removeprefix("http://")
        if name in self.down:
            raise ConnectionError(f"{name} is down")
        if path == "/v1/depth":
            return 200, {
                "leasable": self.controllers[name].leasable_depth()
            }
        return 404, None


def test_spool_redelivery_after_partition_death_bills_once(tmp_path):
    """The ISSUE 18 regression pin: a result spooled against a partition
    that died mid-lease redelivers to the restarted partition (journal
    replay requeues the lease AT THE SAME EPOCH, so the redelivered
    result is accepted, not stale-fenced) and bills exactly once; a late
    duplicate then rejects on the terminal-state guard."""
    fleet = ControllerFleet(["p0", "p1"], str(tmp_path))
    try:
        core = RouterCore(
            fleet.pmap, fleet.post, get_fn=fleet.get,
            steal=StealPolicy(enabled=True, min_advantage=1),
            depth_cache_sec=0.0,
        )
        # An agent homed on p0 leases a p0-homed job through the router.
        agent = next(
            f"w{i}" for i in range(100)
            if core.home_for_agent(f"w{i}") == "p0"
        )
        jid = job_id_for_partition(core.pmap.ring, "p0", prefix="sp")
        status, _ = core.route_submit({
            "op": "echo", "payload": {"x": 1}, "job_id": jid,
        })
        assert status == 200
        status, lease = core.route_lease({
            "agent": agent, "capabilities": {"ops": ["echo"]},
            "max_tasks": 1,
        })
        assert status == 200
        assert lease["lease_id"].startswith("p0!")
        task = lease["tasks"][0]
        assert task["id"] == jid

        result_body = {
            "lease_id": lease["lease_id"], "job_id": jid,
            "job_epoch": task["job_epoch"], "status": "succeeded",
            "result": {"x": 1},
        }
        # The partition dies before the result lands: the post raises,
        # the agent spools the body — TAGGED lease id and all.
        fleet.kill("p0")
        with pytest.raises(PartitionDown):
            core.route_result(result_body)

        # Restart over the same journal; the spool flush retries the
        # identical body and must be APPLIED (same epoch after replay).
        fleet.restart("p0")
        status, out = core.route_result(result_body)
        assert status == 200 and out["accepted"], out
        p0 = fleet.controllers["p0"]
        assert p0.job_snapshot(jid)["state"] == "succeeded"
        assert p0.usage is not None
        assert p0.usage.job_billed_attempts().get(jid) == 1

        # A late duplicate (redelivery raced a competing apply) rejects
        # on the terminal guard and the bill does not move.
        status, dup = core.route_result(result_body)
        assert status == 200 and not dup["accepted"]
        assert dup["reason"] == "already complete"
        assert p0.usage.job_billed_attempts().get(jid) == 1
    finally:
        fleet.close()


def test_stolen_lease_result_routes_to_owner_and_bills_once(tmp_path):
    """A stolen lease is an ordinary lease against the job's OWNER: the
    tagged id routes the thief's result to the victim partition, the
    home partition never hears about it, and billing lands once."""
    fleet = ControllerFleet(["p0", "p1"], str(tmp_path))
    try:
        core = RouterCore(
            fleet.pmap, fleet.post, get_fn=fleet.get,
            steal=StealPolicy(enabled=True, min_advantage=1),
            depth_cache_sec=0.0,
        )
        # A thief homed on p1 steals p0's only job (p1 is empty).
        thief = next(
            f"t{i}" for i in range(100)
            if core.home_for_agent(f"t{i}") == "p1"
        )
        jid = job_id_for_partition(core.pmap.ring, "p0", prefix="st")
        core.route_submit({"op": "echo", "payload": {}, "job_id": jid})
        status, lease = core.route_lease({
            "agent": thief, "capabilities": {"ops": ["echo"]},
            "max_tasks": 1,
        })
        assert status == 200
        assert lease["lease_id"].startswith("p0!")  # granted by the owner
        assert core.counters["lease_grants_stolen_total"] == 1

        task = lease["tasks"][0]
        status, out = core.route_result({
            "lease_id": lease["lease_id"], "job_id": jid,
            "job_epoch": task["job_epoch"], "status": "succeeded",
            "result": {},
        })
        assert status == 200 and out["accepted"]
        p0, p1 = fleet.controllers["p0"], fleet.controllers["p1"]
        assert p0.job_snapshot(jid)["state"] == "succeeded"
        with pytest.raises(KeyError):
            p1.job_snapshot(jid)  # job state never moved partitions
        assert p0.usage.job_billed_attempts().get(jid) == 1
        assert (p1.usage.job_billed_attempts() if p1.usage else {}) == {}
    finally:
        fleet.close()
