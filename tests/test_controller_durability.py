"""Controller durability + sizing feedback (VERDICT r2 items 6 and 8):
profile-derived shard splitting, background TTL sweeper, journal resume."""

import time

from agent_tpu.controller.core import DEFAULT_SHARD_ROWS, Controller
from agent_tpu.sizing.profile import _tpu_batch_hints


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _tpu_profile(hbm_gb: int, chips: int = 4):
    """A worker profile as sizing/profile.py would build it for this HBM."""
    tpu = {
        "tpu_present": True,
        "n_chips": chips,
        "hbm_bytes_per_chip": hbm_gb * 2**30,
    }
    return {"tier": "tpu-pod", "tpu": dict(tpu, **_tpu_batch_hints(tpu))}


class TestSizingFeedback:
    def test_shard_size_derived_from_leased_profile(self):
        """The sizing→controller loop: a TPU agent's advertised profile
        changes how submit_csv_job(shard_size=None) splits the dataset."""
        c = Controller()
        c.lease("a", {"ops": ["x"]}, worker_profile=_tpu_profile(hbm_gb=16))
        big = c.suggested_shard_size()
        ids_big, _ = c.submit_csv_job("d.csv", total_rows=8 * big)
        assert len(ids_big) == 8

        c2 = Controller()
        c2.lease("a", {"ops": ["x"]}, worker_profile=_tpu_profile(hbm_gb=4))
        small = c2.suggested_shard_size()
        assert small < big  # less HBM ⇒ smaller shards
        ids_small, _ = c2.submit_csv_job("d.csv", total_rows=8 * big)
        assert len(ids_small) == 8 * big // small > 8

    def test_fallback_to_reference_default_without_profile(self):
        c = Controller()
        assert c.suggested_shard_size() is None
        ids, _ = c.submit_csv_job("d.csv", total_rows=250)
        assert len(ids) == -(-250 // DEFAULT_SHARD_ROWS)

    def test_cpu_profile_yields_no_suggestion(self):
        c = Controller()
        c.lease("a", {"ops": ["x"]}, worker_profile={"tier": "cpu", "tpu": {}})
        assert c.suggested_shard_size() is None

    def test_explicit_shard_size_still_wins(self):
        c = Controller()
        c.lease("a", {"ops": ["x"]}, worker_profile=_tpu_profile(hbm_gb=16))
        ids, _ = c.submit_csv_job("d.csv", total_rows=100, shard_size=50)
        assert len(ids) == 2

    def test_cpu_agent_poll_does_not_revert_tpu_hint(self):
        """Mixed fleet: a CPU agent's lease between the TPU agent's lease and
        the submit must not flip sizing back to the 100-row fallback."""
        c = Controller()
        c.lease("tpu-a", {"ops": ["x"]}, worker_profile=_tpu_profile(hbm_gb=16))
        hint = c.suggested_shard_size()
        c.lease("cpu-a", {"ops": ["x"]}, worker_profile={"tier": "cpu", "tpu": {}})
        assert c.suggested_shard_size() == hint


class TestSweeper:
    def test_sweep_requeues_without_lease_traffic(self):
        clock = FakeClock()
        c = Controller(lease_ttl_sec=10.0, clock=clock)
        jid = c.submit("echo", {})
        c.lease("a", {"ops": ["echo"]})
        assert c.job(jid).state == "leased"
        clock.t = 11.0
        c.sweep()  # no lease() call involved
        job = c.job(jid)
        assert job.state == "pending" and job.epoch == 1

    def test_background_sweeper_thread(self):
        c = Controller(lease_ttl_sec=0.05, sweep_interval_sec=0.02)
        try:
            jid = c.submit("echo", {})
            c.lease("a", {"ops": ["echo"]})
            deadline = time.time() + 2.0
            while c.job(jid).state != "pending" and time.time() < deadline:
                time.sleep(0.02)
            assert c.job(jid).state == "pending"
        finally:
            c.close()

    def test_close_is_idempotent(self):
        c = Controller(sweep_interval_sec=0.02)
        c.close()
        c.close()


class TestJournalResume:
    def _drain_some(self, c, n):
        done = []
        for _ in range(n):
            lease = c.lease("a1", {"ops": ["read_csv_shard"]})
            task = lease["tasks"][0]
            c.report(
                lease["lease_id"], task["id"], task["job_epoch"],
                "succeeded", {"ok": True, "rows": [task["payload"]["start_row"]]},
            )
            done.append(task["id"])
        return done

    def test_restart_resumes_half_drained_job(self, tmp_path):
        journal = str(tmp_path / "controller.jsonl")
        c1 = Controller(journal_path=journal)
        shard_ids, reduce_id = c1.submit_csv_job(
            "d.csv", total_rows=400, shard_size=100,
            reduce_op="risk_accumulate", collect_partials=True,
        )
        done = self._drain_some(c1, 2)
        # A third shard is in flight (leased, unreported) at crash time.
        inflight = c1.lease("a1", {"ops": ["read_csv_shard"]})
        inflight_task = inflight["tasks"][0]
        c1.close()  # "kill" — no further writes

        c2 = Controller(journal_path=journal)
        counts = c2.counts()
        assert counts == {"succeeded": 2, "pending": 3}
        for jid in done:
            snap = c2.job_snapshot(jid)
            assert snap["state"] == "succeeded"
            assert snap["result"]["ok"] is True

        # The previous incarnation's in-flight agent redelivers its
        # completed result across the restart: ACCEPTED (ISSUE 3 — replay
        # no longer blanket-bumps epochs, so spooled work is salvaged
        # instead of re-executed).
        out = c2.report(
            inflight["lease_id"], inflight_task["id"],
            inflight_task["job_epoch"], "succeeded",
            {"ok": True, "rows": [inflight_task["payload"]["start_row"]]},
        )
        assert out["accepted"] is True
        # Done without a single post-restart lease (attempts only journal
        # inside result events, so the replayed count restarts at 0).
        snap = c2.job_snapshot(inflight_task["id"])
        assert snap["state"] == "succeeded" and snap["attempts"] == 0

        # Finish the remaining shard; reduce leases with ordered partials.
        self._drain_some(c2, 1)
        lease = c2.lease("a1", {"ops": ["risk_accumulate"]})
        assert lease is not None
        partials = lease["tasks"][0]["payload"]["partials"]
        assert [p["rows"][0] for p in partials] == [0, 100, 200, 300]
        c2.close()

    def test_failed_requeue_survives_restart(self, tmp_path):
        journal = str(tmp_path / "c.jsonl")
        c1 = Controller(journal_path=journal)
        jid = c1.submit("echo", {})
        lease = c1.lease("a", {"ops": ["echo"]})
        c1.report(lease["lease_id"], jid, 0, "failed", error={"type": "X"})
        assert c1.job(jid).state == "pending"  # one retry granted
        c1.close()

        c2 = Controller(journal_path=journal)
        job = c2.job(jid)
        assert job.state == "pending" and job.attempts == 1
        # Fails again after restart → retry budget remembered across the
        # replay; a transient-class error exhausting it lands `dead`.
        lease = c2.lease("a", {"ops": ["echo"]})
        c2.report(
            lease["lease_id"], jid, lease["tasks"][0]["job_epoch"],
            "failed", error={"type": "X"},
        )
        assert c2.job(jid).state == "dead"
        c2.close()

    def test_expiry_epoch_bumps_survive_restart(self, tmp_path):
        """Expiry requeues are journaled: an agent the previous incarnation
        fenced off must stay fenced after a restart."""
        clock = FakeClock()
        journal = str(tmp_path / "c.jsonl")
        c1 = Controller(lease_ttl_sec=10.0, clock=clock, journal_path=journal)
        jid = c1.submit("echo", {})
        lease_a = c1.lease("a", {"ops": ["echo"]})     # epoch 0
        clock.t = 11.0
        c1.sweep()                                     # epoch → 1, A fenced
        lease_b = c1.lease("b", {"ops": ["echo"]})     # epoch 1
        clock.t = 22.0
        c1.sweep()                                     # epoch → 2, B fenced
        c1.lease("c", {"ops": ["echo"]})               # epoch 2, in flight
        c1.close()                                     # crash

        c2 = Controller(journal_path=journal)
        # B (fenced at epoch 1 by the old incarnation) posts late: rejected —
        # the journaled requeue fences replay verbatim.
        out = c2.report(lease_b["lease_id"], jid, 1, "succeeded", {"ok": True})
        assert out["accepted"] is False and out["reason"] == "stale epoch"
        out = c2.report(lease_a["lease_id"], jid, 0, "succeeded", {"ok": True})
        assert out["accepted"] is False
        # The job is re-leasable at C's epoch (in-flight epochs are NOT
        # bumped at replay — ISSUE 3: C's spooled result must stay
        # deliverable), still past every journaled fence.
        lease = c2.lease("d", {"ops": ["echo"]})
        assert lease["tasks"][0]["job_epoch"] == 2
        c2.close()

    def test_undepended_result_bodies_not_journaled(self, tmp_path):
        """Drain shards nobody depends on journal state only — the journal
        must not become a second copy of the drain output."""
        import json as _json

        journal = str(tmp_path / "c.jsonl")
        c1 = Controller(journal_path=journal)
        shard_ids, reduce_id = c1.submit_csv_job(
            "d.csv", total_rows=100, shard_size=50,
            reduce_op="risk_accumulate", collect_partials=True,
        )
        solo = c1.submit("echo", {})
        for _ in range(3):  # two shards + the solo echo
            lease = c1.lease("a", {"ops": ["read_csv_shard", "echo"]})
            t = lease["tasks"][0]
            c1.report(lease["lease_id"], t["id"], t["job_epoch"],
                      "succeeded", {"ok": True, "big": "x" * 100})
        c1.close()

        events = [
            _json.loads(line) for line in open(journal, encoding="utf-8")
        ]
        by_id = {e["job_id"]: e for e in events if e["ev"] == "result"}
        for sid in shard_ids:  # depended on by the reduce → kept
            assert by_id[sid]["result"]["ok"] is True
        assert by_id[solo]["result"] is None  # state survives, body dropped
        # And the replayed controller still reports the solo job done.
        c2 = Controller(journal_path=journal)
        assert c2.job_snapshot(solo)["state"] == "succeeded"
        c2.close()

    def test_after_rejects_bare_string(self):
        import pytest as _pytest

        c = Controller()
        jid = c.submit("echo", {})
        with _pytest.raises(ValueError, match="job ids"):
            c.submit("echo", {}, after=jid)

    def test_torn_final_line_ignored(self, tmp_path):
        journal = tmp_path / "c.jsonl"
        c1 = Controller(journal_path=str(journal))
        c1.submit("echo", {"x": 1}, job_id="keep")
        c1.close()
        with open(journal, "a") as f:
            f.write('{"ev": "submit", "job_id": "torn", "op"')  # crash mid-write

        c2 = Controller(journal_path=str(journal))
        assert "keep" in [t["id"] for t in c2.lease("a", {"ops": ["echo"]})["tasks"]]
        c2.close()

    def test_torn_final_line_counted_and_warned(self, tmp_path):
        """ISSUE 4 satellite: the torn-tail tolerance is no longer silent —
        controller_journal_torn_tail_total counts it (and mid-file
        corruption still lands in the *skipped* counter, not this one)."""
        journal = tmp_path / "c.jsonl"
        c1 = Controller(journal_path=str(journal))
        c1.submit("echo", {"x": 1}, job_id="keep")
        c1.close()
        with open(journal, "a") as f:
            f.write('{"ev": "submit", "job_id": "torn", "op"')

        c2 = Controller(journal_path=str(journal))
        snap = c2.metrics.snapshot()
        (torn,) = snap["controller_journal_torn_tail_total"]["series"]
        assert torn["value"] == 1
        assert not snap["controller_journal_replay_skipped_total"]["series"]
        c2.close()

        # A clean journal replays with a zero torn-tail count.
        clean = tmp_path / "clean.jsonl"
        c3 = Controller(journal_path=str(clean))
        c3.submit("echo", {}, job_id="j")
        c3.close()
        c4 = Controller(journal_path=str(clean))
        snap = c4.metrics.snapshot()
        assert not snap["controller_journal_torn_tail_total"]["series"]
        c4.close()

    def test_corrupted_midfile_lines_warned_and_counted(self, tmp_path):
        """Mid-file corruption is NOT a torn final write: replay must skip
        it loudly (warning + counter), keep every parseable line, and still
        tolerate a torn LAST line silently (ISSUE 3 satellite)."""
        journal = tmp_path / "c.jsonl"
        c1 = Controller(journal_path=str(journal))
        c1.submit("echo", {"x": 1}, job_id="first")
        c1.submit("echo", {"x": 2}, job_id="second")
        c1.close()
        lines = journal.read_text().splitlines()
        lines.insert(1, "GARBAGE not json at all")   # mid-file corruption
        lines.insert(2, '{"ev": "submit", "job_id"')  # truncated mid-file too
        lines.append('{"ev": "submit", "job_id": "torn", "op"')  # torn final
        journal.write_text("\n".join(lines))

        c2 = Controller(journal_path=str(journal))
        ids = {t["id"] for t in c2.lease(
            "a", {"ops": ["echo"]}, max_tasks=10)["tasks"]}
        assert ids == {"first", "second"}  # both parseable jobs survive
        snap = c2.metrics.snapshot()
        series = snap["controller_journal_replay_skipped_total"]["series"]
        # Both mid-file bad lines counted; the torn final line is NOT.
        assert series[0]["value"] == 2
        c2.close()

    def test_no_journal_no_files(self, tmp_path):
        c = Controller()
        c.submit("echo", {})
        c.close()
        assert list(tmp_path.iterdir()) == []
