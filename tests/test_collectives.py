"""Collectives: device psum reduction must agree with the host path."""

import math

import numpy as np
import pytest

from agent_tpu.config import DeviceConfig
from agent_tpu.parallel.collectives import _padded_len, mesh_reduce_stats
from agent_tpu.runtime import TpuRuntime


@pytest.fixture(scope="module")
def rt():
    return TpuRuntime(DeviceConfig())


def test_padded_len_buckets():
    assert _padded_len(1, 8) == 8
    assert _padded_len(8, 8) == 8
    assert _padded_len(9, 8) == 16
    assert _padded_len(100, 8) == 128


def test_mesh_reduce_matches_host(rt):
    values = [float(i) * 0.5 - 7.0 for i in range(100)]
    out = mesh_reduce_stats(rt, values)
    assert out["count"] == 100
    assert out["sum"] == pytest.approx(math.fsum(values), rel=1e-5)
    assert out["mean"] == pytest.approx(math.fsum(values) / 100, rel=1e-5)
    assert out["min"] == pytest.approx(min(values))
    assert out["max"] == pytest.approx(max(values))


def test_mesh_reduce_single_value(rt):
    out = mesh_reduce_stats(rt, [3.25])
    assert out == {"count": 1, "sum": 3.25, "mean": 3.25, "min": 3.25, "max": 3.25}


def test_mesh_reduce_subnormal_exact(rt):
    """Round-4 Hypothesis counterexample pinned: subnormal f32 inputs must
    NOT flush to zero in min/max (the reduce runs over monotone bitcast
    integer keys, immune to the device's FTZ float mode)."""
    tiny = 1.401298464324817e-45  # smallest positive f32 subnormal
    out = mesh_reduce_stats(rt, [tiny])
    assert out["min"] == tiny and out["max"] == tiny
    out = mesh_reduce_stats(rt, [-tiny, 0.0, tiny])
    assert out["min"] == -tiny and out["max"] == tiny


def test_mesh_reduce_nan_poisons_all_stats(rt):
    """A NaN input deterministically poisons sum/mean/min/max — in any
    position (Python min/max would be order-dependent; the bitcast-key
    reduce would be asymmetric). Count stays exact host knowledge."""
    for values in ([1.0, float("nan"), 5.0], [float("nan")],
                   [5.0, 1.0, float("nan")]):
        out = mesh_reduce_stats(rt, values)
        assert out["count"] == len(values)
        assert all(
            math.isnan(out[k]) for k in ("sum", "mean", "min", "max")
        ), out


def test_mesh_reduce_inf_keeps_minmax_defined(rt):
    """inf + -inf sums to NaN, but min/max stay the exact extremes — the
    NaN gate is on the inputs, not the total."""
    out = mesh_reduce_stats(rt, [float("inf"), float("-inf"), 2.0])
    assert out["min"] == float("-inf") and out["max"] == float("inf")


def test_risk_accumulate_host_nan_matches_device_semantics():
    """The host path (small payloads) canonicalizes NaN the same way, in
    any input order."""
    from agent_tpu.ops.risk_accumulate import run

    for values in ([float("nan"), 1.0], [1.0, float("nan")]):
        out = run({"values": values})
        assert out["ok"] is True and out["count"] == 2
        assert all(
            math.isnan(out[k]) for k in ("sum", "mean", "min", "max")
        ), out
    out = run({"values": [float("inf"), float("-inf")]})
    assert out["min"] == float("-inf") and out["max"] == float("inf")


def test_mesh_reduce_reuses_executable(rt):
    before = rt.cache.stats()["misses"]
    mesh_reduce_stats(rt, list(np.arange(50, dtype=np.float64)))
    mesh_reduce_stats(rt, list(np.arange(60, dtype=np.float64)))  # same 64-bucket
    after = rt.cache.stats()
    assert after["misses"] == before + 1  # one compile for the shared bucket


def test_mesh_reduce_double_single_beats_f32_cast(rt):
    """The hi/lo transport must recover precision a plain f32 cast loses:
    values whose fractional part vanishes in f32 at magnitude 2^26."""
    base = 2.0**26
    values = [base + 0.1875 * (i % 8) for i in range(1000)]
    want = math.fsum(values)
    out = mesh_reduce_stats(rt, values)
    # Plain f32 input cast would drop every fractional part (0.1875·k < ulp
    # at 2^26), erring by ~656 absolute; the split path must stay within f32
    # accumulation noise of the exact sum.
    naive_err = abs(math.fsum(float(np.float32(v)) for v in values) - want)
    assert naive_err > 100.0  # the failure mode is real at this magnitude
    assert abs(out["sum"] - want) < naive_err / 50
    assert out["sum"] == pytest.approx(want, rel=1e-7)


def test_mesh_reduce_f32_overflow_stays_inf_not_nan(rt):
    """Values beyond f32 range must surface as a detectable inf (plain-cast
    behavior), never as NaN from an inf + -inf hi/lo recombination."""
    out = mesh_reduce_stats(rt, [1e39] + [1.0] * 1023)
    assert np.isinf(out["sum"]) and out["sum"] > 0
    assert not np.isnan(out["mean"])


def test_risk_accumulate_device_path_agrees_with_host(rt):
    from agent_tpu.ops.risk_accumulate import run
    from agent_tpu.runtime import OpContext

    values = [float(i % 97) for i in range(5000)]
    host = run({"values": values})
    dev = run({"values": values}, OpContext(runtime=rt))
    assert dev["device"] == "mesh"
    assert dev["count"] == host["count"]
    assert dev["sum"] == pytest.approx(host["sum"], rel=1e-4)
    assert dev["min"] == host["min"]
    assert dev["max"] == host["max"]
