"""map_summarize: scan-decode seq2seq on the virtual mesh.

VERDICT item 7 acceptance: registry entry real, output deterministic on CPU
backend, decode does not retrace per step.
"""

import jax
import numpy as np
import pytest

from agent_tpu.models import seq2seq
from agent_tpu.models.tokenizer import pad_batch, ByteTokenizer
from agent_tpu.ops import get_op
from agent_tpu.runtime.context import OpContext
from agent_tpu.runtime.runtime import get_runtime

SMALL = {"d_model": 64, "n_heads": 4, "n_enc_layers": 2, "n_dec_layers": 2,
         "d_ff": 128, "max_src_len": 64, "max_tgt_len": 32}


@pytest.fixture(scope="module")
def summarize():
    return get_op("map_summarize")


@pytest.fixture(scope="module")
def ctx():
    return OpContext(runtime=get_runtime())


def test_contract_and_determinism(summarize, ctx):
    payload = {"text": "a long document " * 4, "model_config": SMALL,
               "max_length": 16}
    a = summarize(payload, ctx)
    b = summarize(payload, ctx)
    assert a["ok"] is True
    assert isinstance(a["summary"], str)
    assert a["model"] == "summarize-default"
    assert a["device"] in ("cpu", "tpu", "gpu")
    assert a["summary"] == b["summary"]


def test_batched(summarize, ctx):
    out = summarize(
        {"texts": ["first doc", "second doc", "third doc"],
         "model_config": SMALL, "max_length": 8},
        ctx,
    )
    assert out["ok"] is True
    assert len(out["summaries"]) == 3
    assert out["summary"] == out["summaries"][0]


def test_bad_inputs(summarize, ctx):
    assert summarize({}, ctx)["ok"] is False
    assert summarize({"text": ""}, ctx)["ok"] is False
    assert summarize({"texts": []}, ctx)["ok"] is False
    assert summarize({"text": "x", "max_length": 0}, ctx)["ok"] is False


def test_decode_single_trace():
    """The whole generate (encode + N decode steps) is ONE traced program:
    tracing the model function runs it exactly once regardless of step count."""
    cfg = seq2seq.Seq2SeqConfig(**SMALL)
    params = seq2seq.init_params(cfg, "trace-test")
    ids, mask = pad_batch([[1, 5, 6, 7, 2]])
    traces = {"n": 0}

    def fn(p, i, m):
        traces["n"] += 1
        return seq2seq.greedy_generate(p, i, m, cfg, 16)

    jitted = jax.jit(fn)
    toks, _ = jitted(params, ids, mask)
    toks2, _ = jitted(params, ids, mask)
    assert traces["n"] == 1  # one trace for 16 decode steps, and no retrace
    assert toks.shape == (1, 16)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks2))


def test_incremental_decode_matches_full_attention():
    """KV-cache decode must equal TRUE full-sequence decoder attention: the
    reference below reruns the whole prefix through the decoder blocks with a
    causal mask and NO cache, so a cache-update bug (e.g. a wrong
    dynamic_update_slice index) cannot cancel out between the two sides."""
    import jax.numpy as jnp

    from agent_tpu.models import layers

    cfg = seq2seq.Seq2SeqConfig(**SMALL, dtype="float32")
    params = seq2seq.init_params(cfg, "equiv-test")
    tok = ByteTokenizer()
    src = tok.encode("check equivalence", add_bos=True, add_eos=True)
    ids, mask = pad_batch([src])
    T = 8
    toks, _ = jax.jit(
        lambda p, i, m: seq2seq.greedy_generate(p, i, m, cfg, T)
    )(params, ids, mask)
    toks = np.asarray(toks)[0]

    def full_prefix_logits(prefix_ids):
        """Decoder over the whole prefix, full causal attention, cache-free."""
        dtype = cfg.compute_dtype
        L = prefix_ids.shape[1]
        x = params["embed"].astype(dtype)[prefix_ids] + \
            params["pos"][:L].astype(dtype)[None]
        causal = jnp.asarray(layers.causal_mask(L))                  # [1,1,L,L]
        enc_attn = jnp.asarray(mask)[:, None, None, :]
        enc_out = seq2seq.encode(params, jnp.asarray(ids), jnp.asarray(mask), cfg)
        for block in params["dec"]:
            x, _ = layers.decoder_block(block, x, causal, enc_out, enc_attn, dtype)
        x = layers.layer_norm(params["ln_dec"], x)
        logits = jnp.dot(x.astype(dtype), params["embed"].astype(dtype).T)
        return np.asarray(logits.astype(jnp.float32))                # [1,L,V]

    prefix = [1]  # BOS
    for t in range(T):
        logits = full_prefix_logits(jnp.asarray([prefix], dtype=jnp.int32))
        nxt = int(np.argmax(logits[0, -1]))
        if toks[t] == 0:  # post-EOS padding
            break
        assert nxt == toks[t], f"step {t}: full-attn {nxt} != cached {toks[t]}"
        prefix.append(nxt)
