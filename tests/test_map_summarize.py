"""map_summarize: scan-decode seq2seq on the virtual mesh.

VERDICT item 7 acceptance: registry entry real, output deterministic on CPU
backend, decode does not retrace per step.
"""

import jax
import numpy as np
import pytest

from agent_tpu.models import seq2seq
from agent_tpu.models.tokenizer import pad_batch, ByteTokenizer
from agent_tpu.ops import get_op
from agent_tpu.runtime.context import OpContext
from agent_tpu.runtime.runtime import get_runtime

SMALL = {"d_model": 64, "n_heads": 4, "n_enc_layers": 2, "n_dec_layers": 2,
         "d_ff": 128, "max_src_len": 64, "max_tgt_len": 32}


@pytest.fixture(scope="module")
def summarize():
    return get_op("map_summarize")


@pytest.fixture(scope="module")
def ctx():
    return OpContext(runtime=get_runtime())


def test_contract_and_determinism(summarize, ctx):
    payload = {"text": "a long document " * 4, "model_config": SMALL,
               "max_length": 16}
    a = summarize(payload, ctx)
    b = summarize(payload, ctx)
    assert a["ok"] is True
    assert isinstance(a["summary"], str)
    assert a["model"] == "summarize-default"
    assert a["device"] in ("cpu", "tpu", "gpu")
    assert a["summary"] == b["summary"]


def test_batched(summarize, ctx):
    out = summarize(
        {"texts": ["first doc", "second doc", "third doc"],
         "model_config": SMALL, "max_length": 8},
        ctx,
    )
    assert out["ok"] is True
    assert len(out["summaries"]) == 3
    assert out["summary"] == out["summaries"][0]


def test_bad_inputs(summarize, ctx):
    assert summarize({}, ctx)["ok"] is False
    assert summarize({"text": ""}, ctx)["ok"] is False
    assert summarize({"texts": []}, ctx)["ok"] is False
    assert summarize({"text": "x", "max_length": 0}, ctx)["ok"] is False


def test_decode_single_trace():
    """The whole generate (encode + N decode steps) is ONE traced program:
    tracing the model function runs it exactly once regardless of step count."""
    cfg = seq2seq.Seq2SeqConfig(**SMALL)
    params = seq2seq.init_params(cfg, "trace-test")
    ids, mask = pad_batch([[1, 5, 6, 7, 2]])
    traces = {"n": 0}

    def fn(p, i, m):
        traces["n"] += 1
        return seq2seq.greedy_generate(p, i, m, cfg, 16)

    jitted = jax.jit(fn)
    toks, _ = jitted(params, ids, mask)
    toks2, _ = jitted(params, ids, mask)
    assert traces["n"] == 1  # one trace for 16 decode steps, and no retrace
    assert toks.shape == (1, 16)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks2))


def test_incremental_decode_matches_full_attention():
    """KV-cache decode must equal TRUE full-sequence decoder attention: the
    reference below reruns the whole prefix through the decoder blocks with a
    causal mask and NO cache, so a cache-update bug (e.g. a wrong
    dynamic_update_slice index) cannot cancel out between the two sides."""
    import jax.numpy as jnp

    from agent_tpu.models import layers

    cfg = seq2seq.Seq2SeqConfig(**SMALL, dtype="float32")
    params = seq2seq.init_params(cfg, "equiv-test")
    tok = ByteTokenizer()
    src = tok.encode("check equivalence", add_bos=True, add_eos=True)
    ids, mask = pad_batch([src])
    T = 8
    toks, _ = jax.jit(
        lambda p, i, m: seq2seq.greedy_generate(p, i, m, cfg, T)
    )(params, ids, mask)
    toks = np.asarray(toks)[0]

    def full_prefix_logits(prefix_ids):
        """Decoder over the whole prefix, full causal attention, cache-free."""
        dtype = cfg.compute_dtype
        L = prefix_ids.shape[1]
        x = params["embed"].astype(dtype)[prefix_ids] + \
            params["pos"][:L].astype(dtype)[None]
        causal = jnp.asarray(layers.causal_mask(L))                  # [1,1,L,L]
        enc_attn = jnp.asarray(mask)[:, None, None, :]
        enc_out = seq2seq.encode(params, jnp.asarray(ids), jnp.asarray(mask), cfg)
        for block in params["dec"]:
            x, _ = layers.decoder_block(block, x, causal, enc_out, enc_attn, dtype)
        x = layers.layer_norm(params["ln_dec"], x)
        logits = jnp.dot(x.astype(dtype), params["embed"].astype(dtype).T)
        return np.asarray(logits.astype(jnp.float32))                # [1,L,V]

    prefix = [1]  # BOS
    for t in range(T):
        logits = full_prefix_logits(jnp.asarray([prefix], dtype=jnp.int32))
        nxt = int(np.argmax(logits[0, -1]))
        if toks[t] == 0:  # post-EOS padding
            break
        assert nxt == toks[t], f"step {t}: full-attn {nxt} != cached {toks[t]}"
        prefix.append(nxt)


class TestBeamSearch:
    CFG_KW = dict(
        vocab_size=64, d_model=32, n_heads=4, n_enc_layers=2, n_dec_layers=2,
        d_ff=64, max_src_len=16, max_tgt_len=8, dtype="float32",
    )

    def _setup(self):
        import numpy as np
        import jax.numpy as jnp

        from agent_tpu.models import seq2seq

        cfg = seq2seq.Seq2SeqConfig(**self.CFG_KW)
        params = seq2seq.init_params(cfg, model_id="beam-test")
        rng = np.random.default_rng(7)
        src = jnp.asarray(rng.integers(4, 64, size=(3, 16)), dtype=jnp.int32)
        mask = jnp.ones((3, 16), dtype=jnp.int32)
        return seq2seq, cfg, params, src, mask

    def test_beam1_equals_greedy(self):
        import numpy as np

        seq2seq, cfg, params, src, mask = self._setup()
        g_toks, g_len = seq2seq.greedy_generate(params, src, mask, cfg, 8)
        b_toks, b_len = seq2seq.beam_generate(
            params, src, mask, cfg, 8, num_beams=1
        )
        np.testing.assert_array_equal(np.asarray(g_toks), np.asarray(b_toks))
        np.testing.assert_array_equal(np.asarray(g_len), np.asarray(b_len))

    def test_beam4_runs_and_is_deterministic(self):
        import numpy as np

        seq2seq, cfg, params, src, mask = self._setup()
        t1, l1 = seq2seq.beam_generate(params, src, mask, cfg, 8, num_beams=4)
        t2, l2 = seq2seq.beam_generate(params, src, mask, cfg, 8, num_beams=4)
        np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
        assert np.asarray(t1).shape == (3, 8)
        assert (np.asarray(l1) <= 8).all() and (np.asarray(l1) >= 0).all()
        # Valid token range and PAD-after-EOS structure per row.
        toks = np.asarray(t1)
        assert ((toks >= 0) & (toks < cfg.vocab_size)).all()

    def test_beam_improves_or_matches_sum_logprob(self):
        """With length_penalty=0 the chosen beam's raw sum-logprob must be at
        least greedy's (greedy's path stays in the beam at every step until
        pruned only by K strictly better prefixes)."""
        import numpy as np
        import jax
        import jax.numpy as jnp

        seq2seq, cfg, params, src, mask = self._setup()

        def score_of(toks):
            """Sum logprob of forced decode along `toks` (teacher forcing)."""
            from agent_tpu.models.tokenizer import BOS_ID, EOS_ID, PAD_ID

            B, T = toks.shape
            enc = seq2seq.encode(params, src, mask, cfg)
            caches = seq2seq._empty_cache(cfg, B)
            tok = jnp.full((B,), BOS_ID, dtype=jnp.int32)
            total = np.zeros(B, dtype=np.float64)
            alive = np.ones(B, dtype=bool)
            for t in range(T):
                logits, caches = seq2seq._decode_step(
                    params, tok, jnp.int32(t), enc, mask, caches, cfg
                )
                logp = np.asarray(jax.nn.log_softmax(logits, axis=-1))
                nxt = np.asarray(toks[:, t])
                for b in range(B):
                    if alive[b] and nxt[b] != PAD_ID:
                        total[b] += logp[b, nxt[b]]
                        if nxt[b] == EOS_ID:
                            alive[b] = False
                    elif nxt[b] == PAD_ID:
                        alive[b] = False
                tok = jnp.asarray(nxt, dtype=jnp.int32)
            return total

        g_toks, _ = seq2seq.greedy_generate(params, src, mask, cfg, 8)
        b_toks, _ = seq2seq.beam_generate(
            params, src, mask, cfg, 8, num_beams=4, length_penalty=0.0
        )
        gs = score_of(np.asarray(g_toks))
        bs = score_of(np.asarray(b_toks))
        assert (bs >= gs - 1e-4).all(), (bs, gs)

    def test_cache_reorder_delta_equals_gather(self):
        """The delta (lax.cond identity-skip) KV-cache reorder must emit
        BIT-IDENTICAL tokens to the unconditional per-step gather it
        replaced — same beam_idx, the only difference is whether identity
        permutations move cache bytes. Run across length penalties so both
        early-banking and run-to-the-end hypotheses are covered."""
        import numpy as np
        import jax.numpy as jnp

        from agent_tpu.models.decoding import beam_scan
        from agent_tpu.models.tokenizer import BOS_ID, EOS_ID, PAD_ID

        seq2seq, cfg, params, src, mask = self._setup()
        B, K, T = src.shape[0], 4, 8
        enc_out = seq2seq.encode(params, src, mask, cfg)
        enc_out = jnp.repeat(enc_out, K, axis=0)
        enc_mask = jnp.repeat(mask, K, axis=0)

        def step_fn(tok, step, caches):
            return seq2seq._decode_step(
                params, tok, step, enc_out, enc_mask, caches, cfg
            )

        for lp in (0.0, 1.0, 2.0):
            outs = {}
            for scheme in ("gather", "delta"):
                toks, lens = beam_scan(
                    step_fn, seq2seq._empty_cache(cfg, B * K), B,
                    cfg.vocab_size, T, num_beams=K,
                    start_id=BOS_ID, eos_id=EOS_ID, pad_id=PAD_ID,
                    length_penalty=lp, cache_reorder=scheme,
                )
                outs[scheme] = (np.asarray(toks), np.asarray(lens))
            np.testing.assert_array_equal(
                outs["delta"][0], outs["gather"][0],
                err_msg=f"token mismatch at length_penalty={lp}",
            )
            np.testing.assert_array_equal(outs["delta"][1], outs["gather"][1])

    def test_cache_reorder_rejects_unknown_scheme(self):
        import pytest

        from agent_tpu.models.decoding import beam_scan

        with pytest.raises(ValueError, match="cache_reorder"):
            beam_scan(
                lambda t, s, c: (None, c), None, 1, 8, 4,
                num_beams=2, start_id=1, eos_id=2,
                cache_reorder="sometimes",
            )

    def test_op_accepts_num_beams(self):
        from agent_tpu.ops import get_op

        summarize = get_op("map_summarize")
        payload = {
            "texts": ["beam search document " * 5] * 2,
            "max_length": 6,
            "num_beams": 4,
            "model_config": self.CFG_KW,
        }
        out = summarize(payload)
        assert out["ok"] is True and out["num_beams"] == 4
        assert len(out["summaries"]) == 2
        bad = summarize({**payload, "num_beams": 0})
        assert bad["ok"] is False


def test_summarize_from_csv_shard(tmp_csv):
    """source_uri shard addressing — the summarize half of the drain story."""
    import pytest as _pytest

    from agent_tpu.ops import get_op

    summarize = get_op("map_summarize")
    cfg_kw = {"vocab_size": 260, "d_model": 32, "n_heads": 4,
              "n_enc_layers": 2, "n_dec_layers": 2, "d_ff": 64,
              "max_src_len": 64, "max_tgt_len": 8, "dtype": "float32"}
    out = summarize({"source_uri": tmp_csv, "start_row": 1, "shard_size": 3,
                     "text_field": "text", "max_length": 4,
                     "model_config": cfg_kw})
    assert out["ok"] is True and len(out["summaries"]) == 3
    # Shard problems raise loudly (drain semantics), same as classify.
    with _pytest.raises(RuntimeError):
        summarize({"source_uri": tmp_csv, "start_row": 10_000,
                   "model_config": cfg_kw})
    with _pytest.raises(RuntimeError):
        summarize({"source_uri": tmp_csv, "text_field": "nope",
                   "model_config": cfg_kw})


def test_op_timings_flow_through_context():
    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext

    ctx = OpContext()
    out = get_op("map_classify_tpu")({"texts": ["timing check"], "topk": 2}, ctx)
    assert out["ok"] is True
    t = ctx.tags["timings"]
    assert t["stage_ms"] >= 0 and t["device_ms"] > 0


def test_summarize_drain_blank_cells_get_empty_summaries(tmp_path):
    from agent_tpu.ops import get_op

    path = tmp_path / "blanks.csv"
    path.write_text('id,text\n0,"real document text"\n1,""\n2,"another doc"\n')
    out = get_op("map_summarize")({
        "source_uri": str(path), "shard_size": 3, "max_length": 4,
        "model_config": {"vocab_size": 260, "d_model": 32, "n_heads": 4,
                         "n_enc_layers": 2, "n_dec_layers": 2, "d_ff": 64,
                         "max_src_len": 64, "max_tgt_len": 8,
                         "dtype": "float32"},
    })
    assert out["ok"] is True
    assert out["summaries"][1] == ""          # blank cell → empty summary


def test_greedy_early_exit_equals_scan_path():
    """The while_loop early-exit decode must emit EXACTLY the fixed-trip
    scan's tokens — including rows that hit EOS at different steps and the
    pad tail after the early stop."""
    import jax.numpy as jnp
    import numpy as np

    from agent_tpu.models import decoding

    B, V, T = 4, 11, 12
    eos = 9

    # Scripted logits: row b emits token (step + b) % 7 + 1 until its EOS
    # step (2 + 2*b), then would emit garbage — EOS bookkeeping must pad.
    def step_fn(tok, step, caches):
        logits = jnp.full((B, V), -1e9, dtype=jnp.float32)
        for b in range(B):
            want = jnp.where(step == 2 + 2 * b, eos, (step + b) % 7 + 1)
            logits = logits.at[b, :].set(
                jnp.where(jnp.arange(V) == want, 0.0, -1e9)
            )
        return logits, caches

    kw = dict(batch=B, max_new_tokens=T, start_id=0, eos_id=eos, pad_id=0)
    toks_w, lens_w = decoding.greedy_scan(step_fn, None, early_exit=True, **kw)
    toks_s, lens_s = decoding.greedy_scan(step_fn, None, early_exit=False, **kw)
    np.testing.assert_array_equal(np.asarray(toks_w), np.asarray(toks_s))
    np.testing.assert_array_equal(np.asarray(lens_w), np.asarray(lens_s))
    # Longest row finishes at step 2 + 2*(B-1) = 8 < T: the early-exit tail
    # must be pad, proving the buffer semantics (not just luck).
    assert np.all(np.asarray(toks_w)[:, 9:] == 0)


def test_greedy_early_exit_under_jit_with_caches():
    """Early exit must compose with jit and a threaded KV-cache pytree
    (the real decode shape: caches in the while_loop carry)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from agent_tpu.models import decoding

    B, V, T = 2, 8, 6
    eos = 7

    def step_fn(tok, step, caches):
        # Cache carries a running sum — proves the pytree threads through.
        caches = {"acc": caches["acc"] + tok.sum()}
        logits = jax.nn.one_hot(
            jnp.where(step >= 1, eos, (tok + 1) % V), V, dtype=jnp.float32
        )
        return jnp.log(logits + 1e-9), caches

    caches = {"acc": jnp.int32(0)}

    def run(early):
        return decoding.greedy_scan(
            step_fn, caches, batch=B, max_new_tokens=T,
            start_id=1, eos_id=eos, pad_id=0, early_exit=early,
        )

    toks_w, lens_w = jax.jit(lambda: run(True))()
    toks_s, lens_s = jax.jit(lambda: run(False))()
    np.testing.assert_array_equal(np.asarray(toks_w), np.asarray(toks_s))
    np.testing.assert_array_equal(np.asarray(lens_w), np.asarray(lens_s))
