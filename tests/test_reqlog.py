"""Wide-event request log (ISSUE 17): tail-based sampling (errors + the
slow decile always kept), ring bounding, deterministic hash sampling,
snapshot filters, and the dominant-component helper."""

from __future__ import annotations

from agent_tpu.obs.reqlog import (
    SLOW_MIN_SAMPLES,
    RequestLog,
    _sample_fraction,
    dominant_component,
)


def _rec(i, ttft=10.0, outcome="completed", tenant="default"):
    return {
        "req_id": f"req-{i:08d}",
        "tenant": tenant,
        "outcome": outcome,
        "ttft_ms": ttft,
    }


class TestTailSampling:
    def test_errors_always_kept_even_at_sample_zero(self):
        log = RequestLog(sample=0.0)
        # Enough healthy traffic to get past the warmup keep-everything
        # phase and establish a slow-decile threshold.
        for i in range(200):
            log.add(_rec(i, ttft=10.0 + (i % 7)))
        kept_before = log.kept
        for i in range(200, 220):
            reason = log.add(_rec(i, ttft=1.0, outcome="failed"))
            assert reason == "error"
        assert log.kept == kept_before + 20
        errors = [r for r in log.snapshot() if r["outcome"] == "failed"]
        assert len(errors) == 20

    def test_slow_decile_kept_at_sample_zero(self):
        log = RequestLog(sample=0.0)
        for i in range(300):
            # 10% of traffic is 100x slower — exactly the tail the log
            # must retain when healthy sampling is off.
            slow = i % 10 == 0
            log.add(_rec(i, ttft=1000.0 if slow else 10.0))
        recs = log.snapshot(limit=1000)
        slow_kept = [r for r in recs if r["ttft_ms"] == 1000.0]
        assert slow_kept, "slow decile entirely sampled out"
        # Past warmup, fast/healthy records only survive via sampling —
        # which is off.
        fast_kept = [
            r for r in recs
            if r["ttft_ms"] < 100.0 and r["kept"] == "sampled"
        ]
        assert not fast_kept

    def test_warmup_keeps_everything(self):
        log = RequestLog(sample=0.0)
        for i in range(SLOW_MIN_SAMPLES - 1):
            assert log.add(_rec(i)) is not None

    def test_sample_one_keeps_everything(self):
        log = RequestLog(sample=1.0)
        for i in range(100):
            assert log.add(_rec(i)) is not None
        assert log.sampled_out == 0

    def test_sampling_is_deterministic_per_req_id(self):
        assert _sample_fraction("req-abc") == _sample_fraction("req-abc")
        log1, log2 = RequestLog(sample=0.5), RequestLog(sample=0.5)
        for i in range(300):
            # Varied TTFTs: most records land below the slow decile, so
            # their fate rests on the req_id hash coin alone.
            log1.add(_rec(i, ttft=10.0 + (i % 10)))
            log2.add(_rec(i, ttft=10.0 + (i % 10)))
        ids = lambda log: [r["req_id"] for r in log.snapshot(limit=1000)]  # noqa: E731
        assert ids(log1) == ids(log2)
        assert log1.sampled_out > 0  # the coin actually flips at 0.5

    def test_keep_reason_annotated(self):
        log = RequestLog(sample=1.0)
        log.add(_rec(0))
        (rec,) = log.snapshot()
        assert rec["kept"] in ("slow", "sampled")
        assert "ts" in rec


class TestRingAndFilters:
    def test_ring_bounded(self):
        log = RequestLog(capacity=16, sample=1.0)
        for i in range(100):
            log.add(_rec(i))
        assert len(log) == 16
        newest = log.snapshot(limit=1)[0]
        assert newest["req_id"] == "req-00000099"  # newest-first

    def test_filters(self):
        log = RequestLog(sample=1.0)
        log.add(_rec(0, tenant="acme"))
        log.add(_rec(1, tenant="beta"))
        log.add(_rec(2, tenant="acme", outcome="failed"))
        assert {
            r["req_id"] for r in log.snapshot(tenant="acme")
        } == {"req-00000000", "req-00000002"}
        assert [
            r["req_id"] for r in log.snapshot(outcome="failed")
        ] == ["req-00000002"]
        slow_only = log.snapshot(slow=True)
        assert all(r["kept"] in ("error", "slow") for r in slow_only)
        assert len(log.snapshot(limit=2)) == 2

    def test_stats(self):
        log = RequestLog(capacity=8, sample=1.0)
        for i in range(5):
            log.add(_rec(i))
        s = log.stats()
        assert s["seen"] == 5 and s["kept"] == 5 and s["size"] == 5
        assert s["capacity"] == 8 and s["sample"] == 1.0
        assert sum(s["kept_by_reason"].values()) == 5


class TestDominantComponent:
    def test_picks_largest(self):
        assert dominant_component(
            {"bucket_wait": 1.0, "prefill": 40.0, "kv_wait": 2.0}
        ) == "prefill"

    def test_empty_and_garbage(self):
        assert dominant_component({}) is None
        assert dominant_component(None) is None
        assert dominant_component({"a": "nan?", "b": 1.0}) == "b"
