"""HF-BART family (``models/bart.py`` + ``models/bpe.py``): the imported
checkpoint must reproduce ``transformers``' logits, generation, and
tokenization, and serve through map_summarize from a local checkpoint
directory — the reference's actual summarize model served TPU-side
(reference ``ops/map_summarize.py:29-32``)."""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

import jax  # noqa: E402

from agent_tpu.models import bart  # noqa: E402
from agent_tpu.models.bpe import ByteLevelBPE, bytes_to_unicode  # noqa: E402

TINY = dict(
    d_model=32, encoder_layers=2, decoder_layers=2,
    encoder_attention_heads=4, decoder_attention_heads=4,
    encoder_ffn_dim=64, decoder_ffn_dim=64,
    max_position_embeddings=64,
    pad_token_id=1, bos_token_id=0, eos_token_id=2,
    decoder_start_token_id=2, forced_bos_token_id=0,
)

MERGES = [("h", "e"), ("l", "l"), ("ll", "o"), ("Ġ", "w"), ("Ġw", "o")]


def _build_vocab():
    base = list(bytes_to_unicode().values())
    # Specials at HF's standard ids, full byte alphabet, then the merge
    # products (one vocab entry per MERGES pair).
    toks = ["<s>", "<pad>", "</s>", "<unk>"] + base \
        + ["he", "ll", "llo", "Ġw", "Ġwo"]
    return {t: i for i, t in enumerate(toks)}


@pytest.fixture(scope="module")
def hf_dir(tmp_path_factory):
    """A real on-disk HF BART checkpoint (config.json + pytorch_model.bin +
    vocab.json + merges.txt) built offline from a seeded random model."""
    d = tmp_path_factory.mktemp("bart_ckpt")
    vocab = _build_vocab()
    (d / "vocab.json").write_text(
        __import__("json").dumps(vocab), encoding="utf-8"
    )
    (d / "merges.txt").write_text(
        "#version: 0.2\n" + "\n".join(f"{a} {b}" for a, b in MERGES) + "\n",
        encoding="utf-8",
    )
    torch.manual_seed(0)
    cfg = transformers.BartConfig(vocab_size=len(vocab), **TINY)
    model = transformers.BartForConditionalGeneration(cfg).eval()
    model.save_pretrained(str(d), safe_serialization=False)
    return str(d), model


@pytest.fixture(scope="module")
def hf_tok(hf_dir):
    path, _ = hf_dir
    return transformers.BartTokenizer(
        vocab_file=f"{path}/vocab.json", merges_file=f"{path}/merges.txt"
    )


def test_bpe_matches_transformers(hf_dir, hf_tok):
    path, _ = hf_dir
    tok = ByteLevelBPE.from_dir(path)
    for text in [
        "hello world", "he llo", "wo wo hello", "  spaced  out ",
        "punct, here! (ok)", "unicode: café ≤ λ", "hello's won't",
    ]:
        ours = tok.encode(text)
        theirs = hf_tok(text, add_special_tokens=False)["input_ids"]
        assert ours == theirs, (text, ours, theirs)
        assert tok.decode(ours) == text


def test_forward_matches_transformers(hf_dir):
    path, torch_model = hf_dir
    cfg, params = bart.load_hf_dir(path, dtype="float32")
    assert cfg.n_enc_layers == 2 and cfg.forced_bos_id == 0

    rng = np.random.default_rng(0)
    src = rng.integers(4, cfg.vocab_size, (3, 9)).astype(np.int32)
    src_mask = np.ones((3, 9), dtype=np.int32)
    src_mask[1, 6:] = 0
    src[1, 6:] = cfg.pad_id
    tgt = rng.integers(4, cfg.vocab_size, (3, 5)).astype(np.int32)
    tgt[:, 0] = cfg.decoder_start_id

    with torch.no_grad():
        want = torch_model(
            input_ids=torch.tensor(src, dtype=torch.long),
            attention_mask=torch.tensor(src_mask, dtype=torch.long),
            decoder_input_ids=torch.tensor(tgt, dtype=torch.long),
        ).logits.numpy()
    enc = bart.encode(params, src, src_mask, cfg)
    got = np.asarray(
        jax.jit(
            lambda p, t, e, m: bart.decode_full(p, t, e, m, cfg)
        )(params, tgt, enc, src_mask)
    )
    np.testing.assert_allclose(got, want, atol=3e-4)


def test_greedy_generation_matches_transformers(hf_dir):
    path, torch_model = hf_dir
    cfg, params = bart.load_hf_dir(path, dtype="float32")
    rng = np.random.default_rng(1)
    src = rng.integers(4, cfg.vocab_size, (2, 7)).astype(np.int32)
    mask = np.ones((2, 7), dtype=np.int32)
    T = 8

    with torch.no_grad():
        want = torch_model.generate(
            input_ids=torch.tensor(src, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
            max_new_tokens=T, num_beams=1, do_sample=False, min_length=0,
        ).numpy()
    toks, _ = jax.jit(
        lambda p, i, m: bart.generate(p, i, m, cfg, T)
    )(params, src, mask)
    toks = np.asarray(toks)
    # HF output row = [decoder_start, generated...]; ours is the generated
    # part. Compare up to HF's produced length (HF may stop early at EOS and
    # pad; both pad with cfg.pad_id so full-row comparison holds).
    want_gen = want[:, 1:]
    n = min(want_gen.shape[1], T)
    np.testing.assert_array_equal(toks[:, :n], want_gen[:, :n])


def test_cached_decode_equals_full_forward(hf_dir):
    """The KV-cached step must produce the same logits path as the
    teacher-forced full decoder (greedy tokens re-fed through decode_full
    argmax-match at every step)."""
    path, _ = hf_dir
    cfg, params = bart.load_hf_dir(path, dtype="float32")
    rng = np.random.default_rng(2)
    src = rng.integers(4, cfg.vocab_size, (2, 6)).astype(np.int32)
    mask = np.ones((2, 6), dtype=np.int32)
    T = 6
    toks, _ = bart.generate(params, src, mask, cfg, T)
    toks = np.asarray(toks)
    # Re-run teacher-forced with the generated prefix.
    dec_in = np.concatenate(
        [np.full((2, 1), cfg.decoder_start_id, dtype=np.int32), toks[:, :-1]],
        axis=1,
    )
    enc = bart.encode(params, src, mask, cfg)
    logits = np.asarray(bart.decode_full(params, dec_in, enc, mask, cfg))
    # Wherever the row wasn't finished, the full-forward argmax must equal
    # the emitted token (step 0 is the forced BOS, so start at 1).
    for b in range(2):
        for t in range(1, T):
            if toks[b, t] in (cfg.pad_id, cfg.eos_id):
                break
            assert logits[b, t].argmax() == toks[b, t], (b, t)


def test_serves_through_summarize_op(hf_dir, hf_tok):
    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext
    from agent_tpu.runtime.runtime import get_runtime

    path, torch_model = hf_dir
    summarize = get_op("map_summarize")
    ctx = OpContext(runtime=get_runtime())
    text = "hello world wo hello"
    out = summarize(
        {
            "texts": [text, "he llo wo"],
            "max_length": 6,
            "model_path": path,
            "model_config": {"dtype": "float32"},
        },
        ctx,
    )
    assert out["ok"] is True and out["model"] == path
    assert len(out["summaries"]) == 2

    # Cross-check row 0 against torch at the SAME padded shape the op's
    # 16-bucket produced: this untrained random model has near-tied logits,
    # so an argmax comparison is only meaningful when both sides see
    # identical padding (a trained checkpoint's logits are decisive; the
    # unpadded-vs-HF parity is covered by the direct generation test).
    enc = hf_tok(
        text, return_tensors="pt", padding="max_length", max_length=16
    )
    with torch.no_grad():
        want_ids = torch_model.generate(
            **enc, max_new_tokens=6, num_beams=1, do_sample=False,
            min_length=0,
        )[0]
    want = hf_tok.decode(want_ids, skip_special_tokens=True).strip()
    assert out["summaries"][0] == want


def test_non_bart_checkpoint_dir_fails_loudly(tmp_path):
    """A checkpoint dir of the wrong family must FAIL, not silently serve
    seeded random weights with ok=true."""
    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext
    from agent_tpu.runtime.runtime import get_runtime

    d = tmp_path / "bert_dir"
    d.mkdir()
    (d / "config.json").write_text('{"model_type": "bert", "vocab_size": 8}')
    with pytest.raises(RuntimeError, match="not a BART"):
        get_op("map_summarize")(
            {"texts": ["row text"], "model_path": str(d), "max_length": 4},
            OpContext(runtime=get_runtime()),
        )


def test_beam_runs_and_returns_shapes(hf_dir):
    path, _ = hf_dir
    cfg, params = bart.load_hf_dir(path, dtype="float32")
    src = np.full((2, 5), 10, dtype=np.int32)
    mask = np.ones((2, 5), dtype=np.int32)
    toks, lengths = bart.generate(params, src, mask, cfg, 5, num_beams=3)
    assert np.asarray(toks).shape == (2, 5)
    assert np.asarray(lengths).shape == (2,)


def test_unsupported_activation_function_fails_loudly(tmp_path):
    """_ffn hardcodes exact GELU; any other activation_function must raise
    rather than mis-serve (advisor r3, low)."""
    import json

    cfg = dict(
        model_type="bart", vocab_size=32, d_model=8,
        encoder_attention_heads=2, encoder_layers=1, decoder_layers=1,
        encoder_ffn_dim=16, max_position_embeddings=64,
        activation_function="relu",
    )
    p = tmp_path / "config.json"
    p.write_text(json.dumps(cfg))
    with pytest.raises(RuntimeError, match="activation_function"):
        bart.BartConfig.from_hf_json(str(p))
    cfg["activation_function"] = "gelu"
    p.write_text(json.dumps(cfg))
    assert bart.BartConfig.from_hf_json(str(p)).d_model == 8


def test_beam4_generation_matches_transformers(hf_dir):
    """Beam search must be token-exact vs transformers' BeamSearchScorer —
    the reference's actual decode mode was num_beams=4 (reference
    ops/map_summarize.py:57). Covers the EOS-banking semantics (an early
    EOS hypothesis must win over longer continuations when its normalized
    score is best) and HF's length convention, across length penalties and
    padded rows."""
    path, torch_model = hf_dir
    cfg, params = bart.load_hf_dir(path, dtype="float32")
    rng = np.random.default_rng(11)
    src = rng.integers(4, cfg.vocab_size, (4, 9)).astype(np.int32)
    mask = np.ones((4, 9), dtype=np.int32)
    mask[1, 6:] = 0
    mask[3, 4:] = 0
    for lp, T in ((1.0, 8), (2.0, 6), (0.5, 8)):
        with torch.no_grad():
            want = torch_model.generate(
                input_ids=torch.tensor(src, dtype=torch.long),
                attention_mask=torch.tensor(mask, dtype=torch.long),
                max_new_tokens=T, num_beams=4, do_sample=False,
                min_length=0, length_penalty=lp, early_stopping=False,
            ).numpy()[:, 1:]
        toks, _ = jax.jit(
            lambda p, i, m, T=T, lp=lp: bart.generate(
                p, i, m, cfg, T, num_beams=4, length_penalty=lp
            )
        )(params, src, mask)
        toks = np.asarray(toks)
        n = min(want.shape[1], T)
        np.testing.assert_array_equal(toks[:, :n], want[:, :n])


def test_beam_matches_transformers_without_forced_eos(tmp_path):
    """The no-forced-EOS path (T5-style endings) exercises the finalize
    normalization: rows that run to max_new_tokens bank their running
    beams at generated length T, competing against earlier banked EOS
    hypotheses — the case a forced-EOS final step can never reach. Also
    covers a negative length_penalty (empty-slot sentinel must stay below
    every real hypothesis)."""
    cfg_hf = transformers.BartConfig(
        vocab_size=64, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, max_position_embeddings=64,
        pad_token_id=1, bos_token_id=0, eos_token_id=2,
        decoder_start_token_id=2, forced_bos_token_id=None,
        forced_eos_token_id=None,
    )
    torch.manual_seed(398)
    model = transformers.BartForConditionalGeneration(cfg_hf).eval()
    d = str(tmp_path / "noforce")
    model.save_pretrained(d, safe_serialization=False)
    cfg, params = bart.load_hf_dir(d, dtype="float32")
    rng = np.random.default_rng(103)
    src = rng.integers(4, 64, (4, 9)).astype(np.int32)
    mask = np.ones((4, 9), dtype=np.int32)
    mask[2, 5:] = 0
    for lp, T in ((1.0, 10), (-1.0, 6)):
        with torch.no_grad():
            want = model.generate(
                input_ids=torch.tensor(src, dtype=torch.long),
                attention_mask=torch.tensor(mask, dtype=torch.long),
                max_new_tokens=T, num_beams=4, do_sample=False,
                min_length=0, length_penalty=lp, early_stopping=False,
            ).numpy()[:, 1:]
        toks, _ = jax.jit(
            lambda p, i, m, T=T, lp=lp: bart.generate(
                p, i, m, cfg, T, num_beams=4, length_penalty=lp
            )
        )(params, src, mask)
        toks = np.asarray(toks)
        n = min(want.shape[1], T)
        np.testing.assert_array_equal(toks[:, :n], want[:, :n])


def test_beam_early_stopping_matches_transformers(tmp_path):
    """``early_stopping=True`` (bart-large-cnn's actual setting) follows
    HF: a row closes as soon as K hypotheses are banked, regardless of
    whether running beams could still improve. Token-exact vs
    transformers on these seeds; note random tiny models can fork on
    ~1e-6 cross-framework logit noise near repeated-token ties (logits
    agree to 5e-7; trained models have decisive gaps), so seeds here are
    ones whose distributions are decisive."""
    cfg_hf = transformers.BartConfig(
        vocab_size=64, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, max_position_embeddings=64,
        pad_token_id=1, bos_token_id=0, eos_token_id=2,
        decoder_start_token_id=2, forced_bos_token_id=None,
        forced_eos_token_id=None,
    )
    torch.manual_seed(3 * 77 + 3)
    model = transformers.BartForConditionalGeneration(cfg_hf).eval()
    d = str(tmp_path / "es")
    model.save_pretrained(d, safe_serialization=False)
    cfg, params = bart.load_hf_dir(d, dtype="float32")
    rng = np.random.default_rng(53)
    src = rng.integers(4, 64, (4, 9)).astype(np.int32)
    mask = np.ones((4, 9), dtype=np.int32)
    mask[0, 7:] = 0
    for lp, T in ((1.0, 10), (2.0, 8)):
        with torch.no_grad():
            want = model.generate(
                input_ids=torch.tensor(src, dtype=torch.long),
                attention_mask=torch.tensor(mask, dtype=torch.long),
                max_new_tokens=T, num_beams=4, do_sample=False,
                min_length=0, length_penalty=lp, early_stopping=True,
            ).numpy()[:, 1:]
        toks, _ = jax.jit(
            lambda p, i, m, T=T, lp=lp: bart.generate(
                p, i, m, cfg, T, num_beams=4, length_penalty=lp,
                early_stopping=True)
        )(params, src, mask)
        toks = np.asarray(toks)
        n = min(want.shape[1], T)
        np.testing.assert_array_equal(toks[:, :n], want[:, :n])


def test_min_length_matches_transformers(tmp_path):
    """``min_length`` (HF counting — decoder start + generated tokens)
    bans EOS until the bound is reached, in greedy AND beam; bart-large-cnn
    generated with min_length=56. Token-exact vs transformers'
    MinLengthLogitsProcessor."""
    cfg_hf = transformers.BartConfig(
        vocab_size=64, d_model=32, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=64, decoder_ffn_dim=64, max_position_embeddings=64,
        pad_token_id=1, bos_token_id=0, eos_token_id=2,
        decoder_start_token_id=2, forced_bos_token_id=None,
        forced_eos_token_id=None,
    )
    torch.manual_seed(17)
    model = transformers.BartForConditionalGeneration(cfg_hf).eval()
    d = str(tmp_path / "minlen")
    model.save_pretrained(d, safe_serialization=False)
    cfg, params = bart.load_hf_dir(d, dtype="float32")
    rng = np.random.default_rng(900)
    src = rng.integers(4, 64, (3, 8)).astype(np.int32)
    mask = np.ones((3, 8), dtype=np.int32)
    for beams, ml, T in ((1, 6, 10), (4, 6, 10), (4, 9, 12)):
        with torch.no_grad():
            want = model.generate(
                input_ids=torch.tensor(src, dtype=torch.long),
                attention_mask=torch.tensor(mask, dtype=torch.long),
                max_new_tokens=T, num_beams=beams, do_sample=False,
                min_length=ml, length_penalty=1.0, early_stopping=False,
            ).numpy()[:, 1:]
        toks, _ = jax.jit(
            lambda p, i, m, T=T, b=beams, ml=ml: bart.generate(
                p, i, m, cfg, T, num_beams=b, min_length=ml
            )
        )(params, src, mask)
        toks = np.asarray(toks)
        n = min(want.shape[1], T)
        np.testing.assert_array_equal(toks[:, :n], want[:, :n])
