"""Result-sink mode (``output_uri``): full per-row results go to JSONL on
disk, the wire carries a receipt — the at-scale drain pattern that keeps a
10M-row job's payloads out of controller memory."""

import json
import os

import pytest

from agent_tpu.ops import get_op
from agent_tpu.runtime.context import OpContext
from agent_tpu.runtime.runtime import get_runtime


@pytest.fixture(scope="module")
def ctx():
    return OpContext(runtime=get_runtime())


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_classify_sink_receipt_and_rows(ctx, tmp_path):
    classify = get_op("map_classify_tpu")
    payload = {
        "texts": [f"sink row {i}" for i in range(5)],
        "topk": 3,
        "allow_fallback": False,
    }
    full = classify(dict(payload), ctx)
    out = classify(dict(payload, output_uri=str(tmp_path)), ctx)
    assert out["ok"] is True
    assert out["rows_written"] == 5
    # Receipt, not payload: none of the heavy row fields on the wire.
    assert "topk" not in out and "results" not in out
    assert "indices" not in out and "scores" not in out
    rows = _read_jsonl(out["output_path"])
    assert len(rows) == 5
    # File content matches the wire-format results row for row.
    for row, wire in zip(rows, full["results"]):
        assert row["indices"] == [e["index"] for e in wire["topk"]]
        got = [round(e["score"], 6) for e in wire["topk"]]
        assert row["scores"] == pytest.approx(got, abs=1e-6)


def test_classify_sink_names_by_start_row(ctx, tmp_path):
    classify = get_op("map_classify_tpu")
    out = classify(
        {"texts": ["a", "b"], "output_uri": str(tmp_path),
         "start_row": 8192, "allow_fallback": False},
        ctx,
    )
    assert out["output_path"].endswith("map_classify_tpu_rows_000000008192.jsonl")


def test_classify_sink_retry_is_idempotent(ctx, tmp_path):
    classify = get_op("map_classify_tpu")
    payload = {"texts": ["same shard"], "output_uri": str(tmp_path),
               "allow_fallback": False}
    first = classify(dict(payload), ctx)
    second = classify(dict(payload), ctx)  # controller retry of the shard
    assert first["output_path"] == second["output_path"]
    assert _read_jsonl(first["output_path"]) == _read_jsonl(second["output_path"])
    assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]


def test_classify_sink_bad_uri_soft_error(ctx, tmp_path):
    classify = get_op("map_classify_tpu")
    a_file = tmp_path / "not_a_dir"
    a_file.write_text("x")
    out = classify(
        {"texts": ["row"], "output_uri": str(a_file)}, ctx
    )
    assert out["ok"] is False
    assert "output_uri" in out["error"]


@pytest.mark.parametrize("bad", ["abc", -1, 2.5, True])
def test_bad_start_row_is_soft_error(ctx, tmp_path, bad):
    """Malformed start_row must be a soft {ok: false} (sink files are named
    by it), not a raised exception the controller would retry forever."""
    classify = get_op("map_classify_tpu")
    out = classify(
        {"texts": ["row"], "output_uri": str(tmp_path), "start_row": bad}, ctx
    )
    assert out["ok"] is False and "start_row" in out["error"]
    summarize = get_op("map_summarize")
    out = summarize(
        {"texts": ["row to sum"], "output_uri": str(tmp_path),
         "start_row": bad, "max_length": 4},
        ctx,
    )
    assert out["ok"] is False and "start_row" in out["error"]


def test_summarize_sink_receipt_and_rows(ctx, tmp_path):
    summarize = get_op("map_summarize")
    payload = {"texts": ["summarize this " * 4, "and this " * 4],
               "max_length": 8}
    full = summarize(dict(payload), ctx)
    out = summarize(dict(payload, output_uri=str(tmp_path)), ctx)
    assert out["ok"] is True
    assert out["rows_written"] == 2
    assert "summaries" not in out and "summary" not in out
    rows = _read_jsonl(out["output_path"])
    assert [r["summary"] for r in rows] == full["summaries"]
