"""Pipelined drain (VERDICT r2 item 2, BASELINE.json "host-side double
buffering"): staging and posting overlap device compute; results match the
serial loop exactly; device touches stay on the owning thread."""

import threading
import time

import jax
import pytest
import requests

from agent_tpu.agent.app import Agent
from agent_tpu.config import AgentConfig, Config, DeviceConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.server import ControllerServer
from agent_tpu.runtime.runtime import TpuRuntime

TINY = {
    "d_model": 32, "n_heads": 4, "n_layers": 1, "d_ff": 64,
    "max_len": 64, "dtype": "float32", "n_classes": 16,
}


@pytest.fixture(scope="module")
def runtime():
    return TpuRuntime(
        config=DeviceConfig(tpu_disabled=True, mesh_shape={"dp": 8}),
        devices=jax.devices("cpu"),
    )


def _csv(tmp_path, n=64):
    path = tmp_path / "rows.csv"
    lines = ["id,text"]
    for i in range(n):
        lines.append(f'{i},"pipelined drain row {i} with text"')
    path.write_text("\n".join(lines) + "\n", encoding="utf-8")
    return str(path)


def _drain_pipelined(controller, server, runtime, tasks=("map_classify_tpu",),
                     depth=2):
    """Run the pipelined agent until the controller drains, then stop it."""
    cfg = Config(
        agent=AgentConfig(
            controller_url=server.url, agent_name="pipe",
            tasks=tasks, idle_sleep_sec=0.0, pipeline_depth=depth,
        )
    )
    agent = Agent(config=cfg, session=requests.Session(), runtime=runtime)
    agent._profile = {"tier": "test"}

    def watch():
        deadline = time.time() + 120
        while not controller.drained() and time.time() < deadline:
            time.sleep(0.02)
        agent.shutdown()

    watcher = threading.Thread(target=watch, daemon=True)
    watcher.start()
    agent.run()  # picks the pipelined path (depth > 0, single host)
    watcher.join(timeout=5)
    return agent


def test_pipelined_results_match_serial(runtime, tmp_path):
    csv = _csv(tmp_path)
    extra = {"text_field": "text", "allow_fallback": False,
             "result_format": "columnar", "model_config": dict(TINY),
             "topk": 3}

    serial = Controller()
    serial.submit_csv_job(csv, total_rows=64, shard_size=16,
                          map_op="map_classify_tpu", extra_payload=extra)
    with ControllerServer(serial) as server:
        cfg = Config(agent=AgentConfig(
            controller_url=server.url, agent_name="serial",
            tasks=("map_classify_tpu",), idle_sleep_sec=0.0, pipeline_depth=0))
        agent = Agent(config=cfg, session=requests.Session(), runtime=runtime)
        agent._profile = {"tier": "test"}
        while not serial.drained():
            agent.step()

    piped = Controller()
    piped.submit_csv_job(csv, total_rows=64, shard_size=16,
                         map_op="map_classify_tpu", extra_payload=extra)
    with ControllerServer(piped) as server:
        _drain_pipelined(piped, server, runtime)

    assert piped.counts() == {"succeeded": 4}
    for jid, want in serial.results().items():
        start = serial.job(jid).payload["start_row"]
        got = next(
            r for j, r in piped.results().items()
            if piped.job(j).payload["start_row"] == start
        )
        assert got["indices"] == want["indices"]
        assert got["scores"] == want["scores"]
        assert got["timings"]["device_ms"] > 0  # phase timings survive


TINY_S2S = {
    "d_model": 32, "n_heads": 4, "n_enc_layers": 1, "n_dec_layers": 1,
    "d_ff": 64, "max_src_len": 64, "max_tgt_len": 16, "dtype": "float32",
}


def test_pipelined_summarize_matches_serial_with_sink(runtime, tmp_path):
    """The summarize phase split: pipelined drain output (via JSONL sink)
    must equal the serial monolithic run row for row."""
    import json

    csv = _csv(tmp_path, n=32)
    sink = tmp_path / "sink"

    def extra(out_dir):
        return {"text_field": "text", "max_length": 6,
                "model_config": dict(TINY_S2S), "output_uri": str(out_dir)}

    serial = Controller()
    serial.submit_csv_job(csv, total_rows=32, shard_size=8,
                          map_op="map_summarize",
                          extra_payload=extra(sink / "serial"))
    with ControllerServer(serial) as server:
        cfg = Config(agent=AgentConfig(
            controller_url=server.url, agent_name="serial",
            tasks=("map_summarize",), idle_sleep_sec=0.0, pipeline_depth=0))
        agent = Agent(config=cfg, session=requests.Session(), runtime=runtime)
        agent._profile = {"tier": "test"}
        while not serial.drained():
            agent.step()

    piped = Controller()
    piped.submit_csv_job(csv, total_rows=32, shard_size=8,
                         map_op="map_summarize",
                         extra_payload=extra(sink / "piped"))
    with ControllerServer(piped) as server:
        _drain_pipelined(piped, server, runtime, tasks=("map_summarize",))

    assert piped.counts() == {"succeeded": 4}
    for r in piped.results().values():
        # Receipt on the wire; phase timings prove the split engaged.
        assert r["rows_written"] == 8 and "summaries" not in r
        assert r["timings"]["device_ms"] > 0
        assert "queue_ms" in r["timings"]

    def rows(d):
        out = []
        for p in sorted((sink / d).iterdir()):
            out += [json.loads(ln) for ln in p.read_text().splitlines()]
        return out

    assert rows("piped") == rows("serial")
    assert len(rows("piped")) == 32


def test_pipelined_mixed_ops_and_errors(runtime, tmp_path):
    """Monolithic ops (echo), soft errors, and hard errors all flow through
    the pipeline with the serial loop's result contract."""
    c = Controller()
    ok_id = c.submit("map_classify_tpu",
                     {"texts": ["row a", "row b"], "topk": 2,
                      "model_config": dict(TINY), "allow_fallback": False})
    echo_id = c.submit("echo", {"x": 42})
    soft_id = c.submit("map_classify_tpu", {"topk": 0, "texts": ["x"]})
    hard_id = c.submit("map_classify_tpu",
                       {"source_uri": str(tmp_path / "missing.csv"),
                        "start_row": 0, "shard_size": 8})
    with ControllerServer(c) as server:
        _drain_pipelined(c, server, runtime,
                         tasks=("map_classify_tpu", "echo"))

    assert c.job_snapshot(ok_id)["result"]["ok"] is True
    assert c.job_snapshot(echo_id)["result"]["echo"] == {"x": 42}
    assert c.job_snapshot(soft_id)["state"] == "succeeded"
    assert c.job_snapshot(soft_id)["result"]["ok"] is False
    hard = c.job_snapshot(hard_id)
    # Transient-class error (an I/O failure could heal), budget exhausted
    # after the one retry → terminal `dead` (ISSUE 3).
    assert hard["state"] == "dead"
    assert hard["error"]["type"] in ("FileNotFoundError", "OSError")
    assert hard["attempts"] == 2


def test_pipelined_drain_is_graceful(runtime, tmp_path):
    """Shutdown mid-drain: queued work drains (posted or TTL-requeued), the
    threads join, nothing deadlocks, no task is double-reported."""
    csv = _csv(tmp_path, n=96)
    c = Controller(lease_ttl_sec=1.0)
    c.submit_csv_job(csv, total_rows=96, shard_size=8,
                     map_op="map_classify_tpu",
                     extra_payload={"text_field": "text",
                                    "model_config": dict(TINY),
                                    "allow_fallback": False})
    with ControllerServer(c) as server:
        cfg = Config(agent=AgentConfig(
            controller_url=server.url, agent_name="graceful",
            tasks=("map_classify_tpu",), idle_sleep_sec=0.0, pipeline_depth=2))
        agent = Agent(config=cfg, session=requests.Session(), runtime=runtime)
        agent._profile = {"tier": "test"}

        def stop_soon():
            time.sleep(0.5)
            agent.shutdown()

        threading.Thread(target=stop_soon, daemon=True).start()
        agent.run()
        # Second agent finishes whatever the first one left (expired leases
        # re-queue via TTL) — resumability is the graceful-drain contract.
        c.sweep()
        time.sleep(1.1)
        c.sweep()
        _drain_pipelined(c, server, runtime)
    counts = c.counts()
    assert counts.get("succeeded", 0) == 12 and "failed" not in counts


def test_serial_loop_still_default_for_max_steps(runtime):
    """run(max_steps=N) keeps the deterministic serial loop for tests."""
    c = Controller()
    c.submit("echo", {"v": 1})
    with ControllerServer(c) as server:
        cfg = Config(agent=AgentConfig(
            controller_url=server.url, agent_name="serial",
            tasks=("echo",), idle_sleep_sec=0.0, pipeline_depth=2))
        agent = Agent(config=cfg, session=requests.Session(), runtime=runtime)
        agent._profile = {"tier": "test"}
        agent.run(max_steps=3)
    assert c.counts() == {"succeeded": 1}


def test_wedged_poster_does_not_hang_shutdown(monkeypatch):
    """If the poster thread stops draining (e.g. a deferred fetch wedged on
    a hung device) while the post queue is full, a shutdown must still get
    the device thread out of _put_post after the grace period — an agent
    blocked there forever would hold the TPU."""
    import queue as queue_mod

    from agent_tpu.agent import pipeline as pl

    monkeypatch.setattr(pl, "SHUTDOWN_GRACE_SEC", 1.0)

    class StubAgent:
        running = False  # shutdown already requested

    class StubPoster:
        @staticmethod
        def is_alive():
            return True  # alive but not draining: the wedge

    runner = pl.PipelineRunner.__new__(pl.PipelineRunner)
    runner.agent = StubAgent()
    runner._poster = StubPoster()
    runner.post_q = queue_mod.Queue(maxsize=1)
    runner.post_q.put("occupied")  # full; nothing will ever drain it

    t0 = time.time()
    assert runner._put_post("item") is False
    assert time.time() - t0 < 10  # escaped within the (shrunk) grace window
