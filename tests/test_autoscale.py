"""Autoscaler control loop (ISSUE 10): signal projection, scale decisions
with hysteresis/cooldown, capacity replacement, fleet drivers, and an
end-to-end scale-up/scale-down round against a real controller."""

import threading
import time

import pytest

from agent_tpu.autoscale import (
    DOWN,
    HOLD,
    REPLACE,
    UP,
    Autoscaler,
    FleetDriver,
    Signals,
    ThreadFleetDriver,
    read_signals,
)
from agent_tpu.config import AutoscaleConfig


class FakeDriver(FleetDriver):
    def __init__(self, size=1):
        self._size = size
        self.spawned = 0
        self.retired = 0

    def size(self):
        return self._size

    def spawn(self, n):
        self._size += n
        self.spawned += n
        return [f"m-{i}" for i in range(n)]

    def retire(self, n):
        n = min(n, self._size)
        self._size -= n
        self.retired += n
        return [f"m-{i}" for i in range(n)]


def make_scaler(driver, **cfg_kw):
    cfg_kw.setdefault("min_agents", 1)
    cfg_kw.setdefault("max_agents", 4)
    cfg_kw.setdefault("up_queue_per_agent", 4.0)
    cfg_kw.setdefault("down_idle_evals", 2)
    cfg_kw.setdefault("up_cooldown_sec", 10.0)
    cfg_kw.setdefault("down_cooldown_sec", 10.0)
    clock = {"t": 100.0}
    scaler = Autoscaler(
        driver, lambda: None, config=AutoscaleConfig(**cfg_kw),
        clock=lambda: clock["t"],
    )
    return scaler, clock


class TestReadSignals:
    def test_unreachable_health_is_unhealthy(self):
        assert read_signals(None).healthy is False
        assert read_signals("nope").healthy is False

    def test_projects_queue_slo_and_agents(self):
        sig = read_signals({
            "verdict": "warn",
            "queue": {"depth": 7, "starvation_age_sec": 3.5},
            "slo": {"enabled": True, "objectives": [
                {"objective": "tier8", "state": "warn"},
            ]},
            "counts": {"pending": 5, "leased": 2, "succeeded": 9},
            "agents": {
                "a": {"duty_cycle": 0.9, "stale": False, "draining": False},
                "b": {"duty_cycle": 0.1, "stale": False, "draining": True},
                "c": {"duty_cycle": None, "stale": True, "draining": False},
            },
        })
        assert sig.healthy and sig.slo_burning
        assert sig.queue_depth == 7
        assert sig.starvation_age_sec == 3.5
        assert sig.live_agents == 1          # draining + stale excluded
        assert sig.draining_agents == 1
        assert sig.max_duty == 0.9
        assert sig.active_jobs == 7


class TestDecide:
    def test_scale_up_on_queue_pressure(self):
        scaler, _ = make_scaler(FakeDriver(size=2), step_up=2)
        d = scaler.decide(Signals(queue_depth=20, active_jobs=20))
        assert d.action == UP and d.n == 2 and d.reason == "queue_pressure"

    def test_scale_up_on_slo_burn_and_starvation(self):
        scaler, _ = make_scaler(FakeDriver(size=2))
        d = scaler.decide(
            Signals(queue_depth=1, active_jobs=1, slo_burning=True)
        )
        assert d.action == UP and d.reason == "slo_burn"
        scaler2, _ = make_scaler(FakeDriver(size=2), up_starvation_sec=5.0)
        d = scaler2.decide(
            Signals(queue_depth=1, active_jobs=1, starvation_age_sec=9.0)
        )
        assert d.action == UP and d.reason == "starvation"

    def test_up_clamped_at_max_and_cooldown(self):
        driver = FakeDriver(size=4)
        scaler, clock = make_scaler(driver, max_agents=4)
        d = scaler.decide(Signals(queue_depth=100, active_jobs=100))
        assert d.action == HOLD and d.reason == "at_max"
        driver = FakeDriver(size=2)
        scaler, clock = make_scaler(driver, max_agents=6, step_up=2)
        scaler.apply(scaler.decide(Signals(queue_depth=100,
                                           active_jobs=100)))
        assert driver.spawned == 2
        # Immediately wanting more: blocked by the up cooldown.
        d = scaler.decide(Signals(queue_depth=100, active_jobs=100))
        assert d.action == HOLD and d.reason == "up_cooldown"
        clock["t"] += 60.0
        d = scaler.decide(Signals(queue_depth=100, active_jobs=100))
        assert d.action == UP  # cooldown elapsed, room below max

    def test_scale_down_needs_consecutive_idle_evals(self):
        scaler, clock = make_scaler(FakeDriver(size=3), down_idle_evals=3)
        idle = Signals(queue_depth=0, active_jobs=0, max_duty=0.0)
        assert scaler.decide(idle).action == HOLD
        assert scaler.decide(idle).action == HOLD
        d = scaler.decide(idle)
        assert d.action == DOWN and d.n == 1 and d.reason == "idle"

    def test_busy_signal_resets_the_idle_streak(self):
        scaler, _ = make_scaler(FakeDriver(size=3), down_idle_evals=2)
        idle = Signals(queue_depth=0, active_jobs=0, max_duty=0.0)
        busy = Signals(queue_depth=1, active_jobs=1)
        assert scaler.decide(idle).action == HOLD
        assert scaler.decide(busy).reason == "busy"
        assert scaler.decide(idle).action == HOLD  # streak restarted
        assert scaler.decide(idle).action == DOWN

    def test_duty_gate_blocks_scale_down(self):
        scaler, _ = make_scaler(
            FakeDriver(size=3), down_idle_evals=1, down_max_duty=0.2
        )
        hot = Signals(queue_depth=0, active_jobs=0, max_duty=0.5)
        assert scaler.decide(hot).reason == "busy"
        cold = Signals(queue_depth=0, active_jobs=0, max_duty=0.1)
        assert scaler.decide(cold).action == DOWN

    def test_down_respects_floor_and_cooldown(self):
        driver = FakeDriver(size=1)
        scaler, clock = make_scaler(driver, min_agents=1, down_idle_evals=1)
        idle = Signals(queue_depth=0, active_jobs=0, max_duty=0.0)
        assert scaler.decide(idle).reason == "at_min"
        driver._size = 3
        scaler.apply(scaler.decide(idle))
        assert driver.retired == 1
        d = scaler.decide(idle)
        assert d.action == HOLD and d.reason == "down_cooldown"
        clock["t"] += 60.0
        assert scaler.decide(idle).action == DOWN

    def test_replacement_repairs_reclaimed_capacity(self):
        driver = FakeDriver(size=3)
        scaler, clock = make_scaler(driver, min_agents=1)
        # Earn a desired size of 3 via a scale-up from 1.
        driver._size = 1
        scaler.apply(scaler.decide(Signals(queue_depth=50, active_jobs=50)))
        assert scaler.desired == 3
        # A reclaim drops actual below desired: repair bypasses cooldowns.
        driver._size = 1
        d = scaler.decide(Signals(queue_depth=0, active_jobs=0))
        assert d.action == REPLACE and d.n == 2
        assert d.reason == "capacity_lost"
        scaler.apply(d)
        assert driver.size() == 3
        # Below the hard floor the reason names it.
        driver._size = 0
        scaler.desired = 1
        d = scaler.decide(Signals(queue_depth=0, active_jobs=0))
        assert d.action == REPLACE and d.reason == "below_min"

    def test_unhealthy_controller_holds(self):
        scaler, _ = make_scaler(FakeDriver(size=2))
        d = scaler.decide(read_signals(None))
        assert d.action == HOLD and d.reason == "health_unreachable"

    def test_no_flap_under_oscillating_signal(self):
        """A signal alternating busy/idle every evaluation must produce
        ZERO scale events — the hysteresis contract."""
        driver = FakeDriver(size=2)
        scaler, clock = make_scaler(
            driver, down_idle_evals=3, up_queue_per_agent=4.0
        )
        idle = Signals(queue_depth=0, active_jobs=0, max_duty=0.0)
        mild = Signals(queue_depth=3, active_jobs=3)  # below up threshold
        for i in range(50):
            clock["t"] += 1.0
            scaler.apply(scaler.decide(idle if i % 2 else mild))
        assert driver.spawned == 0 and driver.retired == 0

    def test_step_exports_fleet_size_and_decision_families(self):
        driver = FakeDriver(size=2)
        clock = {"t": 0.0}
        scaler = Autoscaler(
            driver,
            lambda: {"verdict": "ok", "queue": {"depth": 0},
                     "slo": {"objectives": []}, "counts": {}, "agents": {}},
            config=AutoscaleConfig(min_agents=1, max_agents=4),
            clock=lambda: clock["t"],
        )
        scaler.step()
        snap = scaler.metrics.snapshot()
        states = {
            s["labels"]["state"]: s["value"]
            for s in snap["fleet_size"]["series"]
        }
        assert states["actual"] == 2
        assert snap["autoscale_decisions_total"]["series"]


class TestThreadFleetDriver:
    class _StubAgent:
        def __init__(self, name):
            self.name = name
            self.running = True
            self.draining = False
            self.spool = []
            self.session = None
            self.drain_reasons = []

        def run(self):
            while self.running:
                time.sleep(0.005)

        def request_drain(self, reason="drain"):
            self.draining = True
            self.drain_reasons.append(reason)
            self.running = False

    def test_spawn_retire_lifecycle(self):
        driver = ThreadFleetDriver(self._StubAgent, name_prefix="t")
        names = driver.spawn(3)
        assert len(names) == 3 and driver.size() == 3
        retired = driver.retire(2)
        assert len(retired) == 2 and driver.size() == 1
        for entry in driver.retired:
            assert entry["clean_exit"] and entry["spool_len"] == 0
            assert entry["agent"].drain_reasons == ["autoscale_retire"]
        # Retiring an unknown member is a no-op.
        assert driver.retire_member("nope") is False

    def test_kill_skips_the_drain_path(self):
        driver = ThreadFleetDriver(self._StubAgent, name_prefix="t")
        (name,) = driver.spawn(1)
        agent = driver.agent(name)
        assert driver.kill(name) is True
        assert driver.size() == 0
        assert agent.draining is False       # no drain path
        assert driver.killed == [name]


class TestEndToEnd:
    def test_scales_up_under_load_and_down_at_idle(self):
        """Real Controller + real Agents on threads + the real loop: queue
        pressure grows the fleet, the idle tail shrinks it back, nothing is
        lost, retired members drain clean."""
        from agent_tpu.agent.app import Agent
        from agent_tpu.chaos import LoopbackSession
        from agent_tpu.config import AgentConfig, Config
        from agent_tpu.controller.core import Controller

        controller = Controller(
            lease_ttl_sec=5.0, sweep_interval_sec=0.1,
        )

        class ThrottledSession:
            """Loopback with a transport RTT: echo tasks alone drain too
            fast for any control loop to observe queue pressure."""

            def __init__(self, inner):
                self.inner = inner

            def post(self, url, json=None, timeout=None):
                time.sleep(0.02)
                return self.inner.post(url, json=json, timeout=timeout)

        def factory(name):
            cfg = Config(agent=AgentConfig(
                controller_url="http://loopback", agent_name=name,
                tasks=("echo",), max_tasks=1, idle_sleep_sec=0.01,
                error_backoff_sec=0.01, pipeline_depth=0,
            ))
            agent = Agent(
                config=cfg,
                session=ThrottledSession(LoopbackSession(controller)),
            )
            agent._profile = {}
            return agent

        driver = ThreadFleetDriver(factory, name_prefix="e2e")
        scaler = Autoscaler(
            driver, controller.health_json,
            config=AutoscaleConfig(
                min_agents=1, max_agents=3, interval_sec=0.1,
                up_queue_per_agent=2.0, down_idle_evals=2,
                down_max_duty=1.0, up_cooldown_sec=0.3,
                down_cooldown_sec=0.2,
            ),
            registry=controller.metrics,
        )
        stop = threading.Event()
        thread = threading.Thread(
            target=scaler.run, args=(stop,), kwargs={"interval_sec": 0.1},
            daemon=True,
        )
        try:
            driver.spawn(1)
            thread.start()
            for i in range(40):
                controller.submit("echo", {"i": i})
            deadline = time.monotonic() + 30.0
            while not controller.drained() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert controller.drained()
            assert scaler.scale_ups >= 1
            # Idle tail shrinks back to the floor.
            deadline = time.monotonic() + 15.0
            while driver.size() > 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert driver.size() == 1
            assert scaler.scale_downs >= 1
        finally:
            stop.set()
            thread.join(timeout=5)
            driver.retire(driver.size())
            controller.close()
        counts = controller.counts()
        assert counts == {"succeeded": 40}
        for entry in driver.retired:
            assert entry["clean_exit"] and entry["spool_len"] == 0
            # The drain announced itself to the controller.
            assert controller.agents_summary()[entry["name"]]["draining"]
