"""Property-based tests (hypothesis) for the host-side data contracts.

SURVEY.md §4 anticipated property-based testing for the rebuild (the driver
``.gitignore`` reserves ``.hypothesis/``); these cover the invariants whose
input spaces are too large to enumerate with example tests:

- the quote-aware CSV byte-offset index agrees with ``csv.DictReader`` on
  arbitrary quoted tables (embedded commas, quotes, and newlines) — this is
  the data-distribution primitive every shard-addressed op trusts;
- byte-tokenizer roundtrip over arbitrary Unicode;
- padding/bucketing shape invariants behind the executable cache;
- int8 quantization error bounds (``models/quant.py``'s scheme promises
  elementwise error ≤ scale/2);
- controller shard splitting partitions ``[0, total_rows)`` exactly;
- the on-device double-single psum reduction vs exact host arithmetic.
"""

import csv
import math
import os
import tempfile

import numpy as np
import pytest

# The whole module is hypothesis-driven; environments without the optional
# dependency must SKIP it, not error at collection (the rest of tier-1 ran
# with `--continue-on-collection-errors` hiding this for two rounds).
pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, example, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from agent_tpu.config import DeviceConfig
from agent_tpu.runtime import TpuRuntime

# jit compiles (bucketed, but still) and temp-file IO make per-example time
# spiky; correctness, not speed, is under test. Applied per test (NOT via a
# global settings profile, which would silently change hypothesis defaults
# for every other module in the same pytest run).
_settings = settings(
    deadline=None,
    max_examples=50,
    suppress_health_check=[HealthCheck.too_slow],
)


@pytest.fixture(scope="module")
def rt():
    return TpuRuntime(DeviceConfig())


# ---- CSV index vs csv.DictReader ----------------------------------------

# Field text may contain every character the RFC-4180 quoting story has to
# survive: commas, double quotes, embedded newlines. '\r' is excluded — the
# index treats bare '\n' as the row terminator (files are written that way
# by csv.writer(lineterminator="\n")), while csv.DictReader folds a lone
# '\r\n' *inside a field* differently per universal-newlines mode; that
# corner is a file-format choice, not an index property.
_field_text = st.text(
    alphabet=st.sampled_from(list('abz09 ,"\'\n;:!')), max_size=12
)


@st.composite
def _csv_tables(draw):
    n_cols = draw(st.integers(min_value=1, max_value=4))
    n_rows = draw(st.integers(min_value=1, max_value=25))
    rows = [
        [draw(_field_text) for _ in range(n_cols)] for _ in range(n_rows)
    ]
    return ["c%d" % i for i in range(n_cols)], rows


@given(
    table=_csv_tables(),
    start=st.integers(min_value=0, max_value=30),
    size=st.integers(min_value=1, max_value=30),
)
@_settings
def test_csv_index_matches_dictreader(table, start, size):
    """``read_shard`` == the DictReader slice for ANY quoted table: the
    byte-offset scan (C++ or numpy — whichever the install selects) may
    never split a quoted newline or miscount a row."""
    from agent_tpu.data.csv_index import CsvIndex, read_shard

    header, rows = table
    fd, path = tempfile.mkstemp(suffix=".csv")
    try:
        with os.fdopen(fd, "w", encoding="utf-8", newline="") as f:
            w = csv.writer(f, lineterminator="\n")
            w.writerow(header)
            w.writerows(rows)

        with open(path, "r", encoding="utf-8", newline="") as f:
            want_all = list(csv.DictReader(f))

        index = CsvIndex.for_file(path)
        assert index.n_data_rows == len(rows) == len(want_all)
        assert index.header() == header

        got = read_shard(path, start, size)
        want = [dict(r) for r in want_all[start:start + size]]
        assert got == want
    finally:
        os.unlink(path)


# ---- tokenizer roundtrip + padding invariants ----------------------------


@given(text=st.text(max_size=200))
@_settings
def test_byte_tokenizer_roundtrip(text):
    from agent_tpu.models.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    # Specials are transport framing, not content: they must not leak into
    # the decoded text.
    framed = tok.encode(text, add_bos=True, add_eos=True)
    assert tok.decode(framed) == text
    assert len(framed) == len(ids) + 2


@given(
    seqs=st.lists(
        st.lists(st.integers(min_value=0, max_value=259), max_size=50),
        max_size=20,
    ),
    use_batch_buckets=st.booleans(),
)
@_settings
def test_pad_batch_invariants(seqs, use_batch_buckets):
    """Static-shape guarantees the executable cache depends on: bucketed
    dims, exact masks, pad everywhere the mask is 0."""
    from agent_tpu.models.tokenizer import (
        DEFAULT_BUCKETS, PAD_ID, bucket_length, pad_batch,
    )

    batch_buckets = (1, 2, 4, 8, 16, 32) if use_batch_buckets else None
    ids, mask = pad_batch(seqs, batch_buckets=batch_buckets)
    max_len = max((len(s) for s in seqs), default=1)
    L = bucket_length(max(1, max_len))
    assert ids.shape == mask.shape
    assert ids.shape[1] == L and L in DEFAULT_BUCKETS
    if batch_buckets:
        assert ids.shape[0] in batch_buckets and ids.shape[0] >= len(seqs)
    else:
        assert ids.shape[0] == len(seqs)
    for r, s in enumerate(seqs):
        n = min(len(s), L)
        assert mask[r].sum() == n
        assert list(ids[r, :n]) == list(s[:n])
    assert np.all(ids[mask == 0] == PAD_ID)
    assert np.all((mask == 0) | (mask == 1))


@given(n=st.integers(min_value=1, max_value=10_000))
@_settings
def test_bucket_length_minimal(n):
    from agent_tpu.models.tokenizer import DEFAULT_BUCKETS, bucket_length

    b = bucket_length(n)
    assert b in DEFAULT_BUCKETS
    if n <= DEFAULT_BUCKETS[-1]:
        assert b >= n
        # minimality: no smaller bucket also covers n
        assert all(x < n for x in DEFAULT_BUCKETS if x < b)
    else:
        assert b == DEFAULT_BUCKETS[-1]  # callers truncate to the top bucket


@given(
    n=st.integers(min_value=1, max_value=100_000),
    multiple=st.sampled_from([1, 2, 4, 8]),
)
@_settings
def test_padded_len_props(n, multiple):
    from agent_tpu.parallel.collectives import _padded_len

    size = _padded_len(n, multiple)
    assert size >= n and size % multiple == 0
    q = size // multiple
    assert q & (q - 1) == 0  # power-of-two ladder
    assert size <= max(multiple, 2 * n)  # never more than 2× overshoot


# ---- int8 quantization error bounds --------------------------------------


@st.composite
def _weight_matrices(draw):
    rows = draw(st.integers(min_value=1, max_value=8))
    cols = draw(st.integers(min_value=1, max_value=8))
    vals = draw(
        st.lists(
            st.floats(
                min_value=-1e4, max_value=1e4,
                allow_nan=False, allow_infinity=False, width=32,
            ),
            min_size=rows * cols, max_size=rows * cols,
        )
    )
    return np.asarray(vals, dtype=np.float32).reshape(rows, cols)


@given(w=_weight_matrices())
@_settings
def test_quantize_weight_error_bound(w):
    """The scheme's promise: per-channel symmetric int8 with elementwise
    reconstruction error ≤ scale/2, zeros exact, |q| ≤ 127."""
    from agent_tpu.models.quant import quantize_weight

    q = quantize_weight(w, (0,))
    assert q["w_q"].dtype == np.int8
    assert np.all(np.abs(q["w_q"].astype(np.int32)) <= 127)
    assert np.all(q["w_scale"] > 0)
    deq = q["w_q"].astype(np.float32) * q["w_scale"][None, :]
    err = np.abs(deq - w)
    assert np.all(err <= q["w_scale"][None, :] * 0.5 * (1 + 1e-6))
    assert np.all(deq[w == 0.0] == 0.0)


@given(x=_weight_matrices())
@_settings
def test_quantize_act_error_bound(x):
    from agent_tpu.models.quant import quantize_act

    x_q, scale = quantize_act(x)
    x_q, scale = np.asarray(x_q), np.asarray(scale)
    assert x_q.dtype == np.int8
    assert np.all(np.abs(x_q.astype(np.int32)) <= 127)
    deq = x_q.astype(np.float32) * scale
    assert np.all(np.abs(deq - x) <= scale * 0.5 * (1 + 1e-6))
    assert np.all(deq[x == 0.0] == 0.0)


# ---- controller shard splitting ------------------------------------------


@given(
    total=st.integers(min_value=1, max_value=500),
    size=st.integers(min_value=1, max_value=60),
)
@_settings
def test_shard_split_partitions_exactly(total, size):
    """Shards must tile [0, total_rows) with no gap, no overlap, and no
    shard over ``shard_size`` — idempotent re-execution (SURVEY §5.4) rests
    on this addressing."""
    from agent_tpu.controller.core import Controller

    c = Controller()
    shard_ids, reduce_id = c.submit_csv_job(
        "rows.csv", total_rows=total, shard_size=size, map_op="echo"
    )
    assert reduce_id is None
    spans = [
        (c._jobs[sid].payload["start_row"], c._jobs[sid].payload["shard_size"])
        for sid in shard_ids
    ]
    assert spans[0][0] == 0
    assert all(0 < n <= size for _, n in spans)
    for (s0, n0), (s1, _) in zip(spans, spans[1:]):
        assert s1 == s0 + n0  # contiguous, ordered, non-overlapping
    assert spans[-1][0] + spans[-1][1] == total
    assert sum(n for _, n in spans) == total


# ---- map_tokenize chars mode: chunks reassemble --------------------------


@given(
    items=st.lists(st.text(max_size=40), min_size=1, max_size=6),
    chunk_size=st.integers(min_value=1, max_value=16),
)
@_settings
def test_map_tokenize_chars_reassembles(items, chunk_size):
    from agent_tpu.ops import get_op

    out = get_op("map_tokenize")(
        {"items": items, "mode": "chars", "chunk_size": chunk_size}
    )
    assert out["ok"] is True
    assert out["counts"] == [max(1, math.ceil(len(t) / chunk_size))
                             for t in items]
    # Flat chunk list partitions back into the original items.
    chunks = out["chunks"]
    pos = 0
    for t, n in zip(items, out["counts"]):
        part = chunks[pos:pos + n]
        pos += n
        assert "".join(part) == t
        assert all(len(chunk) <= chunk_size for chunk in part)
        # Every chunk but the last is full (the reference's fixed-window
        # semantics, ref ops/map_tokenize.py:6-9).
        assert all(len(chunk) == chunk_size for chunk in part[:-1])
    assert pos == len(chunks)
    assert out["total_chars"] == sum(len(t) for t in items)


# ---- device reduction vs exact host arithmetic ---------------------------


@given(
    values=st.lists(
        st.floats(
            min_value=-1e6, max_value=1e6,
            allow_nan=False, allow_infinity=False,
        ),
        min_size=1, max_size=64,
    )
)
@example(values=[1.401298464324817e-45])  # round-4 counterexample: subnormal
# f32 was flushed to zero by the device float min/max; now reduced as
# monotone bitcast integer keys (collectives._build_stats_fn), immune to FTZ.
@example(values=[-1.401298464324817e-45, 1e-40, -0.0])
# deadline=None: the @example cases above run as the deterministic FIRST
# examples, so a cold jit compile (~220 ms measured) trips the default
# 200 ms deadline whenever no earlier test warmed the backend — an
# order-dependent flake, not a perf signal (ADVICE r5).
@settings(max_examples=25, deadline=None)
def test_mesh_reduce_stats_props(rt, values):
    """The documented numerics contract of ``mesh_reduce_stats``: sum within
    f32 accumulation noise of exact ``math.fsum``; min/max equal to the f32
    rounding of the exact extremes (monotonicity of rounding makes that an
    equality, not a tolerance — subnormals included)."""
    from agent_tpu.parallel.collectives import mesh_reduce_stats

    out = mesh_reduce_stats(rt, values)
    assert out["count"] == len(values)
    want = math.fsum(values)
    tol = max(1e-3, 1e-6 * math.fsum(abs(v) for v in values))
    assert abs(out["sum"] - want) <= tol
    assert out["mean"] == pytest.approx(out["sum"] / len(values))
    assert out["min"] == float(np.float32(min(values)))
    assert out["max"] == float(np.float32(max(values)))
