"""Pure-op payload-contract tests, straight from SURVEY.md §2.3's tables."""

from agent_tpu.ops.echo import run as echo
from agent_tpu.ops.map_tokenize import run as tokenize
from agent_tpu.ops.risk_accumulate import run as risk
from agent_tpu.ops.csv_shard import run as csv_shard
from agent_tpu.ops.trigger_sap import run as sap
from agent_tpu.ops.trigger_oracle import run as oracle


class TestEcho:
    def test_roundtrip(self):
        assert echo({"a": 1}) == {"ok": True, "echo": {"a": 1}}

    def test_tolerates_none_and_nondict(self):
        # ref ops/echo.py:17-22
        assert echo(None) == {"ok": True, "echo": {}}
        assert echo([1, 2])["echo"] == [1, 2]


class TestTokenize:
    def test_chars_mode_parity(self):
        # Reference behavior: fixed char windows (ref ops/map_tokenize.py:24).
        out = tokenize({"text": "ab" * 700, "mode": "chars"})
        assert out["ok"] and out["n_chunks"] == 2
        assert len(out["chunks"][0]) == 1024 and len(out["chunks"][1]) == 376

    def test_chars_items(self):
        out = tokenize({"items": ["x" * 2500, "y"], "mode": "chars", "chunk_size": 1000})
        assert out["counts"] == [3, 1] and out["n_chunks"] == 4

    def test_tokens_mode_default(self):
        out = tokenize({"items": ["hello world", "hi"]})
        assert out["ok"] and out["mode"] == "tokens"
        assert out["token_counts"] == [11, 2]  # byte tokenizer
        assert out["n_tokens"] == 13

    def test_validation_soft_errors(self):
        assert tokenize(None)["ok"] is False
        assert tokenize({"chunk_size": -1, "text": "x"})["ok"] is False
        assert tokenize({"items": [1]})["ok"] is False
        assert tokenize({"mode": "bogus", "text": "x"})["ok"] is False


class TestRisk:
    def test_values(self):
        out = risk({"values": [1, 2, 3, 4]})
        assert out["ok"] and out["count"] == 4
        assert out["sum"] == 10.0 and out["mean"] == 2.5
        assert out["min"] == 1.0 and out["max"] == 4.0
        assert "compute_time_ms" in out

    def test_items_field(self):
        # default field "risk" (ref ops/risk_accumulate.py:44); None skipped.
        out = risk({"items": [{"risk": 2.0}, {"risk": 4.0}, {"other": 9}]})
        assert out["count"] == 2 and out["mean"] == 3.0

    def test_zero_input_shape(self):
        # ref ops/risk_accumulate.py:56-63
        out = risk({"values": []})
        assert out == {**out, "count": 0, "sum": 0.0, "mean": 0.0, "min": None, "max": None}

    def test_validation(self):
        assert risk({"values": "nope"})["ok"] is False
        assert risk({"values": [1, "x"]})["ok"] is False
        assert risk({})["ok"] is False


class TestCsvShard:
    def test_rows_mode(self, tmp_csv):
        out = csv_shard({"source_uri": tmp_csv, "start_row": 5, "shard_size": 3})
        assert out["ok"] and out["count"] == 3
        assert out["rows"][0]["id"] == "5"
        assert out["rows"][0]["text"] == "row 5, text"  # quoted comma preserved
        assert out["total_rows"] == 26

    def test_quoted_newline_row(self, tmp_csv):
        out = csv_shard({"source_uri": tmp_csv, "start_row": 25, "shard_size": 5})
        assert out["count"] == 1
        assert out["rows"][0]["text"] == "line one\nline two"

    def test_count_mode_and_task_wrapping(self, tmp_csv):
        # payload may arrive wrapped in a task dict (ref ops/csv_shard.py:51)
        out = csv_shard({"payload": {"source_uri": tmp_csv, "mode": "count", "shard_size": 1000}})
        assert out["ok"] and out["count"] == 26

    def test_past_end(self, tmp_csv):
        out = csv_shard({"source_uri": tmp_csv, "start_row": 100, "shard_size": 10})
        assert out["ok"] and out["rows"] == [] and out["count"] == 0

    def test_validation(self, tmp_csv):
        assert csv_shard({})["ok"] is False
        assert csv_shard({"source_uri": tmp_csv, "start_row": -1})["ok"] is False
        assert csv_shard({"source_uri": tmp_csv, "shard_size": 0})["ok"] is False
        assert csv_shard({"source_uri": tmp_csv, "mode": "bogus"})["ok"] is False
        assert csv_shard({"source_uri": "/no/such/file.csv"})["ok"] is False

    def test_file_uri(self, tmp_csv):
        out = csv_shard({"source_uri": f"file://{tmp_csv}", "shard_size": 1})
        assert out["ok"] and out["count"] == 1


class TestTriggers:
    def test_sap_dry_run(self, monkeypatch):
        monkeypatch.delenv("SAP_HOST", raising=False)
        out = sap({"event_type": "quality_alert", "material": "M-100", "text": "defect"})
        assert out["ok"] and out["dry_run"]
        assert out["request"]["json"]["Material"] == "M-100"

    def test_sap_validation(self):
        assert sap({})["ok"] is False

    def test_oracle_dry_run(self, monkeypatch):
        monkeypatch.delenv("ORACLE_HOST", raising=False)
        out = oracle({"event": "inventory_adjustment", "item": "I-7", "qty": 5})
        assert out["ok"] and out["dry_run"]
        assert out["request"]["json"]["TransactionQuantity"] == 5

    def test_oracle_validation(self):
        assert oracle({"item": "", "qty": 1})["ok"] is False
        assert oracle({"item": "x", "qty": "many"})["ok"] is False


class TestRiskDeviceThreshold:
    def test_bad_device_threshold_is_soft_error(self):
        from agent_tpu.ops.risk_accumulate import run as risk

        out = risk({"values": [1.0], "device_threshold": "soon"})
        assert out["ok"] is False and "device_threshold" in out["error"]
        assert risk({"values": [1.0], "device_threshold": 0})["ok"] is False
        assert risk({"values": [1.0], "device_threshold": True})["ok"] is False
        # Consistent even on paths that never consult it (empty values)...
        assert risk({"values": [], "device_threshold": "soon"})["ok"] is False
        # ...and a float threshold is fine (it's only compared against).
        assert risk({"values": [1.0], "device_threshold": 8192.0})["ok"] is True


def test_csv_shard_reference_wire_contract(tmp_csv):
    """Reference-era consumers key on dataset_id/end_row/row_count
    (reference ops/csv_shard.py:86-103) — those aliases must ride along."""
    from agent_tpu.ops import get_op

    op = get_op("read_csv_shard")
    out = op({"source_uri": tmp_csv, "start_row": 5, "shard_size": 10,
              "dataset_id": "ds-1"})
    assert out["dataset_id"] == "ds-1"
    assert out["end_row"] == 15 and out["row_count"] == 10
    cnt = op({"source_uri": tmp_csv, "start_row": 20, "shard_size": 10,
              "mode": "count"})
    assert cnt["dataset_id"] == "unknown_dataset"  # reference default
    assert cnt["row_count"] == cnt["count"] == 6   # 26 rows total
    assert cnt["end_row"] == 26


def test_map_tokenize_chars_reference_wire_contract():
    """Reference chars-mode keys (reference ops/map_tokenize.py:42-48,56-61):
    tokens/count/total_chars (+items_count in items mode)."""
    from agent_tpu.ops import get_op

    op = get_op("map_tokenize")
    single = op({"text": "a" * 2500, "mode": "chars", "chunk_size": 1024})
    assert single["tokens"] == single["chunks"]
    assert single["count"] == single["n_chunks"] == 3
    assert single["total_chars"] == 2500

    multi = op({"items": ["ab", "cdef"], "mode": "chars", "chunk_size": 3})
    assert multi["items_count"] == 2
    assert multi["total_chars"] == 6
    assert multi["count"] == len(multi["tokens"])


class TestRiskAccumulateMapReduce:
    def test_source_uri_map_stage(self, tmp_csv):
        from agent_tpu.ops import get_op

        run = get_op("risk_accumulate")
        out = run({"source_uri": tmp_csv, "start_row": 0, "shard_size": 10,
                   "field": "risk"})
        want = [i * 0.5 for i in range(10)]
        assert out["ok"] and out["count"] == 10
        assert abs(out["sum"] - sum(want)) < 1e-9
        import pytest as _pytest

        with _pytest.raises(RuntimeError):
            run({"source_uri": tmp_csv, "start_row": 10_000})
        with _pytest.raises(RuntimeError):
            run({"source_uri": tmp_csv, "field": "text"})  # non-numeric

    def test_partials_merge(self):
        from agent_tpu.ops import get_op

        run = get_op("risk_accumulate")
        p1 = run({"values": [1.0, 2.0, 3.0]})
        p2 = run({"values": [10.0, -5.0]})
        p3 = run({"values": []})
        merged = run({"partials": [p1, p2, p3]})
        assert merged["count"] == 5
        assert abs(merged["sum"] - 11.0) < 1e-9
        assert merged["min"] == -5.0 and merged["max"] == 10.0
        assert merged["n_partials"] == 3
        # All-empty partials → zero shape.
        zero = run({"partials": [p3]})
        assert zero["count"] == 0 and zero["min"] is None
        # Malformed partials → soft error.
        bad = run({"partials": [{"count": "x"}]})
        assert bad["ok"] is False

    def test_partials_merge_nan_poison_order_independent(self):
        """A NaN-poisoned shard partial (the map stage's contract for
        NaN-carrying shards: sum=min=max=NaN) must poison the merged stats
        regardless of partial ORDER — Python min/max alone keep or drop NaN
        depending on argument position, which made the merged result depend
        on shard completion order (ADVICE r5)."""
        import math

        from agent_tpu.ops import get_op

        run = get_op("risk_accumulate")
        poisoned = run({"values": [float("nan"), 1.0]})
        assert math.isnan(poisoned["min"]) and math.isnan(poisoned["max"])
        clean = run({"values": [2.0, 7.0]})
        for order in ([poisoned, clean], [clean, poisoned]):
            merged = run({"partials": list(order)})
            assert merged["ok"] is True and merged["count"] == 4
            for key in ("sum", "mean", "min", "max"):
                assert math.isnan(merged[key]), (key, order, merged)
        # NaN-free merges stay exact.
        merged = run({"partials": [clean, clean]})
        assert merged["min"] == 2.0 and merged["max"] == 7.0


def test_map_tokenize_bpe_mode(tmp_path):
    """tokenizer: 'bpe' with a local vocab dir — ids match the BPE module
    (which is differential-tested against transformers in test_bart.py)."""
    import json

    from agent_tpu.models.bpe import ByteLevelBPE, bytes_to_unicode

    base = list(bytes_to_unicode().values())
    vocab = {t: i for i, t in enumerate(
        ["<s>", "<pad>", "</s>", "<unk>"] + base + ["he", "ll", "llo"]
    )}
    (tmp_path / "vocab.json").write_text(json.dumps(vocab))
    (tmp_path / "merges.txt").write_text("#version: 0.2\nh e\nl l\nll o\n")

    out = tokenize({
        "items": ["hello world", "he"],
        "tokenizer": "bpe",
        "vocab_path": str(tmp_path),
        "chunk_size": 4,
    })
    assert out["ok"] is True and out["tokenizer"] == "bpe"
    assert out["vocab_size"] == len(vocab)
    ref = ByteLevelBPE.from_dir(str(tmp_path))
    want = ref.encode("hello world")
    assert out["chunks"][0] == want[:4]
    assert out["token_counts"] == [len(want), len(ref.encode("he"))]

    missing = tokenize({"items": ["x"], "tokenizer": "bpe"})
    assert missing["ok"] is False and "vocab_path" in missing["error"]
