"""Checkpoint roundtrips: save_npz is the exact inverse of assign_from_npz,
ops load the artifact by model_path, and orbax (when present) restores
sharded."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agent_tpu.models import checkpoint, encoder, seq2seq


CFG = encoder.EncoderConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
    max_len=16, n_classes=10, dtype="float32",
)


def _perturbed_params(model_id="ckpt-test"):
    params = encoder.init_params(CFG, model_id=model_id)
    # Perturb so a load that silently falls back to deterministic init fails.
    params["head"]["b"] = params["head"]["b"] + 0.5
    return params


def test_npz_roundtrip_exact(tmp_path):
    params = _perturbed_params()
    path = checkpoint.save_npz(params, str(tmp_path / "enc.npz"))
    loaded = encoder.load_npz(path, CFG)
    assert checkpoint.params_equal(params, loaded)


def test_npz_roundtrip_seq2seq(tmp_path):
    cfg = seq2seq.Seq2SeqConfig(
        vocab_size=64, d_model=32, n_heads=4, n_enc_layers=2, n_dec_layers=2,
        d_ff=64, max_src_len=16, max_tgt_len=8, dtype="float32",
    )
    params = seq2seq.init_params(cfg, model_id="ckpt-s2s")
    path = checkpoint.save_npz(params, str(tmp_path / "s2s.npz"))
    assert checkpoint.params_equal(params, seq2seq.load_npz(path, cfg))


def test_op_loads_saved_checkpoint(tmp_path):
    """The full §5.4 loop: train-side save → op-side load via model_path."""
    from agent_tpu.ops import get_op

    params = _perturbed_params()
    path = checkpoint.save_npz(params, str(tmp_path / "model.npz"))
    classify = get_op("map_classify_tpu")
    payload = {
        "texts": ["checkpointed weights"],
        "model_path": path,
        "model_config": {
            "vocab_size": 64, "d_model": 32, "n_heads": 4, "n_layers": 2,
            "d_ff": 64, "max_len": 16, "n_classes": 10, "dtype": "float32",
        },
        "allow_fallback": False,
    }
    out = classify(payload)
    assert out["ok"] is True and len(out["topk"]) == 5

    # Ground truth: forward with the saved params directly.
    from agent_tpu.models.tokenizer import ByteTokenizer, pad_batch

    ids, mask = pad_batch([ByteTokenizer().encode("checkpointed weights")[:16]],
                          buckets=[16])
    want = np.asarray(encoder.forward(
        jax.tree_util.tree_map(jnp.asarray, params), ids, mask, CFG
    ))
    top1 = int(np.argmax(want[0]))
    assert out["topk"][0]["index"] == top1


def test_save_npz_atomic_no_partial_file(tmp_path):
    """A failed save must not leave a (partial) file at the target path."""
    class Boom:
        shape = (2,)

        def __array__(self):
            raise RuntimeError("device exploded mid-gather")

    params = {"w": Boom()}
    target = tmp_path / "broken.npz"
    with pytest.raises(RuntimeError):
        checkpoint.save_npz(params, str(target))
    assert not target.exists()
    assert not list(tmp_path.glob("*.tmp"))


@pytest.mark.skipif(not checkpoint.orbax_available(), reason="no orbax")
def test_orbax_sharded_roundtrip(tmp_path):
    """Sharded params save from / restore onto a dp mesh placement."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from agent_tpu.config import DeviceConfig
    from agent_tpu.runtime import TpuRuntime

    rt = TpuRuntime(DeviceConfig(mesh_shape={"dp": 8}))
    params = _perturbed_params("orbax-test")
    sharded = jax.tree_util.tree_map(
        lambda leaf: jax.device_put(
            jnp.asarray(leaf), NamedSharding(rt.mesh, P())
        ),
        params,
    )
    path = str(tmp_path / "orbax_ckpt")
    checkpoint.save_orbax(sharded, path)
    like = jax.tree_util.tree_map(
        lambda leaf: jax.device_put(
            jnp.zeros_like(leaf), NamedSharding(rt.mesh, P())
        ),
        params,
    )
    restored = checkpoint.load_orbax(path, like)
    assert checkpoint.params_equal(params, restored)
