"""Sizing: proof-based detection + profile assembly (ref worker_sizing.py)."""

from agent_tpu.config import Config, DeviceConfig, SizingConfig
from agent_tpu.sizing import (
    build_worker_profile,
    detect_cpu,
    detect_gpu,
    detect_tpu,
)


def test_cpu_sizing_reserves_cores_and_caps():
    out = detect_cpu(SizingConfig())
    assert out["logical_cores"] >= 1
    assert 1 <= out["usable_cores"] <= out["logical_cores"]
    assert out["reserved_cores"] + out["usable_cores"] == out["logical_cores"]
    assert out["target_inflight"] >= 1
    assert out["max_cpu_workers"] >= 1


def test_cpu_sizing_respects_knobs():
    out = detect_cpu(SizingConfig(cpu_pipeline_factor=1.0, cpu_min_workers=3))
    assert out["target_inflight"] >= 3


def test_gpu_detection_honors_visible_devices_none(monkeypatch):
    monkeypatch.setenv("NVIDIA_VISIBLE_DEVICES", "none")
    out = detect_gpu()
    assert out == {"gpu_present": False, "gpus": [], "max_gpu_workers": 0}


def test_tpu_detection_is_proof_based(monkeypatch):
    # Hints alone never flip tpu_present (ref worker_sizing.py:199-200).
    cfg = DeviceConfig(tpu_name="fake-pod", tpu_type="v5e-16")
    out = detect_tpu(cfg)
    # Test env pins the cpu backend, so regardless of hints: no TPU claimed.
    assert out["tpu_present"] is False
    assert out["hints"] == {"tpu_name": "fake-pod", "tpu_type": "v5e-16"}


def test_tpu_disabled_kill_switch_short_circuits():
    out = detect_tpu(DeviceConfig(tpu_disabled=True))
    assert out == {
        "tpu_present": False,
        "max_tpu_workers": 0,
        "disabled": True,
        "hints": {},
    }


def test_profile_assembly_and_limits():
    prof = build_worker_profile(Config())
    assert prof["schema"] == "worker_profile/v2"
    assert prof["tier"] in ("cpu", "tpu", "tpu-pod")
    assert prof["limits"] == {"max_payload_bytes": 262144, "max_tokens": 2048}
    assert (
        prof["max_total_workers"]
        == prof["cpu"]["max_cpu_workers"]
        + prof["gpu"]["max_gpu_workers"]
        + prof["tpu"]["max_tpu_workers"]
    )


def test_tpu_only_mode_caps_host_scheduling():
    prof = build_worker_profile(Config(device=DeviceConfig(tpu_only=True)))
    # cpu/gpu keys survive (schema stability) but can't attract work.
    assert prof["cpu"]["max_cpu_workers"] == 1
    assert prof["gpu"]["max_gpu_workers"] == 0
