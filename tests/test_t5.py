"""HF-T5 family (``models/t5.py``): relative-position-bias attention,
RMSNorm, and tied-head logits must reproduce ``transformers``' reference
outputs — the checkpoint family BASELINE.json names for summarize."""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

import jax  # noqa: E402

from agent_tpu.models import t5  # noqa: E402

TINY = dict(
    vocab_size=64, d_model=32, d_kv=8, num_heads=4, d_ff=64,
    num_layers=2, num_decoder_layers=2, feed_forward_proj="relu",
)


def _torch_model(**overrides):
    torch.manual_seed(0)
    cfg = transformers.T5Config(**{**TINY, **overrides})
    return transformers.T5ForConditionalGeneration(cfg).eval()


def _import(model, tmp_path, name):
    d = tmp_path / name
    model.save_pretrained(str(d), safe_serialization=False)
    return t5.load_hf_dir(str(d), dtype="float32")


def test_bucket_function_matches_transformers():
    from transformers.models.t5.modeling_t5 import T5Attention

    rel = np.arange(-40, 41).reshape(1, -1).repeat(3, axis=0)
    rel = rel + np.array([[-5], [0], [7]])
    for bidir in (True, False):
        want = T5Attention._relative_position_bucket(
            torch.tensor(rel), bidirectional=bidir, num_buckets=32,
            max_distance=128,
        ).numpy()
        got = np.asarray(
            t5.relative_position_bucket(np.asarray(rel), bidir, 32, 128)
        )
        np.testing.assert_array_equal(got, want)


def test_forward_matches_transformers(tmp_path):
    model = _torch_model()
    cfg, params = _import(model, tmp_path, "relu_tied")
    assert cfg.tie_word_embeddings and not cfg.gated_ffn

    rng = np.random.default_rng(0)
    src = rng.integers(2, cfg.vocab_size, (3, 9)).astype(np.int32)
    mask = np.ones((3, 9), dtype=np.int32)
    mask[1, 6:] = 0
    src[1, 6:] = cfg.pad_id
    tgt = rng.integers(2, cfg.vocab_size, (3, 5)).astype(np.int32)
    tgt[:, 0] = cfg.decoder_start_id

    with torch.no_grad():
        want = model(
            input_ids=torch.tensor(src, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
            decoder_input_ids=torch.tensor(tgt, dtype=torch.long),
        ).logits.numpy()
    enc = t5.encode(params, src, mask, cfg)
    got = np.asarray(
        jax.jit(lambda p, t, e, m: t5.decode_full(p, t, e, m, cfg))(
            params, tgt, enc, mask
        )
    )
    np.testing.assert_allclose(got, want, atol=3e-4)


def test_gated_untied_variant_matches(tmp_path):
    model = _torch_model(
        feed_forward_proj="gated-gelu", tie_word_embeddings=False
    )
    cfg, params = _import(model, tmp_path, "gated_untied")
    assert cfg.gated_ffn and not cfg.tie_word_embeddings
    assert "lm_head" in params

    rng = np.random.default_rng(1)
    src = rng.integers(2, cfg.vocab_size, (2, 7)).astype(np.int32)
    mask = np.ones((2, 7), dtype=np.int32)
    tgt = np.full((2, 4), cfg.decoder_start_id, dtype=np.int32)
    tgt[:, 1:] = rng.integers(2, cfg.vocab_size, (2, 3))
    with torch.no_grad():
        want = model(
            input_ids=torch.tensor(src, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
            decoder_input_ids=torch.tensor(tgt, dtype=torch.long),
        ).logits.numpy()
    enc = t5.encode(params, src, mask, cfg)
    got = np.asarray(t5.decode_full(params, tgt, enc, mask, cfg))
    np.testing.assert_allclose(got, want, atol=3e-4)


def test_greedy_generation_matches_transformers(tmp_path):
    model = _torch_model()
    cfg, params = _import(model, tmp_path, "gen")
    rng = np.random.default_rng(2)
    src = rng.integers(2, cfg.vocab_size, (2, 6)).astype(np.int32)
    mask = np.ones((2, 6), dtype=np.int32)
    T = 7
    with torch.no_grad():
        want = model.generate(
            input_ids=torch.tensor(src, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
            max_new_tokens=T, num_beams=1, do_sample=False, min_length=0,
            decoder_start_token_id=cfg.decoder_start_id,  # this transformers
            # version requires it explicitly for a from-config T5
        ).numpy()
    toks, _ = jax.jit(
        lambda p, i, m: t5.generate(p, i, m, cfg, T)
    )(params, src, mask)
    toks = np.asarray(toks)
    want_gen = want[:, 1:]  # HF row = [decoder_start, generated...]
    n = min(want_gen.shape[1], T)
    np.testing.assert_array_equal(toks[:, :n], want_gen[:, :n])


def test_beam_runs_and_returns_shapes(tmp_path):
    model = _torch_model()
    cfg, params = _import(model, tmp_path, "beam")
    src = np.full((2, 5), 9, dtype=np.int32)
    mask = np.ones((2, 5), dtype=np.int32)
    toks, lengths = t5.generate(params, src, mask, cfg, 5, num_beams=3)
    assert np.asarray(toks).shape == (2, 5)
    assert np.asarray(lengths).shape == (2,)


def test_spm_gate_gives_actionable_error(tmp_path):
    with pytest.raises((RuntimeError, ValueError),
                       match="sentencepiece|spiece"):
        t5.hf_spm(str(tmp_path))


def test_t5_dir_through_op_gives_sentencepiece_gate(tmp_path):
    """Without the sentencepiece package, a T5 checkpoint through
    map_summarize must fail with the actionable gate error (not serve
    random weights, not crash obscurely)."""
    pytest.importorskip("agent_tpu.ops")
    try:
        import sentencepiece  # noqa: F401

        pytest.skip("sentencepiece installed; gate not reachable")
    except ImportError:
        pass

    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext
    from agent_tpu.runtime.runtime import get_runtime

    model = _torch_model()
    d = tmp_path / "t5_ckpt"
    model.save_pretrained(str(d), safe_serialization=False)
    with pytest.raises(RuntimeError, match="sentencepiece"):
        get_op("map_summarize")(
            {"texts": ["row text"], "model_path": str(d), "max_length": 4},
            OpContext(runtime=get_runtime()),
        )


def test_flash_t5_kernel_matches_dense(tmp_path):
    """The fused T5 kernel (bias computed per tile in VMEM, interpret mode
    on CPU) must equal the dense bias-attention path, padding included."""
    import jax.numpy as jnp

    from agent_tpu.kernels.flash_attention import flash_attention_t5

    model = _torch_model()
    d = tmp_path / "flash_ckpt"
    model.save_pretrained(str(d), safe_serialization=False)
    cfg, params = t5.load_hf_dir(str(d), dtype="float32")

    rng = np.random.default_rng(3)
    B, H, L, D = 2, cfg.n_heads, 16, cfg.d_kv
    q = jnp.asarray(rng.standard_normal((B, H, L, D)), dtype=jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, L, D)), dtype=jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, L, D)), dtype=jnp.float32)
    mask = np.ones((B, L), dtype=np.int32)
    mask[1, 10:] = 0
    rel_bias = jnp.asarray(params["enc"]["rel_bias"])

    got = flash_attention_t5(
        q, k, v, jnp.asarray(mask)[:, None, None, :], rel_bias,
        bidirectional=True, max_distance=cfg.rel_max_distance,
        scale=1.0, min_key_len=0, block_q=8, block_k=8, interpret=True,
    )
    assert got is not None

    pos = jnp.arange(L, dtype=jnp.int32)
    bias = t5._position_bias(rel_bias, pos, pos, True, cfg) \
        + t5._pad_bias(jnp.asarray(mask))
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) + bias
    probs = jax.nn.softmax(scores, axis=-1)
    want = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5
    )


def test_encode_flash_equals_dense(tmp_path, monkeypatch):
    """t5.encode with the kernel routed in (gate lowered for the test) must
    reproduce the dense encoder exactly — logits-level equivalence."""
    import importlib

    # The kernels package re-exports the flash_attention FUNCTION, which
    # shadows the submodule attribute — resolve the module itself.
    fa = importlib.import_module("agent_tpu.kernels.flash_attention")

    model = _torch_model()
    d = tmp_path / "flash_enc_ckpt"
    model.save_pretrained(str(d), safe_serialization=False)
    cfg, params = t5.load_hf_dir(str(d), dtype="float32")

    monkeypatch.setattr(fa, "FLASH_MIN_KEY_LEN", 8)
    rng = np.random.default_rng(4)
    src = rng.integers(2, cfg.vocab_size, (2, 16)).astype(np.int32)
    mask = np.ones((2, 16), dtype=np.int32)
    mask[0, 12:] = 0

    before = dict(fa.SELECTION_COUNTS)
    flash = np.asarray(t5.encode(params, src, mask, cfg, use_flash=True))
    assert fa.SELECTION_COUNTS.get("t5_flash", 0) > before.get("t5_flash", 0)
    dense = np.asarray(t5.encode(params, src, mask, cfg, use_flash=False))
    np.testing.assert_allclose(flash, dense, atol=3e-5)


def test_unsupported_feed_forward_proj_fails_loudly(tmp_path):
    """A checkpoint whose activation we can't honor must FAIL, not silently
    serve a different activation with ok=true (advisor r3, medium)."""
    import json

    cfg = dict(
        model_type="t5", vocab_size=32, d_model=8, d_kv=4, num_heads=2,
        num_layers=1, d_ff=16, feed_forward_proj="gelu",
    )
    p = tmp_path / "config.json"
    p.write_text(json.dumps(cfg))
    with pytest.raises(RuntimeError, match="feed_forward_proj"):
        t5.T5Config.from_hf_json(str(p))
    cfg["feed_forward_proj"] = "gated-silu"
    p.write_text(json.dumps(cfg))
    with pytest.raises(RuntimeError, match="feed_forward_proj"):
        t5.T5Config.from_hf_json(str(p))
    # The two supported values still load.
    cfg["feed_forward_proj"] = "gated-gelu"
    p.write_text(json.dumps(cfg))
    assert t5.T5Config.from_hf_json(str(p)).gated_ffn is True
    cfg["feed_forward_proj"] = "relu"
    p.write_text(json.dumps(cfg))
    assert t5.T5Config.from_hf_json(str(p)).gated_ffn is False


def test_encode_mesh_kernel_on_dp_tp_mesh(tmp_path, monkeypatch):
    """The mesh-aware T5 kernel wrapper (shard_map: batch over dp, heads
    over tp) routed through t5.encode — the PRODUCT wiring
    (``runtime.t5_attention_kernel()`` → ``map_summarize`` → ``generate``)
    — must equal the dense encoder and tick the t5_flash counter."""
    import importlib

    from jax.sharding import NamedSharding, PartitionSpec as P

    from agent_tpu.kernels.flash_attention import make_flash_attention_t5
    from agent_tpu.runtime.mesh import build_mesh

    fa = importlib.import_module("agent_tpu.kernels.flash_attention")
    model = _torch_model()
    d = tmp_path / "mesh_enc_ckpt"
    model.save_pretrained(str(d), safe_serialization=False)
    cfg, params = t5.load_hf_dir(str(d), dtype="float32")

    monkeypatch.setattr(fa, "FLASH_MIN_KEY_LEN", 8)
    mesh = build_mesh(jax.devices()[:8], {"dp": 4, "tp": 2})
    kernel = make_flash_attention_t5(mesh)

    rng = np.random.default_rng(5)
    src = rng.integers(2, cfg.vocab_size, (4, 16)).astype(np.int32)
    mask = np.ones((4, 16), dtype=np.int32)
    mask[0, 12:] = 0

    before = dict(fa.SELECTION_COUNTS)
    flash = np.asarray(t5.encode(params, src, mask, cfg, kernel=kernel))
    assert fa.SELECTION_COUNTS.get("t5_flash", 0) > before.get("t5_flash", 0)
    dense = np.asarray(t5.encode(params, src, mask, cfg, use_flash=False))
    np.testing.assert_allclose(flash, dense, atol=3e-5)

    # generate() threads the kernel through its encoder pass.
    before = dict(fa.SELECTION_COUNTS)
    toks_k, lens_k = t5.generate(params, src, mask, cfg, 4, kernel=kernel)
    assert fa.SELECTION_COUNTS.get("t5_flash", 0) > before.get("t5_flash", 0)
    toks_d, lens_d = t5.generate(params, src, mask, cfg, 4)
    np.testing.assert_array_equal(np.asarray(toks_k), np.asarray(toks_d))
    np.testing.assert_array_equal(np.asarray(lens_k), np.asarray(lens_d))


def test_beam4_generation_matches_transformers(tmp_path):
    """Beam decode through the T5 plumbing (no forced BOS/EOS — T5's
    natural ending, the finalize-normalization path) must be token-exact
    vs transformers, like the BART twin in tests/test_bart.py."""
    model = _torch_model()
    cfg, params = _import(model, tmp_path, "beam4")
    rng = np.random.default_rng(6)
    src = rng.integers(2, cfg.vocab_size, (3, 7)).astype(np.int32)
    mask = np.ones((3, 7), dtype=np.int32)
    mask[1, 5:] = 0
    for lp, T in ((1.0, 8), (2.0, 6)):
        with torch.no_grad():
            want = model.generate(
                input_ids=torch.tensor(src, dtype=torch.long),
                attention_mask=torch.tensor(mask, dtype=torch.long),
                max_new_tokens=T, num_beams=4, do_sample=False,
                min_length=0, length_penalty=lp, early_stopping=False,
                decoder_start_token_id=cfg.decoder_start_id,
            ).numpy()[:, 1:]
        toks, _ = jax.jit(
            lambda p, i, m, T=T, lp=lp: t5.generate(
                p, i, m, cfg, T, num_beams=4, length_penalty=lp
            )
        )(params, src, mask)
        toks = np.asarray(toks)
        n = min(want.shape[1], T)
        np.testing.assert_array_equal(toks[:, :n], want[:, :n])
