"""Scheduler subsystem (ISSUE 4): fifo bit-compatibility (model-based
property test), fair-share dispatch, placement, admission control,
deadlines, and the scheduler observability surface."""

import json
import random

import pytest

from agent_tpu.config import SchedConfig
from agent_tpu.controller.core import Controller
from agent_tpu.sched import AdmissionError, LeaseContext, make_scheduler
from agent_tpu.sched.fair import FairScheduler
from agent_tpu.sched.fifo import FifoScheduler


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def fair_controller(clock=None, **sched_kw):
    sched_kw.setdefault("policy", "fair")
    return Controller(
        clock=clock or FakeClock(), sched=SchedConfig(**sched_kw)
    )


# ---------------------------------------------------------------------------
# FIFO bit-compatibility: a verbatim reimplementation of the pre-scheduler
# controller's queue semantics, compared against the real controller under
# random interleavings of submit/lease/report/expire.
# ---------------------------------------------------------------------------

TRANSIENT_ERR = {"type": "SomeTransientError", "message": "x"}
PERMANENT_ERR = {"type": "ValueError", "message": "x"}


class ModelFifo:
    """The pre-PR controller's scheduling behavior, re-implemented exactly:
    inline FIFO scan, TTL expiry in job-insertion order, epoch fencing,
    terminal guard, classified retries with requeue delay."""

    def __init__(self, ttl=30.0, max_attempts=2, requeue_delay=0.0):
        self.ttl = ttl
        self.max_attempts = max_attempts
        self.requeue_delay = requeue_delay
        self.t = 0.0
        self.jobs = {}
        self.queue = []

    def submit(self, job_id, op, required_labels=None, after=()):
        self.jobs[job_id] = {
            "op": op, "state": "pending", "epoch": 0, "attempts": 0,
            "not_before": 0.0, "deadline": 0.0,
            "labels": dict(required_labels or {}), "after": tuple(after),
        }
        self.queue.append(job_id)

    def _labels_match(self, job, labels):
        from agent_tpu.controller.core import Controller as C

        class J:
            required_labels = job["labels"]

        return C._labels_match(J, labels or {})

    def sweep(self):
        for jid, job in self.jobs.items():
            if job["state"] == "leased" and self.t >= job["deadline"]:
                job["epoch"] += 1
                job["state"] = "pending"
                self.queue.append(jid)

    def lease(self, ops, labels, max_tasks):
        self.sweep()
        tasks, remaining = [], []
        for jid in self.queue:
            job = self.jobs[jid]
            if (
                len(tasks) < max(1, max_tasks)
                and job["state"] == "pending"
                and job["not_before"] <= self.t
                and (not ops or job["op"] in ops)
                and self._labels_match(job, labels)
                and all(
                    self.jobs[d]["state"] == "succeeded"
                    for d in job["after"] if d in self.jobs
                )
            ):
                job["state"] = "leased"
                job["deadline"] = self.t + self.ttl
                job["attempts"] += 1
                tasks.append((jid, job["epoch"]))
            else:
                remaining.append(jid)
        self.queue = remaining
        return tasks

    def report(self, job_id, epoch, status, error=None):
        from agent_tpu.utils.retry import PERMANENT, classify_error

        job = self.jobs.get(job_id)
        if job is None or epoch != job["epoch"]:
            return False
        if job["state"] in ("succeeded", "failed", "dead"):
            return False
        job["state"] = "succeeded" if status == "succeeded" else "failed"
        if job["state"] == "failed":
            if classify_error(error) == PERMANENT:
                pass
            elif job["attempts"] < self.max_attempts:
                job["state"] = "pending"
                job["epoch"] += 1
                job["not_before"] = self.t + self.requeue_delay
                self.queue.append(job_id)
            else:
                job["state"] = "dead"
        return True


def drive_interleaving(seed, n_ops=60, requeue_delay=0.0):
    """Random submit/lease/report/expire interleaving: the real controller
    (fifo policy) must grant the exact task sequence the pre-PR model
    grants, and land the same final states."""
    rng = random.Random(seed)
    clock = FakeClock()
    real = Controller(
        lease_ttl_sec=30.0, clock=clock, max_attempts=2,
        requeue_delay_sec=requeue_delay,
    )
    model = ModelFifo(ttl=30.0, max_attempts=2, requeue_delay=requeue_delay)
    ops_pool = ["echo", "map_tokenize", "map_classify_tpu"]
    label_pool = [None, {"zone": "eu"}, {"tpu": True}]
    submitted = []
    granted = []  # (job_id, epoch) in grant order, shared ground truth
    outstanding = []

    for i in range(n_ops):
        action = rng.choices(
            ["submit", "lease", "report", "advance", "sweep"],
            weights=[3, 4, 3, 1, 1],
        )[0]
        if action == "submit":
            jid = f"j{i}"
            op = rng.choice(ops_pool)
            req = rng.choice(label_pool)
            after = (
                (rng.choice(submitted),)
                if submitted and rng.random() < 0.2 else ()
            )
            real.submit(op, {"i": i}, job_id=jid,
                        required_labels=req, after=list(after))
            model.submit(jid, op, required_labels=req, after=after)
            submitted.append(jid)
        elif action == "lease":
            ops = set(rng.sample(ops_pool, k=rng.randint(0, 3)))
            labels = rng.choice(
                [{}, {"zone": "eu"}, {"zone": "us", "tpu": True},
                 {"tpu": True}]
            )
            n = rng.randint(1, 3)
            got = real.lease("a", {"ops": sorted(ops)} if ops else {},
                             max_tasks=n, labels=labels)
            real_tasks = [
                (t["id"], t["job_epoch"]) for t in (got or {}).get("tasks", [])
            ]
            model_tasks = model.lease(ops, labels, n)
            assert real_tasks == model_tasks, (
                f"seed {seed} step {i}: grant order diverged\n"
                f"  real  {real_tasks}\n  model {model_tasks}"
            )
            granted.extend(real_tasks)
            outstanding.extend(real_tasks)
        elif action == "report" and outstanding:
            jid, epoch = outstanding.pop(
                rng.randrange(len(outstanding))
            )
            status = rng.choice(["succeeded", "failed"])
            error = (
                rng.choice([TRANSIENT_ERR, PERMANENT_ERR])
                if status == "failed" else None
            )
            real.report("L", jid, epoch, status, error=error)
            model.report(jid, epoch, status, error=error)
        elif action == "advance":
            clock.t += rng.choice([5.0, 31.0])
            model.t = clock.t
        elif action == "sweep":
            real.sweep()
            model.sweep()

    # Drain whatever is left so final states compare meaningfully.
    for _ in range(len(submitted) * 3):
        got = real.lease("a", {}, max_tasks=3)
        model_tasks = model.lease(set(), {}, 3)
        real_tasks = [
            (t["id"], t["job_epoch"]) for t in (got or {}).get("tasks", [])
        ]
        assert real_tasks == model_tasks
        if not real_tasks:
            break
        for jid, epoch in real_tasks:
            real.report("L", jid, epoch, "succeeded", {})
            model.report(jid, epoch, "succeeded")
    for jid in submitted:
        assert real.job(jid).state == model.jobs[jid]["state"], (
            f"seed {seed}: {jid} ended "
            f"{real.job(jid).state} != {model.jobs[jid]['state']}"
        )
    return granted


class TestFifoBitCompat:
    @pytest.mark.parametrize("seed", range(20))
    def test_random_interleavings_match_pre_pr_model(self, seed):
        drive_interleaving(seed)

    def test_interleavings_with_requeue_delay(self):
        for seed in range(5):
            drive_interleaving(seed + 100, requeue_delay=2.0)

    def test_hypothesis_interleavings(self):
        """The same property under hypothesis-generated seeds/op-counts —
        broader search in CI; skipped where hypothesis isn't installed."""
        hyp = pytest.importorskip("hypothesis")
        st = pytest.importorskip("hypothesis.strategies")

        @hyp.settings(deadline=None, max_examples=30)
        @hyp.given(
            seed=st.integers(min_value=0, max_value=2**31),
            n_ops=st.integers(min_value=5, max_value=120),
        )
        def run(seed, n_ops):
            drive_interleaving(seed, n_ops=n_ops)

        run()

    def test_default_submit_journal_bytes_unchanged(self, tmp_path):
        """Journal schema vN+1 only appends the scheduling keys when the
        submitter set them: a default submission's record carries the exact
        key set the pre-scheduler controller wrote."""
        journal = str(tmp_path / "j.jsonl")
        c = Controller(journal_path=journal)
        c.submit("echo", {"x": 1}, job_id="plain")
        c.submit("echo", {"x": 2}, job_id="tagged",
                 priority=8, tenant="rt", deadline_sec=60.0)
        c.close()
        events = [json.loads(line) for line in open(journal)]
        plain = next(e for e in events if e["job_id"] == "plain")
        assert set(plain) == {
            "ev", "job_id", "op", "payload", "after", "required_labels",
            "max_attempts",
        }
        tagged = next(e for e in events if e["job_id"] == "tagged")
        assert tagged["priority"] == 8
        assert tagged["tenant"] == "rt"
        assert tagged["deadline_sec"] == 60.0


# ---------------------------------------------------------------------------
# Fair policy: priority tiers, tenant fair-share, determinism.
# ---------------------------------------------------------------------------

class TestFairDispatch:
    def test_priority_tier_wins(self):
        c = fair_controller()
        c.submit("echo", {}, job_id="low", priority=1)
        c.submit("echo", {}, job_id="high", priority=9)
        c.submit("echo", {}, job_id="mid", priority=5)
        order = []
        while True:
            lease = c.lease("a", {"ops": ["echo"]})
            if lease is None:
                break
            order.extend(t["id"] for t in lease["tasks"])
        assert order == ["high", "mid", "low"]

    def test_tenants_round_robin_within_tier(self):
        c = fair_controller()
        for i in range(3):
            c.submit("echo", {}, job_id=f"a{i}", tenant="A")
        for i in range(3):
            c.submit("echo", {}, job_id=f"b{i}", tenant="B")
        order = []
        for _ in range(6):
            lease = c.lease("w", {"ops": ["echo"]})
            order.append(lease["tasks"][0]["id"])
        # One tenant's backlog cannot run consecutively while the other
        # still has queued work: grants alternate A/B.
        assert order == ["a0", "b0", "a1", "b1", "a2", "b2"]

    def test_tenant_weights_skew_share(self):
        c = fair_controller(tenant_weights={"A": 2.0, "B": 1.0})
        for i in range(8):
            c.submit("echo", {}, job_id=f"a{i}", tenant="A")
            c.submit("echo", {}, job_id=f"b{i}", tenant="B")
        first9 = []
        for _ in range(9):
            lease = c.lease("w", {"ops": ["echo"]})
            first9.append(lease["tasks"][0]["id"])
        a_share = sum(1 for j in first9 if j.startswith("a"))
        assert a_share == 6  # 2:1 weighting → A drains 2 of every 3 grants

    def test_fifo_within_tenant_and_tier(self):
        c = fair_controller()
        for i in range(4):
            c.submit("echo", {}, job_id=f"j{i}", tenant="A", priority=5)
        lease = c.lease("w", {"ops": ["echo"]}, max_tasks=4)
        assert [t["id"] for t in lease["tasks"]] == ["j0", "j1", "j2", "j3"]

    def test_dispatch_is_deterministic(self):
        def run():
            c = fair_controller()
            rng = random.Random(42)
            for i in range(20):
                c.submit("echo", {}, job_id=f"j{i}",
                         tenant=rng.choice(["A", "B", "C"]),
                         priority=rng.choice([2, 5, 8]))
            order = []
            while True:
                lease = c.lease("w", {"ops": ["echo"]},
                                max_tasks=rng.choice([1, 2]))
                if lease is None:
                    break
                order.extend(t["id"] for t in lease["tasks"])
            return order
        assert run() == run()

    def test_dep_gated_job_does_not_block_tenant_queue(self):
        c = fair_controller()
        dep = c.submit("echo", {}, job_id="dep", tenant="A")
        c.submit("reduce", {}, job_id="gated", after=["dep"], tenant="A")
        c.submit("reduce", {}, job_id="free", tenant="A")
        # `gated` is ineligible (dep pending) but must not block `free`.
        lease = c.lease("w", {"ops": ["reduce"]})
        assert lease["tasks"][0]["id"] == "free"

    def test_priority_validation(self):
        c = fair_controller()
        with pytest.raises(ValueError):
            c.submit("echo", {}, priority=10)
        with pytest.raises(ValueError):
            c.submit("echo", {}, priority=-1)
        with pytest.raises(ValueError):
            c.submit("echo", {}, priority=True)
        with pytest.raises(ValueError):
            c.submit("echo", {}, tenant="")
        with pytest.raises(ValueError):
            c.submit("echo", {}, deadline_sec=0)
        assert c.counts() == {}  # nothing half-submitted

    def test_fair_order_survives_journal_replay(self, tmp_path):
        journal = str(tmp_path / "j.jsonl")
        c1 = Controller(journal_path=journal,
                        sched=SchedConfig(policy="fair"))
        c1.submit("echo", {}, job_id="low", priority=1, tenant="A")
        c1.submit("echo", {}, job_id="b0", tenant="B", priority=5)
        c1.submit("echo", {}, job_id="a0", tenant="A", priority=5)
        c1.submit("echo", {}, job_id="high", priority=9)
        c1.close()

        c2 = Controller(journal_path=journal,
                        sched=SchedConfig(policy="fair"))
        order = []
        while True:
            lease = c2.lease("w", {"ops": ["echo"]})
            if lease is None:
                break
            order.extend(t["id"] for t in lease["tasks"])
        # Priority tier first; B before A within tier 5 (arrival order of
        # tenants in the replayed journal).
        assert order == ["high", "b0", "a0", "low"]
        snap = c2.job_snapshot("high")
        assert snap["priority"] == 9 and snap["tenant"] == "default"
        c2.close()


# ---------------------------------------------------------------------------
# Placement: device preference, busy-agent avoidance, grant shrink.
# ---------------------------------------------------------------------------

class TestPlacement:
    def test_tpu_job_prefers_tpu_agent_with_bounded_patience(self):
        c = fair_controller(placement_patience=2)
        c.submit("map_classify_tpu", {}, job_id="tj")
        # A CPU agent is refused while patience lasts...
        caps_cpu = {"ops": ["map_classify_tpu"], "device_kind": "cpu",
                    "mesh_devices": 1, "queue_depth": 0}
        assert c.lease("cpu1", caps_cpu) is None
        assert c.lease("cpu1", caps_cpu) is None
        # ...then patience runs out: preference must never starve the job.
        lease = c.lease("cpu1", caps_cpu)
        assert lease is not None and lease["tasks"][0]["id"] == "tj"

    def test_tpu_agent_takes_tpu_job_immediately(self):
        c = fair_controller()
        c.submit("map_classify_tpu", {}, job_id="tj")
        caps_tpu = {"ops": ["map_classify_tpu"], "device_kind": "tpu",
                    "mesh_devices": 8, "queue_depth": 0}
        lease = c.lease("tpu1", caps_tpu)
        assert lease is not None and lease["tasks"][0]["id"] == "tj"

    def test_legacy_agent_without_device_fields_not_deferred(self):
        c = fair_controller()
        c.submit("map_classify_tpu", {}, job_id="tj")
        lease = c.lease("old", {"ops": ["map_classify_tpu"]})
        assert lease is not None  # unknown device never penalizes

    def test_bulk_shards_avoid_busy_agents(self):
        c = fair_controller(placement_patience=1, busy_queue_depth=2)
        shard_ids, _ = c.submit_csv_job("d.csv", total_rows=100,
                                        shard_size=100)
        busy = {"ops": ["read_csv_shard"], "queue_depth": 9}
        idle = {"ops": ["read_csv_shard"], "queue_depth": 0}
        assert c.lease("busy", busy) is None  # deferred once
        lease = c.lease("idle", idle)
        assert lease is not None and lease["tasks"][0]["id"] == shard_ids[0]

    def test_deep_queue_shrinks_grant(self):
        c = fair_controller(busy_queue_depth=2)
        for i in range(6):
            c.submit("echo", {}, job_id=f"j{i}")
        # An agent 4 past the busy threshold asking for 5 gets 1.
        lease = c.lease("deep", {"ops": ["echo"], "queue_depth": 6},
                        max_tasks=5)
        assert len(lease["tasks"]) == 1
        lease = c.lease("idle", {"ops": ["echo"], "queue_depth": 0},
                        max_tasks=5)
        assert len(lease["tasks"]) == 5

    def test_fifo_ignores_placement_fields(self):
        c = Controller()  # default fifo
        c.submit("map_classify_tpu", {}, job_id="tj")
        lease = c.lease("cpu1", {"ops": ["map_classify_tpu"],
                                 "device_kind": "cpu", "queue_depth": 99})
        assert lease is not None  # fifo: capability filter only


# ---------------------------------------------------------------------------
# Placement at scale (ISSUE 7): multiple agents leasing CONCURRENTLY from
# one in-process controller — the fleet-mode control-plane scenario.
# ---------------------------------------------------------------------------

class TestPlacementAtScale:
    N_SHARDS = 48

    def _fleet_agent(self, controller, name, depth_fn=None):
        """A real Agent over LoopbackSession running a slowed echo op, so
        thread interleaving actually happens between leases."""
        import time as _time

        from agent_tpu.agent.app import Agent
        from agent_tpu.chaos import LoopbackSession
        from agent_tpu.config import AgentConfig, Config

        agent = Agent(
            config=Config(agent=AgentConfig(
                controller_url="http://loopback", agent_name=name,
                tasks=("echo",), idle_sleep_sec=0.0,
            )),
            session=LoopbackSession(controller),
        )
        agent._profile = {"tier": "test"}

        def slow_echo(payload, ctx=None):
            _time.sleep(0.002)
            return {"ok": True, "echo": dict(payload or {})}

        agent.handlers = {"echo": slow_echo}
        if depth_fn is not None:
            agent.staged_depth_fn = depth_fn
        return agent

    def _drain_with_threads(self, controller, agents, deadline_sec=60.0):
        import threading
        import time as _time

        start = threading.Barrier(len(agents))

        def run(agent):
            start.wait()
            end = _time.monotonic() + deadline_sec
            while not controller.drained() and _time.monotonic() < end:
                agent.step()

        threads = [
            threading.Thread(target=run, args=(a,), daemon=True)
            for a in agents
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=deadline_sec + 10)
        assert controller.drained(), controller.counts()

    def test_two_concurrent_agents_share_the_drain_bit_identically(self):
        """Both members of a 2-agent fleet receive shards, and the drained
        results equal the single-agent drain's — order-insensitive (keyed
        by job id), agent-insensitive (same op, same payloads)."""
        def submit_all(c):
            ids = []
            for i in range(self.N_SHARDS):
                ids.append(c.submit(
                    "echo", {"x": i}, job_id=f"shard-{i}-fleettest"
                ))
            return ids

        def payload_part(result):
            # The payload-determined result, sans the per-run stamps
            # (duration_ms, trace lease/span ids) the agent loop adds.
            return {k: result[k] for k in ("ok", "echo")}

        # Reference: one agent drains everything.
        c_ref = Controller(sched=SchedConfig(policy="fair"),
                           lease_ttl_sec=600.0)
        ids = submit_all(c_ref)
        self._drain_with_threads(
            c_ref, [self._fleet_agent(c_ref, "solo")]
        )
        want = {j: payload_part(c_ref.job_snapshot(j)["result"])
                for j in ids}

        c = Controller(sched=SchedConfig(policy="fair"),
                       lease_ttl_sec=600.0)
        ids = submit_all(c)
        agents = [
            self._fleet_agent(c, "fleet-a"),
            self._fleet_agent(c, "fleet-b"),
        ]
        self._drain_with_threads(c, agents)
        got = {j: payload_part(c.job_snapshot(j)["result"]) for j in ids}
        assert got == want  # bit-identical, wherever each shard ran
        executed_by = {c.job_snapshot(j)["agent"] for j in ids}
        assert executed_by == {"fleet-a", "fleet-b"}, (
            f"shards did not spread across the fleet: {executed_by}"
        )

    def test_idle_member_preferred_over_backed_up_member(self):
        """The queue_depth-aware placement that spreads a fleet: a deep-
        staged member is deferred on bulk shards while an idle one takes
        them immediately (patience keeps it from starving)."""
        c = fair_controller(placement_patience=2, busy_queue_depth=2)
        for i in range(4):
            c.submit("echo", {"x": i}, job_id=f"shard-{i}-idlepref")
        busy_caps = {"ops": ["echo"], "queue_depth": 9}
        idle_caps = {"ops": ["echo"], "queue_depth": 0}
        assert c.lease("busy", busy_caps) is None  # deferred, not granted
        lease = c.lease("idle", idle_caps, max_tasks=4)
        assert lease is not None and len(lease["tasks"]) == 4

    def test_concurrent_agents_with_unequal_depth_both_finish(self):
        """Liveness under preference: even a permanently 'busy'-advertising
        member keeps working (patience bound), and the drain completes with
        every shard exactly once."""
        c = Controller(sched=SchedConfig(
            policy="fair", placement_patience=1, busy_queue_depth=2,
        ), lease_ttl_sec=600.0)
        ids = [
            c.submit("echo", {"x": i}, job_id=f"shard-{i}-unequal")
            for i in range(self.N_SHARDS)
        ]
        agents = [
            self._fleet_agent(c, "deep", depth_fn=lambda: 9),
            self._fleet_agent(c, "idle", depth_fn=lambda: 0),
        ]
        self._drain_with_threads(c, agents)
        by_agent: dict = {}
        for j in ids:
            snap = c.job_snapshot(j)
            assert snap["state"] == "succeeded"
            assert snap["attempts"] == 1  # exactly once, no re-leases
            by_agent[snap["agent"]] = by_agent.get(snap["agent"], 0) + 1
        # The idle-advertising member must carry work; the deep one may
        # still win deferred shards once patience expires.
        assert by_agent.get("idle", 0) > 0, by_agent


# ---------------------------------------------------------------------------
# Admission control: budgets → 429 + retry_after_ms, transient class.
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_global_budget(self):
        c = fair_controller(max_pending=2, retry_after_ms=500)
        c.submit("echo", {})
        c.submit("echo", {})
        with pytest.raises(AdmissionError) as ei:
            c.submit("echo", {})
        assert ei.value.retry_after_ms == 500
        assert ei.value.scope == "global"

    def test_per_tenant_budget_isolates_tenants(self):
        c = fair_controller(max_pending_per_tenant=1)
        c.submit("echo", {}, tenant="A")
        with pytest.raises(AdmissionError) as ei:
            c.submit("echo", {}, tenant="A")
        assert ei.value.scope == "tenant" and ei.value.tenant == "A"
        c.submit("echo", {}, tenant="B")  # other tenants unaffected

    def test_budget_frees_as_jobs_lease(self):
        c = fair_controller(max_pending=1)
        c.submit("echo", {})
        with pytest.raises(AdmissionError):
            c.submit("echo", {})
        c.lease("a", {"ops": ["echo"]})
        c.submit("echo", {})  # queue drained → admitted again

    def test_csv_batch_precheck_rejects_whole_job(self):
        c = fair_controller(max_pending=3)
        with pytest.raises(AdmissionError):
            c.submit_csv_job("d.csv", total_rows=400, shard_size=100)
        assert c.counts() == {}  # nothing half-submitted

    def test_http_429_with_retry_after_and_transient_class(self):
        import urllib.error
        import urllib.request

        from agent_tpu.controller.server import ControllerServer
        from agent_tpu.utils.retry import TRANSIENT, classify_http

        c = fair_controller(max_pending=1, retry_after_ms=750)
        with ControllerServer(c) as srv:
            def post(body):
                req = urllib.request.Request(
                    srv.url + "/v1/jobs", data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"})
                return urllib.request.urlopen(req)

            post({"op": "echo", "tenant": "A", "priority": 3})
            with pytest.raises(urllib.error.HTTPError) as ei:
                post({"op": "echo"})
            assert ei.value.code == 429
            body = json.loads(ei.value.read())
            assert body["retry_after_ms"] == 750
            assert ei.value.headers["Retry-After"] == "1"
            # The acceptance bar: an unmodified agent-side RetryPolicy
            # classifier treats the admission response as transient.
            assert classify_http(ei.value.code) == TRANSIENT

    def test_admission_metric_counted(self):
        c = fair_controller(max_pending=1)
        c.submit("echo", {}, tenant="A")
        with pytest.raises(AdmissionError):
            c.submit("echo", {}, tenant="A")
        snap = c.metrics.snapshot()
        series = snap["controller_admission_rejections_total"]["series"]
        assert series[0]["labels"] == {"tenant": "A"}
        assert series[0]["value"] == 1

    def test_unbounded_by_default(self):
        c = Controller()
        for i in range(100):
            c.submit("echo", {}, job_id=f"j{i}")
        assert c.queue_depth() == 100


# ---------------------------------------------------------------------------
# Deadlines: expiry → dead with DeadlineExceeded; escalation one tier.
# ---------------------------------------------------------------------------

class TestDeadlines:
    def test_expired_pending_job_lands_dead_with_reason(self):
        clock = FakeClock()
        c = fair_controller(clock=clock)
        jid = c.submit("echo", {}, deadline_sec=10.0)
        clock.t = 11.0
        c.sweep()
        job = c.job_snapshot(jid)
        assert job["state"] == "dead"
        assert job["error"]["type"] == "DeadlineExceeded"
        assert c.lease("a", {"ops": ["echo"]}) is None  # gone from queue
        assert c.drained()
        snap = c.metrics.snapshot()
        series = snap["controller_jobs_deadline_expired_total"]["series"]
        assert series[0]["value"] == 1

    def test_leased_job_gets_its_chance_past_deadline(self):
        clock = FakeClock()
        c = fair_controller(clock=clock)
        jid = c.submit("echo", {}, deadline_sec=10.0)
        lease = c.lease("a", {"ops": ["echo"]})
        clock.t = 11.0
        c.sweep()  # in-flight: not killed
        assert c.job(jid).state == "leased"
        out = c.report(lease["lease_id"], jid,
                       lease["tasks"][0]["job_epoch"], "succeeded", {})
        assert out["accepted"] is True

    def test_near_deadline_escalates_one_tier(self):
        clock = FakeClock()
        c = fair_controller(clock=clock, escalate_frac=0.75)
        c.submit("echo", {}, job_id="deadline", priority=5,
                 deadline_sec=100.0)
        c.submit("echo", {}, job_id="peer", priority=5)
        clock.t = 80.0  # past 75% of the deadline window
        c.sweep()
        assert c.job_snapshot("deadline")["priority"] == 6
        # Escalated tier now beats the same-tier peer submitted earlier.
        lease = c.lease("a", {"ops": ["echo"]})
        assert lease["tasks"][0]["id"] == "deadline"
        # One-shot: no further bumps.
        clock.t = 95.0
        c.sweep()
        assert c.job_snapshot("deadline")["priority"] == 6

    def test_deadline_dead_survives_journal_replay(self, tmp_path):
        journal = str(tmp_path / "j.jsonl")
        clock = FakeClock()
        c1 = Controller(clock=clock, journal_path=journal,
                        sched=SchedConfig(policy="fair"))
        jid = c1.submit("echo", {}, deadline_sec=5.0)
        clock.t = 6.0
        c1.sweep()
        assert c1.job(jid).state == "dead"
        c1.close()
        c2 = Controller(journal_path=journal,
                        sched=SchedConfig(policy="fair"))
        snap = c2.job_snapshot(jid)
        assert snap["state"] == "dead"
        assert snap["error"]["type"] == "DeadlineExceeded"
        c2.close()

    def test_fifo_also_enforces_deadlines(self):
        clock = FakeClock()
        c = Controller(clock=clock)  # fifo default
        jid = c.submit("echo", {}, deadline_sec=3.0)
        clock.t = 4.0
        c.sweep()
        assert c.job(jid).state == "dead"


# ---------------------------------------------------------------------------
# Observability: depth gauge split (satellite), per-tenant gauges,
# starvation histogram, decision counters.
# ---------------------------------------------------------------------------

def _gauge(snapshot, name, **labels):
    for s in snapshot.get(name, {}).get("series", []):
        if s["labels"] == labels:
            return s["value"]
    return None


class TestSchedObservability:
    def test_queue_depth_splits_held_from_leasable(self):
        """Regression (ISSUE 4 satellite): a requeue-delayed retry is NOT
        leasable and must be reported under state=held, not leasable."""
        clock = FakeClock()
        c = Controller(clock=clock, requeue_delay_sec=10.0, max_attempts=3)
        jid = c.submit("echo", {})
        c.submit("echo", {}, job_id="other")
        lease = c.lease("a", {"ops": ["echo"]})
        c.report(lease["lease_id"], jid, lease["tasks"][0]["job_epoch"],
                 "failed", error={"type": "X"})
        snap = c.metrics.snapshot()
        assert _gauge(snap, "controller_queue_depth", state="leasable") == 1
        assert _gauge(snap, "controller_queue_depth", state="held") == 1
        # The delay elapses → held flows back to leasable.
        clock.t = 11.0
        c.sweep()
        snap = c.metrics.snapshot()
        assert _gauge(snap, "controller_queue_depth", state="leasable") == 2
        assert _gauge(snap, "controller_queue_depth", state="held") == 0

    def test_per_tenant_depth_gauge_and_zeroing(self):
        c = fair_controller()
        c.submit("echo", {}, tenant="A")
        c.submit("echo", {}, tenant="A")
        c.submit("echo", {}, tenant="B")
        snap = c.metrics.snapshot()
        assert _gauge(snap, "sched_queue_depth", tenant="A") == 2
        assert _gauge(snap, "sched_queue_depth", tenant="B") == 1
        while c.lease("a", {"ops": ["echo"]}, max_tasks=3):
            pass
        snap = c.metrics.snapshot()
        assert _gauge(snap, "sched_queue_depth", tenant="A") == 0
        assert _gauge(snap, "sched_queue_depth", tenant="B") == 0

    def test_starvation_age_histogram_observes_first_lease(self):
        clock = FakeClock()
        c = fair_controller(clock=clock)
        c.submit("echo", {}, tenant="A")
        clock.t = 7.0
        c.lease("a", {"ops": ["echo"]})
        fam = c.metrics.snapshot()["sched_starvation_age_seconds"]
        (s,) = fam["series"]
        assert s["labels"] == {"tenant": "A"}
        assert s["count"] == 1 and s["sum"] == pytest.approx(7.0)

    def test_decision_counters(self):
        c = fair_controller(placement_patience=1)
        c.submit("map_classify_tpu", {}, job_id="tj")
        caps_cpu = {"ops": ["map_classify_tpu"], "device_kind": "cpu"}
        c.lease("cpu", caps_cpu)   # deferred once
        c.lease("cpu", caps_cpu)   # patience spent → leased
        snap = c.metrics.snapshot()
        series = {
            s["labels"]["decision"]: s["value"]
            for s in snap["sched_decisions_total"]["series"]
        }
        assert series["deferred_placement"] == 1
        assert series["leased"] == 1

    def test_sched_metrics_visible_over_http(self):
        import urllib.request

        from agent_tpu.controller.server import ControllerServer

        c = fair_controller()
        c.submit("echo", {}, tenant="rt", priority=9)
        with ControllerServer(c) as srv:
            with urllib.request.urlopen(srv.url + "/v1/metrics") as r:
                text = r.read().decode()
        assert 'sched_queue_depth{tenant="rt"}' in text


# ---------------------------------------------------------------------------
# Scheduler units (no controller).
# ---------------------------------------------------------------------------

class TestSchedulerUnits:
    def test_make_scheduler_policies(self):
        assert isinstance(
            make_scheduler(SchedConfig(policy="fifo")), FifoScheduler
        )
        assert isinstance(
            make_scheduler(SchedConfig(policy="fair")), FairScheduler
        )
        with pytest.raises(ValueError):
            make_scheduler(SchedConfig(policy="wat"))

    def test_depth_bookkeeping(self):
        class J:
            def __init__(self, jid, tenant="T", priority=5):
                self.job_id = jid
                self.tenant = tenant
                self.priority = priority
                self.op = "echo"
                self.required_labels = {}
                self.placement_defers = 0

        for sched in (FifoScheduler(), FairScheduler(SchedConfig())):
            a, b = J("a", "A"), J("b", "B")
            sched.add(a)
            sched.add(b)
            assert sched.total() == 2
            assert sched.depth_by_tenant() == {"A": 1, "B": 1}
            assert set(sched.queued_ids()) == {"a", "b"}
            assert sched.discard("a") is True
            assert sched.discard("a") is False
            assert sched.depth_by_tenant() == {"B": 1}
            got = sched.take(
                LeaseContext(limit=5), lambda j: True
            )
            assert [j.job_id for j in got] == ["b"]
            assert sched.total() == 0
