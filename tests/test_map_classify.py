"""map_classify_tpu on the 8-device virtual CPU mesh (SURVEY.md §4.3).

Covers the reference payload contract (reference ``ops/map_classify_tpu.py:31-90``
+ ``CONTRACT.md``): single flat ``input``, topk shape/ordering, degraded
fallback shape, plus the TPU-native batched upgrade.
"""

import numpy as np
import pytest

from agent_tpu.ops import get_op
from agent_tpu.runtime.context import OpContext
from agent_tpu.runtime.runtime import get_runtime


@pytest.fixture(scope="module")
def classify():
    return get_op("map_classify_tpu")


@pytest.fixture(scope="module")
def ctx():
    return OpContext(runtime=get_runtime())


def test_single_input_contract(classify, ctx):
    out = classify({"input": [1, 2, 3, 4, 5], "topk": 3}, ctx)
    assert out["ok"] is True
    assert out["op"] == "map_classify_tpu"
    assert "fallback" not in out
    assert len(out["topk"]) == 3
    for entry in out["topk"]:
        assert set(entry) == {"index", "score"}
    scores = [e["score"] for e in out["topk"]]
    assert scores == sorted(scores, reverse=True)
    assert out["elapsed_ms"] > 0


def test_deterministic_same_model_id(classify, ctx):
    a = classify({"input": [7, 8, 9], "topk": 5}, ctx)
    b = classify({"input": [7, 8, 9], "topk": 5}, ctx)
    assert a["topk"] == b["topk"]


def test_different_model_id_different_weights(classify, ctx):
    a = classify({"input": [7, 8, 9], "model_path": "model-a"}, ctx)
    b = classify({"input": [7, 8, 9], "model_path": "model-b"}, ctx)
    assert a["topk"] != b["topk"]


def test_batched_texts(classify, ctx):
    texts = [f"row {i} of the dataset" for i in range(13)]
    out = classify({"texts": texts, "topk": 2}, ctx)
    assert out["ok"] is True
    assert out["n_rows"] == 13
    assert len(out["results"]) == 13
    for r in out["results"]:
        assert len(r["topk"]) == 2


def test_batch_matches_single(classify, ctx):
    """Padding rows to the batch bucket must not change per-row results."""
    single = classify({"text": "hello world"}, ctx)
    batched = classify({"texts": ["hello world", "another row"]}, ctx)
    s = {e["index"]: e["score"] for e in single["topk"]}
    b = {e["index"]: e["score"] for e in batched["results"][0]["topk"]}
    assert set(s) == set(b)
    for i in s:
        assert np.isclose(s[i], b[i], rtol=1e-4)


def test_bad_input_soft_errors(classify, ctx):
    assert classify({"input": []}, ctx)["ok"] is False
    assert classify({"input": [1, "x"]}, ctx)["ok"] is False
    assert classify({"topk": 0, "input": [1]}, ctx)["ok"] is False
    assert classify({}, ctx)["ok"] is False
    assert classify("not a dict", ctx)["ok"] is False


def test_out_of_range_ids_rejected(classify, ctx):
    """Validate-and-reject like the reference's shape checks (ref :58-69) —
    no silent modulo wrap hiding caller bugs."""
    out = classify({"input": [0, 99999]}, ctx)
    assert out["ok"] is False and "out of range" in out["error"]
    assert classify({"input": [-1]}, ctx)["ok"] is False


class _BrokenRuntime:
    def require_runtime(self):
        raise RuntimeError("device wedged")


def test_fallback_retries_on_cpu(classify):
    """Device failure + allow_fallback → same program on CPU backend, with the
    reference's fallback/reason markers (ref ops/map_classify_tpu.py:84-90)."""
    out = classify({"input": [1, 2, 3]}, _BrokenRuntime())
    assert out["ok"] is True
    assert out["fallback"] == "cpu"
    assert "device wedged" in out["reason"]
    assert len(out["topk"]) == 5  # our fallback actually computes


def test_no_fallback_raises(classify):
    with pytest.raises(RuntimeError):
        classify({"input": [1, 2, 3], "allow_fallback": False}, _BrokenRuntime())


def test_executable_cache_reuse(classify, ctx):
    """Same shape bucket twice → second call hits the executable cache."""
    runtime = ctx.runtime
    before = runtime.cache.stats()
    classify({"input": [5] * 10, "model_path": "cache-test"}, ctx)
    mid = runtime.cache.stats()
    classify({"input": [6] * 11, "model_path": "cache-test"}, ctx)
    after = runtime.cache.stats()
    assert mid["misses"] == before["misses"] + 1
    assert after["misses"] == mid["misses"]
    assert after["hits"] == mid["hits"] + 1


def test_distinct_model_configs_do_not_alias_cache(classify, ctx):
    """Config-aware cache keys: a payload overriding model_config must not
    reuse weights/executables built for a different config."""
    small = {"d_model": 64, "n_heads": 4, "n_layers": 2, "d_ff": 128,
             "max_len": 64, "n_classes": 10}
    tiny = dict(small, n_classes=7)
    a = classify({"input": [1, 2, 3], "model_config": small, "topk": 50}, ctx)
    b = classify({"input": [1, 2, 3], "model_config": tiny, "topk": 50}, ctx)
    assert a["ok"] and b["ok"]
    assert a.get("fallback") is None and b.get("fallback") is None
    # topk is capped by n_classes → proves each ran under its own config.
    assert len(a["topk"]) == 10
    assert len(b["topk"]) == 7


def test_oversize_batch_chunks_instead_of_crashing(classify, ctx, monkeypatch):
    """Batches beyond the top batch bucket split into extra device calls."""
    import agent_tpu.ops.map_classify_tpu as mod

    monkeypatch.setattr(mod, "MAX_BATCH", 4)
    small = {"d_model": 32, "n_heads": 2, "n_layers": 1, "d_ff": 64,
             "max_len": 32, "n_classes": 5}
    texts = [f"row {i}" for i in range(11)]  # 11 > 2 chunks of 4 + 3
    out = classify(
        {"texts": texts, "model_config": small, "allow_fallback": False}, ctx
    )
    assert out["ok"] is True
    assert out["n_rows"] == 11
    assert len(out["results"]) == 11


def test_texts_wins_over_text_and_returns_all_rows(classify, ctx):
    small = {"d_model": 32, "n_heads": 2, "n_layers": 1, "d_ff": 64,
             "max_len": 32, "n_classes": 5}
    out = classify(
        {"texts": ["a", "b", "c"], "text": "a", "model_config": small}, ctx
    )
    assert out["ok"] is True
    assert len(out["results"]) == 3  # batch mode: nothing silently dropped


def test_classify_from_csv_shard(tmp_csv, classify, ctx):
    """source_uri shard addressing: the controller can shard a dataset
    straight into classify tasks (BASELINE 10M-row drain shape)."""
    out = classify({"source_uri": tmp_csv, "start_row": 2, "shard_size": 4,
                    "text_field": "text", "topk": 3}, ctx)
    assert out["ok"] is True and out["n_rows"] == 4
    assert len(out["results"]) == 4

    # Equivalent to passing the same texts directly.
    from agent_tpu.data.csv_index import read_shard

    texts = [r["text"] for r in read_shard(tmp_csv, 2, 4)]
    direct = classify({"texts": texts, "topk": 3}, ctx)
    assert [r["topk"] for r in out["results"]] == [
        r["topk"] for r in direct["results"]
    ]

    # Every shard-level problem must raise (agent reports FAILED, controller
    # retries then visibly marks failed) — a soft {ok: false} result would be
    # recorded as SUCCEEDED and the shard's rows silently vanish from a drain.
    import pytest as _pytest

    with _pytest.raises(RuntimeError):
        classify({"source_uri": tmp_csv, "text_field": "nope"}, ctx)
    with _pytest.raises(RuntimeError):
        classify({"source_uri": tmp_csv, "start_row": 10_000}, ctx)
    with _pytest.raises(OSError):
        classify({"source_uri": "/does/not/exist.csv"}, ctx)


def test_columnar_result_format(classify, ctx):
    rows = classify({"texts": ["col fmt %d" % i for i in range(6)],
                     "topk": 3}, ctx)
    col = classify({"texts": ["col fmt %d" % i for i in range(6)],
                    "topk": 3, "result_format": "columnar"}, ctx)
    assert col["ok"] and "results" not in col and "topk" not in col
    assert len(col["indices"]) == 6 and len(col["indices"][0]) == 3
    # Same ranking as the row format, scores within rounding.
    for r in range(6):
        want = rows["results"][r]["topk"]
        assert col["indices"][r] == [t["index"] for t in want]
        for s_got, t in zip(col["scores"][r], want):
            assert abs(s_got - t["score"]) < 1e-5
    bad = classify({"texts": ["x"], "result_format": "nope"}, ctx)
    assert bad["ok"] is False


def test_columnar_degraded_shape(classify):
    out = classify({"text": "x", "result_format": "columnar"},
                   _BrokenRuntime())
    # CPU retry succeeds here, so force total failure via broken model path:
    # instead just assert the happy fallback keeps columnar keys.
    assert out["ok"] is True and out["fallback"] == "cpu"
    assert "indices" in out and "topk" not in out


def test_deferred_fetch_contract(classify, ctx):
    """No-fallback mode: execute must return UNFETCHED device results
    (pending_dev) so the pipeline's poster thread pays the sync; fallback
    mode keeps the fetched arrays (the CPU-retry path needs them)."""
    from agent_tpu.ops import map_classify_tpu as op

    payload = {"texts": ["deferred row a", "deferred row b"], "topk": 2}

    phase, state = op.stage(dict(payload, allow_fallback=False), ctx)
    assert phase == "staged"
    state = op.execute(state, ctx)
    assert "pending_dev" in state and "vals" not in state
    out = op.finalize(state, ctx)
    assert out["ok"] is True and len(out["results"]) == 2
    assert ctx.tags["timings"]["fetch_ms"] >= 0

    phase, state = op.stage(dict(payload, allow_fallback=True), ctx)
    state = op.execute(state, ctx)
    assert "vals" in state and "pending_dev" not in state
    want = op.finalize(state, ctx)
    assert [e["index"] for e in want["topk"]] == \
        [e["index"] for e in out["topk"]]


def test_split_padded_chunk_unit(monkeypatch):
    """Dense-path dispatch splitting: budget respected, slices are batch
    buckets dividing the parent, real-row accounting exact, flash lengths
    and under-budget chunks untouched."""
    from agent_tpu.ops._model_common import split_padded_chunk

    ids = np.arange(64 * 128, dtype=np.uint16).reshape(64, 128)
    lengths = np.full(64, 100, dtype=np.int32)
    lengths[50:] = 0  # 50 real rows, 14 padding rows

    out = split_padded_chunk(ids, lengths, 50, dp=2)  # budget >> 64*128
    assert len(out) == 1 and out[0][2] == 50

    monkeypatch.setenv("TPU_CHUNK_TOKENS", str(16 * 128))  # 16-row slices
    out = split_padded_chunk(ids, lengths, 50, dp=2)
    assert [o[0].shape[0] for o in out] == [16, 16, 16, 16]
    assert [o[2] for o in out] == [16, 16, 16, 2]  # 50 real rows
    # Row content preserved in order.
    np.testing.assert_array_equal(np.concatenate([o[0] for o in out]), ids)

    # dp floor: even when dp alone exceeds the budget, slices stay dp.
    monkeypatch.setenv("TPU_CHUNK_TOKENS", "8")
    out = split_padded_chunk(ids, lengths, 50, dp=4)
    assert all(o[0].shape[0] == 4 for o in out)

    # Flash-path lengths are never split...
    monkeypatch.setenv("TPU_CHUNK_TOKENS", "128")
    big = np.zeros((8, 2048), dtype=np.uint16)
    out = split_padded_chunk(big, np.ones(8, np.int32), 8, dp=1)
    assert len(out) == 1
    # ...but a ≥2048 length the kernel would REJECT (not tile-divisible →
    # dense fallback) is treated as dense and split.
    odd = np.zeros((8, 3000), dtype=np.uint16)
    out = split_padded_chunk(odd, np.ones(8, np.int32), 8, dp=1)
    assert len(out) == 8  # budget 128 tokens → 1-row slices


def test_split_dispatch_results_align(classify, ctx, monkeypatch):
    """A payload that splits into several device slices must return the
    same per-row results as the unsplit dispatch (order and values).

    Index comparison is tie-aware: the two dispatch shapes compile to
    different XLA programs whose scores can differ in the last ULP, and
    top-k order between two *tied* classes then flips per environment —
    a real tie is not a misalignment, so a position may disagree only when
    both runs score it identically within the score tolerance."""
    texts = ["split alignment row %03d" % i for i in range(37)]
    payload = {"texts": texts, "topk": 3, "result_format": "columnar"}
    want = classify(dict(payload), ctx)
    monkeypatch.setenv("TPU_CHUNK_TOKENS", "512")  # force tiny slices
    got = classify(dict(payload), ctx)
    assert got["ok"] and want["ok"]
    # The split re-buckets batch AND sequence padding, so the two XLA
    # programs round differently at bf16 granularity (~1e-4 on softmax
    # scores); per-rank scores must stay inside that noise band.
    np.testing.assert_allclose(got["scores"], want["scores"], atol=1e-3)
    flips = total = 0
    for gi, wi in zip(got["indices"], want["indices"]):
        for g, w in zip(gi, wi):
            total += 1
            flips += g != w
    # Index order may flip only where two classes score within the noise
    # band (environment-dependent tiebreaks); the score bound above already
    # proves any flipped rank was a near-tie. A real row misalignment flips
    # nearly every position AND blows the score bound by orders of
    # magnitude — a handful of boundary flips is tie noise, not drift.
    assert flips <= max(2, total // 10), (
        f"{flips}/{total} top-k positions flipped — more than tie noise"
    )
