"""Multi-host wiring: single-process degradation must be exact no-ops.

Real multi-process collectives need a multi-host slice; what CI can prove is
that the single-process paths (the ones every test/bench run takes) degrade
cleanly: passthrough broadcasts, leader identity, follower loop that exits
immediately, and an agent whose behavior is unchanged.
"""

from agent_tpu.config import Config, DeviceConfig
from agent_tpu.runtime.distributed import (
    DistInfo,
    broadcast_task,
    is_shutdown,
    maybe_initialize,
)


def test_maybe_initialize_without_coordinator_is_single_process():
    info = maybe_initialize(None)
    assert info == DistInfo(process_index=0, process_count=1)
    assert info.is_leader


def test_broadcast_task_single_process_passthrough():
    task = {"op": "echo", "payload": {"x": [1, 2, 3]}}
    assert broadcast_task(task) is task
    assert broadcast_task(None) is None


def test_shutdown_sentinel():
    from agent_tpu.runtime.distributed import _SHUTDOWN

    assert is_shutdown(_SHUTDOWN)
    assert not is_shutdown(None)
    assert not is_shutdown({"op": "echo"})


def test_agent_dist_info_default_is_leader(monkeypatch):
    from agent_tpu.agent.app import Agent

    monkeypatch.setenv("TASKS", "echo")
    agent = Agent(config=Config.from_env(), session=object())
    info = agent._dist_info()
    assert info.process_count == 1 and info.is_leader


def test_follower_loop_exits_immediately_single_process(monkeypatch):
    """process_count == 1 → broadcast returns None → follower drains at once
    (it can only be entered by mis-configuration in that case)."""
    from agent_tpu.agent.app import Agent

    monkeypatch.setenv("TASKS", "echo")
    agent = Agent(config=Config.from_env(), session=object())
    agent.run_follower()
    assert agent.tasks_done == 0


def test_runtime_exposes_dist_info():
    from agent_tpu.runtime import TpuRuntime

    rt = TpuRuntime(DeviceConfig())
    assert rt.dist.process_count == 1 and rt.dist.is_leader
