"""HF-BERT family (``models/bert.py``): the imported checkpoint must
reproduce ``transformers``' reference outputs, and serve through the classify
op from a plain local checkpoint directory — the pretrained-weights
capability story (reference ``ops/_tpu_runtime.py:23-31``)."""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

import jax  # noqa: E402

from agent_tpu.models import bert  # noqa: E402

TINY = dict(
    vocab_size=120, hidden_size=32, num_hidden_layers=2,
    num_attention_heads=4, intermediate_size=64,
    max_position_embeddings=64, type_vocab_size=2, num_labels=4,
)


def _toy_vocab():
    words = [f"tok{i}" for i in range(80)]
    return ["[PAD]", "[UNK]", "[CLS]", "[SEP]"] + words + list("abcdefgh") \
        + ["##" + c for c in "abcdefgh"]


@pytest.fixture(scope="module")
def hf_dir(tmp_path_factory):
    """A real on-disk HF checkpoint (config.json + pytorch_model.bin +
    vocab.txt) built offline from a seeded random model."""
    d = tmp_path_factory.mktemp("bert_ckpt")
    torch.manual_seed(0)
    cfg = transformers.BertConfig(**TINY)
    model = transformers.BertForSequenceClassification(cfg).eval()
    model.save_pretrained(str(d), safe_serialization=False)
    vocab = _toy_vocab()
    (d / "vocab.txt").write_text("\n".join(vocab) + "\n")
    assert len(vocab) <= TINY["vocab_size"]
    return str(d), model


def test_forward_matches_transformers(hf_dir):
    path, torch_model = hf_dir
    cfg, params = bert.load_hf_dir(path, dtype="float32")
    assert cfg.num_labels == 4 and cfg.num_layers == 2

    rng = np.random.default_rng(0)
    ids = rng.integers(0, TINY["vocab_size"], (3, 10)).astype(np.int32)
    mask = np.ones((3, 10), dtype=np.int32)
    mask[1, 6:] = 0  # ragged row: padding must be excluded identically
    ids[1, 6:] = 0

    with torch.no_grad():
        want = torch_model(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        ).logits.numpy()
    got = np.asarray(
        jax.jit(lambda p, i, m: bert.forward(p, i, m, cfg))(params, ids, mask)
    )
    np.testing.assert_allclose(got, want, atol=3e-4)


def test_missing_head_gets_deterministic_init(hf_dir):
    path, torch_model = hf_dir
    sd = {k: v.numpy() for k, v in torch_model.bert.state_dict().items()}
    cfg, _ = bert.load_hf_dir(path, dtype="float32")
    a = bert.from_state_dict(dict(sd), cfg, head_seed="x")
    b = bert.from_state_dict(dict(sd), cfg, head_seed="x")
    c = bert.from_state_dict(dict(sd), cfg, head_seed="y")
    np.testing.assert_array_equal(np.asarray(a["head"]["w"]),
                                  np.asarray(b["head"]["w"]))
    assert not np.array_equal(np.asarray(a["head"]["w"]),
                              np.asarray(c["head"]["w"]))


def test_basic_normalize_matches_transformers():
    """Accent stripping + CJK spacing must match HF BasicTokenizer so
    'café' finds 'cafe' in the vocab instead of encoding as [UNK]."""
    from transformers.models.bert.tokenization_bert import BasicTokenizer

    basic = BasicTokenizer(do_lower_case=True)
    from agent_tpu.models.tokenizer import WordPieceTokenizer

    for text in ["Café résumé", "naïve Über",
                 "mixed 中文 text", "已只 ascii"]:
        want = basic.tokenize(text)
        tok = WordPieceTokenizer(
            vocab={w: i for i, w in enumerate(
                ["[PAD]", "[UNK]", "[CLS]", "[SEP]"] + want
            )},
            lowercase=True,
        )
        norm = bert.basic_normalize(text, strip_accents=True)
        ids = tok.encode(norm)
        inv = {i: w for w, i in tok.vocab.items()}
        got = [inv[i] for i in ids]
        assert got == want, (text, got, want)


def test_non_bert_checkpoint_dir_fails_loudly(tmp_path):
    d = tmp_path / "bart_dir"
    d.mkdir()
    (d / "config.json").write_text('{"model_type": "bart", "vocab_size": 8}')
    with pytest.raises(RuntimeError, match="not a BERT checkpoint"):
        bert.BertConfig.from_hf_json(str(d / "config.json"))


def test_wordpiece_encode_pad(hf_dir):
    path, _ = hf_dir
    tok = bert.hf_wordpiece(path)
    ids, lengths = bert.encode_pad_batch(
        tok, ["tok1 tok2 abc", "tok3"], 64, [8], [16, 32]
    )
    assert ids.shape == (8, 16)
    cls_id, sep_id = tok.vocab["[CLS]"], tok.vocab["[SEP]"]
    assert ids[0, 0] == cls_id and ids[0, lengths[0] - 1] == sep_id
    assert ids[1, 0] == cls_id and lengths[1] == 3  # [CLS] tok3 [SEP]
    assert (ids[2:] == tok.vocab["[PAD]"]).all()  # batch-bucket padding


def test_unk_id_remapped_to_checkpoint_vocab(hf_dir):
    path, _ = hf_dir
    tok = bert.hf_wordpiece(path)
    assert tok.unk_id == tok.vocab["[UNK]"]
    # OOV word (chars outside the toy alphabet) → the checkpoint's [UNK],
    # not whatever token sits at the class-default id 3 ([SEP] here!).
    ids = tok.encode("zzz")
    assert ids == [tok.vocab["[UNK]"]]


def test_head_override_mismatch_gets_seeded_head(hf_dir):
    """num_labels override ≠ checkpoint head → fresh seeded head of the
    requested size (a clamped top-k must never exceed the logits dim)."""
    path, torch_model = hf_dir
    cfg, params = bert.load_hf_dir(path, dtype="float32", num_labels=10)
    assert params["head"]["w"].shape == (cfg.hidden_size, 10)
    # And the checkpoint's own 4-label head is used when sizes agree.
    cfg4, params4 = bert.load_hf_dir(path, dtype="float32")
    np.testing.assert_array_equal(
        np.asarray(params4["head"]["w"]),
        torch_model.classifier.weight.detach().numpy().T,
    )


def test_corrupt_config_fails_hard_not_soft(tmp_path):
    """A garbled config.json must FAIL the shard (retryable), not soft-drop
    it as caller bad_input."""
    d = tmp_path / "broken_ckpt"
    d.mkdir()
    (d / "config.json").write_text('{"vocab_size": 12')  # truncated
    with pytest.raises(RuntimeError, match="unreadable checkpoint"):
        bert.BertConfig.from_hf_json(str(d / "config.json"))

    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext
    from agent_tpu.runtime.runtime import get_runtime

    with pytest.raises(RuntimeError, match="unreadable checkpoint"):
        get_op("map_classify_tpu")(
            {"texts": ["row"], "model_path": str(d), "allow_fallback": False},
            OpContext(runtime=get_runtime()),
        )


def test_bucket_truncation_keeps_sep(hf_dir):
    """Non-power-of-two max_position: bucket truncation must keep the
    trailing [SEP] (transformers semantics), not cut mid-sequence."""
    path, _ = hf_dir
    tok = bert.hf_wordpiece(path)
    long_text = " ".join(f"tok{i % 70}" for i in range(50))
    ids, lengths = bert.encode_pad_batch(
        tok, [long_text], 40, [1], [16, 40]
    )
    assert ids.shape[1] == 40 and lengths[0] == 40
    assert ids[0, 39] == tok.vocab["[SEP]"]


def test_serves_through_classify_op(hf_dir):
    from agent_tpu.ops import get_op
    from agent_tpu.runtime.context import OpContext
    from agent_tpu.runtime.runtime import get_runtime

    path, torch_model = hf_dir
    classify = get_op("map_classify_tpu")
    ctx = OpContext(runtime=get_runtime())
    out = classify(
        {
            "texts": ["tok1 tok2 abc", "tok5 tok6", "hd ae"],
            "topk": 4,
            "model_path": path,
            "model_config": {"dtype": "float32"},
            "allow_fallback": False,
        },
        ctx,
    )
    assert out["ok"] is True and out["model_path"] == path
    assert len(out["results"]) == 3
    # Cross-check row 0 against torch end to end (same tokenizer contract).
    tok = bert.hf_wordpiece(path)
    row = [tok.vocab["[CLS]"]] + tok.encode("tok1 tok2 abc") \
        + [tok.vocab["[SEP]"]]
    with torch.no_grad():
        logits = torch_model(
            input_ids=torch.tensor([row], dtype=torch.long),
            attention_mask=torch.ones(1, len(row), dtype=torch.long),
        ).logits.numpy()[0]
    want_order = list(np.argsort(-logits))
    got_order = [e["index"] for e in out["results"][0]["topk"]]
    assert got_order == want_order


def test_tp_sharded_bert_matches_replicated(hf_dir):
    """bert_param_specs on a tp mesh: sharded serving == replicated outputs."""
    from jax.sharding import NamedSharding

    from agent_tpu.parallel.shardings import bert_param_specs, sanitize_specs
    from agent_tpu.runtime.mesh import build_mesh

    path, _ = hf_dir
    cfg, params = bert.load_hf_dir(path, dtype="float32")
    mesh = build_mesh(jax.devices(), {"dp": 2, "tp": 4})
    specs = sanitize_specs(mesh, params, bert_param_specs(cfg))
    sharded = jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params, specs,
    )
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, (4, 8)).astype(np.int32)
    mask = np.ones((4, 8), dtype=np.int32)
    want = np.asarray(
        jax.jit(lambda p, i, m: bert.forward(p, i, m, cfg))(params, ids, mask)
    )
    got = np.asarray(
        jax.jit(lambda p, i, m: bert.forward(p, i, m, cfg))(sharded, ids, mask)
    )
    np.testing.assert_allclose(got, want, atol=1e-5)
