"""Controller core: lease issuance, expiry, epoch fencing, shard splitting,
fault injection (SURVEY.md §2.9, §5.3)."""

import pytest

from agent_tpu.controller.core import Controller


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_lease_respects_capabilities_and_max_tasks():
    c = Controller()
    c.submit("echo", {"x": 1})
    c.submit("map_tokenize", {"text": "hi"})
    c.submit("echo", {"x": 2})

    lease = c.lease("a1", {"ops": ["echo"]}, max_tasks=5)
    assert lease is not None
    assert [t["op"] for t in lease["tasks"]] == ["echo", "echo"]

    # Remaining job needs a different capability.
    assert c.lease("a1", {"ops": ["echo"]}) is None
    lease2 = c.lease("a2", {"ops": ["map_tokenize"]})
    assert len(lease2["tasks"]) == 1


def test_report_and_epoch_fencing():
    c = Controller()
    jid = c.submit("echo", {})
    lease = c.lease("a1", {"ops": ["echo"]})
    task = lease["tasks"][0]
    # Stale epoch rejected and counted.
    out = c.report(lease["lease_id"], jid, task["job_epoch"] + 1, "succeeded", {"ok": True})
    assert out["accepted"] is False
    assert c.stale_results == 1
    # Correct epoch accepted.
    out = c.report(lease["lease_id"], jid, task["job_epoch"], "succeeded", {"ok": True})
    assert out["accepted"] is True
    assert c.drained()


def test_lease_expiry_requeues_with_bumped_epoch():
    clock = FakeClock()
    c = Controller(lease_ttl_sec=30.0, clock=clock)
    jid = c.submit("echo", {})
    lease1 = c.lease("a1", {"ops": ["echo"]})
    epoch0 = lease1["tasks"][0]["job_epoch"]

    clock.t = 31.0  # lease expires
    lease2 = c.lease("a2", {"ops": ["echo"]})
    assert lease2 is not None
    assert lease2["tasks"][0]["job_epoch"] == epoch0 + 1

    # The dead agent's late result is fenced off.
    out = c.report(lease1["lease_id"], jid, epoch0, "succeeded", {"late": True})
    assert out["accepted"] is False and out["reason"] == "stale epoch"
    # The re-leased agent's result lands.
    out = c.report(lease2["lease_id"], jid, epoch0 + 1, "succeeded", {"ok": True})
    assert out["accepted"] is True


def test_csv_shard_splitting_and_gated_reduce():
    c = Controller()
    shard_ids, reduce_id = c.submit_csv_job(
        "file:///data.csv", total_rows=250, shard_size=100,
        reduce_op="risk_accumulate",
    )
    assert len(shard_ids) == 3
    # Last shard is the remainder.
    assert c.job(shard_ids[2]).payload["shard_size"] == 50
    assert c.job(shard_ids[2]).payload["start_row"] == 200

    # Reduce is gated until all shards succeed.
    lease = c.lease("a1", {"ops": ["risk_accumulate"]})
    assert lease is None
    for sid in shard_ids:
        lease = c.lease("a1", {"ops": ["read_csv_shard"]})
        task = lease["tasks"][0]
        c.report(lease["lease_id"], task["id"], task["job_epoch"], "succeeded", {})
    lease = c.lease("a1", {"ops": ["risk_accumulate"]})
    assert lease is not None and lease["tasks"][0]["id"] == reduce_id


def test_fault_injection_drop_duplicate_stale():
    c = Controller()
    c.submit("echo", {})
    c.inject("drop_lease")
    assert c.lease("a1", {"ops": ["echo"]}) is None  # dropped once
    c.inject("duplicate_task")
    lease = c.lease("a1", {"ops": ["echo"]})
    assert len(lease["tasks"]) == 2
    assert lease["tasks"][0]["id"] == lease["tasks"][1]["id"]
    t = lease["tasks"][0]
    assert c.report(lease["lease_id"], t["id"], t["job_epoch"], "succeeded", {})["accepted"]
    # Second (duplicate) completion does not overwrite the first.
    out = c.report(lease["lease_id"], t["id"], t["job_epoch"], "succeeded", {"dup": True})
    assert out["accepted"] is False

    jid = c.submit("echo", {})
    c.inject("stale_epoch")
    lease = c.lease("a1", {"ops": ["echo"]})
    t = lease["tasks"][0]
    out = c.report(lease["lease_id"], jid, t["job_epoch"], "succeeded", {})
    assert out["accepted"] is False and out["reason"] == "stale epoch"


def test_failed_job_retried_once():
    c = Controller()
    jid = c.submit("echo", {})
    lease = c.lease("a1", {"ops": ["echo"]})
    t = lease["tasks"][0]
    c.report(lease["lease_id"], jid, t["job_epoch"], "failed", error={"type": "X"})
    # Re-queued with bumped epoch for one retry.
    lease2 = c.lease("a1", {"ops": ["echo"]})
    assert lease2 is not None
    t2 = lease2["tasks"][0]
    assert t2["job_epoch"] == t["job_epoch"] + 1
    c.report(lease2["lease_id"], jid, t2["job_epoch"], "failed", error={"type": "X"})
    # Transient-class error + exhausted budget → terminal `dead` (ISSUE 3;
    # the pre-fault-tolerance controller stuck these `failed`).
    assert c.job(jid).state == "dead"
    assert c.drained()


def test_duplicate_job_id_rejected():
    c = Controller()
    c.submit("echo", {}, job_id="j1")
    with pytest.raises(ValueError):
        c.submit("echo", {}, job_id="j1")


def test_submit_csv_job_rejects_nonpositive_total_rows():
    import pytest as _pytest

    from agent_tpu.controller.core import Controller

    c = Controller()
    with _pytest.raises(ValueError):
        c.submit_csv_job("d.csv", total_rows=0, shard_size=100,
                         reduce_op="risk_accumulate")
    assert c.counts() == {}  # nothing half-submitted


class TestLabelScheduling:
    """required_labels gate leasing on the AGENT_LABELS channel the protocol
    has always carried (reference app.py:49-63,168) but never consumed."""

    def test_job_waits_for_matching_labels(self):
        from agent_tpu.controller.core import Controller

        c = Controller()
        c.submit("echo", {"x": 1}, required_labels={"zone": "eu", "tpu": True})
        # Wrong zone → nothing leased.
        assert c.lease("a1", {"ops": ["echo"]},
                       labels={"zone": "us", "tpu": True}) is None
        # Missing tpu label → nothing.
        assert c.lease("a2", {"ops": ["echo"]}, labels={"zone": "eu"}) is None
        # Bare-token truthy label satisfies a True requirement; zone matches.
        lease = c.lease("a3", {"ops": ["echo"]},
                        labels={"zone": "eu", "tpu": True})
        assert lease is not None and len(lease["tasks"]) == 1

    def test_unlabeled_jobs_lease_to_anyone(self):
        from agent_tpu.controller.core import Controller

        c = Controller()
        c.submit("echo", {})
        assert c.lease("a", {"ops": ["echo"]}) is not None

    def test_labels_flow_over_http(self):
        import json
        import urllib.request

        from agent_tpu.controller.server import ControllerServer

        with ControllerServer() as srv:
            def post(path, body):
                req = urllib.request.Request(
                    srv.url + path, data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"})
                resp = urllib.request.urlopen(req)
                raw = resp.read()
                return resp.status, (json.loads(raw) if raw else None)

            post("/v1/jobs", {"op": "echo", "payload": {},
                              "required_labels": {"pool": "batch"}})
            # 204 for a non-matching agent...
            status, _ = post("/v1/leases", {"agent": "x",
                                            "capabilities": {"ops": ["echo"]},
                                            "labels": {"pool": "realtime"}})
            assert status == 204
            # ...200 with the task for a matching one.
            status, body = post("/v1/leases", {"agent": "y",
                                               "capabilities": {"ops": ["echo"]},
                                               "labels": {"pool": "batch"}})
            assert status == 200 and len(body["tasks"]) == 1

    def test_falsy_advertised_value_does_not_satisfy_true_requirement(self):
        from agent_tpu.controller.core import Controller

        c = Controller()
        c.submit("echo", {}, required_labels={"tpu": True})
        assert c.lease("a", {"ops": ["echo"]}, labels={"tpu": False}) is None
        assert c.lease("b", {"ops": ["echo"]}, labels={"tpu": ""}) is None
        assert c.lease("c", {"ops": ["echo"]}, labels={"tpu": True}) is not None

    def test_numeric_requirement_matches_env_string_label(self):
        """AGENT_LABELS only produces strings; a JSON-number requirement must
        still match (string-coerced compare), not starve silently."""
        from agent_tpu.controller.core import Controller

        c = Controller()
        c.submit("echo", {}, required_labels={"mem_gb": 16})
        assert c.lease("a", {"ops": ["echo"]}, labels={"mem_gb": "16"}) is not None

    def test_float_requirement_matches_int_string_label(self):
        """{"mem_gb": 16.0} must match an agent advertising "16" — numeric
        requirements compare numerically, not via str() coercion."""
        from agent_tpu.controller.core import Controller

        c = Controller()
        c.submit("echo", {}, required_labels={"mem_gb": 16.0})
        assert c.lease("x", {"ops": ["echo"]}, labels={"mem_gb": "nope"}) is None
        assert c.lease("a", {"ops": ["echo"]}, labels={"mem_gb": "16"}) is not None

    def test_bare_flag_label_does_not_satisfy_numeric_requirement(self):
        """A bare token label parses to True; float(True) == 1.0 must not
        make it satisfy {"slots": 1}."""
        from agent_tpu.controller.core import Controller

        c = Controller()
        c.submit("echo", {}, required_labels={"slots": 1})
        assert c.lease("a", {"ops": ["echo"]}, labels={"slots": True}) is None
        assert c.lease("b", {"ops": ["echo"]}, labels={"slots": "1"}) is not None

    def test_after_rejects_unordered_set(self):
        """collect_partials relies on after order — sets are ambiguous."""
        import pytest as _pytest

        from agent_tpu.controller.core import Controller

        c = Controller()
        a = c.submit("echo", {})
        with _pytest.raises(ValueError, match="ordered"):
            c.submit("echo", {}, after={a})
        c.submit("echo", {}, after=[a])  # sequences stay fine

    def test_csv_job_carries_required_labels(self):
        from agent_tpu.controller.core import Controller

        c = Controller()
        shard_ids, reduce_id = c.submit_csv_job(
            "d.csv", total_rows=200, shard_size=100,
            reduce_op="risk_accumulate", required_labels={"zone": "eu"})
        assert c.lease("us", {"ops": ["read_csv_shard"]},
                       labels={"zone": "us"}) is None
        lease = c.lease("eu", {"ops": ["read_csv_shard"]},
                        labels={"zone": "eu"}, max_tasks=2)
        assert lease is not None and len(lease["tasks"]) == 2
        assert c.job(reduce_id).required_labels == {"zone": "eu"}

    def test_string_false_label_does_not_satisfy_true_requirement(self):
        """AGENT_LABELS='tpu=false' advertises the STRING 'false' — it must
        not satisfy a True requirement (env_bool-consistent truthiness)."""
        from agent_tpu.controller.core import Controller

        c = Controller()
        c.submit("echo", {}, required_labels={"tpu": True})
        assert c.lease("a", {"ops": ["echo"]}, labels={"tpu": "false"}) is None
        assert c.lease("b", {"ops": ["echo"]}, labels={"tpu": "0"}) is None
        assert c.lease("c", {"ops": ["echo"]}, labels={"tpu": "yes"}) is not None

    def test_non_scalar_required_labels_rejected_at_submit(self):
        import pytest as _pytest

        from agent_tpu.controller.core import Controller

        c = Controller()
        with _pytest.raises(ValueError):
            c.submit("echo", {}, required_labels={"zone": ["eu"]})
        with _pytest.raises(ValueError):
            c.submit("echo", {}, required_labels={"ok": False})
        assert c.counts() == {}


class TestCollectPartials:
    def test_partials_materialize_in_shard_order(self):
        """shard-10 must not precede shard-2 (lexicographic trap) — partials
        arrive in submission order."""
        from agent_tpu.controller.core import Controller

        c = Controller()
        shard_ids, reduce_id = c.submit_csv_job(
            "d.csv", total_rows=1200, shard_size=100,
            reduce_op="risk_accumulate", collect_partials=True)
        assert len(shard_ids) == 12
        # Complete every shard with a result tagging its index.
        for i, sid in enumerate(shard_ids):
            lease = c.lease("a", {"ops": ["read_csv_shard"]})
            for task in lease["tasks"]:
                c.report(lease["lease_id"], task["id"], task["job_epoch"],
                         "succeeded", result={"ok": True, "shard": None})
        for i, sid in enumerate(shard_ids):
            c._jobs[sid].result = {"ok": True, "shard": i}
        lease = c.lease("a", {"ops": ["risk_accumulate"]})
        (task,) = lease["tasks"]
        assert task["id"] == reduce_id
        assert [p["shard"] for p in task["payload"]["partials"]] == list(range(12))

    def test_failed_shard_partial_fails_reduce_loudly(self):
        import pytest as _pytest

        from agent_tpu.ops import get_op

        run = get_op("risk_accumulate")
        with _pytest.raises(RuntimeError) as ei:
            run({"partials": [{"ok": False, "error": "field must be a string"}]})
        assert "field must be a string" in str(ei.value)

    def test_bool_and_negative_counts_rejected(self):
        from agent_tpu.ops import get_op

        run = get_op("risk_accumulate")
        assert run({"partials": [{"count": True, "sum": 1.0, "min": 1.0,
                                  "max": 1.0}]})["ok"] is False
        assert run({"partials": [{"count": -5, "sum": 1.0, "min": 1.0,
                                  "max": 1.0}]})["ok"] is False


def test_status_endpoint_schema():
    """/v1/status schema pinned (ISSUE 2 satellite): stale_results, per-op
    state counts, queue depth, agents, and the structured summary ride next
    to the legacy counts/drained/last_metrics fields."""
    import json
    import urllib.request

    from agent_tpu.controller.server import ControllerServer

    c = Controller()
    with ControllerServer(c) as srv:
        c.submit("echo", {})
        c.submit("echo", {})
        c.submit("map_tokenize", {"text": "hi"})
        lease = c.lease("a1", {"ops": ["echo"]}, max_tasks=1,
                        metrics={"cpu_util": 0.1})
        t = lease["tasks"][0]
        # one stale post (counted), then the real one
        c.report(lease["lease_id"], t["id"], t["job_epoch"] + 7,
                 "succeeded", {})
        c.report(lease["lease_id"], t["id"], t["job_epoch"], "succeeded", {})

        with urllib.request.urlopen(srv.url + "/v1/status") as r:
            body = json.loads(r.read())

    assert set(body) == {
        "counts", "counts_by_op", "queue_depth", "drained", "stale_results",
        "agents", "summary", "journal", "serving", "last_metrics",
    }
    # ISSUE 15: the serving front-door block (request states, buckets,
    # in-flight batch jobs) — enabled by default.
    assert body["serving"]["enabled"] is True
    # ISSUE 14 satellite: the journal durability block — replay damage
    # (ISSUE 10) plus segment/snapshot/replay-cost numbers, one schema
    # whether or not a journal is configured (enabled=False here).
    assert set(body["journal"]) == {
        "torn_tail", "replay_skipped", "enabled", "segmented", "segments",
        "bytes", "snapshot_bytes", "snapshots_written",
        "last_snapshot_age_sec", "last_replay_sec", "replayed_events",
        "fsync", "promotions",
    }
    assert body["journal"]["torn_tail"] == 0
    assert body["journal"]["replay_skipped"] == 0
    assert body["journal"]["enabled"] is False
    assert body["journal"]["segments"] == 0
    assert body["agents"]["a1"]["draining"] is False
    assert body["counts"] == {"succeeded": 1, "pending": 2}
    assert body["counts_by_op"] == {
        "echo": {"succeeded": 1, "pending": 1},
        "map_tokenize": {"pending": 1},
    }
    assert body["queue_depth"] == 2
    assert body["stale_results"] == 1
    assert body["drained"] is False
    assert body["agents"]["a1"]["metrics"] == {"cpu_util": 0.1}
    assert body["agents"]["a1"]["last_seen_sec_ago"] >= 0
    assert body["summary"]["ops"]["echo"]["succeeded"] == 1
    assert "uptime_sec" in body["summary"]


def test_lease_attempt_rides_task_dict():
    """to_task carries the attempt counter — the trace field agents stamp
    into ctx.tags and result bodies."""
    c = Controller()
    jid = c.submit("echo", {})
    lease = c.lease("a1", {"ops": ["echo"]})
    assert lease["tasks"][0]["attempt"] == 1
    c.report(lease["lease_id"], jid, lease["tasks"][0]["job_epoch"],
             "failed", error={"type": "X"})
    lease2 = c.lease("a1", {"ops": ["echo"]})
    assert lease2["tasks"][0]["attempt"] == 2


def test_metrics_only_poll_leases_nothing():
    """max_tasks=0 records agent telemetry without handing out work — the
    drain-end flush channel."""
    c = Controller()
    c.submit("echo", {})
    assert c.lease("a1", {"ops": ["echo"]}, max_tasks=0,
                   metrics={"ram_mb": 1}) is None
    assert c.counts() == {"pending": 1}  # nothing leased
    assert c.agent_metrics["a1"]["metrics"] == {"ram_mb": 1}
    lease = c.lease("a1", {"ops": ["echo"]})  # a real poll still works
    assert lease is not None and len(lease["tasks"]) == 1


def test_http_job_result_retrieval():
    """Operators submit over HTTP — they must be able to fetch results the
    same way (GET /v1/jobs/<id>)."""
    import json
    import urllib.error
    import urllib.request

    from agent_tpu.controller.server import ControllerServer

    with ControllerServer() as srv:
        req = urllib.request.Request(
            srv.url + "/v1/jobs",
            data=json.dumps({"op": "echo", "payload": {"x": 7}}).encode(),
            headers={"Content-Type": "application/json"})
        job_id = json.loads(urllib.request.urlopen(req).read())["job_id"]

        with urllib.request.urlopen(srv.url + f"/v1/jobs/{job_id}") as r:
            body = json.loads(r.read())
        assert body["state"] == "pending" and body["op"] == "echo"

        # Complete it via the lease/report wire path, then fetch the result.
        lease = srv.controller.lease("a", {"ops": ["echo"]})
        (task,) = lease["tasks"]
        srv.controller.report(lease["lease_id"], task["id"],
                              task["job_epoch"], "succeeded",
                              result={"ok": True, "echo": {"x": 7}})
        with urllib.request.urlopen(srv.url + f"/v1/jobs/{job_id}") as r:
            body = json.loads(r.read())
        assert body["state"] == "succeeded"
        assert body["result"]["echo"] == {"x": 7}

        try:
            urllib.request.urlopen(srv.url + "/v1/jobs/nope")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as exc:
            assert exc.code == 404


class TestDrainProtocol:
    """ISSUE 10: the `released` handback and the `draining` agent mark."""

    def test_released_requeues_without_burning_the_attempt(self):
        c = Controller()
        jid = c.submit("echo", {})
        lease = c.lease("a1", {"ops": ["echo"]})
        task = lease["tasks"][0]
        out = c.report(lease["lease_id"], jid, task["job_epoch"], "released")
        assert out == {"accepted": True, "released": True}
        snap = c.job_snapshot(jid)
        # Instantly leasable again, epoch fenced, attempt given back.
        assert snap["state"] == "pending"
        assert snap["job_epoch"] == task["job_epoch"] + 1
        assert snap["attempts"] == 0
        # The stale duplicate of the released lease is fenced off.
        dup = c.report(
            lease["lease_id"], jid, task["job_epoch"], "succeeded", {"ok": 1}
        )
        assert dup["accepted"] is False
        # A fresh lease completes the job normally with a fresh attempt.
        lease2 = c.lease("a2", {"ops": ["echo"]})
        task2 = lease2["tasks"][0]
        assert task2["id"] == jid
        out = c.report(
            lease2["lease_id"], jid, task2["job_epoch"], "succeeded",
            {"ok": True},
        )
        assert out["accepted"] is True
        assert c.job_snapshot(jid)["attempts"] == 1

    def test_release_of_unleased_job_rejected(self):
        clock = FakeClock()
        c = Controller(lease_ttl_sec=5.0, clock=clock)
        jid = c.submit("echo", {})
        lease = c.lease("a1", {"ops": ["echo"]})
        epoch = lease["tasks"][0]["job_epoch"]
        # TTL expires first: the job re-queued at a bumped epoch, so the
        # late release is a stale epoch, counted not applied.
        clock.t += 10.0
        c.sweep()
        out = c.report(lease["lease_id"], jid, epoch, "released")
        assert out["accepted"] is False and out["reason"] == "stale epoch"
        # A release against a terminal job is a duplicate, not a requeue.
        lease2 = c.lease("a2", {"ops": ["echo"]})
        task2 = lease2["tasks"][0]
        c.report(lease2["lease_id"], jid, task2["job_epoch"], "succeeded",
                 {"ok": True})
        out = c.report(lease2["lease_id"], jid, task2["job_epoch"],
                       "released")
        assert out["accepted"] is False
        assert c.job_snapshot(jid)["state"] == "succeeded"

    def test_released_requeue_is_journaled_for_replay(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        c = Controller(journal_path=path)
        jid = c.submit("echo", {})
        lease = c.lease("a1", {"ops": ["echo"]})
        c.report(lease["lease_id"], jid, lease["tasks"][0]["job_epoch"],
                 "released")
        c.close()
        replayed = Controller(journal_path=path)
        snap = replayed.job_snapshot(jid)
        # The fence survived the restart: epoch 1, pending, re-queued.
        assert snap["state"] == "pending" and snap["job_epoch"] == 1
        assert replayed.queue_depth() == 1
        replayed.close()

    def test_draining_mark_sets_and_clears(self):
        c = Controller()
        c.lease("a1", {"ops": []}, max_tasks=0, metrics={"cpu_util": 0.1},
                draining=True)
        assert c.agents_summary()["a1"]["draining"] is True
        assert c.health_json()["agents"]["a1"]["draining"] is True
        # A fresh incarnation under the same name clears the mark.
        c.lease("a1", {"ops": []}, max_tasks=0, metrics={"cpu_util": 0.1})
        assert c.agents_summary()["a1"]["draining"] is False

    def test_draining_metrics_only_flush_still_ingests_telemetry(self):
        """The retiring agent's final metrics-only lease (satellite 4):
        nothing leases, the snapshot lands, the scheduler's queue is
        untouched, and a previously-unseen agent gets a minimal entry."""
        c = Controller()
        jid = c.submit("echo", {})
        out = c.lease(
            "drainer", {"ops": ["echo"]}, max_tasks=0,
            metrics={"obs": {"tasks_total": {
                "type": "counter", "help": "", "labels": ["op", "status"],
                "series": [{"labels": {"op": "echo", "status": "succeeded"},
                            "value": 3}],
            }}},
            draining=True,
        )
        assert out is None                      # metrics-only: no tasks
        assert c.queue_depth() == 1             # queue untouched
        assert c.agents_summary()["drainer"]["draining"] is True
        assert c.fleet_snapshot().get("tasks_total")  # snapshot ingested
        # draining flag alone (no metrics) also creates a minimal entry.
        out = c.lease("ghost", None, max_tasks=0, draining=True)
        assert out is None
        assert c.agents_summary()["ghost"]["draining"] is True
        # And the pending job still leases normally to a live agent.
        lease = c.lease("live", {"ops": ["echo"]})
        assert lease["tasks"][0]["id"] == jid


class TestJournalStatusCounters:
    """ISSUE 10 satellite: torn-final-line vs mid-file corruption counted
    distinctly AND operator-visible in /v1/status."""

    def test_torn_tail_and_skipped_visible_in_status(self, tmp_path):
        import json as _json
        import urllib.request

        from agent_tpu.controller.server import ControllerServer

        path = str(tmp_path / "journal.jsonl")
        c = Controller(journal_path=path)
        c.submit("echo", {}, job_id="j-keep")
        c.close()
        with open(path, "a", encoding="utf-8") as f:
            f.write('{"ev": "corrupt-mid\n')      # mid-file damage
            f.write('{"ev": "submit", "job_id": "j2", "op": "echo", '
                    '"payload": {}}\n')
            f.write('{"ev": "result", "job_id"')  # torn final write
        replayed = Controller(journal_path=path)
        assert replayed.journal_torn_tail == 1
        assert replayed.journal_replay_skipped == 1
        with ControllerServer(replayed) as srv:
            with urllib.request.urlopen(srv.url + "/v1/status") as r:
                body = _json.loads(r.read())
        assert body["journal"]["torn_tail"] == 1
        assert body["journal"]["replay_skipped"] == 1
        # ISSUE 14: the durability block rides alongside the damage
        # counters — a live journal reports its file-side numbers.
        assert body["journal"]["enabled"] is True
        assert body["journal"]["segments"] == 1
        assert body["journal"]["bytes"] > 0
        assert body["journal"]["replayed_events"] == 2  # j-keep + j2
        assert body["journal"]["last_replay_sec"] >= 0
        replayed.close()

    def test_clean_journal_reports_zero(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        c = Controller(journal_path=path)
        c.submit("echo", {})
        c.close()
        replayed = Controller(journal_path=path)
        assert replayed.journal_torn_tail == 0
        assert replayed.journal_replay_skipped == 0
        replayed.close()
