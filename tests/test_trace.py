"""Distributed tracing (ISSUE 5): span model, ring bounding, exporters,
controller-side assembly, compile-cost attribution, exemplar round-trip,
and the end-to-end acceptance path — a LoopbackSession drain yielding one
causally consistent span tree per job, served on ``GET /v1/trace``."""

import json
import urllib.request

import pytest

from agent_tpu.agent.app import Agent
from agent_tpu.chaos import LoopbackSession
from agent_tpu.config import AgentConfig, Config
from agent_tpu.controller.core import Controller
from agent_tpu.controller.server import ControllerServer
from agent_tpu.obs import trace as obs_trace
from agent_tpu.obs.metrics import (
    MetricsRegistry,
    parse_exemplars,
    parse_exposition,
    render_snapshots,
    validate_exposition,
)
from agent_tpu.obs.trace import (
    SpanBuffer,
    TraceContext,
    TraceStore,
    from_jsonl,
    make_span,
    new_span_id,
    phase_breakdown,
    to_chrome_trace,
    to_jsonl,
    use_context,
    validate_chrome_trace,
)
from agent_tpu.runtime.executor import ExecutableCache


@pytest.fixture(autouse=True)
def _tracing_on():
    """Pin tracing ON for every test here (host env must not flip it), and
    restore the env-driven default afterwards."""
    obs_trace.set_enabled(True)
    yield
    obs_trace.set_enabled(None)


def _span(trace_id="t1", span_id=None, parent=None, name="x", **kw):
    return make_span(
        name, trace_id, parent, span_id=span_id or new_span_id(),
        start_mono=0.0, duration_s=kw.pop("duration_s", 0.001), **kw,
    )


# ---- unit: buffer, store, exporters ----

class TestSpanBuffer:
    def test_ring_is_bounded_and_counts_drops(self):
        buf = SpanBuffer(capacity=8)
        for i in range(100):
            buf.add(_span(span_id=f"s{i}"))
        assert len(buf) == 8
        assert buf.dropped == 92
        assert [s["span_id"] for s in buf.spans()] == \
            [f"s{i}" for i in range(92, 100)]

    def test_drain_and_requeue(self):
        buf = SpanBuffer(capacity=8)
        buf.add(_span(span_id="a"))
        buf.add(_span(span_id="b"))
        taken = buf.drain()
        assert [s["span_id"] for s in taken] == ["a", "b"]
        assert len(buf) == 0
        buf.requeue(taken)  # failed ship puts them back
        assert len(buf) == 2

    def test_disabled_short_circuits(self):
        obs_trace.set_enabled(False)
        buf = SpanBuffer()
        buf.add(_span())
        assert len(buf) == 0

    def test_malformed_spans_rejected(self):
        buf = SpanBuffer()
        buf.add({"span_id": "x"})          # no trace_id
        buf.add({"trace_id": "t"})         # no span_id
        buf.add("not a span")
        assert len(buf) == 0


class TestTraceStore:
    def test_dedup_by_span_id(self):
        store = TraceStore()
        s = _span(span_id="dup")
        assert store.add(s)
        assert store.add(dict(s, name="updated"))
        spans = store.spans("t1")
        assert len(spans) == 1 and spans[0]["name"] == "updated"

    def test_trace_eviction_oldest_first(self):
        store = TraceStore(max_traces=3)
        for i in range(5):
            store.add(_span(trace_id=f"t{i}"))
        assert store.trace_ids() == ["t2", "t3", "t4"]
        assert store.dropped_traces == 2
        assert store.spans("t0") is None

    def test_span_cap_per_trace(self):
        store = TraceStore(max_spans_per_trace=4)
        for i in range(10):
            store.add(_span(span_id=f"s{i}"))
        assert len(store.spans("t1")) == 4
        assert store.dropped_spans == 6

    def test_open_finish_and_assembly(self):
        store = TraceStore()
        root = store.open("t1", "submit", start_clock=10.0)
        child = store.open("t1", "lease", parent_span_id=root,
                           start_clock=11.0)
        out = store.assemble("t1")
        assert out["root_span_id"] == root
        assert out["open_spans"] == sorted([root, child]) or \
            set(out["open_spans"]) == {root, child}
        assert not out["complete"]
        store.finish("t1", child, 12.5, attributes={"outcome": "succeeded"})
        store.finish("t1", root, 13.0)
        out = store.assemble("t1")
        assert out["complete"] and not out["orphans"]
        by_id = {s["span_id"]: s for s in out["spans"]}
        assert by_id[child]["duration_ms"] == pytest.approx(1500.0)
        assert by_id[child]["attributes"]["outcome"] == "succeeded"
        assert by_id[root]["duration_ms"] == pytest.approx(3000.0)

    def test_orphans_flagged(self):
        store = TraceStore()
        store.add(_span(span_id="root"))
        store.add(_span(span_id="kid", parent="root"))
        store.add(_span(span_id="lost", parent="never-existed"))
        out = store.assemble("t1")
        assert out["orphans"] == ["lost"]
        assert not out["complete"]

    def test_assemble_unknown_trace_is_none(self):
        assert TraceStore().assemble("nope") is None

    def test_disabled_store_is_noop(self):
        obs_trace.set_enabled(False)
        store = TraceStore()
        assert store.open("t1", "submit") is None
        assert not store.add(_span())


class TestSpanLinks:
    """Cross-trace span links (ISSUE 17): additive-only — a link-free span
    serializes byte-identically to the pre-links schema, and links never
    participate in parent/child assembly."""

    def test_link_free_wire_bytes_unchanged(self):
        wire = make_span("x", "t1", start_mono=0.0, duration_s=0.001)
        assert "links" not in wire
        assert "links" not in obs_trace.Span(
            trace_id="t1", span_id="s1", name="x"
        ).to_wire()

    def test_make_span_emits_links(self):
        link = obs_trace.span_link("other-trace", "s9", kind="serve_request")
        assert link == {
            "trace_id": "other-trace", "span_id": "s9",
            "attributes": {"kind": "serve_request"},
        }
        wire = make_span("x", "t1", start_mono=0.0, duration_s=0.001,
                         links=[link])
        assert wire["links"] == [link]

    def test_span_link_omits_empty_fields(self):
        assert obs_trace.span_link("t2") == {"trace_id": "t2"}

    def test_store_add_links_post_open_and_read_back(self):
        store = TraceStore()
        root = store.open("job-1", "submit", start_clock=0.0)
        assert store.links("job-1", root) == []
        store.add_links("job-1", root, [obs_trace.span_link("req-a", "s1")])
        store.add_links("job-1", root, [obs_trace.span_link("req-b")])
        assert store.links("job-1", root) == [
            {"trace_id": "req-a", "span_id": "s1"},
            {"trace_id": "req-b"},
        ]
        # Absent span / trace: silent no-op, empty read.
        store.add_links("job-1", "nope", [obs_trace.span_link("x")])
        store.add_links("no-trace", root, [obs_trace.span_link("x")])
        assert store.links("no-trace", root) == []

    def test_links_do_not_affect_assembly(self):
        store = TraceStore()
        root = store.open("t1", "root", start_clock=0.0)
        store.add_links(
            "t1", root, [obs_trace.span_link("elsewhere", "dangling")]
        )
        store.finish("t1", root, 1.0)
        out = store.assemble("t1")
        assert out["complete"] and not out["orphans"]
        (span,) = out["spans"]
        assert span["links"] == [
            {"trace_id": "elsewhere", "span_id": "dangling"}
        ]

    def test_links_survive_jsonl_round_trip(self):
        wire = make_span("x", "t1", start_mono=0.0, duration_s=0.001,
                         links=[obs_trace.span_link("t2", "s2")])
        (back,) = from_jsonl(to_jsonl([wire]))
        assert back["links"] == [{"trace_id": "t2", "span_id": "s2"}]


class TestExporters:
    def test_jsonl_round_trip(self):
        spans = [_span(span_id="a"), _span(span_id="b", parent="a")]
        back = from_jsonl(to_jsonl(spans))
        assert back == [json.loads(json.dumps(s)) for s in spans]

    def test_chrome_trace_schema_valid(self):
        spans = [
            _span(span_id="a", process="controller"),
            _span(span_id="b", parent="a", process="agent:w1"),
        ]
        ct = to_chrome_trace(spans)
        assert validate_chrome_trace(ct) == []
        xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
        ms = [e for e in ct["traceEvents"] if e["ph"] == "M"]
        # one pid per process + process_name metadata for each
        assert len(xs) == 2 and len(ms) == 2
        assert xs[0]["pid"] != xs[1]["pid"]
        assert all(e["dur"] >= 0 and e["ts"] > 0 for e in xs)
        assert xs[1]["args"]["parent_span_id"] == "a"

    def test_chrome_trace_open_span_exports_incomplete(self):
        store = TraceStore()
        store.open("t1", "submit", start_clock=0.0)
        ct = to_chrome_trace(store.spans("t1"))
        assert validate_chrome_trace(ct) == []
        (x,) = [e for e in ct["traceEvents"] if e["ph"] == "X"]
        assert x["dur"] == 0 and x["args"]["incomplete"] is True

    def test_validate_chrome_trace_catches_garbage(self):
        assert validate_chrome_trace([]) != []
        assert validate_chrome_trace({"traceEvents": "nope"}) != []
        assert validate_chrome_trace(
            {"traceEvents": [{"ph": "X", "name": "x", "pid": 1}]}
        ) != []  # missing ts/dur

    def test_phase_breakdown_line(self):
        store = TraceStore()
        root = store.open("job-1", "submit", start_clock=0.0)
        store.add(_span(trace_id="job-1", parent=root, name="execute",
                        duration_s=0.2))
        store.finish("job-1", root, 0.5)
        line = phase_breakdown(store.assemble("job-1"))
        assert "job-1" in line and "execute 200.0ms" in line
        assert "total 500.0ms" in line


class TestCompileAttribution:
    def test_cache_miss_emits_span_and_counters(self):
        buf = SpanBuffer()
        reg = MetricsRegistry()
        cache = ExecutableCache()
        ctx = TraceContext(trace_id="job-c", parent_span_id="exec-span",
                           tracer=buf, registry=reg, process="agent:t")
        with use_context(ctx):
            cache.get_or_build(("my_op", 8, 128, "f32"), lambda: object())
            cache.get_or_build(("my_op", 8, 128, "f32"), lambda: object())
        (span,) = buf.spans()
        assert span["name"] == "xla.compile"
        assert span["trace_id"] == "job-c"
        assert span["parent_span_id"] == "exec-span"
        assert span["attributes"]["op"] == "my_op"
        assert span["attributes"]["shape_key"] == "8,128,f32"
        assert reg.counter(
            "runtime_compile_seconds_total", "", ("op",)
        ).value(op="my_op") >= 0.0
        hits = reg.counter("runtime_compile_cache_total", "",
                           ("op", "outcome"))
        assert hits.value(op="my_op", outcome="miss") == 1
        assert hits.value(op="my_op", outcome="hit") == 1

    def test_params_cache_stays_out_of_compile_series(self):
        buf = SpanBuffer()
        reg = MetricsRegistry()
        cache = ExecutableCache(trace_label=None)
        with use_context(TraceContext(trace_id="j", tracer=buf, registry=reg)):
            cache.get_or_build(("params", "m1", "rep"), lambda: object())
        assert len(buf) == 0
        assert "runtime_compile_seconds_total" not in reg.snapshot()

    def test_disabled_tracing_skips_span_keeps_counter(self):
        obs_trace.set_enabled(False)
        buf = SpanBuffer()
        reg = MetricsRegistry()
        cache = ExecutableCache()
        with use_context(TraceContext(trace_id="j", tracer=buf, registry=reg)):
            cache.get_or_build(("op2", 1), lambda: object())
        assert len(buf) == 0  # span skipped
        assert reg.counter(  # compile cost still counted — it's a metric
            "runtime_compile_seconds_total", "", ("op",)
        ).value(op="op2") >= 0.0


class TestExemplars:
    def test_render_parse_round_trip(self):
        r = MetricsRegistry()
        h = r.histogram("task_phase_seconds", "p", ("op", "phase"),
                        buckets=(0.1, 1.0))
        h.observe(0.05, exemplar={"trace_id": "job-x"},
                  op="echo", phase="execute")
        h.observe(5.0, exemplar={"trace_id": "job-y"},
                  op="echo", phase="execute")
        text = r.render()
        assert validate_exposition(text) == []
        ex = parse_exemplars(text)["task_phase_seconds_bucket"]
        got = {e[1]["trace_id"]: e[2] for e in ex}
        assert got == {"job-x": pytest.approx(0.05),
                       "job-y": pytest.approx(5.0)}
        # plain parsing still works on exemplar-carrying lines
        parsed = parse_exposition(text)
        assert any(lbl.get("le") == "0.1"
                   for lbl, _ in parsed["task_phase_seconds_bucket"])

    def test_exemplars_survive_fleet_merge_latest_wins(self):
        from agent_tpu.obs.metrics import merge_snapshots

        def snap(job, v):
            r = MetricsRegistry()
            r.histogram("h", "", ("op",), buckets=(1.0,)).observe(
                v, exemplar={"trace_id": job}, op="x")
            return r.snapshot()

        first, second = snap("job-old", 0.5), snap("job-new", 0.6)
        merged = merge_snapshots([first, second])
        (series,) = merged["h"]["series"]
        assert series["exemplars"]["0"]["labels"]["trace_id"] == "job-new"
        assert series["count"] == 2
        text = render_snapshots([(merged, {})])
        assert validate_exposition(text) == []
        assert 'trace_id="job-new"' in text

    def test_snapshot_without_exemplars_keeps_legacy_shape(self):
        r = MetricsRegistry()
        r.histogram("h", "", ("op",)).observe(0.1, op="x")
        (series,) = r.snapshot()["h"]["series"]
        assert set(series) == {"labels", "counts", "sum", "count"}


# ---- end-to-end: LoopbackSession drain → causal span tree ----

def _drain_serial(controller, n_steps=10, tasks=("echo",), max_tasks=2):
    cfg = Config(agent=AgentConfig(
        controller_url="http://loopback", agent_name="trace-agent",
        tasks=tasks, max_tasks=max_tasks, idle_sleep_sec=0.0,
    ))
    agent = Agent(config=cfg, session=LoopbackSession(controller))
    agent._profile = {"tier": "test"}
    agent.run(max_steps=n_steps)
    return agent


def test_loopback_drain_yields_causal_span_tree():
    """The acceptance path: submit → drained job has ONE root span with
    sched/lease children, and stage/execute/post parented to the lease —
    every parent id resolves, every span closed."""
    c = Controller()
    jids = [c.submit("echo", {"i": i}) for i in range(3)]
    _drain_serial(c)
    assert c.drained()
    for jid in jids:
        t = c.trace_json(jid)
        assert t is not None and t["complete"], t
        assert t["orphans"] == [] and t["open_spans"] == []
        by_name = {}
        for s in t["spans"]:
            by_name.setdefault(s["name"], []).append(s)
        for name in ("submit", "sched.decide", "lease", "stage",
                     "execute", "post", "apply"):
            assert name in by_name, (name, sorted(by_name))
        root = by_name["submit"][0]
        assert root["span_id"] == t["root_span_id"]
        assert root["parent_span_id"] is None
        lease = by_name["lease"][0]
        assert lease["parent_span_id"] == root["span_id"]
        assert by_name["sched.decide"][0]["parent_span_id"] == \
            root["span_id"]
        assert by_name["apply"][0]["parent_span_id"] == root["span_id"]
        for phase in ("stage", "execute", "post"):
            assert by_name[phase][0]["parent_span_id"] == lease["span_id"]
            assert by_name[phase][0]["process"] == "agent:trace-agent"
        # execute precedes post on the assembled (sorted) timeline
        names = [s["name"] for s in t["spans"]]
        assert names.index("execute") < names.index("post")


def test_retried_job_trace_shows_both_lease_windows():
    """A transient failure retries: the trace carries one lease span per
    attempt, both closed, and the root closes on the terminal state."""
    c = Controller(max_attempts=2)
    jid = c.submit("boom_transient", {})
    lease = c.lease("a1", {"ops": ["boom_transient"]})
    c.report(lease["lease_id"], jid, 0, "failed",
             error={"type": "RuntimeError", "message": "x", "trace": ""})
    lease2 = c.lease("a1", {"ops": ["boom_transient"]})
    task = lease2["tasks"][0]
    c.report(lease2["lease_id"], jid, task["job_epoch"], "succeeded",
             {"ok": True})
    t = c.trace_json(jid)
    leases = [s for s in t["spans"] if s["name"] == "lease"]
    assert len(leases) == 2
    assert [s["attributes"]["attempt"] for s in leases] == [1, 2]
    assert all(s["duration_ms"] is not None for s in leases)
    assert leases[0]["attributes"]["outcome"] == "pending"  # retried
    assert leases[1]["attributes"]["outcome"] == "succeeded"
    assert t["complete"]


def test_lease_expiry_closes_lease_span_as_expired():
    clock = {"t": 0.0}
    c = Controller(lease_ttl_sec=5.0, clock=lambda: clock["t"])
    jid = c.submit("echo", {})
    c.lease("a1", {"ops": ["echo"]})
    clock["t"] = 10.0
    c.sweep()
    t = c.trace_json(jid)
    (lease,) = [s for s in t["spans"] if s["name"] == "lease"]
    assert lease["attributes"]["outcome"] == "expired"
    # closed at the sweep that noticed the expiry (t=10), not the TTL edge
    assert lease["duration_ms"] == pytest.approx(10000.0)


def test_task_wire_carries_trace_context_only_when_enabled():
    c = Controller()
    c.submit("echo", {})
    lease = c.lease("a1", {"ops": ["echo"]})
    task = lease["tasks"][0]
    assert task["trace"]["trace_id"] == task["id"]
    assert isinstance(task["trace"]["span_id"], str)

    obs_trace.set_enabled(False)
    c2 = Controller()
    jid = c2.submit("echo", {})
    lease2 = c2.lease("a1", {"ops": ["echo"]})
    assert "trace" not in lease2["tasks"][0]
    c2.report(lease2["lease_id"], jid, 0, "succeeded", {"ok": True})
    assert c2.trace_json(jid) is None  # nothing recorded at all


def test_trace_disabled_drain_still_clean():
    """TRACE_ENABLED=0 short-circuit: the drain completes, no spans
    anywhere, result bodies carry no span ids."""
    obs_trace.set_enabled(False)
    c = Controller()
    jid = c.submit("echo", {"x": 1})
    agent = _drain_serial(c, n_steps=4)
    assert c.drained()
    assert len(agent.tracer) == 0
    assert c.trace_json(jid) is None
    assert c.traces_json() == []
    trace = c.job_snapshot(jid)["result"]["trace"]
    assert "span_id" not in trace  # ISSUE-2 triple intact, no span leak
    assert trace["job_id"] == jid


def test_fenced_result_spans_still_ingested():
    """A stale-epoch (fenced) result's agent spans still land on the
    timeline — the execution happened; only the application was refused."""
    c = Controller()
    c.inject("stale_epoch")
    jid = c.submit("echo", {})
    agent = _drain_serial(c, n_steps=1)
    # fenced: the bumped-epoch job is still leased; the result was rejected
    assert c.job_snapshot(jid)["state"] != "succeeded"
    assert c.stale_results == 1
    agent.push_metrics()  # ship any spans still buffered
    spans = c.traces.spans(jid) or []
    agent_spans = [s for s in spans if s["process"].startswith("agent:")]
    assert any(s["name"] == "execute" for s in agent_spans)


# ---- HTTP surface ----

def test_trace_endpoints_over_http():
    c = Controller()
    jid = c.submit("echo", {"i": 1})
    _drain_serial(c, n_steps=4)
    with ControllerServer(c) as server:
        with urllib.request.urlopen(f"{server.url}/v1/trace/{jid}") as r:
            body = json.load(r)
        assert body["trace_id"] == jid and body["complete"]

        with urllib.request.urlopen(
            f"{server.url}/v1/trace/{jid}?format=perfetto"
        ) as r:
            perfetto = json.load(r)
        assert validate_chrome_trace(perfetto) == []

        with urllib.request.urlopen(
            f"{server.url}/v1/trace/{jid}?format=jsonl"
        ) as r:
            spans = from_jsonl(r.read().decode())
        assert {s["span_id"] for s in spans} == \
            {s["span_id"] for s in body["spans"]}

        with urllib.request.urlopen(
            f"{server.url}/v1/traces?limit=5"
        ) as r:
            listing = json.load(r)["traces"]
        assert listing and listing[0]["trace_id"] == jid
        assert listing[0]["complete"] is True

        try:
            urllib.request.urlopen(f"{server.url}/v1/trace/unknown-job")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as exc:
            assert exc.code == 404


def test_debug_events_job_id_filter_and_seq():
    """ISSUE 5 satellites: events carry (ts, mono, seq) so dumps interleave
    deterministically, and /v1/debug/events?job_id= filters server-side."""
    c = Controller()
    jid = c.submit("echo", {"i": 1})
    c.submit("echo", {"i": 2})
    _drain_serial(c, n_steps=4)
    events = c.recorder.events()
    assert all(
        {"ts", "mono", "seq", "kind"} <= set(e) for e in events
    )
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    with ControllerServer(c) as server:
        with urllib.request.urlopen(
            f"{server.url}/v1/debug/events?job_id={jid}"
        ) as r:
            mine = json.load(r)["events"]
    assert mine and all(e.get("job_id") == jid for e in mine)
    assert {"submit", "lease", "result"} <= {e["kind"] for e in mine}


def test_exposition_carries_queue_wait_exemplars_end_to_end():
    c = Controller()
    jid = c.submit("echo", {})
    _drain_serial(c, n_steps=4)
    text = c.metrics_text()
    assert validate_exposition(text) == []
    ex = parse_exemplars(text)
    refs = {
        e[1].get("trace_id")
        for samples in ex.values() for e in samples
    }
    assert jid in refs
