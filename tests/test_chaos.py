"""Fault-tolerance layer tests (ISSUE 3): retry policy + classifier, the
result spool (redelivery, persistence, overflow), classified controller
retries (`failed` vs `dead`, per-job max_attempts, requeue delay), chaos
FaultPlan determinism, and plan-driven injection on both sides of the wire.
"""

import json
import random

import pytest

from agent_tpu.agent.app import Agent
from agent_tpu.agent.spool import ResultSpool
from agent_tpu.chaos import (
    ChaosSession,
    ChaosTransportError,
    FaultPlan,
    GatedSession,
    LoopbackSession,
)
from agent_tpu.config import AgentConfig, Config
from agent_tpu.controller.core import Controller
from agent_tpu.utils.retry import (
    PERMANENT,
    TRANSIENT,
    RetryPolicy,
    classify_error,
    classify_http,
    jittered,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def fast_config(**agent_kw):
    agent_kw.setdefault("controller_url", "http://loopback")
    agent_kw.setdefault("idle_sleep_sec", 0.0)
    agent_kw.setdefault("error_backoff_sec", 0.0)
    agent_kw.setdefault("retry_base_sec", 0.0)
    agent_kw.setdefault("retry_max_sec", 0.01)
    agent_kw.setdefault("tasks", ("echo",))
    return Config(agent=AgentConfig(**agent_kw))


def make_agent(controller, **agent_kw):
    agent = Agent(
        config=fast_config(**agent_kw), session=LoopbackSession(controller)
    )
    agent._profile = {"tier": "test"}
    return agent


def counter_value(registry, name, **labels):
    total = 0.0
    for s in registry.snapshot().get(name, {}).get("series", []):
        if all(s.get("labels", {}).get(k) == v for k, v in labels.items()):
            total += s.get("value", 0)
    return total


# ---- retry policy ----


class TestRetryPolicy:
    def test_backoff_bounded_and_capped(self):
        policy = RetryPolicy(base_sec=0.1, max_sec=2.0, multiplier=3.0)
        state = policy.start(rng=random.Random(0))
        prev = 0.1
        for _ in range(50):
            sleep = state.next_backoff()
            assert 0.1 <= sleep <= 2.0
            assert sleep <= max(0.1, prev * 3.0) + 1e-9
            prev = sleep

    def test_backoff_grows_from_base(self):
        """Decorrelated jitter reaches the cap region given enough failures
        (a flat sleep never would)."""
        policy = RetryPolicy(base_sec=0.1, max_sec=10.0)
        state = policy.start(rng=random.Random(1))
        sleeps = [state.next_backoff() for _ in range(30)]
        assert max(sleeps) > 1.0

    def test_reset_restarts_the_streak(self):
        policy = RetryPolicy(base_sec=0.1, max_sec=100.0)
        state = policy.start(rng=random.Random(2))
        for _ in range(20):
            state.next_backoff()
        state.reset()
        assert state.attempts == 0
        assert state.next_backoff() <= 0.1 * 3.0

    def test_deadline_expiry_uses_clock(self):
        clock = FakeClock()
        policy = RetryPolicy(base_sec=0.1, deadline_sec=5.0)
        state = policy.start(rng=random.Random(3), clock=clock)
        assert not state.expired()  # never before the first backoff
        state.next_backoff()
        assert not state.expired()
        clock.t = 5.0
        assert state.expired()
        state.reset()
        assert not state.expired()

    def test_zero_base_stays_zero(self):
        """Tests set error_backoff_sec=0 — the policy must not invent
        sleeps out of nothing."""
        state = RetryPolicy(base_sec=0.0, max_sec=1.0).start(
            rng=random.Random(4)
        )
        assert state.next_backoff() == 0.0

    def test_jittered_bounds(self):
        rng = random.Random(5)
        for _ in range(200):
            v = jittered(1.0, frac=0.25, rng=rng)
            assert 0.75 <= v <= 1.25
        assert jittered(0.0) == 0.0


class TestClassifier:
    @pytest.mark.parametrize("status,want", [
        (0, TRANSIENT),      # transport error sentinel
        (500, TRANSIENT), (503, TRANSIENT), (429, TRANSIENT),
        (400, PERMANENT), (404, PERMANENT), (422, PERMANENT),
        (200, TRANSIENT),    # not a failure class; callers gate on success
        (None, TRANSIENT), ("junk", TRANSIENT),
    ])
    def test_http(self, status, want):
        assert classify_http(status) == want

    @pytest.mark.parametrize("error,want", [
        ({"type": "UnknownOp"}, PERMANENT),
        ({"type": "ValueError"}, PERMANENT),
        ({"type": "OpError"}, PERMANENT),
        ({"type": "RuntimeError"}, TRANSIENT),
        ({"type": "OSError"}, TRANSIENT),
        ("UnknownOp", PERMANENT),
        (None, TRANSIENT), ({}, TRANSIENT),
    ])
    def test_error_types(self, error, want):
        assert classify_error(error) == want


# ---- result spool ----


class TestResultSpool:
    def test_put_head_pop_roundtrip(self):
        spool = ResultSpool(capacity=4)
        spool.put("L1", "j1", 0, "succeeded", result={"ok": True}, op="echo")
        assert len(spool) == 1
        body = ResultSpool.wire_body(spool.head())
        assert body == {
            "lease_id": "L1", "job_id": "j1", "job_epoch": 0,
            "status": "succeeded", "result": {"ok": True}, "error": None,
        }
        assert spool.pop_head()["op"] == "echo"
        assert len(spool) == 0 and spool.head() is None

    def test_overflow_evicts_oldest(self):
        spool = ResultSpool(capacity=2)
        assert spool.put("L", "j1", 0, "succeeded") is None
        assert spool.put("L", "j2", 0, "succeeded") is None
        evicted = spool.put("L", "j3", 0, "succeeded")
        assert evicted["job_id"] == "j1"
        assert [e["job_id"] for e in spool.entries()] == ["j2", "j3"]

    def test_disk_persistence_survives_restart(self, tmp_path):
        path = str(tmp_path / "spool.jsonl")
        s1 = ResultSpool(capacity=8, path=path)
        s1.put("L", "j1", 3, "succeeded", result={"rows": 5}, op="x")
        s1.put("L", "j2", 0, "failed", error={"type": "E"}, op="x")

        s2 = ResultSpool(capacity=8, path=path)
        assert [e["job_id"] for e in s2.entries()] == ["j1", "j2"]
        assert s2.head()["result"] == {"rows": 5}
        s2.pop_head()
        # The pop persisted: a third incarnation sees only j2.
        s3 = ResultSpool(capacity=8, path=path)
        assert [e["job_id"] for e in s3.entries()] == ["j2"]

    def test_torn_spool_line_skipped(self, tmp_path):
        path = tmp_path / "spool.jsonl"
        path.write_text(
            json.dumps({"job_id": "ok", "lease_id": "L"}) + "\n"
            + '{"job_id": "torn", "lease'
        )
        spool = ResultSpool(path=str(path))
        assert [e["job_id"] for e in spool.entries()] == ["ok"]
        assert spool.load_skipped == 1

    def test_age_of_head(self):
        clock = FakeClock()
        spool = ResultSpool(clock=clock)
        assert spool.age_of_head() == 0.0
        spool.put("L", "j", 0, "succeeded")
        clock.t = 4.0
        assert spool.age_of_head() == 4.0


class TestSpoolRedelivery:
    def test_outage_spools_then_redelivers_without_reexecution(self):
        """The headline scenario: controller down inside the lease window →
        the completed result spools, redelivers when it's back; the shard is
        never re-executed."""
        controller = Controller(lease_ttl_sec=60.0)
        jid = controller.submit("echo", {"x": 1})
        agent = make_agent(controller)
        gate = GatedSession(agent.session)
        agent.session = gate

        leased = agent.lease_once()
        lease_id, tasks = leased
        gate.down = True
        agent.run_task(lease_id, tasks[0])
        assert agent.tasks_done == 1
        assert len(agent.spool) == 1
        assert counter_value(
            agent.obs, "result_post_failures_total", op="echo") == 1
        assert controller.job(jid).state == "leased"  # nothing arrived

        gate.down = False
        assert agent.flush_spool(force=True) == 1
        assert len(agent.spool) == 0
        assert controller.job(jid).state == "succeeded"
        assert controller.job(jid).result["echo"] == {"x": 1}
        assert controller.job(jid).attempts == 1  # no re-execution
        assert counter_value(
            agent.obs, "result_redeliveries_total", outcome="delivered") == 1

    def test_flush_respects_backoff_window(self):
        controller = Controller()
        agent = make_agent(controller, retry_base_sec=30.0, retry_max_sec=60.0)
        gate = GatedSession(agent.session)
        agent.session = gate
        gate.down = True
        agent.spool.put("L", "j1", 0, "succeeded", op="echo")
        assert agent.flush_spool() == 0        # attempt fails → backoff armed
        tried = gate.rejected
        assert agent.flush_spool() == 0        # inside the window: no attempt
        assert gate.rejected == tried
        assert agent.flush_spool(force=True) == 0  # force bypasses the window
        assert gate.rejected == tried + 1

    def test_step_drains_spool_before_new_work(self):
        controller = Controller()
        j1 = controller.submit("echo", {"first": 1})
        agent = make_agent(controller)
        gate = GatedSession(agent.session)
        agent.session = gate

        leased = agent.lease_once()
        gate.down = True
        agent.run_task(leased[0], leased[1][0])
        assert len(agent.spool) == 1
        gate.down = False
        j2 = controller.submit("echo", {"second": 2})
        agent.step()  # flushes the spool, then leases + executes j2
        assert controller.job(j1).state == "succeeded"
        assert controller.job(j2).state == "succeeded"
        assert len(agent.spool) == 0

    def test_spooled_stale_result_drains_as_counted_noop(self):
        """Redelivery of a result whose lease TTL-expired mid-outage: the
        fence rejects it (HTTP 200, accepted=False) — the spool must treat
        that as delivered, not retry forever."""
        clock = FakeClock()
        controller = Controller(lease_ttl_sec=5.0, clock=clock)
        jid = controller.submit("echo", {})
        agent = make_agent(controller)
        gate = GatedSession(agent.session)
        agent.session = gate
        leased = agent.lease_once()
        gate.down = True
        agent.run_task(leased[0], leased[1][0])
        clock.t = 10.0
        controller.sweep()  # outage outlived the TTL: epoch fenced
        gate.down = False
        assert agent.flush_spool(force=True) == 1
        assert len(agent.spool) == 0
        assert controller.stale_results == 1
        assert controller.job(jid).state == "pending"  # re-queued, correct

    def test_overflow_and_expiry_counted(self):
        controller = Controller()
        agent = make_agent(controller, result_spool_max=1,
                           retry_deadline_sec=0.0)
        gate = GatedSession(agent.session)
        agent.session = gate
        gate.down = True
        agent.post_result("L", "j1", 0, "succeeded", result={}, op="echo")
        agent.post_result("L", "j2", 0, "succeeded", result={}, op="echo")
        assert len(agent.spool) == 1  # j1 evicted
        assert agent.spool.head()["job_id"] == "j2"
        assert counter_value(
            agent.obs, "result_redeliveries_total",
            outcome="dropped_overflow") == 1

    def test_controller_restart_accepts_spooled_result(self, tmp_path):
        """The tentpole scenario: the CONTROLLER restarts (journal replay)
        inside the lease window while the agent holds a completed, spooled
        result — redelivery to the new incarnation is accepted, so the
        finished shard is never re-executed."""
        journal = str(tmp_path / "controller.jsonl")
        c1 = Controller(lease_ttl_sec=60.0, journal_path=journal)
        jid = c1.submit("echo", {"x": 7})
        agent = make_agent(c1)
        gate = GatedSession(agent.session)
        agent.session = gate
        leased = agent.lease_once()
        gate.down = True
        agent.run_task(leased[0], leased[1][0])  # completes; post spools
        assert len(agent.spool) == 1
        c1.close()  # controller dies with the result undelivered

        c2 = Controller(lease_ttl_sec=60.0, journal_path=journal)
        agent.session = LoopbackSession(c2)  # new incarnation, back up
        assert agent.flush_spool(force=True) == 1
        job = c2.job_snapshot(jid)
        assert job["state"] == "succeeded"
        assert job["result"]["echo"] == {"x": 7}
        assert agent.tasks_done == 1  # executed exactly once, ever
        c2.close()

    def test_restart_rerace_applies_at_most_once(self, tmp_path):
        """If the restarted controller re-leased the job before the original
        agent's redelivery lands, first completion wins and the second is a
        counted duplicate — never applied twice."""
        journal = str(tmp_path / "controller.jsonl")
        c1 = Controller(lease_ttl_sec=60.0, journal_path=journal)
        jid = c1.submit("echo", {"x": 1})
        agent = make_agent(c1)
        gate = GatedSession(agent.session)
        agent.session = gate
        leased = agent.lease_once()
        gate.down = True
        agent.run_task(leased[0], leased[1][0])
        c1.close()

        c2 = Controller(lease_ttl_sec=60.0, journal_path=journal)
        # A second agent drains the re-queued job first.
        other = make_agent(c2)
        lease2 = other.lease_once()
        other.run_task(lease2[0], lease2[1][0])
        assert c2.job_snapshot(jid)["state"] == "succeeded"
        # The original redelivery is rejected by the terminal guard.
        agent.session = LoopbackSession(c2)
        assert agent.flush_spool(force=True) == 1  # delivered = decided
        assert counter_value(
            c2.metrics, "controller_results_total", outcome="duplicate") == 1
        assert counter_value(
            c2.metrics, "controller_results_total", outcome="succeeded") == 1
        c2.close()

    def test_agent_restart_redelivers_from_disk_spool(self, tmp_path):
        """RESULT_SPOOL_PATH: a crashed agent's undelivered results survive
        into the next incarnation and redeliver from there."""
        spool_path = str(tmp_path / "spool.jsonl")
        controller = Controller(lease_ttl_sec=60.0)
        jid = controller.submit("echo", {"x": 9})
        a1 = make_agent(controller, result_spool_path=spool_path)
        gate = GatedSession(a1.session)
        a1.session = gate
        leased = a1.lease_once()
        gate.down = True
        a1.run_task(leased[0], leased[1][0])
        assert len(a1.spool) == 1  # crash here: a1 is abandoned

        a2 = make_agent(controller, result_spool_path=spool_path)
        assert len(a2.spool) == 1  # loaded from disk
        assert a2.flush_spool(force=True) == 1
        assert controller.job(jid).state == "succeeded"
        assert controller.job(jid).attempts == 1


# ---- classified controller retries ----


class TestClassifiedRetries:
    def test_permanent_error_sticks_failed_immediately(self):
        c = Controller(max_attempts=5)
        jid = c.submit("nope", {})
        lease = c.lease("a", {"ops": ["nope"]})
        c.report(lease["lease_id"], jid, 0, "failed",
                 error={"type": "UnknownOp", "message": "no such op"})
        job = c.job(jid)
        assert job.state == "failed"
        assert job.attempts == 1  # no retry burned
        assert c.drained()
        assert counter_value(c.metrics, "controller_retries_total") == 0

    def test_transient_errors_retry_until_dead(self):
        c = Controller(max_attempts=3)
        jid = c.submit("echo", {})
        for attempt in range(3):
            lease = c.lease("a", {"ops": ["echo"]})
            assert lease is not None, f"attempt {attempt + 1} did not lease"
            c.report(lease["lease_id"], jid, lease["tasks"][0]["job_epoch"],
                     "failed", error={"type": "RuntimeError"})
        job = c.job(jid)
        assert job.state == "dead" and job.attempts == 3
        assert c.drained()
        assert counter_value(
            c.metrics, "controller_jobs_dead_total", op="echo") == 1
        assert counter_value(c.metrics, "controller_retries_total") == 2
        assert c.counts() == {"dead": 1}  # surfaced via /v1/status counts

    def test_per_job_max_attempts_overrides_default(self):
        c = Controller(max_attempts=2)
        jid = c.submit("echo", {}, max_attempts=1)
        lease = c.lease("a", {"ops": ["echo"]})
        c.report(lease["lease_id"], jid, lease["tasks"][0]["job_epoch"],
                 "failed", error={"type": "RuntimeError"})
        assert c.job(jid).state == "dead"  # no retry at all

    def test_submit_rejects_bad_max_attempts(self):
        c = Controller()
        for bad in (0, -1, True, 1.5, "3"):
            with pytest.raises(ValueError):
                c.submit("echo", {}, max_attempts=bad)

    def test_requeue_delay_prevents_hot_loop(self):
        clock = FakeClock()
        c = Controller(clock=clock, max_attempts=5, requeue_delay_sec=2.0)
        jid = c.submit("echo", {})
        lease = c.lease("a", {"ops": ["echo"]})
        c.report(lease["lease_id"], jid, 0, "failed",
                 error={"type": "RuntimeError"})
        assert c.job(jid).state == "pending"
        assert c.lease("a", {"ops": ["echo"]}) is None  # held back
        clock.t = 2.1
        assert c.lease("a", {"ops": ["echo"]}) is not None

    def test_max_attempts_honored_across_journal_replay(self, tmp_path):
        journal = str(tmp_path / "c.jsonl")
        c1 = Controller(journal_path=journal, max_attempts=2)
        jid = c1.submit("echo", {}, max_attempts=3)
        for _ in range(2):
            lease = c1.lease("a", {"ops": ["echo"]})
            c1.report(lease["lease_id"], jid, lease["tasks"][0]["job_epoch"],
                      "failed", error={"type": "RuntimeError"})
        assert c1.job(jid).state == "pending"  # 2 of 3 attempts burned
        c1.close()

        c2 = Controller(journal_path=journal, max_attempts=2)
        job = c2.job(jid)
        assert job.state == "pending" and job.attempts == 2
        assert job.max_attempts == 3  # the per-job budget replayed
        lease = c2.lease("a", {"ops": ["echo"]})
        c2.report(lease["lease_id"], jid, lease["tasks"][0]["job_epoch"],
                  "failed", error={"type": "RuntimeError"})
        assert c2.job(jid).state == "dead"
        c2.close()

    def test_dead_state_survives_restart(self, tmp_path):
        journal = str(tmp_path / "c.jsonl")
        c1 = Controller(journal_path=journal, max_attempts=1)
        jid = c1.submit("echo", {})
        lease = c1.lease("a", {"ops": ["echo"]})
        c1.report(lease["lease_id"], jid, 0, "failed",
                  error={"type": "RuntimeError"})
        assert c1.job(jid).state == "dead"
        c1.close()
        c2 = Controller(journal_path=journal, max_attempts=1)
        assert c2.job(jid).state == "dead"  # terminal: not re-queued
        assert c2.lease("a", {"ops": ["echo"]}) is None
        c2.close()

    def test_duplicate_success_after_dead_is_rejected(self):
        c = Controller(max_attempts=1)
        jid = c.submit("echo", {})
        lease = c.lease("a", {"ops": ["echo"]})
        epoch = lease["tasks"][0]["job_epoch"]
        c.report(lease["lease_id"], jid, epoch, "failed",
                 error={"type": "RuntimeError"})
        out = c.report(lease["lease_id"], jid, epoch, "succeeded", {"late": 1})
        assert out["accepted"] is False
        assert c.job(jid).state == "dead"


# ---- chaos plan + sessions ----


class TestFaultPlan:
    def test_same_seed_same_decisions(self):
        kinds = ["drop_request", "http_500", "drop_response"] * 40
        p1 = FaultPlan(seed=42, drop_request=0.3, http_500=0.2,
                       drop_response=0.1)
        p2 = FaultPlan(seed=42, drop_request=0.3, http_500=0.2,
                       drop_response=0.1)
        seq1 = [p1.decide(k) for k in kinds]
        seq2 = [p2.decide(k) for k in kinds]
        assert seq1 == seq2
        assert p1.counts == p2.counts
        assert any(seq1)  # the plan actually fires at these rates

    def test_different_seed_diverges(self):
        kinds = ["drop_request"] * 200
        p1 = FaultPlan(seed=1, drop_request=0.5)
        p2 = FaultPlan(seed=2, drop_request=0.5)
        assert [p1.decide(k) for k in kinds] != [p2.decide(k) for k in kinds]

    def test_zero_probability_consumes_no_randomness(self):
        p1 = FaultPlan(seed=7, drop_request=0.5)
        p2 = FaultPlan(seed=7, drop_request=0.5, http_500=0.0)
        seq1 = [p1.decide("drop_request") for _ in range(50)]
        seq2 = []
        for _ in range(50):
            p2.decide("http_500")  # disabled: must not perturb the stream
            seq2.append(p2.decide("drop_request"))
        assert seq1 == seq2

    def test_counts_tally_hits(self):
        p = FaultPlan(seed=3, drop_request=1.0)
        for _ in range(5):
            assert p.decide("drop_request")
        assert p.counts == {"drop_request": 5}
        assert p.total_injected() == 5


class TestChaosSession:
    def test_drop_request_never_reaches_controller(self):
        controller = Controller()
        controller.submit("echo", {})
        plan = FaultPlan(seed=0, drop_request=1.0)
        agent = make_agent(controller)
        agent.session = ChaosSession(LoopbackSession(controller), plan,
                                     registry=agent.obs)
        with pytest.raises(RuntimeError, match="transport"):
            agent.lease_once()
        assert controller.job(controller._queue[0]).state == "pending"
        assert counter_value(
            agent.obs, "chaos_faults_injected_total",
            fault="drop_request", path="leases") == 1

    def test_http_500_after_delivery_forces_fenced_redelivery(self):
        """The nastiest transport fault: the controller APPLIED the result
        but the agent was told 500 — redelivery must be a counted no-op."""
        controller = Controller()
        jid = controller.submit("echo", {"x": 1})
        plan = FaultPlan(seed=0, http_500=1.0)
        agent = make_agent(controller)
        chaos = ChaosSession(LoopbackSession(controller), plan,
                             registry=agent.obs)
        leased = agent.lease_once()  # plain session
        agent.session = chaos        # faults start now
        agent.run_task(leased[0], leased[1][0])
        assert controller.job(jid).state == "succeeded"  # was applied
        assert len(agent.spool) == 1                     # agent disagrees
        agent.session = LoopbackSession(controller)      # fault clears
        assert agent.flush_spool(force=True) == 1
        assert counter_value(
            controller.metrics, "controller_results_total",
            outcome="duplicate") == 1
        assert counter_value(
            controller.metrics, "controller_results_total",
            outcome="succeeded") == 1  # applied exactly once

    def test_duplicate_result_applied_once(self):
        controller = Controller()
        jid = controller.submit("echo", {})
        plan = FaultPlan(seed=0, duplicate_result=1.0)
        agent = make_agent(controller)
        leased = agent.lease_once()
        agent.session = ChaosSession(LoopbackSession(controller), plan,
                                     registry=agent.obs)
        agent.run_task(leased[0], leased[1][0])
        assert controller.job(jid).state == "succeeded"
        assert counter_value(
            controller.metrics, "controller_results_total",
            outcome="duplicate") == 1
        assert len(agent.spool) == 0  # first response was the success

    def test_controller_plan_injection(self):
        controller = Controller()
        plan = FaultPlan(seed=0, drop_lease=1.0)
        controller.inject(plan=plan)
        controller.submit("echo", {})
        assert controller.lease("a", {"ops": ["echo"]}) is None
        assert counter_value(
            controller.metrics, "controller_faults_injected_total",
            fault="drop_lease") == 1
        controller.inject(plan=None)  # cleared
        assert controller.lease("a", {"ops": ["echo"]}) is not None

    def test_controller_plan_duplicate_task_and_stale_epoch(self):
        controller = Controller()
        controller.inject(plan=FaultPlan(seed=0, duplicate_task=1.0))
        controller.submit("echo", {}, job_id="dup")
        lease = controller.lease("a", {"ops": ["echo"]})
        assert [t["id"] for t in lease["tasks"]] == ["dup", "dup"]

        c2 = Controller()
        c2.inject(plan=FaultPlan(seed=0, stale_epoch=1.0))
        jid = c2.submit("echo", {})
        lease = c2.lease("a", {"ops": ["echo"]})
        out = c2.report(lease["lease_id"], jid,
                        lease["tasks"][0]["job_epoch"], "succeeded", {})
        assert out["accepted"] is False and out["reason"] == "stale epoch"


class TestPreemptionFaultKinds:
    """ISSUE 10: spot_reclaim / hard_kill join the seeded plan — same
    Bernoulli machinery, counted, and zero-probability kinds stay inert
    without consuming randomness (the cross-kind determinism guarantee)."""

    def test_seeded_counts_are_deterministic(self):
        a = FaultPlan(seed=13, spot_reclaim=0.5, hard_kill=0.25)
        b = FaultPlan(seed=13, spot_reclaim=0.5, hard_kill=0.25)
        seq_a = [(a.decide("spot_reclaim"), a.decide("hard_kill"))
                 for _ in range(200)]
        seq_b = [(b.decide("spot_reclaim"), b.decide("hard_kill"))
                 for _ in range(200)]
        assert seq_a == seq_b
        assert a.counts == b.counts
        assert a.counts.get("spot_reclaim", 0) > 0
        assert a.counts.get("hard_kill", 0) > 0

    def test_zero_probability_consumes_no_randomness(self):
        # Enabling the preemption kinds at p=0 must not perturb the draw
        # sequence of any other kind.
        ref = FaultPlan(seed=5, drop_request=0.5)
        mixed = FaultPlan(seed=5, drop_request=0.5,
                          spot_reclaim=0.0, hard_kill=0.0)
        for _ in range(100):
            assert mixed.decide("spot_reclaim") is False
            assert mixed.decide("hard_kill") is False
            assert mixed.decide("drop_request") == ref.decide("drop_request")
        assert "spot_reclaim" not in mixed.counts
