"""Pipeline parallelism (``parallel/pipeline.py``): the pp schedule must be a
pure re-scheduling of the block stack — identical numerics to the sequential
dense forward, for every (pp, dp, n_micro) the 8-device mesh can express."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agent_tpu.models import encoder
from agent_tpu.models.encoder import EncoderConfig
from agent_tpu.parallel.pipeline import (
    encoder_forward_pp,
    pipeline_blocks,
    stack_blocks,
    stage_blocks,
)
from agent_tpu.runtime.mesh import build_mesh

CFG = EncoderConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
    max_len=16, n_classes=8, dtype="float32",
)


def _batch(rng, b, l=16):
    ids = rng.integers(4, CFG.vocab_size, (b, l)).astype(np.int32)
    mask = np.ones((b, l), dtype=np.int32)
    # Ragged tail: masking must survive the pipeline untouched.
    mask[0, l // 2:] = 0
    return jnp.asarray(ids), jnp.asarray(mask)


@pytest.mark.parametrize(
    "mesh_shape,n_micro",
    [
        ({"pp": 4}, None),          # minimal schedule, pure pp
        ({"pp": 2}, 4),             # more microbatches than stages
        ({"dp": 2, "pp": 4}, None), # dp × pp composition
        ({"dp": 4, "pp": 2}, 2),
    ],
)
def test_pp_matches_dense_forward(mesh_shape, n_micro):
    mesh = build_mesh(jax.devices(), mesh_shape)
    params = encoder.init_params(CFG, model_id="pp-test")
    rng = np.random.default_rng(0)
    # build_mesh absorbs leftover devices into dp — read the built shape.
    dp = mesh.shape.get("dp", 1)
    ids, mask = _batch(rng, b=2 * (n_micro or mesh_shape["pp"]) * dp)

    want = encoder.forward(params, ids, mask, CFG)
    got = jax.jit(
        lambda p, i, m: encoder_forward_pp(
            p, i, m, CFG, mesh, n_micro=n_micro
        )
    )(params, ids, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pp_weights_are_actually_sharded():
    """Each device must hold only its stage's slice of the stacked blocks —
    the whole point of pp (a too-deep model split across chips)."""
    mesh = build_mesh(jax.devices(), {"pp": 4})
    params = encoder.init_params(CFG, model_id="pp-test")
    staged = stage_blocks(stack_blocks(params["blocks"]), 4)
    from jax.sharding import NamedSharding, PartitionSpec as P

    leaf = jax.device_put(
        staged["attn"]["wq"], NamedSharding(mesh, P("pp"))
    )
    shard = leaf.addressable_shards[0]
    assert shard.data.shape[0] == 1          # one stage per device
    assert leaf.shape[0] == 4


def test_pp_rejects_indivisible_layers():
    with pytest.raises(ValueError, match="not divisible"):
        stage_blocks(
            stack_blocks(
                encoder.init_params(CFG, model_id="pp-test")["blocks"]
            ),
            pp=3,
        )


def test_pp_rejects_indivisible_batch():
    mesh = build_mesh(jax.devices(), {"pp": 4})
    params = encoder.init_params(CFG, model_id="pp-test")
    staged = stage_blocks(stack_blocks(params["blocks"]), 4)
    x = jnp.zeros((6, 16, CFG.d_model), dtype=jnp.float32)
    m = jnp.ones((6, 16), dtype=jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_blocks(mesh, staged, x, m, jnp.float32)
