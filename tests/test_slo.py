"""Fleet health & SLO engine (ISSUE 8): spec parsing, sliding windows,
burn-rate math, alert hysteresis, the controller feed (submit→apply
latencies at result-apply time), ``/v1/health`` assembly, the lease-borne
page alerts + flight-recorder auto-dumps, and the per-op device
attribution primitives (rolling duty window, peak-FLOPs resolution)."""

import json
import os
import threading
import time
import urllib.request

import pytest

from agent_tpu.config import AgentConfig, Config, SchedConfig, SloConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.server import ControllerServer
from agent_tpu.obs.health import (
    RollingWindow,
    build_health,
    resolve_peak_flops,
)
from agent_tpu.obs.metrics import MetricsRegistry
from agent_tpu.obs.slo import (
    DEFAULT_SLO_SPEC,
    Objective,
    SloTracker,
    parse_slo_spec,
)


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---- spec parsing ----

class TestSpecParsing:
    def test_default_spec_is_the_interactive_tier(self):
        obj, ttft = parse_slo_spec("")
        assert obj.name == "interactive"
        assert obj.tier == 8
        assert obj.p99_ms == 1000
        assert obj.availability == 0.999
        assert obj.metric == "latency"
        # ISSUE 15: the default gains a serving TTFT objective — fed only
        # by the /v1/infer completion fan-out, so it idles on batch-only
        # deployments instead of judging job latencies.
        assert ttft.name == "interactive_ttft"
        assert ttft.metric == "ttft"
        assert ttft.tier == 8
        assert ttft.p99_ms == 2500
        assert parse_slo_spec(None) == [obj, ttft]
        assert parse_slo_spec(DEFAULT_SLO_SPEC) == [obj, ttft]

    def test_metric_routing(self):
        objs = parse_slo_spec(
            '[{"name": "lat", "tier": 8, "p99_ms": 100},'
            ' {"name": "ttft", "tier": 8, "metric": "ttft", "p99_ms": 100}]'
        )
        assert objs[0].matches(8, "t", "op")
        assert not objs[0].matches(8, "t", "op", metric="ttft")
        assert objs[1].matches(8, "t", "op", metric="ttft")
        assert not objs[1].matches(8, "t", "op")
        with pytest.raises(ValueError):
            parse_slo_spec('[{"metric": "bogus", "p99_ms": 1}]')

    def test_explicit_spec_round_trips(self):
        objs = parse_slo_spec(
            '[{"tier": 8, "p99_ms": 250, "availability": 0.999},'
            ' {"op": "map_classify_tpu", "tenant": "acme", "p50_ms": 50}]'
        )
        assert [o.name for o in objs] == ["tier8", "tenantacme_opmap_classify_tpu"]
        assert objs[0].latency_targets() == [("p99_ms", pytest.approx(0.01), 0.25)]
        assert objs[1].tenant == "acme" and objs[1].op == "map_classify_tpu"

    @pytest.mark.parametrize("bad", [
        "not json",
        "{}",                                       # not a list
        '[{"tier": 8}]',                            # no targets
        '[{"tier": "eight", "p99_ms": 10}]',        # tier not int
        '[{"p99_ms": -5}]',                         # non-positive target
        '[{"availability": 1.5, "p99_ms": 10}]',    # availability out of range
        '[{"p99_ms": 10, "bogus_key": 1}]',         # unknown key
        '[{"name": "a", "p99_ms": 1}, {"name": "a", "p99_ms": 2}]',  # dup
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(ValueError):
            parse_slo_spec(bad)

    def test_matching_selectors(self):
        o = Objective(name="x", tier=8, op="echo")
        assert o.matches(8, "anyone", "echo")
        assert not o.matches(7, "anyone", "echo")
        assert not o.matches(8, "anyone", "other")
        assert Objective(name="all", p99_ms=1).matches(0, "t", "op")


# ---- tracker math and state machine ----

def make_tracker(clock, registry=None, on_alert=None, **kw):
    defaults = dict(
        window_short_sec=10.0, window_long_sec=40.0,
        burn_warn=2.0, burn_page=8.0, burn_exit_frac=0.5,
    )
    defaults.update(kw)
    return SloTracker(
        parse_slo_spec('[{"name": "o", "p99_ms": 100, "availability": 0.9}]'),
        registry=registry, clock=clock, on_alert=on_alert, **defaults,
    )


class TestTrackerMath:
    def test_burn_rate_is_bad_fraction_over_budget(self):
        clock = FakeClock()
        t = make_tracker(clock)
        # 100 requests, 10 over the 100ms p99 target: slow_frac 0.1,
        # budget 0.01 → burn 10. Availability clean → its burn 0.
        for i in range(100):
            t.observe(0.5 if i < 10 else 0.01, ok=True)
        (r,) = t.evaluate()
        short = r["windows"]["short"]
        assert short["requests"] == 100
        assert short["burn_rate"] == pytest.approx(10.0)
        assert short["targets"]["p99_ms"]["attained"] == pytest.approx(0.9)
        assert short["targets"]["availability"]["burn_rate"] == 0.0
        assert r["attainment"] == pytest.approx(0.9)
        # error budget: long window burn 10 → fully consumed
        assert r["error_budget_remaining"] == 0.0

    def test_availability_breaches_count_errors(self):
        clock = FakeClock()
        t = make_tracker(clock)
        for i in range(50):
            t.observe(0.01, ok=i >= 10)  # 10 failures, all fast
        (r,) = t.evaluate()
        av = r["windows"]["short"]["targets"]["availability"]
        assert av["attained"] == pytest.approx(0.8)
        # budget 0.1 → burn = 0.2 / 0.1 = 2
        assert av["burn_rate"] == pytest.approx(2.0)

    def test_short_window_ages_out_old_observations(self):
        clock = FakeClock()
        t = make_tracker(clock)
        for _ in range(20):
            t.observe(0.5, ok=True)  # all slow NOW
        (r,) = t.evaluate()
        assert r["windows"]["short"]["burn_rate"] == pytest.approx(100.0)
        clock.advance(15.0)  # past the 10s short window
        (r,) = t.evaluate()
        assert r["windows"]["short"]["requests"] == 0
        assert r["windows"]["short"]["burn_rate"] == 0.0
        # ...but still inside the 40s long window
        assert r["windows"]["long"]["requests"] == 20

    def test_empty_tracker_reports_no_attainment(self):
        (r,) = make_tracker(FakeClock()).evaluate()
        assert r["attainment"] is None
        assert r["state"] == "ok"
        assert r["error_budget_remaining"] == 1.0

    def test_quantile_estimates_ride_along(self):
        clock = FakeClock()
        t = make_tracker(clock)
        for _ in range(100):
            t.observe(0.03, ok=True)
        (r,) = t.evaluate()
        # 30ms lands in the (25ms, 50ms] bucket: estimate within it
        assert 25.0 <= r["windows"]["short"]["p99_ms"] <= 50.0


class TestAlertHysteresis:
    def test_page_enters_holds_and_recovers(self):
        clock = FakeClock()
        transitions = []
        t = make_tracker(
            clock,
            on_alert=lambda res, old, new: transitions.append((old, new)),
        )
        # Both windows burn at 100 → page.
        for _ in range(20):
            t.observe(0.5, ok=True)
        (r,) = t.evaluate()
        assert r["state"] == "page"
        assert transitions == [("ok", "page")]
        # Mixed traffic drops the short burn to ~8·exit_frac ± — still
        # above the exit threshold (8·0.5 = 4): the page HOLDS.
        # (13s, not 10s: window reads include whole cells, so aging out is
        # accurate to one 2s cell width — the documented granularity.)
        clock.advance(13.0)  # slow burst leaves the short window
        for i in range(100):
            t.observe(0.5 if i < 5 else 0.01, ok=True)  # burn 5 ∈ [4, 8)
        (r,) = t.evaluate()
        assert r["windows"]["short"]["burn_rate"] == pytest.approx(5.0)
        assert r["state"] == "page", "hysteresis must hold above exit"
        # Clean traffic in a fresh short window → burn < exit → recover.
        clock.advance(13.0)
        for _ in range(50):
            t.observe(0.01, ok=True)
        (r,) = t.evaluate()
        assert r["windows"]["short"]["burn_rate"] < 4.0
        assert r["state"] == "ok"
        assert transitions == [("ok", "page"), ("page", "ok")]

    def test_warn_requires_both_windows(self):
        clock = FakeClock(10_000.0)
        t = make_tracker(clock)
        # Pre-fill the LONG window with lots of clean traffic, outside the
        # short window.
        for _ in range(1000):
            t.observe(0.01, ok=True)
        clock.advance(15.0)
        # A short burst of pure slowness: short burn 100, long burn
        # diluted 20/1020 / 0.01 ≈ 1.96 < warn → NO alert (the long
        # window is the "is this real" guard).
        for _ in range(20):
            t.observe(0.5, ok=True)
        (r,) = t.evaluate()
        assert r["windows"]["short"]["burn_rate"] == pytest.approx(100.0)
        assert r["windows"]["long"]["burn_rate"] < 2.0
        assert r["state"] == "ok"

    def test_gauges_and_transition_counter_export(self):
        clock = FakeClock()
        reg = MetricsRegistry()
        t = make_tracker(clock, registry=reg)
        for _ in range(10):
            t.observe(0.5, ok=False)
        t.evaluate()
        snap = reg.snapshot()
        state = {
            tuple(sorted(s["labels"].items())): s["value"]
            for s in snap["slo_alert_state"]["series"]
        }
        assert state == {(("objective", "o"),): 2.0}  # page
        burn = {
            s["labels"]["window"]: s["value"]
            for s in snap["slo_burn_rate"]["series"]
        }
        assert burn["short"] > 8.0 and burn["long"] > 8.0
        trans = snap["slo_alert_transitions_total"]["series"]
        assert [(s["labels"], s["value"]) for s in trans] == [
            ({"objective": "o", "state": "page"}, 1.0)
        ]

    def test_maybe_evaluate_rate_limits(self):
        clock = FakeClock()
        t = make_tracker(clock)
        first = t.maybe_evaluate()
        t.observe(0.5, ok=False)
        # Within the interval: the cached judgment comes back unchanged.
        assert t.maybe_evaluate() is first
        clock.advance(2.0)
        assert t.maybe_evaluate() is not first


# ---- controller integration ----

def make_controller(clock, spec=None, **slo_kw):
    slo = SloConfig(
        spec=spec if spec is not None else (
            '[{"name": "echo", "op": "echo", "p99_ms": 100, '
            '"availability": 0.9}]'
        ),
        window_short_sec=10.0, window_long_sec=40.0,
        burn_warn=2.0, burn_page=8.0, **slo_kw,
    )
    return Controller(clock=clock, slo=slo)


def run_jobs(c, clock, n, latency_s, ok=True, op="echo", priority=None):
    for _ in range(n):
        jid = c.submit(op, {"x": 1}, priority=priority)
        lease = c.lease("a1", {"ops": [op]})
        assert lease is not None
        clock.advance(latency_s)
        c.report(
            lease["lease_id"], jid, 0,
            "succeeded" if ok else "failed",
            result={"ok": True} if ok else None,
            error=None if ok else {"type": "RuntimeError", "message": "x",
                                   "trace": ""},
        )


class TestControllerIntegration:
    def test_submit_to_apply_latency_feeds_the_tracker(self):
        clock = FakeClock()
        c = make_controller(clock)
        run_jobs(c, clock, 9, 0.01)
        run_jobs(c, clock, 1, 0.5)  # one slow job: slow_frac 0.1 → burn 10
        (r,) = c.slo.evaluate()
        short = r["windows"]["short"]
        assert short["requests"] == 10
        assert short["targets"]["p99_ms"]["burn_rate"] == pytest.approx(10.0)

    def test_failed_jobs_burn_the_availability_budget(self):
        clock = FakeClock()
        c = make_controller(clock)
        # max_attempts=1 → first failure is terminal (one observation).
        for _ in range(4):
            jid = c.submit("echo", {}, max_attempts=1)
            lease = c.lease("a1", {"ops": ["echo"]})
            clock.advance(0.01)
            c.report(lease["lease_id"], jid, 0, "failed",
                     error={"type": "RuntimeError", "message": "x",
                            "trace": ""})
        (r,) = c.slo.evaluate()
        av = r["windows"]["short"]["targets"]["availability"]
        assert av["attained"] == 0.0

    def test_deadline_dead_jobs_count_as_breaches(self):
        clock = FakeClock()
        c = Controller(clock=clock, slo=SloConfig(
            spec='[{"name": "echo", "op": "echo", "availability": 0.9}]',
            window_short_sec=10.0, window_long_sec=40.0,
        ), sched=SchedConfig(policy="fair"))
        c.submit("echo", {}, deadline_sec=1.0)
        clock.advance(5.0)
        c.sweep()  # deadline expiry → dead → SLO observation (ok=False)
        (r,) = c.slo.evaluate()
        assert r["windows"]["short"]["targets"]["availability"]["attained"] \
            == 0.0

    def test_slo_disabled_no_ops_the_whole_path(self):
        clock = FakeClock()
        c = Controller(clock=clock, slo=SloConfig(enabled=False))
        assert c.slo is None
        jid = c.submit("echo", {})
        lease = c.lease("a1", {"ops": ["echo"]})
        out = c.report(lease["lease_id"], jid, 0, "succeeded", result={})
        assert out == {"accepted": True}
        health = c.health_json()
        assert health["slo"] == {"enabled": False, "objectives": []}
        assert health["verdict"] == "ok"
        # no slo_* families ever registered
        assert not any(k.startswith("slo_") for k in c.metrics.snapshot())

    def test_malformed_spec_fails_controller_boot(self):
        with pytest.raises(ValueError):
            Controller(slo=SloConfig(spec="[{}]"))

    def test_page_dumps_controller_ring_tagged(self, tmp_path, monkeypatch):
        monkeypatch.setenv("FLIGHT_RECORDER_DIR", str(tmp_path))
        clock = FakeClock()
        c = make_controller(clock)
        run_jobs(c, clock, 10, 0.5)  # all slow → burn 100 → page
        c.sweep()  # evaluation cadence without lease traffic
        assert c.slo.states() == {"echo": "page"}
        assert len(c.slo_dump_paths) == 1
        path = c.slo_dump_paths[0]
        assert path.startswith(str(tmp_path))
        assert "slo-echo" in path and "opecho" in path
        events = [json.loads(line) for line in open(path)]
        kinds = {e["kind"] for e in events}
        # the dump carries the alert transition AND the drain history
        assert "slo_alert" in kinds and "lease" in kinds
        alert = next(e for e in events if e["kind"] == "slo_alert")
        assert alert["op"] == "echo" and alert["new_state"] == "page"
        # a second sweep must not dump again (one per episode)
        clock.advance(0.5)
        c.sweep()
        assert len(c.slo_dump_paths) == 1

    def test_lease_piggybacks_page_alerts_and_agent_dumps(
        self, tmp_path, monkeypatch
    ):
        from agent_tpu.agent.app import Agent
        from agent_tpu.chaos import LoopbackSession

        monkeypatch.setenv("FLIGHT_RECORDER_DIR", str(tmp_path))
        clock = FakeClock()
        c = make_controller(clock)
        run_jobs(c, clock, 10, 0.5)
        clock.advance(1.1)  # past the maybe_evaluate rate limit
        c.submit("echo", {"x": 2})
        lease = c.lease("a2", {"ops": ["echo"]})
        assert lease["alerts"] == [
            {"objective": "echo", "state": "page", "op": "echo"}
        ]
        # The real agent path: lease_once sees the alerts and dumps its ring.
        cfg = Config(agent=AgentConfig(
            controller_url="http://loopback", agent_name="pagee",
            tasks=("echo",), idle_sleep_sec=0.0,
        ))
        agent = Agent(config=cfg, session=LoopbackSession(c))
        agent._profile = {"tier": "test"}
        c.submit("echo", {"x": 3})
        clock.advance(1.1)
        assert agent.lease_once() is not None
        assert len(agent.slo_dump_paths) == 1
        assert "agent-pagee-slo-echo" in agent.slo_dump_paths[0]
        events = [json.loads(line) for line in open(agent.slo_dump_paths[0])]
        assert any(e["kind"] == "slo_page" for e in events)
        # same episode → no second dump
        c.submit("echo", {"x": 4})
        clock.advance(1.1)
        agent.lease_once()
        assert len(agent.slo_dump_paths) == 1

    def test_health_json_queue_and_starvation(self):
        clock = FakeClock()
        c = Controller(
            clock=clock, sched=SchedConfig(policy="fair"),
            slo=SloConfig(enabled=False),
        )
        c.submit("echo", {}, priority=8)
        clock.advance(3.0)
        c.submit("echo", {}, priority=2)
        c.submit("echo", {}, priority=2)
        h = c.health_json()
        assert h["queue"]["depth"] == 3
        assert h["queue"]["by_tier"] == {"2": 2, "8": 1}
        assert h["queue"]["starvation_age_sec"] == pytest.approx(3.0)
        assert h["counts"] == {"pending": 3}

    def test_health_json_depth_by_tier_fifo(self):
        c = Controller(slo=SloConfig(enabled=False))
        c.submit("echo", {}, priority=8)
        c.submit("echo", {}, priority=4)
        assert c.health_json()["queue"]["by_tier"] == {"4": 1, "8": 1}

    def test_stale_agents_flip_the_verdict_to_warn(self):
        clock = FakeClock()
        c = Controller(clock=clock, slo=SloConfig(
            enabled=False, agent_stale_sec=5.0,
        ))
        c.lease("old-agent", {"ops": ["echo"]}, max_tasks=0,
                metrics={"cpu_util": 0.1})
        # make the last_seen wall timestamp old
        c.agent_metrics["old-agent"]["last_seen_wall"] = time.time() - 60.0
        c.submit("echo", {})  # queued work + a silent fleet = warn
        h = c.health_json()
        assert h["verdict"] == "warn"
        assert h["agents"]["old-agent"]["stale"] is True
        assert {r["kind"] for r in h["reasons"]} == {"no_live_agents"}
        # no queued work → stale agents alone stay informational
        c2 = Controller(slo=SloConfig(enabled=False, agent_stale_sec=5.0))
        c2.lease("idle", {"ops": []}, max_tasks=0, metrics={"x": 1})
        c2.agent_metrics["idle"]["last_seen_wall"] = time.time() - 60.0
        assert c2.health_json()["verdict"] == "ok"

    def test_health_over_http(self):
        c = Controller()
        with ControllerServer(c) as server:
            with urllib.request.urlopen(server.url + "/v1/health") as r:
                body = json.load(r)
        assert body["verdict"] == "ok"
        assert body["slo"]["enabled"] is True
        assert body["slo"]["objectives"][0]["objective"] == "interactive"


# ---- device-attribution primitives ----

class TestRollingWindow:
    def test_fraction_and_aging(self):
        clock = FakeClock(100.0)
        w = RollingWindow(window_sec=10.0, clock=clock)
        clock.advance(20.0)  # tracker lifetime exceeds the window
        w.add(5.0)
        assert w.fraction() == pytest.approx(0.5)
        clock.advance(20.0)  # busy span ages out
        assert w.fraction() == 0.0

    def test_young_tracker_clips_span_to_lifetime(self):
        clock = FakeClock(100.0)
        w = RollingWindow(window_sec=60.0, clock=clock)
        clock.advance(2.0)
        w.add(1.0)
        # 1 busy second over a 2s lifetime, not over the whole minute
        assert w.fraction() == pytest.approx(0.5)

    def test_events_coalesce_per_second(self):
        clock = FakeClock(50.0)
        w = RollingWindow(window_sec=30.0, clock=clock)
        for _ in range(1000):
            w.add(0.001)
        assert len(w._events) == 1
        assert w.total() == pytest.approx(1.0)


class TestPeakFlops:
    def test_env_override_wins(self, monkeypatch):
        monkeypatch.setenv("PEAK_TFLOPS", "2.5")
        assert resolve_peak_flops(None) == pytest.approx(2.5e12)

    def test_unknown_device_returns_none(self, monkeypatch):
        monkeypatch.delenv("PEAK_TFLOPS", raising=False)
        assert resolve_peak_flops(None) is None

        class FakeDev:
            device_kind = "Quantum Abacus"

        class FakeRt:
            devices = [FakeDev()]

        assert resolve_peak_flops(FakeRt()) is None

        class V5e:
            device_kind = "TPU v5e"

        class Rt5:
            devices = [V5e()]

        assert resolve_peak_flops(Rt5()) == pytest.approx(197e12)


class TestBuildHealthPure:
    def test_page_objective_pages_the_verdict(self):
        h = build_health(
            slo_enabled=True,
            slo_objectives=[{
                "objective": "o", "state": "page",
                "burn_rate_short": 50.0, "burn_rate_long": 20.0,
            }],
        )
        assert h["verdict"] == "page"
        assert h["reasons"][0]["kind"] == "slo_burn"

    def test_agent_rows_prefer_rolling_duty_gauge(self):
        reg = MetricsRegistry()
        reg.counter(
            "device_busy_seconds_total", "b", ("op",)
        ).inc(30.0, op="x")
        reg.counter("device_idle_seconds_total", "i").inc(70.0)
        reg.gauge("device_duty_cycle", "d").set(0.85)
        reg.gauge("device_mfu", "m", ("op",)).set(0.44, op="x")
        h = build_health(
            slo_enabled=False,
            agents={"a": {"last_seen_wall": time.time(),
                          "obs": reg.snapshot()}},
        )
        row = h["agents"]["a"]
        assert row["duty_cycle"] == 0.85  # the gauge, not 0.3
        assert row["mfu"] == {"x": 0.44}
        assert row["device_busy_s_by_op"] == {"x": 30.0}
        assert row["stale"] is False

    def test_legacy_unlabeled_busy_counter_degrades(self):
        reg = MetricsRegistry()
        reg.counter("device_busy_seconds_total", "b").inc(25.0)
        reg.counter("device_idle_seconds_total", "i").inc(75.0)
        h = build_health(
            slo_enabled=False,
            agents={"a": {"last_seen_wall": time.time(),
                          "obs": reg.snapshot()}},
        )
        assert h["agents"]["a"]["duty_cycle"] == pytest.approx(0.25)
