"""Journal segmentation/snapshot/compaction, hot-standby failover, and the
agent-side CONTROLLER_URLS rotation (ISSUE 14)."""

import json
import os
import threading
import time

import pytest

from agent_tpu.agent.app import Agent
from agent_tpu.chaos import ChaosTransportError, LoopbackSession
from agent_tpu.config import AgentConfig, Config, JournalConfig
from agent_tpu.controller.core import Controller
from agent_tpu.controller.journal import (
    JournalTailer,
    SegmentedJournal,
    list_segments,
    load_snapshot,
    segment_path,
)
from agent_tpu.controller.standby import HotStandby
from agent_tpu.obs.usage import UsageLedger

SEG_CFG = JournalConfig(segment_max_bytes=400)
SNAP_CFG = JournalConfig(segment_max_bytes=400, snapshot_every_events=8)


def drain_n(c, n, agent="a", ops=("echo",)):
    done = []
    for _ in range(n):
        lease = c.lease(agent, {"ops": list(ops)})
        t = lease["tasks"][0]
        c.report(lease["lease_id"], t["id"], t["job_epoch"], "succeeded",
                 {"ok": True})
        done.append(t["id"])
    return done


def snapshot_of(c, ids):
    return {j: c.job_snapshot(j) for j in ids}


def states_equal(a, b):
    for jid, live in a.items():
        re = b[jid]
        for k in ("state", "job_epoch", "attempts"):
            assert re[k] == live[k], (jid, k, live[k], re[k])


class TestSegmentation:
    def test_default_config_stays_single_file(self, tmp_path):
        """Byte-compat: a default JournalConfig is the historical single
        append-only file — no segments, no snapshot, same bytes."""
        path = str(tmp_path / "j.jsonl")
        c = Controller(journal_path=path)
        c.submit("echo", {"x": 1}, job_id="j1")
        c.close()
        assert os.path.exists(path)
        assert list_segments(path) == []
        assert not os.path.exists(path + ".snapshot")
        (line,) = open(path, encoding="utf-8").read().splitlines()
        assert json.loads(line) == {
            "ev": "submit", "job_id": "j1", "op": "echo",
            "payload": {"x": 1}, "after": [], "required_labels": {},
            "max_attempts": None,
        }

    def test_rotation_bounds_segments(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        c = Controller(journal_path=path, journal=SEG_CFG)
        ids = [c.submit("echo", {"i": i}) for i in range(30)]
        c.close()
        segs = list_segments(path)
        assert len(segs) > 1
        for _seq, seg in segs[:-1]:
            # Every sealed segment respects the budget (within one event).
            assert os.path.getsize(seg) <= SEG_CFG.segment_max_bytes + 200
        # The full chain replays every submit.
        c2 = Controller(journal_path=path, journal=SEG_CFG)
        assert c2.counts() == {"pending": 30}
        assert {t["id"] for t in c2.lease(
            "a", {"ops": ["echo"]}, max_tasks=30)["tasks"]} == set(ids)
        c2.close()

    def test_event_budget_rotation(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        cfg = JournalConfig(segment_max_events=5)
        c = Controller(journal_path=path, journal=cfg)
        for i in range(12):
            c.submit("echo", {"i": i})
        c.close()
        assert len(list_segments(path)) == 3  # 5 + 5 + 2

    def test_legacy_file_replays_before_segments(self, tmp_path):
        """An operator flipping segmentation on mid-life: the old single
        file replays first, then the new segments."""
        path = str(tmp_path / "j.jsonl")
        c = Controller(journal_path=path)
        c.submit("echo", {}, job_id="old")
        c.close()
        c2 = Controller(journal_path=path, journal=SEG_CFG)
        c2.submit("echo", {}, job_id="new")
        c2.close()
        c3 = Controller(journal_path=path, journal=SEG_CFG)
        assert set(j for j in c3._jobs) == {"old", "new"}
        c3.close()


class TestSnapshot:
    def test_snapshot_compacts_and_replays_identically(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        c = Controller(journal_path=path, journal=SNAP_CFG)
        shard_ids, reduce_id = c.submit_csv_job(
            "d.csv", total_rows=400, shard_size=100,
            reduce_op="risk_accumulate", collect_partials=True,
        )
        drain_n(c, 2, ops=("read_csv_shard",))
        c.maybe_snapshot(force=True)
        drain_n(c, 1, ops=("read_csv_shard",))
        live = snapshot_of(c, shard_ids + [reduce_id])
        c.close()

        snap = load_snapshot(path)
        assert snap is not None and snap["version"] == 1
        # GC: every covered segment is gone.
        assert all(s > snap["through_seq"] for s, _ in list_segments(path))

        c2 = Controller(journal_path=path, journal=SNAP_CFG)
        states_equal(live, snapshot_of(c2, shard_ids + [reduce_id]))
        # Depended-on result bodies survive the snapshot: the reduce still
        # materializes ordered partials.
        drain_n(c2, 1, ops=("read_csv_shard",))
        lease = c2.lease("a", {"ops": ["risk_accumulate"]})
        partials = lease["tasks"][0]["payload"]["partials"]
        assert [p["ok"] for p in partials] == [True] * 4
        c2.close()

    def test_snapshot_cadence_fires_automatically(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        c = Controller(journal_path=path, journal=SNAP_CFG)
        for i in range(20):  # > snapshot_every_events appends
            c.submit("echo", {"i": i})
        c.sweep()  # the sweeper cadence drives maybe_snapshot()
        assert os.path.exists(path + ".snapshot")
        assert c.journal_status()["snapshots_written"] >= 1
        c.close()

    def test_snapshot_write_is_atomic_tmp_rename(self, tmp_path,
                                                 monkeypatch):
        """Kill-the-writer-mid-snapshot regression (ISSUE 14 satellite):
        death before the rename leaves the PREVIOUS snapshot (or none)
        intact — never a half image — and replay falls back to segments."""
        path = str(tmp_path / "j.jsonl")
        # Force-only cadence: no automatic snapshot may land first.
        c = Controller(journal_path=path, journal=SEG_CFG)
        ids = [c.submit("echo", {"i": i}) for i in range(10)]
        drain_n(c, 4)
        live = snapshot_of(c, ids)

        real_replace = os.replace

        def die_before_rename(src, dst):
            raise OSError("chaos: writer killed mid-snapshot")

        monkeypatch.setattr(os, "replace", die_before_rename)
        with pytest.raises(OSError):
            c.maybe_snapshot(force=True)
        monkeypatch.setattr(os, "replace", real_replace)
        c.close()
        # No snapshot landed; the half-written tmp is cleaned up; replay
        # rebuilds the identical state from segments alone.
        assert not os.path.exists(path + ".snapshot")
        assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
        c2 = Controller(journal_path=path, journal=SNAP_CFG)
        states_equal(live, snapshot_of(c2, ids))
        assert c2.counts() == {"succeeded": 4, "pending": 6}
        c2.close()

    def test_half_written_snapshot_ignored(self, tmp_path):
        """A corrupt/truncated snapshot file (external damage, version
        skew) is IGNORED in favor of full-segment replay, counted."""
        path = str(tmp_path / "j.jsonl")
        c = Controller(journal_path=path, journal=SEG_CFG)
        ids = [c.submit("echo", {"i": i}) for i in range(6)]
        drain_n(c, 2)
        live = snapshot_of(c, ids)
        c.close()
        with open(path + ".snapshot", "w", encoding="utf-8") as f:
            f.write('{"version": 1, "through_seq": 99, "jobs": [')  # torn
        c2 = Controller(journal_path=path, journal=SEG_CFG)
        states_equal(live, snapshot_of(c2, ids))
        snap = c2.metrics.snapshot()
        (s,) = snap["controller_journal_snapshot_invalid_total"]["series"]
        assert s["value"] == 1
        assert c2.journal_replayed_events > 0
        c2.close()

    def test_terminal_retention_bounds_snapshot(self, tmp_path):
        """SNAPSHOT_RETAIN_TERMINAL: old droppable terminal jobs leave
        the snapshot (restart forgets them — late duplicates reject as
        unknown job, still at-most-once), live jobs and depended-on
        terminal jobs always survive."""
        path = str(tmp_path / "j.jsonl")
        cfg = JournalConfig(
            segment_max_bytes=4096, snapshot_retain_terminal=2
        )
        c = Controller(journal_path=path, journal=cfg)
        # A completed map-reduce whose shards stay depended-on...
        shard_ids, reduce_id = c.submit_csv_job(
            "d.csv", total_rows=100, shard_size=50,
            reduce_op="risk_accumulate", collect_partials=True,
        )
        drain_n(c, 2, ops=("read_csv_shard",))
        # ...the reduce stays PENDING (never leased): its deps must
        # never drop. Plus 6 droppable terminal singles and 1 live one.
        singles = [c.submit("echo", {"i": i}) for i in range(7)]
        drain_n(c, 6)
        c.maybe_snapshot(force=True)
        c.close()

        c2 = Controller(journal_path=path, journal=cfg)
        # Depended-on shards survive (reduce is still pending).
        for sid in shard_ids:
            assert c2.job_snapshot(sid)["state"] == "succeeded"
        assert c2.job_snapshot(reduce_id)["state"] == "pending"
        # Only the 2 newest droppable singles survive; the live one too.
        survivors = [s for s in singles if s in c2._jobs]
        assert singles[-1] in survivors          # the pending single
        assert len(survivors) == 3               # 2 retained + 1 live
        assert survivors[-3:] == singles[-3:]    # newest-first retention
        # A late duplicate for a forgotten job: cleanly rejected.
        out = c2.report("lease-x", singles[0], 0, "succeeded", {})
        assert out["accepted"] is False
        assert out["reason"] == "unknown job"
        # And the reduce still materializes its ordered partials.
        lease = c2.lease("a", {"ops": ["risk_accumulate"]})
        assert len(lease["tasks"][0]["payload"]["partials"]) == 2
        c2.close()

    def test_usage_ledger_survives_snapshot(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        c = Controller(journal_path=path, journal=SNAP_CFG)
        jid = c.submit("echo", {}, tenant="acme", priority=7)
        lease = c.lease("a", {"ops": ["echo"]})
        c.report(lease["lease_id"], jid, 0, "succeeded",
                 {"ok": True, "usage": {"device_s": 1.5, "rows": 10}})
        c.maybe_snapshot(force=True)
        billed = c.usage.billed_tasks
        attempts = c.usage.job_billed_attempts()
        c.close()
        c2 = Controller(journal_path=path, journal=SNAP_CFG)
        assert c2.usage.billed_tasks == billed == 1
        assert c2.usage.job_billed_attempts() == attempts
        report = c2.usage.report()
        assert report["by_tenant"]["acme"]["device_seconds"] == 1.5
        assert report["by_tenant"]["acme"]["rows"] == 10
        # The (job, attempt) dedupe survives too: a replayed duplicate
        # bill is rejected.
        assert c2.usage.bill(jid, tenant="acme", tier=7, op="echo",
                             attempt=1, usage={"device_s": 9.0}) is None
        c2.close()


class TestTornLinePositions:
    """Parameterized torn-final-line matrix (ISSUE 14 satellite): the
    existing torn_tail/replay_skipped counters still fire in every
    position and state converges."""

    def _build(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        c = Controller(journal_path=path, journal=SEG_CFG)
        ids = [c.submit("echo", {"i": i, "pad": "x" * 60})
               for i in range(12)]
        drain_n(c, 5)
        live = snapshot_of(c, ids)
        c.close()
        segs = list_segments(path)
        assert len(segs) >= 3
        return path, ids, live, segs

    @pytest.mark.parametrize("position", ["mid_segment", "tail_segment"])
    def test_torn_line_positions(self, tmp_path, position):
        path, ids, live, segs = self._build(tmp_path)
        torn = '{"ev": "result", "job_id'
        if position == "mid_segment":
            # Torn line at the end of a NON-final segment: mid-stream
            # corruption → the skipped counter, not torn_tail.
            with open(segs[0][1], "a", encoding="utf-8") as f:
                f.write(torn)
            want_torn, want_skipped = 0, 1
        else:
            # Torn final line of the FINAL segment: the expected crash
            # artifact → tolerated, counted torn_tail.
            with open(segs[-1][1], "a", encoding="utf-8") as f:
                f.write(torn)
            want_torn, want_skipped = 1, 0
        c2 = Controller(journal_path=path, journal=SEG_CFG)
        assert c2.journal_torn_tail == want_torn
        assert c2.journal_replay_skipped == want_skipped
        states_equal(live, snapshot_of(c2, ids))
        c2.close()

    def test_torn_snapshot_position(self, tmp_path):
        """Torn SNAPSHOT + torn tail segment at once: snapshot ignored
        (invalid counter), segments replay, torn_tail still fires."""
        path, ids, live, segs = self._build(tmp_path)
        with open(path + ".snapshot", "w", encoding="utf-8") as f:
            f.write('{"version": 1,')
        with open(segs[-1][1], "a", encoding="utf-8") as f:
            f.write('{"ev": "result"')
        c2 = Controller(journal_path=path, journal=SEG_CFG)
        assert c2.journal_torn_tail == 1
        assert c2.journal_replay_skipped == 0
        snap = c2.metrics.snapshot()
        (s,) = snap["controller_journal_snapshot_invalid_total"]["series"]
        assert s["value"] == 1
        states_equal(live, snapshot_of(c2, ids))
        c2.close()


class TestFsync:
    def test_fsync_off_by_default_and_on_when_asked(self, tmp_path):
        """Both durability paths (ISSUE 14 satellite): default writes are
        flush-only; JOURNAL_FSYNC=1 fdatasyncs per append; fsync_every=N
        group-commits."""
        off = SegmentedJournal(str(tmp_path / "off.jsonl"))
        off.open_for_append()
        off.append({"ev": "submit", "job_id": "a", "op": "echo"})
        assert off.fsyncs == 0
        off.close()

        per = SegmentedJournal(str(tmp_path / "per.jsonl"), fsync=True)
        per.open_for_append()
        for i in range(3):
            per.append({"ev": "submit", "job_id": f"j{i}", "op": "echo"})
        assert per.fsyncs == 3
        per.close()

        grp = SegmentedJournal(
            str(tmp_path / "grp.jsonl"), fsync=True, fsync_every=4
        )
        grp.open_for_append()
        for i in range(6):
            grp.append({"ev": "submit", "job_id": f"j{i}", "op": "echo"})
        assert grp.fsyncs == 1   # one group commit at 4
        grp.close()
        assert grp.fsyncs == 2   # close drains the unsynced remainder

    def test_fsync_journal_replays_identically(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        cfg = JournalConfig(fsync=True, fsync_every=2)
        c = Controller(journal_path=path, journal=cfg)
        ids = [c.submit("echo", {"i": i}) for i in range(4)]
        drain_n(c, 2)
        live = snapshot_of(c, ids)
        c.close()
        c2 = Controller(journal_path=path, journal=cfg)
        states_equal(live, snapshot_of(c2, ids))
        c2.close()

    def test_journal_config_from_env(self, monkeypatch):
        monkeypatch.setenv("JOURNAL_FSYNC", "1")
        monkeypatch.setenv("JOURNAL_FSYNC_EVERY", "16")
        monkeypatch.setenv("JOURNAL_SEGMENT_MAX_BYTES", "1048576")
        monkeypatch.setenv("SNAPSHOT_EVERY_EVENTS", "5000")
        cfg = JournalConfig.from_env()
        assert cfg.fsync is True
        assert cfg.fsync_every == 16
        assert cfg.segment_max_bytes == 1048576
        assert cfg.snapshot_every_events == 5000


class TestTailer:
    def test_tail_across_rotation_and_partial_lines(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        j = SegmentedJournal(path, segment_max_events=3)
        j.open_for_append()
        tail = JournalTailer(path)
        for i in range(4):
            j.append({"ev": "submit", "job_id": f"j{i}", "op": "echo"})
        got = tail.poll()
        assert [e["job_id"] for e in got] == ["j0", "j1", "j2", "j3"]
        # A partial (newline-less) line is held back until complete.
        j._file.write('{"ev": "submit", "job_id": "j4"')
        j._file.flush()
        assert tail.poll() == []
        assert tail.lag_bytes() > 0
        j._file.write(', "op": "echo"}\n')
        j._file.flush()
        assert [e["job_id"] for e in tail.poll()] == ["j4"]
        assert tail.lag_bytes() == 0
        j.close()

    def test_seal_truncates_only_torn_tail(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        j = SegmentedJournal(path, segment_max_events=100)
        j.open_for_append()
        j.append({"ev": "submit", "job_id": "whole", "op": "echo"})
        tail = JournalTailer(path)
        tail.poll()
        # One late complete event + one torn write after the last poll.
        j.append({"ev": "submit", "job_id": "late", "op": "echo"})
        j._file.write('{"ev": "submit", "job_id": "torn"')
        j._file.flush()
        late, cut = tail.seal()
        assert [e["job_id"] for e in late] == ["late"]
        assert cut == len('{"ev": "submit", "job_id": "torn"')
        # The file now ends at the last complete line.
        seg = list_segments(path)[-1][1]
        lines = open(seg, encoding="utf-8").read().splitlines()
        assert json.loads(lines[-1])["job_id"] == "late"


class TestHotStandby:
    def _controller(self, path, **kw):
        return Controller(
            journal_path=path, journal=SNAP_CFG, lease_ttl_sec=30.0, **kw
        )

    def test_warm_replica_tracks_primary(self, tmp_path):
        path = str(tmp_path / "j.jsonl")
        prim = self._controller(path)
        sb = HotStandby(path, journal=SNAP_CFG, poll_interval_sec=0.01)
        sb.start()
        try:
            [prim.submit("echo", {"i": i}) for i in range(8)]
            drain_n(prim, 5)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if sb.replica_counts() == {"succeeded": 5, "pending": 3} \
                        and sb.lag_bytes() == 0:
                    break
                time.sleep(0.01)
            assert sb.replica_counts() == {"succeeded": 5, "pending": 3}
            assert sb.lag_bytes() == 0
        finally:
            sb.stop()
            prim.close()

    def test_compaction_outrunning_tail_resyncs(self, tmp_path):
        """A snapshot that GCs segments the standby has not finished
        reading must not lose events: the tailer flags a resync and the
        replica reloads from the snapshot (which folds them in)."""
        path = str(tmp_path / "j.jsonl")
        cfg = JournalConfig(segment_max_events=5)
        prim = Controller(journal_path=path, journal=cfg)
        sb = HotStandby(path, journal=cfg)  # never started: manual polls
        ids = [prim.submit("echo", {"i": i}) for i in range(12)]
        assert sb.catch_up() == 12
        # More traffic + a compacting snapshot: the segments under the
        # standby's cursor are garbage-collected.
        ids += [prim.submit("echo", {"i": i}) for i in range(12, 22)]
        drain_n(prim, 4)
        prim.maybe_snapshot(force=True)
        sb.catch_up()  # events arrive via the snapshot, not the tail
        assert sb.resyncs >= 1
        assert sb.replica_counts() == prim.counts()
        assert all(j in sb.controller._jobs for j in ids)
        prim.close()

    def test_promote_apply_once_or_cleanly_rejected(self, tmp_path):
        """The ISSUE 14 fencing bar: results posted to the OLD incarnation
        are applied-once (spool redelivery accepted at the same epoch) or
        cleanly rejected (journaled fences + terminal guard replay)."""
        path = str(tmp_path / "j.jsonl")
        prim = self._controller(path)
        sb = HotStandby(path, journal=SNAP_CFG, poll_interval_sec=0.01)
        sb.start()
        try:
            done_id = prim.submit("echo", {}, job_id="done")
            inflight_id = prim.submit("echo", {}, job_id="inflight")
            fenced_id = prim.submit("echo", {}, job_id="fenced")
            drain_n(prim, 1)                      # "done" completes
            inflight = prim.lease("a", {"ops": ["echo"]})  # "inflight"
            # "fenced": lease expires on the primary → journaled epoch bump.
            clockless = prim.lease("b", {"ops": ["echo"]})
            prim._jobs[fenced_id].lease_deadline = -1.0  # force expiry
            prim.sweep()
            time.sleep(0.2)  # let the tail drain
        finally:
            sb.stop()
        # Primary "dies" (no close — handles just stop being used).
        promoted = sb.promote()
        try:
            assert promoted.counts() == {"succeeded": 1, "pending": 2}
            # 1. duplicate of the completed job: cleanly rejected.
            out = promoted.report("lease-x", done_id, 0, "succeeded", {})
            assert out["accepted"] is False
            assert out["reason"] == "already complete"
            # 2. the old incarnation's fence replays: stale epoch rejected.
            out = promoted.report(
                clockless["lease_id"], fenced_id, 0, "succeeded", {})
            assert out["accepted"] is False
            assert out["reason"] == "stale epoch"
            # 3. the in-flight agent's spooled result redelivers at its
            # original epoch: applied exactly once.
            t = inflight["tasks"][0]
            out = promoted.report(
                inflight["lease_id"], inflight_id, t["job_epoch"],
                "succeeded", {"ok": True})
            assert out["accepted"] is True
            out = promoted.report(
                inflight["lease_id"], inflight_id, t["job_epoch"],
                "succeeded", {"ok": True})
            assert out["accepted"] is False  # second application rejected
            assert promoted.promotions == 1
            assert promoted.journal_status()["promotions"] == 1
        finally:
            promoted.close()

    def test_promotion_survives_replay(self, tmp_path):
        """The promoted incarnation's appends land on a fresh segment and
        the whole healed chain replays clean."""
        path = str(tmp_path / "j.jsonl")
        prim = self._controller(path)
        ids = [prim.submit("echo", {"i": i}) for i in range(4)]
        drain_n(prim, 2)
        # Torn death write, no close.
        prim._journal_impl._file.write('{"ev": "result", "job_')
        prim._journal_impl._file.flush()
        prim._sweep_stop.set()

        sb = HotStandby(path, journal=SNAP_CFG)
        promoted = sb.promote()
        assert sb.torn_sealed_bytes > 0
        assert promoted.journal_torn_tail == 1  # operator-visible
        drain_n(promoted, 2)
        assert promoted.drained()
        live = snapshot_of(promoted, ids)
        promoted.close()

        c2 = Controller(journal_path=path, journal=SNAP_CFG)
        assert c2.journal_torn_tail == 0      # sealed at promotion
        assert c2.journal_replay_skipped == 0
        states_equal(live, snapshot_of(c2, ids))
        c2.close()


class FlakySession:
    """Session whose post raises for URLs in `down`, else loops back."""

    def __init__(self, controller, down):
        self.inner = LoopbackSession(controller)
        self.down = down
        self.posts = []

    def post(self, url, json=None, timeout=None):  # noqa: A002
        self.posts.append(url)
        for prefix in self.down:
            if url.startswith(prefix):
                raise ChaosTransportError(f"down: {url}")
        return self.inner.post(url, json=json, timeout=timeout)


class TestAgentFailover:
    def _agent(self, controller, urls, down):
        cfg = Config(agent=AgentConfig(
            controller_url=urls[0], controller_urls=tuple(urls),
            agent_name="fo", tasks=("echo",), idle_sleep_sec=0.01,
            error_backoff_sec=0.01, retry_base_sec=0.005,
            retry_max_sec=0.02, pipeline_depth=0,
        ))
        session = FlakySession(controller, down)
        agent = Agent(config=cfg, session=session)
        agent._profile = {"tier": "test"}
        return agent, session

    def test_urls_env_parse(self, monkeypatch):
        monkeypatch.delenv("CONTROLLER_URL", raising=False)
        monkeypatch.setenv(
            "CONTROLLER_URLS", "http://p:8080, http://s:8080/"
        )
        cfg = AgentConfig.from_env()
        assert cfg.controller_urls == ("http://p:8080", "http://s:8080")
        # The list head doubles as the primary when CONTROLLER_URL unset.
        assert cfg.controller_url == "http://p:8080"

    def test_transport_error_rotates_sticky(self, tmp_path):
        c = Controller()
        jid = c.submit("echo", {"v": 1})
        agent, session = self._agent(
            c, ["http://primary", "http://standby"], down=["http://primary"]
        )
        assert agent.active_controller_url() == "http://primary"
        # First lease hits the dead primary, rotates; the step's backoff
        # returns False, the NEXT step leases from the standby.
        agent.step()
        assert agent.active_controller_url() == "http://standby"
        assert agent.step() is True
        assert c.job_snapshot(jid)["state"] == "succeeded"
        snap = agent.obs.snapshot()
        (fo,) = snap["controller_failovers_total"]["series"]
        assert fo["value"] == 1
        # Sticky: success pins the standby; no further rotation.
        agent.step()
        assert agent.active_controller_url() == "http://standby"

    def test_spool_redelivers_to_standby(self):
        """A completed result that failed to post to the dead primary
        redelivers to the standby — the ISSUE 14 'redeliver instead of
        drop' bar, spool + failover composing."""
        c = Controller()
        jid = c.submit("echo", {"v": 2})
        agent, session = self._agent(
            c, ["http://primary", "http://standby"], down=[]
        )
        lease = c.lease("fo", {"ops": ["echo"]})
        # Primary dies between lease and post.
        session.down = ["http://primary"]
        t = lease["tasks"][0]
        ok = agent.post_result(
            lease["lease_id"], jid, t["job_epoch"], "succeeded",
            {"ok": True}, op="echo",
        )
        assert ok is False and len(agent.spool) == 1
        # Rotation happened inside the failed post; the flush delivers.
        assert agent.active_controller_url() == "http://standby"
        assert agent.flush_spool(force=True) == 1
        assert c.job_snapshot(jid)["state"] == "succeeded"
        assert len(agent.spool) == 0

    def test_single_url_never_rotates(self):
        c = Controller()
        agent, session = self._agent(
            c, ["http://primary"], down=["http://primary"]
        )
        agent.step()
        assert agent.active_controller_url() == "http://primary"
        snap = agent.obs.snapshot()
        assert not snap["controller_failovers_total"]["series"]


class TestUsageLedgerState:
    def test_export_import_round_trip(self):
        a = UsageLedger()
        a.bill("j1", tenant="t1", tier=3, op="x", attempt=1,
               usage={"device_s": 2.0, "rows": 7}, wire_bytes=10)
        a.bill("j2", tenant="t2", tier=8, op="y", attempt=1,
               usage={"device_s": 0.5, "flops": 1e9})
        a.bill("j2", tenant="t2", tier=8, op="y", attempt=2,
               usage={"device_s": 0.25})
        doc = a.export_state()
        # The export is JSON-serializable (it rides the snapshot).
        doc = json.loads(json.dumps(doc))
        b = UsageLedger()
        b.import_state(doc)
        assert b.billed_tasks == a.billed_tasks == 3
        assert b.job_billed_attempts() == a.job_billed_attempts()
        ra, rb = a.report(), b.report()
        assert rb["by_tenant"] == ra["by_tenant"]
        assert rb["totals"] == ra["totals"]
        # Dedupe state survives: re-billing an imported attempt no-ops.
        assert b.bill("j2", tenant="t2", tier=8, op="y", attempt=2,
                      usage={"device_s": 9.9}) is None


class TestSnapshotConcurrency:
    def test_snapshot_under_live_traffic(self, tmp_path):
        """Snapshots race live submits/reports without losing events: the
        rotation + state capture are lock-ordered with appends."""
        path = str(tmp_path / "j.jsonl")
        cfg = JournalConfig(segment_max_bytes=2000, snapshot_every_events=25)
        c = Controller(journal_path=path, journal=cfg)
        stop = threading.Event()

        def traffic():
            i = 0
            while not stop.is_set():
                jid = c.submit("echo", {"i": i})
                lease = c.lease("a", {"ops": ["echo"]})
                if lease:
                    t = lease["tasks"][0]
                    c.report(lease["lease_id"], t["id"], t["job_epoch"],
                             "succeeded", {"ok": True})
                i += 1

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            if c.journal_status()["snapshots_written"] >= 3:
                break
            c.maybe_snapshot()
            time.sleep(0.005)
        stop.set()
        t.join(timeout=5)
        assert c.journal_status()["snapshots_written"] >= 3
        live = {j: c.job_snapshot(j) for j in list(c._jobs)}
        n = len(live)
        c.close()
        c2 = Controller(journal_path=path, journal=cfg)
        assert len(c2._jobs) == n
        states_equal(live, {j: c2.job_snapshot(j) for j in list(c2._jobs)})
        assert c2.journal_torn_tail == 0
        assert c2.journal_replay_skipped == 0
        c2.close()
